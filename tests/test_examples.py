"""Every shipped example network behaves exactly as its _doc promises."""

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # covered every `make test-all`; fast lane favors iteration speed

from misaka_tpu.runtime.topology import Topology

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def load(name):
    with open(os.path.join(EXAMPLES, name)) as f:
        return Topology.from_json(f.read())


def run_pairs(net, inputs, expected_outputs):
    """Feed everything, run until len(expected_outputs) outputs arrive."""
    _, outs = net.compute_stream(
        net.init_state(), inputs, max_steps=20_000, expected=len(expected_outputs)
    )
    assert outs == expected_outputs


def test_running_total():
    net = load("running_total.json").compile()
    state = net.init_state()
    state, outs = net.compute_stream(state, [5, 3, 10, -4])
    assert outs == [5, 8, 18, 14]


def test_absolute():
    net = load("absolute.json").compile()
    state = net.init_state()
    state, outs = net.compute_stream(state, [-7, 7, 0, -1000])
    assert outs == [7, 7, 0, 1000]


def test_reverse4():
    net = load("reverse4.json").compile()
    state = net.init_state()
    state, outs = net.compute_stream(state, [1, 2, 3, 4, 9, 8, 7, 6])
    assert outs == [4, 3, 2, 1, 6, 7, 8, 9]


@pytest.mark.parametrize("a,b", [(2, 3), (0, 9), (5, -4), (1, 1), (7, 0), (10, 10)])
def test_multiply(a, b):
    net = load("multiply.json").compile()
    run_pairs(net, [a, b], [a * b])


def test_multiply_stream_of_pairs():
    """Back-to-back multiplications reuse the adder correctly (reset path)."""
    net = load("multiply.json").compile()
    run_pairs(net, [2, 3, 4, 5, 0, 99, 3, 3], [6, 20, 0, 9])


def test_overflow64():
    net = load("overflow64.json").compile()
    state = net.init_state()
    state, outs = net.compute_stream(state, [5, -7])
    # 64-bit acc: JLZ not taken; OUT emits the sint32 wire truncation
    want = [int(np.int64(v + 4_000_000_000).astype(np.int32)) for v in (5, -7)]
    assert outs == want


def test_reverse_any():
    # engine-level: caps sized for the stream (the MASTER auto-grows; the
    # raw engine honors whatever capacity it was compiled with)
    net = load("reverse_any.json").compile()
    state = net.init_state()
    state, outs = net.compute_stream(
        state, [7, 8, 9, 0], max_steps=20_000, expected=4
    )
    assert outs == [0, 9, 8, 7]


def test_reverse_any_autogrows_under_master():
    # serving-path: 40 values through an initial stack_cap of 8 — completes
    # only because the master grows the stack.  tests/test_autogrow.py pins
    # the mechanism; this only pins that the SHIPPED example lowers to the
    # same reverser, so it reuses that suite's driver.
    from misaka_tpu.runtime.master import MasterNode
    from tests.test_autogrow import run_reverser

    top = load("reverse_any.json")
    top.stack_cap = 8
    master = MasterNode(top, chunk_steps=32)
    master.run()
    run_reverser(master)
    assert master._net.stack_cap >= 64


def test_examples_disassemble_cleanly():
    """Round-trip every example through the disassembler (docs never lie)."""
    from misaka_tpu.tis.disasm import disassemble_network
    from misaka_tpu.tis.lower import lower_program

    for name in os.listdir(EXAMPLES):
        if not name.endswith(".json"):
            continue
        top = load(name)
        net = top.compile()
        lane_ids = top.lane_ids()
        stack_ids = top.stack_ids()
        lane_names = list(lane_ids)
        stack_names = list(stack_ids)
        texts = disassemble_network(net.code, net.prog_len, lane_names, stack_names)
        for lane, text in texts.items():
            again = lower_program(text, lane_ids, stack_ids)
            i = lane_ids[lane]
            assert again.length == int(net.prog_len[i]), f"{name}:{lane} truncated"
            np.testing.assert_array_equal(
                again.code, net.code[i, : again.length], err_msg=f"{name}:{lane}"
            )


def test_running_total_on_native_engine():
    # the README's interactive-tier claim: examples serve unchanged on
    # MISAKA_ENGINE=native, stateful across requests (running total)
    from misaka_tpu.core import native_serve
    from misaka_tpu.runtime.master import MasterNode

    if not native_serve.available():
        pytest.skip("no C++ toolchain for the native engine")
    m = MasterNode(load("running_total.json"), chunk_steps=32, engine="native")
    m.run()
    try:
        assert [m.compute(v) for v in (5, 3, -2)] == [5, 8, 6]
    finally:
        m.pause()
