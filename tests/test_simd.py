"""SIMD struct-of-arrays interpreter + per-program specialization (ISSUE 12).

The differential corpus for the group engine (native/interpreter.cpp): the
pool's three execution ladders — AVX2 group ticks, the generic group
fallback (`MISAKA_SIMD=generic`, the forced no-AVX2 rung), and the shipped
scalar per-replica path (`MISAKA_SIMD=0`) — must be BIT-IDENTICAL to each
other and to the XLA batched serve twins, including tick counts,
partial-fill active lists, and checkpoint/restore round trips through a
specialized engine.  Per-program specialization (core/specialize.py) must
engage when armed, fall back gracefully on compile failure (the
`specialize_fail` chaos point), and never change results.
"""

import os

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.core import native_serve, specialize
from misaka_tpu.core.state import NetworkState
from misaka_tpu.runtime.master import MasterNode
from misaka_tpu.runtime.registry import ProgramRegistry
from misaka_tpu.runtime.topology import Topology
from misaka_tpu.utils import faults

pytestmark = pytest.mark.skipif(
    not native_serve.available(), reason="native interpreter unavailable (no g++)"
)

SMALL = dict(stack_cap=8, in_cap=16, out_cap=16)

# Control-flow DIVERGENCE across replicas in one SIMD group: the branch a
# replica takes depends on its input's sign, so the 8 lanes of a group run
# different instructions at the same tick — the exact shape a masked/SoA
# rewrite gets wrong if arbitration or commit leaks across the replica axis.
DIVERGE = Topology(
    node_info={"p": "program"},
    programs={
        "p": (
            "IN ACC\n"
            "JGZ pos\n"
            "JLZ neg\n"
            "OUT 0\n"
            "JMP end\n"
            "pos: ADD 100\n"
            "OUT ACC\n"
            "JMP end\n"
            "neg: NEG\n"
            "OUT ACC\n"
            "end: NOP"
        )
    },
    **SMALL,
)


def topologies():
    return {
        "add2": networks.add2(**SMALL),
        "acc_loop": networks.acc_loop(**SMALL),
        "ring4": networks.ring(4, **SMALL),
        "diverge": DIVERGE,
    }


def state_dict(state: NetworkState) -> dict:
    return {f: np.asarray(getattr(state, f)) for f in NetworkState._fields}


def assert_state_equal(a: dict, b: dict, msg: str = ""):
    for f, av in a.items():
        np.testing.assert_array_equal(av, b[f], err_msg=f"{msg}: field {f}")


def run_schedule(net, mode: str | None, rounds: int = 8, spec: str | None = None,
                 threads: int = 6, seed: int = 3, active_fn=None):
    """One deterministic feed schedule through a NativeServePool under the
    given MISAKA_SIMD mode; returns (final state dict, [packed/ctr rows]).
    The schedule's randomness depends only on the seed, and ring headroom
    depends only on prior state — identical across modes by induction."""
    B = net.batch
    prev = os.environ.get("MISAKA_SIMD")
    if mode is None:
        os.environ.pop("MISAKA_SIMD", None)
    else:
        os.environ["MISAKA_SIMD"] = mode
    try:
        pool = native_serve.NativeServePool(
            net, chunk_steps=64, threads=threads, specialized=spec
        )
    finally:
        if prev is None:
            os.environ.pop("MISAKA_SIMD", None)
        else:
            os.environ["MISAKA_SIMD"] = prev
    rng = np.random.default_rng(seed)
    state = net.init_state()
    rows = []

    def materialize(st):
        # resident-state pools (r17) return their identity anchor with
        # stale contents; the schedule below reads ring counters (and the
        # final state_dict reads everything), so export each round — this
        # keeps the loop on the resident hit path AND pins the export's
        # coherence against every mode's reference run
        exported = pool.export_resident(st)
        return exported if exported is not None else st

    try:
        for it in range(rounds):
            if it % 4 == 3:
                state, ctrs = pool.idle(state, 32)
                state = materialize(state)
                rows.append(np.asarray(ctrs).copy())
                continue
            free = net.in_cap - (
                np.asarray(state.in_wr) - np.asarray(state.in_rd)
            )
            counts = np.minimum(
                rng.integers(0, net.in_cap + 1, size=B), free
            ).astype(np.int32)
            vals = rng.integers(
                np.iinfo(np.int32).min, np.iinfo(np.int32).max,
                size=(B, net.in_cap), dtype=np.int64,
            ).astype(np.int32)  # full int32 range: wrap arithmetic included
            active = active_fn(it, counts) if active_fn else None
            if active is not None:
                mask = np.zeros((B,), bool)
                mask[active] = True
                counts[~mask] = 0
            state, packed = pool.serve(state, vals, counts, active=active)
            state = materialize(state)
            packed = np.asarray(packed).copy()
            if active is not None:
                # skipped rows carry ONLY their counters (columns 4+ are
                # np.empty garbage by contract) — blank them for comparison
                skipped = np.ones((B,), bool)
                skipped[active] = False
                packed[skipped, 4:] = 0
            rows.append(packed)
        return state_dict(state), rows
    finally:
        pool.close()


@pytest.mark.parametrize("name", sorted(topologies()))
def test_simd_generic_scalar_bit_identity(name):
    """AVX2 group path vs generic group fallback vs scalar per-replica
    path: full-state bit-identity (tick counts included) over a mixed
    serve/idle schedule on a batch with both full groups and a scalar
    remainder (B=19 -> 2 group units + 3 stragglers)."""
    net = topologies()[name].compile(batch=19)
    d_auto, rows_auto = run_schedule(net, None)
    d_gen, rows_gen = run_schedule(net, "generic")
    d_off, rows_off = run_schedule(net, "0")
    assert_state_equal(d_auto, d_gen, f"{name}: avx2 vs generic")
    assert_state_equal(d_auto, d_off, f"{name}: simd vs scalar")
    for i, (ra, rb, rc) in enumerate(zip(rows_auto, rows_gen, rows_off)):
        np.testing.assert_array_equal(ra, rb, err_msg=f"{name} row {i}")
        np.testing.assert_array_equal(ra, rc, err_msg=f"{name} row {i}")


def test_partial_fill_active_lists_parity():
    """Active lists covering full groups, partial groups, and stragglers:
    the unit builder must route each correctly (group vs scalar) with
    results identical to the all-scalar path."""
    net = topologies()["add2"].compile(batch=24)

    def actives(it, counts):
        return [
            None,                                   # full batch
            list(range(0, 8)),                      # exactly one group
            list(range(0, 12)),                     # group + partial
            [1, 3, 8, 9, 10, 11, 12, 13, 14, 15, 23],  # stragglers + group
            [17],                                   # serial fast path
            list(range(8, 24)),                     # two aligned groups
        ][it % 6]

    d_simd, rows_simd = run_schedule(net, None, rounds=12, active_fn=actives)
    d_off, rows_off = run_schedule(net, "0", rounds=12, active_fn=actives)
    assert_state_equal(d_simd, d_off, "partial fill")
    for i, (ra, rb) in enumerate(zip(rows_simd, rows_off)):
        np.testing.assert_array_equal(ra, rb, err_msg=f"row {i}")


def test_forced_fallback_reports_and_matches():
    """Feature detection forced off (MISAKA_SIMD=generic): the pool must
    report the scalar-codegen group path (width 8, avx2 False) and produce
    the same outputs — the no-AVX2 ladder rung exercised on any box."""
    net = topologies()["acc_loop"].compile(batch=16)
    prev = os.environ.get("MISAKA_SIMD")
    os.environ["MISAKA_SIMD"] = "generic"
    try:
        pool = native_serve.NativeServePool(net, chunk_steps=32)
        info = pool.simd_info()
        pool.close()
    finally:
        if prev is None:
            os.environ.pop("MISAKA_SIMD", None)
        else:
            os.environ["MISAKA_SIMD"] = prev
    assert info == {
        "width": 8, "avx2": False, "specialized": False, "jit": False,
    }
    # and the kill switch reports the scalar path
    os.environ["MISAKA_SIMD"] = "0"
    try:
        pool = native_serve.NativeServePool(net, chunk_steps=32)
        assert pool.simd_info()["width"] == 0
        pool.close()
    finally:
        if prev is None:
            os.environ.pop("MISAKA_SIMD", None)
        else:
            os.environ["MISAKA_SIMD"] = prev


def masked_stack(arr, top):
    col = np.arange(arr.shape[-1])
    return np.where(col[None, None, :] < top[:, :, None], arr, 0)


def test_simd_vs_xla_batched_twins():
    """Three-way: the SIMD group path vs the jitted XLA batched serve
    twins, at a batch wide enough for full groups (the pre-existing
    pool-vs-XLA pin runs B=4, all-scalar units).  Tick counts included."""
    B = 16
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile(batch=B)
    serve_fn, idle_fn = net.make_batched_serve(None, 16)
    pool = native_serve.NativeServePool(net, chunk_steps=16, threads=6)
    assert pool.simd_info()["width"] == 8  # the group path is live
    s_dev, s_nat = net.init_state(), net.init_state()
    rng = np.random.default_rng(11)
    try:
        for it in range(10):
            if it % 4 == 3:
                s_dev, c_dev = idle_fn(s_dev)
                s_nat, c_nat = pool.idle(s_nat)
                s_nat = pool.export_resident(s_nat) or s_nat
                np.testing.assert_array_equal(np.asarray(c_dev), c_nat)
            else:
                free = net.in_cap - (
                    np.asarray(s_nat.in_wr) - np.asarray(s_nat.in_rd)
                )
                counts = np.minimum(
                    rng.integers(0, 6, size=B), free
                ).astype(np.int32)
                vals = np.zeros((B, net.in_cap), np.int32)
                for b in range(B):
                    vals[b, : counts[b]] = rng.integers(
                        -1000, 1000, size=counts[b]
                    )
                s_dev, p_dev = serve_fn(s_dev, vals, counts)
                s_nat, p_nat = pool.serve(s_nat, vals, counts)
                s_nat = pool.export_resident(s_nat) or s_nat
                np.testing.assert_array_equal(
                    np.asarray(p_dev), p_nat, err_msg=f"iter {it}"
                )
            a, b = state_dict(s_dev), state_dict(s_nat)
            for f in NetworkState._fields:
                if f == "stack_mem":
                    np.testing.assert_array_equal(
                        masked_stack(a[f], a["stack_top"]),
                        masked_stack(b[f], b["stack_top"]),
                        err_msg=f"iter {it}: stack_mem",
                    )
                else:
                    np.testing.assert_array_equal(
                        a[f], b[f], err_msg=f"iter {it}: {f}"
                    )
    finally:
        pool.close()


# --- per-program specialization ---------------------------------------------


def test_specialized_engages_and_matches(tmp_path):
    """A specialized build must engage (simd_info) and stay bit-identical
    to the generic group path and the scalar path; the second build of the
    same content is a cache hit."""
    net = topologies()["add2"].compile(batch=16)
    so = specialize.build(net, cache_dir=str(tmp_path))
    assert so is not None and os.path.exists(so)
    built = specialize.M_SPECIALIZE.labels(status="built").value
    hits = specialize.M_SPECIALIZE.labels(status="hit").value
    assert specialize.build(net, cache_dir=str(tmp_path)) == so
    assert specialize.M_SPECIALIZE.labels(status="built").value == built
    assert specialize.M_SPECIALIZE.labels(status="hit").value == hits + 1

    prev = os.environ.get("MISAKA_SIMD")
    os.environ.pop("MISAKA_SIMD", None)
    try:
        pool = native_serve.NativeServePool(net, chunk_steps=32, specialized=so)
        info = pool.simd_info()
        pool.close()
    finally:
        if prev is not None:
            os.environ["MISAKA_SIMD"] = prev
    assert info["specialized"] and info["width"] == 8

    d_spec, rows_spec = run_schedule(net, None, spec=so)
    d_gen, rows_gen = run_schedule(net, None)
    d_off, rows_off = run_schedule(net, "0")
    assert_state_equal(d_spec, d_gen, "spec vs generic")
    assert_state_equal(d_spec, d_off, "spec vs scalar")
    for i, (ra, rb, rc) in enumerate(zip(rows_spec, rows_gen, rows_off)):
        np.testing.assert_array_equal(ra, rb, err_msg=f"row {i}")
        np.testing.assert_array_equal(ra, rc, err_msg=f"row {i}")


def test_mismatched_specialization_degrades(tmp_path):
    """A spec .so keyed for ANOTHER program must load but NOT engage (the
    C++ side memcmps the baked tables) — and still compute correctly via
    the generic group path."""
    net_a = topologies()["add2"].compile(batch=16)
    net_b = topologies()["acc_loop"].compile(batch=16)
    so_a = specialize.build(net_a, cache_dir=str(tmp_path))
    assert so_a is not None
    fallback = specialize.M_SPECIALIZE.labels(status="fallback").value
    pool = native_serve.NativeServePool(net_b, chunk_steps=32, specialized=so_a)
    try:
        assert not pool.simd_info()["specialized"]
        assert specialize.M_SPECIALIZE.labels(
            status="fallback"
        ).value == fallback + 1
    finally:
        pool.close()
    d_mis, _ = run_schedule(net_b, None, spec=so_a, seed=9)
    d_ok, _ = run_schedule(net_b, "0", seed=9)
    assert_state_equal(d_mis, d_ok, "mismatched spec")


def test_specialized_checkpoint_roundtrip(tmp_path, monkeypatch):
    """Checkpoint/restore through a SPECIALIZED engine: state saved from a
    specialized master restores bit-identically into a fresh specialized
    master AND into a scalar-path master, and the continuation stream
    matches (the delay-line shape: outputs prove the restored state)."""
    # the JIT rung outranks specialization on the ladder (r21); pin it off
    # so this test exercises the spec rung it is about
    monkeypatch.setenv("MISAKA_JIT", "0")
    topo = Topology(
        node_info={"p": "program"},
        programs={"p": "IN ACC\nSWP\nOUT ACC\nSWP\nSAV\n"},  # delay line
        **SMALL,
    )
    spec_dir = str(tmp_path / "spec")
    masters = {}

    def build_master(spec: bool):
        prev = os.environ.get("MISAKA_SIMD")
        if not spec:
            os.environ["MISAKA_SIMD"] = "0"
        try:
            m = MasterNode(
                topo, chunk_steps=32, batch=16, engine="native",
                native_spec_dir=spec_dir if spec else None,
            )
        finally:
            if prev is None:
                os.environ.pop("MISAKA_SIMD", None)
            else:
                os.environ["MISAKA_SIMD"] = prev
        return m

    m_spec = build_master(spec=True)
    assert m_spec._runner.simd_info()["specialized"]
    # the /status observability block: execution ladder + cache outcomes
    native = m_spec.status()["native"]
    assert native["specialized"] and native["width"] == 8
    assert set(native["specialize_cache"]) == {
        "hit", "built", "error", "fallback", "disabled"
    }
    masters["spec"] = m_spec
    try:
        m_spec.run()
        first = m_spec.compute_many(list(range(1, 33)))
        ckpt = str(tmp_path / "spec.npz")
        m_spec.pause()
        m_spec.save_checkpoint(ckpt)

        for label, spec in (("spec2", True), ("scalar", False)):
            m2 = build_master(spec=spec)
            masters[label] = m2
            m2.load_checkpoint(ckpt)
            # restored state is bit-identical to the checkpointed master's
            assert_state_equal(
                state_dict(m2._state), state_dict(m_spec._state),
                f"restore into {label}",
            )
            m2.run()
            cont = m2.compute_many([100, 200, 300])
            m2.pause()
            # the delay line's continuation proves live state: the first
            # restored output is the LAST pre-checkpoint input
            assert list(cont) == [32, 100, 200], (label, list(cont))
        assert list(first) == [0] + list(range(1, 32))
    finally:
        for m in masters.values():
            m.close()


def test_specialize_fail_chaos_graceful_fallback(tmp_path, monkeypatch):
    """The specialize_fail fault at the compile site: registry activation
    must SUCCEED on the generic interpreter, the failure must count on
    misaka_native_specialize_total{status="error"}, and clients see zero
    errors."""
    monkeypatch.setenv("MISAKA_JIT", "0")  # pin the spec rung (see above)
    errors = specialize.M_SPECIALIZE.labels(status="error").value
    faults.configure("specialize_fail")
    try:
        reg = ProgramRegistry(
            str(tmp_path), batch=16, engine="native", chunk_steps=32,
            caps=SMALL,
        )
        try:
            reg.publish("victim", tis="IN ACC\nADD 7\nOUT ACC\n")
            with reg.lease("victim", values=3) as m:
                out = m.compute_many([1, 2, 3])
                assert list(out) == [8, 9, 10]
                assert not m._runner.simd_info()["specialized"]
        finally:
            reg.close()
    finally:
        faults.configure(None)
    assert specialize.M_SPECIALIZE.labels(status="error").value > errors
    # disarmed again: the same store now specializes on reactivation
    reg = ProgramRegistry(
        str(tmp_path), batch=16, engine="native", chunk_steps=32, caps=SMALL,
    )
    try:
        with reg.lease("victim", values=3) as m:
            out = m.compute_many([4, 5, 6])
            assert list(out) == [11, 12, 13]
            assert m._runner.simd_info()["specialized"]
    finally:
        reg.close()
