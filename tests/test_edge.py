"""The production edge (runtime/edge.py): middleware chain, auth, quotas,
admission control, priority lanes, plane handshake, and TLS.

Covers the chain itself (ordering, per-route composition, kill switches),
the key file (scopes, hot reload, malformed-file behavior), the typed
401/403/429 contract on every surface — direct engine HTTP, the frontend
compute plane, and the fleet control server — plus the ServeBatcher's
priority lanes and the chaos points (`overload[:<tenant>]`,
`quota_exhaust`) at the real admission sites.
"""

import http.client
import json
import os
import shutil
import socket
import struct
import subprocess
import threading
import time
import urllib.error

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.client import MisakaClient, MisakaClientError
from misaka_tpu.runtime import edge
from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.utils import faults


def _master(batch=4, engine="scan", **kw):
    return MasterNode(
        networks.add2(in_cap=16, out_cap=16, stack_cap=16),
        chunk_steps=32, batch=batch, engine=engine, **kw,
    )


def _write_keys(path, entries) -> str:
    with open(path, "w") as f:
        json.dump({"keys": entries}, f)
    return str(path)


KEYS = [
    {"key": "adm-secret", "tenant": "ops", "admin": True},
    {"key": "bob-secret", "tenant": "bob", "quota": "rps<2"},
    {"key": "eve-secret", "tenant": "eve", "disabled": True},
    {"key": "pin-secret", "tenant": "pin", "programs": ["dense"]},
]


@pytest.fixture(autouse=True)
def _edge_cleanup():
    yield
    edge.reset()
    faults.configure(None)


@pytest.fixture
def served(tmp_path, monkeypatch):
    """An engine HTTP server with the edge armed: key file + env quota."""
    kf = _write_keys(tmp_path / "keys.json", KEYS)
    monkeypatch.setenv("MISAKA_API_KEYS", kf)
    m = _master(batch=2)
    m.run()
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield m, httpd.server_address[1], kf
    finally:
        m.pause()
        httpd.shutdown()


# --- chain units ------------------------------------------------------------


def test_quota_spec_grammar():
    assert edge.parse_quota_spec("rps<100,vps<50000,cpu<0.5") == {
        "rps": 100.0, "vps": 50000.0, "cpu": 0.5,
    }
    assert edge.parse_quota_spec("rps=3") == {"rps": 3.0}
    assert edge.parse_quota_spec(None) == {}
    assert edge.parse_quota_spec("") == {}
    for bad in ("zps<1", "rps<abc", "rps<0", "rps<-1", "rps"):
        with pytest.raises(edge.QuotaSpecError):
            edge.parse_quota_spec(bad)


def test_token_bucket_math():
    b = edge.TokenBucket(10.0, burst_s=2.0)  # capacity 20
    ok, _ = b.take(20)
    assert ok
    ok, retry = b.take(1)
    assert not ok and 0 < retry <= 0.2
    time.sleep(0.15)
    ok, _ = b.take(1)  # ~1.5 tokens refilled
    assert ok


def test_route_policy_composition():
    assert edge.route_policy("/healthz", "GET") == ()
    assert edge.route_policy("/metrics", "GET") == ()
    assert edge.route_policy("/compute") == ("auth", "quota", "admission")
    assert edge.route_policy("/compute_raw") == (
        "auth", "quota", "admission")
    for admin in ("/run", "/pause", "/load", "/checkpoint", "/fleet/roll"):
        assert edge.route_policy(admin) == ("auth_admin",)
    assert edge.route_policy("/programs", "POST") == ("auth_admin",)
    assert edge.route_policy("/programs", "GET") == ("auth",)
    assert edge.route_policy("/status", "GET") == ("auth",)
    assert edge.route_policy("/debug/usage", "GET") == ("auth",)


def test_chain_ordering_auth_rejects_before_quota(tmp_path):
    """The chain is ORDERED: an unauthenticated request must answer 401,
    never leak that a quota exists (or bill a bucket)."""
    kf = edge.KeyFile(_write_keys(tmp_path / "k.json", KEYS))
    chain = edge.EdgeChain(keyfile=kf, quota_defaults={"rps": 0.001})
    d = chain.check("/compute", key="wrong")
    assert d.reject is not None and d.reject.status == 401
    assert d.reject.reason == "unauthenticated"
    # a valid key then hits the quota stage
    ok = chain.check("/compute", key="bob-secret")
    assert ok.tenant == "bob" and ok.reject is None  # burst tokens
    for _ in range(8):
        d = chain.check("/compute", key="bob-secret")
        if d.reject is not None:
            break
    assert d.reject is not None and d.reject.status == 429
    assert d.reject.retry_after is not None and d.reject.retry_after > 0


def test_key_scopes_admin_programs_disabled(tmp_path):
    kf = edge.KeyFile(_write_keys(tmp_path / "k.json", KEYS))
    chain = edge.EdgeChain(keyfile=kf)
    # admin route needs admin scope
    assert chain.check("/pause", key="adm-secret").reject is None
    d = chain.check("/pause", key="bob-secret")
    assert d.reject is not None and d.reject.status == 403
    # disabled key: 403 everywhere
    d = chain.check("/compute", key="eve-secret")
    assert d.reject is not None and d.reject.status == 403
    # program allowlist: 403 outside it, admitted inside
    assert chain.check(
        "/compute", key="pin-secret", program="dense"
    ).reject is None
    assert chain.check(
        "/compute", key="pin-secret", program="dense@abc123"
    ).reject is None
    d = chain.check("/compute", key="pin-secret", program="compact")
    assert d.reject is not None and d.reject.status == 403
    # missing key on a guarded route
    d = chain.check("/compute", key=None)
    assert d.reject is not None and d.reject.status == 401


def test_keyfile_hot_reload_and_malformed(tmp_path):
    path = tmp_path / "k.json"
    _write_keys(path, [{"key": "a", "tenant": "t1"}])
    kf = edge.KeyFile(str(path))
    assert kf.lookup("a")["tenant"] == "t1"
    assert kf.lookup("b") is None
    # rotate: stat throttle is 0.5s, so age past it and bump mtime
    time.sleep(0.6)
    _write_keys(path, [{"key": "b", "tenant": "t2"}])
    os.utime(path, (time.time() + 5, time.time() + 5))
    assert kf.lookup("b")["tenant"] == "t2"
    assert kf.lookup("a") is None
    # a malformed rewrite KEEPS the previous table (never opens the edge,
    # never locks everyone out)
    time.sleep(0.6)
    with open(path, "w") as f:
        f.write("{not json")
    os.utime(path, (time.time() + 10, time.time() + 10))
    assert kf.lookup("b")["tenant"] == "t2"


def test_kill_switches():
    base = {"MISAKA_API_KEYS": "/nonexistent-keys.json",
            "MISAKA_QUOTA": "rps<1"}
    # master switch disarms everything
    chain = edge.from_env(signals=lambda: (0, False),
                          environ={**base, "MISAKA_EDGE": "0"})
    assert not chain.armed
    # per-stage switches
    chain = edge.from_env(signals=lambda: (0, False),
                          environ={**base, "MISAKA_EDGE_AUTH": "0"})
    assert chain.keyfile is None and chain.quota_enabled
    chain = edge.from_env(signals=lambda: (0, False),
                          environ={**base, "MISAKA_EDGE_QUOTA": "0"})
    assert not chain.quota_enabled and chain.governor is not None
    chain = edge.from_env(signals=lambda: (0, False),
                          environ={**base, "MISAKA_EDGE_ADMISSION": "0"})
    assert chain.governor is None
    # quota without auth: the program label is the tenant
    chain = edge.from_env(signals=lambda: (0, False),
                          environ={"MISAKA_QUOTA": "rps<1"})
    got_429 = False
    for _ in range(5):
        d = chain.check("/compute", program="p1")
        if d.reject is not None:
            got_429 = True
            assert d.reject.status == 429 and d.tenant == "p1"
            break
    assert got_429


def test_admission_fair_share_sheds_flooder_first():
    waiting = [0]
    gov = edge.AdmissionGovernor(lambda: (waiting[0], False), 1000)
    # below the watermark: everyone flows (and builds window history:
    # the flooder holds ~97% of admitted values)
    for _ in range(40):
        assert gov.check("flood", 100) is None
    for _ in range(3):
        assert gov.check("good", 40) is None
    # soft zone: the over-share tenant sheds, the neighbor keeps flowing
    waiting[0] = 1500
    rej = gov.check("flood", 100)
    assert rej is not None and rej.status == 429
    assert rej.reason == "overload" and rej.retry_after > 0
    assert gov.check("good", 40) is None
    # hard cap: everyone sheds
    waiting[0] = 2500
    assert gov.check("good", 40) is not None
    assert gov.check("flood", 100) is not None


def test_admission_single_tenant_rides_to_hard_cap():
    waiting = [1500]
    gov = edge.AdmissionGovernor(lambda: (waiting[0], False), 1000)
    # one tenant in the soft zone: no one to be fair to — admit
    assert gov.check("only", 100) is None
    waiting[0] = 2500
    assert gov.check("only", 100) is not None


def test_admission_slo_page_halves_watermark():
    page = [False]
    gov = edge.AdmissionGovernor(lambda: (700, page[0]), 1000)
    for _ in range(20):
        assert gov.check("flood", 100) is None
    assert gov.check("good", 10) is None
    # 700 < soft(1000) while ok; page halves soft to 500 -> fair-share
    # zone engages and the over-share tenant sheds
    page[0] = True
    assert gov.check("flood", 100) is not None
    assert gov.check("good", 10) is None


def test_chaos_overload_and_quota_exhaust_points():
    chain = edge.EdgeChain(
        governor=edge.AdmissionGovernor(lambda: (0, False), 1000),
    )
    # scoped overload: only the named tenant sheds, at the REAL site
    faults.configure("overload:noisy")
    d = chain.check("/compute", program="noisy")
    assert d.reject is not None and d.reject.status == 429
    assert d.reject.reason == "overload"
    assert chain.check("/compute", program="quiet").reject is None
    # unscoped overload sheds everyone
    faults.configure("overload")
    assert chain.check("/compute", program="quiet").reject is not None
    # quota_exhaust trips the quota stage even with no spec configured
    faults.configure("quota_exhaust")
    d = chain.check("/compute", program="quiet")
    assert d.reject is not None and d.reject.status == 429
    assert d.reject.retry_after is not None
    faults.configure(None)
    assert chain.check("/compute", program="quiet").reject is None


def test_reject_wire_round_trip():
    r = edge.EdgeReject(429, "rate", "slow down", retry_after=2.5)
    back = edge.EdgeReject.from_wire(429, r.to_wire())
    assert back.reason == "rate" and back.retry_after == 2.5
    assert back.message == "slow down"
    assert ("Retry-After", "3") in r.headers()
    assert edge.EdgeReject.from_wire(429, b"not an edge body") is None
    # 401s carry the auth challenge
    assert any(
        k == "WWW-Authenticate"
        for k, _ in edge.EdgeReject(401, "unauthenticated", "x").headers()
    )


def test_program_quota_precedence(tmp_path):
    """Field-wise precedence: key > program > env default."""
    kf = edge.KeyFile(_write_keys(
        tmp_path / "k.json",
        [{"key": "k1", "tenant": "t1", "quota": "rps<7"}],
    ))
    chain = edge.EdgeChain(
        keyfile=kf, quota_defaults={"rps": 1.0, "vps": 100.0},
    )
    chain.set_program_quota("p", "rps<3,cpu<0.5")
    q = chain._effective_quota(kf.lookup("k1"), "p@deadbeef")
    assert q == {"rps": 7.0, "vps": 100.0, "cpu": 0.5}
    q = chain._effective_quota(None, "p")
    assert q == {"rps": 3.0, "vps": 100.0, "cpu": 0.5}
    q = chain._effective_quota(None, "other")
    assert q == {"rps": 1.0, "vps": 100.0}
    # clearing restores the env default
    chain.set_program_quota("p", None)
    assert chain._effective_quota(None, "p") == {"rps": 1.0, "vps": 100.0}
    with pytest.raises(edge.QuotaSpecError):
        chain.set_program_quota("p", "bogus<1")


def test_cpu_meter_sliding_window():
    meter = edge.CpuMeter(window_s=10.0)
    ok, _ = meter.check(0.0, 0.5)  # budget: 5 core-seconds per window
    assert ok
    ok, retry = meter.check(20.0, 0.5)  # 20s consumed in one hop
    assert not ok and 1.0 <= retry <= 10.0


# --- the direct engine surface ----------------------------------------------


def test_http_typed_rejections_and_open_routes(served):
    m, port, kf = served
    base = f"http://127.0.0.1:{port}"
    anon = MisakaClient(base, api_key="")
    anon.api_key = None
    # open routes answer without credentials (probes + scrapers)
    assert anon.healthz()["ok"] is True
    assert "misaka_edge_rejected_total" in anon.metrics()
    # the /healthz ops view of the door
    assert anon.healthz()["edge"]["auth"] is True
    # 401 without a key: compute AND introspection
    for call in (lambda: anon.compute(1), anon.status):
        with pytest.raises(MisakaClientError) as ei:
            call()
        assert ei.value.status == 401
    # Authorization: Bearer works like X-Misaka-Key
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("POST", "/run", b"", {"Authorization": "Bearer adm-secret"})
    r = conn.getresponse()
    assert r.status == 200 and r.read() == b"Success"
    # 401 carries the challenge header
    conn.request("POST", "/compute", b"value=1")
    r = conn.getresponse()
    assert r.status == 401 and r.getheader("WWW-Authenticate")
    r.read()
    conn.close()
    adm = MisakaClient(base, api_key="adm-secret")
    assert int(adm.compute(7)) == 9
    # 403: valid key without admin scope on a lifecycle route
    bob = MisakaClient(base, api_key="bob-secret")
    with pytest.raises(MisakaClientError) as ei:
        bob.pause()
    assert ei.value.status == 403
    # 403: disabled (revoked-in-place) key
    with pytest.raises(MisakaClientError) as ei:
        MisakaClient(base, api_key="eve-secret").compute(1)
    assert ei.value.status == 403
    # 429 with Retry-After once bob's rps<2 burst is gone
    statuses = []
    for _ in range(10):
        try:
            bob.compute(1)
            statuses.append(200)
        except MisakaClientError as e:
            statuses.append(e.status)
            assert e.status == 429
            assert e.retry_after is not None and e.retry_after > 0
            break
    assert statuses[-1] == 429
    # the rejection series carries reason + tenant labels
    text = adm.metrics()
    assert 'misaka_edge_rejected_total{reason="rate",tenant="bob"}' in text
    assert 'reason="unauthenticated"' in text


def test_http_keyfile_hot_reload_rotation(served):
    m, port, kf = served
    base = f"http://127.0.0.1:{port}"
    bob = MisakaClient(base, api_key="bob-secret")
    assert int(bob.compute(1)) == 3
    time.sleep(0.6)
    _write_keys(kf, [{"key": "bob-rotated", "tenant": "bob"}])
    os.utime(kf, (time.time() + 5, time.time() + 5))
    with pytest.raises(MisakaClientError) as ei:
        bob.compute(1)
    assert ei.value.status == 401
    assert int(MisakaClient(base, api_key="bob-rotated").compute(1)) == 3


def test_edge_fully_disarmed_is_byte_compatible(monkeypatch):
    """No key file, no quota env: every pre-edge behavior is intact
    (the default-env compatibility contract)."""
    monkeypatch.delenv("MISAKA_API_KEYS", raising=False)
    monkeypatch.delenv("MISAKA_QUOTA", raising=False)
    m = _master(batch=2)
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        c = MisakaClient(f"http://127.0.0.1:{httpd.server_address[1]}")
        c.run()
        assert int(c.compute(5)) == 7
        assert c.status()["running"] is True
    finally:
        m.pause()
        httpd.shutdown()


# --- priority lanes in the ServeBatcher -------------------------------------


def test_priority_lanes_small_preempts_bulk(monkeypatch):
    """Hot-lane entries cut into passes ahead of a bulk entry's
    remaining stripes: every small request finishes while the bulk
    stream is still being served."""
    monkeypatch.setenv("MISAKA_LANE_SMALL", "64")
    m = _master(batch=4)
    m.run()
    try:
        done: dict[str, float] = {}
        bulk_vals = np.arange(4096, dtype=np.int32)  # 64 passes at 4x16

        def run_bulk():
            out = m.compute_coalesced(bulk_vals, timeout=120,
                                      return_array=True)
            done["bulk"] = time.monotonic()
            np.testing.assert_array_equal(out, bulk_vals + 2)

        t = threading.Thread(target=run_bulk)
        t.start()
        time.sleep(0.05)  # let the bulk entry occupy the scheduler
        smalls = []
        for i in range(6):
            def run_small(i=i):
                out = m.compute_coalesced(
                    np.arange(8, dtype=np.int32) + i, timeout=120,
                    return_array=True,
                )
                done[f"s{i}"] = time.monotonic()
                np.testing.assert_array_equal(
                    out, np.arange(8, dtype=np.int32) + i + 2
                )
            st = threading.Thread(target=run_small)
            st.start()
            smalls.append(st)
        for st in smalls:
            st.join(120)
        t.join(120)
        assert "bulk" in done and all(f"s{i}" in done for i in range(6))
        # the preemption contract: every small beat the bulk stream out
        assert max(done[f"s{i}"] for i in range(6)) < done["bulk"]
    finally:
        m.pause()


def test_priority_lane_metric_and_kill_switch(monkeypatch):
    from misaka_tpu.utils import metrics as metrics_mod
    from misaka_tpu.runtime.master import M_SERVE_LANE_ENTRIES

    monkeypatch.setenv("MISAKA_LANE_SMALL", "0")  # single lane: all bulk
    m = _master(batch=2)
    m.run()
    try:
        before = M_SERVE_LANE_ENTRIES.labels(lane="bulk").value
        m.compute_coalesced(np.arange(4, dtype=np.int32))
        assert M_SERVE_LANE_ENTRIES.labels(lane="bulk").value == before + 1
    finally:
        m.pause()
    monkeypatch.setenv("MISAKA_LANE_SMALL", "8192")
    m2 = _master(batch=2)
    m2.run()
    try:
        before = M_SERVE_LANE_ENTRIES.labels(lane="hot").value
        m2.compute_coalesced(np.arange(4, dtype=np.int32))
        assert M_SERVE_LANE_ENTRIES.labels(lane="hot").value == before + 1
    finally:
        m2.pause()


# --- the frontend compute-plane surface -------------------------------------


@pytest.fixture
def frontend_edge(tmp_path, monkeypatch):
    """Engine + compute plane + in-process frontend worker, edge armed."""
    from misaka_tpu.runtime import frontends

    kf = _write_keys(tmp_path / "keys.json", KEYS)
    monkeypatch.setenv("MISAKA_API_KEYS", kf)
    m = _master(batch=4)
    engine_httpd = make_http_server(m, port=0)
    threading.Thread(target=engine_httpd.serve_forever, daemon=True).start()
    plane_path = str(tmp_path / "plane.sock")
    plane = frontends.start_compute_plane(m, plane_path)
    fe = frontends.make_frontend_server(
        0, f"http://127.0.0.1:{engine_httpd.server_address[1]}",
        plane_path, plane_conns=2,
    )
    threading.Thread(target=fe.serve_forever, daemon=True).start()
    m.run()
    try:
        yield m, fe.server_address[1]
    finally:
        m.pause()
        fe.shutdown()
        plane.close()
        engine_httpd.shutdown()


def test_plane_auth_and_quota_typing(frontend_edge):
    """The frame-level edge: 401/403/429 decided engine-side per frame,
    typed headers restored by the worker."""
    m, port = frontend_edge
    vals = np.arange(8, dtype=np.int32).astype("<i4").tobytes()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    # no key -> 401 through the plane, with the auth challenge
    conn.request("POST", "/compute_raw?spread=1", vals)
    r = conn.getresponse()
    assert r.status == 401 and r.getheader("WWW-Authenticate")
    r.read()
    # valid key -> served
    conn.request("POST", "/compute_raw?spread=1", vals,
                 {"X-Misaka-Key": "adm-secret"})
    r = conn.getresponse()
    assert r.status == 200
    np.testing.assert_array_equal(
        np.frombuffer(r.read(), dtype="<i4"),
        np.arange(8, dtype=np.int32) + 2,
    )
    # disabled key -> 403 through the plane
    conn.request("POST", "/compute_raw?spread=1", vals,
                 {"X-Misaka-Key": "eve-secret"})
    r = conn.getresponse()
    assert r.status == 403
    r.read()
    # bob's rps<2: burst out the bucket -> 429 WITH Retry-After header
    status, retry_after = None, None
    for _ in range(10):
        conn.request("POST", "/compute_raw?spread=1", vals,
                     {"X-Misaka-Key": "bob-secret"})
        r = conn.getresponse()
        status = r.status
        retry_after = r.getheader("Retry-After")
        r.read()
        if status == 429:
            break
    assert status == 429 and retry_after is not None
    assert float(retry_after) > 0
    # the proxied scalar lifecycle path carries credentials to the engine
    conn.request("POST", "/compute_batch", b"values=1+2+3",
                 {"X-Misaka-Key": "adm-secret",
                  "Content-Type": "application/x-www-form-urlencoded"})
    r = conn.getresponse()
    assert r.status == 200
    assert json.loads(r.read())["values"] == [3, 4, 5]
    conn.close()


def test_plane_handshake_gates_connections(tmp_path, monkeypatch):
    """MISAKA_PLANE_SECRET: a client presenting the HMAC serves frames;
    a raw connection without it is cut before any frame is read."""
    from misaka_tpu.runtime import frontends

    monkeypatch.setenv("MISAKA_PLANE_SECRET", "sesame")
    m = _master(batch=2)
    plane_path = str(tmp_path / "plane.sock")
    plane = frontends.start_compute_plane(m, plane_path)
    m.run()
    try:
        client = frontends.PlaneClient(plane_path, conns=1)
        out = client.compute_raw(
            np.arange(4, dtype=np.int32).astype("<i4").tobytes()
        )
        np.testing.assert_array_equal(
            np.frombuffer(out, dtype="<i4"),
            np.arange(4, dtype=np.int32) + 2,
        )
        client.close()
        # no handshake: the engine closes the connection unanswered
        raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        raw.settimeout(5)
        raw.connect(plane_path)
        raw.sendall(struct.pack("<II", 1, 0) + struct.pack("<i", 1))
        # the bytes we sent are consumed as a (bad) handshake and the
        # server hangs up: EOF or a reset, never a served frame
        try:
            raw.sendall(b"\x00" * 24)
            assert raw.recv(8) == b""
        except ConnectionError:
            pass
        raw.close()
        # wrong secret: same cut
        monkeypatch.setenv("MISAKA_PLANE_SECRET", "wrong")
        bad = frontends.PlaneClient(plane_path, conns=1)
        with pytest.raises(frontends.PlaneError):
            bad.compute_raw(
                np.arange(4, dtype=np.int32).astype("<i4").tobytes(),
                timeout=5,
            )
        bad.close()
    finally:
        m.pause()
        plane.close()


# --- the fleet control surface ----------------------------------------------


def test_fleet_control_auth(tmp_path, monkeypatch):
    """The operator surface rejects bad keys at the control server
    itself (a roll is not proxied, so no replica would)."""
    from misaka_tpu.runtime.fleet import FleetManager, make_fleet_http_server

    kf = _write_keys(tmp_path / "keys.json", KEYS)
    monkeypatch.setenv("MISAKA_API_KEYS", kf)
    fm = FleetManager(2, str(tmp_path / "fleet"))
    ctrl = None
    try:
        ctrl = make_fleet_http_server(fm, port=0)
        threading.Thread(target=ctrl.serve_forever, daemon=True).start()
        conn = http.client.HTTPConnection(
            "127.0.0.1", ctrl.server_address[1], timeout=10
        )
        # 401: no key on the operator route
        conn.request("POST", "/fleet/roll", b"")
        r = conn.getresponse()
        assert r.status == 401 and r.getheader("WWW-Authenticate")
        r.read()
        # 403: non-admin key
        conn.request("POST", "/fleet/roll", b"",
                     {"X-Misaka-Key": "bob-secret"})
        r = conn.getresponse()
        assert r.status == 403
        r.read()
        # 401 on lifecycle fan-out too
        conn.request("POST", "/pause", b"")
        r = conn.getresponse()
        assert r.status == 401
        r.read()
        # admitted past auth: the admin key reaches the route body (503
        # here — no replica is up in this stub fleet)
        conn.request("POST", "/pause", b"",
                     {"X-Misaka-Key": "adm-secret"})
        r = conn.getresponse()
        assert r.status == 503
        r.read()
        # open routes stay open on the control surface
        conn.request("GET", "/healthz")
        r = conn.getresponse()
        assert r.status == 200
        r.read()
        conn.close()
    finally:
        if ctrl is not None:
            ctrl.shutdown()
        fm.close()


# --- TLS on the HTTP edge ---------------------------------------------------


@pytest.fixture(scope="module")
def tls_certs(tmp_path_factory):
    if shutil.which("openssl") is None:
        pytest.skip("openssl unavailable")
    d = tmp_path_factory.mktemp("edge-certs")
    cert, key = str(d / "service.pem"), str(d / "service.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "ec",
            "-pkeyopt", "ec_paramgen_curve:prime256v1", "-nodes",
            "-keyout", key, "-out", cert, "-days", "1",
            "-subj", "/CN=localhost",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True, capture_output=True,
    )
    return cert, key


def test_tls_engine_listener_and_client(tls_certs, monkeypatch):
    cert, key = tls_certs
    monkeypatch.setenv("MISAKA_TLS_CERT", cert)
    monkeypatch.setenv("MISAKA_TLS_KEY", key)
    m = _master(batch=2)
    httpd = make_http_server(m, port=0)
    assert getattr(httpd, "misaka_tls", False)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    try:
        # CA-pinned client round-trips over TLS
        c = MisakaClient(f"https://127.0.0.1:{port}", ca=cert)
        c.run()
        assert int(c.compute(5)) == 7
        assert c.healthz()["ok"] is True
        c.close()
        # a client that does NOT trust the self-signed cert is refused
        bad = MisakaClient(f"https://127.0.0.1:{port}", timeout=5)
        with pytest.raises(urllib.error.URLError):
            bad.healthz()
        bad.close()
        # plain HTTP against the TLS port fails the handshake
        plain = MisakaClient(f"http://127.0.0.1:{port}", timeout=5,
                             connect_retries=0, retry_stale=False)
        with pytest.raises(urllib.error.URLError):
            plain.healthz()
        plain.close()
    finally:
        m.pause()
        httpd.shutdown()


def test_tls_env_validation(monkeypatch):
    monkeypatch.setenv("MISAKA_TLS_CERT", "/nonexistent.pem")
    monkeypatch.delenv("MISAKA_TLS_KEY", raising=False)
    with pytest.raises(ValueError):
        edge.tls_context_from_env()
    monkeypatch.setenv("MISAKA_TLS_KEY", "/nonexistent.key")
    with pytest.raises(OSError):
        edge.tls_context_from_env()
    monkeypatch.delenv("MISAKA_TLS_CERT", raising=False)
    monkeypatch.delenv("MISAKA_TLS_KEY", raising=False)
    assert edge.tls_context_from_env() is None


# --- client surface ---------------------------------------------------------


def test_client_api_key_env_default(monkeypatch):
    monkeypatch.setenv("MISAKA_API_KEY", "env-key")
    c = MisakaClient("http://localhost:1")
    assert c.api_key == "env-key"
    c2 = MisakaClient("http://localhost:1", api_key="explicit")
    assert c2.api_key == "explicit"
    monkeypatch.delenv("MISAKA_API_KEY")
    c3 = MisakaClient("http://localhost:1")
    assert c3.api_key is None


def test_client_rejects_unknown_scheme():
    with pytest.raises(ValueError):
        MisakaClient("ftp://localhost:8000")


# --- per-program quota overrides via upload metadata ------------------------


def test_registry_quota_upload_override(monkeypatch):
    """The `quota` upload field (like `slo`): validated compile-first,
    installed into the edge chain when the version becomes latest, and
    enforced per program — without auth the program label IS the
    tenant, so only the uploaded program's tenant sheds."""
    from misaka_tpu import networks as _networks
    from misaka_tpu.runtime.master import MasterNode as _MasterNode
    from misaka_tpu.runtime.registry import ProgramRegistry, RegistryError

    monkeypatch.delenv("MISAKA_API_KEYS", raising=False)
    small = dict(stack_cap=16, in_cap=16, out_cap=16)
    reg = ProgramRegistry(None, batch=2, engine="scan", chunk_steps=32,
                          caps=small)
    top = _networks.add2(**small)
    m = _MasterNode(top, chunk_steps=32, batch=2, engine="scan")
    reg.seed("default", m, top)
    m.run()
    httpd = make_http_server(m, port=0, registry=reg)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        # a malformed quota spec is a 400 that touches nothing
        with pytest.raises(RegistryError):
            reg.publish("bad", tis="IN ACC\nADD 1\nOUT ACC\n",
                        quota_spec="zps<1")
        reg.publish("tight", tis="IN ACC\nADD 10\nOUT ACC\n",
                    quota_spec="rps<1")
        c = MisakaClient(base, program="tight")
        assert int(c.compute(1)) == 11  # burst tokens
        statuses = []
        for _ in range(6):
            try:
                c.compute(1)
                statuses.append(200)
            except MisakaClientError as e:
                statuses.append(e.status)
                assert e.status == 429
                assert e.retry_after is not None
                break
        assert statuses[-1] == 429
        # the default program's tenant is untouched by the override
        d = MisakaClient(base)
        for i in range(6):
            assert int(d.compute(i)) == i + 2
        # republishing latest WITHOUT a quota clears the override
        reg.publish("tight", tis="IN ACC\nADD 11\nOUT ACC\n")
        time.sleep(0.1)
        for _ in range(6):
            assert int(c.compute(1)) == 12
        c.close()
        d.close()
    finally:
        m.pause()
        reg.close()
        httpd.shutdown()


def test_oversized_request_gets_terminal_413_not_retry_loop():
    """A request larger than the vps burst capacity can NEVER be
    admitted: it must answer a terminal 413, not a finite Retry-After
    that sends a compliant client into an infinite retry loop."""
    chain = edge.EdgeChain(quota_defaults={"vps": 10.0}, burst_s=2.0)
    d = chain.check("/compute_raw", program="p", values=100)
    assert d.reject is not None
    assert d.reject.status == 413 and d.reject.reason == "values"
    assert d.reject.retry_after is None
    # a request within capacity still gets the 429 + Retry-After shape
    chain.check("/compute_raw", program="p", values=20)  # drain burst
    d = chain.check("/compute_raw", program="p", values=15)
    assert d.reject is not None and d.reject.status == 429
    assert d.reject.retry_after is not None


def test_bucket_not_reset_by_program_quota_alternation(tmp_path):
    """ONE tenant alternating between programs with different quota
    overrides must not get a fresh full-burst bucket on every flip
    (that recreation was a complete rate-limit bypass): each
    (tenant, rate) pair is its own bounded bucket."""
    kf = edge.KeyFile(_write_keys(
        tmp_path / "k.json", [{"key": "k", "tenant": "t"}]
    ))
    chain = edge.EdgeChain(
        keyfile=kf, quota_defaults={"rps": 2.0}, burst_s=2.0,
    )
    chain.set_program_quota("slow", "rps<1")
    admitted = 0
    for i in range(40):
        prog = "slow" if i % 2 else "fast"
        d = chain.check("/compute", key="k", program=prog)
        assert d.tenant == "t"
        if d.reject is None:
            admitted += 1
    # one tenant, two buckets (rates 2.0 and 1.0): admissions bounded by
    # the two burst capacities (4 + 2) plus a trickle of refill — the
    # recreation bug admitted all 40
    assert admitted <= 12


def test_sustained_hot_stream_does_not_starve_bulk(monkeypatch):
    """The anti-starvation reservation: with the hot lane saturated
    continuously, an admitted bulk entry still gets its slice of every
    pass and completes (strict priority would park it until
    ComputeTimeout)."""
    monkeypatch.setenv("MISAKA_LANE_SMALL", "64")
    m = _master(batch=4)
    m.run()
    stop = threading.Event()
    errors = []

    def hot_spam():
        vals = np.arange(16, dtype=np.int32)
        try:
            while not stop.is_set():
                out = m.compute_coalesced(vals, timeout=60,
                                          return_array=True)
                np.testing.assert_array_equal(out, vals + 2)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=hot_spam) for _ in range(6)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.1)  # hot lane saturated before the bulk arrives
        bulk = np.arange(2048, dtype=np.int32)
        out = m.compute_coalesced(bulk, timeout=60, return_array=True)
        np.testing.assert_array_equal(out, bulk + 2)
    finally:
        stop.set()
        for t in threads:
            t.join(30)
        m.pause()
    assert not errors


def test_fleet_internal_token_admits_admin_routes(tmp_path):
    """The fleet parent's per-boot internal token must pass the
    replica-side chain as an admin credential (an authenticated fleet
    could otherwise never drain/checkpoint its own replicas mid-roll),
    while any other token stays a 401."""
    kf = edge.KeyFile(_write_keys(tmp_path / "k.json", KEYS))
    chain = edge.EdgeChain(keyfile=kf, internal_token="boot-secret")
    for route in ("/fleet/drain", "/checkpoint", "/pause"):
        d = chain.check(route, key="boot-secret")
        assert d.reject is None and d.tenant == "_fleet"
    d = chain.check("/fleet/drain", key="not-the-token")
    assert d.reject is not None and d.reject.status == 401
    # token unset: nothing special about the string
    plain = edge.EdgeChain(keyfile=kf)
    assert plain.check("/fleet/drain", key="boot-secret").reject.status == 401


def test_keyfile_strips_cpu_from_key_quota(tmp_path):
    """cpu budgets are per-program (the ledger's attribution unit): a
    key-level cpu field is ignored at load — billing one tenant for a
    program all tenants share would shed the innocent one."""
    kf = edge.KeyFile(_write_keys(
        tmp_path / "k.json",
        [{"key": "k", "tenant": "t", "quota": "rps<5,cpu<0.1"}],
    ))
    entry = kf.lookup("k")
    assert entry["quota_spec"] == {"rps": 5.0}


def test_worker_shed_counts_reach_engine_metrics(frontend_edge):
    """Worker-local shed-cache rejections ride frame metadata back to
    the engine's misaka_edge_rejected_total — the headline counter must
    cover the WHOLE door, not just engine-made decisions."""
    from misaka_tpu.utils import metrics as metrics_mod

    m, port = frontend_edge
    series = 'misaka_edge_rejected_total{reason="rate",tenant="bob"}'

    def scrape():
        return metrics_mod.parse_text(metrics_mod.render()).get(series, 0)

    before = scrape()
    vals = np.arange(8, dtype=np.int32).astype("<i4").tobytes()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    seen_429 = 0
    for _ in range(20):
        conn.request("POST", "/compute_raw?spread=1", vals,
                     {"X-Misaka-Key": "bob-secret"})
        r = conn.getresponse()
        r.read()
        if r.status == 429:
            seen_429 += 1
    assert seen_429 >= 5  # burst gone; the cache absorbed most of these
    # an admitted frame flushes the worker's pending shed report
    conn.request("POST", "/compute_raw?spread=1", vals,
                 {"X-Misaka-Key": "adm-secret"})
    assert conn.getresponse().status == 200
    conn.close()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and scrape() - before < seen_429:
        time.sleep(0.1)
    assert scrape() - before >= seen_429


def test_tls_silent_connection_does_not_block_accept(tls_certs, monkeypatch):
    """The deferred-handshake contract: a client that connects to the
    TLS port and sends NOTHING must not park the accept loop — other
    clients keep being served (with handshake-on-accept, one idle
    socket was a full listener outage)."""
    cert, key = tls_certs
    monkeypatch.setenv("MISAKA_TLS_CERT", cert)
    monkeypatch.setenv("MISAKA_TLS_KEY", key)
    m = _master(batch=2)
    m.run()
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    idle = socket.create_connection(("127.0.0.1", port), timeout=10)
    try:
        c = MisakaClient(f"https://127.0.0.1:{port}", ca=cert, timeout=10)
        for i in range(3):  # several accepts behind the idle socket
            assert int(c.compute(i)) == i + 2
        c.close()
    finally:
        idle.close()
        m.pause()
        httpd.shutdown()


def test_round4_hardening_units(tmp_path):
    """Fourth review pass pins: non-ASCII keys never crash an
    internal-token-armed chain; coalesced frames over the vps burst
    clamp instead of answering an unactionable 413; decision counters
    bill per fused request, not per frame."""
    from misaka_tpu.utils import metrics as metrics_mod

    # non-ASCII key vs internal token: 401, not TypeError/500
    chain = edge.EdgeChain(
        keyfile=edge.KeyFile(_write_keys(
            tmp_path / "k.json", [{"key": "k", "tenant": "t"}]
        )),
        internal_token="boot-secret",
    )
    d = chain.check("/compute", key="café")
    assert d.reject is not None and d.reject.status == 401
    # frame-fused values over burst capacity: clamped 429-or-admit,
    # never the terminal 413 (each fused client sent a small request)
    q = edge.EdgeChain(quota_defaults={"vps": 1000.0}, burst_s=2.0)
    d = q.check("/compute_raw", program="p", values=5000, requests=100)
    assert d.reject is None or d.reject.status == 429
    # a SINGLE oversized request keeps the terminal 413
    d = q.check("/compute_raw", program="p", values=5000, requests=1)
    assert d.reject is not None and d.reject.status == 413
    # decision counters bill per fused request
    before = metrics_mod.parse_text(metrics_mod.render()).get(
        'misaka_edge_admitted_total{tenant="counted"}', 0
    )
    c2 = edge.EdgeChain(quota_defaults={"rps": 1e9})
    c2.check("/compute_raw", program="counted", values=64, requests=7)
    after = metrics_mod.parse_text(metrics_mod.render()).get(
        'misaka_edge_admitted_total{tenant="counted"}', 0
    )
    assert after - before == 7
