"""Vectorized decimal codec: round-trips, edge values, malformed rejection.

The codec is the /compute_batch wire format (utils/textcodec.py) — its
output must stay loadable by ordinary json/int() clients, and its parser
must reject exactly what the round-2 per-value parser rejected (pinned by
test_runtime.py's 400-path tests, which now route through it).

Every behavioral test runs against BOTH backends (the numpy passes and the
native/textcodec.cpp single-pass C++, forced via MISAKA_NATIVE_CODEC), and
the differential lane pins them byte-identical on fuzzed streams.
"""

import json

import numpy as np
import pytest

from misaka_tpu.utils import textcodec
from misaka_tpu.utils.textcodec import dec_to_ints, ints_to_dec


@pytest.fixture(params=["numpy", "native"])
def codec_backend(request, monkeypatch):
    """Force one codec backend for the test (skip native sans toolchain)."""
    if request.param == "native":
        if not textcodec.native_available():
            pytest.skip("no C++ toolchain for the native codec")
        monkeypatch.setenv("MISAKA_NATIVE_CODEC", "1")
    else:
        monkeypatch.setenv("MISAKA_NATIVE_CODEC", "0")
    return request.param

EDGES = np.array(
    [0, 1, -1, 9, 10, -10, 99, 100, 2**31 - 1, -(2**31), 123456789, -987654321],
    np.int32,
)


@pytest.mark.parametrize("sep", [b" ", b",", b"+"])
def test_roundtrip_edges(sep, codec_backend):
    txt = ints_to_dec(EDGES, sep)
    np.testing.assert_array_equal(dec_to_ints(txt), EDGES)


@pytest.mark.parametrize("lo,hi", [(-10, 10), (-1000, 1000), (-2**31, 2**31)])
def test_roundtrip_random(lo, hi, codec_backend):
    rng = np.random.default_rng(42)
    arr = rng.integers(lo, hi, size=10_000).astype(np.int32)
    for sep in (b" ", b",", b"+"):
        np.testing.assert_array_equal(dec_to_ints(ints_to_dec(arr, sep)), arr)


def test_tokens_match_python_str():
    rng = np.random.default_rng(3)
    arr = rng.integers(-(2**31), 2**31, size=2000).astype(np.int32)
    toks = ints_to_dec(arr, b" ").split()
    assert [int(t) for t in toks] == arr.tolist()


def test_comma_sep_is_valid_json_array():
    # pad spaces are JSON whitespace: a json.loads client must decode the
    # /compute_batch response unchanged
    body = b'{"values": [' + ints_to_dec(EDGES, b",") + b"]}"
    assert json.loads(body) == {"values": EDGES.tolist()}


def test_empty(codec_backend):
    assert ints_to_dec(np.empty((0,), np.int32)) == b""
    assert dec_to_ints(b"").size == 0
    assert dec_to_ints("  , \t\n").size == 0


def test_accepts_mixed_separators(codec_backend):
    np.testing.assert_array_equal(
        dec_to_ints("1, 2 3,4\t5\n-6"), np.array([1, 2, 3, 4, 5, -6], np.int32)
    )


@pytest.mark.parametrize(
    "bad",
    ["1 two 3", "5x", "x5", "--5", "5-", "5-6", "1.5", "0x10",
     "9999999999999", "-9999999999999", "2147483648", "-2147483649", "-", "- 5",
     # equal-width out-of-range tokens land on the fixed-stride grid with a
     # 12+ char field: must 400 (ValueError), not crash (round-3 regression)
     "999999999999 999999999999", "999999999999,999999999999"],
)
def test_rejects_malformed(bad, codec_backend):
    with pytest.raises(ValueError):
        dec_to_ints(bad)


def test_rejects_non_ascii(codec_backend):
    with pytest.raises((ValueError, UnicodeEncodeError)):
        dec_to_ints("１２３")  # fullwidth digits must not silently parse


# --- native/numpy differential lane ------------------------------------

needs_native = pytest.mark.skipif(
    not textcodec.native_available(),
    reason="no C++ toolchain for the native codec",
)


def _both(monkeypatch, fn):
    monkeypatch.setenv("MISAKA_NATIVE_CODEC", "0")
    ref = fn()
    monkeypatch.setenv("MISAKA_NATIVE_CODEC", "1")
    nat = fn()
    return ref, nat


@needs_native
@pytest.mark.parametrize("zero_pad", [False, True])
@pytest.mark.parametrize("sep", [b" ", b",", b"+"])
def test_native_format_byte_exact(monkeypatch, sep, zero_pad):
    rng = np.random.default_rng(11)
    for arr in (
        EDGES,
        np.zeros(7, np.int32),
        rng.integers(-9, 10, size=501).astype(np.int32),
        rng.integers(-(2**31), 2**31, size=5000).astype(np.int32),
    ):
        ref, nat = _both(monkeypatch, lambda: ints_to_dec(arr, sep, zero_pad))
        assert ref == nat


@needs_native
def test_native_parse_identical(monkeypatch):
    rng = np.random.default_rng(12)
    arr = rng.integers(-(2**31), 2**31, size=5000).astype(np.int32)
    streams = [
        ints_to_dec(arr, b" "),
        ints_to_dec(arr, b"+", zero_pad=True),
        b"1, 2 3,4\t5\n-6",
        b"-2147483648 2147483647",
        b"0000005 -08 -0 0000000000005",  # leading zeros, ragged widths
        b"7",            # single token, no separator
        b"7\n",          # trailing separator
        b"  , \t\n",     # separators only -> empty
    ]
    for txt in streams:
        ref, nat = _both(monkeypatch, lambda: dec_to_ints(txt))
        np.testing.assert_array_equal(ref, nat)


@needs_native
def test_native_rejects_match(monkeypatch):
    # the native parser must reject exactly the numpy parser's reject set
    for bad in ["1 two 3", "5x", "--5", "5-", "5-6", "1.5", "2147483648",
                "-2147483649", "-", "- 5", "99999999999999999999 1",
                "999999999999,999999999999", "\x005"]:
        for knob in ("0", "1"):
            monkeypatch.setenv("MISAKA_NATIVE_CODEC", knob)
            with pytest.raises(ValueError):
                dec_to_ints(bad)


@needs_native
def test_native_fuzz_roundtrip(monkeypatch):
    """Random arrays through every (backend-pair, sep, pad) combination:
    format bytes identical, parse returns the input."""
    rng = np.random.default_rng(13)
    for trial in range(25):
        n = int(rng.integers(1, 2000))
        lo, hi = sorted(rng.integers(-(2**31), 2**31, size=2).tolist())
        arr = rng.integers(lo, hi + 1, size=n, dtype=np.int64).astype(np.int32)
        sep = [b" ", b",", b"+"][trial % 3]
        zp = bool(trial % 2)
        ref, nat = _both(monkeypatch, lambda: ints_to_dec(arr, sep, zp))
        assert ref == nat, f"trial {trial}"
        for knob in ("0", "1"):
            monkeypatch.setenv("MISAKA_NATIVE_CODEC", knob)
            np.testing.assert_array_equal(dec_to_ints(nat), arr)


@needs_native
def test_native_accepts_bytearray(monkeypatch):
    # c_char_p wants bytes; the wrapper must normalize other buffer types
    # instead of leaking a ctypes.ArgumentError past the ValueError contract
    ref, nat = _both(monkeypatch, lambda: dec_to_ints(bytearray(b"1 2 -3")))
    np.testing.assert_array_equal(ref, nat)
    np.testing.assert_array_equal(nat, np.array([1, 2, -3], np.int32))
