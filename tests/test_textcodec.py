"""Vectorized decimal codec: round-trips, edge values, malformed rejection.

The codec is the /compute_batch wire format (utils/textcodec.py) — its
output must stay loadable by ordinary json/int() clients, and its parser
must reject exactly what the round-2 per-value parser rejected (pinned by
test_runtime.py's 400-path tests, which now route through it).
"""

import json

import numpy as np
import pytest

from misaka_tpu.utils.textcodec import dec_to_ints, ints_to_dec

EDGES = np.array(
    [0, 1, -1, 9, 10, -10, 99, 100, 2**31 - 1, -(2**31), 123456789, -987654321],
    np.int32,
)


@pytest.mark.parametrize("sep", [b" ", b",", b"+"])
def test_roundtrip_edges(sep):
    txt = ints_to_dec(EDGES, sep)
    np.testing.assert_array_equal(dec_to_ints(txt), EDGES)


@pytest.mark.parametrize("lo,hi", [(-10, 10), (-1000, 1000), (-2**31, 2**31)])
def test_roundtrip_random(lo, hi):
    rng = np.random.default_rng(42)
    arr = rng.integers(lo, hi, size=10_000).astype(np.int32)
    for sep in (b" ", b",", b"+"):
        np.testing.assert_array_equal(dec_to_ints(ints_to_dec(arr, sep)), arr)


def test_tokens_match_python_str():
    rng = np.random.default_rng(3)
    arr = rng.integers(-(2**31), 2**31, size=2000).astype(np.int32)
    toks = ints_to_dec(arr, b" ").split()
    assert [int(t) for t in toks] == arr.tolist()


def test_comma_sep_is_valid_json_array():
    # pad spaces are JSON whitespace: a json.loads client must decode the
    # /compute_batch response unchanged
    body = b'{"values": [' + ints_to_dec(EDGES, b",") + b"]}"
    assert json.loads(body) == {"values": EDGES.tolist()}


def test_empty():
    assert ints_to_dec(np.empty((0,), np.int32)) == b""
    assert dec_to_ints(b"").size == 0
    assert dec_to_ints("  , \t\n").size == 0


def test_accepts_mixed_separators():
    np.testing.assert_array_equal(
        dec_to_ints("1, 2 3,4\t5\n-6"), np.array([1, 2, 3, 4, 5, -6], np.int32)
    )


@pytest.mark.parametrize(
    "bad",
    ["1 two 3", "5x", "x5", "--5", "5-", "5-6", "1.5", "0x10",
     "9999999999999", "-9999999999999", "2147483648", "-2147483649", "-", "- 5",
     # equal-width out-of-range tokens land on the fixed-stride grid with a
     # 12+ char field: must 400 (ValueError), not crash (round-3 regression)
     "999999999999 999999999999", "999999999999,999999999999"],
)
def test_rejects_malformed(bad):
    with pytest.raises(ValueError):
        dec_to_ints(bad)


def test_rejects_non_ascii():
    with pytest.raises((ValueError, UnicodeEncodeError)):
        dec_to_ints("１２３")  # fullwidth digits must not silently parse
