"""The embedded TSDB (utils/tsdb.py): ring semantics, staged
downsampling, kind derivation (counter rates, histogram quantiles),
query stage selection, the cardinality cap's loud drop counter, and the
strictly-newer snapshot/restore merge the checkpoint path rides.

All deterministic: tests drive sample_once()/add() directly with
synthetic clocks — the collector thread and its governor get one
liveness check only.
"""

import time

import pytest

from misaka_tpu.utils import metrics
from misaka_tpu.utils import tsdb
from misaka_tpu.utils import watchdog

# Unique metric names per test: the metrics registry is process-global
# and get-or-create, so a reused name would leak state across tests.
_seq = iter(range(10 ** 6))


def _name(kind):
    return f"t_tsdb_{kind}_{next(_seq)}"


def _private_db(interval_s=1.0, **kw):
    """A TSDB over its OWN registry: under the full suite the process
    registry holds hundreds of series, and a fresh default-capped TSDB
    sampling it would drop these tests' (non-priority) series."""
    reg = metrics.Registry()
    return tsdb.TSDB(interval_s=interval_s, registry=reg, **kw), reg


# --- parse_window -----------------------------------------------------------


@pytest.mark.parametrize("text,want", [
    ("30s", 30.0), ("5m", 300.0), ("1h", 3600.0), ("90", 90.0),
    (120, 120.0), ("0.5s", 0.5),
])
def test_parse_window(text, want):
    assert tsdb.parse_window(text) == want


@pytest.mark.parametrize("bad", ["", "abc", "-5s", "0", "5x"])
def test_parse_window_rejects(bad):
    with pytest.raises(tsdb.TSDBError):
        tsdb.parse_window(bad)


def test_parse_window_zero_gate():
    with pytest.raises(tsdb.TSDBError):
        tsdb.parse_window("0s")
    assert tsdb.parse_window("0s", allow_zero=True) == 0.0


# --- ring semantics ---------------------------------------------------------


def test_ring_positional_reclaim_and_points():
    ring = tsdb._Ring(width=1.0, length=4)
    ring.add(1000.0, 5.0)
    ring.add(1000.2, 7.0)   # same slot: aggregates mean + max
    ring.add(1001.0, 1.0)
    pts = ring.points(1001.5, window_s=4.0)
    assert pts == [[1000.0, 6.0, 7.0], [1001.0, 1.0, 1.0]]
    # wrap far enough that slot 1000 % 4 is reused: the stale epoch must
    # be reclaimed, not leak month-old values into a fresh window
    ring.add(1004.0, 9.0)   # 1004 % 4 == 1000 % 4
    pts = ring.points(1004.5, window_s=4.0)
    assert [p[0] for p in pts] == [1001.0, 1004.0]
    # and an idle gap produces NO points, not zeros
    assert ring.points(2000.0, window_s=4.0) == []


def test_ring_install_strictly_newer_only():
    ring = tsdb._Ring(width=1.0, length=8)
    ring.add(1000.0, 5.0)
    # older epoch on the same slot index: refused
    ring.install(1000 - 8, 99.0, 1, 99.0)
    assert ring.points(1000.5, 8.0) == [[1000.0, 5.0, 5.0]]
    # same epoch: refused (re-restoring a snapshot must not double-count)
    ring.install(1000, 99.0, 1, 99.0)
    assert ring.points(1000.5, 8.0) == [[1000.0, 5.0, 5.0]]
    # strictly newer: installs
    ring.install(1001, 4.0, 2, 3.0)
    assert ring.points(1001.5, 8.0) == [
        [1000.0, 5.0, 5.0], [1001.0, 2.0, 3.0],
    ]


def test_stage_plan_tracks_interval():
    assert tsdb._stage_plan(5.0) == ((5.0, 720), (60.0, 360), (300.0, 288))
    # a test-scale interval keeps the coarser absolute tiers
    assert tsdb._stage_plan(0.1)[0] == (0.1, 720)
    assert len(tsdb._stage_plan(0.1)) == 3
    # a huge interval drops the now-finer-than-interval tiers
    assert tsdb._stage_plan(600.0) == ((600.0, 720),)


def test_query_picks_finest_covering_stage():
    db, reg = _private_db()
    g = metrics.gauge(_name("g"), "x", registry=reg)
    g.set(3.0)
    db.sample_once()
    [row] = db.query(g.name, window_s=10.0)
    assert row["stage_s"] == 1.0        # stage 0 covers 720 s
    [row] = db.query(g.name, window_s=1000.0)
    assert row["stage_s"] == 60.0       # stage 0 (720 s) no longer covers
    [row] = db.query(g.name, window_s=100000.0)
    assert row["stage_s"] == 300.0      # beyond every span: coarsest


# --- kind derivation --------------------------------------------------------


def test_counter_becomes_rate_and_reset_rebases():
    db, reg = _private_db()
    c = metrics.counter(_name("c"), "x", registry=reg)
    c.inc(10)
    db.sample_once()                     # baseline only: no point yet
    assert db.query(c.name, window_s=60) == []
    time.sleep(0.05)
    c.inc(10)
    db.sample_once()
    [row] = db.query(c.name, window_s=60)
    assert row["kind"] == "rate"
    assert row["points"][-1][1] > 0
    # a counter RESET (process restart semantics) must re-base on the
    # fresh value, never emit a negative spike
    child = c._default()
    with child._lock:
        child._value = 1.0
    time.sleep(0.05)
    db.sample_once()
    values = [p[1] for p in db.query(c.name, window_s=60)[0]["points"]]
    assert all(v >= 0 for v in values)


def test_histogram_derives_quantiles_and_rate():
    db, reg = _private_db()
    h = metrics.histogram(_name("h"), "x", registry=reg)
    db.sample_once()                     # baseline
    for _ in range(50):
        h.observe(0.01)
    h.observe(1.0)
    time.sleep(0.05)
    db.sample_once()
    [p50] = db.query(f"{h.name}:p50", window_s=60)
    [p99] = db.query(f"{h.name}:p99", window_s=60)
    [rate] = db.query(f"{h.name}:rate", window_s=60)
    assert p50["kind"] == "quantile" and p99["kind"] == "quantile"
    assert p50["points"][-1][1] < 0.05          # the mass sits at 10 ms
    assert p99["points"][-1][1] > 0.1           # the tail shows in p99
    assert rate["points"][-1][1] > 0
    # an idle interval writes NO false-zero quantile point
    time.sleep(0.05)
    db.sample_once()
    assert len(db.query(f"{h.name}:p99", window_s=60)[0]["points"]) == 1


def test_labeled_children_become_labeled_series():
    db, reg = _private_db()
    g = metrics.gauge(_name("gl"), "x", ("route",), registry=reg)
    g.labels(route="/a").set(1.0)
    g.labels(route="/b").set(2.0)
    db.sample_once()
    rows = db.query(g.name, window_s=60)
    assert [r["labels"] for r in rows] == [
        {"route": "/a"}, {"route": "/b"},
    ]
    [only_b] = db.query(g.name, labels={"route": "/b"}, window_s=60)
    assert only_b["points"][-1][1] == 2.0


# --- bounded cardinality ----------------------------------------------------


def test_series_cap_drops_loudly_and_priority_survives():
    db, reg = _private_db(max_series=16)  # 16 = the floor
    flood = metrics.gauge(_name("flood"), "x", ("k",), registry=reg)
    for i in range(40):
        flood.labels(k=str(i)).set(1.0)
    # a priority family registered AFTER the flood still gets a slot:
    # priority prefixes sample first each pass
    canary = metrics.gauge(
        "misaka_canary_success", "x", ("tier",), registry=reg
    )
    canary.labels(tier="full").set(1.0)
    db.sample_once()
    idx = db.series_index()
    assert idx["series_count"] == 16
    assert idx["dropped_series"] > 0            # loud, not silent
    assert db.query("misaka_canary_success", window_s=60)
    # documented worst-case memory: bytes_per_series x max_series
    assert idx["bytes_per_series"] == 28 * (720 + 360 + 288)


# --- snapshot / restore -----------------------------------------------------


def test_snapshot_restore_round_trip_and_idempotence():
    db, reg = _private_db()
    g = metrics.gauge(_name("snap"), "x", registry=reg)
    g.set(42.0)
    db.sample_once()
    snap = db.snapshot()
    fresh, _ = _private_db()
    assert fresh.restore(snap) >= 1
    [row] = fresh.query(g.name, window_s=60)
    assert row["points"][-1][1] == 42.0
    # replaying the same snapshot is a no-op (strictly-newer rule)
    fresh.restore(snap)
    [row2] = fresh.query(g.name, window_s=60)
    assert row2["points"] == row["points"]


def test_restore_never_clobbers_fresher_history():
    db, reg = _private_db()
    g = metrics.gauge(_name("clob"), "x", registry=reg)
    g.set(1.0)
    db.sample_once()
    stale = db.snapshot()                # the eviction-era checkpoint
    time.sleep(1.1)                      # next stage-0 slot
    g.set(2.0)
    db.sample_once()
    db.restore(stale)
    [row] = db.query(g.name, window_s=60)
    assert row["points"][-1][1] == 2.0   # the live point survived


def test_restore_rejects_garbage():
    db, _ = _private_db()
    with pytest.raises(tsdb.TSDBError):
        db.restore({"format": 99})
    with pytest.raises(tsdb.TSDBError):
        db.restore({"format": 1, "series": [{"name": 7}]})


def test_snapshot_bytes_module_surface(monkeypatch):
    tsdb.shutdown()
    monkeypatch.setenv("MISAKA_TSDB_INTERVAL_S", "1.0")
    db = tsdb.ensure_started()
    g = metrics.gauge(_name("mod"), "x")
    g.set(5.0)
    db.sample_once()
    blob = tsdb.snapshot_bytes()
    assert blob and isinstance(blob, bytes)
    tsdb.shutdown()
    assert tsdb.snapshot_bytes() is None
    monkeypatch.setenv("MISAKA_TSDB_INTERVAL_S", "1.0")
    assert tsdb.restore_bytes(blob) >= 1
    [row] = tsdb.query(g.name, window_s=60)
    assert row["points"][-1][1] == 5.0
    tsdb.shutdown()


def test_kill_switch(monkeypatch):
    tsdb.shutdown()
    monkeypatch.setenv("MISAKA_TSDB", "0")
    assert tsdb.ensure_started() is None
    assert tsdb.restore_bytes(b"{}") == 0
    assert tsdb.index_payload()["running"] is False
    monkeypatch.delenv("MISAKA_TSDB")


def test_collector_thread_and_governor_liveness(monkeypatch):
    tsdb.shutdown()
    monkeypatch.setenv("MISAKA_TSDB_INTERVAL_S", "0.05")
    monkeypatch.setenv("MISAKA_TSDB_BUDGET", "0.5")
    db = tsdb.ensure_started()
    g = metrics.gauge(_name("live"), "x")
    g.set(1.0)
    deadline = time.monotonic() + 10
    while db._samples < 3 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert db._samples >= 3, "collector thread never sampled"
    # the governor stretches the period when a sample's cost would blow
    # the duty-cycle budget
    db._cost_ema = 1.0
    assert db._current_period() == pytest.approx(1.0 / db.budget)
    tsdb.shutdown()


# --- the watchdog over it ---------------------------------------------------


@pytest.fixture(autouse=True)
def _clean_watchdog():
    yield
    watchdog.shutdown()
    tsdb.shutdown()


def test_watchdog_spec_parse():
    [r] = watchdog.parse_spec(
        "p99=foo_seconds:p99{route=/x}>2x@1h for 5m ->page"
    )
    assert (r.name, r.series) == ("p99", "foo_seconds:p99")
    assert r.labels == {"route": "/x"}
    assert r.factor == 2.0 and r.baseline_s == 3600.0
    assert r.sustain_s == 300.0 and r.severity == "page"
    [r] = watchdog.parse_spec("bar<1")
    assert r.threshold == 1.0 and r.op == "<" and r.severity == "warning"
    assert r.sustain_s == 0.0


@pytest.mark.parametrize("bad", [
    "nonsense", "foo>>1", "foo>1 ->fatal", "foo>0x@1h",
])
def test_watchdog_spec_rejects(bad):
    with pytest.raises(watchdog.WatchdogSpecError):
        watchdog.parse_spec(bad)


def test_watchdog_absolute_rule_fires_sustains_and_clears():
    db, reg = _private_db(interval_s=0.05)
    g = metrics.gauge(_name("wd"), "x", registry=reg)
    w = watchdog.Watchdog(
        watchdog.parse_spec(f"hot={g.name}>2 for 0.15s ->page"),
        recent_s=0.2,
    )
    g.set(5.0)
    db.sample_once()
    w.evaluate(db)
    assert w.overall_state() == "ok"    # bad, but not sustained yet
    deadline = time.monotonic() + 5
    while w.overall_state() == "ok" and time.monotonic() < deadline:
        time.sleep(0.06)
        db.sample_once()
        w.evaluate(db)
    assert w.overall_state() == "page"
    [rp] = w.payload()["rules"]
    assert rp["state"] == "page" and rp["value"] == pytest.approx(5.0)
    assert rp["since_unix"] > 0
    # recovery must ALSO sustain before clearing (no alert strobe)
    g.set(0.0)
    deadline = time.monotonic() + 5
    while w.overall_state() == "page" and time.monotonic() < deadline:
        time.sleep(0.06)
        db.sample_once()
        w.evaluate(db)
    assert w.overall_state() == "ok"


def test_watchdog_ratio_rule_needs_baseline_then_catches_drift():
    db, reg = _private_db(interval_s=0.05)
    g = metrics.gauge(_name("drift"), "x", registry=reg)
    # baseline 30s: stage 0 at the test interval spans 0.05 x 720 = 36 s,
    # so the baseline query stays on the fine stage (a 60s baseline would
    # fall to the 60s-wide tier = one slot — exactly the production
    # contract, where the default 5s interval gives stage 0 a 1h span
    # matching the default 1h baseline)
    w = watchdog.Watchdog(
        watchdog.parse_spec(f"d={g.name}>3x@30s for 0s"),
        recent_s=0.1, min_points=3,
    )
    g.set(1.0)
    db.sample_once()
    w.evaluate(db)
    assert w.overall_state() == "ok"    # no baseline yet: silent
    assert w.payload()["rules"][0].get("baseline") is None
    for _ in range(8):                  # build the trailing baseline ~1.0
        time.sleep(0.06)
        db.sample_once()
    g.set(10.0)                         # 10x the 1.0 median
    deadline = time.monotonic() + 5
    while w.overall_state() == "ok" and time.monotonic() < deadline:
        time.sleep(0.06)
        db.sample_once()
        w.evaluate(db)
    assert w.overall_state() == "warning"
    rp = w.payload()["rules"][0]
    assert rp["baseline"] == pytest.approx(1.0, abs=0.2)
    assert rp["threshold"] == pytest.approx(3.0, abs=0.6)


def test_watchdog_no_data_holds_state():
    db, _ = _private_db(interval_s=0.05)
    w = watchdog.Watchdog(
        watchdog.parse_spec("ghost=misaka_never_exists<1 for 0s ->page"),
        recent_s=0.2,
    )
    w.evaluate(db)
    assert w.overall_state() == "ok"    # absent series: no verdict


def test_watchdog_defaults_and_env(monkeypatch):
    rules = watchdog.default_rules(5.0)
    assert {r.name for r in rules} == {
        "canary-full", "p99-drift", "replica-restarts",
        "tsdb-spool-drops", "capture-spool-drops", "spool-errors",
    }
    # env spec replaces the defaults; a malformed one is LOUD and falls
    # back to them
    tsdb.shutdown()
    watchdog.shutdown()
    monkeypatch.setenv("MISAKA_TSDB_INTERVAL_S", "1.0")
    monkeypatch.setenv("MISAKA_WATCHDOG", "one=foo>1 for 1s")
    w = watchdog.ensure_started()
    assert [r.name for r in w.rules] == ["one"]
    watchdog.shutdown()
    monkeypatch.setenv("MISAKA_WATCHDOG", "][broken")
    w = watchdog.ensure_started()
    assert {r.name for r in w.rules} == {
        "canary-full", "p99-drift", "replica-restarts",
        "tsdb-spool-drops", "capture-spool-drops", "spool-errors",
    }
    assert "spec_error" in watchdog.debug_payload()
    watchdog.shutdown()
    monkeypatch.setenv("MISAKA_WATCHDOG", "0")
    assert watchdog.ensure_started() is None
    assert watchdog.overall_state() is None
