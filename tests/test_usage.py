"""The r12 per-program usage ledger (runtime/usage.py).

Pins the attribution CONSERVATION contracts the admission-control and
fleet-health work will lean on: per-program CPU-seconds across a
multi-tenant run sum to the total fused-pass wall time (within 5%), and
attributed native-seconds match the C++ pool's measured busy-ns (within
10%) — plus the surfaces (GET /debug/usage, the `usage` block in
GET /programs, misaka_usage_* series, client helpers, the jsonlog
`program` field) and the MISAKA_USAGE=0 kill switch.
"""

import http.client
import json
import logging
import threading
import time

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.runtime import usage
from misaka_tpu.runtime.master import (
    ComputeTimeout, MasterNode, make_http_server,
)
from misaka_tpu.runtime.registry import ProgramRegistry

CAPS = dict(in_cap=32, out_cap=32, stack_cap=16)


def _native_or_skip():
    from misaka_tpu.core import native_serve

    if not native_serve.available():
        pytest.skip("no C++ toolchain for the native engine")


def _post(port, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", path, body)
    r = conn.getresponse()
    data = r.read()
    conn.close()
    return r.status, data


def _get_json(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    r = conn.getresponse()
    data = r.read()
    conn.close()
    assert r.status == 200, (path, r.status, data[:200])
    return json.loads(data)


@pytest.fixture
def tenants():
    """Registry + three native tenants behind one in-process server."""
    _native_or_skip()
    reg = ProgramRegistry(None, batch=16, engine="native", caps=CAPS)
    top = networks.add2(**CAPS)
    master = MasterNode(top, chunk_steps=64, batch=16, engine="native")
    reg.seed("dense", master, top)
    for name, topo in (
        ("compact", networks.acc_loop(**CAPS)),
        ("chained", networks.pipeline(4, **CAPS)),
    ):
        reg.publish(name, topology_json=json.dumps(
            {"nodes": topo.node_info, "programs": topo.programs, **CAPS}
        ))
    httpd = make_http_server(master, port=0, registry=reg)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    master.run()
    try:
        yield reg, master, httpd.server_address[1]
    finally:
        master.pause()
        reg.close()
        httpd.shutdown()


def _drive(port, programs, rounds=10, values=48):
    """Concurrent multi-tenant traffic; every response parity-checked."""
    deltas = {"dense": 2, "compact": 3, "chained": 4}
    errors = []

    def worker(name):
        vals = np.arange(values, dtype=np.int32)
        try:
            for _ in range(rounds):
                s, d = _post(
                    port, f"/programs/{name}/compute_raw?spread=1",
                    vals.tobytes(),
                )
                assert s == 200, (s, d[:200])
                assert (np.frombuffer(d, "<i4") == vals + deltas[name]).all()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ts = [
        threading.Thread(target=worker, args=(name,))
        for name in programs for _ in range(2)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors[0]


def _usage_delta(before, after):
    out = {}
    for name, a in after["programs"].items():
        b = before["programs"].get(name, {})
        out[name] = {
            k: a[k] - b.get(k, 0)
            for k in ("requests", "values", "cpu_seconds",
                      "native_seconds", "queue_seconds")
        }
    return out


# --- the acceptance contract: attribution conservation ----------------------


def test_multi_tenant_cpu_conservation(tenants):
    """Per-program CPU-seconds summed across a multi-tenant run equal the
    total fused-pass wall time within 5% — the ledger neither leaks nor
    double-counts (the anchor counter accumulates at the pass sites, the
    splits per segment; two independent code paths)."""
    reg, master, port = tenants
    names = ("dense", "compact", "chained")
    before = _get_json(port, "/debug/usage")
    _drive(port, names, rounds=12)
    after = _get_json(port, "/debug/usage")
    delta = _usage_delta(before, after)
    for name in names:
        assert delta[name]["requests"] >= 24, (name, delta[name])
        assert delta[name]["values"] >= 24 * 48
        assert delta[name]["cpu_seconds"] > 0, (name, delta[name])
    cpu_sum = sum(delta[n]["cpu_seconds"] for n in delta)
    pass_total = (
        after["pass_seconds_total"] - before["pass_seconds_total"]
    )
    assert pass_total > 0
    assert abs(cpu_sum - pass_total) <= 0.05 * pass_total, (
        cpu_sum, pass_total
    )


def test_multi_tenant_native_conservation(tenants):
    """Attributed native-seconds match the pools' MEASURED busy-ns within
    10% — native attribution is a counter read, not a wall-clock guess."""
    reg, master, port = tenants
    names = ("dense", "compact", "chained")

    def pool_busy_ns():
        total = 0
        with reg._cond:
            engines = [
                e.master for e in reg._engines.values()
                if e.master is not None
            ]
        for m in engines:
            pool = getattr(m._runner, "_pool", None)
            if pool is not None:
                # work_ns is first-class (r18): worker busy + the
                # caller-inline lane in one field
                total += pool.counters()["work_ns"]
        return total

    before = _get_json(port, "/debug/usage")
    busy_before = pool_busy_ns()
    _drive(port, names, rounds=12)
    # traffic done: pause the engines so no further busy accrues between
    # the ledger read and the counter read (idle chunks would skew it)
    with reg._cond:
        masters = [
            e.master for e in reg._engines.values() if e.master is not None
        ]
    for m in masters:
        m.pause()
    after = _get_json(port, "/debug/usage")
    busy_after = pool_busy_ns()
    delta = _usage_delta(before, after)
    native_sum = sum(d["native_seconds"] for d in delta.values())
    busy_s = (busy_after - busy_before) / 1e9
    assert busy_s > 0 and native_sum > 0
    # the last take_busy_ns per pool ran at its final serve/idle call;
    # anything after (there is nothing: engines are paused) is the only
    # legitimate gap
    assert abs(native_sum - busy_s) <= 0.10 * busy_s, (native_sum, busy_s)


def test_queue_seconds_accumulate(tenants):
    reg, master, port = tenants
    before = _get_json(port, "/debug/usage")
    _drive(port, ("dense",), rounds=8)
    after = _get_json(port, "/debug/usage")
    d = _usage_delta(before, after)["dense"]
    # queue delay is near-zero on an idle engine but strictly observed
    assert d["queue_seconds"] >= 0
    assert d["requests"] == 16


# --- surfaces ---------------------------------------------------------------


def test_pool_counters_aggregate_across_engines(tenants):
    """/debug/usage's native_pool block aggregates EVERY live pool (one
    per active program engine) with a per-program split — a single
    last-constructed slot reported the wrong tenant after activations."""
    reg, master, port = tenants
    _drive(port, ("dense", "compact"), rounds=4)
    payload = _get_json(port, "/debug/usage")
    np_block = payload.get("native_pool")
    assert np_block is not None
    # the caller-inline lane counts as work, first-class (r18): a
    # partial-fill-regime box must not read ~0% busy while saturated
    assert np_block["work_ns"] > 0
    assert np_block["caller_inline_ns"] == np_block["serial_ns"]
    pools = np_block.get("pools")
    assert pools is not None and len(pools) >= 2, np_block.keys()
    labels = {p["program"] for p in pools}
    assert {"dense", "compact"} <= labels, labels  # seeded + activated


def test_pool_gauges_aggregate_across_engines(tenants):
    """misaka_native_pool_{threads,replicas} sum over EVERY live pool at
    scrape time (and fill stays a ratio) — the per-instance binding read
    only the last-constructed pool, so evicting the newest pool zeroed
    the gauges while older pools still served."""
    from misaka_tpu.core import native_serve

    reg, master, port = tenants
    _drive(port, ("dense", "compact"), rounds=2)
    pools = native_serve._live_pools()
    assert len(pools) >= 2
    threads = native_serve._G_POOL_THREADS._default().value
    replicas = native_serve._G_POOL_REPLICAS._default().value
    assert threads == sum(p.threads for p in pools)
    assert replicas == sum(p._replicas for p in pools)
    assert 0.0 <= native_serve._G_POOL_FILL._default().value <= 1.0


def test_build_info_restamps_after_jax_import(monkeypatch):
    """A jax import after boot re-stamps misaka_build_info (dropping the
    stale jax="unloaded" child) — the gauge must never disagree with the
    /status build block."""
    from misaka_tpu.utils import buildinfo, metrics as umetrics

    buildinfo.install_metric()
    real = buildinfo.info()["jax"]
    assert real != "unloaded"  # jax is imported in this process
    monkeypatch.setattr(
        buildinfo, "_info_cache", dict(buildinfo.info(), jax="unloaded")
    )
    assert buildinfo.info()["jax"] == real  # the upgrade branch fired
    fam = umetrics.REGISTRY.get("misaka_build_info")
    jax_labels = {
        dict(zip(fam.labelnames, key))["jax"] for key, _ in fam._items()
    }
    assert jax_labels == {real}


def test_native_watermark_advances_while_disabled(monkeypatch):
    """The busy-ns watermark advances even with MISAKA_USAGE=0 —
    re-enabling must not bill the whole disabled period in one spike."""
    _native_or_skip()
    m = MasterNode(networks.add2(**CAPS), chunk_steps=64, batch=8,
                   engine="native")
    m.run()
    try:
        import numpy as _np

        m.compute_many(_np.arange(16, dtype=_np.int32))
        monkeypatch.setenv("MISAKA_USAGE", "0")
        usage.configure()
        for _ in range(3):
            m.compute_many(_np.arange(16, dtype=_np.int32))
        monkeypatch.delenv("MISAKA_USAGE")
        usage.configure()
        before = (usage.program_snapshot("default") or {}).get(
            "native_seconds", 0.0
        )
        m.compute_many(_np.arange(16, dtype=_np.int32))
        after = (usage.program_snapshot("default") or {}).get(
            "native_seconds", 0.0
        )
        # one 16-value pass on a warm pool is well under 50ms of busy;
        # a stale watermark would have dumped the 3 disabled passes here
        assert after - before < 0.05, (before, after)
    finally:
        m.pause()


def test_programs_listing_carries_usage(tenants):
    reg, master, port = tenants
    _drive(port, ("dense", "compact"), rounds=3)
    listing = _get_json(port, "/programs")
    dense = listing["programs"]["dense"]
    assert dense["usage"] is not None
    assert dense["usage"]["requests"] > 0
    assert dense["usage"]["cpu_seconds"] > 0
    # a program that never served reports no ledger entry, not zeros
    chained = listing["programs"]["chained"]
    assert chained["usage"] is None or chained["usage"]["requests"] >= 0


def test_usage_metrics_series(tenants):
    reg, master, port = tenants
    _drive(port, ("dense",), rounds=3)
    conn = http.client.HTTPConnection(
        "127.0.0.1", port, timeout=15
    )
    conn.request("GET", "/metrics")
    text = conn.getresponse().read().decode()
    conn.close()
    from misaka_tpu.utils import metrics as umetrics

    parsed = umetrics.parse_text(text)  # exposition stays valid
    assert any(
        k.startswith("misaka_usage_cpu_seconds_total") and 'program="dense"' in k
        for k in parsed
    )
    assert "misaka_serve_pass_wall_seconds_total" in parsed
    assert any(k.startswith("misaka_build_info") for k in parsed)


def test_client_usage_helper(tenants):
    reg, master, port = tenants
    from misaka_tpu.client import MisakaClient

    c = MisakaClient(f"http://127.0.0.1:{port}", program="dense")
    c.compute_raw(np.arange(8, dtype=np.int32))
    u = c.usage()
    assert u["enabled"] is True
    assert u["programs"]["dense"]["requests"] > 0
    fl = c.flamegraph()
    assert "stacks" in fl and "folded" in fl
    c.close()


def test_status_build_block(tenants):
    reg, master, port = tenants
    st = _get_json(port, "/status")
    build = st["build"]
    assert build["version"]
    assert "git_sha" in build and "jax" in build and "python" in build


def test_failed_pass_not_billed():
    """A ComputeTimeout'd fused pass bills NOTHING — charging the victim
    tenant its whole timeout window as cpu_seconds would penalize it
    through the very signal admission control sheds load on (the direct
    lanes were already success-only; the batcher lane must match).  The
    note_pass anchor skips with it, keeping conservation exact."""
    _native_or_skip()
    m = MasterNode(networks.add2(**CAPS), chunk_steps=64, batch=2,
                   engine="native")
    m.run()
    try:
        assert m.compute_coalesced([1], timeout=30) == [3]  # healthy pass
        m.pause()  # park the engine: the next fused pass wedges
        label = m.program_label or usage.DEFAULT_LABEL
        cpu0 = (usage.program_snapshot(label) or {}).get("cpu_seconds", 0.0)
        pass0 = usage.pass_seconds_total()
        with pytest.raises(ComputeTimeout):
            m.compute_coalesced([1, 2], timeout=1.2)
        time.sleep(1.0)  # let the pass worker hit its own deadline too
        cpu1 = (usage.program_snapshot(label) or {}).get("cpu_seconds", 0.0)
        assert cpu1 - cpu0 < 0.6, "failed pass charged the tenant"
        assert usage.pass_seconds_total() - pass0 < 0.6, \
            "failed pass moved the conservation anchor"
    finally:
        m.run()
        m.pause()


# --- the kill switch + cardinality guard ------------------------------------


def test_kill_switch(monkeypatch):
    monkeypatch.setenv("MISAKA_USAGE", "0")
    usage.configure()
    try:
        before = usage.snapshot().get("killswitch-prog")
        usage.add_request("killswitch-prog", 10)
        usage.add_cpu("killswitch-prog", 1.0)
        usage.note_pass(1.0)
        assert usage.snapshot().get("killswitch-prog") == before
    finally:
        monkeypatch.delenv("MISAKA_USAGE")
        usage.configure()


def test_label_cardinality_guard(monkeypatch):
    monkeypatch.setenv("MISAKA_USAGE_LABEL_MAX", "4")
    # the guard counts EXISTING accounts; new ones past the cap collapse
    usage.add_request("guard-a", 1)
    for i in range(16):
        usage.add_request(f"guard-flood-{i}", 1)
    other = usage.program_snapshot("other")
    assert other is not None and other["requests"] > 0


# --- the lease context (jsonlog's program field) ----------------------------


def test_jsonlog_program_field():
    from misaka_tpu.utils.jsonlog import JsonFormatter

    rec = logging.LogRecord(
        "misaka_tpu.test", logging.INFO, __file__, 1, "hello", (), None
    )
    with usage.program_scope("tenant-x"):
        line = json.loads(JsonFormatter().format(rec))
    assert line["program"] == "tenant-x"
    line = json.loads(JsonFormatter().format(rec))
    assert "program" not in line
    # an explicit extra wins over the (absent) context
    rec.program = "explicit"
    line = json.loads(JsonFormatter().format(rec))
    assert line["program"] == "explicit"


def test_slow_request_log(monkeypatch, caplog):
    _native_or_skip()
    monkeypatch.setenv("MISAKA_SLOW_REQ_MS", "0.0001")
    m = MasterNode(networks.add2(**CAPS), chunk_steps=64, batch=4,
                   engine="native")
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    m.run()
    try:
        with caplog.at_level(logging.WARNING, logger="misaka_tpu.master"):
            s, _ = _post(
                httpd.server_address[1], "/compute_raw?spread=1",
                np.arange(4, dtype=np.int32).tobytes(),
            )
            assert s == 200
        slow = [r for r in caplog.records if "slow request" in r.message]
        assert slow, "no slow-request line at a 0.0001ms threshold"
        assert hasattr(slow[0], "program") and hasattr(slow[0], "trace_id")
    finally:
        m.pause()
        httpd.shutdown()
