"""The five BASELINE benchmark configs compute their specified functions."""

import pytest

from misaka_tpu import networks


def stream(topology, inputs, **kw):
    net = topology.compile()
    state = net.init_state()
    state, outs = net.compute_stream(state, inputs, **kw)
    return outs


def test_add2():
    assert stream(networks.add2(), [0, 5, -3]) == [2, 7, -1]


def test_acc_loop():
    assert stream(networks.acc_loop(), [0, 10, -10]) == [3, 13, -7]


def test_ring4():
    assert stream(networks.ring(4), [0, 100]) == [4, 104]


def test_ring8():
    assert stream(networks.ring(8), [1]) == [9]


def test_sorter():
    assert stream(networks.sorter(), [5, -9, 0, 1, -1]) == [11, -11, 0, 11, -11]


def test_mesh8_serialized():
    assert stream(networks.mesh8(), [0, 6, 20]) == [4, 10, 24]


@pytest.mark.parametrize("name", sorted(networks.BASELINE_CONFIGS))
def test_all_configs_compile(name):
    net = networks.BASELINE_CONFIGS[name]().compile()
    assert net.num_lanes >= 1
