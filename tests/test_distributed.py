"""Per-process compatibility mode: gRPC nodes on localhost, end to end.

Covers SURVEY.md §2 C7 (the transport) and the drop-in deployment story: a
network of OS-process nodes speaking the reference's wire protocol
(messenger.proto services /grpc.Master /grpc.Program /grpc.Stack), driven
through the same HTTP surface.  The reference can only test this with a
4-container docker-compose cluster (SURVEY.md §4); here the nodes bind
ephemeral loopback ports in one process.
"""


import json
import threading
import time
import urllib.request
import urllib.parse

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # real-gRPC loopback cluster — `make test-all` lane

from misaka_tpu.runtime.nodes import (
    BroadcastError,
    MasterNodeProcess,
    ProgramNodeProcess,
    Resolver,
    StackNodeProcess,
)
from misaka_tpu.transport import ProgramClient, StackClient, RpcError

# The docker-compose add-2 programs (docker-compose.yml:35-40,:54-59).
MISAKA1 = "IN ACC\nADD 1\nMOV ACC, misaka2:R0\nMOV R0, ACC\nOUT ACC"
MISAKA2 = "MOV R0, ACC\nADD 1\nPUSH ACC, misaka3\nPOP misaka3, ACC\nMOV ACC, misaka1:R0"


@pytest.fixture
def add2_cluster():
    """master + 2 program nodes + 1 stack node on loopback ephemeral ports."""
    resolver = Resolver()
    nodes = {}

    stack = StackNodeProcess(grpc_port=0, host="127.0.0.1")
    resolver.set_addr("misaka3", f"127.0.0.1:{stack.start()}")
    nodes["misaka3"] = stack

    for name, program in (("misaka1", MISAKA1), ("misaka2", MISAKA2)):
        p = ProgramNodeProcess(
            master_uri="last_order", resolver=resolver, grpc_port=0, host="127.0.0.1"
        )
        p.load_program(program)
        resolver.set_addr(name, f"127.0.0.1:{p.start()}")
        nodes[name] = p

    master = MasterNodeProcess(
        node_info={
            "misaka1": {"type": "program"},
            "misaka2": {"type": "program"},
            "misaka3": {"type": "stack"},
        },
        resolver=resolver,
        grpc_port=0,
        host="127.0.0.1",
    )
    resolver.set_addr("last_order", f"127.0.0.1:{master.start()}")

    yield master, nodes
    master.close()
    for n in nodes.values():
        n.close()


def test_add2_end_to_end(add2_cluster):
    master, _ = add2_cluster
    master.run()
    assert master.is_running
    for v in (5, -3, 1000, 0):
        assert master.compute(v, timeout=10) == v + 2


def test_add2_pause_resume(add2_cluster):
    master, nodes = add2_cluster
    master.run()
    assert master.compute(1, timeout=10) == 3
    master.pause()
    assert not master.is_running
    assert not nodes["misaka1"]._life.is_running
    master.run()
    assert master.compute(7, timeout=10) == 9


def test_reset_clears_state(add2_cluster):
    master, nodes = add2_cluster
    master.run()
    assert master.compute(2, timeout=10) == 4
    master.reset()
    assert nodes["misaka1"].acc == 0
    assert nodes["misaka3"].depth == 0
    master.run()
    assert master.compute(10, timeout=10) == 12


def test_load_reprograms_target(add2_cluster):
    """The /load path — which the reference cannot actually perform (it dials
    port 8000 where no node listens, quirk #1, master.go:178)."""
    master, _ = add2_cluster
    master.run()
    assert master.compute(1, timeout=10) == 3
    # Make misaka2 add 10 instead of 1.
    master.load(
        "misaka2",
        "MOV R0, ACC\nADD 10\nPUSH ACC, misaka3\nPOP misaka3, ACC\nMOV ACC, misaka1:R0",
    )
    master.run()
    assert master.compute(1, timeout=10) == 12


def test_load_rejects_unknown_node(add2_cluster):
    from misaka_tpu.runtime.topology import TopologyError

    master, _ = add2_cluster
    with pytest.raises(TopologyError, match="not valid on this network"):
        master.load("nobody", "NOP")


def test_load_bad_program_surfaces_error(add2_cluster):
    master, _ = add2_cluster
    with pytest.raises(BroadcastError, match="not a valid instruction"):
        master.load("misaka1", "FROB 3")


def test_http_surface(add2_cluster):
    """The reference's curl workflow (README.md:50-80) against the
    distributed master, byte-for-byte."""
    from misaka_tpu.runtime.master import make_http_server

    master, _ = add2_cluster
    server = make_http_server(master, 0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        def post(path, data=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=urllib.parse.urlencode(data or {}).encode(),
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=15) as resp:
                    return resp.status, resp.read().decode()
            except urllib.error.HTTPError as e:
                return e.code, e.read().decode()

        def get(path):
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=15
                ) as resp:
                    return resp.status, resp.read().decode()
            except urllib.error.HTTPError as e:
                return e.code, e.read().decode()

        assert post("/run") == (200, "Success")
        status, body = post("/compute", {"value": 40})
        assert status == 200 and '"value": 42' in body
        # the stream lanes serve the distributed control plane too: one
        # request, FIFO pairing through the live gRPC pipeline
        status, body = post("/compute_batch", {"values": "1, 2 3"})
        assert status == 200 and json.loads(body) == {"values": [3, 4, 5]}
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/compute_raw",
            data=np.asarray([10, 11], "<i4").tobytes(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=15) as resp:
            assert np.frombuffer(resp.read(), "<i4").tolist() == [12, 13]
        # GET /trace must 404 cleanly: the distributed control plane has no
        # fused trace ring (only the fused MasterNode does).
        status, body = get("/trace")
        assert (status, body) == (404, "not found")
        assert post("/pause") == (200, "Success")
        assert post("/reset") == (200, "Success")
    finally:
        server.shutdown()
        server.server_close()


def test_port_backpressure():
    """Send blocks while the cap-1 port is full (program.go:160-175): the
    second send must not complete until the program consumes the first."""
    p = ProgramNodeProcess(master_uri="x", grpc_port=0, host="127.0.0.1")
    port = p.start()
    try:
        with ProgramClient(f"127.0.0.1:{port}") as client:
            client.send(1, 0, timeout=5)  # fills r0
            fut = client.send_future(2, 0)  # must block: port full
            time.sleep(0.3)
            assert not fut.done()
            # Consume r0 twice; the blocked send should then land.
            p.load_program("MOV R0, ACC")
            p.run_cmd()
            fut.result(timeout=5)
            deadline = time.time() + 5
            while p.acc != 2 and time.time() < deadline:
                time.sleep(0.02)
            assert p.acc == 2
    finally:
        p.close()


def test_send_invalid_register_rejected():
    p = ProgramNodeProcess(master_uri="x", grpc_port=0, host="127.0.0.1")
    port = p.start()
    try:
        with ProgramClient(f"127.0.0.1:{port}") as client:
            with pytest.raises(RpcError, match="not a valid register"):
                client.send(1, 7, timeout=5)
    finally:
        p.close()


def test_stack_pop_blocks_until_push():
    s = StackNodeProcess(grpc_port=0, host="127.0.0.1")
    port = s.start()
    try:
        with StackClient(f"127.0.0.1:{port}") as client:
            fut = client.pop_future()
            time.sleep(0.2)
            assert not fut.done()
            client.push(42, timeout=5)
            assert fut.result(timeout=5).value == 42
            # LIFO order.
            client.push(1, timeout=5)
            client.push(2, timeout=5)
            assert client.pop(timeout=5) == 2
            assert client.pop(timeout=5) == 1
    finally:
        s.close()


def test_stack_pop_cancelled_by_reset():
    """A reset cancels a blocked Pop with the reference's error message
    (stack.go:150-153) — and, unlike the reference (quirk #4), no leaked
    consumer swallows the next pushed value."""
    s = StackNodeProcess(grpc_port=0, host="127.0.0.1")
    port = s.start()
    try:
        with StackClient(f"127.0.0.1:{port}") as client:
            fut = client.pop_future()
            time.sleep(0.2)
            client.reset(timeout=5)
            with pytest.raises(Exception, match="stack pop cancelled"):
                fut.result(timeout=5)
            # The next push+pop pair works: nothing swallowed the value.
            client.push(7, timeout=5)
            assert client.pop(timeout=5) == 7
    finally:
        s.close()


def test_int32_wire_truncation():
    """Cross-node transfers truncate to sint32 exactly like the reference's
    int32(v) casts (program.go:498, messenger.proto:34-41)."""
    p = ProgramNodeProcess(master_uri="x", grpc_port=0, host="127.0.0.1")
    port = p.start()
    try:
        with ProgramClient(f"127.0.0.1:{port}") as client:
            client.send(2**31 + 5, 0, timeout=5)  # wraps to -2**31+5
            p.load_program("MOV R0, ACC")
            p.run_cmd()
            deadline = time.time() + 5
            while p.acc == 0 and time.time() < deadline:
                time.sleep(0.02)
            assert p.acc == -(2**31) + 5
    finally:
        p.close()


def test_broadcast_error_on_dead_node():
    """Any single node failure fails the whole broadcast (master.go:288-292)."""
    resolver = Resolver()
    resolver.set_addr("ghost", "127.0.0.1:1")  # nothing listens there
    master = MasterNodeProcess(
        node_info={"ghost": {"type": "program"}},
        resolver=resolver,
        grpc_port=0,
        host="127.0.0.1",
    )
    master.start()
    try:
        with pytest.raises(BroadcastError):
            master.run()
    finally:
        master.close()


@pytest.fixture(scope="module")
def tls_cert(tmp_path_factory):
    """Self-signed cert with loopback SANs — the reference's `make cert`
    openssl flow (Makefile:7-12, openssl/certificate.conf), loopback SANs
    instead of compose hostnames."""
    import subprocess

    d = tmp_path_factory.mktemp("certs")
    conf = d / "certificate.conf"
    conf.write_text(
        "[req]\ndefault_bits = 2048\nprompt = no\ndefault_md = sha256\n"
        "req_extensions = req_ext\ndistinguished_name = dn\n"
        "[dn]\nC = JP\nST = TOK\nL = Academy City\nO = SYSTEM\nOU = Level 6 Shift\n"
        "CN = localhost\n"
        "[req_ext]\nsubjectAltName = @alt_names\n"
        "[alt_names]\nDNS.1 = localhost\nIP.1 = 127.0.0.1\n"
    )
    cert, key = d / "service.pem", d / "service.key"
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(cert), "-days", "1",
            "-config", str(conf), "-extensions", "req_ext",
        ],
        check=True,
        capture_output=True,
    )
    return str(cert), str(key)


def test_tls_program_node_roundtrip(tls_cert):
    """CERT_FILE/KEY_FILE TLS on the node server, the same cert as the
    client's root CA (program.go:52-55, :98-101)."""
    cert, key = tls_cert
    p = ProgramNodeProcess(
        master_uri="x", cert_file=cert, key_file=key, grpc_port=0, host="127.0.0.1"
    )
    port = p.start()
    try:
        with ProgramClient(f"127.0.0.1:{port}", cert_file=cert) as client:
            client.send(11, 1, timeout=5)
            p.load_program("MOV R1, ACC")
            p.run_cmd()
            deadline = time.time() + 5
            while p.acc != 11 and time.time() < deadline:
                time.sleep(0.02)
            assert p.acc == 11
    finally:
        p.close()


def test_tls_rejects_plaintext_client(tls_cert):
    cert, key = tls_cert
    s = StackNodeProcess(cert_file=cert, key_file=key, grpc_port=0, host="127.0.0.1")
    port = s.start()
    try:
        with StackClient(f"127.0.0.1:{port}") as client:  # no cert: plaintext
            with pytest.raises(RpcError):
                client.push(1, timeout=3)
    finally:
        s.close()


def test_port_value_survives_rpc_retry():
    """A consumed port value must survive a transient RPC failure: the hold
    latch keeps it across retries (the reference would re-read the port and
    silently lose it, program.go:80-92 + :435-472)."""
    import socket

    # Reserve a port, leave it dead for now.
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()

    resolver = Resolver()
    resolver.set_addr("peer", f"127.0.0.1:{dead_port}")
    p = ProgramNodeProcess(master_uri="x", resolver=resolver, grpc_port=0, host="127.0.0.1")
    p.load_program("MOV R0, peer:R1")
    port = p.start()
    try:
        with ProgramClient(f"127.0.0.1:{port}") as client:
            client.send(123, 0, timeout=5)  # consumed into the hold latch
        time.sleep(0.4)  # let the send fail against the dead peer at least once
        p.run_cmd()
        time.sleep(0.4)
        assert p._hold == 123  # consumed, latched, not lost

        peer = ProgramNodeProcess(
            master_uri="x", grpc_port=dead_port, host="127.0.0.1"
        )
        peer.start()
        try:
            deadline = time.time() + 10
            while peer._ports[1].qsize() == 0 and time.time() < deadline:
                time.sleep(0.05)
            assert peer._ports[1].get_nowait() == 123  # retry delivered it
        finally:
            peer.close()
    finally:
        p.close()
