"""The benchmark harness itself is product code: verify it on tiny shapes.

bench.py asserts completion + parity before reporting a number; these tests
run every BASELINE config through the same code path (XLA engine on CPU) so
a harness regression (wrong oracle, wrong ordering assumption, undersized
tick budget that can't recover) fails here, not on TPU bench night.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402 — bench.py lives at the repo root


@pytest.mark.parametrize("name", sorted(bench.CONFIGS))
def test_bench_config_tiny(name):
    r = bench.bench_config(name, batch=64, per_instance=8)
    assert r["throughput"] > 0
    assert r["values"] == 64 * 8


def test_bench_add2_alias():
    r = bench.bench_add2(batch=32, per_instance=4)
    assert r["name"] == "add2"


def test_bench_latency_tiny():
    lat = bench.bench_latency(samples=10, warmup=2)
    assert lat["p50_us"] > 0 and lat["p99_us"] >= lat["p50_us"]


def test_bench_lanes_tiny():
    r = bench.bench_lanes(8, batch=16, per_instance=4)
    assert r["ticks_per_sec"] > 0 and r["throughput"] > 0


def test_bench_lanes_parity_guard():
    # the pipeline oracle is v + n: make sure the asserted path really runs
    r = bench.bench_lanes(4, batch=8, per_instance=4)
    assert r["lanes"] == 4


def test_last_tpu_context_reads_committed_artifacts():
    # the CPU-fallback payload must carry the latest real-TPU headline so a
    # reduced artifact never reads as a cross-round regression
    ctx = bench._last_tpu_context()
    assert ctx is not None and ctx["round"] >= 2
    assert ctx["metric"] == "add2_compute_throughput"
    assert ctx["value"] > 1e6  # a real TPU number, not a CPU fallback


def test_lane_matrix_reports_median():
    r = bench.bench_lanes(4, batch=8, per_instance=4)
    # best-of-reps methodology: median emitted alongside for cross-round
    # comparability with pre-r4 single-shot numbers
    assert r["ticks_per_sec_median"] <= r["ticks_per_sec"] * 1.0001
    assert r["reps"] >= 1


# --- TPU attach retry / labeled fallback (bench._retry_or_fallback) ---------


class _Exec:
    """Records the execve call _retry_or_fallback would have made."""

    def __init__(self):
        self.calls = []

    def __call__(self, path, argv, env):
        self.calls.append((path, argv, env))


def test_attach_failure_retries_with_backoff():
    ex, slept = _Exec(), []
    bench._retry_or_fallback(
        RuntimeError("backend init crash"),
        environ={"JAX_PLATFORMS": "tpu,cpu"},
        execve=ex, sleep=slept.append, argv=["bench.py"],
    )
    assert slept == [bench.ATTACH_BACKOFF_S]
    (_, argv, env), = ex.calls
    assert env["MISAKA_ATTACH_ATTEMPT"] == "1"
    assert "backend init crash" in env["MISAKA_TPU_ATTACH_ERROR"]
    # a RETRY keeps the TPU platform; only the spent-attempts path goes CPU
    assert env.get("JAX_PLATFORMS") == "tpu,cpu"
    assert env.get("MISAKA_BENCH_FALLBACK") != "cpu"


def test_attach_backoff_doubles_per_attempt():
    ex, slept = _Exec(), []
    bench._retry_or_fallback(
        RuntimeError("again"),
        environ={"MISAKA_ATTACH_ATTEMPT": "1"},
        execve=ex, sleep=slept.append, argv=["bench.py"],
    )
    assert slept == [bench.ATTACH_BACKOFF_S * 2]
    assert ex.calls[0][2]["MISAKA_ATTACH_ATTEMPT"] == "2"


def test_attach_retries_spent_falls_back_to_labeled_cpu():
    ex = _Exec()
    bench._retry_or_fallback(
        RuntimeError("still down"),
        environ={"MISAKA_ATTACH_ATTEMPT": "2"},
        execve=ex, sleep=lambda s: None,
        argv=["bench.py", "--all", "--roofline"],
    )
    (_, argv, env), = ex.calls
    # the fallback capture is CPU, reduced, and LABELED with the reason —
    # never a silent platform switch (ISSUE r6 acceptance)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["MISAKA_BENCH_FALLBACK"] == "cpu"
    assert "still down" in env["MISAKA_TPU_ATTACH_ERROR"]
    assert "--all" not in argv and "--roofline" not in argv


def test_attach_no_fallback_reraises_when_spent():
    with pytest.raises(RuntimeError, match="down for good"):
        bench._retry_or_fallback(
            RuntimeError("down for good"),
            environ={"MISAKA_ATTACH_ATTEMPT": "2",
                     "MISAKA_BENCH_NO_FALLBACK": "1"},
            execve=_Exec(), sleep=lambda s: None, argv=["bench.py"],
        )


def test_attach_cpu_only_init_failure_is_a_real_bug():
    # JAX_PLATFORMS=cpu failing to init is not an attach blip: no retry,
    # no fallback, the exception propagates
    with pytest.raises(RuntimeError, match="cpu broke"):
        bench._retry_or_fallback(
            RuntimeError("cpu broke"),
            environ={"JAX_PLATFORMS": "cpu"},
            execve=_Exec(), sleep=lambda s: None, argv=["bench.py"],
        )


def test_attach_retry_inherits_remaining_ttl():
    ex = _Exec()
    bench._retry_or_fallback(
        RuntimeError("crash"),
        environ={"MISAKA_BENCH_TTL_S": "1140"},
        execve=ex, sleep=lambda s: None, argv=["bench.py"],
    )
    # the re-exec'd child gets what REMAINS of the driver's TTL budget
    assert float(ex.calls[0][2]["MISAKA_BENCH_TTL_S"]) <= 1140.0


def test_bench_native_pool_tiny():
    from misaka_tpu.core import native_serve

    if not native_serve.available():
        pytest.skip("native interpreter unavailable (no g++)")
    r = bench.bench_native_pool(threads=2, batch=4, in_cap=8,
                                chunk_steps=256, rounds=2)
    assert r["throughput"] > 0 and r["values"] == 2 * 4 * 8
    assert r["threads"] == 2
