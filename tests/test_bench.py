"""The benchmark harness itself is product code: verify it on tiny shapes.

bench.py asserts completion + parity before reporting a number; these tests
run every BASELINE config through the same code path (XLA engine on CPU) so
a harness regression (wrong oracle, wrong ordering assumption, undersized
tick budget that can't recover) fails here, not on TPU bench night.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402 — bench.py lives at the repo root


@pytest.mark.parametrize("name", sorted(bench.CONFIGS))
def test_bench_config_tiny(name):
    r = bench.bench_config(name, batch=64, per_instance=8)
    assert r["throughput"] > 0
    assert r["values"] == 64 * 8


def test_bench_add2_alias():
    r = bench.bench_add2(batch=32, per_instance=4)
    assert r["name"] == "add2"


def test_bench_latency_tiny():
    lat = bench.bench_latency(samples=10, warmup=2)
    assert lat["p50_us"] > 0 and lat["p99_us"] >= lat["p50_us"]


def test_bench_lanes_tiny():
    r = bench.bench_lanes(8, batch=16, per_instance=4)
    assert r["ticks_per_sec"] > 0 and r["throughput"] > 0


def test_bench_lanes_parity_guard():
    # the pipeline oracle is v + n: make sure the asserted path really runs
    r = bench.bench_lanes(4, batch=8, per_instance=4)
    assert r["lanes"] == 4


def test_last_tpu_context_reads_committed_artifacts():
    # the CPU-fallback payload must carry the latest real-TPU headline so a
    # reduced artifact never reads as a cross-round regression
    ctx = bench._last_tpu_context()
    assert ctx is not None and ctx["round"] >= 2
    assert ctx["metric"] == "add2_compute_throughput"
    assert ctx["value"] > 1e6  # a real TPU number, not a CPU fallback


def test_lane_matrix_reports_median():
    r = bench.bench_lanes(4, batch=8, per_instance=4)
    # best-of-reps methodology: median emitted alongside for cross-round
    # comparability with pre-r4 single-shot numbers
    assert r["ticks_per_sec_median"] <= r["ticks_per_sec"] * 1.0001
    assert r["reps"] >= 1
