"""The project lint engine (misaka_tpu/lint): rules MSK001-MSK006.

Every rule is pinned by a seeded-bad fixture (the EXACT defect shape
from the review incident that motivated it — reintroducing the pattern
must fail `make lint`) and a corrected good twin (the shipped fix's
shape must stay clean).  Plus: the baseline suppress/stale round-trip,
inline `lint: disable=`, the derived lock/launder registries over the
REAL modules they were seeded from, and the acceptance gate — a full
run over the live tree with the committed baseline reports zero new
findings.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from misaka_tpu import lint
from misaka_tpu.lint.checkers import (
    ExceptionBreadth,
    HandlerDrain,
    LabelCardinality,
    LockDiscipline,
    ThreadLifecycle,
)
from misaka_tpu.lint.engine import Module, apply_baseline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_rule(rule: str, source: str):
    return lint.run_source(textwrap.dedent(source), [lint.checker_for(rule)])


def rules_of(findings):
    return [f.rule for f in findings]


# --- MSK001 lock-discipline --------------------------------------------------

BAD_MSK001_MODULE = """
    import threading

    _lock = threading.Lock()
    _accounts = {}

    def account(label):
        with _lock:
            return _accounts.setdefault(label, object())

    def flush():
        with _lock:
            acct = account("other")   # re-acquires _lock: deadlock
            return acct
"""

GOOD_MSK001_MODULE = """
    import threading

    _lock = threading.Lock()
    _accounts = {}

    def account(label):
        with _lock:
            return _accounts.setdefault(label, object())

    def flush():
        acct = account("other")   # resolved BEFORE taking the lock
        with _lock:
            return acct
"""


def test_msk001_module_lock_self_deadlock_caught():
    findings = run_rule("MSK001", BAD_MSK001_MODULE)
    assert rules_of(findings) == ["MSK001"]
    assert "account()" in findings[0].message
    assert run_rule("MSK001", GOOD_MSK001_MODULE) == []


BAD_MSK001_CLASS = """
    import threading

    class Governor:
        def __init__(self):
            self._lock = threading.Lock()
            self._tenants = {}

        def _evict(self, now):
            with self._lock:
                self._tenants.clear()

        def check(self, tenant):
            with self._lock:
                self._evict(0.0)   # self._evict re-takes self._lock
                return tenant in self._tenants
"""

GOOD_MSK001_CLASS = """
    import threading

    class Governor:
        def __init__(self):
            self._lock = threading.Lock()
            self._tenants = {}

        def _evict_locked(self, now):
            self._tenants.clear()   # caller holds the lock

        def check(self, tenant):
            with self._lock:
                self._evict_locked(0.0)
                return tenant in self._tenants
"""


def test_msk001_instance_lock_self_deadlock_caught():
    findings = run_rule("MSK001", BAD_MSK001_CLASS)
    assert rules_of(findings) == ["MSK001"]
    assert "self._evict()" in findings[0].message
    assert run_rule("MSK001", GOOD_MSK001_CLASS) == []


def test_msk001_rlock_and_nested_def_are_exempt():
    src = """
        import threading

        _lock = threading.RLock()    # reentrant: re-entry is the point

        def account(label):
            with _lock:
                return label

        def flush():
            with _lock:
                return account("x")

        _plain = threading.Lock()

        def taker():
            with _plain:
                pass

        def schedule():
            with _plain:
                def later():
                    return taker()   # runs later, not under the lock
                return later
    """
    assert run_rule("MSK001", src) == []


def test_msk001_derived_registry_matches_known_modules():
    """The derivation is seeded by the repo's real lock registries: the
    usage ledger and SLO window modules (the r12 self-deadlocks) must
    derive exactly the acquirer sets a reviewer would write down."""
    checker = LockDiscipline()
    for rel, lock, expect_some in [
        ("misaka_tpu/runtime/usage.py", "_lock",
         {"account", "snapshot", "reset"}),
        ("misaka_tpu/utils/slo.py", "_lock",
         {"set_objectives", "_windows_for"}),
    ]:
        path = os.path.join(REPO, rel)
        with open(path, encoding="utf-8") as fh:
            module = Module(path, rel, fh.read())
        reg = checker.module_locks(module)
        assert lock in reg, f"{rel}: module lock {lock} not derived"
        missing = expect_some - reg[lock]
        assert not missing, f"{rel}: {lock} acquirers missing {missing}"


# --- MSK002 exception-breadth ------------------------------------------------

BAD_MSK002 = """
    import http.client

    def proxy(rh, path):
        try:
            status, payload = rh.post_form(path)
        except OSError as e:
            return 502, str(e).encode()
        return status, payload
"""

GOOD_MSK002 = """
    import http.client

    def proxy(rh, path):
        try:
            status, payload = rh.post_form(path)
        except (OSError, http.client.HTTPException) as e:
            return 502, str(e).encode()
        return status, payload
"""


def test_msk002_narrow_oserror_around_http_caught():
    findings = run_rule("MSK002", BAD_MSK002)
    assert rules_of(findings) == ["MSK002"]
    assert "post_form" in findings[0].message
    assert run_rule("MSK002", GOOD_MSK002) == []


def test_msk002_bare_except_caught_anywhere():
    findings = run_rule("MSK002", """
        def f():
            try:
                return 1
            except:
                return 2
    """)
    assert rules_of(findings) == ["MSK002"]
    assert "bare" in findings[0].message


def test_msk002_split_handlers_and_exception_cover():
    # a second handler naming HTTPException covers the try; so does a
    # broad `except Exception`; plain socket cleanup is out of scope
    assert run_rule("MSK002", """
        import http.client

        def f(conn):
            try:
                return conn.getresponse()
            except http.client.HTTPException:
                return None
            except OSError:
                return None
    """) == []
    assert run_rule("MSK002", """
        def f(rh):
            try:
                return rh.post_form("/x")
            except Exception:
                return None
    """) == []
    assert run_rule("MSK002", """
        def close(sock):
            try:
                sock.close()
            except OSError:
                pass
    """) == []


# --- MSK003 label-cardinality ------------------------------------------------

BAD_MSK003 = """
    from misaka_tpu.utils import metrics

    M = metrics.counter("m_total", "h", ("tenant",))

    def record(tenant):
        M.labels(tenant=tenant).inc()   # client-minted series, unbounded
"""

GOOD_MSK003 = """
    from misaka_tpu.utils import metrics

    M = metrics.counter("m_total", "h", ("tenant",))
    _seen = set()

    def record(tenant):
        label = metrics.capped_label(_seen, tenant, 64)
        _seen.add(label)
        M.labels(tenant=label).inc()
"""


def test_msk003_unlaundered_tenant_label_caught():
    findings = run_rule("MSK003", BAD_MSK003)
    assert rules_of(findings) == ["MSK003"]
    assert "tenant" in findings[0].message
    assert run_rule("MSK003", GOOD_MSK003) == []


def test_msk003_module_launder_wrappers_are_derived():
    # a module function that calls capped_label is itself laundering —
    # the edge.tenant_metric_label shape; and calling it inline is clean
    src = """
        from misaka_tpu.utils import metrics

        M = metrics.counter("m_total", "h", ("tenant",))
        _seen = set()

        def tenant_metric_label(tenant):
            label = metrics.capped_label(_seen, tenant, 64)
            _seen.add(label)
            return label

        def record(tenant):
            M.labels(tenant=tenant_metric_label(tenant)).inc()
    """
    assert run_rule("MSK003", src) == []
    rel = "misaka_tpu/runtime/edge.py"
    path = os.path.join(REPO, rel)
    with open(path, encoding="utf-8") as fh:
        module = Module(path, rel, fh.read())
    assert "tenant_metric_label" in LabelCardinality()._launder_fns(module)


def test_msk003_server_chosen_labels_are_exempt():
    assert run_rule("MSK003", """
        from misaka_tpu.utils import metrics

        M = metrics.counter("m_total", "h", ("route",))

        def record(route):
            M.labels(route=route).inc()   # route names are server-chosen
    """) == []


# --- MSK004 thread-lifecycle -------------------------------------------------

BAD_MSK004 = """
    import threading

    class Plane:
        def __init__(self):
            self._accept_thread = threading.Thread(target=self._accept)
            self._accept_thread.start()

        def _accept(self):
            pass

        def close(self):
            pass   # never joins: one OS thread leaked per lifecycle
"""

GOOD_MSK004_JOIN = """
    import threading

    class Plane:
        def __init__(self):
            self._accept_thread = threading.Thread(target=self._accept)
            self._accept_thread.start()

        def _accept(self):
            pass

        def close(self):
            self._accept_thread.join()
"""


def test_msk004_unjoined_accept_thread_caught():
    findings = run_rule("MSK004", BAD_MSK004)
    assert rules_of(findings) == ["MSK004"]
    assert "_accept_thread" in findings[0].message
    assert run_rule("MSK004", GOOD_MSK004_JOIN) == []


def test_msk004_daemon_and_list_join_shapes_pass():
    assert run_rule("MSK004", """
        import threading

        def fire():
            threading.Thread(target=print, daemon=True).start()
    """) == []
    assert run_rule("MSK004", """
        import threading

        def fanout(items):
            ts = [threading.Thread(target=print, args=(i,)) for i in items]
            ts.append(threading.Thread(target=print))
            extra = []
            extra += [threading.Thread(target=print)]
            for t in ts:
                t.start()
            for t in ts + extra:
                t.join()
    """) == []
    # late daemonization before start() is the sampler's shape
    assert run_rule("MSK004", """
        import threading

        def fire():
            t = threading.Thread(target=print)
            t.daemon = True
            t.start()
    """) == []


def test_msk004_unjoined_list_caught():
    findings = run_rule("MSK004", """
        import threading

        def fanout(items):
            ts = [threading.Thread(target=print, args=(i,)) for i in items]
            for t in ts:
                t.start()
    """)
    assert rules_of(findings) == ["MSK004"]


# --- MSK005 clock-discipline -------------------------------------------------

BAD_MSK005 = """
    import time

    def running_s(started):
        return time.time() - started   # wall clock as a duration
"""

GOOD_MSK005 = """
    import time

    def running_s(started_mono):
        return time.monotonic() - started_mono

    def stamp():
        return round(time.time(), 3)   # timestamp VALUE: legal
"""


def test_msk005_walltime_duration_caught():
    findings = run_rule("MSK005", BAD_MSK005)
    assert rules_of(findings) == ["MSK005"]
    assert "monotonic" in findings[0].message
    assert run_rule("MSK005", GOOD_MSK005) == []


def test_msk005_deadline_add_caught():
    findings = run_rule("MSK005", """
        import time

        def deadline():
            return time.time() + 30
    """)
    assert rules_of(findings) == ["MSK005"]


# --- MSK006 handler-drain ----------------------------------------------------

BAD_MSK006 = """
    class Handler:
        def _handle_post(self):
            if self.headers.get("Content-Length") is None:
                self._text(411, "Content-Length required")   # body unread
                return
            raw = self.rfile.read(10)
            self._text(200, "ok")
"""

GOOD_MSK006_CLOSE = """
    class Handler:
        def _handle_post(self):
            if self.headers.get("Content-Length") is None:
                self.close_connection = True
                self._text(411, "Content-Length required")
                return
            raw = self.rfile.read(10)
            self._text(200, "ok")
"""

GOOD_MSK006_DRAIN = """
    from misaka_tpu.runtime import edge as edge_mod

    class Handler:
        def _handle_post(self):
            if not self._authorized():
                edge_mod.drain_or_close(self)
                self._text(401, "who are you")
                return
            form = self._form()
            self._text(200, "ok")
"""


def test_msk006_undrained_post_error_caught():
    findings = run_rule("MSK006", BAD_MSK006)
    assert rules_of(findings) == ["MSK006"]
    assert "drain_or_close" in findings[0].message
    assert run_rule("MSK006", GOOD_MSK006_CLOSE) == []
    assert run_rule("MSK006", GOOD_MSK006_DRAIN) == []


def test_msk006_get_handlers_out_of_scope():
    assert run_rule("MSK006", """
        class Handler:
            def _handle_get(self):
                self._text(404, "not found")   # GETs carry no body
    """) == []


# --- engine mechanics --------------------------------------------------------


def test_fingerprint_stable_across_line_drift():
    base = run_rule("MSK005", BAD_MSK005)[0]
    shifted = run_rule("MSK005", "\n\n# a comment\n" + textwrap.dedent(
        BAD_MSK005))[0]
    assert base.line != shifted.line
    assert base.fingerprint == shifted.fingerprint


def test_repeated_findings_get_distinct_fingerprints():
    findings = run_rule("MSK005", """
        import time

        def f(a, b):
            x = time.time() - a
            y = time.time() - b
            return x + y
    """)
    assert len(findings) == 2
    assert len({f.fingerprint for f in findings}) == 2


def test_baseline_round_trip(tmp_path):
    findings = run_rule("MSK005", BAD_MSK005)
    path = str(tmp_path / "baseline.txt")
    lint.save_baseline(path, findings, header="justify me")
    baseline = lint.load_baseline(path)
    new, suppressed, stale = apply_baseline(findings, baseline)
    assert (new, len(suppressed), stale) == ([], 1, set())
    # the fixed tree: the entry goes stale (reported, not fatal)
    new, suppressed, stale = apply_baseline([], baseline)
    assert new == [] and suppressed == [] and len(stale) == 1
    # comments and blank lines survive the parse
    raw = open(path, encoding="utf-8").read()
    assert raw.startswith("# justify me")


def test_inline_disable_comment():
    src = """
        import time

        def age(started):
            return time.time() - started  # lint: disable=MSK005 epoch arg
    """
    assert run_rule("MSK005", src) == []
    # the wrong rule name does not suppress
    src2 = src.replace("MSK005", "MSK001")
    assert rules_of(run_rule("MSK005", src2)) == ["MSK005"]
    # sloppy separators still suppress; a FORGOTTEN rule list ("disable="
    # with nothing after it) suppresses nothing and must not crash
    src3 = src.replace("disable=MSK005 epoch arg",
                       "disable=MSK001, MSK005")
    assert run_rule("MSK005", src3) == []
    src4 = src.replace("disable=MSK005 epoch arg", "disable=")
    assert rules_of(run_rule("MSK005", src4)) == ["MSK005"]


def test_syntax_error_is_a_located_finding(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    findings = lint.run_tree([str(bad)], lint.ALL_CHECKERS,
                             base=str(tmp_path))
    assert [f.rule for f in findings] == ["MSK000"]
    assert "syntax error" in findings[0].message


# --- the acceptance gate -----------------------------------------------------


def test_live_tree_zero_new_findings():
    """`make lint` over the committed tree: every finding is either
    fixed or baselined with a justification — zero NEW findings."""
    from misaka_tpu.lint.__main__ import DEFAULT_ROOTS, BASELINE_DEFAULT

    roots = [r for r in DEFAULT_ROOTS if os.path.exists(os.path.join(REPO, r))]
    findings = lint.run_tree(roots, lint.ALL_CHECKERS, base=REPO)
    baseline = lint.load_baseline(os.path.join(REPO, BASELINE_DEFAULT))
    new, suppressed, stale = apply_baseline(findings, baseline)
    assert new == [], "new lint findings:\n" + lint.format_findings(new)
    assert not stale, f"stale baseline entries (remove them): {stale}"
    # the committed baseline is real debt, not a dumping ground: every
    # entry must carry a justification comment within the 6 lines above
    lines = open(os.path.join(REPO, BASELINE_DEFAULT),
                 encoding="utf-8").read().splitlines()
    for i, line in enumerate(lines):
        if line.strip() and not line.startswith("#"):
            window = lines[max(0, i - 6):i]
            assert any(w.startswith("#") for w in window), \
                f"baseline entry without a justification comment: {line}"


def test_cli_exit_codes(tmp_path):
    """`python -m misaka_tpu.lint <file>` exits 1 on a fresh finding,
    0 once it is baselined — the make-lint contract, end to end."""
    victim = tmp_path / "victim.py"
    victim.write_text(
        "import time\n\n\ndef f(s):\n    return time.time() - s\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    cmd = [sys.executable, "-m", "misaka_tpu.lint", str(victim),
           "--baseline", str(tmp_path / "b.txt")]
    r1 = subprocess.run(cmd, capture_output=True, text=True, env=env)
    assert r1.returncode == 1, r1.stdout + r1.stderr
    assert "MSK005" in r1.stdout
    r2 = subprocess.run(cmd + ["--update-baseline"], capture_output=True,
                        text=True, env=env)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    r3 = subprocess.run(cmd, capture_output=True, text=True, env=env)
    assert r3.returncode == 0, r3.stdout + r3.stderr


@pytest.mark.parametrize("rule,bad", [
    ("MSK001", BAD_MSK001_MODULE),
    ("MSK001", BAD_MSK001_CLASS),
    ("MSK002", BAD_MSK002),
    ("MSK003", BAD_MSK003),
    ("MSK004", BAD_MSK004),
    ("MSK005", BAD_MSK005),
    ("MSK006", BAD_MSK006),
])
def test_every_rule_catches_its_seed_under_full_checker_set(rule, bad):
    """Seeded-bad fixtures stay caught when ALL checkers run together
    (no checker masks another's findings)."""
    findings = lint.run_source(textwrap.dedent(bad), lint.ALL_CHECKERS)
    assert rule in {f.rule for f in findings}
