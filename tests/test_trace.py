"""Trace ring buffer: device-recorded history matches an independent replay.

traced_step must (a) leave network semantics bit-identical to the untraced
step, (b) record exactly what each lane fetched and whether it committed,
(c) wrap correctly once past capacity, and (d) decode to truthful listings.
"""

import jax
import numpy as np

from misaka_tpu import networks
from misaka_tpu.core import CompiledNetwork, init_trace, traced_step
from misaka_tpu.core.trace import (
    TR_ACC,
    TR_COMMIT,
    TR_OP,
    TR_PC,
    decode_trace,
    format_trace,
    run_traced,
)
from misaka_tpu.tis import isa


def make_add2(**kw):
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    return top, top.compile(**kw)


def test_traced_step_state_identical():
    """Tracing must not perturb execution: same state trajectory as `run`."""
    _, net = make_add2()
    # Two independent states (net.run donates its input buffers, so a
    # tree-level alias would be deleted by the first run).
    s_plain = net.init_state()
    s_plain, _ = net.feed(s_plain, [5, 6, 7])
    s_traced = net.init_state()
    s_traced, _ = net.feed(s_traced, [5, 6, 7])
    trace = net.init_trace(cap=64)

    s_plain = net.run(s_plain, 40)
    s_traced, trace = net.run_traced(s_traced, trace, 40)

    for a, b, name in zip(s_plain, s_traced, s_plain._fields):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    assert int(trace.wr) == 40


def test_records_fetch_and_commit():
    """Tick 0 on add2: misaka1 fetches IN (commits — input queued), misaka2
    fetches MOV R0, ACC (parks — port empty)."""
    _, net = make_add2()
    state = net.init_state()
    state, _ = net.feed(state, [10])
    trace = net.init_trace(cap=16)
    state, trace = net.run_traced(state, trace, 1)

    buf = np.asarray(trace.buf)
    # lane 0 = misaka1: IN ACC committed, acc now 10
    assert buf[0, 0, TR_PC] == 0
    assert buf[0, 0, TR_OP] == isa.OP_IN
    assert buf[0, 0, TR_COMMIT] == 1
    assert buf[0, 0, TR_ACC] == 10
    # lane 1 = misaka2: MOV R0, ACC parked on empty port
    assert buf[1, 0, TR_OP] == isa.OP_MOV_LOCAL
    assert buf[1, 0, TR_COMMIT] == 0


def test_ring_wrap_keeps_last_cap_ticks():
    _, net = make_add2()
    state = net.init_state()
    state, _ = net.feed(state, [1, 2, 3])
    trace = net.init_trace(cap=8)
    state, trace = net.run_traced(state, trace, 20)

    assert int(trace.wr) == 20
    entries = decode_trace(trace, net.code, net.prog_len)
    ticks = sorted({e["tick"] for e in entries})
    assert ticks == list(range(12, 20))  # only the last 8 survive


def test_decode_disassembles_truthfully():
    top, net = make_add2()
    state = net.init_state()
    state, _ = net.feed(state, [41])
    trace = net.init_trace(cap=64)
    state, trace = net.run_traced(state, trace, 30)

    entries = decode_trace(
        trace,
        net.code,
        net.prog_len,
        lane_names=list(top.lane_ids()),
        stack_names=list(top.stack_ids()),
    )
    texts = {e["text"] for e in entries}
    assert "IN ACC" in texts
    assert "PUSH ACC, misaka3" in texts
    listing = format_trace(entries)
    assert "misaka1" in listing and "*" in listing  # parked ticks marked

    # And the computation still finished: 41 + 2 emitted.
    state, outs = net.drain(state)
    assert outs == [43]


def test_decode_last_n():
    _, net = make_add2()
    state = net.init_state()
    trace = net.init_trace(cap=32)
    state, trace = net.run_traced(state, trace, 10)
    entries = decode_trace(trace, net.code, net.prog_len, last=3)
    assert sorted({e["tick"] for e in entries}) == [7, 8, 9]


def test_trace_under_jit():
    """traced_step composes with jit/scan (no host callbacks inside)."""
    _, net = make_add2()
    code, prog_len = net._tables
    state = net.init_state()
    trace = net.init_trace(cap=16)

    @jax.jit
    def chunk(s, t):
        return run_traced(code, prog_len, s, t, 12)

    state, trace = chunk(state, trace)
    assert int(trace.wr) == 12


def test_batched_tracing_matches_unbatched():
    """A batched run tracing instance k records exactly what an unbatched run
    of that instance's inputs records (instances are independent)."""
    import numpy as np

    _, net_b = make_add2(batch=4)
    _, net_1 = make_add2()

    state_b = net_b.init_state()
    # distinct inputs per instance; instance 2 gets value 41
    vals = np.asarray([[10], [20], [41], [30]], np.int32)
    state_b = state_b._replace(
        in_buf=state_b.in_buf.at[:, 0].set(vals[:, 0]),
        in_wr=state_b.in_wr + 1,
    )
    trace_b = net_b.init_trace(cap=32)
    state_b, trace_b = net_b.run_traced(state_b, trace_b, 20, instance=2)

    state_1 = net_1.init_state()
    state_1 = state_1._replace(
        in_buf=state_1.in_buf.at[0].set(41), in_wr=state_1.in_wr + 1
    )
    trace_1 = net_1.init_trace(cap=32)
    state_1, trace_1 = net_1.run_traced(state_1, trace_1, 20)

    assert int(trace_b.wr) == int(trace_1.wr) == 20
    assert (np.asarray(trace_b.buf) == np.asarray(trace_1.buf)).all()
    # and the batched state advanced all four instances
    assert (np.asarray(state_b.out_wr) == 1).all()


def test_batched_tracing_instance_out_of_range():
    _, net = make_add2(batch=4)
    try:
        net.run_traced(net.init_state(), init_trace(2, 4), 1, instance=4)
    except ValueError as e:
        assert "out of range" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_single_step_api():
    """traced_step is usable directly, one tick at a time (debugger path)."""
    _, net = make_add2()
    code, prog_len = net._tables
    state = net.init_state()
    trace = net.init_trace(cap=4)
    state, trace = traced_step(code, prog_len, state, trace)
    assert int(trace.wr) == 1
    assert int(state.tick) == 1


def test_master_trace_live():
    """MasterNode with trace_cap: live instruction history over HTTP
    GET /debug/isa_trace, with GET /trace kept as a deprecated alias
    answering the same body plus a Deprecation header (the old name
    collided with the request-tracing namespace, /debug/requests)."""
    import threading
    import urllib.request

    from misaka_tpu.runtime.master import MasterNode, make_http_server

    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    master = MasterNode(top, chunk_steps=16, trace_cap=64)
    httpd = make_http_server(master, port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        master.run()
        assert master.compute(7) == 9  # tracing must not perturb execution

        entries = master.trace(last=50)
        assert entries and any(e["text"] == "IN ACC" for e in entries)

        import json

        with urllib.request.urlopen(
            base + "/debug/isa_trace?last=5", timeout=10
        ) as resp:
            payload = resp.read().decode()
            assert resp.headers.get("Deprecation") is None
        decoded = json.loads(payload)["entries"]
        assert decoded and {"tick", "lane", "name", "pc", "op", "committed", "acc", "text"} <= set(decoded[0])
        assert len({e["tick"] for e in decoded}) <= 5

        # the deprecated alias answers the same body + Deprecation header
        with urllib.request.urlopen(base + "/trace?last=5", timeout=10) as resp:
            alias = resp.read().decode()
            assert resp.headers.get("Deprecation") == "true"
            assert "/debug/isa_trace" in (resp.headers.get("Link") or "")
        assert {e["tick"] for e in json.loads(alias)["entries"]} \
            <= {e["tick"] for e in master.trace()}

        # reset reinitializes the ring
        master.reset()
        assert master.trace() == []
    finally:
        master.pause()
        httpd.shutdown()


def test_master_trace_disabled():
    from misaka_tpu.runtime.master import MasterNode

    master = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8), chunk_steps=16)
    try:
        master.trace()
    except RuntimeError as e:
        assert "tracing disabled" in str(e)
    else:
        raise AssertionError("expected RuntimeError")
