"""Batched serving: concurrent /compute round-robined over vmapped instances.

The reference allows concurrent /compute only by racing (response swaps,
master.go:216-219).  A batched master gives real concurrency — up to `batch`
requests in flight, per-instance FIFO pairing — with deterministic results.
"""

import threading

import numpy as np
import pytest

from misaka_tpu.networks import add2
from misaka_tpu.runtime.master import ComputeTimeout, MasterNode


def make_master(batch=4, **kw):
    return MasterNode(
        add2(in_cap=8, out_cap=8, stack_cap=8), chunk_steps=32, batch=batch, **kw
    )


def test_sequential_computes():
    master = make_master()
    master.run()
    try:
        for v in (5, -3, 0, 999, 12):  # rolls through all slots and wraps
            assert master.compute(v) == v + 2
    finally:
        master.pause()


def test_concurrent_computes_all_correct():
    master = make_master(batch=8)
    master.run()
    results = {}
    errors = []

    def worker(v):
        try:
            results[v] = master.compute(v, timeout=60)
        except Exception as e:  # pragma: no cover — failure path
            errors.append(e)

    try:
        threads = [threading.Thread(target=worker, args=(v,)) for v in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        master.pause()
    assert not errors
    assert results == {v: v + 2 for v in range(32)}


@pytest.mark.slow
def test_concurrency_spreads_over_instances():
    master = make_master(batch=4)
    master.run()
    try:
        for v in range(8):
            master.compute(v)
    finally:
        master.pause()
    # retired totals show >1 instance did work: each add2 instance retires
    # ~12 instructions per value; with perfect round-robin every instance
    # handled 2 of the 8 values.
    state = master.snapshot()
    per_instance = np.asarray(state.retired).sum(axis=1)
    assert (per_instance > 0).all()


def test_status_reports_batch_and_totals():
    master = make_master(batch=4)
    master.run()
    try:
        for v in range(4):
            master.compute(v)
    finally:
        master.pause()
    s = master.status()
    assert s["batch"] == 4
    assert s["retired_per_lane"]["misaka1"] >= 4  # summed across instances
    assert s["in_queue"] == 0 and s["out_queue"] == 0


@pytest.mark.slow
def test_timeout_keeps_pairing_per_instance():
    master = make_master(batch=2)  # paused: nothing will compute
    with pytest.raises(ComputeTimeout):
        master.compute(1, timeout=0.2)
    master.run()
    try:
        # The slot that timed out discards its stale output; pairing holds.
        for v in (10, 20, 30, 40):
            assert master.compute(v, timeout=60) == v + 2
    finally:
        master.pause()


@pytest.mark.slow
def test_checkpoint_roundtrip_batched(tmp_path):
    master = make_master(batch=4)
    master.run()
    try:
        assert master.compute(7) == 9
    finally:
        master.pause()
    path = str(tmp_path / "b.npz")
    master.save_checkpoint(path)

    m2 = make_master(batch=4)
    m2.load_checkpoint(path)
    m2.run()
    try:
        assert m2.compute(100) == 102
    finally:
        m2.pause()

    m3 = make_master(batch=2)
    with pytest.raises(ValueError, match="batch"):
        m3.load_checkpoint(path)


def test_load_recompiles_batched():
    master = make_master(batch=4)
    master.load("misaka1", "IN ACC\nADD 10\nOUT ACC")
    master.run()
    try:
        assert master.compute(1) == 11
    finally:
        master.pause()


def test_batched_tracing_records_one_instance():
    """trace_cap with batch traces one instance exactly (round-2 closure of
    the round-1 gap: the production batched config is now debuggable)."""
    master = make_master(batch=2, trace_cap=4096)
    assert master.engine_name == "scan-traced"
    master.run()
    try:
        for v in (5, 6, 7):
            assert master.compute(v) == v + 2
    finally:
        master.pause()
    entries = master.trace()
    assert entries, "batched master recorded no trace"
    committed = [e for e in entries if e["committed"]]
    assert committed, "traced instance committed nothing"
    # the traced instance runs the same add2 program: its records carry real
    # opcodes from both lanes
    assert {e["name"] for e in entries} == {"misaka1", "misaka2"}


def test_batched_trace_instance_selectable():
    master = make_master(batch=3, trace_cap=4096, trace_instance=2)
    master.run()
    try:
        for v in range(6):  # round-robin lands two values on instance 2
            master.compute(v)
    finally:
        master.pause()
    entries = master.trace()
    assert any(e["committed"] for e in entries)


def test_compute_many_fifo_pairing():
    master = make_master(batch=2)
    master.run()
    try:
        vals = list(range(40))
        assert master.compute_many(vals, timeout=60) == [v + 2 for v in vals]
        # interleaved with single computes on the other slot
        assert master.compute(99) == 101
    finally:
        master.pause()


@pytest.mark.slow
def test_compute_many_concurrent_chunks():
    master = make_master(batch=4)
    master.run()
    results = {}
    errors = []

    def worker(base):
        try:
            vals = list(range(base, base + 50))
            results[base] = master.compute_many(vals, timeout=60)
        except Exception as e:  # pragma: no cover — failure path
            errors.append(e)

    try:
        threads = [
            threading.Thread(target=worker, args=(b,)) for b in (0, 100, 200, 300)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        master.pause()
    assert not errors
    for base, outs in results.items():
        assert outs == [v + 2 for v in range(base, base + 50)]


def test_compute_spread_order_and_parity():
    master = MasterNode(
        add2(in_cap=4, out_cap=4, stack_cap=8), chunk_steps=32, batch=8
    )
    master.run()
    try:
        vals = list(range(-30, 70))  # 100 values over 8 instances, ring cap 4
        assert master.compute_spread(vals, timeout=60) == [v + 2 for v in vals]
        # instances genuinely shared the work
        state = master.snapshot()
        per_instance = np.asarray(state.retired).sum(axis=1)
        assert (per_instance > 0).sum() >= 4
    finally:
        master.pause()


def test_compute_spread_small_falls_back():
    master = make_master(batch=4)
    master.run()
    try:
        assert master.compute_spread([7]) == [9]  # single-slot path
        assert master.compute_spread([]) == []
    finally:
        master.pause()


@pytest.mark.slow
def test_compute_spread_concurrent_with_compute():
    master = make_master(batch=8)
    master.run()
    errors = []
    results = {}

    def spreader():
        try:
            vals = list(range(200))
            results["spread"] = master.compute_spread(vals, timeout=60)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def singles():
        try:
            results["singles"] = [
                master.compute(v, timeout=60) for v in (1000, 2000, 3000)
            ]
        except Exception as e:  # pragma: no cover
            errors.append(e)

    try:
        ts = [threading.Thread(target=spreader), threading.Thread(target=singles)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        master.pause()
    assert not errors
    assert results["spread"] == [v + 2 for v in range(200)]
    assert results["singles"] == [1002, 2002, 3002]


def test_compute_many_empty_and_bad_shape():
    master = make_master(batch=2)
    assert master.compute_many([]) == []
    with pytest.raises(ValueError, match="flat"):
        master.compute_many([[1, 2]])


@pytest.mark.slow
def test_fused_interpret_engine_serves():
    """The fused Pallas kernel on the REAL serving path (interpret mode off
    TPU): MISAKA_ENGINE=fused-interpret must produce identical results."""
    master = MasterNode(
        add2(in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=32,
        batch=128,  # fused kernel needs a multiple of 128
        engine="fused-interpret",
    )
    assert master.engine_name == "fused"
    assert master.status()["engine"] == "fused"
    master.run()
    try:
        assert master.compute_many([3, 4, 5], timeout=120) == [5, 6, 7]
    finally:
        master.pause()


def test_fused_engine_requires_batch():
    with pytest.raises(ValueError, match="fused engine requires"):
        MasterNode(add2(), engine="fused")


def test_auto_engine_falls_back_off_tpu():
    from misaka_tpu.core import native_serve

    master = make_master(batch=2, engine="auto")
    if native_serve.available():
        # off-TPU, auto prefers the multi-threaded native host tier (r6):
        # the r4/r5 CPU captures served scan-compact at a third of the
        # north star while this tier sat unused
        assert master.engine_name == "native"
    else:
        # no C++ toolchain: scan engine, with the platform-auto kernel
        # surfaced (CPU: compact)
        assert master.engine_name.startswith("scan-")
    assert master.engine_name != "scan-traced"


def test_unbatched_still_serializes():
    master = MasterNode(add2(in_cap=8, out_cap=8, stack_cap=8), chunk_steps=32)
    master.run()
    try:
        assert master.compute(5) == 7
        assert "batch" not in master.status()
    finally:
        master.pause()


@pytest.mark.slow
def test_reset_during_blocked_compute_keeps_slot_healthy():
    """A reset that wipes a waiting request must not poison its slot's
    pairing (phantom stale counter -> every later compute times out)."""
    master = make_master(batch=2)  # not running: computes block
    errors = []

    def doomed():
        try:
            master.compute(1, timeout=3)
        except ComputeTimeout:
            pass
        except Exception as e:  # pragma: no cover
            errors.append(e)

    t = threading.Thread(target=doomed)
    t.start()
    import time

    time.sleep(0.3)
    master.reset()  # wipes the queued request mid-wait (epoch bump)
    t.join()
    assert not errors

    master.run()
    try:
        # Every slot must still pair correctly (4 values roll through both).
        for v in (10, 20, 30, 40):
            assert master.compute(v, timeout=60) == v + 2
    finally:
        master.pause()


@pytest.mark.slow
def test_free_slot_preferred_over_busy():
    """With one instance stuck, requests flow through the free one instead
    of head-of-line blocking behind the round-robin cursor."""
    master = make_master(batch=2)
    master.run()
    master._compute_locks[0].acquire()  # simulate a stuck in-flight request
    try:
        # generous timeout: this test flaked at 10s under a saturated CI box
        # (the full suite once ran 3x slow); the property is routing, not speed
        for v in (1, 2, 3):  # rr start alternates; all must use slot 1
            assert master.compute(v, timeout=30) == v + 2
    finally:
        master._compute_locks[0].release()
        master.pause()
