"""jax.profiler surface: captures real traces, enforces one-at-a-time."""

import glob
import os
import threading
import urllib.error
import urllib.parse
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from misaka_tpu.networks import add2
from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.utils.profiling import Profiler, ProfilerError, capture


def _trace_files(log_dir):
    return glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"), recursive=True)


def test_capture_writes_trace(tmp_path):
    log_dir = str(tmp_path / "trace")
    with capture(log_dir):
        jnp.arange(64).sum().block_until_ready()
    assert _trace_files(log_dir), "no xplane trace written"


def test_profiler_start_stop(tmp_path):
    p = Profiler()
    log_dir = str(tmp_path / "p1")
    p.start(log_dir)
    assert p.active_dir == log_dir
    with pytest.raises(ProfilerError):
        p.start(str(tmp_path / "p2"))  # already capturing
    jnp.ones((8, 8)).sum().block_until_ready()
    assert p.stop() == log_dir
    assert p.active_dir is None
    with pytest.raises(ProfilerError):
        p.stop()  # not capturing
    assert _trace_files(log_dir)


def test_profile_routes(tmp_path):
    profile_dir = str(tmp_path / "profiles")
    master = MasterNode(add2(in_cap=8, out_cap=8, stack_cap=8), chunk_steps=16)
    httpd = make_http_server(master, port=0, profile_dir=profile_dir)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    def post(path, data=None):
        body = urllib.parse.urlencode(data or {}).encode()
        req = urllib.request.Request(base + path, data=body, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    try:
        master.run()
        assert post("/profile/start", {"name": "run1"}) == (200, "Success")
        code, _ = post("/profile/start", {"name": "run2"})
        assert code == 409  # one capture at a time
        assert master.compute(1) == 3  # device work lands inside the capture
        code, out_dir = post("/profile/stop")
        assert code == 200
        assert _trace_files(out_dir)
        code, _ = post("/profile/stop")
        assert code == 409  # nothing capturing

        code, _ = post("/profile/start", {"name": "../escape"})
        assert code == 400
    finally:
        master.pause()
        httpd.shutdown()


def test_profile_disabled_without_dir():
    master = MasterNode(add2(in_cap=8, out_cap=8, stack_cap=8), chunk_steps=16)
    httpd = make_http_server(master, port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        req = urllib.request.Request(base + "/profile/start", data=b"", method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 403
    finally:
        httpd.shutdown()
