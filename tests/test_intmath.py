"""Host-side int math parity (utils/math.go twins) vs the kernel's clamp."""

import numpy as np

from misaka_tpu.utils.intmath import int_clamp, int_max, int_min


def test_minmax():
    assert int_max(3, -5) == 3
    assert int_min(3, -5) == -5
    assert int_max(2, 2) == 2


def test_clamp_matches_numpy_clip():
    rng = np.random.default_rng(7)
    for _ in range(200):
        v, lo = int(rng.integers(-100, 100)), int(rng.integers(-50, 0))
        hi = lo + int(rng.integers(0, 60))
        assert int_clamp(v, lo, hi) == int(np.clip(v, lo, hi))


def test_jro_bound_semantics():
    """The exact JRO use: clamp(pc+offset, 0, len-1) (program.go:354)."""
    length = 5
    assert int_clamp(3 + 100, 0, length - 1) == 4
    assert int_clamp(3 - 100, 0, length - 1) == 0
