"""Native C++ assembler parity: corpus + fuzz against the Python frontend."""

import numpy as np
import pytest

from misaka_tpu.tis.lower import TISLowerError, lower_program
from misaka_tpu.tis.native import assemble_native, native_available
from misaka_tpu.tis.parser import TISParseError
from tests.test_differential import build_random_network, random_program

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain for the native assembler"
)

LANES = {"misaka1": 0, "misaka2": 1}
STACKS = {"misaka3": 0}

CORPUS = [
    "IN ACC\nADD 1\nMOV ACC, misaka2:R0\nMOV R0, ACC\nOUT ACC\n",
    "MOV R0, ACC\nADD 1\nPUSH ACC, misaka3\nPOP misaka3, ACC\nMOV ACC, misaka1:R0\n",
    "start: NOP\nJMP start\nJEZ START\nJNZ start\nJGZ start\nJLZ start",
    "# comment\n\nlbl:\nlbl2: SWP\nSAV\nNEG",
    "MOV -3, NIL\nMOV 7, misaka2:R3\nSUB R2\nJRO -1\nJRO ACC",
    "PUSH 3, misaka3\nPUSH R1, misaka3\nPOP misaka3, NIL\nIN NIL\nOUT 12\nOUT R3",
    "ADD 2147483650",  # int32 wrap
]


@pytest.mark.parametrize("idx", range(len(CORPUS)))
def test_corpus_parity(idx):
    program = CORPUS[idx]
    want = lower_program(program, LANES, STACKS)
    got = assemble_native(program, LANES, STACKS)
    assert got.length == want.length
    np.testing.assert_array_equal(got.code, want.code)


@pytest.mark.parametrize(
    "program,exc",
    [
        ("FROB 1", TISParseError),
        ("MOV 1,ACC", TISParseError),
        ("JMP nowhere", TISParseError),
        ("a:\nA:", TISParseError),
        ("MOV ACC, ghost:R0", TISLowerError),
        ("PUSH 1, ghost", TISLowerError),
    ],
)
def test_error_parity(program, exc):
    with pytest.raises(exc) as native_err:
        assemble_native(program, LANES, STACKS)
    with pytest.raises(exc) as py_err:
        try:
            lower_program(program, LANES, STACKS)
        except (TISParseError, TISLowerError) as e:
            raise e
    assert str(native_err.value) == str(py_err.value)


@pytest.mark.parametrize("seed", range(25))
def test_fuzz_parity(seed):
    rng = np.random.default_rng(1000 + seed)
    lane_names = list(LANES)
    stack_names = list(STACKS)
    program = random_program(rng, lane_names, stack_names, int(rng.integers(1, 12)))
    want = lower_program(program, LANES, STACKS)
    got = assemble_native(program, LANES, STACKS)
    np.testing.assert_array_equal(
        got.code, want.code, err_msg=f"seed {seed} program:\n{program}"
    )
