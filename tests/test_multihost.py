"""Multi-host DCN support: hybrid mesh + a real 2-process collective run.

The heavyweight test spawns two OS processes that join a jax.distributed
coordinator (gloo CPU collectives) and run the sharded superstep engine with
its all_gather/pmin/psum routing crossing the process boundary — the CPU
stand-in for a multi-slice TPU deployment (parallel/multihost.py doctrine:
batch over DCN, lanes over ICI).
"""


import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # two-process DCN coordinator run — `make test-all` lane

import jax

from misaka_tpu import networks
from misaka_tpu.parallel import (
    hybrid_mesh,
    initialize_from_env,
    make_global_state,
    make_mesh,
    make_sharded_runner,
    put_global,
)
from jax.sharding import PartitionSpec as P


def test_initialize_noop_without_env():
    assert initialize_from_env({}) is False


def test_hybrid_mesh_single_process_matches_make_mesh():
    m = hybrid_mesh(model_parallel=2)
    ref = make_mesh(model_parallel=2)
    assert m.shape == ref.shape
    assert m.axis_names == ref.axis_names


def test_put_global_single_process():
    mesh = make_mesh(model_parallel=2)
    arr = np.arange(8, dtype=np.int32)
    out = put_global(arr, mesh, P("model"))
    np.testing.assert_array_equal(np.asarray(out), arr)


def test_make_global_state_matches_shard_state():
    """Single-process: make_global_state places the same values shard_state does."""
    from misaka_tpu.parallel import shard_state

    mesh = make_mesh(model_parallel=2)
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile(batch=4)
    state = net.init_state()
    a = make_global_state(state, mesh)
    b = shard_state(state, mesh)
    for x, y, name in zip(a, b, a._fields):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), err_msg=name)
        assert x.sharding == y.sharding, name


@pytest.mark.skipif(
    getattr(jax.config, "jax_cpu_collectives_implementation", None) != "gloo",
    reason="needs gloo CPU collectives for cross-process tests "
           "(config key absent on jax < 0.5)",
)
def test_two_process_dcn_run():
    """Two real processes, one coordinator, full sharded engine with parity."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    env = {k: v for k, v in os.environ.items() if not k.startswith("JAX")}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=repo_root,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out:\n" + "\n---\n".join(outs))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
        assert "MULTIHOST_OK" in out, f"worker did not verify:\n{out}"
