"""An independent, deliberately naive Python oracle for the superstep semantics.

Implements the documented tick discipline (core/step.py module docstring) with
plain Python ints/lists and sequential lane iteration — no numpy, no sharing
of kernel code paths beyond the ISA field layout.  Used by the randomized
differential tests to cross-check both the XLA and Pallas kernels.

Semantics implemented (in this order, per tick):
  phase A  every lane with a ready inbound-port source consumes it into its
           hold latch (port cleared) — before any delivery
  phase B  sends/stack ops/IN/OUT arbitrate by LOWEST LANE INDEX; sends see
           post-consume port occupancy; one op per stack, one IN, one OUT
           per network per tick; stack/ring feasibility uses begin-of-tick
           tops/counters
  commit   a lane commits iff its source was ready and its destination
           granted; effects read begin-of-tick registers; PC advances
           (wrap/jump/JRO-clamp) only on commit
"""

from __future__ import annotations

import numpy as np

from misaka_tpu.tis import isa

_M32 = 1 << 32
_M64 = 1 << 64


def _i32(v: int) -> int:
    v &= _M32 - 1
    return v - _M32 if v >= (1 << 31) else v


def _i64(v: int) -> int:
    """Wrap to Go's 64-bit int: acc/bak are `int` (program.go:27-28)."""
    v &= _M64 - 1
    return v - _M64 if v >= (1 << 63) else v


class Oracle:
    def __init__(self, code, prog_len, num_stacks, stack_cap, in_cap, out_cap):
        self.progs = [
            [list(map(int, code[n, l])) for l in range(int(prog_len[n]))]
            for n in range(code.shape[0])
        ]
        n = len(self.progs)
        self.acc = [0] * n
        self.bak = [0] * n
        self.pc = [0] * n
        self.port_val = [[0] * 4 for _ in range(n)]
        self.port_full = [[False] * 4 for _ in range(n)]
        self.hold_val = [0] * n
        self.holding = [False] * n
        self.num_stacks = max(1, num_stacks)
        self.stack_cap = stack_cap
        self.stacks = [[] for _ in range(self.num_stacks)]
        self.in_cap = in_cap
        self.out_cap = out_cap
        self.in_buf = [0] * in_cap
        self.in_rd = 0
        self.in_wr = 0
        self.out_buf = [0] * out_cap
        self.out_rd = 0
        self.out_wr = 0
        self.tick_count = 0
        self.retired = [0] * n

    def feed(self, values):
        for v in values:
            assert self.in_wr - self.in_rd < self.in_cap
            self.in_buf[self.in_wr % self.in_cap] = _i32(v)
            self.in_wr += 1

    def _instr(self, n):
        return self.progs[n][self.pc[n]]

    def tick(self):
        n_lanes = len(self.progs)
        f = isa

        # --- phase A: consumes ---------------------------------------------
        for n in range(n_lanes):
            ins = self._instr(n)
            if ins[f.F_OP] in f.READS_SRC and ins[f.F_SRC] >= f.SRC_R0:
                p = ins[f.F_SRC] - f.SRC_R0
                if not self.holding[n] and self.port_full[n][p]:
                    self.hold_val[n] = self.port_val[n][p]
                    self.holding[n] = True
                    self.port_full[n][p] = False

        # --- source resolution ---------------------------------------------
        src_ok = [True] * n_lanes
        src_val = [0] * n_lanes
        for n in range(n_lanes):
            ins = self._instr(n)
            if ins[f.F_OP] not in f.READS_SRC:
                continue
            s = ins[f.F_SRC]
            if s == f.SRC_IMM:
                src_val[n] = ins[f.F_IMM]
            elif s == f.SRC_ACC:
                src_val[n] = self.acc[n]
            elif s == f.SRC_NIL:
                src_val[n] = 0
            else:
                src_val[n] = self.hold_val[n]
                src_ok[n] = self.holding[n]

        # --- arbitration ----------------------------------------------------
        granted = [False] * n_lanes
        begin_tops = [len(s) for s in self.stacks]
        stack_taken = [False] * self.num_stacks
        in_taken = False
        out_taken = False
        in_avail = self.in_wr - self.in_rd > 0
        out_free = self.out_wr - self.out_rd < self.out_cap
        deliveries = []   # (lane_to, port, value)
        stack_pushes = [] # (stack, value)
        stack_pops = {}   # lane -> value
        in_winner = None
        out_value = None

        for n in range(n_lanes):
            ins = self._instr(n)
            op = ins[f.F_OP]
            if op == f.OP_MOV_NET and src_ok[n]:
                tgt, port = ins[f.F_TGT], ins[f.F_PORT]
                occupied = self.port_full[tgt][port] or any(
                    d[0] == tgt and d[1] == port for d in deliveries
                )
                if not occupied:
                    deliveries.append((tgt, port, _i32(src_val[n])))  # wire: sint32
                    granted[n] = True
            elif op == f.OP_PUSH and src_ok[n]:
                s = ins[f.F_TGT]
                if not stack_taken[s] and begin_tops[s] < self.stack_cap:
                    stack_taken[s] = True
                    stack_pushes.append((s, _i32(src_val[n])))  # wire: sint32
                    granted[n] = True
            elif op == f.OP_POP:
                s = ins[f.F_TGT]
                if not stack_taken[s] and begin_tops[s] > 0:
                    stack_taken[s] = True
                    stack_pops[n] = self.stacks[s][-1]
                    granted[n] = True
            elif op == f.OP_IN:
                if in_avail and not in_taken:
                    in_taken = True
                    in_winner = n
                    granted[n] = True
            elif op == f.OP_OUT and src_ok[n]:
                if out_free and not out_taken:
                    out_taken = True
                    out_value = _i32(src_val[n])  # wire: sint32
                    granted[n] = True

        # --- commit + effects ----------------------------------------------
        old_acc = list(self.acc)
        old_bak = list(self.bak)
        for n in range(n_lanes):
            ins = self._instr(n)
            op = ins[f.F_OP]
            needs_grant = op in (
                f.OP_MOV_NET, f.OP_PUSH, f.OP_POP, f.OP_IN, f.OP_OUT
            )
            commit = granted[n] if needs_grant else src_ok[n]
            if not commit:
                continue
            ln = len(self.progs[n])
            if op == f.OP_MOV_LOCAL and ins[f.F_DST] == f.DST_ACC:
                self.acc[n] = src_val[n]
            elif op == f.OP_ADD:
                self.acc[n] = _i64(old_acc[n] + src_val[n])
            elif op == f.OP_SUB:
                self.acc[n] = _i64(old_acc[n] - src_val[n])
            elif op == f.OP_NEG:
                self.acc[n] = _i64(-old_acc[n])
            elif op == f.OP_SWP:
                self.acc[n] = old_bak[n]
                self.bak[n] = old_acc[n]
            elif op == f.OP_SAV:
                self.bak[n] = old_acc[n]
            elif op == f.OP_POP and ins[f.F_DST] == f.DST_ACC:
                self.acc[n] = stack_pops[n]
            elif op == f.OP_IN and ins[f.F_DST] == f.DST_ACC:
                self.acc[n] = self.in_buf[self.in_rd % self.in_cap]

            # pc
            taken = (
                op == f.OP_JMP
                or (op == f.OP_JEZ and old_acc[n] == 0)
                or (op == f.OP_JNZ and old_acc[n] != 0)
                or (op == f.OP_JGZ and old_acc[n] > 0)
                or (op == f.OP_JLZ and old_acc[n] < 0)
            )
            if taken:
                self.pc[n] = ins[f.F_JMP]
            elif op == f.OP_JRO:
                self.pc[n] = max(0, min(self.pc[n] + src_val[n], ln - 1))
            else:
                self.pc[n] = (self.pc[n] + 1) % ln
            self.holding[n] = False
            self.retired[n] += 1

        # --- apply resource effects ----------------------------------------
        for (tgt, port, v) in deliveries:
            self.port_full[tgt][port] = True
            self.port_val[tgt][port] = v
        for (s, v) in stack_pushes:
            self.stacks[s].append(v)
        pushed_stacks = {s for s, _ in stack_pushes}
        for s in range(self.num_stacks):
            if stack_taken[s] and s not in pushed_stacks:
                self.stacks[s].pop()  # the tick's single op was a pop
        if in_winner is not None:
            self.in_rd += 1
        if out_taken:
            self.out_buf[self.out_wr % self.out_cap] = out_value
            self.out_wr += 1
        self.tick_count += 1

    def run(self, steps):
        for _ in range(steps):
            self.tick()

    def state_arrays(self):
        """Mirror NetworkState for comparison (unbatched)."""
        n = len(self.progs)
        sm = np.zeros((self.num_stacks, self.stack_cap), np.int32)
        st = np.zeros((self.num_stacks,), np.int32)
        for s, vals in enumerate(self.stacks):
            st[s] = len(vals)
            for c, v in enumerate(vals):
                sm[s, c] = v
        return {
            "acc": np.array([_i32(v) for v in self.acc], np.int32),
            "bak": np.array([_i32(v) for v in self.bak], np.int32),
            "acc_hi": np.array([_i64(v) >> 32 for v in self.acc], np.int32),
            "bak_hi": np.array([_i64(v) >> 32 for v in self.bak], np.int32),
            "pc": np.array(self.pc, np.int32),
            "port_val": np.array(self.port_val, np.int32),
            "port_full": np.array(self.port_full, bool),
            "hold_val": np.array(self.hold_val, np.int32),
            "holding": np.array(self.holding, bool),
            "stack_top": st,
            "stack_mem_used": sm,
            "in_rd": np.int32(self.in_rd),
            "out_wr": np.int32(self.out_wr),
            "out_buf": np.array(self.out_buf, np.int32),
            "tick": np.int32(self.tick_count),
            "retired": np.array(self.retired, np.int32),
        }
