"""The metrics plane (ISSUE 2): registry correctness, exposition-format
validity, counter monotonicity under concurrent traffic, and /metrics +
/healthz served in both fused and distributed modes.

Exposition checks go through utils/metrics.parse_text — a strict parser
that raises on any malformed non-comment line — so "renders" here means
"every line is valid Prometheus text exposition v0.0.4", not "looks
plausible".
"""

import json
import math
import threading
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from misaka_tpu.networks import add2
from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.utils import metrics


# --- registry unit tests ---------------------------------------------------


def test_log_buckets_shape():
    b = metrics.log_buckets(1e-5, 10.0, per_decade=3)
    assert b[0] == 1e-5 and b[-1] == 10.0
    assert all(y > x for x, y in zip(b, b[1:]))
    # log spacing: constant ratio within float-render tolerance
    ratios = [y / x for x, y in zip(b, b[1:])]
    assert all(abs(r - 10 ** (1 / 3)) < 0.01 for r in ratios)
    assert metrics.pow2_buckets(1, 16) == (1.0, 2.0, 4.0, 8.0, 16.0)
    with pytest.raises(metrics.MetricError):
        metrics.log_buckets(10, 1)


def test_counter_rejects_negative_and_gauge_callback():
    r = metrics.Registry()
    c = metrics.counter("t_total", "h", registry=r)
    c.inc(2.5)
    with pytest.raises(metrics.MetricError):
        c.inc(-1)
    g = metrics.gauge("t_gauge", "h", registry=r)
    g.set_function(lambda: 41 + 1)
    assert g.value == 42
    # a crashing callback falls back to the stored value, never raises
    g.set(7)
    g.set_function(lambda: 1 / 0)
    assert g.value == 7
    assert "t_gauge 7" in r.render()


def test_get_or_create_idempotent_and_shape_checked():
    r = metrics.Registry()
    a = metrics.counter("same_total", "h", ("x",), registry=r)
    assert metrics.counter("same_total", "h", ("x",), registry=r) is a
    with pytest.raises(metrics.MetricError):
        metrics.gauge("same_total", "h", registry=r)  # type mismatch
    with pytest.raises(metrics.MetricError):
        metrics.counter("same_total", "h", ("y",), registry=r)  # label mismatch
    h = metrics.histogram("same_h", "h", buckets=(1, 2), registry=r)
    assert metrics.histogram("same_h", "h", buckets=(1, 2), registry=r) is h
    with pytest.raises(metrics.MetricError):
        metrics.histogram("same_h", "h", buckets=(1, 2, 3), registry=r)


def test_labels_validated():
    r = metrics.Registry()
    c = metrics.counter("lab_total", "h", ("route",), registry=r)
    with pytest.raises(metrics.MetricError):
        c.inc()  # labeled metric used without labels
    with pytest.raises(metrics.MetricError):
        c.labels(wrong="x")
    c.labels(route="/a").inc()
    assert c.labels(route="/a") is c.labels(route="/a")


def test_exposition_roundtrip_with_escaping():
    r = metrics.Registry()
    c = metrics.counter("esc_total", "back\\slash and\nnewline", ("v",), registry=r)
    weird = 'quote " back \\ newline \n end'
    c.labels(v=weird).inc(3)
    text = r.render()
    parsed = metrics.parse_text(text)  # raises on any malformed line
    [(series, value)] = [kv for kv in parsed.items() if kv[0].startswith("esc")]
    assert value == 3
    name, labels = metrics.parse_series(series)
    assert name == "esc_total" and labels == {"v": weird}


def test_histogram_render_consistency():
    r = metrics.Registry()
    h = metrics.histogram(
        "lat_seconds", "h", ("k",), buckets=metrics.log_buckets(0.001, 1.0),
        registry=r,
    )
    rng = np.random.default_rng(0)
    obs = list(rng.uniform(0.0001, 2.0, size=200))
    for v in obs:
        h.labels(k="a").observe(v)
    parsed = metrics.parse_text(r.render())
    # bucket monotonicity + le ordering
    buckets = sorted(
        (
            (math.inf if lbl["le"] == "+Inf" else float(lbl["le"]), v)
            for s, v in parsed.items()
            for n, lbl in [metrics.parse_series(s)]
            if n == "lat_seconds_bucket" and lbl["k"] == "a"
        ),
    )
    uppers = [u for u, _ in buckets]
    counts = [c for _, c in buckets]
    assert uppers[-1] == math.inf
    assert counts == sorted(counts), "cumulative buckets must be monotone"
    # +Inf bucket == _count; _sum matches the observations
    assert counts[-1] == parsed['lat_seconds_count{k="a"}'] == len(obs)
    assert parsed['lat_seconds_sum{k="a"}'] == pytest.approx(sum(obs), rel=1e-9)
    # every bucket's count equals a direct recount of the observations
    for upper, cum in buckets[:-1]:
        assert cum == sum(1 for v in obs if v <= upper)


def test_registry_thread_safety():
    r = metrics.Registry()
    c = metrics.counter("conc_total", "h", registry=r)
    h = metrics.histogram("conc_seconds", "h", buckets=(1, 2, 4), registry=r)

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(1.5)

    ts = [threading.Thread(target=work) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    parsed = metrics.parse_text(r.render())
    assert parsed["conc_total"] == 8000
    assert parsed["conc_seconds_count"] == 8000
    assert parsed["conc_seconds_sum"] == pytest.approx(8000 * 1.5)


def test_json_log_formatter():
    import logging

    from misaka_tpu.utils.jsonlog import JsonFormatter

    fmt = JsonFormatter()
    rec = logging.LogRecord(
        "misaka_tpu.master", logging.INFO, __file__, 1, "served %d", (7,), None
    )
    rec.route = "/compute"
    obj = json.loads(fmt.format(rec))
    assert obj["msg"] == "served 7"
    assert obj["logger"] == "misaka_tpu.master"
    assert obj["level"] == "INFO"
    assert obj["route"] == "/compute"
    assert obj["time"].endswith("Z")
    # exceptions collapse into one parseable event
    try:
        raise ValueError("boom")
    except ValueError:
        import sys

        rec2 = logging.LogRecord(
            "x", logging.ERROR, __file__, 1, "failed", (), sys.exc_info()
        )
    obj2 = json.loads(fmt.format(rec2))
    assert "boom" in obj2["exc"]


# --- the live HTTP surface (fused mode) ------------------------------------


@pytest.fixture(scope="module")
def server():
    master = MasterNode(add2(), chunk_steps=32, batch=4)
    httpd = make_http_server(master, port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", master
    master.pause()
    httpd.shutdown()


def post(base, path, data=None, raw=None):
    body = raw if raw is not None else urllib.parse.urlencode(data or {}).encode()
    req = urllib.request.Request(base + path, data=body, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=15) as resp:
            return resp.status, resp.read(), resp.headers.get("Content-Type")
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers.get("Content-Type")


def scrape(base):
    status, body, ctype = get(base, "/metrics")
    assert status == 200
    assert ctype == metrics.CONTENT_TYPE
    return metrics.parse_text(body.decode())


def test_healthz_cheap_liveness(server):
    base, master = server
    status, body, _ = get(base, "/healthz")
    assert status == 200
    h = json.loads(body)
    assert h["ok"] is True
    assert h["engine"] == master.engine_name
    assert h["uptime_seconds"] >= 0
    assert isinstance(h["running"], bool)


def test_metrics_exposition_valid_and_counters_move(server):
    base, _ = server
    before = scrape(server[0])  # parse_text raises on any malformed line
    post(base, "/run")
    status, body = post(base, "/compute", {"value": "3"})
    assert status == 200 and json.loads(body) == {"value": 5}
    vals = np.arange(8, dtype="<i4")
    status, body = post(base, "/compute_raw?spread=1", raw=vals.tobytes())
    assert status == 200
    assert (np.frombuffer(body, "<i4") == vals + 2).all()
    # http counters are recorded in the handler's finally AFTER the
    # response bytes flush (the duration series must cover the write),
    # so a scrape racing the last response can miss them by one beat —
    # poll until both route counters moved, then assert the full set
    import time as _time

    want = (
        'misaka_http_requests_total{route="/compute",method="POST"}',
        'misaka_http_requests_total{route="/compute_raw",method="POST"}',
    )
    deadline = _time.monotonic() + 5
    while True:
        after = scrape(base)
        moved = metrics.delta(before, after)
        if all(moved.get(k, 0) >= 1 for k in want):
            break
        if _time.monotonic() > deadline:
            break
        _time.sleep(0.02)
    assert moved['misaka_http_requests_total{route="/compute",method="POST"}'] >= 1
    assert moved['misaka_http_requests_total{route="/compute_raw",method="POST"}'] >= 1
    assert moved['misaka_http_request_duration_seconds_count{route="/compute"}'] >= 1
    assert moved["misaka_compute_values_total"] >= 9
    assert moved["misaka_device_loop_ticks_total"] > 0
    assert moved["misaka_device_loop_chunk_seconds_count"] > 0
    # occupancy histogram saw the fed slots
    assert moved.get("misaka_device_loop_fed_slots_count", 0) >= 1
    # /status additions
    st = json.loads(get(base, "/status")[1])
    assert st["served_engine"] == st["engine"]
    assert st["uptime_seconds"] > 0
    assert st["requests_total"] >= 2


def test_native_pool_series_present(server):
    base, master = server
    if master.engine_name != "native":
        pytest.skip("native tier unavailable (no toolchain)")
    post(base, "/run")
    post(base, "/compute", {"value": "1"})
    after = scrape(base)
    # the gauges aggregate EVERY live pool in the process (r12): this
    # server's 4 replicas are part of the sum, other suites' still-live
    # pools may add to it
    from misaka_tpu.core import native_serve

    expected = sum(p._replicas for p in native_serve._live_pools())
    assert after["misaka_native_pool_replicas"] == expected >= 4
    assert after["misaka_native_pool_threads"] >= 1
    assert after['misaka_native_serve_calls_total{kind="serve"}'] >= 1
    assert after['misaka_native_serve_seconds_count{kind="serve"}'] >= 1
    assert after['misaka_native_engines_created_total{kind="pool"}'] >= 1


def test_native_pool_gauges_zero_after_close():
    """Pool gauges aggregate every LIVE pool at scrape time (r12): a
    closed pool must stop contributing — an engine swap away from the
    native tier must not leave /metrics reporting a pool that no longer
    exists."""
    from misaka_tpu.core import native_serve

    if not native_serve.available():
        pytest.skip("native tier unavailable (no toolchain)")
    before = metrics.parse_text(metrics.render())
    net = add2(in_cap=16, out_cap=16, stack_cap=8).compile(batch=2)
    pool = native_serve.NativeServePool(net, chunk_steps=16)
    live = metrics.parse_text(metrics.render())
    assert live["misaka_native_pool_replicas"] == (
        before["misaka_native_pool_replicas"] + 2
    )
    assert live["misaka_native_pool_threads"] >= 1
    pool.close()
    closed = metrics.parse_text(metrics.render())
    assert closed["misaka_native_pool_replicas"] == (
        before["misaka_native_pool_replicas"]
    )
    assert closed["misaka_native_pool_threads"] == (
        before["misaka_native_pool_threads"]
    )


def test_counter_monotonic_under_concurrent_compute(server):
    base, _ = server
    post(base, "/run")
    before = scrape(base)
    n_threads, per_thread = 8, 4
    errors = []

    def client(seed):
        try:
            for i in range(per_thread):
                status, body = post(base, "/compute", {"value": str(seed + i)})
                assert status == 200
                assert json.loads(body) == {"value": seed + i + 2}
        except Exception as e:  # pragma: no cover — surfaced below
            errors.append(e)

    ts = [threading.Thread(target=client, args=(100 * i,)) for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    after = scrape(base)
    total = n_threads * per_thread
    key = 'misaka_http_requests_total{route="/compute",method="POST"}'
    assert after[key] - before[key] == total
    assert (
        after["misaka_compute_values_total"]
        - before["misaka_compute_values_total"]
        == total
    )
    # monotonicity: no counter series ever decreases
    decreased = [
        s for s, v in after.items()
        if s in before and s.endswith("_total") and v < before[s]
    ]
    assert not decreased
    # in-flight gauge settled: only the scrape request itself is in flight
    # at render time (it is inside its own _observed window)
    assert after["misaka_http_inflight"] == 1


def test_http_error_counter_and_route_cardinality(server):
    base, _ = server
    before = scrape(base)
    status, _ = post(base, "/compute", {"value": "not-a-number"})
    assert status == 400
    status, _, _ = get(base, "/no/such/route")
    assert status == 405  # reference parity: GET on unknown -> 405
    after = scrape(base)
    moved = metrics.delta(before, after)
    assert moved['misaka_http_errors_total{route="/compute",code="400"}'] >= 1
    # unknown paths collapse to route="other": scanners cannot mint labels
    assert moved['misaka_http_requests_total{route="other",method="GET"}'] >= 1
    assert not any("/no/such/route" in s for s in after)


def test_trace_disabled_is_409_with_hint(server):
    base, _ = server
    status, body, _ = get(base, "/trace")
    assert status == 409
    assert b"MISAKA_TRACE_CAP" in body


def test_checkpoint_metrics(tmp_path):
    m = MasterNode(add2(in_cap=16, out_cap=16, stack_cap=8), chunk_steps=16)
    before_save = metrics.REGISTRY.get("misaka_checkpoint_save_seconds")
    b = metrics.parse_text(metrics.render())
    path = str(tmp_path / "c.npz")
    m.save_checkpoint(path)
    m.load_checkpoint(path)
    a = metrics.parse_text(metrics.render())
    assert before_save is not None
    assert a["misaka_checkpoint_save_seconds_count"] - b.get(
        "misaka_checkpoint_save_seconds_count", 0) == 1
    assert a["misaka_checkpoint_restore_seconds_count"] - b.get(
        "misaka_checkpoint_restore_seconds_count", 0) == 1
    assert a['misaka_engine_swap_total{reason="restore"}'] - b.get(
        'misaka_engine_swap_total{reason="restore"}', 0) == 1


# --- distributed mode ------------------------------------------------------


def test_metrics_and_healthz_distributed_mode():
    """The distributed control plane serves the same observability surface
    through the shared make_http_server (no gRPC cluster needed for the
    endpoints themselves; the full-traffic distributed check lives in the
    slow lane below)."""
    from misaka_tpu.runtime.nodes import MasterNodeProcess

    master = MasterNodeProcess(
        node_info={"n1": {"type": "program"}, "s1": {"type": "stack"}}
    )
    httpd = make_http_server(master, port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        status, body, _ = get(base, "/healthz")
        assert status == 200
        h = json.loads(body)
        assert h["ok"] is True and h["engine"] == "distributed-grpc"
        parsed = scrape(base)  # valid exposition, same strict parser
        assert "misaka_dist_compute_values_total" in parsed
        assert 'misaka_http_requests_total{route="/healthz",method="GET"}' in parsed
        st = json.loads(get(base, "/status")[1])
        assert st["served_engine"] == "distributed-grpc"
        assert st["uptime_seconds"] >= 0 and st["requests_total"] == 0
    finally:
        httpd.shutdown()
        master.close()


@pytest.mark.slow
def test_distributed_counters_move_with_traffic():
    """Real loopback gRPC cluster: compute traffic moves the distributed
    control-plane, data-plane, and stack push/pop counters."""
    from misaka_tpu.runtime.nodes import build_loopback_cluster

    programs = {
        "misaka1": "IN ACC\nADD 1\nMOV ACC, misaka2:R0\nMOV R0, ACC\nOUT ACC",
        "misaka2": "MOV R0, ACC\nADD 1\nPUSH ACC, misaka3\nPOP misaka3, ACC\n"
                   "MOV ACC, misaka1:R0",
    }
    master, close = build_loopback_cluster(
        {"misaka1": "program", "misaka2": "program", "misaka3": "stack"},
        programs,
    )
    httpd = make_http_server(master, port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        before = scrape(base)
        post(base, "/run")
        status, body = post(base, "/compute", {"value": "40"})
        assert status == 200 and json.loads(body) == {"value": 42}
        after = scrape(base)
        moved = metrics.delta(before, after)
        assert moved["misaka_dist_compute_requests_total"] == 1
        assert moved["misaka_dist_compute_values_total"] == 1
        assert moved["misaka_dist_inputs_total"] >= 1
        assert moved["misaka_dist_outputs_total"] >= 1
        assert moved['misaka_dist_broadcasts_total{command="run"}'] >= 1
        # the loopback cluster shares this process: stack + program-node
        # series are visible on the same registry
        assert moved["misaka_stack_push_total"] >= 1
        assert moved["misaka_stack_pop_total"] >= 1
        assert moved["misaka_program_instructions_total"] >= 1
        st = json.loads(get(base, "/status")[1])
        assert st["requests_total"] == 1
        post(base, "/pause")
    finally:
        httpd.shutdown()
        close()


# --- histogram estimation math (r12: reused by the SLO windows) -------------


def test_quantile_from_buckets_interpolation():
    uppers = (1.0, 2.0, 4.0)
    # all mass in one bucket: linear interpolation inside (1, 2]
    counts = [0, 100, 0, 0]
    assert metrics.quantile_from_buckets(uppers, counts, 0.5) == pytest.approx(1.5)
    assert metrics.quantile_from_buckets(uppers, counts, 0.25) == pytest.approx(1.25)
    assert metrics.quantile_from_buckets(uppers, counts, 1.0) == pytest.approx(2.0)
    # first bucket interpolates from 0
    assert metrics.quantile_from_buckets(
        uppers, [100, 0, 0, 0], 0.5
    ) == pytest.approx(0.5)


def test_quantile_from_buckets_boundaries():
    uppers = (1.0, 2.0, 4.0)
    # mass split across buckets: the bucket boundary is the exact
    # quantile where the cumulative count crosses it
    counts = [50, 50, 0, 0]
    assert metrics.quantile_from_buckets(uppers, counts, 0.5) == pytest.approx(1.0)
    assert metrics.quantile_from_buckets(uppers, counts, 0.75) == pytest.approx(1.5)
    # +Inf bucket saturates at the last finite bound
    assert metrics.quantile_from_buckets(
        uppers, [0, 0, 0, 10], 0.5
    ) == pytest.approx(4.0)
    # empty histogram
    assert metrics.quantile_from_buckets(uppers, [0, 0, 0, 0], 0.99) == 0.0
    with pytest.raises(metrics.MetricError):
        metrics.quantile_from_buckets(uppers, [0, 0, 0, 0], 1.5)
    with pytest.raises(metrics.MetricError):
        metrics.quantile_from_buckets(uppers, [0, 0], 0.5)


def test_quantile_matches_exact_on_dense_grid():
    # against numpy's exact quantile for samples ON the duration grid:
    # the estimator must land within one bucket's width
    uppers = metrics.DURATION_BUCKETS
    rng = np.random.default_rng(3)
    samples = rng.lognormal(mean=-6.0, sigma=1.0, size=4000)
    counts = [0] * (len(uppers) + 1)
    import bisect

    for s in samples:
        counts[bisect.bisect_left(uppers, s)] += 1
    for q in (0.5, 0.9, 0.99):
        est = metrics.quantile_from_buckets(uppers, counts, q)
        exact = float(np.quantile(samples, q))
        i = bisect.bisect_left(uppers, exact)
        lo = uppers[i - 1] if i > 0 else 0.0
        hi = uppers[i] if i < len(uppers) else uppers[-1]
        assert lo <= est <= hi * 1.0001, (q, est, exact, lo, hi)


def test_fraction_over():
    uppers = (1.0, 2.0, 4.0)
    counts = [10, 80, 10, 0]
    # threshold mid-bucket: the straddling bucket contributes linearly
    assert metrics.fraction_over(uppers, counts, 1.5) == pytest.approx(
        (80 * 0.5 + 10) / 100
    )
    assert metrics.fraction_over(uppers, counts, 4.0) == 0.0
    assert metrics.fraction_over(uppers, counts, 0.0) == pytest.approx(1.0)
    # the +Inf bucket counts whole (conservative for an unbounded tail)
    assert metrics.fraction_over(uppers, [0, 0, 0, 10], 100.0) == 1.0
    assert metrics.fraction_over(uppers, [0, 0, 0, 0], 1.0) == 0.0
