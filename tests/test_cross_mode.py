"""Cross-mode differential: the jitted superstep engine vs the per-process
gRPC network — the two independent rebuilds of the reference's semantics.

Round 1 proved five implementations of the *superstep spec* bit-identical
(tests/test_differential.py); what it never tested is that the superstep
discipline itself models the reference's free-running concurrency
(program.go:80-92).  This suite closes that: random networks run through
BOTH the lockstep engine (core/) and a real loopback cluster of gRPC node
processes (runtime/nodes.py — free-running threads, blocking ports, live
RPCs), and their /compute output streams must be identical.

Free-running execution is only comparable where the dataflow is
deterministic, so the generator emits Kahn-style networks by construction:

  * every inbound port has exactly ONE sender lane (the pipeline backbone
    sends to the next lane's R0; extra self-sends use the lane's own R1-R3);
  * each stack is touched by exactly ONE lane (balanced PUSH/POP pairs, so
    depth is bounded);
  * exactly one lane executes IN (the head) and one executes OUT (the tail);
  * jumps target forward segment boundaries only — pairs are skipped
    atomically and every loop iteration reaches the tail, so the network is
    1:1 (K inputs -> K outputs) and livelock-free.

Under those rules any legal interleaving of the free-running cluster must
produce the same output stream as the lockstep engine; a divergence means
the superstep discipline (or the per-process interpreter, nodes.py:299-365)
mis-models the reference.  This doubles as the randomized fuzz for the
per-process interpreter (round-1 VERDICT items 2 and 8).

Round 3 widens the generator with the three deterministic forms it missed
(JRO with a static offset, MOV <int> to a network port, OUT <int>) and adds
a CONTENDED suite: networks where several lanes race for one stack, one
port, and the OUT grant.  There output order is schedule-dependent by
design, so the invariant asserted is multiset equality — arbitration
differences may reorder values but must never lose or duplicate one.
"""


import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # per-process cluster fuzz — `make test-all` lane

from misaka_tpu.runtime.nodes import build_loopback_cluster
from misaka_tpu.runtime.topology import Topology

IN_CAP = OUT_CAP = 32
STACK_CAP = 64
N_INPUTS = 8
ENGINE_TICKS = 768


def gen_network(seed):
    """A deterministic (Kahn-style) random network: (node_info, programs)."""
    rng = np.random.default_rng(seed)
    n_lanes = int(rng.integers(1, 5))
    n_stacks = int(rng.integers(0, 3))
    lanes = [f"n{i}" for i in range(n_lanes)]
    stacks = [f"s{i}" for i in range(n_stacks)]
    # each stack is owned by exactly one lane
    stack_owner = {s: int(rng.integers(n_lanes)) for s in stacks}

    def imm():
        return int(rng.integers(-50, 50))

    programs = {}
    for i, name in enumerate(lanes):
        segments: list[list[str]] = []
        n_seg = int(rng.integers(0, 5))
        owned = [s for s in stacks if stack_owner[s] == i]
        for _ in range(n_seg):
            kind = int(rng.integers(0, 12))
            if kind <= 3:  # local register op
                segments.append([
                    rng.choice([
                        "NOP", "SWP", "SAV", "NEG",
                        f"ADD {imm()}", f"SUB {imm()}",
                        f"MOV {imm()}, ACC", "MOV ACC, NIL",
                    ])
                ])
            elif kind <= 5 and owned:  # balanced stack round trip (own stack)
                s = rng.choice(owned)
                src = rng.choice(["ACC", str(imm())])
                segments.append([f"PUSH {src}, {s}", f"POP {s}, ACC"])
            elif kind <= 7:  # self-send round trip on a private port R1-R3;
                # the sent value is ACC or an immediate (MOV_VAL_NETWORK)
                port = int(rng.integers(1, 4))
                src = rng.choice(["ACC", str(imm())])
                segments.append(
                    [f"MOV {src}, {name}:R{port}", f"MOV R{port}, ACC"]
                )
            elif kind <= 9:  # forward conditional/unconditional jump
                segments.append([rng.choice(["JMP", "JEZ", "JNZ", "JGZ", "JLZ"])])
            else:  # computed jump with a static offset: "JRO 2" atomically
                # skips its partner line, "JRO 1" falls through — both land
                # on the next segment boundary regardless of surroundings
                segments.append(
                    ["JRO 2", "NEG"] if rng.integers(2) else ["JRO 1"]
                )

        # resolve forward jumps to segment-boundary labels (atomic skips)
        lines: list[str] = []
        lines.append("IN ACC" if i == 0 else "MOV R0, ACC")
        bound_labels = {}  # segment index -> label name
        for j, seg in enumerate(segments):
            if len(seg) == 1 and seg[0] in ("JMP", "JEZ", "JNZ", "JGZ", "JLZ"):
                tgt = int(rng.integers(j + 1, len(segments) + 1))
                bound_labels.setdefault(tgt, f"b{tgt}")
                seg = [f"{seg[0]} b{tgt}"]
                segments[j] = seg
        # tail: the last lane emits its value; sometimes it also emits a
        # constant (OUT_VAL) — a fixed 2-outputs-per-iteration cadence, still
        # deterministic (same lane, successive lines)
        outs_per_input = 1
        if i == n_lanes - 1:
            tail = ["OUT ACC"]
            if rng.integers(3) == 0:
                tail.append(f"OUT {imm()}")
                outs_per_input = 2
        else:
            tail = [f"MOV ACC, {lanes[i + 1]}:R0"]
        for j, seg in enumerate(segments):
            if j in bound_labels:
                lines.append(f"{bound_labels[j]}:")
            lines.extend(seg)
        if len(segments) in bound_labels:
            lines.append(f"{bound_labels[len(segments)]}:")
        lines.extend(tail)
        programs[name] = "\n".join(lines)

    node_info = {name: "program" for name in lanes}
    node_info.update({s: "stack" for s in stacks})
    return node_info, programs, outs_per_input


def run_engine(node_info, programs, inputs):
    """The lockstep path: compile + feed + run + drain (XLA scan engine)."""
    top = Topology(
        node_info=node_info,
        programs=programs,
        stack_cap=STACK_CAP,
        in_cap=IN_CAP,
        out_cap=OUT_CAP,
    )
    net = top.compile()
    state = net.init_state()
    state, took = net.feed(state, inputs)
    assert took == len(inputs)
    state = net.run(state, ENGINE_TICKS)
    state, outs = net.drain(state)
    return outs


def run_cluster(node_info, programs, inputs, expect_n, timeout=30.0):
    """The free-running path: real gRPC nodes on loopback, fed as a stream."""
    master, close = build_loopback_cluster(node_info, programs)
    try:
        master.run()
        # stream all inputs into the master's IN queue (the GetInput side of
        # master.go:233-242) and wait for the output stream
        with master._io_cond:
            master._in_q.extend(int(v) for v in inputs)
            master._io_cond.notify_all()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with master._io_cond:
                if len(master._out_q) >= expect_n:
                    return list(master._out_q)[:expect_n]
            time.sleep(0.01)
        with master._io_cond:
            got = list(master._out_q)
        raise AssertionError(
            f"cluster produced {len(got)}/{expect_n} outputs in {timeout}s: {got}"
        )
    finally:
        close()


@pytest.mark.parametrize("seed", range(40))
def test_engine_matches_cluster(seed):
    node_info, programs, outs_per_input = gen_network(seed)
    inputs = np.random.default_rng(1000 + seed).integers(
        -100, 100, size=N_INPUTS
    ).tolist()

    engine_outs = run_engine(node_info, programs, inputs)
    # the generator guarantees liveness: every input must produce its
    # full output cadence (1, or 2 with an OUT_VAL tail)
    assert len(engine_outs) == N_INPUTS * outs_per_input, (
        f"seed {seed}: engine emitted {len(engine_outs)}/"
        f"{N_INPUTS * outs_per_input} — generator liveness broken\n"
        + "\n---\n".join(programs.values())
    )

    cluster_outs = run_cluster(node_info, programs, inputs, len(engine_outs))
    assert cluster_outs == engine_outs, (
        f"seed {seed}: cross-mode divergence\nengine:  {engine_outs}\n"
        f"cluster: {cluster_outs}\nprograms:\n" + "\n---\n".join(programs.values())
    )


def gen_contended(seed):
    """A deliberately CONTENDED network: multiple lanes race for one stack,
    one destination port, and the OUT grant.  Output ORDER is
    schedule-dependent, but every worker applies the same transform, so the
    output MULTISET is not: arbitration differences may reorder values but
    must never lose or duplicate one.
    """
    rng = np.random.default_rng(seed)
    k = int(rng.integers(-20, 20))
    n_workers = int(rng.integers(2, 4))
    via_port = bool(rng.integers(2))  # workers -> shared port -> tail OUT
    node_info = {"head": "program", "st": "stack"}
    programs = {"head": "IN ACC\nPUSH ACC, st\n"}
    for w in range(n_workers):
        name = f"w{w}"
        node_info[name] = "program"
        sink = "MOV ACC, tail:R0" if via_port else "OUT ACC"
        programs[name] = f"POP st, ACC\nADD {k}\n{sink}\n"
    if via_port:
        node_info["tail"] = "program"
        programs["tail"] = "MOV R0, ACC\nOUT ACC\n"
    return node_info, programs, k


def gen_contended_in(seed):
    """Multiple lanes execute IN against ONE input stream — the remaining
    arbitration surface (master.go:233-242's GetInput races) — with MIXED
    sinks inside one network: direct OUT, a shared port into a tail lane,
    and a shared stack drained by a dedicated popper.  After sinking its
    value every consumer OUTs a lane TAG (1000+w), so each mode's per-lane
    consumption counts are observable in its own output stream: exactly one
    tag per consumed input, tags only from real consumer lanes.
    """
    rng = np.random.default_rng(seed)
    k = int(rng.integers(-20, 20))
    n_workers = int(rng.integers(2, 5))
    node_info, programs = {}, {}
    uses_port = uses_stack = False
    for w in range(n_workers):
        sink = rng.choice(["out", "port", "stack"])
        lines = ["IN ACC", f"ADD {k}"]
        if sink == "out":
            lines.append("OUT ACC")
        elif sink == "port":
            lines.append("MOV ACC, tail:R0")
            uses_port = True
        else:
            lines.append("PUSH ACC, st")
            uses_stack = True
        lines.append(f"OUT {1000 + w}")  # the lane tag
        node_info[f"w{w}"] = "program"
        programs[f"w{w}"] = "\n".join(lines)
    if uses_port:
        node_info["tail"] = "program"
        programs["tail"] = "MOV R0, ACC\nOUT ACC\n"
    if uses_stack:
        node_info["st"] = "stack"
        node_info["drain"] = "program"
        programs["drain"] = "POP st, ACC\nOUT ACC\n"
    return node_info, programs, k, n_workers


@pytest.mark.parametrize("seed", range(40))
def test_contended_multi_in_conservation(seed):
    """2-4 lanes race IN for one input stream (mixed stack/port/OUT sinks):
    in BOTH modes every input must be consumed exactly once (value multiset
    conserved) and must emit exactly one consumer-lane tag (per-lane-count
    conservation) — which lane wins may differ between the engine's
    lowest-lane rule and the cluster's free-running race, but values can
    never be lost, duplicated, or consumed by a phantom lane."""
    node_info, programs, k, n_workers = gen_contended_in(seed)
    inputs = np.random.default_rng(3000 + seed).integers(
        -100, 100, size=N_INPUTS
    ).tolist()
    expect_vals = sorted(v + k for v in inputs)  # all < 1000, tags >= 1000
    valid_tags = set(range(1000, 1000 + n_workers))

    def check(outs, mode):
        vals = sorted(o for o in outs if o < 1000)
        tags = [o for o in outs if o >= 1000]
        assert vals == expect_vals, (
            f"seed {seed} [{mode}]: value multiset wrong\n{outs}\nprograms:\n"
            + "\n---\n".join(programs.values())
        )
        assert len(tags) == N_INPUTS and set(tags) <= valid_tags, (
            f"seed {seed} [{mode}]: per-lane consumption tags wrong "
            f"({tags})\nprograms:\n" + "\n---\n".join(programs.values())
        )

    engine_outs = run_engine(node_info, programs, inputs)
    assert len(engine_outs) == 2 * N_INPUTS, (
        f"seed {seed}: engine emitted {len(engine_outs)}/{2 * N_INPUTS}\n"
        + "\n---\n".join(programs.values())
    )
    check(engine_outs, "engine")
    cluster_outs = run_cluster(node_info, programs, inputs, 2 * N_INPUTS)
    check(cluster_outs, "cluster")


@pytest.mark.parametrize("seed", range(40))
def test_contended_multiset_equal(seed):
    """Two+ lanes share a stack (and possibly a port and the OUT grant):
    the engine's lowest-lane arbitration and the cluster's free-running
    races must produce the SAME MULTISET of outputs — schedule-independent
    conservation, the property quirk-free arbitration must preserve."""
    node_info, programs, k = gen_contended(seed)
    inputs = np.random.default_rng(2000 + seed).integers(
        -100, 100, size=N_INPUTS
    ).tolist()
    expect = sorted(v + k for v in inputs)

    engine_outs = run_engine(node_info, programs, inputs)
    assert sorted(engine_outs) == expect, (
        f"seed {seed}: engine multiset wrong\n{engine_outs}\nprograms:\n"
        + "\n---\n".join(programs.values())
    )
    cluster_outs = run_cluster(node_info, programs, inputs, len(engine_outs))
    assert sorted(cluster_outs) == expect, (
        f"seed {seed}: cluster multiset wrong\n{cluster_outs}\nprograms:\n"
        + "\n---\n".join(programs.values())
    )
