"""Ring-counter rebasing: long-soak masters must never wrap int32 counters.

A master at ~1e5-1e6 values/sec crosses 2^31 ring-counter increments within
hours; a wrapped-negative counter breaks `% capacity` indexing.  Every chunk
runner rebases counters past 2^30 by a multiple of the ring capacity
(core/state.rebase_rings) — these tests start engines just past the
threshold and prove computation is unaffected and counters come back small.
"""

import jax.numpy as jnp
import numpy as np

from misaka_tpu import networks
from misaka_tpu.core import cinterp
from misaka_tpu.core.state import REBASE_THRESHOLD, rebase_rings

BIG = REBASE_THRESHOLD + 7


def near_wrap_state(net):
    """An add2 state whose ring counters sit just past the rebase threshold.

    Counters are advanced by an exact multiple of each ring's capacity, so
    slot indices are identical to a fresh state's.
    """
    state = net.init_state()
    in_base = (BIG // net.in_cap + 1) * net.in_cap
    out_base = (BIG // net.out_cap + 1) * net.out_cap
    return state._replace(
        in_rd=state.in_rd + np.int32(in_base),
        in_wr=state.in_wr + np.int32(in_base),
        out_rd=state.out_rd + np.int32(out_base),
        out_wr=state.out_wr + np.int32(out_base),
    )


def test_rebase_rings_preserves_depth_and_slots():
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    state = near_wrap_state(net)
    state = state._replace(in_wr=state.in_wr + 3)  # depth 3
    rebased = rebase_rings(state)
    assert int(rebased.in_rd) < REBASE_THRESHOLD
    assert int(rebased.in_wr - rebased.in_rd) == 3
    assert int(rebased.in_rd) % net.in_cap == int(state.in_rd) % net.in_cap


def test_rebase_noop_below_threshold():
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    state = net.init_state()
    rebased = rebase_rings(state)
    assert int(rebased.in_rd) == 0 and int(rebased.out_wr) == 0


def test_engine_computes_through_threshold():
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    state = near_wrap_state(net)
    state, outs = net.compute_stream(state, [5, 6, 7])
    assert outs == [7, 8, 9]
    assert int(state.in_rd) < REBASE_THRESHOLD
    assert int(state.out_wr) < REBASE_THRESHOLD


def test_batched_engine_rebases():
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile(batch=4)
    state = net.init_state()
    in_base = (BIG // net.in_cap + 1) * net.in_cap
    vals = np.tile(np.arange(4, dtype=np.int32)[:, None], (1, 4))
    in_buf = np.zeros((4, 8), np.int32)
    in_buf[:, :4] = vals
    state = state._replace(
        in_buf=jnp.asarray(in_buf),
        in_rd=state.in_rd + np.int32(in_base),
        in_wr=state.in_wr + np.int32(in_base + 4),
    )
    state = net.run(state, 64)
    assert (np.asarray(state.out_wr) == 4).all()
    np.testing.assert_array_equal(np.asarray(state.out_buf)[:, :4], vals + 2)
    assert (np.asarray(state.in_rd) < REBASE_THRESHOLD).all()


def test_native_interp_rebases():
    if not cinterp.available():
        import pytest

        pytest.skip("native interpreter unavailable")
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    with cinterp.NativeInterpreter(net.code, net.prog_len, 1, 8, 8, 8) as n:
        # Seed the counters just past the threshold (multiple of cap keeps
        # slot indices aligned with the empty buffers), then compute through.
        big = (BIG // 8 + 1) * 8
        n.seed_counters(big, big, big, big)
        n.feed([1, 2])
        n.run(100)
        assert n.drain() == [3, 4]
        st = n.state_arrays()
        assert 0 < int(st["in_rd"]) < REBASE_THRESHOLD
        assert int(st["out_wr"]) < REBASE_THRESHOLD
        # depth/slot invariants held across the rebase
        assert int(st["in_rd"]) % 8 == big % 8 + 2


def test_fused_kernel_rebases():
    """The Pallas path (interpret mode on CPU) rebases like the XLA path."""
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile(batch=128)
    state = net.init_state()
    in_base = (BIG // net.in_cap + 1) * net.in_cap
    vals = np.tile(np.arange(128, dtype=np.int32)[:, None], (1, 2))
    in_buf = np.zeros((128, 8), np.int32)
    in_buf[:, :2] = vals
    state = state._replace(
        in_buf=jnp.asarray(in_buf),
        in_rd=state.in_rd + np.int32(in_base),
        in_wr=state.in_wr + np.int32(in_base + 2),
    )
    runner = net.fused_runner(48, interpret=True)
    state = runner(state)
    assert (np.asarray(state.out_wr) - np.asarray(state.out_rd) == 2).all()
    np.testing.assert_array_equal(np.asarray(state.out_buf)[:, :2], vals + 2)
    assert (np.asarray(state.in_rd) < REBASE_THRESHOLD).all()
