"""The r12 SLO burn-rate engine (utils/slo.py).

Spec grammar, ring-of-buckets window behavior (rotation, stale-slot
reclaim, concurrent writers), multi-window burn-rate state transitions,
the per-program override surface, the /debug/alerts + /healthz wiring —
and the acceptance chaos scenario: an injected serve-path latency fault
against ONE tenant flips only that program's state to page, /healthz
reports degraded, and recovery clears it.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.runtime.registry import ProgramRegistry
from misaka_tpu.utils import faults
from misaka_tpu.utils import slo

CAPS = dict(in_cap=32, out_cap=32, stack_cap=16)


@pytest.fixture(autouse=True)
def _restore_slo():
    yield
    faults.configure(None)
    slo.configure()  # back to the (disarmed) env defaults


def _arm(monkeypatch, spec="p99<50ms,err<5%", windows="0.5,1,2,4",
         min_events=5):
    monkeypatch.setenv("MISAKA_SLO", spec)
    monkeypatch.setenv("MISAKA_SLO_WINDOWS", windows)
    monkeypatch.setenv("MISAKA_SLO_MIN_EVENTS", str(min_events))
    slo.configure()


# --- spec parsing -----------------------------------------------------------


def test_parse_spec():
    objs = slo.parse_spec("p99<25ms,err<0.1%")
    assert [o.kind for o in objs] == ["latency", "error"]
    assert objs[0].threshold_s == pytest.approx(0.025)
    assert objs[0].budget == pytest.approx(0.01)
    assert objs[1].budget == pytest.approx(0.001)
    assert slo.parse_spec("p50<2s")[0].threshold_s == 2.0
    assert slo.parse_spec("p95<100us")[0].threshold_s == pytest.approx(1e-4)
    assert slo.parse_spec("") == []


@pytest.mark.parametrize("bad", [
    "p99<25", "p0<1ms", "p100<1ms", "err<0%", "err<200%", "latency<5ms",
    "p99>25ms",
])
def test_parse_spec_rejects(bad):
    with pytest.raises(slo.SLOSpecError):
        slo.parse_spec(bad)


# --- window rings -----------------------------------------------------------


def test_ring_rotation_and_stale_reclaim():
    ring = slo._Ring(width=1.0, length=4)
    ring.observe(100.0, 0.01, False)
    ring.observe(100.5, 0.01, True)
    reqs, errs, lat = ring.window_sum(100.9, 1.0)
    assert (reqs, errs) == (2, 1)
    # one bucket later the old bucket still covers a 2s window
    ring.observe(101.2, 0.02, False)
    reqs, errs, _ = ring.window_sum(101.3, 2.0)
    assert (reqs, errs) == (3, 1)
    # far in the future every slot is stale: nothing leaks into a fresh
    # window even though the ring positions collide modulo length
    reqs, errs, lat = ring.window_sum(100 + 4000.0, 4.0)
    assert (reqs, errs) == (0, 0) and sum(lat) == 0


def test_window_rotation_under_concurrent_writers(monkeypatch):
    _arm(monkeypatch, windows="0.2,0.4,0.8,1.6")
    errors = []

    def writer(i):
        try:
            t_end = time.monotonic() + 0.6
            while time.monotonic() < t_end:
                slo.observe("concurrent", 0.001 * (i + 1), error=(i == 0))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ts = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    payload = slo.evaluate("concurrent")
    wins = payload["windows"]
    counts = [w["requests"] for w in wins.values()]
    # longer windows contain at least the shorter ones' events
    assert counts == sorted(counts)
    assert counts[-1] > 0
    assert 0.0 < list(wins.values())[-1]["error_ratio"] < 1.0


# --- burn-rate states -------------------------------------------------------


def _flood(program, dur_s, n=60, error=False):
    for _ in range(n):
        slo.observe(program, dur_s, error=error)


def test_latency_burn_pages_and_min_events_guard(monkeypatch):
    _arm(monkeypatch)
    _flood("hot", 0.5)  # every request blows the 50ms objective
    assert slo.evaluate("hot")["state"] == "page"
    # below the sample floor burn reads 0 — one unlucky request can't page
    _flood("tiny", 0.5, n=2)
    assert slo.evaluate("tiny")["state"] == "ok"


def test_error_burn_pages(monkeypatch):
    _arm(monkeypatch)
    _flood("err-prog", 0.001, error=True)
    assert slo.evaluate("err-prog")["state"] == "page"
    _flood("fine-prog", 0.001, error=False)
    assert slo.evaluate("fine-prog")["state"] == "ok"


def test_per_program_override(monkeypatch):
    _arm(monkeypatch, spec="p99<10s")  # env default: impossible to violate
    slo.set_objectives("strict", "p99<1ms")
    _flood("strict", 0.1)
    _flood("lax", 0.1)
    assert slo.evaluate("strict")["state"] == "page"
    assert slo.evaluate("lax")["state"] == "ok"
    assert slo.overall_state() == "page"
    slo.set_objectives("strict", None)  # cleared: back to the env default
    assert slo.evaluate("strict")["state"] == "ok"


def test_replaced_objective_prunes_stale_burn_series(monkeypatch):
    """A replaced override DROPS the old objective's burn-rate series:
    a frozen misaka_slo_burn_rate child would hold a Prometheus alert
    open forever after /debug/alerts recovered."""
    _arm(monkeypatch, spec="p99<10s")
    slo.set_objectives("swapper", "p99<1ms")
    _flood("swapper", 0.1)
    assert slo.evaluate("swapper")["state"] == "page"

    def burn_objectives():
        return {
            dict(zip(slo.M_SLO_BURN.labelnames, key))["objective"]
            for key, _ in slo.M_SLO_BURN._items()
            if key and key[0] == "swapper"
        }

    assert "p99<1ms" in burn_objectives()
    slo.set_objectives("swapper", "p99<10s")  # the relaxed replacement
    slo._eval_cache.clear()  # bypass the 0.25s evaluation TTL
    assert slo.evaluate("swapper")["state"] == "ok"
    objs = burn_objectives()
    assert "p99<1ms" not in objs
    assert "p99<10s" in objs


def test_override_budget_bounds_gauge_cardinality(monkeypatch):
    """Past the shared cap a NEW override raises (the registry logs and
    serves the program under env defaults) — overrides name programs
    verbatim in misaka_slo_* labels, so an upload flood must not mint
    unbounded series.  Replacing an installed override always works."""
    _arm(monkeypatch, spec="")
    monkeypatch.setenv("MISAKA_USAGE_LABEL_MAX", "3")
    for i in range(3):
        slo.set_objectives(f"ovr-{i}", "p99<50ms")
    with pytest.raises(slo.SLOSpecError):
        slo.set_objectives("ovr-overflow", "p99<50ms")
    slo.set_objectives("ovr-1", "p95<10ms")  # replacement: allowed
    assert slo.objectives_for("ovr-1")[0].name == "p95<10ms"
    slo.set_objectives("ovr-0", None)  # clearing frees a slot
    slo.set_objectives("ovr-new", "p99<50ms")


def test_malformed_env_spec_is_loud(monkeypatch):
    """A typo'd MISAKA_SLO disarms (never crashes) but must not hide:
    /debug/alerts carries spec_error so 'pages that never fire' is
    visible at a glance."""
    monkeypatch.setenv("MISAKA_SLO", "p99<25")  # missing unit
    slo.configure()
    assert not slo.armed()
    payload = slo.debug_payload()
    assert "spec_error" in payload and "p99<25" in payload["spec_error"]
    monkeypatch.setenv("MISAKA_SLO", "p99<25ms")
    slo.configure()
    assert slo.armed()
    assert "spec_error" not in slo.debug_payload()


def test_window_cardinality_guard_collapses(monkeypatch):
    """Past MISAKA_USAGE_LABEL_MAX distinct programs, new windows fold
    into "other" — inline, because recursing for "other" under the
    non-reentrant module lock self-deadlocked (the r12 hang)."""
    _arm(monkeypatch)
    monkeypatch.setenv("MISAKA_USAGE_LABEL_MAX", "3")
    for i in range(8):
        slo.observe(f"cap-flood-{i}", 0.001)
    assert "other" in slo._windows
    assert len(slo._windows) <= 4  # 3 named + "other"
    assert slo.evaluate("other")["windows"]


def test_override_program_exempt_from_window_collapse(monkeypatch):
    """A program with an EXPLICIT objective override keeps its own
    windows past the cardinality cap — collapsed into "other", its
    declared objectives would evaluate 0 requests forever (a page that
    can never fire, the exact failure spec_error exists to prevent)."""
    _arm(monkeypatch)
    monkeypatch.setenv("MISAKA_USAGE_LABEL_MAX", "3")
    for i in range(5):
        slo.observe(f"cap-flood-{i}", 0.001)
    assert "other" in slo._windows
    slo.set_objectives("vip", "p99<1ms,err<1%")
    # burn hard against the override: every request violates p99<1ms
    for _ in range(50):
        slo.observe("vip", 0.5)
    assert "vip" in slo._windows  # own windows, not folded into "other"
    assert slo.evaluate("vip")["state"] == "page"


def test_disarmed_is_free(monkeypatch):
    monkeypatch.delenv("MISAKA_SLO", raising=False)
    slo.configure()
    assert not slo.armed()
    slo.observe("ghost", 99.0, error=True)  # no-op
    assert slo.overall_state() is None
    assert slo.debug_payload()["programs"] == {}


# --- registry override via upload metadata ----------------------------------


def test_registry_slo_upload(monkeypatch):
    _arm(monkeypatch, spec="")  # no env default: override only
    reg = ProgramRegistry(None, batch=None, engine="scan", caps=CAPS)
    try:
        topo = networks.acc_loop(**CAPS)
        out = reg.publish("slo-ten", topology_json=json.dumps(
            {"nodes": topo.node_info, "programs": topo.programs, **CAPS}
        ), slo_spec="p99<1ms")
        assert out["version"]
        assert slo.armed()
        assert [o.name for o in slo.objectives_for("slo-ten")] == ["p99<1ms"]
        # a bad spec is a 400-shaped error that touches nothing
        from misaka_tpu.runtime.registry import RegistryError

        with pytest.raises(RegistryError):
            reg.publish("slo-ten2", topology_json=json.dumps(
                {"nodes": topo.node_info, "programs": topo.programs, **CAPS}
            ), slo_spec="p99>nope")
    finally:
        reg.close()


# --- the chaos scenario (acceptance) ----------------------------------------


def _native_or_skip():
    from misaka_tpu.core import native_serve

    if not native_serve.available():
        pytest.skip("no C++ toolchain for the native engine")


def test_tenant_latency_fault_pages_only_that_tenant(tmp_path):
    """Injected serve-path latency against ONE tenant flips only that
    program's /debug/alerts state to page within a short window, /healthz
    reports degraded, and recovery clears it.

    Runs against an ISOLATED SUBPROCESS server (ISSUE 11 deflake): the
    in-process version shared its box with the whole grown suite's
    accumulated threads, and under full-suite saturation the un-faulted
    neighbor's real p99 crept over any sane objective (see the PR 10
    history of margin rescales).  A dedicated process keeps the
    neighbor's latency honest without weakening any pin — and the fault
    now rides the production POST /debug/faults route, the same
    mechanism the fleet drill uses across process boundaries."""
    import os
    import subprocess
    import sys
    import urllib.error
    import urllib.parse
    import urllib.request

    _native_or_skip()
    from misaka_tpu.runtime import frontends

    port = frontends.pick_free_port()
    base = f"http://127.0.0.1:{port}"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "MISAKA_PORT": str(port),
        "MISAKA_BATCH": "8",
        "MISAKA_ENGINE": "native",
        "MISAKA_AUTORUN": "1",
        "MISAKA_IN_CAP": "32",
        "MISAKA_OUT_CAP": "32",
        "MISAKA_STACK_CAP": "16",
        "MISAKA_PROGRAMS_DIR": str(tmp_path / "programs"),
        "MISAKA_DEFAULT_PROGRAM": "ten-a",
        # a 250ms objective against a 400ms injected fault; short
        # windows so page -> recovery fits the test lane
        "MISAKA_SLO": "p99<250ms",
        "MISAKA_SLO_WINDOWS": "3,6,12,24",
        "MISAKA_SLO_MIN_EVENTS": "3",
        "MISAKA_TTL_S": "600",
        "NODE_INFO": json.dumps({"main": {"type": "program"}}),
        "MISAKA_PROGRAMS": json.dumps(
            {"main": "IN ACC\nADD 2\nOUT ACC\n"}
        ),
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "misaka_tpu.runtime.app"], env=env
    )
    stop = threading.Event()
    errors = []

    def post_form(path, **fields):
        body = urllib.parse.urlencode(fields).encode()
        req = urllib.request.Request(base + path, data=body, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def get_json(path):
        with urllib.request.urlopen(base + path, timeout=15) as r:
            return json.loads(r.read())

    def client(name, delta):
        vals = np.arange(8, dtype=np.int32)
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            while not stop.is_set():
                conn.request(
                    "POST", f"/programs/{name}/compute_raw?spread=1",
                    vals.tobytes(),
                )
                raw = conn.getresponse().read()
                assert (np.frombuffer(raw, "<i4") == vals + delta).all()
                time.sleep(0.005)
            conn.close()
        except Exception as e:  # pragma: no cover
            errors.append(e)
            stop.set()

    def states():
        progs = get_json("/debug/alerts")["programs"]
        return (
            progs.get("ten-a", {}).get("state"),
            progs.get("ten-b", {}).get("state"),
        )

    ts = []
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            try:
                if get_json("/healthz").get("ok"):
                    break
            except OSError:
                pass
            time.sleep(0.25)
        else:
            raise AssertionError("subprocess server never came up")
        st, body = post_form(
            "/programs", name="ten-b", program="IN ACC\nADD 3\nOUT ACC\n"
        )
        assert st == 200, body
        ts = [
            threading.Thread(target=client, args=("ten-a", 2)),
            threading.Thread(target=client, args=("ten-b", 3)),
        ]
        for t in ts:
            t.start()
        # warm both tenants healthy first (activates ten-b's engine)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not stop.is_set():
            if states() == ("ok", "ok"):
                break
            time.sleep(0.1)
        assert states() == ("ok", "ok"), states()
        # inject 400ms into ONLY ten-b's serve passes — over the
        # production fault route, not an in-process configure
        st, body = post_form("/debug/faults", spec="serve_delay:ten-b=0.4")
        assert st == 200, body
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline and not stop.is_set():
            a, b = states()
            if b == "page":
                break
            time.sleep(0.1)
        a, b = states()
        assert b == "page", (a, b)
        assert a == "ok", (a, b)  # the neighbor stays green
        health = get_json("/healthz")
        assert health["slo"] == "page" and health["degraded"] is True
        # the page carries exemplar trace IDs linking to the flight
        # recorder (ISSUE 11: alert -> /debug/requests/<id> in one curl)
        alert_b = get_json("/debug/alerts")["programs"]["ten-b"]
        assert alert_b.get("exemplars"), alert_b
        # recovery: disarm over the same route, keep healthy traffic
        # flowing, page clears (the 12s window must age the fault's bad
        # events out; the deadline is a poll, not a cost on green runs)
        st, body = post_form("/debug/faults", spec="")
        assert st == 200, body
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not stop.is_set():
            if states()[1] == "ok":
                break
            time.sleep(0.2)
        assert states()[1] == "ok", states()
        health = get_json("/healthz")
        assert health["degraded"] is False
        assert not errors, errors[0]
    finally:
        stop.set()
        for t in ts:
            t.join(timeout=10)
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


# --- edge observations through the compute plane ----------------------------


def test_plane_edge_feeds_windows(monkeypatch, tmp_path):
    """Requests served over the unix-socket compute plane land in the SLO
    windows with the frontend-edge clock (frame metadata `edge`)."""
    _arm(monkeypatch, windows="0.5,1,2,4")
    from misaka_tpu.runtime import frontends

    m = MasterNode(networks.add2(**CAPS), chunk_steps=32, batch=4)
    plane_path = str(tmp_path / "plane.sock")
    plane = frontends.start_compute_plane(m, plane_path)
    client = frontends.PlaneClient(plane_path, conns=1)
    m.run()
    try:
        vals = np.arange(16, dtype=np.int32)
        for _ in range(8):
            out = client.compute_raw(vals.astype("<i4").tobytes())
            assert (np.frombuffer(out, "<i4") == vals + 2).all()
        # The engine-side record lands AFTER the response bytes go out —
        # since r17 on the plane's pipeline executor thread, which a
        # contended box (this 1-core container with suite-order
        # neighbors) can deschedule for hundreds of ms.  POLL the longest
        # window instead of sleeping a fixed beat: the pin is that
        # plane-edge observations REACH the windows, not the recording
        # thread's scheduling latency or the 0.5s window's knife-edge.
        deadline = time.monotonic() + 3
        payload = slo.evaluate("default")
        while (payload["windows"]["4s"]["requests"] < 8
               and time.monotonic() < deadline):
            time.sleep(0.1)
            payload = slo.evaluate("default")
        assert payload["windows"]["4s"]["requests"] >= 8
        assert payload["windows"]["4s"]["p99_ms"] > 0
    finally:
        client.close()
        m.pause()
        plane.close()


def test_alerts_route_and_gauges(monkeypatch):
    _arm(monkeypatch)
    _flood("gauge-prog", 0.001)
    m = MasterNode(networks.add2(**CAPS), chunk_steps=32, batch=None,
                   engine="scan")
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", httpd.server_address[1], timeout=15
        )
        conn.request("GET", "/debug/alerts")
        body = json.loads(conn.getresponse().read())
        assert body["enabled"] is True
        assert body["programs"]["gauge-prog"]["state"] == "ok"
        assert body["burn_rules"][0]["state"] == "page"
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        from misaka_tpu.utils import metrics as umetrics

        parsed = umetrics.parse_text(text)
        assert any(
            k.startswith("misaka_slo_state") and 'program="gauge-prog"' in k
            for k in parsed
        )
        assert any(k.startswith("misaka_slo_burn_rate") for k in parsed)
    finally:
        m.pause()
        httpd.shutdown()
