"""Master HTTP runtime tests: route parity with the reference's control surface.

Drives a real ThreadingHTTPServer over a loopback socket with the same
form-POST flow the reference README documents (README.md:50-80) against the
add-2 compose network.
"""

import json
import os
import threading
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.runtime.topology import Topology, TopologyError

from misaka_tpu.networks import ADD2_PROGRAMS, add2


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    topology = add2()
    master = MasterNode(topology, chunk_steps=32)
    ckpt_dir = str(tmp_path_factory.mktemp("ckpts"))
    httpd = make_http_server(master, port=0, checkpoint_dir=ckpt_dir)  # ephemeral port
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", master
    master.pause()
    httpd.shutdown()


def post(base, path, data=None):
    body = urllib.parse.urlencode(data or {}).encode()
    req = urllib.request.Request(base + path, data=body, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_compute_before_run_rejected(server):
    base, _ = server
    status, body = post(base, "/compute", {"value": "1"})
    assert status == 400
    assert body == "network is not running"


def test_run_then_compute_parity(server):
    base, _ = server
    status, body = post(base, "/run")
    assert (status, body) == (200, "Success")
    for v in [0, 41, -7]:
        status, body = post(base, "/compute", {"value": str(v)})
        assert status == 200
        assert json.loads(body) == {"value": v + 2}


def test_compute_batch_route(server):
    base, _ = server
    post(base, "/run")
    status, body = post(base, "/compute_batch", {"values": "1, 2 3,4"})
    assert status == 200
    assert json.loads(body) == {"values": [3, 4, 5, 6]}
    # empty stream is a valid no-op
    status, body = post(base, "/compute_batch", {"values": ""})
    assert (status, json.loads(body)) == (200, {"values": []})


def test_compute_raw_route(server):
    import numpy as np

    base, _ = server
    post(base, "/run")
    vals = np.arange(-5, 20, dtype="<i4")
    req = urllib.request.Request(
        base + "/compute_raw", data=vals.tobytes(), method="POST"
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        out = np.frombuffer(resp.read(), dtype="<i4")
    assert (out == vals + 2).all()
    # truncated body rejected
    req = urllib.request.Request(
        base + "/compute_raw", data=b"\x01\x02\x03", method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            status = resp.status
    except urllib.error.HTTPError as e:
        status = e.code
    assert status == 400


def test_compute_batch_bad_values(server):
    base, _ = server
    post(base, "/run")
    status, body = post(base, "/compute_batch", {"values": "1 two 3"})
    assert (status, body) == (400, "cannot parse values")


def test_get_method_not_allowed(server):
    base, _ = server
    status, body = get(base, "/run")
    assert status == 405
    assert body == "method GET not allowed"


def test_compute_bad_value(server):
    base, _ = server
    post(base, "/run")
    status, body = post(base, "/compute", {"value": "twelve"})
    assert (status, body) == (400, "cannot parse value")


def test_pause_blocks_compute_and_resume_continues(server):
    base, _ = server
    post(base, "/run")
    status, body = post(base, "/pause")
    assert (status, body) == (200, "Success")
    status, body = post(base, "/compute", {"value": "1"})
    assert (status, body) == (400, "network is not running")
    post(base, "/run")
    status, body = post(base, "/compute", {"value": "5"})
    assert json.loads(body) == {"value": 7}


def test_load_unknown_node_leaves_network_running(server):
    # Target validation precedes the reset (master.go:158-163): a bad target
    # must not stop a running network.
    base, _ = server
    post(base, "/run")
    status, body = post(base, "/load", {"program": "NOP", "targetURI": "ghost"})
    assert status == 400
    assert "node ghost not valid on this network" in body
    status, body = post(base, "/compute", {"value": "1"})
    assert json.loads(body) == {"value": 3}


def test_load_bad_program_leaves_network_running_untouched(server):
    # COMPILE-FIRST (r10, the registry discipline): a parse failure is
    # discovered BEFORE anything stops — the running network keeps
    # serving its old programs and its in-flight state.  (The reference
    # discovers the error after resetting, program.go:185-191, leaving
    # the network stopped; the pre-r10 port of that ordering wiped live
    # state on every typo'd /load.)
    base, _ = server
    post(base, "/run")
    status, body = post(base, "/compute", {"value": "7"})
    assert json.loads(body) == {"value": 9}
    status, body = post(base, "/load", {"program": "FROB", "targetURI": "misaka1"})
    assert status == 400
    # still RUNNING, old program intact, no /run needed
    status, body = post(base, "/compute", {"value": "1"})
    assert json.loads(body) == {"value": 3}


def test_load_parse_error(server):
    base, _ = server
    status, body = post(base, "/load", {"program": "FROB 1", "targetURI": "misaka1"})
    assert status == 400
    assert "error loading program on node misaka1" in body
    assert "not a valid instruction" in body


def test_load_stack_node_rejected(server):
    base, _ = server
    status, body = post(base, "/load", {"program": "NOP", "targetURI": "misaka3"})
    assert status == 400
    assert "not a program node" in body


def test_load_reprograms_network(server):
    base, master = server
    # Turn misaka1 into an add-10 passthrough that skips misaka2 entirely.
    status, body = post(
        base, "/load", {"program": "IN ACC\nADD 10\nOUT ACC", "targetURI": "misaka1"}
    )
    assert (status, body) == (200, "Success")
    # /load resets and stops the network (master.go:166-175)
    status, body = post(base, "/compute", {"value": "1"})
    assert (status, body) == (400, "network is not running")
    post(base, "/run")
    status, body = post(base, "/compute", {"value": "3"})
    assert json.loads(body) == {"value": 13}
    # restore the original program for other tests
    post(base, "/load", {"program": ADD2_PROGRAMS["misaka1"], "targetURI": "misaka1"})
    post(base, "/run")
    status, body = post(base, "/compute", {"value": "3"})
    assert json.loads(body) == {"value": 5}


def test_reset_zeroes_state(server):
    base, master = server
    post(base, "/run")
    post(base, "/compute", {"value": "9"})
    status, body = post(base, "/reset")
    assert (status, body) == (200, "Success")
    assert not master.is_running
    state = master.snapshot()
    import numpy as np

    assert int(np.asarray(state.tick)) == 0
    assert not bool(np.asarray(state.port_full).any())


def test_snapshot_restore_roundtrip(server):
    base, master = server
    post(base, "/run")
    post(base, "/compute", {"value": "1"})
    post(base, "/pause")
    snap = master.snapshot()
    post(base, "/run")
    post(base, "/compute", {"value": "2"})
    post(base, "/pause")
    master.restore(snap)
    post(base, "/run")
    status, body = post(base, "/compute", {"value": "10"})
    assert json.loads(body) == {"value": 12}


def test_compute_timeout_keeps_pairing():
    # A timed-out /compute's eventual output must be discarded, not handed to
    # the next caller (the correlation guarantee that fixes quirk #2).
    from misaka_tpu.runtime.master import ComputeTimeout

    top = Topology(node_info={"n": "program"}, programs={"n": "IN ACC\nOUT ACC"})
    master = MasterNode(top, chunk_steps=16)
    master.run()
    master.pause()  # network stalled: inputs accepted, nothing computes
    with pytest.raises(ComputeTimeout):
        master.compute(1, timeout=0.3)
    master.run()   # the orphaned value 1 now computes; its output is stale
    assert master.compute(5, timeout=30) == 5  # not 1
    master.pause()


def test_status_endpoint(server):
    base, _ = server
    post(base, "/run")
    post(base, "/compute", {"value": "1"})
    status, body = get(base, "/status")
    assert status == 200
    s = json.loads(body)
    assert s["running"] is True
    assert s["tick"] > 0
    assert s["nodes"] == {
        "misaka1": "program",
        "misaka2": "program",
        "misaka3": "stack",
    }
    assert s["retired_per_lane"]["misaka1"] > 0
    assert "misaka3" in s["stack_depth"]


def test_checkpoint_restore_over_http(server):
    base, _ = server
    post(base, "/run")
    post(base, "/compute", {"value": "4"})
    status, body = post(base, "/checkpoint", {"name": "net"})
    assert (status, body) == (200, "Success")
    # mutate: load a different program, compute differently
    post(base, "/load", {"program": "IN ACC\nADD 100\nOUT ACC", "targetURI": "misaka1"})
    post(base, "/run")
    status, body = post(base, "/compute", {"value": "1"})
    assert json.loads(body) == {"value": 101}
    # restore: original programs and state come back
    status, body = post(base, "/restore", {"name": "net"})
    assert (status, body) == (200, "Success")
    post(base, "/run")
    status, body = post(base, "/compute", {"value": "1"})
    assert json.loads(body) == {"value": 3}


def test_restore_missing_checkpoint(server):
    base, _ = server
    status, body = post(base, "/restore", {"name": "nope"})
    assert status == 400
    assert "error restoring checkpoint" in body


def test_checkpoint_name_traversal_rejected(server):
    base, _ = server
    for bad in ["../../etc/pwned", "/etc/pwned", "a/b", ""]:
        status, body = post(base, "/checkpoint", {"name": bad})
        assert (status, body) == (400, "invalid checkpoint name"), bad


def test_checkpoint_disabled_without_dir():
    import threading

    master = MasterNode(add2(), chunk_steps=16)
    httpd = make_http_server(master, port=0)  # no checkpoint_dir
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        status, body = post(base, "/checkpoint", {"name": "x"})
        assert status == 403
        assert "disabled" in body
    finally:
        httpd.shutdown()


def test_checkpoint_pre_regs64_compat(tmp_path):
    # checkpoints written before the 64-bit register planes existed lack
    # acc_hi/bak_hi; those states were int32-exact, so loading must
    # reconstruct the hi planes by sign extension — not KeyError
    top = Topology(
        node_info={"n": "program"},
        programs={"n": "IN ACC\nADD 1\nOUT ACC"},
        in_cap=16, out_cap=16, stack_cap=4,
    )
    m1 = MasterNode(top, chunk_steps=16)
    with m1._state_lock:
        m1._state = m1._state._replace(
            acc=m1._state.acc.at[0].set(-5),
            acc_hi=m1._state.acc_hi.at[0].set(-1),
        )
    path = str(tmp_path / "old.npz")
    m1.save_checkpoint(path)
    # rewrite the npz without the hi planes (the pre-upgrade format) — and
    # without the durability manifest, which that era didn't write either
    # (verify_checkpoint then takes its legacy zip-CRC path)
    with np.load(path) as data:
        arrays = {k: data[k] for k in data.files if k not in ("acc_hi", "bak_hi")}
    np.savez(path, **arrays)
    os.unlink(path + ".manifest")

    m2 = MasterNode(top, chunk_steps=16)
    m2.load_checkpoint(path)
    assert int(np.asarray(m2._state.acc)[0]) == -5
    assert int(np.asarray(m2._state.acc_hi)[0]) == -1  # sign-extended
    m2.run()
    assert m2.compute(9, timeout=30) == 10
    m2.pause()


def test_checkpoint_caps_roundtrip(tmp_path):
    # Caps travel inside the checkpoint: restoring onto a master built with
    # different caps must keep state arrays and compiled network consistent.
    small = Topology(
        node_info={"n": "program"},
        programs={"n": "IN ACC\nADD 1\nOUT ACC"},
        in_cap=16,
        out_cap=16,
        stack_cap=4,
    )
    m1 = MasterNode(small, chunk_steps=16)
    path = str(tmp_path / "c.npz")
    m1.save_checkpoint(path)

    big = Topology(node_info={"n": "program"}, programs={"n": "NOP"})
    m2 = MasterNode(big, chunk_steps=16)
    m2.load_checkpoint(path)
    m2.run()
    assert m2.compute(9, timeout=30) == 10
    m2.pause()
    assert m2._net.in_cap == 16  # restored caps, not the host's


def test_spread_lanes_without_serve_scheduler():
    """A master exposing compute_spread but NOT compute_coalesced — the
    distributed control plane's shape — must still serve the spread lanes
    of /compute_raw and /compute_batch through compute_spread.  Pins the
    r8 regression where both routes called compute_coalesced
    unconditionally and 500'd on every distributed spread request."""
    import numpy as np

    class SchedulerlessMaster:
        is_running = True
        engine_name = "stub"

        def compute_spread(self, values, timeout=30.0, return_array=False):
            out = np.asarray(values, np.int32) + 2
            return out if return_array else out.tolist()

        def compute_many(self, values, timeout=30.0, return_array=False):
            return self.compute_spread(values, return_array=return_array)

    server = make_http_server(SchedulerlessMaster(), 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/compute_raw",  # spread=1 is the default
            data=np.asarray([10, 11], "<i4").tobytes(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
            out = np.frombuffer(resp.read(), "<i4")
        np.testing.assert_array_equal(out, [12, 13])
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/compute_batch",
            data=b"values=1+2&spread=1",
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
            assert json.loads(resp.read()) == {"values": [3, 4]}
    finally:
        server.shutdown()


def test_topology_validation():
    with pytest.raises(TopologyError, match="invalid node type"):
        Topology(node_info={"x": "quantum"})
    with pytest.raises(TopologyError, match="no program nodes"):
        Topology(node_info={"s": "stack"}).compile()
    with pytest.raises(TopologyError, match="non-program nodes"):
        Topology(node_info={"s": "stack"}, programs={"s": "NOP"})


def test_node_info_json_roundtrip():
    # The exact NODE_INFO blob from docker-compose.yml:16-21.
    blob = '{"misaka1": {"type": "program"}, "misaka2": {"type": "program"}, "misaka3": {"type": "stack"}}'
    t = Topology.from_node_info_json(blob, ADD2_PROGRAMS)
    assert t.lane_ids() == {"misaka1": 0, "misaka2": 1}
    assert t.stack_ids() == {"misaka3": 0}
    net = t.compile()
    state = net.init_state()
    state, outs = net.compute_stream(state, [5])
    assert outs == [7]
