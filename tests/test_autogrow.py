"""Stack auto-grow: the engine answer to the reference's unbounded stacks.

intStack.go:9-45 grows without limit; XLA shapes are static, so rounds 1-2
parked the pusher forever once a stack filled — a program the reference
completes could wedge the rebuild (VERDICT r2 missing #3).  The master's
device loop now detects the wedge (in-flight request, nothing moving, a
stack at capacity) and doubles capacity — recompile + zero-pad, geometric —
up to a byte budget.

The test network is a reverser that NEEDS depth len(values): push every
value until a 0 sentinel, then emit the sentinel and pop everything back
out.  With stack_cap=8 and 40 values it deadlocks without growth.
"""


import numpy as np
import pytest

pytestmark = pytest.mark.slow  # simulated-compile grow windows — `make test-all` lane

from misaka_tpu.runtime.master import ComputeTimeout, MasterNode
from misaka_tpu.runtime.topology import Topology

REVERSER = (
    "top: IN ACC\n"
    "JEZ dump\n"
    "PUSH ACC, st\n"
    "JMP top\n"
    "dump: OUT ACC\n"
    "pop: POP st, ACC\n"
    "OUT ACC\n"
    "JMP pop\n"
)


def reverser_top(stack_cap=8):
    return Topology(
        node_info={"p": "program", "st": "stack"},
        programs={"p": REVERSER},
        in_cap=64, out_cap=64, stack_cap=stack_cap,
    )


def run_reverser(master, n=40, timeout=60.0):
    vals = list(range(1, n + 1))
    try:
        outs = master.compute_many(vals + [0], timeout=timeout)
    finally:
        master.pause()
    assert outs == [0] + vals[::-1]


def test_autogrow_unbatched():
    master = MasterNode(reverser_top(), chunk_steps=32)
    master.run()
    run_reverser(master)
    # capacity actually grew (8 -> >= 64 for depth 40) and topology followed
    assert master._net.stack_cap >= 64
    assert master._topology.stack_cap == master._net.stack_cap
    # growth is observable on the metrics surface
    assert master.status()["stack_cap"] == master._net.stack_cap


def test_autogrow_batched():
    master = MasterNode(reverser_top(), chunk_steps=32, batch=4)
    master.run()
    run_reverser(master, n=24)
    assert master._net.stack_cap >= 32


def test_autogrow_disabled_stays_wedged():
    master = MasterNode(
        reverser_top(), chunk_steps=32, stack_autogrow=False
    )
    master.run()
    try:
        with pytest.raises(ComputeTimeout):
            master.compute_many(list(range(1, 21)) + [0], timeout=3.0)
    finally:
        master.pause()
    assert master._net.stack_cap == 8  # untouched


def test_autogrow_respects_budget():
    master = MasterNode(
        reverser_top(), chunk_steps=32,
        stack_grow_max_bytes=8 * 4,  # one doubling would already exceed this
    )
    master.run()
    try:
        with pytest.raises(ComputeTimeout):
            master.compute_many(list(range(1, 21)) + [0], timeout=3.0)
    finally:
        master.pause()
    assert master._net.stack_cap == 8


def test_status_responsive_during_grow():
    """/status (and any _state_lock reader) must stay responsive while a
    grow compiles the new engine: the compile+warm half runs OFF the lock
    (VERDICT r3 weak #4; intStack.go's growth never stalls the Go master).

    Compile cost is simulated by wrapping Topology.compile with a 1.5s
    sleep; with the old under-lock grow every status() during the window
    blocked for the full compile, so the max observed latency is the
    regression trip-wire.
    """
    import threading
    import time

    from misaka_tpu.runtime.topology import Topology as T

    master = MasterNode(reverser_top(), chunk_steps=32)
    real_compile = T.compile
    grew = threading.Event()

    SIM_COMPILE_S = 3.0

    def slow_compile(self, *a, **k):
        if self.stack_cap > 8:  # only the grow path compiles a bigger cap
            grew.set()
            time.sleep(SIM_COMPILE_S)
        return real_compile(self, *a, **k)

    latencies = []
    poll_errors = []
    stop = threading.Event()

    def poll_status():
        try:
            while not stop.is_set():
                t0 = time.monotonic()
                st = master.status()
                latencies.append(time.monotonic() - t0)
                assert "stack_cap" in st
                time.sleep(0.02)
        except BaseException as e:  # pragma: no cover — must not pass silently
            poll_errors.append(e)

    T.compile = slow_compile
    poller = threading.Thread(target=poll_status)
    try:
        master.run()
        poller.start()
        run_reverser(master, n=40, timeout=90)
    finally:
        stop.set()
        poller.join()
        T.compile = real_compile
        master.pause()
    assert not poll_errors, f"status poller died: {poll_errors[0]!r}"
    assert grew.is_set(), "the grow path never ran"
    assert master._net.stack_cap >= 64
    worst = max(latencies)
    print(f"grow-window status latency: worst={worst * 1e3:.1f}ms over {len(latencies)} polls")
    # Old behavior: one poll blocks for the whole simulated compile.  The
    # trip-wire is a FRACTION of that compile, not a fixed wall-clock
    # number, so a saturated CI box can't flake it without a regression.
    assert worst < 0.5 * SIM_COMPILE_S, f"status blocked {worst:.2f}s during grow"


def test_restore_pads_pre_grow_snapshot():
    # a snapshot taken BEFORE a grow must restore against the grown engine
    # (zero-padded), not crash the device loop on its next chunk
    master = MasterNode(reverser_top(), chunk_steps=32)
    master.run()
    snap = master.snapshot()  # stack_cap=8 shapes
    run_reverser(master)      # grows to >= 64
    grown_cap = master._net.stack_cap
    master.restore(snap)      # must pad, not wedge
    assert master._state.stack_mem.shape[-1] == grown_cap
    master.run()
    run_reverser(master, n=4)  # restored state still serves
    master.pause()


def test_restore_rejects_true_shape_mismatch():
    m1 = MasterNode(reverser_top(), chunk_steps=32)
    m2 = MasterNode(
        Topology(
            node_info={"a": "program", "b": "program"},
            programs={"a": "IN ACC\nOUT ACC", "b": "NOP"},
            in_cap=64, out_cap=64, stack_cap=8,
        ),
        chunk_steps=32,
    )
    with pytest.raises(ValueError, match="snapshot shapes"):
        m1.restore(m2.snapshot())


def test_autogrow_not_triggered_by_starvation():
    # a stalled request whose stacks are NOT full (a sink program that
    # consumes inputs and never emits) must not trigger growth
    sink = Topology(
        node_info={"p": "program"},
        programs={"p": "top: IN ACC\nJMP top"},
        in_cap=8, out_cap=8, stack_cap=8,
    )
    master = MasterNode(sink, chunk_steps=16)
    master.run()
    try:
        with pytest.raises(ComputeTimeout):
            master.compute_many([1, 2], timeout=2.5)
    finally:
        master.pause()
    assert master._net.stack_cap == 8
