"""Fast-lane smoke for the fused kernel's r5 additions.

test_fused.py (the full interpret-mode parity sweep) is slow-marked, so the
hi-plane elision gating and the shared block-size walk need one small
unmarked case each — a regression in either must fail `make test`, not
surface 20 minutes into `make test-all` (or on the rarely-reachable TPU).
"""

import numpy as np

from misaka_tpu import networks


def _prep(net, vals):
    state = net.init_state()
    return state._replace(
        in_buf=state.in_buf.at[:, : vals.shape[1]].set(vals),
        in_wr=state.in_wr + vals.shape[1],
    )


def test_elide_dead_hi_smoke():
    """add2 (fully hi-dead) under elision: every observable plane identical
    to the scan engine; sorter keeps a JRO/cond-jump reader so the same
    flag must leave it fully live (pinned via acc_hi equality)."""
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    net = top.compile(batch=128)
    vals = np.random.default_rng(0).integers(
        -100, 100, size=(128, 3)
    ).astype(np.int32)
    ref = net.run(_prep(net, vals), 50)
    out = net.fused_runner(
        50, block_batch=128, interpret=True, elide_dead_hi=True
    )(_prep(net, vals))
    for field in ref._fields:
        if field in ("acc_hi", "bak_hi"):
            continue  # unspecified on hi-dead lanes by contract
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, field)),
            np.asarray(getattr(out, field)),
            err_msg=f"field {field} diverged under elide_dead_hi",
        )
    assert int(np.asarray(out.out_wr).min()) > 0

    # a hi-LIVE lane (sorter branches on acc) must be untouched by the flag
    sort = networks.sorter(in_cap=8, out_cap=8, stack_cap=8).compile(batch=128)
    sref = sort.run(_prep(sort, vals), 40)
    sout = sort.fused_runner(
        40, block_batch=128, interpret=True, elide_dead_hi=True
    )(_prep(sort, vals))
    np.testing.assert_array_equal(
        np.asarray(sref.acc_hi), np.asarray(sout.acc_hi),
        err_msg="hi-live lane's acc_hi must stay exact under the flag",
    )


def test_fused_runner_walk_smoke():
    """The shared walk skips oversized/non-dividing candidates and returns
    a runner that actually runs at the block it reports."""
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    net = top.compile(batch=256)
    runner, bb = net.fused_runner_walk(
        16, candidates=(1024, 512, 256, 128), interpret=True
    )
    assert bb == 256  # 1024/512 > batch are skipped, 256 fits the budget
    vals = np.random.default_rng(1).integers(
        -100, 100, size=(256, 2)
    ).astype(np.int32)
    out = runner(_prep(net, vals))
    assert int(np.asarray(out.tick)[0]) == 16
