"""Failure injection: the rebuild degrades gracefully where the reference dies.

The reference has 17 log.Fatalf sites — any transient error kills a node
process (SURVEY.md quirk #8).  Here: a crashed device loop stops the network
cleanly and /run restarts it; a per-process node with an unreachable master
keeps serving and retrying instead of exiting.
"""

import time

import pytest

from misaka_tpu.networks import add2
from misaka_tpu.runtime.master import ComputeTimeout, MasterNode


def test_device_loop_crash_stops_cleanly_and_restarts():
    master = MasterNode(add2(in_cap=8, out_cap=8, stack_cap=8), chunk_steps=16)
    master.run()
    try:
        assert master.compute(1) == 3

        real_run = master._net.run
        real_serve = master._net.serve_chunk  # the unbatched loop's one-dispatch path
        # auto may have picked the native host tier (off-TPU since r6): the
        # loop then calls the RUNNER's serve_chunk — inject there too
        native_serve = getattr(master._runner, "serve_chunk", None)

        def boom(*a, **k):
            raise RuntimeError("injected device fault")

        master._net.run = boom
        master._net.serve_chunk = boom
        if native_serve is not None:
            master._runner.serve_chunk = boom
        deadline = time.monotonic() + 10
        while master.is_running and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not master.is_running  # supervised: loop stopped, no hang

        # A compute against the stopped network fails fast-ish (timeout),
        # and does not poison later pairing.
        with pytest.raises(ComputeTimeout):
            master.compute(2, timeout=0.3)

        # Heal the fault; /run restarts the loop and service resumes.
        master._net.run = real_run
        master._net.serve_chunk = real_serve
        if native_serve is not None:
            master._runner.serve_chunk = native_serve
        master.run()
        assert master.compute(5) == 7
    finally:
        master.pause()


def test_program_node_survives_unreachable_master():
    """IN against a dead master retries forever instead of killing the node
    (the reference would log.Fatalf on the dial error, program.go:494)."""
    grpc = pytest.importorskip("grpc")
    from misaka_tpu.runtime.nodes import ProgramNodeProcess, Resolver
    from misaka_tpu.transport.rpc import ProgramClient

    node = ProgramNodeProcess(
        master_uri="master",
        resolver=Resolver({"master": "127.0.0.1:1"}),  # nothing listens there
        grpc_port=0,
        host="127.0.0.1",
    )
    port = node.start()
    try:
        node.load_program("IN ACC")
        node.run_cmd()
        time.sleep(1.0)  # the IN keeps failing and retrying the whole time
        with ProgramClient(f"127.0.0.1:{port}") as client:
            client.pause(timeout=5)  # node still alive and serving RPCs
            client.load("MOV 7, ACC", timeout=5)  # and still reprogrammable
            client.run(timeout=5)
        deadline = time.monotonic() + 5
        while node.acc != 7 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert node.acc == 7
    finally:
        node.close()
