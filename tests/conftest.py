"""Test configuration: force an 8-device virtual CPU mesh before jax imports.

Multi-chip hardware is unavailable in CI, so sharding tests run against
XLA's host-platform device virtualization (8 CPU devices), exactly as the
driver's dryrun does.  This must run before any module imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
