"""Test configuration: force an 8-device virtual CPU mesh before jax imports.

Multi-chip hardware is unavailable in CI, so sharding tests run against
XLA's host-platform device virtualization (8 CPU devices), exactly as the
driver's dryrun does.  This must run before any module imports jax.
"""

import os

# The axon sitecustomize may have initialized JAX backends at interpreter
# start (it runs before conftest), which makes env-var routes (XLA_FLAGS /
# JAX_PLATFORMS) unreliable here.  The config API works post-import as long
# as no computation has run yet.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
