"""Test configuration: force an 8-device virtual CPU mesh before jax imports.

Multi-chip hardware is unavailable in CI, so sharding tests run against
XLA's host-platform device virtualization (8 CPU devices), exactly as the
driver's dryrun does.  This must run before any module imports jax.
"""

import os
import sys

_TPU_LANE = bool(os.environ.get("MISAKA_TPU_TESTS")) and any(
    "tpu" in arg for arg in sys.argv
)

if _TPU_LANE:
    # The real-hardware lane (`make test-tpu` / MISAKA_TPU_TESTS=1
    # pytest -m tpu tests/test_tpu.py): leave the platform alone so
    # tests/test_tpu.py runs the Mosaic-compiled kernel on the attached
    # chip.  The argv check keeps a leftover exported MISAKA_TPU_TESTS
    # from silently unforcing CPU for a plain `pytest tests/` run.
    pass
else:
    # The axon sitecustomize may have initialized JAX backends at
    # interpreter start (it runs before conftest), which makes env-var
    # routes (XLA_FLAGS / JAX_PLATFORMS) unreliable here.  The config API
    # works post-import as long as no computation has run yet.
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Belt and suspenders for the 8-device mesh: pre-0.5 jax has no
    # jax_num_cpu_devices config key, so the XLA_FLAGS route must already
    # be in place before the import in case THIS process is the one that
    # initializes the backends.
    if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # pre-0.5 jax: the XLA_FLAGS route above is the only lever; if a
        # sitecustomize already initialized the backends the mesh suites
        # will see fewer devices and skip/fail individually rather than
        # the whole suite dying at collection
        pass
    # Persistent compile cache: the suite compiles the same tiny kernels
    # every run (single-CPU box — recompilation IS the suite's wall-clock);
    # repeat runs hit the disk cache instead.  Keyed by JAX on program +
    # flags; the dir carries a CPU fingerprint because /tmp can outlive a
    # machine migration and foreign-CPU entries make XLA's AOT loader
    # spam machine-mismatch errors.  ONE copy of the fingerprint logic:
    # bench._cpu_cache_dir (tests run from the repo root).
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import _cpu_cache_dir

    jax.config.update(
        "jax_compilation_cache_dir", _cpu_cache_dir("/tmp/misaka_jax_test_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: runs the compiled Mosaic kernel on real TPU hardware "
        "(requires MISAKA_TPU_TESTS=1; skipped otherwise)",
    )
    config.addinivalue_line(
        "markers",
        "slow: fuzz / scale / multi-process suites — `make test` skips "
        "these (fast lane, <3 min); `make test-all` runs everything",
    )
