"""Randomized lifecycle fuzz: the master's state machine under fire.

Fixed lifecycle scenarios live in test_lifecycle/test_runtime; this lane
drives RANDOM interleavings of the whole control surface — compute,
compute_many, pause/run cycles, reset, live /load reprograms, snapshot/
restore, checkpoint save/load — against a behavioral model (the add-K
pipeline: after `load`ing misaka1 with ADD k, every compute(v) must
return v + k + 1), on both the scan and native engines.  Every output is
checked; a wedge surfaces as a ComputeTimeout, a state-machine bug as a
wrong value.  This is the failure class behind the round-3 post-mortem
(lifecycle guards), now fuzzed instead of only scripted.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # many run/pause/compile cycles per seed

from misaka_tpu import networks
from misaka_tpu.runtime.master import MasterNode


def _m1_program(k: int) -> str:
    return f"IN ACC\nADD {k}\nMOV ACC, misaka2:R0\nMOV R0, ACC\nOUT ACC"


def lifecycle_fuzz(seed: int, n_ops: int = 25, engine: str | None = None) -> None:
    rng = np.random.default_rng(seed)
    engine = engine or ("native" if seed % 2 else "scan")
    if engine == "native":
        from misaka_tpu.core import native_serve

        if not native_serve.available():
            pytest.skip("no C++ toolchain for the native engine")
    m = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                   chunk_steps=16, engine=engine)
    m.run()
    delta = 2              # add2: v -> v + 2
    snap = None            # last snapshot() pytree.  NOTE: a snapshot is
    # STATE only — programs are topology, carried by checkpoints, not
    # snapshots — so restore() after a /load keeps the LOADED program and
    # delta does not roll back (found by this very fuzz, seed 2006).
    try:
        for _ in range(n_ops):
            op = int(rng.integers(7))
            if op == 0:
                v = int(rng.integers(-1000, 1000))
                assert m.compute(v, timeout=30) == v + delta, (seed, "compute")
            elif op == 1:
                vals = rng.integers(-1000, 1000, size=int(rng.integers(1, 6)))
                got = m.compute_many(vals.tolist(), timeout=30)
                assert got == [int(v) + delta for v in vals], (seed, "many")
            elif op == 2:
                m.pause()
                m.run()
            elif op == 3:
                m.reset()
                m.run()
            elif op == 4:
                k = int(rng.integers(1, 10))
                m.load("misaka1", _m1_program(k))  # resets + stops (reference order)
                delta = k + 1
                m.run()
            elif op == 5:
                m.pause()
                snap = m.snapshot()
                m.run()
            elif snap is not None:
                m.pause()
                m.restore(snap)  # registers/rings roll back; programs stay
                m.run()
        # the network must still be live and exact at the end
        assert m.compute(7, timeout=30) == 7 + delta, (seed, "final")
    finally:
        m.pause()


@pytest.mark.parametrize("seed", range(2000, 2010))
def test_lifecycle_fuzz(seed):
    lifecycle_fuzz(seed)


@pytest.mark.parametrize("engine", ["scan", "native"])
def test_concurrent_compute_races_lifecycle(engine, tmp_path):
    """N threads of small mixed compute/compute_coalesced requests racing
    reset/load/restore mid-flight (the r8 serve-scheduler concurrency
    lane): every completed request must return EXACTLY its own outputs
    (input/output pairing, zero cross-request leakage), and a request
    wiped by a lifecycle op must fail as ComputeTimeout without
    poisoning any later request's pairing."""
    import threading
    import time

    import numpy as np

    from misaka_tpu.runtime.master import ComputeTimeout

    if engine == "native":
        from misaka_tpu.core import native_serve

        if not native_serve.available():
            pytest.skip("no C++ toolchain for the native engine")
    m = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                   chunk_steps=16, batch=4, engine=engine)
    m.run()
    # delta is ONLY mutated with the compute threads quiescent?  No — the
    # whole point is racing /load.  A request in flight across a /load may
    # legally compute under either program, so workers accept BOTH deltas
    # current at submit and at completion (the set of loaded ks is small).
    deltas = {2}
    deltas_lock = threading.Lock()
    stop = threading.Event()
    failures = []

    def worker(i):
        rng = np.random.default_rng(1000 + i)
        while not stop.is_set():
            n = int(rng.integers(1, 7))
            vals = rng.integers(-1000, 1000, size=n).astype(np.int32)
            with deltas_lock:
                ok_deltas = set(deltas)
            try:
                if int(rng.integers(2)):
                    out = m.compute_coalesced(vals, timeout=15,
                                              return_array=True)
                else:
                    out = np.asarray(
                        m.compute_many(vals, timeout=15), np.int32
                    )
            except ComputeTimeout:
                continue  # wiped by a lifecycle op: isolation, not failure
            with deltas_lock:
                ok_deltas |= set(deltas)
            if not any(
                np.array_equal(out, vals + d) for d in ok_deltas
            ):
                failures.append((i, vals.tolist(), out.tolist(),
                                 sorted(ok_deltas)))
                stop.set()
                return

    workers = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in workers:
        t.start()
    rng = np.random.default_rng(99)
    snap = None
    try:
        for _ in range(10):
            time.sleep(0.15)
            op = int(rng.integers(4))
            if op == 0:
                m.reset()
                m.run()
            elif op == 1:
                k = int(rng.integers(1, 10))
                m.load("misaka1", _m1_program(k))
                with deltas_lock:
                    deltas.add(k + 1)
                m.run()
            elif op == 2:
                m.pause()
                snap = m.snapshot()
                m.run()
            elif snap is not None:
                m.pause()
                m.restore(snap)
                m.run()
    finally:
        stop.set()
        for t in workers:
            t.join(30)
        m.pause()
    assert not failures, failures[:3]


def test_lifecycle_fuzz_checkpoint_roundtrip(tmp_path):
    # checkpoint mid-fuzz and resume on a FRESH master with the OTHER engine
    from misaka_tpu.core import native_serve

    rng = np.random.default_rng(77)
    m = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                   chunk_steps=16, engine="scan")
    m.run()
    k = int(rng.integers(2, 9))
    m.load("misaka1", _m1_program(k))
    m.run()
    assert m.compute(1) == 1 + k + 1
    m.pause()
    path = str(tmp_path / "mid.npz")
    m.save_checkpoint(path)
    if not native_serve.available():
        pytest.skip("no C++ toolchain for the native engine")
    m2 = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                    chunk_steps=16, engine="native")
    m2.load_checkpoint(path)  # programs travel in the checkpoint
    m2.run()
    assert m2.compute(5) == 5 + k + 1
    m2.pause()
