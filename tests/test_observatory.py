"""The observatory end to end (ISSUE 11): /debug/series shapes over a
live server, the self-contained dashboard, POST /debug/faults, watchdog
fire/clear through the alert surface, alert exemplars, the canary's
tier attribution + billing/SLO exclusion contract, history surviving
the checkpoint path — and the slow fleet-mode live drill (scoped fault
-> canary attribution -> watchdog page with exemplars -> degraded ->
recovery -> history across a /fleet/roll).
"""

import http.client
import json
import os
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.runtime import canary as canary_mod
from misaka_tpu.runtime import usage
from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.runtime.registry import ProgramRegistry
from misaka_tpu.utils import faults
from misaka_tpu.utils import slo
from misaka_tpu.utils import tsdb
from misaka_tpu.utils import watchdog

CAPS = dict(in_cap=32, out_cap=32, stack_cap=16)


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.configure(None)
    slo.configure()
    canary_mod.shutdown()
    watchdog.shutdown()
    tsdb.shutdown()
    usage.reset()


def _fast_tsdb(monkeypatch, watchdog_spec=None, recent="0.5"):
    """Test-scale observatory knobs, set BEFORE make_http_server builds
    the process-global collector."""
    tsdb.shutdown()
    watchdog.shutdown()
    monkeypatch.setenv("MISAKA_TSDB_INTERVAL_S", "0.1")
    # the duty-cycle governor would stretch a 100 ms interval on a busy
    # test box; give it headroom — production keeps the 1% default
    monkeypatch.setenv("MISAKA_TSDB_BUDGET", "0.5")
    # the process-global metrics registry accumulates hundreds of series
    # over a full suite run (per-program labels from every earlier test
    # file); the default 512 cap would drop THESE tests' series late in
    # the run — production keeps the documented default
    monkeypatch.setenv("MISAKA_TSDB_MAX_SERIES", "8192")
    monkeypatch.setenv("MISAKA_WATCHDOG_RECENT_S", recent)
    if watchdog_spec is not None:
        monkeypatch.setenv("MISAKA_WATCHDOG", watchdog_spec)


class _Server:
    def __init__(self, registry=True, batch=8):
        top = networks.add2(**CAPS)
        self.master = MasterNode(top, chunk_steps=64, batch=batch)
        self.registry = None
        if registry:
            self.registry = ProgramRegistry(
                None, batch=batch, engine="auto", caps=CAPS
            )
            self.registry.seed("default", self.master, top)
        self.httpd = make_http_server(
            self.master, port=0, registry=self.registry
        )
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()
        self.port = self.httpd.server_address[1]
        self.master.run()

    def get(self, path):
        conn = http.client.HTTPConnection(
            "127.0.0.1", self.port, timeout=30
        )
        conn.request("GET", path)
        r = conn.getresponse()
        body = r.read()
        conn.close()
        return r.status, body

    def post(self, path, body=b"",
             ctype="application/x-www-form-urlencoded"):
        conn = http.client.HTTPConnection(
            "127.0.0.1", self.port, timeout=30
        )
        conn.request("POST", path, body, {"Content-Type": ctype})
        r = conn.getresponse()
        out = r.read()
        conn.close()
        return r.status, out

    def traffic(self, n=20, pause=0.0):
        vals = np.arange(8, dtype=np.int32)
        for _ in range(n):
            st, out = self.post(
                "/compute_raw?spread=1", vals.tobytes(),
                "application/octet-stream",
            )
            assert st == 200, out
            assert (np.frombuffer(out, "<i4") == vals + 2).all()
            if pause:
                time.sleep(pause)

    def wait_samples(self, n, deadline_s=30):
        db = tsdb.get()
        assert db is not None
        start = db._samples
        deadline = time.monotonic() + deadline_s
        while db._samples < start + n:
            assert time.monotonic() < deadline, "collector too slow"
            time.sleep(0.05)

    def close(self):
        self.master.pause()
        if self.registry is not None:
            self.registry.close()
        self.httpd.shutdown()


# --- /debug/series + dashboard ----------------------------------------------


def test_series_route_shapes(monkeypatch):
    _fast_tsdb(monkeypatch)
    s = _Server(registry=False)
    try:
        s.traffic(10)
        s.wait_samples(3)
        s.traffic(10)
        s.wait_samples(2)

        st, body = s.get("/debug/series")
        assert st == 200
        idx = json.loads(body)
        assert idx["running"] and idx["series_count"] > 0
        assert idx["dropped_series"] == 0
        assert [st_["width_s"] for st_ in idx["stages"]] == \
            [0.1, 60.0, 300.0]
        assert idx["bytes_per_series"] == 28 * (720 + 360 + 288)

        st, body = s.get(
            "/debug/series?name=misaka_compute_values_total&window=5m"
        )
        q = json.loads(body)
        assert st == 200 and q["window_s"] == 300.0
        [row] = q["series"]
        assert row["kind"] == "rate" and row["points"]
        t, avg, mx = row["points"][-1]
        assert t > 0 and avg >= 0 and mx >= avg

        # histogram-derived quantile series with a label filter
        st, body = s.get(
            "/debug/series?name=misaka_http_request_duration_seconds:p99"
            "&window=5m&label=route=/compute_raw"
        )
        q = json.loads(body)
        assert st == 200
        for row in q["series"]:
            assert row["labels"]["route"] == "/compute_raw"
            assert row["kind"] == "quantile"

        st, body = s.get("/debug/series?name=x&window=bogus")
        assert st == 400
        st, body = s.get("/debug/series?name=x&label=notkv")
        assert st == 400
    finally:
        s.close()


def test_dashboard_html_populated(monkeypatch):
    _fast_tsdb(monkeypatch)
    s = _Server(registry=False)
    try:
        s.traffic(10)
        s.wait_samples(3)
        s.traffic(10)
        s.wait_samples(2)
        st, body = s.get("/debug/dashboard?window=5m")
        assert st == 200
        page = body.decode()
        assert "misaka observatory" in page
        m = re.search(r"const DATA = (.*);\n", page)
        assert m, "no baked DATA object"
        data = json.loads(m.group(1))
        assert data["window_s"] == 300.0
        titles = [p["title"] for p in data["panels"]]
        assert "Throughput (values/s)" in titles
        assert "Canary success" in titles
        populated = [
            p for p in data["panels"]
            if any(row["points"] for row in p["series"])
        ]
        assert populated, "no panel has any points"
        assert "watchdog" in data
        st, body = s.get("/debug/dashboard?window=junk")
        assert st == 400
    finally:
        s.close()


# --- POST /debug/faults -----------------------------------------------------


def test_debug_faults_route(monkeypatch):
    _fast_tsdb(monkeypatch)
    s = _Server(registry=False)
    try:
        st, body = s.get("/debug/faults")
        assert st == 200 and json.loads(body)["armed"] == []
        st, body = s.post(
            "/debug/faults", b"spec=serve_delay=0.01,rpc_drop@0.5"
        )
        assert st == 200
        assert json.loads(body)["armed"] == ["rpc_drop", "serve_delay"]
        assert faults.active() == {"rpc_drop", "serve_delay"}
        st, body = s.post("/debug/faults", b"spec=bogus_point")
        assert st == 400 and b"unknown fault point" in body
        assert faults.active() == {"rpc_drop", "serve_delay"}  # unchanged
        st, body = s.post("/debug/faults", b"spec=")
        assert st == 200 and json.loads(body)["armed"] == []
    finally:
        s.close()


# --- watchdog through the server --------------------------------------------


def test_watchdog_fires_on_injected_fault_and_clears(monkeypatch):
    _fast_tsdb(
        monkeypatch,
        watchdog_spec=(
            "p99hot=misaka_http_request_duration_seconds:p99{route=/compute_raw}"
            ">0.05 for 0.3s ->page"
        ),
    )
    s = _Server(registry=False)
    stop = threading.Event()
    errors = []

    def pump():
        try:
            while not stop.is_set():
                s.traffic(1)
                time.sleep(0.02)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    t = threading.Thread(target=pump, daemon=True)
    try:
        t.start()
        s.wait_samples(3)
        st, body = s.get("/debug/alerts")
        assert json.loads(body)["watchdog"]["state"] == "ok"

        # inject 150 ms into every serve pass THROUGH THE ROUTE (the
        # drill's entry point), not an in-process configure
        st, _ = s.post("/debug/faults", b"spec=serve_delay=0.15")
        assert st == 200
        deadline = time.monotonic() + 30
        wd = None
        while time.monotonic() < deadline:
            wd = json.loads(s.get("/debug/alerts")[1])["watchdog"]
            if wd["state"] == "page":
                break
            time.sleep(0.2)
        assert wd and wd["state"] == "page", wd
        [rule] = [r for r in wd["rules"] if r["state"] == "page"]
        assert rule["rule"] == "p99hot"
        # alert exemplars: the slowest traces ride the finding, each
        # resolvable at /debug/requests/<id>
        assert rule["exemplars"], rule
        ex = rule["exemplars"][0]
        assert ex["href"] == f"/debug/requests/{ex['trace_id']}"
        st, body = s.get(ex["href"])
        assert st == 200 and json.loads(body)["trace_id"] == ex["trace_id"]
        # the page raises the shared degraded flag
        health = json.loads(s.get("/healthz")[1])
        assert health["watchdog"] == "page" and health["degraded"] is True

        # recovery: clear the fault through the same route; the rule
        # must sustain-clear and drop the degraded flag
        st, _ = s.post("/debug/faults", b"spec=")
        assert st == 200
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            wd = json.loads(s.get("/debug/alerts")[1])["watchdog"]
            if wd["state"] == "ok":
                break
            time.sleep(0.2)
        assert wd["state"] == "ok", wd
        health = json.loads(s.get("/healthz")[1])
        assert health.get("degraded") is not True
        assert not errors, errors[0]
    finally:
        stop.set()
        t.join(timeout=10)
        s.close()


def test_slo_page_carries_exemplars(monkeypatch):
    _fast_tsdb(monkeypatch)
    monkeypatch.setenv("MISAKA_SLO", "p99<50ms")
    monkeypatch.setenv("MISAKA_SLO_WINDOWS", "0.5,1,2,4")
    monkeypatch.setenv("MISAKA_SLO_MIN_EVENTS", "3")
    slo.configure()
    s = _Server(registry=False)
    try:
        faults.configure("serve_delay=0.2")
        deadline = time.monotonic() + 30
        state = None
        while time.monotonic() < deadline:
            s.traffic(3)
            payload = json.loads(s.get("/debug/alerts")[1])
            state = payload["programs"].get("default", {})
            if state.get("state") == "page":
                break
        assert state and state["state"] == "page", state
        assert state["exemplars"], state
        ex = state["exemplars"][0]
        st, body = s.get(ex["href"])
        assert st == 200
        assert ex["duration_ms"] >= 150  # the injected delay shows
    finally:
        s.close()


# --- the canary -------------------------------------------------------------


def test_canary_probes_attributes_and_is_excluded(monkeypatch):
    _fast_tsdb(monkeypatch)
    monkeypatch.setenv("MISAKA_SLO", "p99<5s,err<5%")
    slo.configure()
    s = _Server(registry=True)
    try:
        usage.reset()
        c = canary_mod.CanaryProber(
            f"http://127.0.0.1:{s.port}", registry=s.registry,
            server=s.httpd, interval_s=30,
        )
        state = c.probe_once()
        tiers = state["tiers"]
        assert tiers["edge"]["ok"] is True
        assert tiers["engine"]["ok"] is True
        assert tiers["full"]["ok"] is True
        assert tiers["plane"]["ok"] is None  # no plane in this process
        assert state["failing_tier"] is None
        assert state["consecutive_full_failures"] == 0
        # the known-answer program exists in the registry, unpinned
        # (eviction re-exercises the checkpoint path, by design)
        listing = s.registry.list_programs()["programs"]
        assert canary_mod.PROGRAM in listing
        assert listing[canary_mod.PROGRAM]["pinned"] is False

        # EXCLUSION (the billing contract): probe traffic bills ONLY the
        # _canary account — no real tenant moved
        snap = usage.snapshot()
        assert snap[canary_mod.PROGRAM]["values"] > 0
        assert snap.get("default", {}).get("values", 0) == 0
        # EXCLUSION (the SLO contract): no canary windows were minted,
        # so a slow canary can never burn a tenant's budget
        assert canary_mod.PROGRAM not in slo._windows
        alerts = json.loads(s.get("/debug/alerts")[1])
        assert canary_mod.PROGRAM not in alerts["programs"]
        # and slo.observe is a hard chokepoint, not a route accident
        slo.observe(canary_mod.PROGRAM, 99.0, error=True)
        assert canary_mod.PROGRAM not in slo._windows

        # canary metrics exist for the TSDB/dashboard to pick up
        from misaka_tpu.utils import metrics as umetrics

        text = umetrics.render()
        assert 'misaka_canary_success{tier="full"} 1' in text
        assert "misaka_canary_latency_seconds_count" in text

        # ATTRIBUTION: delay ONLY the canary program's serve passes past
        # the probe timeout — the shallow tiers stay green (the scoped
        # serve_delay lives in the ServeBatcher, which the engine tier's
        # direct lane bypasses), so the fault pins to the serving path
        c2 = canary_mod.CanaryProber(
            f"http://127.0.0.1:{s.port}", registry=s.registry,
            server=s.httpd, interval_s=30, probe_timeout_s=1.0,
        )
        faults.configure(f"serve_delay:{canary_mod.PROGRAM}=3")
        state = c2.probe_once()
        assert state["tiers"]["edge"]["ok"] is True
        assert state["tiers"]["engine"]["ok"] is True
        assert state["tiers"]["full"]["ok"] is False
        assert state["failing_tier"] == "serve"
        assert state["consecutive_full_failures"] == 1
        faults.configure(None)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            state = c2.probe_once()
            if state["failing_tier"] is None:
                break
        assert state["failing_tier"] is None
        assert state["consecutive_full_failures"] == 0
    finally:
        s.close()


def test_canary_healthz_block(monkeypatch):
    _fast_tsdb(monkeypatch)
    s = _Server(registry=True)
    try:
        c = canary_mod.ensure_started(
            f"http://127.0.0.1:{s.port}", registry=s.registry,
            server=s.httpd,
        )
        c.probe_once()
        health = json.loads(s.get("/healthz")[1])
        assert health["canary"]["failing_tier"] is None
        assert health["canary"]["tiers"]["full"] is True
    finally:
        s.close()


# --- client helpers ---------------------------------------------------------


def test_client_series_and_canary_status(monkeypatch):
    from misaka_tpu.client import MisakaClient, MisakaClientError

    _fast_tsdb(monkeypatch)
    s = _Server(registry=True)
    c = MisakaClient(f"http://127.0.0.1:{s.port}")
    try:
        s.traffic(10)
        s.wait_samples(3)
        s.traffic(10)
        s.wait_samples(2)
        idx = c.series()
        assert idx["series_count"] > 0 and "names" in idx
        q = c.series("misaka_compute_values_total", window="5m")
        assert q["window_s"] == 300.0
        assert q["series"] and q["series"][0]["points"]
        q = c.series(
            "misaka_http_request_duration_seconds:p99", window="5m",
            labels={"route": "/compute_raw"},
        )
        for row in q["series"]:
            assert row["labels"]["route"] == "/compute_raw"
        with pytest.raises(MisakaClientError):
            c.series("x", window="bogus")
        # no canary running in this process: a clean None, not a KeyError
        assert c.canary_status() is None
        prober = canary_mod.ensure_started(
            f"http://127.0.0.1:{s.port}", registry=s.registry,
            server=s.httpd,
        )
        prober.probe_once()
        status = c.canary_status()
        assert status["failing_tier"] is None
        assert status["tiers"]["full"] is True
    finally:
        c.close()
        s.close()


# --- history across the checkpoint path -------------------------------------


def test_history_rides_checkpoints(monkeypatch, tmp_path):
    _fast_tsdb(monkeypatch)
    s = _Server(registry=False)
    try:
        s.traffic(10)
        s.wait_samples(3)
        s.traffic(10)
        s.wait_samples(2)
        before = tsdb.query("misaka_compute_values_total", window_s=300)
        assert before and before[0]["points"]
        path = str(tmp_path / "obs.npz")
        s.master.save_checkpoint(path)
        # simulate the process restart a fleet roll performs: the new
        # process boots a FRESH tsdb, then restores the checkpoint
        tsdb.shutdown()
        monkeypatch.setenv("MISAKA_TSDB_INTERVAL_S", "0.1")
        s.master.load_checkpoint(path)
        after = tsdb.query("misaka_compute_values_total", window_s=300)
        assert after and after[0]["points"], "history lost across restore"
        assert after[0]["points"][0][0] <= before[0]["points"][-1][0]
        s.master.run()
    finally:
        s.close()


# --- the live fleet drill (acceptance) --------------------------------------


ADD2_ENV = {
    "NODE_INFO": json.dumps({"main": {"type": "program"}}),
    "MISAKA_PROGRAMS": json.dumps({"main": "IN ACC\nADD 2\nOUT ACC\n"}),
}


def _get_json(base, path, timeout=15):
    import urllib.request

    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _post(base, path, data, timeout=30):
    import urllib.error
    import urllib.parse
    import urllib.request

    body = urllib.parse.urlencode(data).encode()
    req = urllib.request.Request(base + path, data=body, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.mark.slow
def test_fleet_observatory_drill(tmp_path):
    """The ISSUE 11 acceptance drill on a REAL fleet-mode server: a
    scoped fault injected over POST /debug/faults (fanned to every
    replica) makes the canary fail with tier attribution, the watchdog
    pages on /debug/alerts with exemplar trace IDs, /healthz flips
    degraded, recovery clears it — and /debug/series history (replica-
    labeled) survives a POST /fleet/roll."""
    from misaka_tpu.runtime import frontends

    port = frontends.pick_free_port()
    base = f"http://127.0.0.1:{port}"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "MISAKA_FLEET": "2",
        "MISAKA_HTTP_WORKERS": "2",
        "MISAKA_AUTORUN": "1",
        "MISAKA_PORT": str(port),
        "MISAKA_FLEET_DIR": str(tmp_path / "fleet"),
        "MISAKA_PROGRAMS_DIR": str(tmp_path / "programs"),
        "MISAKA_TTL_S": "600",
        "MISAKA_BATCH": "8",
        "MISAKA_IN_CAP": "32",
        "MISAKA_OUT_CAP": "32",
        "MISAKA_STACK_CAP": "16",
        # observatory at test cadence (fans out to the replicas)
        "MISAKA_TSDB_INTERVAL_S": "0.5",
        "MISAKA_TSDB_BUDGET": "0.5",
        "MISAKA_CANARY_INTERVAL_S": "0.5",
        "MISAKA_WATCHDOG_RECENT_S": "2",
        "MISAKA_WATCHDOG":
            "canary=misaka_canary_success{tier=full}<1 for 2s ->page",
        **ADD2_ENV,
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "misaka_tpu.runtime.app"], env=env
    )
    try:
        # fleet healthy AND the parent canary green end to end
        deadline = time.monotonic() + 240
        health = None
        while time.monotonic() < deadline:
            try:
                health = _get_json(base, "/healthz", timeout=5)
                can = health.get("canary") or {}
                if (
                    health.get("ok")
                    and not health.get("degraded")
                    and can.get("tiers", {}).get("full") is True
                ):
                    break
            except OSError:
                pass
            time.sleep(0.5)
        else:
            pytest.fail(f"fleet canary never went green: {health}")

        # replica-labeled history on the merged /debug/series
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            q = _get_json(
                base,
                "/debug/series?name=misaka_canary_success&window=5m",
            )
            replicas = {
                row["labels"].get("replica")
                for row in q["series"] if row["points"]
            }
            if {"0", "1"} <= replicas:
                break
            time.sleep(0.5)
        else:
            pytest.fail(f"no replica-labeled canary history: {q}")

        # the replica label is a server-side drill-down filter: it
        # selects which replica's history comes back (resolved at the
        # parent — the replicas' own series carry no replica label)
        q0 = _get_json(
            base,
            "/debug/series?name=misaka_canary_success&window=5m"
            "&label=replica=0",
        )
        assert q0["series"], q0
        assert all(
            row["labels"]["replica"] == "0" for row in q0["series"]
        ), q0

        # the merged dashboard serves with fleet data baked in
        import urllib.request

        with urllib.request.urlopen(
            base + "/debug/dashboard?window=5m", timeout=15
        ) as r:
            page = r.read().decode()
        assert "misaka observatory" in page and "Canary success" in page

        # DRILL: scope a serve delay onto the canary program only, via
        # the fanned-out route — longer than the canary's own probe
        # timeout, so full-stack probes fail while real traffic and the
        # shallow tiers stay green
        st, body = _post(
            base, "/debug/faults",
            {"spec": f"serve_delay:{canary_mod.PROGRAM}=12"},
        )
        assert st == 200, body

        deadline = time.monotonic() + 120
        health = None
        while time.monotonic() < deadline:
            health = _get_json(base, "/healthz", timeout=10)
            can = health.get("canary") or {}
            if health.get("degraded") and can.get("failing_tier"):
                break
            time.sleep(1.0)
        else:
            pytest.fail(f"drill never degraded /healthz: {health}")
        # the fault is BELOW the edge and plane: attribution names the
        # serving path, not the door
        assert health["canary"]["failing_tier"] in ("serve", "engine")

        alerts = _get_json(base, "/debug/alerts", timeout=10)
        fired = [
            r for r in alerts["fleet_watchdog"]["rules"]
            if r["state"] != "ok"
        ]
        assert fired, alerts["fleet_watchdog"]
        assert "exemplars" in fired[0]

        # RECOVERY: clear the fault the same way; everything greens
        st, body = _post(base, "/debug/faults", {"spec": ""})
        assert st == 200, body
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            health = _get_json(base, "/healthz", timeout=10)
            can = health.get("canary") or {}
            if (
                not health.get("degraded")
                and can.get("failing_tier") is None
            ):
                break
            time.sleep(1.0)
        else:
            pytest.fail(f"drill never recovered: {health}")

        # HISTORY SURVIVES THE ROLL: note the oldest canary point, roll
        # the fleet, and require pre-roll points to still be there
        q = _get_json(
            base, "/debug/series?name=misaka_canary_success&window=10m"
        )
        oldest_before = min(
            row["points"][0][0] for row in q["series"] if row["points"]
        )
        t_roll = time.time()
        st, body = _post(base, "/fleet/roll", {}, timeout=600)
        assert st == 200, body
        report = json.loads(body)
        assert report["ok"] and all(
            r.get("restored") for r in report["replicas"]
        )
        q = _get_json(
            base, "/debug/series?name=misaka_canary_success&window=10m"
        )
        survived = [
            row for row in q["series"]
            if row["labels"].get("replica") in ("0", "1")
            and row["points"] and row["points"][0][0] < t_roll - 5
        ]
        assert survived, (
            f"no pre-roll replica history survived the roll "
            f"(oldest before: {oldest_before}): "
            f"{[(r['labels'], r['points'][:1]) for r in q['series']]}"
        )
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
