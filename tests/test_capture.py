"""The deterministic traffic-capture + shadow-replay plane
(runtime/capture.py, tools/replay.py, the registry's ?verify=replay gate).

Covers the recorder's bounded ring under concurrent flood, sampling with
the inbound-trace bypass, the durable segment format through its manifest
verifier, byte-for-byte replay of a >=500-request mixed-tenant
parity-corpus capture on both engines, the loud per-request divergence
diff on a mutated program, the HTTP verify=replay accept/reject contract
(including the structured 409 diffs the client surfaces), the admin gate
on every capture route, and the MISAKA_CAPTURE=0 kill switch.
"""

import glob
import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.client import MisakaClient, MisakaClientError
from misaka_tpu.runtime import capture
from misaka_tpu.runtime import edge
from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.runtime.registry import ProgramRegistry, ReplayDivergence
from misaka_tpu.runtime.topology import Topology

SMALL = dict(stack_cap=16, in_cap=16, out_cap=16)
ADD10 = "IN ACC\nADD 10\nOUT ACC\n"
ADD20 = "IN ACC\nADD 20\nOUT ACC\n"

CORPUS = os.path.join(os.path.dirname(__file__), "corpus", "parity")


@pytest.fixture(autouse=True)
def _capture_reset():
    """The recorder is module-global state: every test starts idle with
    the default knobs and leaves nothing armed behind."""
    capture.configure()
    if capture.recording():
        capture.stop()
    capture.start()  # start() clears the ring; stop right after so
    capture.stop()   # every test begins idle AND empty
    yield
    if capture.recording():
        capture.stop()
    capture.configure()


# --- ring discipline ---------------------------------------------------------


def test_ring_bounded_under_concurrent_flood():
    """MISAKA_CAPTURE_MB is a hard ceiling: 8 writer threads flooding
    2KiB records never push the ring past the budget (sampled live, not
    just at the end), the oldest records evict, and the survivors keep a
    contiguous newest-last seq tail."""
    capture.configure({"MISAKA_CAPTURE_MB": "1", "MISAKA_CAPTURE_SAMPLE": "1.0"})
    budget = capture.status()["budget_bytes"]
    assert budget == 1 << 20
    capture.start()
    overruns = []
    payload = b"\x01\x02\x03\x04" * 256  # 1KiB vals + 1KiB resp per record

    def writer(w):
        for i in range(400):
            capture.note(
                "http", program=f"w{w % 2}", trace=None, inbound=False,
                vals=payload, resp=payload, status=200, tick=i,
            )
            if capture.mem_bytes() > budget:
                overruns.append(capture.mem_bytes())

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    st = capture.status()
    assert not overruns, f"ring exceeded budget: {max(overruns)} > {budget}"
    assert st["ring_bytes"] <= budget
    assert st["dropped"] > 0, "flood must evict, not grow"
    assert st["dropped"] + st["records"] == 8 * 400
    seqs = [r["seq"] for r in capture.records()]
    assert seqs == sorted(seqs)
    assert seqs[-1] - seqs[0] == len(seqs) - 1, "retained tail must be contiguous"
    # eviction is visible to the replay-soundness check
    assert (capture.dropped_since_anchor("w0")
            + capture.dropped_since_anchor("w1")) == st["dropped"]


def test_sampling_and_trace_id_bypass():
    """MISAKA_CAPTURE_SAMPLE drops the complement; a request that arrived
    with an inbound X-Misaka-Trace header bypasses sampling entirely (the
    operator asked to see exactly that request)."""
    capture.configure({"MISAKA_CAPTURE_SAMPLE": "0.0"})
    capture.start()
    for i in range(50):
        capture.note("http", program="p", trace=f"t{i}", inbound=False,
                     vals=b"\0\0\0\0", resp=b"\0\0\0\0", status=200, tick=i)
    assert capture.status()["records"] == 0
    assert capture.status()["sampled_out"] == 50
    capture.note("http", program="p", trace="wanted", inbound=True,
                 vals=b"\0\0\0\0", resp=b"\0\0\0\0", status=200, tick=0)
    recs = capture.records()
    assert [r["trace"] for r in recs] == ["wanted"]
    assert recs[0]["inbound"] is True
    # ingest rows (worker/edge rejects) sample the same way
    capture.ingest("worker", [
        {"t": time.time(), "program": "p", "trace": None, "in": 0,
         "status": 429, "reason": "overload"},
        {"t": time.time(), "program": "p", "trace": "kept", "in": 1,
         "status": 429, "reason": "overload"},
    ])
    traces = [r["trace"] for r in capture.records()]
    assert "kept" in traces and len(traces) == 2


def test_kill_switch_is_terminal():
    """MISAKA_CAPTURE=0: start() refuses, note() is a no-op, and the
    hooks' RECORDING flag stays False — the disabled path is one module
    attribute load."""
    capture.configure({"MISAKA_CAPTURE": "0"})
    assert not capture.available()
    with pytest.raises(capture.CaptureError):
        capture.start()
    assert capture.RECORDING is False
    capture.note("http", program="p", trace=None, inbound=False,
                 vals=b"", resp=b"", status=200, tick=0)
    assert capture.status()["records"] == 0


# --- the durable segment -----------------------------------------------------


def _record_some(n=5):
    capture.configure({"MISAKA_CAPTURE_SAMPLE": "1.0"})
    capture.start()
    for i in range(n):
        vals = np.arange(i + 1, dtype="<i4")
        capture.note("http", program="p", trace=f"t{i}", inbound=False,
                     vals=vals.tobytes(), resp=(vals + 10).tobytes(),
                     status=200, tick=i, op="coalesced")
    capture.stop()


def test_segment_roundtrip_through_manifest_verifier(tmp_path):
    _record_some()
    path = str(tmp_path / "seg.mskcap")
    capture.write_segment(path)
    header, recs = capture.read_segment(path, verify=True)
    assert header["records"] == 5 and len(recs) == 5
    for i, r in enumerate(recs):
        assert r["trace"] == f"t{i}"
        assert np.array_equal(np.frombuffer(r["vals"], "<i4"),
                              np.arange(i + 1))
        assert np.array_equal(np.frombuffer(r["resp"], "<i4"),
                              np.arange(i + 1) + 10)
    manifest = capture.verify_segment(path)
    assert manifest["records"] == 5 and manifest["sha256"]


def test_segment_corruption_detected(tmp_path):
    """A flipped byte (sha mismatch) and a torn tail (size mismatch) must
    both refuse loudly before any replay trusts the file."""
    _record_some()
    path = str(tmp_path / "seg.mskcap")
    capture.write_segment(path)
    blob = open(path, "rb").read()
    with open(path, "r+b") as f:  # flip one payload byte
        f.seek(len(blob) - 3)
        f.write(bytes([blob[-3] ^ 0xFF]))
    with pytest.raises(capture.CaptureError, match="sha256"):
        capture.verify_segment(path)
    with open(path, "wb") as f:  # torn write: manifest size mismatch
        f.write(blob[: len(blob) // 2])
    with pytest.raises(capture.CaptureError, match="torn|bytes"):
        capture.verify_segment(path)
    # no sidecar: the structural frame walk itself is the gate
    os.unlink(capture._segment_manifest_path(path))
    with pytest.raises(capture.CaptureError):
        capture.read_segment(path, verify=True)


def test_export_writes_anchor_checkpoints(tmp_path):
    m = MasterNode(Topology(node_info={"main": "program"},
                            programs={"main": ADD10}, **SMALL),
                   chunk_steps=32, batch=2, engine="scan")
    try:
        m.run()
        capture.configure({"MISAKA_CAPTURE_SAMPLE": "1.0"})
        a = capture.anchor_from_master("default", m)
        capture.start(anchors={"default": a})
        out = m.compute_coalesced(np.arange(3, dtype=np.int32),
                                  return_array=True)
        capture.note("http", program="default", trace="t0", inbound=False,
                     vals=np.arange(3, dtype="<i4").tobytes(),
                     resp=np.asarray(out, dtype="<i4").tobytes(),
                     status=200, tick=0)
        capture.stop()
        res = capture.export(str(tmp_path / "cap.mskcap"))
    finally:
        m.close()
    assert res["records"] == 1
    apath = res["anchors"]["default"]
    assert os.path.exists(apath) and os.path.exists(apath + ".manifest")
    header, _ = capture.read_segment(res["path"], verify=True)
    assert header["anchors"]["default"]["file"] == os.path.basename(apath)
    assert header["anchors"]["default"]["dropped_since_anchor"] == 0


# --- byte-for-byte replay ----------------------------------------------------

# order-preserving (compare == "stream") corpus cases as the mixed-tenant
# program set; every case is 1:1 input->output so the serving lanes apply
_CORPUS_TENANTS = ["add2", "kahn_002", "branch_sign"]


def _corpus_case(name):
    with open(os.path.join(CORPUS, f"{name}.json")) as f:
        return json.load(f)


def _corpus_master(case, engine):
    top = Topology(node_info=case["node_info"], programs=case["programs"],
                   stack_cap=64, in_cap=32, out_cap=32)
    return top, MasterNode(top, chunk_steps=64, batch=2, engine=engine)


@pytest.mark.parametrize("engine", ["scan", "native"])
def test_parity_corpus_replay_byte_identical(engine):
    """The tentpole acceptance pin: >=500 requests of mixed-tenant
    parity-corpus traffic, captured at sample=1.0, replay byte-for-byte
    against shadows restored from the anchors — on both engines."""
    if engine == "native":
        from misaka_tpu.core import native_serve

        if not native_serve.available():
            pytest.skip("native interpreter unavailable (no g++)")
    capture.configure({"MISAKA_CAPTURE_SAMPLE": "1.0",
                       "MISAKA_CAPTURE_MB": "64"})
    cases = {n: _corpus_case(n) for n in _CORPUS_TENANTS}
    masters = {}
    anchors = {}
    try:
        for name, case in cases.items():
            _, m = _corpus_master(case, engine)
            m.run()
            masters[name] = m
            anchors[name] = capture.anchor_from_master(name, m)
        capture.start(anchors=anchors)
        rng = np.random.default_rng(17)
        total = 0
        ops = ("coalesced", "many")
        while total < 510:
            name = _CORPUS_TENANTS[total % len(_CORPUS_TENANTS)]
            m = masters[name]
            pool = cases[name]["inputs"]
            vals = np.array(
                [pool[int(j)] for j in rng.integers(0, len(pool),
                                                    rng.integers(1, 5))],
                dtype=np.int32,
            )
            op = ops[total % 2]
            if op == "many":
                out = m.compute_many(vals, return_array=True)
            else:
                out = m.compute_coalesced(vals, return_array=True)
            capture.note(
                "http", program=name, trace=f"t{total:05d}", inbound=False,
                vals=vals.astype("<i4").tobytes(),
                resp=np.asarray(out).astype("<i4").tobytes(),
                status=200, tick=int(m._ticks_done), op=op,
            )
            total += 1
        capture.stop()
        st = capture.status()
        assert st["records"] >= 510 and st["dropped"] == 0
        for name in _CORPUS_TENANTS:
            recs = capture.replayable(capture.records(program=name))
            assert len(recs) >= 150
            _, shadow = _corpus_master(cases[name], engine)
            try:
                shadow.restore(anchors[name]["state"])
                shadow.run()
                diffs = capture.replay_records(shadow, recs)
            finally:
                shadow.close()
            assert diffs == [], (
                f"{name}/{engine}: {len(diffs)} divergences; first: "
                + capture.format_diff(diffs[0])
            )
    finally:
        for m in masters.values():
            m.close()


def test_mutated_program_diverges_loudly():
    """A semantically-changed candidate must fail replay on every request
    it answers differently, and the diff names the trace ID, stream
    offset, and the expected/actual heads."""
    capture.configure({"MISAKA_CAPTURE_SAMPLE": "1.0"})
    topo10 = Topology(node_info={"main": "program"}, programs={"main": ADD10},
                      **SMALL)
    topo20 = Topology(node_info={"main": "program"}, programs={"main": ADD20},
                      **SMALL)
    m = MasterNode(topo10, chunk_steps=32, batch=2, engine="scan")
    try:
        m.run()
        anchor = capture.anchor_from_master("p", m)
        capture.start(anchors={"p": anchor})
        for i in range(8):
            vals = np.arange(i + 1, dtype=np.int32)
            out = m.compute_coalesced(vals, return_array=True)
            capture.note("http", program="p", trace=f"req-{i}",
                         inbound=False, vals=vals.astype("<i4").tobytes(),
                         resp=np.asarray(out).astype("<i4").tobytes(),
                         status=200, tick=0)
        capture.stop()
    finally:
        m.close()
    recs = capture.replayable(capture.records(program="p"))
    shadow = MasterNode(topo20, chunk_steps=32, batch=2, engine="scan")
    try:
        shadow.restore(anchor["state"])
        shadow.run()
        diffs = capture.replay_records(shadow, recs)
    finally:
        shadow.close()
    assert len(diffs) == 8
    for off, d in enumerate(diffs):
        assert d["offset"] == off and d["trace"] == f"req-{off}"
        assert d["expected_head"][0] + 10 == d["actual_head"][0]
        line = capture.format_diff(d)
        assert f"req-{off}" in line and "expected=" in line

    # the same verdict through the registry's publish gate
    reg = ProgramRegistry(None, batch=2, engine="scan", chunk_steps=32,
                          caps=SMALL)
    try:
        reg.publish("p", tis=ADD10)
        with pytest.raises(ReplayDivergence) as ei:
            reg.publish("p", tis=ADD20, verify="replay")
        assert len(ei.value.diffs) == 8
        assert ei.value.diffs[0]["trace"] == "req-0"
    finally:
        reg.close()


def test_verify_bundle_refuses_unsound_replay():
    """No anchor, no records, or an evicted (non-contiguous) stream each
    refuse with a typed CaptureError — replay never lies."""
    capture.configure({"MISAKA_CAPTURE_SAMPLE": "1.0"})
    capture.start()
    with pytest.raises(capture.CaptureError, match="anchor"):
        capture.verify_bundle("ghost")
    capture.stop()

    # eviction since the anchor poisons soundness for that program
    capture.configure({"MISAKA_CAPTURE_MB": "1",
                       "MISAKA_CAPTURE_SAMPLE": "1.0"})
    m = MasterNode(Topology(node_info={"main": "program"},
                            programs={"main": ADD10}, **SMALL),
                   chunk_steps=32, batch=2, engine="scan")
    try:
        anchor = capture.anchor_from_master("p", m)
        capture.start(anchors={"p": anchor})
        blob = b"\0" * 65536
        for i in range(40):  # 40 * 128KiB >> 1MiB: forced eviction
            capture.note("http", program="p", trace=None, inbound=False,
                         vals=blob, resp=blob, status=200, tick=i)
        assert capture.dropped_since_anchor("p") > 0
        with pytest.raises(capture.CaptureError, match="evicted"):
            capture.verify_bundle("p")
    finally:
        m.close()


# --- the HTTP surface --------------------------------------------------------


@pytest.fixture
def served_registry():
    capture.configure({"MISAKA_CAPTURE_SAMPLE": "1.0"})
    reg = ProgramRegistry(None, batch=2, engine="scan", chunk_steps=32,
                          caps=SMALL)
    top = networks.add2(**SMALL)
    master = MasterNode(top, chunk_steps=32, batch=2, engine="scan")
    reg.seed("default", master, top)
    master.run()
    httpd = make_http_server(master, port=0, registry=reg)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield master, reg, httpd.server_address[1]
    finally:
        httpd.shutdown()
        reg.close()
        master.close()


def test_http_capture_and_verify_replay(served_registry):
    """The full wire loop: arm over HTTP, serve traffic, verify=replay
    accepts the unchanged program and 409s the mutant with structured
    diffs the client surfaces, export writes the segment + anchors, and
    /healthz reports the ring under debug_mem."""
    _, _, port = served_registry
    c = MisakaClient(f"http://127.0.0.1:{port}")
    c.upload_program("p", program=ADD10)
    cp = MisakaClient(f"http://127.0.0.1:{port}", program="p")
    cp.compute_batch([0])  # lease the engine before anchoring

    st = c.capture_start()
    assert st["recording"] and "p" in st["anchors"]
    with pytest.raises(MisakaClientError) as ei:  # double-arm refuses
        c.capture_start()
    assert ei.value.status == 409
    for i in range(6):
        assert list(cp.compute_batch([i, i + 1])) == [i + 10, i + 11]

    # unchanged semantics: replay-verified publish goes green
    res = c.replay("p", program=ADD10)
    assert res["name"] == "p"

    # mutated: 409, typed error, structured diffs, nothing swapped
    with pytest.raises(MisakaClientError) as ei:
        c.replay("p", program=ADD20)
    assert ei.value.status == 409
    assert len(ei.value.diffs) == 6
    d = ei.value.diffs[0]
    assert d["program"] == "p" and d["trace"] and "offset" in d
    assert [v + 10 for v in d["expected_head"]] == d["actual_head"]
    assert list(cp.compute_batch([1])) == [11], "mutant must not have swapped"

    # invalid verifier name is a typed 400, not a silent publish
    with pytest.raises(MisakaClientError) as ei:
        c.upload_program("p", program=ADD10, verify="nonsense")
    assert ei.value.status == 400

    dbg = c.capture_status(n=3)
    assert dbg["recording"] and len(dbg["preview"]) == 3
    assert dbg["preview"][-1]["program"] == "p"
    hz = c.healthz()
    assert hz["debug_mem"]["capture_bytes"] > 0
    assert hz["debug_mem"]["total_bytes"] >= hz["debug_mem"]["capture_bytes"]


def test_http_export_then_offline_tool_replay(served_registry, tmp_path):
    """POST /captures/export -> tools/replay.py round trip: the exported
    segment replays green offline, and the tool's --candidate path
    renders the loud diff and exits 1."""
    import subprocess
    import sys

    _, _, port = served_registry
    c = MisakaClient(f"http://127.0.0.1:{port}")
    c.upload_program("p", program=ADD10)
    cp = MisakaClient(f"http://127.0.0.1:{port}", program="p")
    cp.compute_batch([0])
    c.capture_start()
    for i in range(5):
        cp.compute_batch([i, i + 7])
    exp = c.capture_export(str(tmp_path / "wire.mskcap"))
    c.capture_stop()
    assert exp["records"] >= 5 and "p" in exp["anchors"]

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    tool = os.path.join(os.path.dirname(__file__), "..", "tools", "replay.py")
    r = subprocess.run(
        [sys.executable, tool, exp["path"], "--program", "p"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "replay green" in r.stdout

    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps({"nodes": {"main": "program"},
                                "programs": {"main": ADD20}}))
    model = tmp_path / "model.json"
    r = subprocess.run(
        [sys.executable, tool, exp["path"], "--program", "p",
         "--candidate", str(cand), "--emit-model", str(model)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "DIVERGENCE" in r.stdout and "DIVERGED" in r.stdout
    fitted = json.loads(model.read_text())
    assert fitted["format"] == 1 and fitted["arrival"]["rate_rps"] > 0
    assert "p" in fitted["tenants"]


KEYS = [
    {"key": "adm-secret", "tenant": "ops", "admin": True},
    {"key": "bob-secret", "tenant": "bob"},
]


def test_capture_routes_admin_gated(tmp_path, monkeypatch):
    """With edge auth armed, every capture route is admin-scope: anon
    401s, a plain tenant key 403s, the admin key operates the recorder."""
    kf = tmp_path / "keys.json"
    kf.write_text(json.dumps({"keys": KEYS}))
    monkeypatch.setenv("MISAKA_API_KEYS", str(kf))
    capture.configure({"MISAKA_CAPTURE_SAMPLE": "1.0"})
    m = MasterNode(networks.add2(**SMALL), chunk_steps=32, batch=2,
                   engine="scan")
    m.run()
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    try:
        for route in ("/captures/start", "/captures/stop",
                      "/captures/export"):
            assert edge.route_policy(route, "POST") == ("auth_admin",)
        assert edge.route_policy("/debug/captures", "GET") == ("auth_admin",)

        anon = MisakaClient(f"http://127.0.0.1:{port}", api_key="")
        anon.api_key = None
        bob = MisakaClient(f"http://127.0.0.1:{port}", api_key="bob-secret")
        adm = MisakaClient(f"http://127.0.0.1:{port}", api_key="adm-secret")
        for call in (anon.capture_start, lambda: anon.capture_status(1)):
            with pytest.raises(MisakaClientError) as ei:
                call()
            assert ei.value.status == 401
        for call in (bob.capture_start, bob.capture_stop,
                     bob.capture_export, lambda: bob.capture_status(1)):
            with pytest.raises(MisakaClientError) as ei:
                call()
            assert ei.value.status == 403
        st = adm.capture_start()
        assert st["recording"]
        assert adm.capture_status(0)["recording"]
        adm.capture_stop()
    finally:
        edge.reset()
        httpd.shutdown()
        m.close()


def test_http_kill_switch_409(served_registry):
    _, _, port = served_registry
    capture.configure({"MISAKA_CAPTURE": "0"})
    c = MisakaClient(f"http://127.0.0.1:{port}")
    with pytest.raises(MisakaClientError) as ei:
        c.capture_start()
    assert ei.value.status == 409 and "kill switch" in ei.value.body
    assert c.healthz()["ok"] is True  # serving is untouched


# --- load models -------------------------------------------------------------


def test_fit_load_model_shapes():
    capture.configure({"MISAKA_CAPTURE_SAMPLE": "1.0"})
    capture.start()
    t0 = time.time()
    rng = np.random.default_rng(3)
    for i in range(60):
        n = int(rng.integers(1, 30))
        capture.note(
            "http", program=("a" if i % 3 else "b"), trace=None,
            inbound=False, vals=b"\0" * (4 * n), resp=b"\0" * (4 * n),
            status=200, tick=i, t=t0 + i * 0.01,
        )
    capture.stop()
    model = capture.fit_load_model(capture.records())
    assert model["format"] == 1
    assert model["source"]["requests"] == 60
    assert model["arrival"]["rate_rps"] > 0
    assert abs(sum(model["tenants"].values()) - 1.0) < 1e-6
    assert model["tenants"]["a"] > model["tenants"]["b"]
    assert model["values"]["p50"] >= 1
    assert sum(w for _, w in model["values"]["hist"]) == 60
    # TSDB history widens the arrival fit
    widened = capture.fit_load_model(
        capture.records(), series=[(t0, 1000.0), (t0 + 60, 1000.0)]
    )
    assert widened["arrival"]["rate_rps"] > model["arrival"]["rate_rps"]
    with pytest.raises(capture.CaptureError):
        capture.fit_load_model([])
