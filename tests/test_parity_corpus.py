"""The committed Go-parity corpus stays honest.

tools/parity_go.py replays tests/corpus/parity/*.json against the real Go
reference (needs Docker — skipped in this environment); THIS test re-runs
every case's engine side so the committed `engine_outputs` can never drift
from what the current engine actually produces.
"""

import glob
import json
import os

import pytest

pytestmark = pytest.mark.slow  # covered every `make test-all`; fast lane favors iteration speed

CORPUS = os.path.join(os.path.dirname(__file__), "corpus", "parity")
CASES = sorted(glob.glob(os.path.join(CORPUS, "*.json")))


def test_corpus_exists():
    assert len(CASES) >= 10, "parity corpus missing; run tools/gen_parity_corpus.py"


@pytest.mark.parametrize("path", CASES, ids=[os.path.basename(p) for p in CASES])
def test_corpus_engine_outputs_current(path):
    from tests.test_cross_mode import run_engine

    with open(path) as f:
        case = json.load(f)
    outs = run_engine(case["node_info"], case["programs"], case["inputs"])
    if case["compare"] == "stream":
        assert outs == case["engine_outputs"], case["name"]
    else:
        assert sorted(outs) == sorted(case["engine_outputs"]), case["name"]


def test_replayer_local_cluster_mode():
    """tools/parity_go.py --local replays the corpus against OUR
    wire-compatible per-process gRPC cluster through the same serialized
    POST /compute feed/compare code the Docker replay uses — the harness
    itself is exercised end to end, not just written down (a subset of
    cases keeps the suite fast; the full 13 run in `make parity-local`)."""
    import subprocess
    import sys

    out = subprocess.run(
        [
            sys.executable,
            os.path.join(os.path.dirname(__file__), "..", "tools", "parity_go.py"),
            "--local", "add2", "kahn_002", "contended_000",
        ],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert out.stdout.count("OK ") == 3, out.stdout


def test_replayer_skips_cleanly_without_docker():
    """`make parity-go` must be safe everywhere: in an environment without
    Docker (this one) the replayer exits 0 with a SKIP notice."""
    import shutil
    import subprocess
    import sys

    if shutil.which("docker") or shutil.which("docker-compose"):
        pytest.skip("Docker available: the replayer would do the real "
                    "13-case replay here — run `make parity-go` instead")
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..", "tools", "parity_go.py")],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    assert "SKIP" in out.stdout or "OK" in out.stdout
