"""Copy-and-patch JIT tick tier + quiescent pack-row elision (ISSUE 18).

The top rung of the native tick ladder (core/jit.py + native/stencils.cpp):
stencils compiled once into a content-keyed cache, spliced and patched
per-(lane, pc) into W^X executable buffers, armed onto the pool.  The
ladder contract pinned here:

* bit-identity against the scalar, generic, and switch-threaded rungs on
  the differential schedules AND the 510-request mixed-tenant parity
  corpus;
* MISAKA_JIT=0 and EVERY failure path (ABI drift, scalar pool, chaos
  fault, corrupt cache) fall back exactly one rung with zero serving
  errors;
* the stencil cache rebuilds through corruption/truncation and re-keys on
  a version bump (spec-cache robustness, satellite 3);
* pack-row elision fires on sparse fills, counts on the observability
  plane, and never changes results (MISAKA_PACK_ELIDE=0 kill included).
"""

import json
import os

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.core import cinterp, jit, native_serve, specialize
from misaka_tpu.core.state import NetworkState
from misaka_tpu.runtime.master import MasterNode
from misaka_tpu.runtime.topology import Topology
from misaka_tpu.utils import faults

pytestmark = pytest.mark.skipif(
    not native_serve.available(), reason="native interpreter unavailable (no g++)"
)

SMALL = dict(stack_cap=8, in_cap=16, out_cap=16)

# Control-flow divergence + stacks + network moves: the shapes a fragment
# library gets wrong if a hole is patched with the wrong plane offset.
DIVERGE = Topology(
    node_info={"p": "program"},
    programs={
        "p": (
            "IN ACC\n"
            "JGZ pos\n"
            "JLZ neg\n"
            "OUT 0\n"
            "JMP end\n"
            "pos: ADD 100\n"
            "OUT ACC\n"
            "JMP end\n"
            "neg: NEG\n"
            "OUT ACC\n"
            "end: NOP"
        )
    },
    **SMALL,
)


def topologies():
    return {
        "add2": networks.add2(**SMALL),
        "acc_loop": networks.acc_loop(**SMALL),
        "ring4": networks.ring(4, **SMALL),
        "diverge": DIVERGE,
    }


def state_dict(state: NetworkState) -> dict:
    return {f: np.asarray(getattr(state, f)) for f in NetworkState._fields}


def assert_state_equal(a: dict, b: dict, msg: str = ""):
    for f, av in a.items():
        np.testing.assert_array_equal(av, b[f], err_msg=f"{msg}: field {f}")


def run_schedule(net, rounds: int = 8, spec: str | None = None,
                 jit_prog=None, mode: str | None = None, seed: int = 3,
                 active_fn=None, threads: int = 4):
    """The test_simd.py differential schedule, extended with the JIT arm:
    randomness depends only on the seed and ring headroom only on prior
    state, so every rung sees the identical feed by induction."""
    B = net.batch
    prev = os.environ.get("MISAKA_SIMD")
    if mode is None:
        os.environ.pop("MISAKA_SIMD", None)
    else:
        os.environ["MISAKA_SIMD"] = mode
    try:
        pool = native_serve.NativeServePool(
            net, chunk_steps=64, threads=threads, specialized=spec,
            jit_program=jit_prog,
        )
    finally:
        if prev is None:
            os.environ.pop("MISAKA_SIMD", None)
        else:
            os.environ["MISAKA_SIMD"] = prev
    rng = np.random.default_rng(seed)
    state = net.init_state()
    rows = []

    def materialize(st):
        exported = pool.export_resident(st)
        return exported if exported is not None else st

    try:
        for it in range(rounds):
            if it % 4 == 3:
                state, ctrs = pool.idle(state, 32)
                state = materialize(state)
                rows.append(np.asarray(ctrs).copy())
                continue
            free = net.in_cap - (
                np.asarray(state.in_wr) - np.asarray(state.in_rd)
            )
            counts = np.minimum(
                rng.integers(0, net.in_cap + 1, size=B), free
            ).astype(np.int32)
            vals = rng.integers(
                np.iinfo(np.int32).min, np.iinfo(np.int32).max,
                size=(B, net.in_cap), dtype=np.int64,
            ).astype(np.int32)
            active = active_fn(it, counts) if active_fn else None
            if active is not None:
                mask = np.zeros((B,), bool)
                mask[active] = True
                counts[~mask] = 0
            state, packed = pool.serve(state, vals, counts, active=active)
            state = materialize(state)
            packed = np.asarray(packed).copy()
            if active is not None:
                # skipped rows carry only their counters (cols 4+ are
                # np.empty residue by contract) — blank for comparison
                skipped = np.ones((B,), bool)
                skipped[active] = False
                packed[skipped, 4:] = 0
            rows.append(packed)
        return state_dict(state), rows, pool.simd_info()
    finally:
        pool._pull_trace_stats(force=True)
        pool.close()


# --- differential bit-identity ----------------------------------------------


@pytest.mark.parametrize("name", sorted(topologies()))
def test_jit_bit_identity_differential(name, tmp_path):
    """JIT rung vs switch-threaded (specialized), generic group, and
    scalar rungs: full-state bit-identity (tick counts included) over the
    mixed serve/idle schedule, straggler batch B=19 included."""
    net = topologies()[name].compile(batch=19)
    prog = jit.prepare(net, cache_dir=str(tmp_path))
    assert prog is not None
    so = specialize.build(net, cache_dir=str(tmp_path))
    assert so is not None
    d_jit, rows_jit, info = run_schedule(net, jit_prog=prog)
    assert info["jit"], "JIT rung did not arm"
    d_spec, rows_spec, _ = run_schedule(net, spec=so)
    d_gen, rows_gen, _ = run_schedule(net, mode="generic")
    d_off, rows_off, _ = run_schedule(net, mode="0")
    assert_state_equal(d_jit, d_spec, f"{name}: jit vs switch-threaded")
    assert_state_equal(d_jit, d_gen, f"{name}: jit vs generic")
    assert_state_equal(d_jit, d_off, f"{name}: jit vs scalar")
    for i, (ra, rb, rc, rd) in enumerate(
            zip(rows_jit, rows_spec, rows_gen, rows_off)):
        np.testing.assert_array_equal(ra, rb, err_msg=f"{name} row {i}")
        np.testing.assert_array_equal(ra, rc, err_msg=f"{name} row {i}")
        np.testing.assert_array_equal(ra, rd, err_msg=f"{name} row {i}")


def test_jit_partial_fill_active_lists(tmp_path):
    """Masked serves through the JIT rung: full groups, partial groups,
    stragglers, and the serial fast path all bit-identical to scalar."""
    net = topologies()["add2"].compile(batch=24)
    prog = jit.prepare(net, cache_dir=str(tmp_path))
    assert prog is not None

    def actives(it, counts):
        return [
            None,
            list(range(0, 8)),
            list(range(0, 12)),
            [1, 3, 8, 9, 10, 11, 12, 13, 14, 15, 23],
            [17],
            list(range(8, 24)),
        ][it % 6]

    d_jit, rows_jit, _ = run_schedule(net, rounds=12, jit_prog=prog,
                                      active_fn=actives)
    d_off, rows_off, _ = run_schedule(net, rounds=12, mode="0",
                                      active_fn=actives)
    assert_state_equal(d_jit, d_off, "jit partial fill")
    for i, (ra, rb) in enumerate(zip(rows_jit, rows_off)):
        np.testing.assert_array_equal(ra, rb, err_msg=f"row {i}")


# --- the 510-request mixed-tenant parity corpus ------------------------------

CORPUS = os.path.join(os.path.dirname(__file__), "corpus", "parity")
_CORPUS_TENANTS = ["add2", "kahn_002", "branch_sign"]


def _corpus_case(name):
    with open(os.path.join(CORPUS, f"{name}.json")) as f:
        return json.load(f)


def _corpus_requests(cases, total=510, seed=17):
    """The capture-plane mixed-tenant request schedule (test_capture.py):
    deterministic given the seed, 510 requests round-robined across
    tenants with 1-4 values each."""
    rng = np.random.default_rng(seed)
    reqs = []
    for t in range(total):
        name = _CORPUS_TENANTS[t % len(_CORPUS_TENANTS)]
        pool = cases[name]["inputs"]
        vals = [int(pool[int(j)])
                for j in rng.integers(0, len(pool), rng.integers(1, 5))]
        reqs.append((name, vals))
    return reqs


def _corpus_replay(cases, reqs, spec_dir, jit_on: bool):
    prev = os.environ.get("MISAKA_JIT")
    os.environ["MISAKA_JIT"] = "1" if jit_on else "0"
    masters = {}
    try:
        for name, case in cases.items():
            top = Topology(node_info=case["node_info"],
                           programs=case["programs"],
                           stack_cap=64, in_cap=32, out_cap=32)
            m = MasterNode(top, chunk_steps=64, batch=16, engine="native",
                           native_spec_dir=spec_dir)
            m.run()
            masters[name] = m
        if jit_on:
            assert all(m._runner.simd_info()["jit"]
                       for m in masters.values()), "JIT did not arm"
        else:
            assert not any(m._runner.simd_info()["jit"]
                           for m in masters.values())
        outs = []
        for t, (name, vals) in enumerate(reqs):
            m = masters[name]
            if t % 2:
                out = m.compute_many(vals, return_array=True)
            else:
                out = m.compute_coalesced(vals, return_array=True)
            outs.append(np.asarray(out).tolist())
        return outs
    finally:
        for m in masters.values():
            m.close()
        if prev is None:
            os.environ.pop("MISAKA_JIT", None)
        else:
            os.environ["MISAKA_JIT"] = prev


def test_jit_parity_corpus_510_requests(tmp_path):
    """The acceptance pin: 510 mixed-tenant parity-corpus requests served
    through JIT-armed native masters answer byte-for-byte what the
    MISAKA_JIT=0 ladder (switch-threaded rung) answers — zero errors on
    either side."""
    cases = {n: _corpus_case(n) for n in _CORPUS_TENANTS}
    reqs = _corpus_requests(cases)
    spec_dir = str(tmp_path / "spec")
    base = _corpus_replay(cases, reqs, spec_dir, jit_on=False)
    jitted = _corpus_replay(cases, reqs, spec_dir, jit_on=True)
    diverged = [t for t, (a, b) in enumerate(zip(base, jitted)) if a != b]
    assert diverged == [], (
        f"{len(diverged)}/510 requests diverged; first at {diverged[0]}: "
        f"{base[diverged[0]]} vs {jitted[diverged[0]]}")


# --- observability: rung counters + simd_info --------------------------------


def _jit_rung_ticks() -> float:
    """misaka_native_tick_rung_total summed over the jit rung labels
    (`jit` on a no-AVX2 box, `jit-avx2` where the wide loads engage)."""
    return sum(
        native_serve._C_TICK_RUNG.labels(rung=r).value
        for r in ("jit", "jit-avx2", "spec-jit", "spec-avx2-jit")
    )


def test_jit_rung_counter_and_flight_tags(tmp_path):
    """An armed pool ticks on a jit-tagged rung: trace_stats reps carry
    the rung tag and misaka_native_tick_rung_total{rung=~"jit.*"}
    advances."""
    net = topologies()["add2"].compile(batch=16)
    prog = jit.prepare(net, cache_dir=str(tmp_path))
    assert prog is not None
    before = _jit_rung_ticks()
    d, rows, info = run_schedule(net, jit_prog=prog)
    assert info["jit"]
    assert _jit_rung_ticks() > before
    # the scalar run must NOT touch the jit rungs
    mark = _jit_rung_ticks()
    run_schedule(net, mode="0")
    assert _jit_rung_ticks() == mark


def test_jit_metrics_and_program_shape(tmp_path):
    """prepare() reports splice outcomes: fragment/byte gauges move, the
    program owns executable memory, and close() is idempotent."""
    net = topologies()["diverge"].compile(batch=8)
    spliced = jit.M_JIT.labels(status="spliced").value
    prog = jit.prepare(net, cache_dir=str(tmp_path))
    assert prog is not None
    assert jit.M_JIT.labels(status="spliced").value == spliced + 1
    assert prog.fragments > 0 and prog.code_bytes > 0
    assert jit.G_JIT_FRAGMENTS.value == prog.fragments
    assert jit.G_JIT_CODE_BYTES.value == prog.code_bytes
    assert prog.n_lanes == 1 and prog.max_len >= 11
    prog.close()
    prog.close()  # idempotent


# --- fallback ladder ---------------------------------------------------------


def test_jit_kill_switch(tmp_path, monkeypatch):
    """MISAKA_JIT=0: prepare() declines (status=disabled), the master's
    ladder serves one rung down (switch-threaded), results unchanged."""
    monkeypatch.setenv("MISAKA_JIT", "0")
    net = topologies()["add2"].compile(batch=16)
    disabled = jit.M_JIT.labels(status="disabled").value
    assert jit.prepare(net, cache_dir=str(tmp_path)) is None
    assert jit.M_JIT.labels(status="disabled").value == disabled + 1
    m = MasterNode(topologies()["add2"], chunk_steps=32, batch=16,
                   engine="native", native_spec_dir=str(tmp_path))
    try:
        m.run()
        info = m._runner.simd_info()
        assert not info["jit"] and info["specialized"]
        assert list(m.compute_many([1, 2, 3])) == [3, 4, 5]
    finally:
        m.close()


def test_jit_master_ladder_arms_and_serves(tmp_path):
    """The default ladder: a master with a spec cache dir arms the JIT
    rung (not the per-program .so compile) and serves correctly."""
    m = MasterNode(topologies()["add2"], chunk_steps=32, batch=16,
                   engine="native", native_spec_dir=str(tmp_path))
    try:
        m.run()
        info = m._runner.simd_info()
        assert info["jit"] and not info["specialized"]
        assert list(m.compute_many([1, 2, 3])) == [3, 4, 5]
        spread = m.compute_spread(list(range(10)))
        assert list(spread) == [v + 2 for v in range(10)]
    finally:
        m.close()


def test_jit_abi_mismatch_refused(tmp_path):
    """An ABI-drifted program must be REFUSED at arm time (rc -1) and the
    pool serves on the rung below — never a torn dispatch table."""
    net = topologies()["add2"].compile(batch=16)
    prog = jit.prepare(net, cache_dir=str(tmp_path))
    assert prog is not None
    prog.abi = 999
    pool = cinterp.NativePool(net.code, net.prog_len, net.num_stacks,
                              net.stack_cap, net.in_cap, net.out_cap,
                              replicas=16, threads=2)
    try:
        assert pool.jit_arm(prog) == -1
        assert not pool.simd_info()["jit"]
    finally:
        pool.close()
    prog.abi = jit.MISAKA_JIT_ABI
    errors = jit.M_JIT.labels(status="error").value
    prog.abi = 999
    sp = native_serve.NativeServePool(net, chunk_steps=32, jit_program=prog)
    try:
        assert not sp.simd_info()["jit"]
        assert jit.M_JIT.labels(status="error").value == errors + 1
        state = net.init_state()
        vals = np.zeros((16, net.in_cap), np.int32)
        vals[:, 0] = np.arange(16)
        counts = np.ones((16,), np.int32)
        state, packed = sp.serve(state, vals, counts)  # zero serving errors
        assert np.asarray(packed).shape[0] == 16
    finally:
        sp.close()


def test_jit_scalar_pool_refused(tmp_path, monkeypatch):
    """A scalar pool (MISAKA_SIMD=0) has no group engine to splice into:
    arm answers rc -2 and the pool stays on the scalar rung."""
    monkeypatch.setenv("MISAKA_SIMD", "0")
    net = topologies()["add2"].compile(batch=16)
    prog = jit.prepare(net, cache_dir=str(tmp_path))
    assert prog is not None
    pool = cinterp.NativePool(net.code, net.prog_len, net.num_stacks,
                              net.stack_cap, net.in_cap, net.out_cap,
                              replicas=16, threads=2)
    try:
        assert pool.jit_arm(prog) == -2
        assert not pool.simd_info()["jit"]
    finally:
        pool.close()


def test_jit_fail_chaos_graceful_fallback(tmp_path):
    """The jit_fail chaos point: prepare() returns None (status=error),
    the master ladder falls back to the switch-threaded rung, and clients
    see zero errors."""
    errors = jit.M_JIT.labels(status="error").value
    faults.configure("jit_fail")
    try:
        m = MasterNode(topologies()["add2"], chunk_steps=32, batch=16,
                       engine="native", native_spec_dir=str(tmp_path))
        try:
            m.run()
            info = m._runner.simd_info()
            assert not info["jit"] and info["specialized"]
            assert list(m.compute_many([5, 6])) == [7, 8]
        finally:
            m.close()
    finally:
        faults.configure(None)
    assert jit.M_JIT.labels(status="error").value > errors


# --- spec-cache robustness (satellite 3) -------------------------------------


def _evict_inproc_cache():
    with jit._lib_lock:
        jit._lib_cache.clear()


def test_stencil_cache_corrupt_object_rebuilds(tmp_path):
    """A corrupted cached stencil .o (disk fault, torn write) is evicted
    and rebuilt ONCE; the rebuilt library splices and serves."""
    cache = str(tmp_path)
    path = jit.build_stencils(cache)
    assert path is not None and os.path.exists(path)
    with open(path, "r+b") as f:  # scribble over the section table
        f.seek(0x28)
        f.write(b"\xff" * 16)
    _evict_inproc_cache()
    built = jit.M_JIT.labels(status="built").value
    lib = jit.load_stencils(cache)
    assert lib is not None
    assert jit.M_JIT.labels(status="built").value == built + 1
    net = topologies()["add2"].compile(batch=16)
    prog = jit.prepare(net, cache_dir=cache)
    assert prog is not None
    prog.close()


def test_stencil_cache_truncated_object_rebuilds(tmp_path):
    """A truncated cached object (partial write) follows the same
    evict-and-rebuild path instead of crashing the parser."""
    cache = str(tmp_path)
    path = jit.build_stencils(cache)
    assert path is not None
    with open(path, "r+b") as f:
        f.truncate(100)
    _evict_inproc_cache()
    lib = jit.load_stencils(cache)
    assert lib is not None and len(lib.stencils) >= 24


def test_stencil_cache_version_bump_rekeys(tmp_path, monkeypatch):
    """Bumping JIT_VERSION changes the content key: the old cached object
    is ignored (stale key) and a fresh library is built beside it."""
    cache = str(tmp_path)
    old_key = jit.stencil_key()
    old_path = jit.build_stencils(cache)
    assert old_path is not None
    monkeypatch.setattr(jit, "JIT_VERSION", jit.JIT_VERSION + 1)
    new_key = jit.stencil_key()
    assert new_key != old_key
    built = jit.M_JIT.labels(status="built").value
    new_path = jit.build_stencils(cache)
    assert new_path is not None and new_path != old_path
    assert jit.M_JIT.labels(status="built").value == built + 1
    assert os.path.exists(old_path)  # LRU prune owns aging, not the bump


def test_stencil_cache_unparseable_twice_falls_back(tmp_path, monkeypatch):
    """If the library STAYS unparseable after the rebuild (toolchain emits
    something outside the contract), load gives up (status=error) and
    prepare() returns None — the ladder serves one rung down."""
    def bad_parse(path):
        raise jit.JitError("forced: contract violation")

    monkeypatch.setattr(jit, "_parse_stencils", bad_parse)
    _evict_inproc_cache()
    errors = jit.M_JIT.labels(status="error").value
    assert jit.load_stencils(str(tmp_path)) is None
    assert jit.M_JIT.labels(status="error").value == errors + 1
    net = topologies()["add2"].compile(batch=16)
    assert jit.prepare(net, cache_dir=str(tmp_path)) is None


# --- quiescent pack-row elision ----------------------------------------------


def _mk_raw_pool(net, B):
    return cinterp.NativePool(net.code, net.prog_len, net.num_stacks,
                              net.stack_cap, net.in_cap, net.out_cap,
                              replicas=B, threads=2)


def _sparse_resident_run(net, pool, reuse, rounds=12, seed=7):
    """Resident serves with ONE hot replica: every other group is fully
    quiescent — the elision fast path's home turf."""
    B = net.batch
    rng = np.random.default_rng(seed)
    state = net.init_state()
    d = {f: np.array(np.asarray(getattr(state, f))) for f in state._fields}
    assert pool.import_state(d)
    rows = []
    active = np.array([0], np.int32)
    in_wr = d["in_wr"].copy()
    in_rd = d["in_rd"].copy()
    for _ in range(rounds):
        free = net.in_cap - (in_wr - in_rd)
        counts = np.minimum(rng.integers(0, net.in_cap + 1, size=B),
                            free).astype(np.int32)
        counts[1:] = 0
        vals = rng.integers(-10_000, 10_000,
                            size=(B, net.in_cap)).astype(np.int32)
        packed, progress = pool.serve_resident(vals, counts, 48,
                                               active=active,
                                               reuse_out=reuse)
        packed = np.array(packed)
        packed[1:, 4:] = 0  # skipped rows: unspecified out-cell residue
        rows.append((packed, np.array(progress)))
        ex = pool.export_state()
        in_wr, in_rd = ex["in_wr"], ex["in_rd"]
    return pool.export_state(), rows


def test_pack_row_elision_sparse_fill_bit_identical(tmp_path):
    """Sparse fill (1 hot replica of 24): the elision path must skip the
    quiescent rows' pack writes, count them, and stay bit-identical to
    the always-copy reference."""
    net = topologies()["add2"].compile(batch=24)
    ref = _mk_raw_pool(net, 24)
    try:
        d_ref, rows_ref = _sparse_resident_run(net, ref, reuse=False)
        ref_ctrs = ref.counters()
    finally:
        ref.close()
    assert ref_ctrs["elided_rows"] == 0  # reuse off -> no ledger, no skip

    el = _mk_raw_pool(net, 24)
    try:
        prog = jit.prepare(net, cache_dir=str(tmp_path))
        assert prog is not None and el.jit_arm(prog) == 0
        d_el, rows_el = _sparse_resident_run(net, el, reuse=True)
        ctrs = el.counters()
    finally:
        el.close()
    assert ctrs["elided_rows"] > 0, "elision never fired on sparse fill"
    assert ctrs["skip_packed_rows"] > 0
    for f in d_ref:
        np.testing.assert_array_equal(d_ref[f], d_el[f], err_msg=f)
    for i, ((pa, ga), (pb, gb)) in enumerate(zip(rows_ref, rows_el)):
        np.testing.assert_array_equal(pa, pb, err_msg=f"packed {i}")
        np.testing.assert_array_equal(ga, gb, err_msg=f"progress {i}")


def test_pack_elide_kill_switch(monkeypatch):
    """MISAKA_PACK_ELIDE=0: the reuse path still serves identically but
    elides nothing — the kill switch isolates the layer."""
    net = topologies()["add2"].compile(batch=24)
    monkeypatch.setenv("MISAKA_PACK_ELIDE", "0")
    pool = _mk_raw_pool(net, 24)
    try:
        d_off, rows_off = _sparse_resident_run(net, pool, reuse=True)
        ctrs = pool.counters()
    finally:
        pool.close()
    assert ctrs["elided_rows"] == 0
    monkeypatch.delenv("MISAKA_PACK_ELIDE")
    ref = _mk_raw_pool(net, 24)
    try:
        d_ref, rows_ref = _sparse_resident_run(net, ref, reuse=False)
    finally:
        ref.close()
    for f in d_ref:
        np.testing.assert_array_equal(d_ref[f], d_off[f], err_msg=f)
    for i, ((pa, ga), (pb, gb)) in enumerate(zip(rows_ref, rows_off)):
        np.testing.assert_array_equal(pa, pb, err_msg=f"packed {i}")
        np.testing.assert_array_equal(ga, gb, err_msg=f"progress {i}")


def test_elision_counters_reach_metrics_plane(tmp_path):
    """The serve pool pipes pool-level elision counters into the process
    counters misaka_native_elided_rows_total / _skip_packed_rows_total."""
    net = topologies()["add2"].compile(batch=24)
    before = native_serve._C_ELIDED_ROWS.value
    pool = native_serve.NativeServePool(net, chunk_steps=48)
    try:
        state = net.init_state()
        vals = np.zeros((24, net.in_cap), np.int32)
        counts = np.zeros((24,), np.int32)
        counts[0] = 2
        vals[0, :2] = (3, 4)
        active = np.array([0], np.int32)
        for _ in range(6):
            state, _ = pool.serve(state, vals, counts, active=active)
        pool.take_busy_ns()  # flushes the elision watermarks
    finally:
        pool.close()
    assert native_serve._C_ELIDED_ROWS.value > before
