"""The program registry (runtime/registry.py): multi-tenant serving of
versioned TIS networks.

Covers the registry core (content-address dedup, version/alias
resolution, LRU eviction order, concurrent upload races, the typed
unknown-program 404), the HTTP surface (POST/GET /programs,
/programs/<name>/compute*, X-Misaka-Program on the legacy routes, full
legacy single-program compat), hot-swap under concurrency, eviction/
reactivation state round-trips through the manifest-verified checkpoint
path, the per-program compute-plane frames, client helpers, and the
persistent MISAKA_PROGRAMS_DIR store.
"""

import json
import threading
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.client import MisakaClient, MisakaClientError
from misaka_tpu.runtime.master import (
    MasterNode,
    make_http_server,
    verify_checkpoint,
)
from misaka_tpu.runtime.registry import (
    ProgramNotFound,
    ProgramRegistry,
    RegistryError,
    canonical_topology,
    version_of,
)
from misaka_tpu.runtime.topology import Topology

SMALL = dict(stack_cap=16, in_cap=16, out_cap=16)

ADD10 = "IN ACC\nADD 10\nOUT ACC\n"
ADD20 = "IN ACC\nADD 20\nOUT ACC\n"
ADD30 = "IN ACC\nADD 30\nOUT ACC\n"
# A DELAY LINE: output_i = input_{i-1} (0 first) — the persistent state
# (BAK holds the last value) is what eviction must round-trip.
DELAY = "IN ACC\nSWP\nOUT ACC\nSWP\nSAV\n"


def make_registry(**kw):
    kw.setdefault("batch", 2)
    kw.setdefault("engine", "scan")
    kw.setdefault("chunk_steps", 32)
    kw.setdefault("caps", SMALL)
    return ProgramRegistry(None, **kw)


def seeded_registry(**kw):
    reg = make_registry(**kw)
    top = networks.add2(**SMALL)
    master = MasterNode(top, chunk_steps=32, batch=reg._batch, engine="scan")
    reg.seed("default", master, top)
    master.run()
    return reg, master


# --- registry core ----------------------------------------------------------


def test_content_address_dedup():
    reg, master = seeded_registry()
    try:
        r1 = reg.publish("p", tis=ADD10)
        r2 = reg.publish("p", tis=ADD10)
        assert r1["created"] and not r2["created"]
        assert r1["version"] == r2["version"]
        # the same network as explicit topology JSON (different key
        # order) content-addresses identically
        r3 = reg.publish(
            "q",
            topology_json=json.dumps({
                "programs": {"main": ADD10},
                "nodes": {"main": "program"},
                "out_cap": 16, "in_cap": 16, "stack_cap": 16,
            }),
        )
        assert r3["version"] == r1["version"]
        # and a different program is a different version
        assert reg.publish("p", tis=ADD20)["version"] != r1["version"]
    finally:
        master.pause()
        reg.close()


def test_canonicalization_is_key_order_invariant():
    t = Topology(node_info={"main": "program"}, programs={"main": ADD10},
                 **SMALL)
    assert version_of(canonical_topology(t)) == version_of(
        canonical_topology(
            Topology(node_info={"main": "program"},
                     programs={"main": ADD10}, **SMALL)
        )
    )


def test_version_and_alias_resolution():
    reg, master = seeded_registry()
    try:
        v1 = reg.publish("p", tis=ADD10)["version"]
        v2 = reg.publish("p", tis=ADD20)["version"]
        assert reg.resolve("p") == ("p", v2)
        assert reg.resolve("p@latest") == ("p", v2)
        assert reg.resolve(f"p@{v1}") == ("p", v1)
        assert reg.resolve(None) == ("default", reg.resolve("default")[1])
        with pytest.raises(ProgramNotFound):
            reg.resolve("ghost")
        with pytest.raises(ProgramNotFound):
            reg.resolve("p@000000000000")
        # exact-version addressing serves the OLD program after a publish
        with reg.lease(f"p@{v1}") as m:
            assert m.compute_coalesced([1]) == [11]
        with reg.lease("p") as m:
            assert m.compute_coalesced([1]) == [21]
    finally:
        master.pause()
        reg.close()


def test_lru_eviction_order(tmp_path):
    reg, master = seeded_registry(max_active=3)
    try:
        for name, src in (("a", ADD10), ("b", ADD20), ("c", ADD30)):
            reg.publish(name, tis=src)
        with reg.lease("a") as m:
            assert m.compute_coalesced([1]) == [11]
        with reg.lease("b") as m:
            assert m.compute_coalesced([1]) == [21]
        # active: default(pinned), a, b — at the cap of 3.  Touch a so b
        # is the LRU candidate, then activate c: b must be the eviction.
        with reg.lease("a") as m:
            pass
        with reg.lease("c") as m:
            assert m.compute_coalesced([1]) == [31]
        active = {f"{n}@{v}"[: len(n)] or n for n, v in reg.active_versions()}
        names = {n for n, _ in reg.active_versions()}
        assert names == {"default", "a", "c"}, active
        # the evicted program left a manifest-verified checkpoint behind
        vb = reg.resolve("b")[1]
        verify_checkpoint(reg._state_path("b", vb))
        # ... and the pinned default was never a candidate
        assert "default" in names
        # reactivating b works (and now evicts the new LRU, a)
        with reg.lease("b") as m:
            assert m.compute_coalesced([2]) == [22]
        assert {n for n, _ in reg.active_versions()} == {"default", "c", "b"}
    finally:
        master.pause()
        reg.close()


def test_eviction_restores_state_bit_identically():
    # batch=None: ONE instance, so the delay line's persistent state and
    # every value share it (a batched master round-robins instances,
    # which would scatter the continuation check across fresh replicas)
    reg, master = seeded_registry(max_active=4, batch=None)
    try:
        v = reg.publish("delay", tis=DELAY)["version"]
        with reg.lease("delay") as m:
            assert m.compute_coalesced([5]) == [0]
            assert m.compute_coalesced([6]) == [5]
        # evict: drain + durable checkpoint (manifest sidecar) + close
        assert reg.deactivate("delay")
        ckpt = reg._state_path("delay", v)
        verify_checkpoint(ckpt)  # the durability gate passes
        # bit-identical restore at the state level: a fresh master that
        # loads the eviction checkpoint holds EXACTLY the saved arrays
        fresh = MasterNode(
            Topology(node_info={"main": "program"},
                     programs={"main": DELAY}, **SMALL),
            chunk_steps=32, batch=None, engine="scan",
        )
        fresh.load_checkpoint(ckpt)
        snap = fresh.snapshot()
        with np.load(ckpt) as data:
            for field in snap._fields:
                if field in data:
                    np.testing.assert_array_equal(
                        np.asarray(getattr(snap, field)), data[field],
                        err_msg=field,
                    )
        fresh.close()
        # functional continuation: the delay line remembers its last
        # value across the eviction (fresh state would answer 0)
        with reg.lease("delay") as m:
            assert m.compute_coalesced([7]) == [6]
    finally:
        master.pause()
        reg.close()


def test_concurrent_upload_races():
    reg, master = seeded_registry()
    try:
        sources = [f"IN ACC\nADD {i}\nOUT ACC\n" for i in range(1, 9)]
        errors = []

        def upload(src):
            try:
                reg.publish("raced", tis=src)
            except Exception as e:  # pragma: no cover — the failure path
                errors.append(e)

        ts = [threading.Thread(target=upload, args=(s,)) for s in sources]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors
        info = reg.list_programs()["programs"]["raced"]
        assert len(info["versions"]) == len(sources)
        assert info["latest"] in info["versions"]
        # the alias landed on SOME upload; serving through it works and
        # matches that version's program
        with reg.lease("raced") as m:
            out = m.compute_coalesced([0])[0]
        assert 1 <= out <= 8
    finally:
        master.pause()
        reg.close()


def test_publish_over_seeded_program_rejected():
    reg, master = seeded_registry()
    try:
        with pytest.raises(RegistryError, match="seeded boot program"):
            reg.publish("default", tis=ADD10)
    finally:
        master.pause()
        reg.close()


def test_publish_compile_first_touches_nothing():
    reg, master = seeded_registry()
    try:
        v1 = reg.publish("p", tis=ADD10)["version"]
        with reg.lease("p") as m:
            assert m.compute_coalesced([1]) == [11]
        from misaka_tpu.tis.parser import TISParseError

        with pytest.raises(TISParseError):
            reg.publish("p", tis="FROB 1\n")
        # the bad upload changed nothing: same latest, engine serving
        assert reg.resolve("p")[1] == v1
        with reg.lease("p") as m:
            assert m.compute_coalesced([2]) == [12]
    finally:
        master.pause()
        reg.close()


def test_registry_persistence_across_restart(tmp_path):
    d = str(tmp_path / "programs")
    reg = ProgramRegistry(d, batch=2, engine="scan", chunk_steps=32,
                          caps=SMALL)
    v = reg.publish("keeper", tis=ADD10)["version"]
    with reg.lease("keeper") as m:
        assert m.compute_coalesced([1]) == [11]
    reg.close()  # checkpoints + closes the active engine
    reg2 = ProgramRegistry(d, batch=2, engine="scan", chunk_steps=32,
                           caps=SMALL)
    info = reg2.list_programs()["programs"]
    assert info["keeper"]["latest"] == v
    assert info["keeper"]["versions"][v]["checkpoint"]
    with reg2.lease("keeper") as m:  # revives from the shutdown checkpoint
        assert m.compute_coalesced([2]) == [12]
    reg2.close()


# --- the HTTP surface -------------------------------------------------------


@pytest.fixture(scope="module")
def reg_server():
    reg = make_registry()
    top = networks.add2(**SMALL)
    master = MasterNode(top, chunk_steps=32, batch=2, engine="scan")
    reg.seed("default", master, top)
    httpd = make_http_server(master, port=0, registry=reg)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    master.run()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", master, reg
    master.pause()
    reg.close()
    httpd.shutdown()


def post(base, path, data=None, headers=None, raw=None):
    body = raw if raw is not None else urllib.parse.urlencode(data or {}).encode()
    req = urllib.request.Request(
        base + path, data=body, method="POST", headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_http_upload_and_program_routes(reg_server):
    base, _, _ = reg_server
    status, body = post(base, "/programs", {"name": "web", "program": ADD10})
    assert status == 200, body
    out = json.loads(body)
    assert out["created"] and out["name"] == "web"
    # all three compute ops, program-addressed
    status, body = post(base, "/programs/web/compute", {"value": "1"})
    assert (status, json.loads(body)) == (200, {"value": 11})
    status, body = post(
        base, "/programs/web/compute_batch", {"values": "1 2", "spread": "1"}
    )
    assert json.loads(body) == {"values": [11, 12]}
    status, body = post(
        base, "/programs/web/compute_raw?spread=1",
        raw=np.asarray([3], "<i4").tobytes(),
    )
    assert np.frombuffer(body, "<i4").tolist() == [13]
    # version-pinned addressing
    status, body = post(
        base, f"/programs/web@{out['version']}/compute", {"value": "2"}
    )
    assert json.loads(body) == {"value": 12}


def test_http_legacy_routes_serve_default(reg_server):
    base, _, _ = reg_server
    status, body = post(base, "/compute", {"value": "5"})
    assert (status, json.loads(body)) == (200, {"value": 7})
    status, body = post(base, "/compute_batch", {"values": "1 2", "spread": "1"})
    assert json.loads(body) == {"values": [3, 4]}
    status, body = post(
        base, "/compute_raw?spread=1", raw=np.asarray([1, 2], "<i4").tobytes()
    )
    assert np.frombuffer(body, "<i4").tolist() == [3, 4]


def test_http_header_addressing(reg_server):
    base, _, _ = reg_server
    post(base, "/programs", {"name": "hdr", "program": ADD20})
    status, body = post(
        base, "/compute", {"value": "1"}, headers={"X-Misaka-Program": "hdr"}
    )
    assert json.loads(body) == {"value": 21}
    status, body = post(
        base, "/compute_raw?spread=1",
        raw=np.asarray([5], "<i4").tobytes(),
        headers={"X-Misaka-Program": "hdr"},
    )
    assert np.frombuffer(body, "<i4").tolist() == [25]


def test_http_unknown_program_typed_404(reg_server):
    base, _, _ = reg_server
    status, body = post(base, "/programs/ghost/compute", {"value": "1"})
    assert status == 404 and b"unknown program" in body
    status, body = post(
        base, "/compute", {"value": "1"},
        headers={"X-Misaka-Program": "ghost"},
    )
    assert status == 404 and b"unknown program" in body
    status, body = get(base, "/programs/ghost")
    assert status == 404
    # an unknown VERSION of a known program is typed too
    post(base, "/programs", {"name": "known", "program": ADD10})
    status, body = post(
        base, "/programs/known@ffffffffffff/compute", {"value": "1"}
    )
    assert status == 404 and b"no version" in body


def test_http_listing_and_status(reg_server):
    base, _, _ = reg_server
    post(base, "/programs", {"name": "listed", "program": ADD10})
    status, body = get(base, "/programs")
    listing = json.loads(body)
    assert "listed" in listing["programs"]
    assert listing["programs"]["default"]["pinned"]
    status, body = get(base, "/programs/listed")
    assert json.loads(body)["latest"]
    status, body = get(base, "/status")
    assert "programs" in json.loads(body)
    # GET on a compute route is the reference's method rejection
    status, body = get(base, "/programs/listed/compute")
    assert (status, body) == (405, b"method GET not allowed")


def test_http_bad_upload_400(reg_server):
    base, _, _ = reg_server
    status, body = post(base, "/programs", {"name": "bad", "program": "FROB"})
    assert status == 400 and b"not a valid instruction" in body
    status, body = post(base, "/programs", {"name": "bad/../evil",
                                            "program": ADD10})
    assert status == 400
    status, body = post(base, "/programs", {"name": "noform"})
    assert status == 400 and b"exactly one" in body
    # publishing over the seeded default is rejected, not swapped
    status, body = post(base, "/programs", {"name": "default",
                                            "program": ADD10})
    assert status == 400 and b"seeded boot program" in body


def test_http_hot_swap_under_concurrency(reg_server):
    base, _, _ = reg_server
    post(base, "/programs", {"name": "swapper", "program": ADD10})
    stop = threading.Event()
    failures = []
    odd = []

    def hammer():
        body = np.asarray([1, 2], "<i4").tobytes()
        while not stop.is_set():
            status, out = post(
                base, "/programs/swapper/compute_raw?spread=1", raw=body
            )
            if status != 200:
                failures.append((status, out))
                return
            got = np.frombuffer(out, "<i4").tolist()
            if got not in ([11, 12], [21, 22]):
                odd.append(got)
                return

    ts = [threading.Thread(target=hammer) for _ in range(8)]
    for t in ts:
        t.start()
    status, body = post(base, "/programs", {"name": "swapper",
                                            "program": ADD20})
    assert status == 200 and json.loads(body)["swapped"]
    import time as _time

    _time.sleep(0.3)
    stop.set()
    for t in ts:
        t.join()
    assert not failures and not odd
    # post-swap traffic serves the new version
    status, body = post(base, "/programs/swapper/compute", {"value": "1"})
    assert json.loads(body) == {"value": 21}


def test_metrics_carry_program_labels(reg_server):
    base, _, _ = reg_server
    post(base, "/programs", {"name": "metered", "program": ADD10})
    post(base, "/programs/metered/compute", {"value": "1"})
    post(base, "/compute", {"value": "1"})
    status, body = get(base, "/metrics")
    text = body.decode()
    assert 'misaka_program_requests_total{program="metered"}' in text
    assert 'misaka_program_requests_total{program="default"}' in text
    assert 'misaka_program_values_total{program="metered"}' in text


def test_client_helpers_and_pinned_session(reg_server):
    base, _, _ = reg_server
    c = MisakaClient(base)
    out = c.upload_program("cli", program=ADD10)
    assert out["name"] == "cli"
    dup = c.upload_program(
        "cli2",
        topology={"nodes": {"main": "program"}, "programs": {"main": ADD10},
                  "stack_cap": 16, "in_cap": 16, "out_cap": 16},
    )
    assert dup["version"] == out["version"]  # content-addressed dedup
    assert "cli" in c.list_programs()["programs"]
    assert c.program_info("cli")["latest"] == out["version"]
    pinned = MisakaClient(base, program="cli")
    assert int(pinned.compute(1)) == 11
    assert pinned.compute_raw([1, 2]).tolist() == [11, 12]
    assert pinned.compute_batch([3]).tolist() == [13]
    with pytest.raises(MisakaClientError) as exc:
        MisakaClient(base, program="ghost").compute(1)
    assert exc.value.status == 404
    c.close()
    pinned.close()


def test_serve_pass_span_carries_program_attr(reg_server):
    base, _, _ = reg_server
    post(base, "/programs", {"name": "traced", "program": ADD10})
    status, body = post(
        base, "/programs/traced/compute", {"value": "1"},
        headers={"X-Misaka-Trace": "prog-attr-test-1"},
    )
    assert status == 200
    status, body = get(base, "/debug/requests/prog-attr-test-1")
    tree = json.loads(body)
    spans = [s for s in tree["spans"] if s["name"] == "serve.pass"]
    assert spans and spans[0]["attrs"]["program"] == "traced"


# --- the compute plane ------------------------------------------------------


def test_plane_frames_route_per_program(tmp_path):
    from misaka_tpu.runtime import frontends

    reg, master = seeded_registry()
    plane_path = str(tmp_path / "plane.sock")
    plane = frontends.start_compute_plane(master, plane_path, registry=reg)
    client = frontends.PlaneClient(plane_path, conns=2)
    try:
        reg.publish("pl", tis=ADD10)
        # default and program frames interleaved from many threads: the
        # coalescer must keep frames per-program
        results = {}

        def worker(i):
            vals = np.asarray([i, i + 1], "<i4")
            prog = "pl" if i % 2 else None
            out = client.compute_raw(vals.tobytes(), program=prog)
            want = vals + (10 if i % 2 else 2)
            results[i] = np.frombuffer(out, "<i4").tolist() == want.tolist()

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(12)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(results.values()) and len(results) == 12
        # unknown program: the typed 404 crosses the plane
        with pytest.raises(frontends.PlaneError) as exc:
            client.compute_raw(np.asarray([1], "<i4").tobytes(),
                               program="ghost")
        assert exc.value.status == 404
    finally:
        client.close()
        plane.close()
        master.pause()
        reg.close()
