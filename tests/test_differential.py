"""Randomized differential testing: kernels vs the independent Python oracle.

Random TIS source programs (every opcode, random topologies) are run for a
fixed number of ticks through (a) the XLA superstep engine and (b) the fused
Pallas kernel (interpret mode), and compared field-by-field against the naive
sequential oracle.  Deadlocked programs are fine — state equality after T
ticks needs no liveness.  The generator emits SOURCE TEXT, so the parser and
lowering are inside the tested pipeline too.
"""


import numpy as np
import pytest

pytestmark = pytest.mark.slow  # fuzzed five-way differential — `make test-all` lane

from misaka_tpu.core import CompiledNetwork
from misaka_tpu.tis.lower import lower_program, pad_programs
from tests.oracle import Oracle

IN_CAP = OUT_CAP = 8
STACK_CAP = 4


def random_program(rng, lane_names, stack_names, length):
    lines = []
    # All four inbound ports as sources; lane_names includes the program's
    # own node, so self-sends (examples/running_total.json's trick) are
    # generated too.
    srcs = ["ACC", "NIL", "R0", "R1", "R2", "R3", str(rng.integers(-50, 50))]

    def src():
        return srcs[rng.integers(len(srcs))]

    for i in range(length):
        kind = rng.integers(12)
        if kind == 0:
            lines.append(rng.choice(["NOP", "SWP", "SAV", "NEG"]))
        elif kind == 1:
            lines.append(f"MOV {src()}, {rng.choice(['ACC', 'NIL'])}")
        elif kind == 2:
            tgt = rng.choice(lane_names)
            lines.append(f"MOV {src()}, {tgt}:R{rng.integers(4)}")
        elif kind == 3:
            lines.append(f"ADD {src()}")
        elif kind == 4:
            lines.append(f"SUB {src()}")
        elif kind == 5:
            target = int(rng.integers(length))
            op = rng.choice(["JMP", "JEZ", "JNZ", "JGZ", "JLZ"])
            lines.append((op, target))  # resolved to labels below
        elif kind == 6:
            lines.append(f"JRO {rng.integers(-3, 4)}")
        elif kind == 7 and stack_names:
            lines.append(f"PUSH {src()}, {rng.choice(stack_names)}")
        elif kind == 8 and stack_names:
            lines.append(f"POP {rng.choice(stack_names)}, {rng.choice(['ACC', 'NIL'])}")
        elif kind == 9:
            lines.append(f"IN {rng.choice(['ACC', 'NIL'])}")
        elif kind == 10:
            lines.append(f"OUT {src()}")
        else:
            lines.append("NOP")

    # Resolve jump targets into labels.
    out = []
    needed = {t for l in lines if isinstance(l, tuple) for t in [l[1]]}
    for i, l in enumerate(lines):
        prefix = f"l{i}: " if i in needed else ""
        text = f"{l[0]} l{l[1]}" if isinstance(l, tuple) else l
        out.append(prefix + text)
    return "\n".join(out)


def build_random_network(seed):
    rng = np.random.default_rng(seed)
    n_lanes = int(rng.integers(1, 5))
    n_stacks = int(rng.integers(0, 3))
    lane_names = [f"n{i}" for i in range(n_lanes)]
    stack_names = [f"s{i}" for i in range(n_stacks)]
    lane_ids = {name: i for i, name in enumerate(lane_names)}
    stack_ids = {name: i for i, name in enumerate(stack_names)}
    programs = [
        random_program(rng, lane_names, stack_names, int(rng.integers(1, 9)))
        for _ in lane_names
    ]
    lowered = [lower_program(p, lane_ids, stack_ids) for p in programs]
    code, lengths = pad_programs(lowered)
    inputs = rng.integers(-100, 100, size=6).tolist()
    return code, lengths, n_stacks, inputs, programs


def compare(seed, steps=48, fused=False, engine=None):
    code, lengths, n_stacks, inputs, programs = build_random_network(seed)
    net = CompiledNetwork(
        code=code,
        prog_len=lengths,
        num_stacks=max(1, n_stacks),
        stack_cap=STACK_CAP,
        in_cap=IN_CAP,
        out_cap=OUT_CAP,
        batch=128 if fused else None,
    )
    state = net.init_state()
    if fused:
        vals = np.zeros((128, IN_CAP), np.int32)
        vals[:, : len(inputs)] = inputs
        state = state._replace(
            in_buf=state.in_buf.at[:].set(vals), in_wr=state.in_wr + len(inputs)
        )
        state = net.fused_runner(steps, block_batch=128, interpret=True)(state)
        pick = lambda x: np.asarray(x)[0]
    else:
        state, _ = net.feed(state, inputs)
        state = net.run(state, steps, engine=engine)
        pick = np.asarray

    oracle = Oracle(code, lengths, max(1, n_stacks), STACK_CAP, IN_CAP, OUT_CAP)
    oracle.feed(inputs)
    oracle.run(steps)
    want = oracle.state_arrays()

    got = {
        "acc": pick(state.acc),
        "bak": pick(state.bak),
        "acc_hi": pick(state.acc_hi),
        "bak_hi": pick(state.bak_hi),
        "pc": pick(state.pc),
        "port_val": pick(state.port_val),
        "port_full": pick(state.port_full),
        "hold_val": pick(state.hold_val),
        "holding": pick(state.holding),
        "stack_top": pick(state.stack_top),
        "in_rd": pick(state.in_rd),
        "out_wr": pick(state.out_wr),
        "out_buf": pick(state.out_buf),
        "tick": pick(state.tick),
        "retired": pick(state.retired),
    }
    for key, want_v in want.items():
        if key == "stack_mem_used":
            # only compare live slots (dead slots may hold stale values)
            got_mem = pick(state.stack_mem)
            for s in range(want["stack_top"].shape[0]):
                top = int(want["stack_top"][s])
                np.testing.assert_array_equal(
                    got_mem[s, :top],
                    want_v[s, :top],
                    err_msg=f"seed {seed}: live stack slots diverged\n"
                    + "\n---\n".join(programs),
                )
            continue
        np.testing.assert_array_equal(
            got[key],
            want_v,
            err_msg=f"seed {seed}: field '{key}' diverged; programs:\n"
            + "\n---\n".join(programs),
        )


@pytest.mark.parametrize("seed", range(40))
def test_xla_kernel_matches_oracle(seed):
    compare(seed)


@pytest.mark.parametrize("seed", range(0, 40, 5))
def test_fused_kernel_matches_oracle(seed):
    compare(seed, fused=True)


@pytest.mark.parametrize("seed", range(0, 40, 3))
def test_compact_kernel_matches_oracle(seed):
    """The compact scatter-election kernel (core/routing.py) against the
    independent Python oracle — not merely against core/step.py (that
    equality is pinned by tests/test_scale.py); a shared misunderstanding
    between the two jitted kernels would still be caught here."""
    compare(seed, engine="compact")
