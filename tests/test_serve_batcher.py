"""The r8 serving plane: cross-request micro-batching + frontends.

Covers the ServeBatcher (exact FIFO pairing under concurrency, entry
splitting, epoch invalidation, the MISAKA_SERVE_BATCH=0 fallback), the
native partial-fill fast path (active-subset parity against a full-batch
pass), the HTTP robustness satellites (411/413, keep-alive
desynchronization), the pooled client transport, and the multi-process
frontend tier driven in-process (PlaneClient + ComputePlane + frontend
HTTP server threads — no subprocesses, so the lane stays fast).
"""

import http.client
import threading
import urllib.request

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.runtime.master import ComputeTimeout, MasterNode, make_http_server


def _master(batch=4, engine="scan", **kw):
    return MasterNode(
        networks.add2(in_cap=16, out_cap=16, stack_cap=16),
        chunk_steps=32, batch=batch, engine=engine, **kw,
    )


def _native_or_skip():
    from misaka_tpu.core import native_serve

    if not native_serve.available():
        pytest.skip("no C++ toolchain for the native engine")


# --- ServeBatcher correctness ----------------------------------------------


@pytest.mark.parametrize("batch", [None, 4])
def test_coalesced_exact_pairing_concurrent(batch):
    m = _master(batch=batch)
    m.run()
    try:
        results = {}

        def worker(i):
            rng = np.random.default_rng(i)
            out = []
            for _ in range(6):
                vals = rng.integers(-1000, 1000, size=int(rng.integers(1, 9)))
                got = m.compute_coalesced(vals.astype(np.int32))
                out.append(got == [int(v) + 2 for v in vals])
            results[i] = all(out)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(results.values()), results
    finally:
        m.pause()


def test_coalesced_large_entry_splits_across_passes():
    # bigger than the whole machine's one-refill capacity (4 slots x 16):
    # the scheduler must split it over multiple passes, order preserved
    m = _master(batch=4)
    m.run()
    try:
        vals = np.arange(500, dtype=np.int32)
        out = m.compute_coalesced(vals, timeout=60, return_array=True)
        np.testing.assert_array_equal(out, vals + 2)
    finally:
        m.pause()


def test_coalesced_empty_and_validation():
    m = _master(batch=2)
    try:
        assert m.compute_coalesced([]) == []
        with pytest.raises(ValueError):
            m.compute_coalesced([[1, 2], [3, 4]])
    finally:
        m.pause()


def test_serve_batch_disabled_falls_back(monkeypatch):
    monkeypatch.setenv("MISAKA_SERVE_BATCH", "0")
    m = _master(batch=2)
    assert m._batcher is None
    m.run()
    try:
        assert m.compute_coalesced([5, 6]) == [7, 8]  # compute_spread path
    finally:
        m.pause()


def test_reset_fails_inflight_request_promptly():
    # a paused network holds the request in flight; reset must fail it in
    # well under the request timeout (the _WIPED sentinel), and the next
    # request must compute cleanly (no stale pairing pollution)
    import time

    m = _master(batch=2)
    m.run()
    m.compute_coalesced([1])
    m.pause()
    errs = []

    def waiter():
        t0 = time.monotonic()
        try:
            m.compute_coalesced([1, 2, 3], timeout=20)
        except ComputeTimeout:
            errs.append(time.monotonic() - t0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.3)
    m.reset()
    t.join(10)
    assert errs and errs[0] < 5, errs
    m.run()
    try:
        assert m.compute_coalesced([9]) == [11]
    finally:
        m.pause()


def test_coalesced_on_native_engine():
    _native_or_skip()
    m = _master(batch=8, engine="native")
    m.run()
    try:
        results = {}

        def worker(i):
            vals = np.arange(i * 10, i * 10 + 7, dtype=np.int32)
            results[i] = m.compute_coalesced(vals, return_array=True)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(12)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for i in range(12):
            np.testing.assert_array_equal(
                results[i], np.arange(i * 10, i * 10 + 7) + 2
            )
    finally:
        m.pause()


# --- native partial fill ----------------------------------------------------


def test_native_pool_active_subset_parity():
    """A partial-fill pass must serve the active rows EXACTLY like a
    full-batch pass does, and leave the skipped rows' state untouched."""
    _native_or_skip()
    from misaka_tpu.core.native_serve import NativeServePool

    net = networks.add2(in_cap=16, out_cap=16, stack_cap=8).compile(batch=8)
    pool_a = NativeServePool(net, chunk_steps=64)
    pool_b = NativeServePool(net, chunk_steps=64)
    try:
        vals = np.zeros((8, 16), np.int32)
        counts = np.zeros((8,), np.int32)
        vals[2, :5] = np.arange(5)
        vals[5, :3] = np.arange(100, 103)
        counts[2], counts[5] = 5, 3
        active = np.array([2, 5], np.int32)
        sa, pa = pool_a.serve(net.init_state(), vals, counts, active=active)
        sb, pb = pool_b.serve(net.init_state(), vals, counts)
        # packed rows identical on the served rows; counters identical on
        # the skipped ones (freshly-initialized rings are all zeros)
        np.testing.assert_array_equal(pa[[2, 5]], pb[[2, 5]])
        np.testing.assert_array_equal(pa[:, :4], pb[:, :4])
        for f in ("acc", "pc", "in_rd", "in_wr", "out_rd", "out_wr"):
            np.testing.assert_array_equal(
                np.asarray(getattr(sa, f))[[2, 5]],
                np.asarray(getattr(sb, f))[[2, 5]],
                err_msg=f,
            )
        # skipped rows did not tick
        assert (np.asarray(sa.tick)[[0, 1, 3, 4, 6, 7]] == 0).all()
    finally:
        pool_a.close()
        pool_b.close()


def test_native_pool_active_must_cover_fed_rows():
    _native_or_skip()
    from misaka_tpu.core.native_serve import NativeServePool

    net = networks.add2(in_cap=16, out_cap=16, stack_cap=8).compile(batch=4)
    pool = NativeServePool(net, chunk_steps=32)
    try:
        vals = np.zeros((4, 16), np.int32)
        counts = np.zeros((4,), np.int32)
        counts[3] = 1
        with pytest.raises(ValueError, match="active must cover"):
            pool.serve(
                net.init_state(), vals, counts,
                active=np.array([0], np.int32),
            )
        with pytest.raises(ValueError, match="strictly increasing"):
            pool.serve(
                net.init_state(), vals, counts,
                active=np.array([3, 3], np.int32),
            )
    finally:
        pool.close()


# --- HTTP surface: robustness + keep-alive ---------------------------------


@pytest.fixture
def served():
    m = _master(batch=4)
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield m, httpd.server_address[1]
    finally:
        m.pause()
        httpd.shutdown()


def test_compute_raw_411_and_413(served, monkeypatch):
    m, port = served
    m.run()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    # missing Content-Length: 411 (and the server closes the connection)
    conn.putrequest("POST", "/compute_raw?spread=1")
    conn.endheaders()
    resp = conn.getresponse()
    assert resp.status == 411
    resp.read()
    conn.close()
    # oversized declared body: 413 against the MISAKA_MAX_BODY cap
    monkeypatch.setenv("MISAKA_MAX_BODY", "1024")
    httpd2 = make_http_server(m, port=0)
    threading.Thread(target=httpd2.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", httpd2.server_address[1], timeout=10
        )
        conn.putrequest("POST", "/compute_raw?spread=1")
        conn.putheader("Content-Length", "2048")
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 413
        assert b"MISAKA_MAX_BODY" in resp.read()
        conn.close()
    finally:
        httpd2.shutdown()


def test_keep_alive_survives_error_responses(served):
    """Early-return error paths must consume the request body: on a
    keep-alive connection an unread body desynchronizes every later
    request (found by the r8 pooled client; urllib's Connection: close
    had been masking it)."""
    m, port = served  # network NOT running: /compute answers 400
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("POST", "/compute", b"value=1")
    r = conn.getresponse()
    assert r.status == 400 and b"not running" in r.read()
    # same connection must still speak clean HTTP
    m.run()
    conn.request("POST", "/compute", b"value=5")
    r = conn.getresponse()
    assert r.status == 200 and b'"value": 7' in r.read()
    # raw lane over the same connection too
    vals = np.arange(8, dtype=np.int32)
    conn.request("POST", "/compute_raw?spread=1", vals.astype("<i4").tobytes())
    r = conn.getresponse()
    assert r.status == 200
    np.testing.assert_array_equal(
        np.frombuffer(r.read(), dtype="<i4"), vals + 2
    )
    conn.close()


def test_fast_parser_matches_stock(served):
    """The serving-plane parser and the stock parser must answer the
    byte-compatible routes identically (urllib exercises close-mode,
    http.client exercises keep-alive)."""
    m, port = served
    m.run()
    base = f"http://127.0.0.1:{port}"
    req = urllib.request.Request(
        base + "/compute", data=b"value=3", method="POST"
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.read() == b'{"value": 5}\n'
    with urllib.request.urlopen(base + "/status", timeout=10) as resp:
        assert b'"running": true' in resp.read()


# --- pooled client ----------------------------------------------------------


def test_client_pools_and_reconnects(served):
    from misaka_tpu.client import MisakaClient

    m, port = served
    m.run()
    client = MisakaClient(f"http://127.0.0.1:{port}", timeout=15)
    assert client.compute(1) == 3
    assert len(client._pool) == 1  # connection returned to the pool
    pooled = client._pool[0]
    assert client.compute(2) == 4
    assert client._pool[0] is pooled  # and reused
    # a dead pooled socket must reconnect cleanly (shutdown produces the
    # EPIPE/RemoteDisconnected shape a server-side drop produces; a
    # garbled mid-response failure must NOT retry — see client._request)
    import socket as _socket

    pooled.sock.shutdown(_socket.SHUT_RDWR)
    assert client.compute(3) == 5
    out = client.compute_raw(np.arange(16, dtype=np.int32))
    np.testing.assert_array_equal(out, np.arange(16) + 2)
    client.close()
    assert client._pool == []


# --- the frontend tier (in-process) ----------------------------------------


@pytest.fixture
def frontend(tmp_path):
    from misaka_tpu.runtime import frontends

    m = _master(batch=4)
    engine_httpd = make_http_server(m, port=0)
    threading.Thread(target=engine_httpd.serve_forever, daemon=True).start()
    plane_path = str(tmp_path / "plane.sock")
    plane = frontends.start_compute_plane(m, plane_path)
    fe = frontends.make_frontend_server(
        0, f"http://127.0.0.1:{engine_httpd.server_address[1]}",
        plane_path, plane_conns=2,
    )
    threading.Thread(target=fe.serve_forever, daemon=True).start()
    try:
        yield m, fe.server_address[1]
    finally:
        m.pause()
        fe.shutdown()
        plane.close()
        engine_httpd.shutdown()


def test_frontend_compute_routes_and_proxy(frontend):
    m, port = frontend
    m.run()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    # hot raw lane via the compute plane
    vals = np.arange(32, dtype=np.int32)
    conn.request("POST", "/compute_raw?spread=1", vals.astype("<i4").tobytes())
    r = conn.getresponse()
    assert r.status == 200
    np.testing.assert_array_equal(
        np.frombuffer(r.read(), dtype="<i4"), vals + 2
    )
    # hot scalar lane, byte-compatible body
    conn.request("POST", "/compute", b"value=5")
    r = conn.getresponse()
    assert r.status == 200 and r.read() == b'{"value": 7}\n'
    # proxied routes reach the engine
    conn.request("GET", "/status")
    r = conn.getresponse()
    assert r.status == 200 and b'"running": true' in r.read()
    conn.request("GET", "/healthz")
    r = conn.getresponse()
    assert r.status == 200 and b'"ok": true' in r.read()
    # proxied lifecycle: pause through the public port
    conn.request("POST", "/pause", b"")
    r = conn.getresponse()
    assert r.status == 200 and r.read() == b"Success"
    assert not m.is_running
    # error shape for the raw lane when paused (exact route body)
    conn.request("POST", "/compute_raw?spread=1", vals.astype("<i4").tobytes())
    r = conn.getresponse()
    assert r.status == 400 and b"network is not running" in r.read()
    conn.close()


def test_frontend_411_and_spread0_proxy(frontend):
    m, port = frontend
    m.run()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    conn.putrequest("POST", "/compute_raw?spread=1")
    conn.endheaders()
    r = conn.getresponse()
    assert r.status == 411
    r.read()
    conn.close()
    # spread=0 (pinned single-instance FIFO) proxies to the engine
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    vals = np.arange(8, dtype=np.int32)
    conn.request("POST", "/compute_raw?spread=0", vals.astype("<i4").tobytes())
    r = conn.getresponse()
    assert r.status == 200
    np.testing.assert_array_equal(
        np.frombuffer(r.read(), dtype="<i4"), vals + 2
    )
    conn.close()


# --- metrics ----------------------------------------------------------------


def test_serve_scheduler_metrics_move():
    from misaka_tpu.utils import metrics

    def snap():
        return metrics.parse_text(metrics.render())

    before = snap()
    m = _master(batch=4)
    m.run()
    try:
        m.compute_coalesced(list(range(10)))
    finally:
        m.pause()
    delta = metrics.delta(before, snap())
    assert delta.get("misaka_serve_passes_total", 0) >= 1
    assert delta.get("misaka_serve_coalesced_values_sum", 0) >= 10
    assert delta.get("misaka_serve_queue_delay_seconds_count", 0) >= 1
