"""The r12 continuous profiler (utils/sampler.py).

Samples land with thread-rooted folded stacks, the aggregate decays and
stays bounded, the /debug/flamegraph route serves JSON + the
self-contained HTML viewer, and the jax-profiler 409 carries
active-capture info (the satellite guard).
"""

import http.client
import json
import threading
import time

import pytest

from misaka_tpu.utils.sampler import StackSampler
from misaka_tpu.utils import sampler


def test_samples_capture_busy_thread():
    s = StackSampler(hz=200)
    stop = threading.Event()

    def busy():
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=busy, name="sampler-busy-probe")
    t.start()
    s.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            stacks, samples = s.snapshot()
            if any(k.startswith("sampler-busy-probe;") for k in stacks):
                break
            time.sleep(0.05)
        stacks, samples = s.snapshot()
        assert samples > 0
        hits = [k for k in stacks if k.startswith("sampler-busy-probe;")]
        assert hits, sorted(stacks)[:5]
        # frames read leaf-last with function (file) context — no line
        # numbers: one function is one label-cache entry, which is what
        # keeps the sample walk allocation-free per frame
        assert "busy (" in hits[0]
    finally:
        stop.set()
        s.stop()
        t.join()
    assert not s.running


def test_decay_halves_and_prunes():
    s = StackSampler(hz=1, decay_s=0.01)
    with s._lock:
        s._stacks = {"keep;me": 8.0, "prune;me": 1.0}
        s._last_decay = time.monotonic() - 10  # decay is due NOW
    s._sample_once(skip_ident=threading.get_ident())
    stacks, _ = s.snapshot()
    assert "prune;me" not in stacks  # 0.5 < 1 pruned
    assert 3.9 <= stacks["keep;me"] <= 5.1  # halved (+ maybe a live hit)


def test_bounded_stacks():
    s = StackSampler(hz=1, max_stacks=16)
    with s._lock:
        for i in range(16):
            s._stacks[f"prefill;{i}"] = 1.0
    # several sampling passes with the cap exhausted: every NEW stack
    # shape folds into "(other)" instead of growing the dict

    def busy(n):
        t_end = time.monotonic() + 0.1
        while time.monotonic() < t_end:
            pass

    ts = [
        threading.Thread(target=busy, args=(i,), name=f"cap-probe-{i}")
        for i in range(4)
    ]
    for t in ts:
        t.start()
    for _ in range(5):
        s._sample_once(skip_ident=0)
    for t in ts:
        t.join()
    stacks, _ = s.snapshot()
    assert len(stacks) <= 16 + 1  # the cap + the "(other)" bucket
    assert stacks.get("(other)", 0) > 0


def test_folded_format_and_payload():
    s = StackSampler(hz=1)
    with s._lock:
        s._stacks = {"a;b;c": 5.0, "a;d": 2.0}
        s._samples = 7
    folded = s.folded()
    assert folded.splitlines() == ["a;b;c 5", "a;d 2"]
    p = s.payload()
    assert p["samples"] == 7 and p["distinct_stacks"] == 2
    assert p["stacks"]["a;b;c"] == 5.0


def test_flamegraph_route_json_and_html():
    import numpy as np

    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    m = MasterNode(
        networks.add2(in_cap=16, out_cap=16, stack_cap=16),
        chunk_steps=32, batch=4,
    )
    httpd = make_http_server(m, port=0)  # starts the global sampler
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    m.run()
    try:
        assert sampler.get() is not None and sampler.get().running
        m.compute_coalesced(np.arange(8, dtype=np.int32))
        time.sleep(0.1)  # a few sampling periods
        conn = http.client.HTTPConnection(
            "127.0.0.1", httpd.server_address[1], timeout=15
        )
        conn.request("GET", "/debug/flamegraph")
        r = conn.getresponse()
        body = json.loads(r.read())
        assert r.status == 200
        assert body["running"] is True and body["rate_hz"] > 0
        assert isinstance(body["stacks"], dict)
        conn.request("GET", "/debug/flamegraph?html=1")
        r = conn.getresponse()
        html = r.read().decode()
        conn.close()
        assert r.status == 200
        assert r.getheader("Content-Type").startswith("text/html")
        assert "<script>" in html and "misaka continuous profiler" in html
    finally:
        m.pause()
        httpd.shutdown()


def test_duty_cycle_governor():
    """A sample whose measured cost would blow the budget stretches the
    period — an always-on profiler must never become the workload."""
    s = StackSampler(hz=67, budget=0.02)
    assert s._current_period() == pytest.approx(1 / 67.0)
    s._cost_ema = 0.005  # 5ms samples at 2% budget -> >=0.25s period
    assert s._current_period() == pytest.approx(0.25)
    p = s.payload()
    assert p["effective_hz"] == pytest.approx(4.0)
    assert p["sample_cost_us"] == pytest.approx(5000.0)


def test_parked_thread_fold_cache():
    """A thread parked at the same leaf instruction between samples is
    served from the fold cache (no walk); the cache prunes dead idents."""
    s = StackSampler(hz=1)
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="park-probe")
    t.start()
    try:
        time.sleep(0.05)
        s._sample_once(skip_ident=threading.get_ident())
        hit = s._fold_cache.get(t.ident)
        assert hit is not None and "park-probe" in hit[2]
        s._sample_once(skip_ident=threading.get_ident())
        assert s._fold_cache[t.ident][2] == hit[2]
        stacks, _ = s.snapshot()
        parked = [k for k in stacks if k.startswith("park-probe;")]
        assert parked and stacks[parked[0]] >= 2  # both samples counted
    finally:
        stop.set()
        t.join()


def test_kill_switch(monkeypatch):
    assert not sampler.enabled({"MISAKA_SAMPLER": "0"})
    assert sampler.ensure_started({"MISAKA_SAMPLER": "0"}) is None


def test_profiler_409_carries_active_info():
    import time as _time

    from misaka_tpu.utils.profiling import Profiler, ProfilerError

    p = Profiler()
    assert p.active() is None
    # simulate an in-flight capture without touching jax's global state
    # (wall stamp for display, monotonic for the elapsed math — MSK005)
    p._active_dir = "/tmp/some-capture"
    p._started_unix = _time.time() - 42
    p._started_mono = _time.monotonic() - 42
    info = p.active()
    assert info["dir"] == "/tmp/some-capture" and info["running_s"] >= 42
    with pytest.raises(ProfilerError) as e:
        p.start("/tmp/another")
    msg = str(e.value)
    assert "/tmp/some-capture" in msg and "/profile/stop" in msg
