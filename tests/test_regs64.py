"""64-bit local registers (reference parity): acc/bak past the int32 wall.

The reference's acc/bak are Go `int` — 64-bit (program.go:27-33); only the
wire truncates to sint32 (messenger.proto:34-41).  Rounds 1-2 were int32
end-to-end, so a program whose ACC legitimately passes 2^31 BRANCHED
differently than the Go binary without touching the wire (VERDICT r2
missing #2).  These tests pin the closed gap across every implementation:
the XLA scan engine, the Pallas fused kernel, the Python oracle, the C++
native interpreter, and the per-process gRPC cluster — all carrying 64-bit
registers (core/regs64.py hi/lo planes on device; int64 on hosts) with
truncation exactly at the wire.
"""


import numpy as np
import pytest

pytestmark = pytest.mark.slow  # 64-bit fuzz (four-way differential) — `make test-all` lane

from misaka_tpu.core import cinterp
from misaka_tpu.runtime.topology import Topology
from tests.oracle import Oracle

IN_CAP = OUT_CAP = 16
STACK_CAP = 16

# ACC passes 2^31 via two ADDs, then branches: 64-bit sees a positive value
# (JLZ not taken) and OUT emits the wire-truncated low word; an int32
# implementation would see a wrapped negative and take the branch.
OVERFLOW_BRANCH = (
    "IN ACC\n"
    "ADD 2000000000\n"
    "ADD 2000000000\n"
    "JLZ neg\n"
    "OUT ACC\n"
    "JMP end\n"
    "neg: OUT 0\n"
    "end: NOP\n"
)


def overflow_branch_expect(v):
    # low word of v + 4e9 (the wire truncation of the 64-bit acc)
    return int(np.int64(v + 4_000_000_000).astype(np.int32))


# NEG of int32-min: 64-bit gives +2^31 (positive -> JGZ taken, OUT emits the
# low word 0x80000000 = int32 min); int32 NEG(min) stays min (negative).
NEG_MIN = (
    "IN ACC\n"
    "NEG\n"
    "JGZ pos\n"
    "OUT 0\n"
    "JMP end\n"
    "pos: OUT ACC\n"
    "end: NOP\n"
)

# JRO with a 64-bit positive offset (~4e9) must clamp FORWARD to the last
# line; an int32 implementation sees a negative offset and clamps to 0
# (looping back to a parked IN: no output ever).
# NOTE: no trailing newline — a trailing newline lowers to a parity NOP
# line (YAML block-scalar parity) and the JRO clamp must land on OUT 2.
JRO_HUGE = (
    "IN ACC\n"
    "ADD 2000000000\n"
    "ADD 2000000000\n"
    "JRO ACC\n"
    "OUT 1\n"
    "OUT 2"
)

CASES = [
    ("overflow_branch", OVERFLOW_BRANCH, [5, -7, 123],
     [overflow_branch_expect(v) for v in [5, -7, 123]]),
    ("neg_min", NEG_MIN, [-(2**31)], [-(2**31)]),
    ("jro_huge", JRO_HUGE, [1], [2]),
]


def single_lane_top(program):
    return Topology(
        node_info={"solo": "program"},
        programs={"solo": program},
        in_cap=IN_CAP, out_cap=OUT_CAP, stack_cap=STACK_CAP,
    )


@pytest.mark.parametrize("name,program,inputs,expect", CASES)
def test_scan_engine(name, program, inputs, expect):
    net = single_lane_top(program).compile()
    state, outs = net.compute_stream(
        net.init_state(), inputs, expected=len(expect)
    )
    assert outs == expect, name


@pytest.mark.parametrize("name,program,inputs,expect", CASES)
def test_fused_kernel(name, program, inputs, expect):
    net = single_lane_top(program).compile(batch=128)
    vals = np.tile(np.asarray(inputs, np.int32), (128, 1))
    state = net.init_state()
    state = state._replace(
        in_buf=state.in_buf.at[:, : len(inputs)].set(vals),
        in_wr=state.in_wr + len(inputs),
    )
    out = net.fused_runner(64, block_batch=128, interpret=True)(state)
    np.testing.assert_array_equal(np.asarray(out.out_wr), len(expect))
    np.testing.assert_array_equal(
        np.asarray(out.out_buf)[:, : len(expect)],
        np.tile(np.asarray(expect, np.int32), (128, 1)),
        err_msg=name,
    )


@pytest.mark.parametrize("name,program,inputs,expect", CASES)
def test_python_oracle(name, program, inputs, expect):
    net = single_lane_top(program).compile()
    oracle = Oracle(net.code, net.prog_len, 1, STACK_CAP, IN_CAP, OUT_CAP)
    oracle.feed(inputs)
    oracle.run(64)
    st = oracle.state_arrays()
    assert list(st["out_buf"][: len(expect)]) == expect, name
    assert int(st["out_wr"]) == len(expect)


@pytest.mark.parametrize("name,program,inputs,expect", CASES)
def test_native_interpreter(name, program, inputs, expect):
    if not cinterp.available():
        pytest.skip("native interpreter unavailable")
    net = single_lane_top(program).compile()
    with cinterp.NativeInterpreter(
        net.code, net.prog_len, 1, STACK_CAP, IN_CAP, OUT_CAP
    ) as n:
        assert n.feed(inputs) == len(inputs)
        n.run(64)
        assert n.drain() == expect, name


@pytest.mark.parametrize("name,program,inputs,expect", CASES)
def test_per_process_cluster(name, program, inputs, expect):
    pytest.importorskip("grpc")
    from tests.test_cross_mode import run_cluster

    outs = run_cluster(
        {"solo": "program"}, {"solo": program}, inputs, len(expect)
    )
    assert outs == expect, name


# --- randomized four-way differential past the int32 wall -------------------

BIG_OPS = [
    "ADD 2000000000", "ADD 1999999999", "SUB 2000000000", "SUB 1500000007",
    "NEG", "SAV", "SWP", "ADD 3", "SUB 1",
]


@pytest.mark.parametrize("seed", range(20))
def test_random_big_arithmetic_four_way(seed):
    """Random big-magnitude ADD/SUB/NEG/SAV/SWP streams: scan engine, fused
    kernel, Python oracle, and C++ interpreter must agree on the FULL 64-bit
    register file (hi and lo planes) and the truncated output stream."""
    rng = np.random.default_rng(seed)
    body = "\n".join(rng.choice(BIG_OPS) for _ in range(10))
    program = f"IN ACC\n{body}\nOUT ACC\n"
    inputs = rng.integers(-(2**31), 2**31, size=4).tolist()
    net = single_lane_top(program).compile()
    steps = 64

    oracle = Oracle(net.code, net.prog_len, 1, STACK_CAP, IN_CAP, OUT_CAP)
    oracle.feed(inputs)
    oracle.run(steps)
    want = oracle.state_arrays()

    state = net.init_state()
    state, _ = net.feed(state, inputs)
    state = net.run(state, steps)
    for key in ("acc", "bak", "acc_hi", "bak_hi", "pc", "out_wr", "out_buf"):
        np.testing.assert_array_equal(
            np.asarray(getattr(state, key)), want[key],
            err_msg=f"seed {seed} scan field {key}\n{program}",
        )

    netb = single_lane_top(program).compile(batch=128)
    sb = netb.init_state()
    sb = sb._replace(
        in_buf=sb.in_buf.at[:, : len(inputs)].set(
            np.tile(np.asarray(inputs, np.int32), (128, 1))
        ),
        in_wr=sb.in_wr + len(inputs),
    )
    outb = netb.fused_runner(steps, block_batch=128, interpret=True)(sb)
    for key in ("acc", "bak", "acc_hi", "bak_hi", "pc", "out_wr", "out_buf"):
        np.testing.assert_array_equal(
            np.asarray(getattr(outb, key))[0], want[key],
            err_msg=f"seed {seed} fused field {key}\n{program}",
        )

    if cinterp.available():
        with cinterp.NativeInterpreter(
            net.code, net.prog_len, 1, STACK_CAP, IN_CAP, OUT_CAP
        ) as n:
            n.feed(inputs)
            n.run(steps)
            got = n.state_arrays()
            for key in ("acc", "bak", "acc_hi", "bak_hi", "pc", "out_wr",
                        "out_buf"):
                np.testing.assert_array_equal(
                    got[key], want[key],
                    err_msg=f"seed {seed} native field {key}\n{program}",
                )
