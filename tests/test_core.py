"""Kernel semantics tests: every opcode class, stall rule, and arbiter.

Single-node cases mirror the reference's documented instruction semantics
(program.go:225-432); multi-node cases pin the rendezvous/backpressure rules
(program.go:160-175, getFromSrc :441-468; stack.go:95-155) under the
deterministic superstep discipline documented in core/step.py.
"""

import numpy as np
import pytest

from misaka_tpu.core import CompiledNetwork
from misaka_tpu.tis.lower import lower_program, pad_programs


def build(programs: dict[str, str], stacks: list[str] | None = None, **kw) -> CompiledNetwork:
    stacks = stacks or []
    lane_ids = {name: i for i, name in enumerate(programs)}
    stack_ids = {name: i for i, name in enumerate(stacks)}
    lowered = [lower_program(p, lane_ids, stack_ids) for p in programs.values()]
    code, lengths = pad_programs(lowered)
    return CompiledNetwork(code=code, prog_len=lengths, num_stacks=max(1, len(stacks)), **kw)


def run_collect(programs, stacks, inputs, **kw):
    net = build(programs, stacks, **kw)
    state = net.init_state()
    state, outs = net.compute_stream(state, inputs, max_steps=100_000)
    return outs


# --- single-lane local semantics -------------------------------------------

def test_acc_arithmetic_pipeline():
    # MOV/ADD/SUB/NEG over an input stream.
    prog = "IN ACC\nADD 5\nSUB 2\nNEG\nOUT ACC"
    assert run_collect({"n": prog}, [], [0, 10, -4]) == [-3, -13, 1]


def test_sav_swp():
    # acc=in+1, bak=acc (SAV), acc=-acc (NEG), SWP -> acc=in+1 again
    prog = "IN ACC\nADD 1\nSAV\nNEG\nSWP\nOUT ACC"
    assert run_collect({"n": prog}, [], [41]) == [42]


def test_swp_swaps_both_ways():
    # bak starts 0: SWP gives acc=0, bak=in; second SWP restores.
    prog = "IN ACC\nSWP\nSWP\nOUT ACC"
    assert run_collect({"n": prog}, [], [7]) == [7]


def test_mov_val_local_and_nil_discard():
    prog = "IN NIL\nMOV 9, ACC\nMOV 5, NIL\nOUT ACC"
    assert run_collect({"n": prog}, [], [123]) == [9]


def test_nil_reads_as_zero():
    prog = "IN ACC\nADD NIL\nMOV NIL, ACC\nSUB 1\nOUT ACC"
    # ADD NIL is +0; MOV NIL, ACC zeroes; SUB 1 -> -1
    assert run_collect({"n": prog}, [], [55]) == [-1]


def test_out_immediate():
    prog = "IN NIL\nOUT 77"
    assert run_collect({"n": prog}, [], [0, 0]) == [77, 77]


def test_program_wraps_around():
    # After OUT (last line), PC wraps to line 0 (program.go:429).
    prog = "IN ACC\nADD 1\nOUT ACC"
    assert run_collect({"n": prog}, [], [1, 2, 3]) == [2, 3, 4]


# --- jumps ------------------------------------------------------------------

def test_jez_taken_and_not_taken():
    prog = (
        "IN ACC\n"
        "JEZ zero\n"
        "OUT 1\n"
        "JMP end\n"
        "zero: OUT 0\n"
        "end: NOP"
    )
    assert run_collect({"n": prog}, [], [0, 5, 0]) == [0, 1, 0]


def test_jnz_jgz_jlz():
    prog = (
        "IN ACC\n"
        "JGZ pos\n"
        "JLZ neg\n"
        "OUT 0\n"
        "JMP end\n"
        "pos: OUT 1\n"
        "JMP end\n"
        "neg: OUT -1\n"
        "end: NOP"
    )
    assert run_collect({"n": prog}, [], [3, -3, 0]) == [1, -1, 0]


def test_jmp_skips_pc_increment():
    # Tight self-loop at a label: JMP back to IN forever.
    prog = "loop: IN ACC\nOUT ACC\nJMP loop\nOUT 999"  # OUT 999 unreachable
    assert run_collect({"n": prog}, [], [4, 5]) == [4, 5]


def test_jro_forward_and_clamp():
    # JRO 2 skips the next line; JRO 99 clamps to the last line
    # (program.go:354, utils.IntClamp).
    prog = "IN ACC\nJRO 2\nOUT 111\nOUT ACC\nJRO 99\nNOP"
    # flow: IN, JRO 2 -> line 3 (OUT ACC), JRO 99 -> clamp to line 5 (NOP), wrap
    assert run_collect({"n": prog}, [], [8, 9]) == [8, 9]


def test_jro_negative_clamps_to_zero():
    prog = "IN ACC\nOUT ACC\nJRO -99"
    assert run_collect({"n": prog}, [], [1, 2]) == [1, 2]


def test_jro_src_uses_acc():
    # ACC=2 -> JRO ACC jumps 2 lines forward from the JRO line.
    prog = "IN ACC\nJRO ACC\nOUT 111\nOUT 222\nJMP 0".replace("JMP 0", "JRO -99")
    # inputs fixed at 2: JRO ACC from line1 -> line3 -> OUT 222
    assert run_collect({"n": prog}, [], [2, 2]) == [222, 222]


# --- multi-lane port rendezvous --------------------------------------------

def test_two_lane_ping_pong():
    # a sends in+1 to b, b adds 1, sends back; a outputs.
    progs = {
        "a": "IN ACC\nADD 1\nMOV ACC, b:R0\nMOV R0, ACC\nOUT ACC",
        "b": "MOV R0, ACC\nADD 1\nMOV ACC, a:R0",
    }
    assert run_collect(progs, [], [5, 10]) == [7, 12]


def test_port_read_blocks_until_send():
    # b reads R1 before anyone sends: must stall, not read garbage.
    progs = {
        "a": "IN ACC\nNOP\nNOP\nNOP\nMOV ACC, b:R1",
        "b": "MOV R1, ACC\nOUT ACC",
    }
    assert run_collect(progs, [], [33]) == [33]


def test_cap1_port_backpressure():
    # a tries to send twice before b consumes; the second send must park
    # until b's read frees the buffer (Send handler blocking, program.go:160-175).
    progs = {
        "a": "IN ACC\nMOV ACC, b:R0\nMOV 100, b:R0\nIN NIL",
        "b": "NOP\nNOP\nNOP\nNOP\nNOP\nMOV R0, ACC\nOUT ACC\nMOV R0, ACC\nOUT ACC",
    }
    # First output is the original value, second is 100 — order preserved.
    assert run_collect(progs, [], [6, 0]) == [6, 100]


def test_send_arbitration_lowest_lane_wins():
    # Lanes a and b both send to c:R0 on the same tick; a (lower index) must
    # win, b parks and delivers second.
    progs = {
        "a": "MOV 1, c:R0\nJRO 0",   # JRO 0 self-loop: park forever after send
        "b": "MOV 2, c:R0\nJRO 0",
        "c": "MOV R0, ACC\nOUT ACC\nMOV R0, ACC\nOUT ACC\nJRO 0",
    }
    net = build(progs, [])
    state = net.init_state()
    state = net.run(state, 64)
    _, outs = net.drain(state)
    assert outs == [1, 2]


def test_port_forward_consume_then_send():
    # `MOV R0, n:R0` with R0 full must complete: the reference CONSUMES the
    # port (getFromSrc) before the send blocks, so the slot frees itself.
    # An atomic src+dst commit would deadlock here (hold-latch regression).
    prog = "IN ACC\nMOV ACC, n:R0\nMOV R0, n:R0\nMOV R0, ACC\nOUT ACC"
    assert run_collect({"n": prog}, [], [64]) == [64]


def test_mutual_port_swap_makes_progress():
    # Both lanes' R0 full, both run `MOV R0, other:R0`: each consumes first,
    # so both sends find free slots — values swap instead of deadlocking.
    # (The Go reference makes progress here for the same reason: getFromSrc
    # drains the channel before the send RPC blocks.)
    progs = {
        "a": "MOV R0, b:R0\nMOV R0, ACC\nOUT ACC\nJRO 0",
        "b": "MOV R0, a:R0\nMOV R0, ACC\nOUT ACC\nJRO 0",
    }
    net = build(progs, [])
    state = net.init_state()
    state = state._replace(
        port_full=state.port_full.at[:, 0].set(True),
        port_val=state.port_val.at[0, 0].set(7).at[1, 0].set(8),
    )
    state = net.run(state, 32)
    _, outs = net.drain(state)
    assert outs == [8, 7]  # swapped; a (lane 0) wins the OUT arbiter first


def test_parked_sender_port_refills_behind_latch():
    # After a consumes R0 into its latch and parks on a full destination, a
    # second value can land in a's R0 behind it (Go: channel refills while the
    # handler blocks in the send RPC).
    progs = {
        # a forwards two values to b; b only consumes after a delay
        "a": "MOV R0, b:R0\nMOV R0, b:R0\nJRO 0",
        "b": "NOP\nNOP\nNOP\nNOP\nNOP\nNOP\nMOV R0, ACC\nOUT ACC\nMOV R0, ACC\nOUT ACC\nJRO 0",
        "c": "MOV 1, a:R0\nMOV 2, a:R0\nJRO 0",
    }
    net = build(progs, [])
    state = net.init_state()
    state = net.run(state, 64)
    _, outs = net.drain(state)
    assert outs == [1, 2]


def test_self_send():
    # A lane may send to its own port (the reference would self-dial).
    prog = "IN ACC\nMOV ACC, n:R2\nMOV R2, ACC\nOUT ACC"
    assert run_collect({"n": prog}, [], [13]) == [13]


# --- stacks -----------------------------------------------------------------

def test_stack_push_pop_roundtrip():
    progs = {"n": "IN ACC\nPUSH ACC, st\nMOV 0, ACC\nPOP st, ACC\nOUT ACC"}
    assert run_collect(progs, ["st"], [17, -4]) == [17, -4]


def test_stack_is_lifo():
    progs = {
        "n": (
            "IN ACC\nPUSH ACC, st\n"
            "IN ACC\nPUSH ACC, st\n"
            "POP st, ACC\nOUT ACC\n"
            "POP st, ACC\nOUT ACC"
        )
    }
    assert run_collect(progs, ["st"], [1, 2, 3, 4]) == [2, 1, 4, 3]


def test_pop_blocks_until_push():
    # b pops before a pushes; must park (waitPop, stack.go:133-155).
    progs = {
        "a": "IN ACC\nNOP\nNOP\nNOP\nNOP\nPUSH ACC, st\nIN NIL",
        "b": "POP st, ACC\nOUT ACC",
    }
    assert run_collect(progs, ["st"], [21]) == [21]


def test_push_immediate_and_pop_nil():
    progs = {"n": "IN NIL\nPUSH 55, st\nPOP st, NIL\nPUSH 66, st\nPOP st, ACC\nOUT ACC"}
    assert run_collect(progs, ["st"], [0]) == [66]


def test_two_stacks_independent():
    progs = {
        "n": (
            "IN ACC\nPUSH ACC, s1\nIN ACC\nPUSH ACC, s2\n"
            "POP s1, ACC\nOUT ACC\nPOP s2, ACC\nOUT ACC"
        )
    }
    assert run_collect(progs, ["s1", "s2"], [10, 20]) == [10, 20]


def test_stack_capacity_backpressure():
    # cap-2 stack: third push parks until a pop frees a slot.
    progs = {
        "a": "PUSH 1, st\nPUSH 2, st\nPUSH 3, st\nJRO 0",
        "b": (
            "NOP\nNOP\nNOP\nNOP\nNOP\nNOP\nNOP\nNOP\n"
            "POP st, ACC\nOUT ACC\nPOP st, ACC\nOUT ACC\nPOP st, ACC\nOUT ACC\nJRO 0"
        ),
    }
    net = build(progs, ["st"], stack_cap=2)
    state = net.init_state()
    state = net.run(state, 128)
    _, outs = net.drain(state)
    # first pop frees a slot -> the parked PUSH 3 lands immediately, so LIFO
    # order is 2, then 3, then 1
    assert outs == [2, 3, 1]


# --- the add-2 network (BASELINE config #1) ---------------------------------

ADD2 = {
    "misaka1": "IN ACC\nADD 1\nMOV ACC, misaka2:R0\nMOV R0, ACC\nOUT ACC\n",
    "misaka2": "MOV R0, ACC\nADD 1\nPUSH ACC, misaka3\nPOP misaka3, ACC\nMOV ACC, misaka1:R0\n",
}


def test_add2_network_parity():
    # The docker-compose example: every input comes back +2, in order
    # (docker-compose.yml:35-59).
    inputs = [0, 1, 5, -7, 2147483646]
    # 2147483646 + 2 wraps to INT32_MIN: int32 end-to-end is our documented
    # divergence from the reference's 64-bit Go locals (tis/lower.py).
    assert run_collect(ADD2, ["misaka3"], inputs) == [2, 3, 7, -5, -2147483648]


def test_add2_sequential_stream():
    inputs = list(range(50))
    assert run_collect(ADD2, ["misaka3"], inputs) == [v + 2 for v in inputs]


# --- I/O rings ---------------------------------------------------------------

def test_out_ring_backpressure():
    # out_cap=2: producer parks after 2 un-drained outputs, no loss.
    net = build({"n": "OUT 1\nADD 1\nOUT ACC\nJRO -99"}, [], out_cap=2)
    state = net.init_state()
    state = net.run(state, 64)
    state, outs = net.drain(state)
    assert len(outs) == 2
    state = net.run(state, 64)
    state, outs2 = net.drain(state)
    assert len(outs2) == 2
    assert outs + outs2 == [1, 1, 1, 2]


def test_in_ring_order_preserved():
    prog = "IN ACC\nOUT ACC"
    inputs = list(range(30))
    assert run_collect({"n": prog}, [], inputs) == inputs


def test_retired_and_tick_metrics():
    net = build({"n": "NOP"}, [])
    state = net.init_state()
    state = net.run(state, 10)
    assert int(state.tick) == 10
    assert int(state.retired[0]) == 10


def test_parked_lane_does_not_retire():
    # IN with no input parks forever.
    net = build({"n": "IN ACC"}, [])
    state = net.init_state()
    state = net.run(state, 10)
    assert int(state.retired[0]) == 0
    assert int(state.pc[0]) == 0


# --- batch axis --------------------------------------------------------------

def test_batched_instances_are_independent():
    net = build({"n": "IN ACC\nADD 1\nOUT ACC"}, [], batch=4)
    state = net.init_state()
    # feed different values to each instance via direct ring writes
    import jax.numpy as jnp

    vals = jnp.asarray([[10], [20], [30], [40]], dtype=jnp.int32)
    in_buf = state.in_buf.at[:, 0].set(vals[:, 0])
    state = state._replace(in_buf=in_buf, in_wr=state.in_wr + 1)
    state = net.run(state, 16)
    out = np.asarray(state.out_buf[:, 0])
    np.testing.assert_array_equal(out, [11, 21, 31, 41])
    np.testing.assert_array_equal(np.asarray(state.out_wr), [1, 1, 1, 1])


# --- the one-dispatch serve path ---------------------------------------------

def test_serve_chunk_equals_piecewise():
    """serve_chunk (feed+run+snapshot+drain in one dispatch) must land in
    exactly the state the piecewise feed/run/drain path produces, and its
    packed snapshot must carry the same outputs."""
    net = build({"n": "IN ACC\nADD 1\nOUT ACC"}, [])
    s1 = net.init_state()
    s1, took = net.feed(s1, [5, 6])
    assert took == 2
    s1 = net.run(s1, 40)
    s1, outs1 = net.drain(s1)

    s2 = net.init_state()
    vals = np.zeros(net.in_cap, np.int32)
    vals[:2] = [5, 6]
    s2, packed = net.serve_chunk(s2, vals, 2, 40)
    p = np.asarray(packed)
    rd, wr = int(p[2]), int(p[3])
    outs2 = [int(p[4 + ((rd + i) % net.out_cap)]) for i in range(wr - rd)]

    assert outs1 == outs2 == [6, 7]
    for f in s1._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(s1, f)), np.asarray(getattr(s2, f)),
            err_msg=f"serve_chunk diverged from piecewise path on '{f}'",
        )


def test_serve_chunk_zero_count_is_pure_run():
    net = build({"n": "IN ACC\nADD 1\nOUT ACC"}, [])
    s1 = net.run(net.init_state(), 16)
    s2, packed = net.serve_chunk(
        net.init_state(), np.zeros(net.in_cap, np.int32), 0, 16
    )
    assert int(np.asarray(packed)[3]) == 0  # nothing produced
    for f in s1._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(s1, f)), np.asarray(getattr(s2, f)), err_msg=f
        )


def test_batched_serve_equals_piecewise():
    """The batched one-dispatch serve pair must land exactly where the
    piecewise feed_batched/run/drain_batched sequence lands."""
    net = build({"n": "IN ACC\nADD 1\nOUT ACC"}, [], batch=4)
    vals = np.zeros((4, net.in_cap), np.int32)
    vals[:, 0] = [10, 20, 30, 40]
    counts = np.ones(4, np.int32)

    s1 = net.feed_batched(net.init_state(), vals, counts)
    s1 = net.run(s1, 16)
    c = net.counters(s1)
    s1, outs1 = net.drain_batched(s1, rd=c[2], wr=c[3])

    serve_fn, idle_fn = net.make_batched_serve(None, 16)
    s2, packed = serve_fn(net.init_state(), vals, counts)
    p = np.asarray(packed)
    outs2 = net.drain_from_snapshot(p[:, 4:], p[:, 2], p[:, 3], net.out_cap)

    assert [(b, o.tolist()) for b, o in outs1] \
        == [(b, o.tolist()) for b, o in outs2] \
        == [(0, [11]), (1, [21]), (2, [31]), (3, [41])]
    for f in s1._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(s1, f)), np.asarray(getattr(s2, f)),
            err_msg=f"batched serve diverged from piecewise path on '{f}'",
        )

    # idle advances identically to a plain run, returns counters only
    # ([B, 4]) and leaves the output ring undrained
    s3 = net.run(net.init_state(), 16)
    s4, ctrs = idle_fn(net.init_state())
    assert np.asarray(ctrs).shape == (4, 4)
    assert int(np.asarray(ctrs)[:, 3].sum()) == 0
    for f in s3._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(s3, f)), np.asarray(getattr(s4, f)), err_msg=f
        )

    # idle after production leaves outputs in the ring for drain_batched
    s5 = net.feed_batched(net.init_state(), vals, counts)
    s5, ctrs = idle_fn(s5)
    c = np.asarray(ctrs)
    assert (c[:, 3] > c[:, 2]).all()
    s5, outs5 = net.drain_batched(s5, rd=c[:, 2], wr=c[:, 3])
    assert [(b, o.tolist()) for b, o in outs5] \
        == [(0, [11]), (1, [21]), (2, [31]), (3, [41])]


def test_chained_election_smoke():
    """Fast-lane pin for the scatter-free chained election (the full fuzz
    lives in test_scale's slow lane): bit-identical to compact on add2 and
    the branch-heavy sorter, end to end through run()."""
    from misaka_tpu import networks

    for name in ("add2", "sorter"):
        net = networks.BASELINE_CONFIGS[name](
            in_cap=8, out_cap=8, stack_cap=8
        ).compile()
        vals = np.random.default_rng(4).integers(-100, 100, size=6).astype(np.int32)
        state0 = net.init_state()
        prep = state0._replace(
            in_buf=state0.in_buf.at[:6].set(vals), in_wr=state0.in_wr + 6
        )
        a = net.run(prep, 80, engine="compact")
        state0 = net.init_state()
        prep = state0._replace(
            in_buf=state0.in_buf.at[:6].set(vals), in_wr=state0.in_wr + 6
        )
        b = net.run(prep, 80, engine="chained")
        for f in a._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
                err_msg=f"{name}.{f}",
            )
