"""The durable telemetry plane (utils/spool.py + the TSDB/usage/capture
spools): crash-shaped recovery — torn final segments truncated and
continued on reopen, disk-budget eviction oldest-first, the billing
ledger's cumulative counters monotone across restarts, signed-export
tamper rejection, boot-time TSDB reload so day-scale windows answer
across restarts — plus the acceptance restart drill against a REAL
`python -m misaka_tpu.runtime.app` subprocess: kill -9 with the spool
armed, relaunch, /debug/series spans the restart, the usage export
conserves vs pass-wall, and a pre-kill rotated capture segment replays
byte-for-byte green.
"""

import json
import os
import signal
import struct
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from misaka_tpu.utils import metrics
from misaka_tpu.utils import tsdb
from misaka_tpu.utils.spool import SegmentSpool

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Unique names per test: the metrics registry is process-global and
# get-or-create, so a reused name would leak state across tests.
_seq = iter(range(10 ** 6))


def _name(kind):
    return f"t_durable_{kind}_{next(_seq)}"


# --- segment spool: crash-shaped recovery -----------------------------------


def test_spool_torn_tail_truncated_and_continued(tmp_path):
    sp = SegmentSpool(str(tmp_path), prefix="t")
    for i in range(3):
        assert sp.append({"i": i})
    sp.flush()
    sp.close()
    [(_, path)] = sp.segments()
    good_size = os.path.getsize(path)
    # a kill mid-append leaves a torn tail: a length prefix promising
    # more bytes than the file holds
    with open(path, "ab") as f:
        f.write(struct.pack("<I", 64) + b"torn")
    # reopen: the tail is truncated IN PLACE and appending continues
    sp2 = SegmentSpool(str(tmp_path), prefix="t")
    seen = []
    assert sp2.reload(seen.append) == 3
    assert [fr["i"] for fr in seen] == [0, 1, 2]
    assert os.path.getsize(path) == good_size
    assert sp2.append({"i": 3})
    sp2.flush()
    sp2.close()
    sp3 = SegmentSpool(str(tmp_path), prefix="t")
    seen = []
    assert sp3.reload(seen.append) == 4
    assert [fr["i"] for fr in seen] == [0, 1, 2, 3]
    sp3.close()


def test_spool_garbage_tail_truncated(tmp_path):
    """Non-JSON bytes after the last good frame (a torn frame body) are
    cut away, not surfaced as frames and not fatal."""
    sp = SegmentSpool(str(tmp_path), prefix="g")
    sp.append({"ok": True})
    sp.flush()
    sp.close()
    [(_, path)] = sp.segments()
    blob = b"\xff\xfe not json"
    with open(path, "ab") as f:
        f.write(struct.pack("<I", len(blob)) + blob)
    sp2 = SegmentSpool(str(tmp_path), prefix="g")
    seen = []
    assert sp2.reload(seen.append) == 1
    assert seen == [{"ok": True}]
    sp2.close()


def test_spool_budget_evicts_oldest_never_active(tmp_path):
    evicted = []
    sp = SegmentSpool(
        str(tmp_path), prefix="e",
        budget_bytes=1 << 16, segment_bytes=1 << 12,
        on_evict=evicted.append,
    )
    pad = "x" * 400
    total = 400
    for i in range(total):
        assert sp.append({"i": i, "pad": pad})
        sp.flush()  # budget enforcement runs on every flush
    segs = sp.segments()
    assert segs, "everything evicted — the active segment must survive"
    assert segs[0][0] > 0, "oldest segments were not evicted"
    assert sum(evicted) > 0
    assert sp.disk_bytes() <= (1 << 16)
    # retention is a contiguous NEWEST suffix — no holes
    ids = []
    sp.read_frames(lambda fr: ids.append(fr["i"]))
    assert ids == list(range(ids[0], total))
    assert ids[-1] == total - 1
    sp.close()


# --- window grammar ---------------------------------------------------------


@pytest.mark.parametrize("text,want", [
    ("1d", 86400.0), ("7d", 604800.0), ("0.5d", 43200.0),
])
def test_parse_window_day_suffix(text, want):
    assert tsdb.parse_window(text) == want


def test_parse_window_day_suffix_rejects_bare():
    with pytest.raises(tsdb.TSDBError):
        tsdb.parse_window("d")


# --- kill switch: MISAKA_TSDB_DIR unset = today's behavior ------------------


def test_spools_disarmed_without_tsdb_dir(tmp_path):
    from misaka_tpu.runtime import capture
    from misaka_tpu.runtime import usage

    db = tsdb.TSDB(interval_s=1.0, registry=metrics.Registry())
    assert db.spool_status() is None
    db.sample_once()  # no spool, no side effects
    assert usage.spool_dir({}) is None
    assert capture.spool_dir({}) is None
    # per-plane opt-outs under an armed root
    armed = {"MISAKA_TSDB_DIR": str(tmp_path)}
    assert usage.spool_dir({**armed, "MISAKA_USAGE_SPOOL": "0"}) is None
    assert capture.spool_dir({**armed, "MISAKA_CAPTURE_SPOOL": "0"}) is None


# --- TSDB reload across a restart -------------------------------------------


def test_tsdb_reload_answers_day_windows_across_restart(tmp_path):
    name = _name("g")
    reg = metrics.Registry()
    g = metrics.gauge(name, "x", registry=reg)
    db1 = tsdb.TSDB(interval_s=0.05, registry=reg, spool_dir=str(tmp_path))
    assert db1.spool_status() is not None
    for i in range(15):
        g.set(float(i + 1))
        db1.sample_once()
        time.sleep(0.055)
    time.sleep(0.06)  # finalize the last touched slot
    db1._spool_flush()
    assert db1.spooled_frames > 0
    db1.stop()  # closes the spools (the simulated crash point is fsync'd)

    # "restart": a fresh TSDB over the same directory, EMPTY registry —
    # every point it can answer came off disk
    db2 = tsdb.TSDB(
        interval_s=0.05, registry=metrics.Registry(),
        spool_dir=str(tmp_path),
    )
    assert db2.reloaded_frames > 0
    # fine stage: the pre-restart points at full resolution
    [row] = db2.query(name, window_s=30.0)
    assert len(row["points"]) >= 5
    assert all(p[1] > 0 for p in row["points"])
    # day window: picks the coarsest ring — a young spool has no
    # finalized long-tier slots, so fine frames must have filled it
    [row] = db2.query(name, window_s=tsdb.parse_window("7d"))
    assert row["stage_s"] == 300.0
    assert row["points"] and row["points"][0][2] >= 1.0
    db2.stop()


def test_tsdb_writer_resumes_after_reloaded_epochs(tmp_path):
    """Same epoch must never be spooled twice across a restart (reload
    merge would double-count it)."""
    name = _name("g")
    reg = metrics.Registry()
    g = metrics.gauge(name, "x", registry=reg)
    db1 = tsdb.TSDB(interval_s=0.05, registry=reg, spool_dir=str(tmp_path))
    for i in range(6):
        g.set(1.0)
        db1.sample_once()
        time.sleep(0.055)
    time.sleep(0.06)
    db1._spool_flush()
    db1.stop()
    db2 = tsdb.TSDB(interval_s=0.05, registry=reg, spool_dir=str(tmp_path))
    before = db2.query(name, window_s=30.0)[0]["points"]
    db2._spool_flush()  # immediately after boot: nothing new to write
    after = db2.query(name, window_s=30.0)[0]["points"]
    assert after == before
    db2.stop()


# --- billing ledger: restart-safe cumulative counters -----------------------


def test_usage_cumulative_monotone_across_rearm(tmp_path):
    from misaka_tpu.runtime import usage

    label = _name("tenant")
    env = {"MISAKA_TSDB_DIR": str(tmp_path), "MISAKA_USAGE_FLUSH_S": "60"}
    usage.shutdown_spool()
    try:
        assert usage.ensure_spool(env) is not None
        usage.add_request(label, 8)
        usage.add_cpu(label, 0.5)
        usage.note_pass(0.5)
        assert usage.flush_now(force=True)
        snap1 = usage.cumulative_snapshot()
        row1 = snap1["programs"][label]
        assert row1["requests"] == 1 and row1["values"] == 8
        # "restart": drop the armed spool + bases, re-arm over the same
        # directory — the flushed frame is the new base, live accrual
        # since arm is offset away (never double-counted)
        usage.shutdown_spool()
        assert usage.ensure_spool(env) is not None
        row2 = usage.cumulative_snapshot()["programs"][label]
        for f, v in row1.items():
            assert row2[f] >= v - 1e-9, (f, row2[f], v)
        usage.add_request(label, 2)
        row3 = usage.cumulative_snapshot()["programs"][label]
        assert row3["requests"] == row2["requests"] + 1
        assert row3["values"] == row2["values"] + 2
    finally:
        usage.shutdown_spool()


def test_usage_export_sign_and_tamper_rejection(tmp_path):
    from misaka_tpu.runtime import usage

    label = _name("tenant")
    env = {"MISAKA_TSDB_DIR": str(tmp_path), "MISAKA_USAGE_FLUSH_S": "60"}
    signed_env = {**env, "MISAKA_USAGE_SECRET": "hunter2"}
    usage.shutdown_spool()
    try:
        assert usage.ensure_spool(env) is not None
        usage.add_request(label, 4)
        usage.add_cpu(label, 0.25)
        usage.note_pass(0.25)
        lines = usage.export_lines(environ=signed_env)
        periods = [
            i for i, ln in enumerate(lines)
            if ln.get("kind") == "period" and ln.get("program") == label
        ]
        assert periods, lines
        assert lines[-1]["kind"] == "totals" and "sig" in lines[-1]
        totals = usage.totals_from_lines(lines, secret=b"hunter2")
        assert totals["verified"]
        assert totals["programs"][label]["requests"] == 1.0
        assert totals["cumulative"][label]["cpu_seconds"] == \
            pytest.approx(0.25)
        # unverified read still works (no secret at hand)
        assert not usage.totals_from_lines(lines)["verified"]
        # tampering with any signed field is rejected, loudly
        forged = [dict(ln) for ln in lines]
        forged[periods[0]]["cpu_seconds"] = 99.0
        with pytest.raises(usage.UsageExportError):
            usage.totals_from_lines(forged, secret=b"hunter2")
        # a different key is indistinguishable from tampering
        with pytest.raises(usage.UsageExportError):
            usage.totals_from_lines(lines, secret=b"not-the-key")
    finally:
        usage.shutdown_spool()


# --- capture spool: rotation + on-disk history ------------------------------


def _fake_traffic(capture, n, program="p0"):
    for i in range(n):
        vals = np.arange(4, dtype="<i4") + i
        capture.note(
            "compute_raw", program=program, trace=None, inbound=True,
            vals=vals.tobytes(), resp=(vals + 1).tobytes(),
            status=200, tick=None,
        )


def test_capture_spool_rotation_and_history(tmp_path):
    from misaka_tpu.runtime import capture

    env = {
        "MISAKA_TSDB_DIR": str(tmp_path),
        "MISAKA_CAPTURE_SEG_S": "9999",     # explicit rotate_now() only
        "MISAKA_CAPTURE_SEG_KB": "100000",
    }
    capture.shutdown_spool()
    if capture.RECORDING:
        capture.stop()
    try:
        st = capture.ensure_spool(env, anchor_fn=None)
        assert st is not None and capture.RECORDING
        _fake_traffic(capture, 10)
        r1 = capture.rotate_now()
        assert r1["records"] == 10
        assert capture.verify_segment(r1["path"])["records"] == 10
        # rotation re-armed recording with a fresh ring
        _fake_traffic(capture, 5)
        r2 = capture.rotate_now()
        assert r2["records"] == 5
        d = os.path.join(str(tmp_path), "capture")
        assert [os.path.basename(p) for p in
                capture.history_segments(directory=d)] == \
            ["spool-00000000.mskcap", "spool-00000001.mskcap"]
        assert capture.rotate_now() is None  # empty ring: no segment
        status = capture.spool_status()
        assert status["rotations"] == 2 and status["segments"] == 2
        # a later boot resumes the sequence — never overwrites history
        capture.shutdown_spool()
        capture.stop()
        st = capture.ensure_spool(env, anchor_fn=None)
        assert st["next_seq"] == 2
    finally:
        capture.shutdown_spool()
        if capture.RECORDING:
            capture.stop()


def test_fit_diurnal_hour_weights():
    from misaka_tpu.runtime import capture

    pts = [(10 * 3600 + 60 * i, 1.0) for i in range(5)]
    pts += [(11 * 3600 + 60 * i, 3.0) for i in range(5)]
    model = capture._fit_diurnal(pts)
    assert model["hours_observed"] == 2
    w = model["hour_weights_utc"]
    assert len(w) == 24
    assert w[10] == pytest.approx(0.5) and w[11] == pytest.approx(1.5)
    # mean over ALL hours stays 1.0: unobserved hours replay at par
    assert sum(w) / 24 == pytest.approx(1.0)
    # one observed hour has no day shape worth replaying
    assert capture._fit_diurnal(pts[:5]) is None


# --- the acceptance restart drill (real subprocess server) ------------------


SOLO_ENV = {
    "NODE_INFO": json.dumps({"solo": {"type": "program"}}),
    "MISAKA_PROGRAMS": json.dumps({"solo": "IN ACC\nADD 1\nOUT ACC\n"}),
}


def _drill_env(tmp_path, port):
    env = {k: v for k, v in os.environ.items() if not k.startswith("JAX")}
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        MISAKA_PORT=str(port),
        MISAKA_TTL_S="600",
        MISAKA_AUTORUN="1",
        # the canary's background traffic would race the byte-exact
        # replay comparand; the drill wants deterministic history
        MISAKA_CANARY="0",
        MISAKA_TSDB_DIR=os.path.join(str(tmp_path), "telemetry"),
        MISAKA_TSDB_INTERVAL_S="0.25",
        MISAKA_USAGE_FLUSH_S="0.5",
        MISAKA_CAPTURE_SEG_S="9999",  # rotation via POST only
        PYTHONPATH=_ROOT,
        **SOLO_ENV,
    )
    return env


def _wait_healthy(base, deadline_s=180):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("ok") and not payload.get("degraded"):
                return payload
        except OSError:
            pass
        time.sleep(0.5)
    raise AssertionError("server never became healthy")


def _usage_totals(base):
    from misaka_tpu.runtime import usage

    with urllib.request.urlopen(base + "/usage/export", timeout=10) as r:
        lines = [
            json.loads(ln) for ln in r.read().decode().splitlines() if ln
        ]
    return usage.totals_from_lines(lines)


def test_restart_drill_durable_telemetry(tmp_path):
    """ISSUE 20 acceptance: MISAKA_TSDB_DIR armed, kill -9, relaunch —
    /debug/series returns pre-restart points (day windows included),
    the usage export is monotone across the restart and conserves vs
    pass-wall within 5%, and the capture segment rotated before the
    kill replays byte-for-byte green."""
    from misaka_tpu.client import MisakaClient
    from misaka_tpu.runtime import frontends

    port = frontends.pick_free_port()
    base = f"http://127.0.0.1:{port}"
    env = _drill_env(tmp_path, port)
    launch = [sys.executable, "-m", "misaka_tpu.runtime.app"]
    proc = subprocess.Popen(launch, env=env)
    proc2 = None
    client = None
    try:
        _wait_healthy(base)
        client = MisakaClient(base, timeout=60)
        vals = np.arange(16, dtype=np.int32)
        for _ in range(20):
            assert np.array_equal(client.compute_raw(vals), vals + 1)
        # let >=2 usage flush ticks land and the traffic's TSDB slots
        # finalize onto disk before pulling the plug
        time.sleep(1.5)
        req = urllib.request.Request(
            base + "/captures/rotate", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            rotated = json.loads(r.read())
        assert rotated.get("records", 0) > 0, rotated
        segment = rotated["path"]
        assert os.path.exists(segment)
        totals1 = _usage_totals(base)
        assert totals1["pass_wall_seconds"] > 0
        assert totals1["cumulative"], totals1
        client.close()
        client = None

        t_kill = time.time()
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

        proc2 = subprocess.Popen(launch, env=env)
        _wait_healthy(base)
        client = MisakaClient(base, timeout=60)
        # 1. series history spans the kill: points measured BEFORE the
        # restart are still queryable, including through the day-window
        # grammar the durable tier answers
        got = client.series("misaka_compute_values_total", window="15m")
        pts = [p for row in got["series"] for p in row["points"]]
        assert any(p[0] < t_kill and p[1] > 0 for p in pts), pts
        week = client.series("misaka_compute_values_total", window="7d")
        wpts = [p for row in week["series"] for p in row["points"]]
        assert wpts and min(p[0] for p in wpts) < t_kill, wpts
        # 2. the billing ledger reloaded its base: more traffic, then
        # every cumulative counter is monotone vs the pre-kill export
        for _ in range(10):
            assert np.array_equal(client.compute_raw(vals), vals + 1)
        time.sleep(1.2)
        totals2 = _usage_totals(base)
        for prog, row in totals1["cumulative"].items():
            after = totals2["cumulative"].get(prog)
            assert after is not None, (prog, totals2)
            for f, v in row.items():
                assert after[f] >= v - 1e-6, (prog, f, after[f], v)
        assert totals2["pass_wall_seconds"] >= \
            totals1["pass_wall_seconds"] - 1e-6
        # conservation: attributed cpu-seconds vs the pass-wall anchor
        wall = totals2["pass_wall_seconds"]
        cpu = totals2["cpu_seconds_total"]
        assert abs(wall - cpu) <= 0.05 * max(wall, cpu), (wall, cpu)
        client.close()
        client = None
        # 3. the pre-kill rotated segment replays byte-for-byte green
        r = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools", "replay.py"),
             segment],
            env=env, cwd=_ROOT, capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "green" in (r.stdout + r.stderr), r.stdout + r.stderr
    finally:
        if client is not None:
            client.close()
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
