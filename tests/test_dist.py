"""The multi-host fleet (ISSUE 19): TCP + mTLS plane transport, remote
peer supervision, and fleet-coherent edge state.

Covers the plane address grammar and TCP transport (the MSK1 codec is
byte-identical over AF_UNIX and TCP), the mTLS gate (plaintext and
wrong-CA peers refused with a typed counted close; certificate rotation
under traffic drops zero frames), the dial-backoff guard against
reconnect storms, the remote-peer supervision surface on FleetManager
(registration, probing, the remote roll protocol), the usage-gossip hub
that bounds a flooded tenant's aggregate over-admission across replicas,
and the signed short-lived tenant tokens minted at /edge/token and
verified locally at every replica.
"""

import json
import os
import shutil
import socket
import ssl
import subprocess
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

import numpy as np
import pytest

from misaka_tpu.runtime import edge, fleet, frontends
from misaka_tpu.utils import faults


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    edge.reset()
    faults.configure(None)


# --- plane address grammar ---------------------------------------------------


def test_parse_plane_addr():
    assert frontends.parse_plane_addr("10.0.0.2:9001") == \
        ("tcp", "10.0.0.2", 9001)
    assert frontends.parse_plane_addr(":9001") == ("tcp", "127.0.0.1", 9001)
    # anything with a '/' is a unix path, colon or not
    assert frontends.parse_plane_addr("/tmp/plane-0.sock") == \
        ("unix", "/tmp/plane-0.sock", None)
    assert frontends.parse_plane_addr("/tmp/x:y.sock") == \
        ("unix", "/tmp/x:y.sock", None)
    # a colon whose tail is not a port falls through to unix (a relative
    # socket name like "plane:a.sock" must not become a dial)
    assert frontends.parse_plane_addr("plane:a.sock") == \
        ("unix", "plane:a.sock", None)
    assert frontends.parse_plane_addr("plane.sock") == \
        ("unix", "plane.sock", None)


def test_parse_fleet_peers():
    assert fleet.parse_fleet_peers(None) == []
    assert fleet.parse_fleet_peers(" ") == []
    peers = fleet.parse_fleet_peers("10.0.0.2:9000, 10.0.0.3:9000:9501")
    assert peers == [
        {"host": "10.0.0.2", "port": 9000, "plane": "10.0.0.2:9001"},
        {"host": "10.0.0.3", "port": 9000, "plane": "10.0.0.3:9501"},
    ]
    for bad in ("justahost", ":9000", "h:port", "h:1:2:3", "h:1:x"):
        with pytest.raises(ValueError):
            fleet.parse_fleet_peers(bad)


# --- TCP plane transport -----------------------------------------------------


class _StubMaster:
    """Jax-free engine twin (values + 2) — the test_fleet harness."""

    is_running = True

    def __init__(self, delay: float = 0.0):
        self.calls = 0
        self.values = 0
        self.delay = delay
        self._lock = threading.Lock()

    def compute_coalesced(self, values, timeout=30.0, return_array=True,
                          traces=()):
        with self._lock:
            self.calls += 1
            self.values += int(np.asarray(values).size)
        if self.delay:
            time.sleep(self.delay)
        return np.asarray(values) + 2


BODY = np.arange(8, dtype=np.int32).tobytes()
WANT = np.arange(8, dtype=np.int32) + 2


def _check(out):
    assert np.array_equal(np.frombuffer(out, dtype="<i4"), WANT)


def _tcp_addr() -> str:
    return f"127.0.0.1:{frontends.pick_free_port()}"


def test_tcp_plane_roundtrip():
    """The MSK1 frame codec over loopback TCP: same coalescing, same
    payloads, no unix socket anywhere."""
    master = _StubMaster()
    addr = _tcp_addr()
    plane = frontends.start_compute_plane(master, addr)
    client = frontends.PlaneClient(addr, conns=1, timeout=5)
    try:
        for _ in range(3):
            _check(client.compute_raw(BODY, timeout=5))
        assert master.values == 24
    finally:
        client.close()
        plane.close()


def test_tcp_dial_backoff_bounds_reconnect_storms():
    """Dispatcher dials against a DEAD TCP peer ride the shared backoff
    curve: the first dial fails on the wire, dials inside the hold fail
    FAST (no SYN storm against the dead host), and the hold is re-armed
    by the next wire failure."""
    addr = _tcp_addr()  # nothing listens here
    client = frontends.PlaneClient(addr, conns=1, timeout=2)
    try:
        with pytest.raises(OSError) as e1:
            client._connect()
        assert "backoff" not in str(e1.value)
        assert client._next_dial > time.monotonic()  # hold armed
        t0 = time.monotonic()
        with pytest.raises(OSError) as e2:
            client._connect()
        assert "backoff" in str(e2.value)
        assert time.monotonic() - t0 < 0.05  # failed fast, no dial
        # after the hold a real dial happens (and fails on the wire again)
        client._next_dial = 0.0
        with pytest.raises(OSError) as e3:
            client._connect()
        assert "backoff" not in str(e3.value)
        assert client._next_dial > time.monotonic()
    finally:
        client.close()


def test_plane_partition_fault_blackholes_dials():
    addr = _tcp_addr()
    client = frontends.PlaneClient(addr, conns=1, timeout=2)
    try:
        faults.configure("plane_partition")
        with pytest.raises(OSError, match="partitioned"):
            client._connect()
        # scoped to a DIFFERENT peer: this client dials the wire (and
        # fails honestly — nothing listens), not the injected partition
        faults.configure("plane_partition:10.9.9.9:1")
        with pytest.raises(OSError) as e:
            client._connect()
        assert "partitioned" not in str(e.value)
        # scoped to THIS peer's address substring
        faults.configure(f"plane_partition:{addr}")
        with pytest.raises(OSError, match="partitioned"):
            client._connect()
    finally:
        client.close()


def test_plane_delay_fault_slows_frames():
    master = _StubMaster()
    addr = _tcp_addr()
    plane = frontends.start_compute_plane(master, addr)
    client = frontends.PlaneClient(addr, conns=1, timeout=5)
    try:
        _check(client.compute_raw(BODY, timeout=5))  # connection warm
        faults.configure("plane_delay=0.15")
        t0 = time.monotonic()
        _check(client.compute_raw(BODY, timeout=5))
        assert time.monotonic() - t0 >= 0.15
    finally:
        client.close()
        plane.close()


# --- plane mTLS --------------------------------------------------------------

_HAVE_OPENSSL = shutil.which("openssl") is not None


def _gen_cert(directory, name, cn):
    cert = str(directory / f"{name}.pem")
    key = str(directory / f"{name}.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "ec",
            "-pkeyopt", "ec_paramgen_curve:prime256v1", "-nodes",
            "-keyout", key, "-out", cert, "-days", "1",
            "-subj", f"/CN={cn}",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True, capture_output=True,
    )
    return cert, key


@pytest.fixture(scope="module")
def plane_certs(tmp_path_factory):
    """(fleet cert, fleet key, rogue cert, rogue key): each self-signed,
    so each cert is its own CA — the fleet pair models CA membership, the
    rogue pair a peer outside the trust domain."""
    if not _HAVE_OPENSSL:
        pytest.skip("openssl unavailable")
    d = tmp_path_factory.mktemp("plane-certs")
    cert, key = _gen_cert(d, "fleet", "misaka-fleet")
    rogue_cert, rogue_key = _gen_cert(d, "rogue", "rogue-peer")
    return cert, key, rogue_cert, rogue_key


def _tls_env(monkeypatch, cert, key, ca):
    monkeypatch.setenv("MISAKA_PLANE_TLS_CERT", cert)
    monkeypatch.setenv("MISAKA_PLANE_TLS_KEY", key)
    monkeypatch.setenv("MISAKA_PLANE_TLS_CA", ca)


def test_plane_tls_env_validation(monkeypatch, plane_certs):
    cert, key, _, _ = plane_certs
    monkeypatch.delenv("MISAKA_PLANE_TLS_CERT", raising=False)
    monkeypatch.delenv("MISAKA_PLANE_TLS_KEY", raising=False)
    monkeypatch.delenv("MISAKA_PLANE_TLS_CA", raising=False)
    assert edge.plane_tls_from_env() is None
    monkeypatch.setenv("MISAKA_PLANE_TLS_CERT", cert)
    with pytest.raises(ValueError):  # partial triple: fail loud
        edge.plane_tls_from_env()
    _tls_env(monkeypatch, cert, key, cert)
    reloader = edge.plane_tls_from_env()
    assert reloader is not None
    assert reloader.client_context().verify_mode == ssl.CERT_REQUIRED
    assert reloader.server_context().verify_mode == ssl.CERT_REQUIRED


def _reject_count(reason):
    return edge.M_PLANE_TLS_REJECTED.labels(reason=reason).value


def _wait_reject(reason, before, timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _reject_count(reason) > before:
            return True
        time.sleep(0.02)
    return False


def test_plane_mtls_roundtrip_and_plaintext_refusal(monkeypatch,
                                                    plane_certs):
    cert, key, _, _ = plane_certs
    _tls_env(monkeypatch, cert, key, cert)
    master = _StubMaster()
    addr = _tcp_addr()
    plane = frontends.start_compute_plane(master, addr)
    client = frontends.PlaneClient(addr, conns=1, timeout=5)
    try:
        for _ in range(3):
            _check(client.compute_raw(BODY, timeout=5))
        # a plaintext peer (no TLS at all) is refused with a typed,
        # counted close before any frame byte reaches the codec
        before = _reject_count("plaintext")
        served_before = master.calls
        _, host, port = frontends.parse_plane_addr(addr)
        raw = socket.create_connection((host, port), timeout=2)
        try:
            raw.sendall(b"\x08\x00\x00\x00\x00\x00\x00\x00plaintext!")
            raw.settimeout(2)
            try:
                data = raw.recv(64)
            except ConnectionResetError:
                data = b""
            assert data == b""  # peer closed, no response bytes
        finally:
            raw.close()
        assert _wait_reject("plaintext", before)
        assert master.calls == served_before  # nothing reached the engine
        # the data path is unaffected by the refused peer
        _check(client.compute_raw(BODY, timeout=5))
    finally:
        client.close()
        plane.close()


def test_plane_mtls_wrong_ca_refused(monkeypatch, plane_certs):
    cert, key, rogue_cert, rogue_key = plane_certs
    _tls_env(monkeypatch, cert, key, cert)
    master = _StubMaster()
    addr = _tcp_addr()
    plane = frontends.start_compute_plane(master, addr)
    try:
        before = _reject_count("bad_cert")
        # a TLS client whose certificate the fleet CA did not sign: it
        # trusts the server, but the server must refuse ITS cert
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(rogue_cert, rogue_key)
        ctx.load_verify_locations(cert)
        ctx.check_hostname = False
        _, host, port = frontends.parse_plane_addr(addr)
        raw = socket.create_connection((host, port), timeout=2)
        raw.settimeout(2)
        try:
            # TLS 1.3 delivers the server's rejection alert on the first
            # read after the handshake; TLS 1.2 fails inside wrap_socket
            s = ctx.wrap_socket(raw, server_hostname=host)
            s.sendall(b"\x00" * 8)
            s.recv(64)
        except OSError:
            pass
        finally:
            raw.close()
        # the server's typed counted close is the contract
        assert _wait_reject("bad_cert", before)
    finally:
        plane.close()


def test_plane_tls_reloader_rotation_and_bad_material(tmp_path,
                                                      plane_certs):
    cert, key, rogue_cert, rogue_key = plane_certs
    live_cert = str(tmp_path / "live.pem")
    live_key = str(tmp_path / "live.key")
    live_ca = str(tmp_path / "ca.pem")
    shutil.copy(cert, live_cert)
    shutil.copy(key, live_key)
    shutil.copy(cert, live_ca)
    reloader = edge.PlaneTLSReloader(live_cert, live_key, live_ca)
    s1 = reloader.server_context()
    ok0 = edge.M_PLANE_TLS_RELOADS.labels(status="ok").value
    err0 = edge.M_PLANE_TLS_RELOADS.labels(status="error").value
    # rotate to a fresh pair (CA carries both: old sessions stay valid)
    shutil.copy(rogue_cert, live_cert)
    shutil.copy(rogue_key, live_key)
    with open(live_ca, "wb") as f, open(cert, "rb") as a, \
            open(rogue_cert, "rb") as b:
        f.write(a.read() + b.read())
    now = time.time() + 5
    for p in (live_cert, live_key, live_ca):
        os.utime(p, (now, now))
    reloader._next_stat = 0.0  # skip the 0.5s stat throttle
    s2 = reloader.server_context()
    assert s2 is not s1
    assert edge.M_PLANE_TLS_RELOADS.labels(status="ok").value == ok0 + 1
    # a broken rotation (half-written key) KEEPS the previous contexts
    with open(live_key, "w") as f:
        f.write("not a key")
    os.utime(live_key, (now + 5, now + 5))
    reloader._next_stat = 0.0
    s3 = reloader.server_context()
    assert s3 is s2
    assert edge.M_PLANE_TLS_RELOADS.labels(status="error").value == err0 + 1


def test_plane_mtls_rotation_under_traffic(monkeypatch, tmp_path,
                                           plane_certs):
    """Certificate rotation without restart: established plane sessions
    keep streaming through the swap (zero dropped frames), and fresh
    dials complete under the NEW material."""
    cert, key, _, _ = plane_certs
    live_cert = str(tmp_path / "live.pem")
    live_key = str(tmp_path / "live.key")
    live_ca = str(tmp_path / "ca.pem")
    shutil.copy(cert, live_cert)
    shutil.copy(key, live_key)
    shutil.copy(cert, live_ca)
    _tls_env(monkeypatch, live_cert, live_key, live_ca)
    master = _StubMaster()
    addr = _tcp_addr()
    plane = frontends.start_compute_plane(master, addr)
    client = frontends.PlaneClient(addr, conns=1, timeout=5)
    c2 = None
    try:
        for _ in range(10):
            _check(client.compute_raw(BODY, timeout=5))
        # rotate: new keypair on disk, CA trusting old + new
        new_cert, new_key = _gen_cert(tmp_path, "rotated", "misaka-fleet-2")
        with open(live_ca, "wb") as f, open(cert, "rb") as a, \
                open(new_cert, "rb") as b:
            f.write(a.read() + b.read())
        shutil.copy(new_cert, live_cert)
        shutil.copy(new_key, live_key)
        now = time.time() + 5
        for p in (live_cert, live_key, live_ca):
            os.utime(p, (now, now))
        plane._tls._next_stat = 0.0
        client._tls._next_stat = 0.0
        # the established session streams on, frame for frame
        for _ in range(10):
            _check(client.compute_raw(BODY, timeout=5))
        assert master.values == 160  # 20 frames x 8 values, none dropped
        # a fresh dial handshakes under the rotated certificate
        c2 = frontends.PlaneClient(addr, conns=1, timeout=5)
        _check(c2.compute_raw(BODY, timeout=5))
    finally:
        if c2 is not None:
            c2.close()
        client.close()
        plane.close()


# --- remote peer supervision -------------------------------------------------


class _FakePeer:
    """A remote replica's control surface, just deep enough for the
    fleet's probe / roll / gossip protocols: /healthz, /fleet/drain,
    /checkpoint, /edge/gossip.  Records every (method, path, form/json)
    and every presented X-Misaka-Key."""

    def __init__(self, chain=None, checkpoint_status=200, healthy=True):
        self.calls = []
        self.keys = []
        self.chain = chain
        self.checkpoint_status = checkpoint_status
        self.healthy = healthy
        peer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, status, obj):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                peer.keys.append(self.headers.get("X-Misaka-Key"))
                peer.calls.append(("GET", self.path, None))
                if self.path == "/healthz":
                    if not peer.healthy:
                        self._reply(503, {"ok": False})
                        return
                    self._reply(200, {"ok": True, "running": True,
                                      "degraded": False})
                else:
                    self._reply(404, {"error": "no route"})

            def do_POST(self):
                peer.keys.append(self.headers.get("X-Misaka-Key"))
                raw = self.rfile.read(
                    int(self.headers.get("Content-Length") or 0)
                )
                if self.path == "/fleet/drain":
                    form = {k: v[-1] for k, v in
                            parse_qs(raw.decode()).items()}
                    peer.calls.append(("POST", self.path, form))
                    self._reply(200, {
                        "draining": form.get("state") == "on",
                        "inflight": 0, "http_inflight": 0,
                    })
                elif self.path == "/checkpoint":
                    peer.calls.append(("POST", self.path, raw.decode()))
                    self._reply(peer.checkpoint_status,
                                {"ok": peer.checkpoint_status == 200})
                elif self.path == "/edge/gossip":
                    payload = json.loads(raw or b"{}")
                    peer.calls.append(("POST", self.path, payload))
                    drained = peer.chain.apply_remote_usage(
                        payload.get("usage") or {},
                        source=str(payload.get("source") or "peer"),
                    ) if peer.chain is not None else 0
                    self._reply(200, {
                        "drained": drained,
                        "usage": peer.chain.usage_snapshot()
                        if peer.chain is not None else {},
                    })
                else:
                    peer.calls.append(("POST", self.path, raw))
                    self._reply(404, {"error": "no route"})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()


def test_fleet_registers_remote_peers(tmp_path):
    fm = fleet.FleetManager(1, str(tmp_path), base_env={
        "MISAKA_FLEET_PEERS": "10.0.0.2:9000,10.0.0.3:9000:9501",
        "MISAKA_FLEET_PEER_KEY": "peer-admin-key",
    })
    try:
        assert [p["idx"] for p in fm._peers] == [1, 2]
        assert fm._peer_key == "peer-admin-key"
        # router fan-out: local unix sockets first, then peer planes
        paths = fm.plane_paths()
        assert len(paths) == 3
        assert paths[1:] == ["10.0.0.2:9001", "10.0.0.3:9501"]
        st = fm.state()
        assert st["peers"] == 2 and st["peers_up"] == 0
        remote_rows = [r for r in st["replicas"] if r.get("remote")]
        assert [r["replica"] for r in remote_rows] == [1, 2]
        assert all(r["state"] == "starting" and r["pid"] is None
                   for r in remote_rows)
        # the probe-only state ladder
        peer = fm._peers[0]
        assert fm.peer_state(peer) == "starting"
        peer["probe_fails"] = 1
        assert fm.peer_state(peer) == "degraded"
        peer["probe_fails"] = fm._down_after
        assert fm.peer_state(peer) == "down"
        peer["probe_ok"] = True
        assert fm.peer_state(peer) == "up"
        peer["rolling"] = True
        assert fm.peer_state(peer) == "draining"
    finally:
        fm.close()


def test_fleet_peer_probing_up_and_down(tmp_path):
    """A live peer probes up; a killed peer walks degraded -> down on
    the same ladder as a local replica (no local process to poll, so
    liveness is probe-only)."""
    peer_srv = _FakePeer()
    fm = fleet.FleetManager(
        1, str(tmp_path), probe_s=0.05, down_after=2,
        base_env={"MISAKA_FLEET_PEERS": f"127.0.0.1:{peer_srv.port}",
                  "MISAKA_FLEET_PEER_KEY": "pk"},
    )
    peer = fm._peers[0]
    try:
        threading.Thread(target=fm._peer_probe_loop, args=(peer,),
                         daemon=True).start()
        deadline = time.monotonic() + 5
        while fm.peer_state(peer) != "up":
            assert time.monotonic() < deadline, "peer never probed up"
            time.sleep(0.02)
        assert peer["running"] is True and peer["degraded"] is False
        assert "pk" in peer_srv.keys  # probes authenticate with the key
        # kill the peer: probes fail, the ladder walks to down
        peer_srv.close()
        deadline = time.monotonic() + 5
        while fm.peer_state(peer) != "down":
            assert time.monotonic() < deadline, "dead peer never down"
            time.sleep(0.02)
        assert fm.state()["peers_up"] == 0
    finally:
        fm.close()
        peer_srv.close()


def test_roll_peer_drain_checkpoint_readmit(tmp_path):
    peer_srv = _FakePeer()
    fm = fleet.FleetManager(
        1, str(tmp_path),
        base_env={"MISAKA_FLEET_PEERS": f"127.0.0.1:{peer_srv.port}",
                  "MISAKA_FLEET_PEER_KEY": "pk"},
    )
    peer = fm._peers[0]
    try:
        peer["probe_ok"] = True
        entry = fm._roll_peer(peer, drain_timeout_s=5.0)
        assert entry["remote"] is True and entry["host"] == "127.0.0.1"
        # the peer host's own supervisor replaces the process
        assert entry["restored"] is False
        assert entry["checkpoint"].startswith("fleet-roll-")
        assert entry["readmitted_in_s"] >= 0
        assert peer["rolling"] is False
        posts = [(p, f) for (m, p, f) in peer_srv.calls if m == "POST"]
        drains = [f for (p, f) in posts if p == "/fleet/drain"]
        assert drains[0]["state"] == "on"
        assert drains[-1]["state"] == "off"
        assert any(p == "/checkpoint" for (p, _) in posts)
        # the checkpoint request lands AFTER the drain began
        paths = [p for (p, _) in posts]
        assert paths.index("/checkpoint") > paths.index("/fleet/drain")
    finally:
        fm.close()
        peer_srv.close()


def test_roll_peer_failure_undrains(tmp_path):
    """'deploy didn't happen, replica not lost': a failed roll step
    leaves the peer serving — the undrain still goes out."""
    peer_srv = _FakePeer(checkpoint_status=500)
    fm = fleet.FleetManager(
        1, str(tmp_path),
        base_env={"MISAKA_FLEET_PEERS": f"127.0.0.1:{peer_srv.port}"},
    )
    peer = fm._peers[0]
    try:
        peer["probe_ok"] = True
        with pytest.raises(RuntimeError, match="checkpoint failed"):
            fm._roll_peer(peer, drain_timeout_s=5.0)
        assert peer["rolling"] is False
        drains = [f for (m, p, f) in peer_srv.calls
                  if m == "POST" and p == "/fleet/drain"]
        assert drains[-1]["state"] == "off"  # best-effort undrain
    finally:
        fm.close()
        peer_srv.close()


# --- usage gossip ------------------------------------------------------------


def _flood_chain(rate=100.0, burst_s=1.0):
    return edge.EdgeChain(
        quota_defaults={"rps": rate}, burst_s=burst_s,
        auth_enabled=False, admission_enabled=False,
    )


def _rps_bucket(chain, tenant="flood"):
    with chain._lock:
        buckets = [b for (t, f, _r), b in chain._buckets.items()
                   if t == tenant and f == "rps"]
    assert len(buckets) == 1
    return buckets[0]


def test_gossip_hub_round_reconciles_peer_buckets(tmp_path):
    """The star topology end to end over real HTTP: the hub collects each
    participant's cumulative usage snapshot and pushes everyone else's
    sum back, so a tenant's admissions at replica A drain its bucket at
    replica B."""
    chain_a, chain_b = _flood_chain(), _flood_chain()
    # A admits 60 quota tokens; B only 1 (the bucket must exist — gossip
    # never mints per-tenant state for names a replica hasn't seen)
    for _ in range(3):
        assert chain_a.check("/compute", program="flood",
                             requests=20).reject is None
    assert chain_b.check("/compute", program="flood").reject is None
    srv_a, srv_b = _FakePeer(chain=chain_a), _FakePeer(chain=chain_b)
    fm = fleet.FleetManager(1, str(tmp_path), base_env={
        "MISAKA_FLEET_PEERS":
            f"127.0.0.1:{srv_a.port},127.0.0.1:{srv_b.port}",
        "MISAKA_GOSSIP_S": "0",
    })
    try:
        for p in fm._peers:
            p["probe_ok"] = True
        ok0 = fleet.M_FLEET_GOSSIP.labels(status="ok").value
        fm._gossip_round()  # collects both snapshots
        fm._gossip_round()  # distributes each side's sum to the other
        assert fleet.M_FLEET_GOSSIP.labels(status="ok").value == ok0 + 4
        # B's bucket drained by A's 60 admitted tokens (and vice versa)
        assert _rps_bucket(chain_b).tokens <= 100.0 - 1 - 60 + 1.0
        assert _rps_bucket(chain_a).tokens <= 100.0 - 60 - 1 + 1.0
        # idempotent: a third round re-ships the same cumulative totals,
        # and the per-source delta accounting drains nothing new
        t_b = _rps_bucket(chain_b).tokens
        fm._gossip_round()
        assert _rps_bucket(chain_b).tokens <= t_b + 0.5  # refill only
    finally:
        fm.close()
        srv_a.close()
        srv_b.close()


def test_gossip_loop_counts_unreachable_peer_errors(tmp_path):
    fm = fleet.FleetManager(1, str(tmp_path), base_env={
        "MISAKA_FLEET_PEERS": f"127.0.0.1:{frontends.pick_free_port()}",
        "MISAKA_GOSSIP_S": "0",
    })
    try:
        fm._peers[0]["probe_ok"] = True  # up per the prober, gone on the wire
        err0 = fleet.M_FLEET_GOSSIP.labels(status="error").value
        fm._gossip_round()
        assert fleet.M_FLEET_GOSSIP.labels(status="error").value == err0 + 1
    finally:
        fm.close()


def _simulate_flood(reconcile: bool) -> float:
    """Two replicas, one flooded tenant, simulated clock: each replica's
    edge sees 800 req/s of demand against a 400 req/s fleet quota for
    2.5 s.  Returns the aggregate admitted quota tokens.  `reconcile`
    exchanges usage snapshots every 0.2 s (the gossip cadence); without
    it each replica admits the FULL quota independently."""
    rate, burst_s, horizon, dt, gossip_every = 400.0, 0.25, 2.5, 0.0125, 0.2
    chains = [_flood_chain(rate=rate, burst_s=burst_s) for _ in range(2)]
    steps = int(horizon / dt)
    gossip_steps = int(gossip_every / dt)
    for step in range(steps):
        for c in chains:
            c.check("/compute", program="flood", requests=10)
            # advance the simulated clock: backdate every bucket stamp
            with c._lock:
                for bk in c._buckets.values():
                    bk.stamp -= dt
        if reconcile and step and step % gossip_steps == 0:
            a, b = chains
            b.apply_remote_usage(a.usage_snapshot(), source="a")
            a.apply_remote_usage(b.usage_snapshot(), source="b")
    return sum(c.usage_snapshot().get("flood|rps", 0.0) for c in chains)


def test_gossip_bounds_fleet_over_admission():
    """THE pinned acceptance factor: a flooded tenant's aggregate
    admission across 2 replicas stays <= 1.25x its quota with usage
    gossip reconciling the buckets, vs ~2x when each replica admits the
    full quota unreconciled."""
    quota = 400.0 * 2.5
    reconciled = _simulate_flood(reconcile=True)
    unreconciled = _simulate_flood(reconcile=False)
    assert unreconciled >= 1.8 * quota, unreconciled  # ~2x: the failure
    assert reconciled <= 1.25 * quota, reconciled     # the documented bound


# --- tenant tokens -----------------------------------------------------------


def test_tenant_token_mint_verify_expiry_renewal():
    secret = b"fleet-token-secret"
    tok, exp = edge.mint_tenant_token(secret, "alice", ttl_s=60.0,
                                      now=1000.0)
    assert tok.startswith(edge.TOKEN_PREFIX)
    assert exp == pytest.approx(1060.0)
    entry, why = edge.verify_tenant_token(secret, tok, now=1001.0)
    assert why == "ok"
    assert entry["tenant"] == "alice" and entry["admin"] is False
    # expiry is typed — "expired", never "invalid" (the client must know
    # to renew, not to debug its key) — and renewal just works
    entry, why = edge.verify_tenant_token(secret, tok, now=1060.0)
    assert entry is None and why == "expired"
    tok2, _ = edge.mint_tenant_token(secret, "alice", ttl_s=60.0,
                                     now=1060.0)
    assert edge.verify_tenant_token(secret, tok2, now=1061.0)[1] == "ok"
    # tampered signature, wrong secret, garbage: all "invalid"
    assert edge.verify_tenant_token(
        secret, tok2[:-2] + ("AA" if not tok2.endswith("AA") else "BB"),
        now=1061.0,
    )[1] == "invalid"
    assert edge.verify_tenant_token(b"other", tok2, now=1061.0)[1] == \
        "invalid"
    assert edge.verify_tenant_token(secret, "mst1.garbage")[1] == "invalid"
    # admin + program claims ride the signed payload
    tok3, _ = edge.mint_tenant_token(secret, "ops", ttl_s=60.0,
                                     admin=True, programs=["dense"],
                                     now=1000.0)
    entry, why = edge.verify_tenant_token(secret, tok3, now=1001.0)
    assert why == "ok" and entry["admin"] is True
    assert entry["programs"] == frozenset({"dense"})


def _write_keys(path, entries) -> str:
    with open(path, "w") as f:
        json.dump({"keys": entries}, f)
    return str(path)


def test_chain_verifies_tokens_locally(tmp_path):
    """Every replica holding the secret verifies tokens with zero
    coordination: no key-table entry, no round trip to the minter."""
    secret = b"s3"
    kf = edge.KeyFile(_write_keys(tmp_path / "k.json", [
        {"key": "adm-secret", "tenant": "ops", "admin": True},
    ]))
    chain = edge.EdgeChain(keyfile=kf, token_secret=secret,
                           quota_enabled=False, admission_enabled=False)
    tok, _ = edge.mint_tenant_token(secret, "alice", ttl_s=60.0)
    d = chain.check("/status", method="GET", key=tok)
    assert d.reject is None and d.tenant == "alice"
    # admin scope comes from the signed claim
    adm, _ = edge.mint_tenant_token(secret, "ops", ttl_s=60.0, admin=True)
    assert chain.check("/fleet/roll", key=adm).reject is None
    r = chain.check("/fleet/roll", key=tok).reject
    assert r is not None and r.status == 403
    # an expired token answers a typed 401 naming the mint route, even
    # on a replica with NO key table armed
    bare = edge.EdgeChain(token_secret=secret, quota_enabled=False,
                          admission_enabled=False)
    old, _ = edge.mint_tenant_token(secret, "alice", ttl_s=1.0,
                                    now=time.time() - 10)
    r = bare.check("/compute", key=old).reject
    assert r is not None and r.status == 401
    assert "expired" in r.message and "/edge/token" in r.message
    r = bare.check("/compute", key="mst1.bogus.sig").reject
    assert r is not None and r.status == 401 and "invalid" in r.message


def test_token_secret_sources(tmp_path, monkeypatch):
    monkeypatch.delenv("MISAKA_TOKEN_SECRET", raising=False)
    monkeypatch.delenv("MISAKA_TOKEN_SECRET_FILE", raising=False)
    monkeypatch.delenv("MISAKA_PLANE_SECRET", raising=False)
    monkeypatch.delenv("MISAKA_PLANE_SECRET_FILE", raising=False)
    assert edge.token_secret() is None
    # falls back to the plane secret: one fleet-wide secret, already
    # distributed to every replica
    monkeypatch.setenv("MISAKA_PLANE_SECRET", "plane-s")
    assert edge.token_secret() == b"plane-s"
    monkeypatch.setenv("MISAKA_TOKEN_SECRET", "token-s")
    assert edge.token_secret() == b"token-s"
    p = tmp_path / "tsecret"
    p.write_text("file-s\n")
    monkeypatch.delenv("MISAKA_TOKEN_SECRET")
    monkeypatch.setenv("MISAKA_TOKEN_SECRET_FILE", str(p))
    assert edge.token_secret() == b"file-s"


def test_edge_token_and_gossip_routes(tmp_path, monkeypatch):
    """The admin HTTP surface: POST /edge/token mints a bearer token the
    data plane accepts; POST /edge/gossip reconciles remote usage and
    answers the local snapshot."""
    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    kf = _write_keys(tmp_path / "keys.json", [
        {"key": "adm-secret", "tenant": "ops", "admin": True},
        {"key": "bob-secret", "tenant": "bob"},
    ])
    monkeypatch.setenv("MISAKA_API_KEYS", kf)
    monkeypatch.setenv("MISAKA_TOKEN_SECRET", "route-test-secret")
    m = MasterNode(
        networks.add2(in_cap=16, out_cap=16, stack_cap=16),
        chunk_steps=32, batch=2,
    )
    m.run()
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]

    import http.client

    def post(path, body, key=None, ctype="application/x-www-form-urlencoded"):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            headers = {"Content-Type": ctype}
            if key is not None:
                headers["X-Misaka-Key"] = key
            conn.request("POST", path, body, headers)
            r = conn.getresponse()
            return r.status, r.read()
        finally:
            conn.close()

    def get(path, key=None):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", path,
                         headers={"X-Misaka-Key": key} if key else {})
            r = conn.getresponse()
            return r.status, r.read()
        finally:
            conn.close()

    try:
        # minting is an admin mutation: anonymous and tenant-scoped keys
        # are refused before the route body
        assert post("/edge/token", b"tenant=alice")[0] == 401
        assert post("/edge/token", b"tenant=alice",
                    key="bob-secret")[0] == 403
        status, body = post("/edge/token", b"tenant=alice&ttl=60",
                            key="adm-secret")
        assert status == 200
        payload = json.loads(body)
        assert payload["tenant"] == "alice"
        assert payload["token"].startswith(edge.TOKEN_PREFIX)
        assert payload["ttl_s"] == 60.0
        # the minted token IS a credential on the serving surface
        assert get("/status", key=payload["token"])[0] == 200
        assert get("/status", key="mst1.not.real")[0] == 401
        # form validation is typed
        assert post("/edge/token", b"ttl=60", key="adm-secret")[0] == 400
        assert post("/edge/token", b"tenant=x&ttl=bogus",
                    key="adm-secret")[0] == 400
        # gossip: reconcile + snapshot round trip
        status, body = post(
            "/edge/gossip",
            json.dumps({"source": "peer-1",
                        "usage": {"alice|rps": 5.0}}).encode(),
            key="adm-secret", ctype="application/json",
        )
        assert status == 200
        payload = json.loads(body)
        assert payload["drained"] == 0  # no local alice bucket yet
        assert isinstance(payload["usage"], dict)
        # malformed usage is a typed 400, counted as a gossip error
        err0 = edge.M_EDGE_GOSSIP_ROUNDS.labels(status="error").value
        status, _ = post("/edge/gossip",
                         json.dumps({"usage": "nope"}).encode(),
                         key="adm-secret", ctype="application/json")
        assert status == 400
        assert edge.M_EDGE_GOSSIP_ROUNDS.labels(
            status="error").value == err0 + 1
    finally:
        m.pause()
        httpd.shutdown()
