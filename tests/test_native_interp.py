"""Native C++ interpreter vs the Python oracle vs the XLA kernel.

Three independent implementations of the superstep discipline must agree
field-for-field on fuzzed networks — the strongest cross-check the suite has
(a shared misunderstanding would have to be implemented identically three
times in three languages to slip through).
"""

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.core import CompiledNetwork, cinterp
from tests.oracle import Oracle
from tests.test_differential import IN_CAP, OUT_CAP, STACK_CAP, build_random_network

pytestmark = pytest.mark.skipif(
    not cinterp.available(), reason="native interpreter unavailable (no g++)"
)

COMPARE_KEYS = [
    "acc", "bak", "acc_hi", "bak_hi", "pc", "port_val", "port_full",
    "hold_val", "holding", "stack_top", "stack_mem_used", "in_rd", "out_wr",
    "out_buf", "tick", "retired",
]


def make_native(code, lengths, n_stacks):
    return cinterp.NativeInterpreter(
        code, lengths, max(1, n_stacks), STACK_CAP, IN_CAP, OUT_CAP
    )


@pytest.mark.parametrize("seed", range(40))
def test_matches_python_oracle(seed):
    code, lengths, n_stacks, inputs, programs = build_random_network(seed)
    oracle = Oracle(code, lengths, n_stacks, STACK_CAP, IN_CAP, OUT_CAP)
    oracle.feed(inputs)
    with make_native(code, lengths, n_stacks) as native:
        assert native.feed(inputs) == len(inputs)
        oracle.run(48)
        native.run(48)
        a, b = oracle.state_arrays(), native.state_arrays()
        for key in COMPARE_KEYS:
            # holding lanes' hold_val is architecturally meaningful only while
            # holding; both impls keep the stale latch, so compare directly.
            np.testing.assert_array_equal(
                np.asarray(a[key]), np.asarray(b[key]),
                err_msg=f"seed {seed} field {key}\nprograms: {programs}",
            )


@pytest.mark.parametrize("seed", range(8))
def test_matches_xla_kernel(seed):
    code, lengths, n_stacks, inputs, programs = build_random_network(seed)
    net = CompiledNetwork(
        code=code, prog_len=lengths, num_stacks=max(1, n_stacks),
        stack_cap=STACK_CAP, in_cap=IN_CAP, out_cap=OUT_CAP,
    )
    state = net.init_state()
    state, took = net.feed(state, inputs)
    with make_native(code, lengths, n_stacks) as native:
        assert native.feed(inputs) == took
        state = net.run(state, 48)
        native.run(48)
        b = native.state_arrays()
        np.testing.assert_array_equal(np.asarray(state.acc), b["acc"])
        np.testing.assert_array_equal(np.asarray(state.pc), b["pc"])
        np.testing.assert_array_equal(np.asarray(state.port_full), b["port_full"])
        np.testing.assert_array_equal(np.asarray(state.stack_top), b["stack_top"])
        np.testing.assert_array_equal(int(state.out_wr), b["out_wr"])
        np.testing.assert_array_equal(np.asarray(state.retired), b["retired"])


@pytest.mark.parametrize("config,transform", [
    ("add2", lambda v: v + 2),
    ("acc_loop", lambda v: v + 3),
    ("ring4", lambda v: v + 4),
    ("sorter", lambda v: 11 if v > 0 else (-11 if v < 0 else 0)),
])
def test_baseline_configs_end_to_end(config, transform):
    top = networks.BASELINE_CONFIGS[config](in_cap=16, out_cap=16, stack_cap=16)
    net = top.compile()
    with cinterp.NativeInterpreter(
        net.code, net.prog_len, net.num_stacks, 16, 16, 16
    ) as native:
        vals = [5, -3, 0, 999]
        assert native.feed(vals) == len(vals)
        native.run(400)
        assert native.drain() == [transform(v) for v in vals]


def test_feed_respects_capacity():
    top = networks.acc_loop(in_cap=4, out_cap=4)
    net = top.compile()
    with cinterp.NativeInterpreter(net.code, net.prog_len, 1, 4, 4, 4) as native:
        assert native.feed(list(range(10))) == 4


def test_invalid_tables_rejected():
    with pytest.raises(ValueError):
        cinterp.NativeInterpreter(
            np.zeros((1, 1, 7), np.int32), np.array([2], np.int32), 1, 4, 4, 4
        )


def test_malformed_shapes_rejected():
    """Wrong field/lane dimensions must raise before any pointer crosses the
    ABI (a [2,4,6] table used to over-read the buffer in C++)."""
    with pytest.raises(ValueError, match="code must be"):
        cinterp.NativeInterpreter(
            np.zeros((2, 4, 6), np.int32), np.array([1, 1], np.int32), 1, 4, 4, 4
        )
    with pytest.raises(ValueError, match="prog_len must have shape"):
        cinterp.NativeInterpreter(
            np.zeros((2, 4, 7), np.int32), np.array([1], np.int32), 1, 4, 4, 4
        )


def test_closed_handle_raises():
    top = networks.acc_loop(in_cap=4, out_cap=4)
    net = top.compile()
    n = cinterp.NativeInterpreter(net.code, net.prog_len, 1, 4, 4, 4)
    n.close()
    for call in (lambda: n.feed([1]), lambda: n.run(1), n.drain, n.state_arrays):
        with pytest.raises(RuntimeError, match="closed"):
            call()
    n.close()  # double-close is fine


def test_out_of_bounds_fields_rejected():
    """Malformed field values must be rejected at create, not corrupt memory
    at run time (MOV_NET target OOB used to segfault)."""
    from misaka_tpu.tis import isa

    def table(**fields):
        row = np.zeros((1, 1, isa.NFIELDS), np.int32)
        for name, v in fields.items():
            row[0, 0, getattr(isa, name)] = v
        return row

    bad = [
        table(F_OP=99),                                        # unknown opcode
        table(F_OP=isa.OP_MOV_NET, F_TGT=1_000_000),           # lane OOB
        table(F_OP=isa.OP_MOV_NET, F_PORT=7),                  # port OOB
        table(F_OP=isa.OP_PUSH, F_TGT=5),                      # stack OOB
        table(F_OP=isa.OP_POP, F_TGT=-1),                      # stack negative
        table(F_OP=isa.OP_JMP, F_JMP=3),                       # jump past end
        table(F_OP=isa.OP_ADD, F_SRC=42),                      # bad selector
        table(F_OP=isa.OP_IN, F_DST=9),                        # bad dst
    ]
    for code in bad:
        with pytest.raises(ValueError):
            cinterp.NativeInterpreter(code, np.array([1], np.int32), 1, 4, 4, 4)
