"""Mesh-aware serving: multi-chip execution through the PRODUCT surface.

Round-1 shipped the sharded engine as a library (parallel/sharded.py) with
no way to reach it from MasterNode/app.py; these tests pin the round-2
closure: MasterNode(data_parallel=D, model_parallel=M) serves /compute over
a (data, model) jax.sharding.Mesh — the replacement for the reference's
docker-compose scale-out (docker-compose.yml:26-74).  Runs on the 8-device
virtual CPU mesh (conftest.py), exactly as the driver's dryrun does.
"""


import numpy as np
import pytest

pytestmark = pytest.mark.slow  # virtual-mesh serving lifecycle — `make test-all` lane

from misaka_tpu import networks
from misaka_tpu.runtime.master import MasterNode


def test_data_parallel_serving_parity():
    master = MasterNode(
        networks.add2(in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=32,
        batch=16,
        data_parallel=8,
    )
    assert master.status()["mesh"] == {"data": 8, "model": 1}
    master.run()
    try:
        vals = list(range(-20, 80))
        assert master.compute_spread(vals, timeout=60) == [v + 2 for v in vals]
        assert master.compute(7, timeout=60) == 9
    finally:
        master.pause()


def test_model_parallel_serving_parity():
    # mesh8: 8 program lanes + 2 stacks (BASELINE config #5) — lanes shard
    # 1-per-chip over the 8-device mesh; MOV/stack/ring traffic crosses chips.
    master = MasterNode(
        networks.mesh8(in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=64,
        batch=2,
        model_parallel=8,
    )
    assert master.engine_name == "routed"
    assert master.status()["mesh"] == {"data": 1, "model": 8}
    master.run()
    try:
        for v in (0, 5, -3, 100):
            assert master.compute(v, timeout=60) == v + 4
    finally:
        master.pause()


def test_model_parallel_gather_engine_parity():
    # The first-generation occupancy-gather kernel stays servable behind
    # engine="gather" (A/B surface for the routed-vs-gather bench).
    master = MasterNode(
        networks.mesh8(in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=64,
        batch=2,
        model_parallel=8,
        engine="gather",
    )
    assert master.engine_name == "gather"
    master.run()
    try:
        for v in (0, 5, -3):
            assert master.compute(v, timeout=60) == v + 4
    finally:
        master.pause()


def test_dp_x_mp_combined():
    # ring4: 4 lanes over model=4, batch 4 over data=2.
    master = MasterNode(
        networks.ring(4, in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=64,
        batch=4,
        data_parallel=2,
        model_parallel=4,
    )
    master.run()
    try:
        vals = list(range(12))
        out = master.compute_spread(vals, timeout=60)
        assert out == [v + 4 for v in vals]
    finally:
        master.pause()


def test_mesh_serving_lifecycle():
    """reset / load / checkpoint keep working on a mesh (state stays sharded)."""
    master = MasterNode(
        networks.add2(in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=32,
        batch=8,
        data_parallel=4,
    )
    master.run()
    try:
        assert master.compute(1, timeout=60) == 3
    finally:
        master.pause()
    master.reset()
    master.load("misaka1", "IN ACC\nADD 10\nOUT ACC")
    master.run()
    try:
        assert master.compute(1, timeout=60) == 11
    finally:
        master.pause()


def test_mesh_checkpoint_roundtrip(tmp_path):
    master = MasterNode(
        networks.add2(in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=32,
        batch=8,
        data_parallel=4,
    )
    master.run()
    try:
        assert master.compute(7, timeout=60) == 9
    finally:
        master.pause()
    path = str(tmp_path / "mesh.npz")
    master.save_checkpoint(path)

    m2 = MasterNode(
        networks.add2(in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=32,
        batch=8,
        data_parallel=4,
    )
    m2.load_checkpoint(path)
    m2.run()
    try:
        assert m2.compute(100, timeout=60) == 102
    finally:
        m2.pause()


def test_mesh_mp_load_rebuilds_route_table():
    """/load on a model-parallel mesh recompiles the routed kernel: the new
    program's MOV_NET edges produce a NEW static route table (the old one
    must not leak into the rebuilt runner)."""
    master = MasterNode(
        networks.ring(4, in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=64, batch=2, model_parallel=4,
    )
    master.run()
    try:
        assert master.compute(5, timeout=60) == 9  # ring4: v + 4
    finally:
        master.pause()
    # reroute ring0: skip the lap, add 10, emit (edges change: ring0 no
    # longer sends to ring1 — its dest slot disappears from the table)
    master.load("ring0", "IN ACC\nADD 10\nOUT ACC")
    master.run()
    try:
        assert master.engine_name == "routed"
        assert master.compute(5, timeout=60) == 15
    finally:
        master.pause()


def test_mesh_mp_autogrow():
    """Stack auto-grow under model-parallel serving: the grow path rebuilds
    the routed runner for the doubled stack_cap and pads the sharded state."""
    from misaka_tpu.runtime.topology import Topology

    top = Topology(
        node_info={"p": "program", "q": "program", "st": "stack"},
        programs={
            # p: push until 0 sentinel, then emit sentinel and drain (needs
            # depth len(values), wedges at stack_cap=8 with 12 values)
            "p": (
                "top: IN ACC\nJEZ dump\nPUSH ACC, st\nJMP top\n"
                "dump: OUT ACC\npop: POP st, ACC\nOUT ACC\nJMP pop\n"
            ),
            "q": "NOP\n",  # second lane so the lane axis shards over mp=2
        },
        in_cap=32, out_cap=32, stack_cap=8,
    )
    master = MasterNode(top, chunk_steps=32, batch=2, model_parallel=2)
    master.run()
    try:
        vals = list(range(1, 13))
        outs = master.compute_many(vals + [0], timeout=90)
        assert outs == [0] + vals[::-1]
    finally:
        master.pause()
    assert master._net.stack_cap >= 16
    assert master.engine_name == "routed"


def test_mesh_mp_checkpoint_roundtrip(tmp_path):
    """Checkpoint/restore with lane-sharded (model-parallel) state: the
    snapshot gathers sharded arrays to host; restore re-places them on the
    mesh with the canonical shardings."""
    def fresh():
        return MasterNode(
            networks.mesh8(in_cap=8, out_cap=8, stack_cap=8),
            chunk_steps=64, batch=2, model_parallel=8,
        )

    m1 = fresh()
    m1.run()
    try:
        assert m1.compute(7, timeout=60) == 11
    finally:
        m1.pause()
    path = str(tmp_path / "mesh_mp.npz")
    m1.save_checkpoint(path)

    m2 = fresh()
    m2.load_checkpoint(path)
    m2.run()
    try:
        assert m2.compute(100, timeout=60) == 104
    finally:
        m2.pause()


def test_mesh_requires_batch_and_divisibility():
    with pytest.raises(ValueError, match="requires batch"):
        MasterNode(networks.add2(), data_parallel=8)
    with pytest.raises(ValueError, match="not divisible"):
        MasterNode(networks.add2(), batch=3, data_parallel=2)
    with pytest.raises(ValueError, match="lanes not divisible"):
        MasterNode(networks.add2(), batch=2, model_parallel=8)  # add2 has 2 lanes
    with pytest.raises(ValueError, match="single-chip"):
        MasterNode(networks.add2(), batch=8, data_parallel=8, trace_cap=16)


def test_mesh_env_surface():
    """app.py's MISAKA_DATA_PARALLEL/MODEL_PARALLEL reach the mesh master."""
    import json

    from misaka_tpu.runtime.app import build_topology_from_env

    env = {
        "NODE_INFO": json.dumps(
            {
                "misaka1": {"type": "program"},
                "misaka2": {"type": "program"},
                "misaka3": {"type": "stack"},
            }
        ),
        "MISAKA_PROGRAMS": json.dumps(
            {
                "misaka1": "IN ACC\nADD 1\nMOV ACC, misaka2:R0\nMOV R0, ACC\nOUT ACC",
                "misaka2": "MOV R0, ACC\nADD 1\nPUSH ACC, misaka3\nPOP misaka3, ACC\nMOV ACC, misaka1:R0",
            }
        ),
    }
    top = build_topology_from_env(env)
    master = MasterNode(top, chunk_steps=32, batch=8, data_parallel=2)
    assert master.status()["mesh"] == {"data": 2, "model": 1}
    master.run()
    try:
        assert master.compute(5, timeout=60) == 7
    finally:
        master.pause()
