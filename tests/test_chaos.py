"""Chaos suite: the r9 fault-tolerance plane under injected failure.

Covers the durable-checkpoint contract (atomic writes, manifest
verification rejecting truncation at any byte offset, torn-write and
crash-mid-save fault points, auto-checkpoint rotation + fallback
restore), the fault harness itself (MISAKA_FAULTS spec), the RPC backoff
policy, and the frontend supervisor (kill -9 respawn, crash-loop circuit
breaker, degraded-state surfacing, recovery under concurrent client load
with zero client-visible errors).

`make chaos-smoke` runs the fast lane of this file; the multi-second
process-pool scenarios are marked slow (the `make test-all` lane).
"""

import json
import os
import shutil
import signal
import threading
import time

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.runtime.master import (
    AutoCheckpointer,
    CheckpointError,
    MasterNode,
    make_http_server,
    manifest_path,
    verify_checkpoint,
)
from misaka_tpu.utils import faults, metrics


def _master(batch=None, **kw):
    return MasterNode(
        networks.add2(in_cap=16, out_cap=16, stack_cap=16),
        chunk_steps=32, engine="scan", batch=batch, **kw,
    )


def _snap():
    return metrics.parse_text(metrics.render())


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No test may leak an armed fault into the rest of the suite."""
    yield
    faults.configure(None)


# --- the fault harness ------------------------------------------------------


def test_fault_spec_parsing():
    spec = faults.parse_spec("ckpt_torn_write=0.5, rpc_delay=0.2@0.1,worker_exit")
    assert spec == {
        "ckpt_torn_write": (0.5, 1.0),
        "rpc_delay": (0.2, 0.1),
        "worker_exit": (1.0, 1.0),
    }
    assert faults.parse_spec("") == {}
    assert faults.parse_spec(None) == {}
    # the multi-host plane points: bare, scoped-to-one-peer, and delay
    spec = faults.parse_spec(
        "plane_partition,plane_partition:10.0.0.2:9001,plane_delay=0.05@0.5"
    )
    assert spec == {
        "plane_partition": (1.0, 1.0),
        "plane_partition:10.0.0.2:9001": (1.0, 1.0),
        "plane_delay": (0.05, 0.5),
    }
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec("plane_delay:peer=0.1")  # plane_delay is unscoped
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec("plane_partition:")  # empty scope
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec("not_a_point=1")
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec("rpc_drop@2")  # probability out of range
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec("rpc_delay=abc")


def test_fault_fire_and_disarm():
    faults.configure("ckpt_torn_write=0.25")
    assert faults.active() == {"ckpt_torn_write"}
    assert faults.fire("ckpt_torn_write") == 0.25
    assert faults.fire("rpc_drop") is None
    faults.configure(None)
    assert faults.fire("ckpt_torn_write") is None
    # probability 0 never fires
    faults.configure("rpc_drop@0")
    assert all(faults.fire("rpc_drop") is None for _ in range(50))


def test_backoff_bounded_and_jittered():
    # the ONE shared policy (utils/backoff.py): node RPC retries
    # (transport/rpc.py re-exports it), supervisor respawns, client
    # connect-retry all ride this curve
    from misaka_tpu.utils.backoff import Backoff

    b = Backoff(base=0.1, cap=1.0)
    raw = [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]  # doubles, then pinned at the cap
    for expect in raw:
        d = b.next_delay()
        assert expect * 0.5 <= d <= expect  # jitter in [delay/2, delay]
    b.reset()
    assert b.next_delay() <= 0.1  # fast first retry again
    assert b.delay_for(10) <= 1.0  # the stateless form honors the cap too
    with pytest.raises(ValueError):
        Backoff(base=2.0, cap=1.0)


# --- durable checkpoints ----------------------------------------------------


def test_save_checkpoint_atomic_with_manifest(tmp_path):
    m = _master()
    path = str(tmp_path / "ck.npz")
    m.save_checkpoint(path)
    # manifest sidecar describes the exact bytes on disk
    with open(manifest_path(path)) as f:
        manifest = json.load(f)
    assert manifest["size"] == os.path.getsize(path)
    assert len(manifest["sha256"]) == 64
    verify_checkpoint(path)  # passes
    # no tmp litter: the write path either commits or cleans up
    assert [n for n in os.listdir(tmp_path) if ".tmp." in n] == []
    m2 = _master()
    m2.load_checkpoint(path)


def test_truncated_checkpoint_rejected_at_any_offset(tmp_path):
    m = _master()
    path = str(tmp_path / "ck.npz")
    m.save_checkpoint(path)
    blob = open(path, "rb").read()
    before = _snap()
    cut = str(tmp_path / "cut.npz")
    offsets = [0, 1, len(blob) // 4, len(blob) // 2, len(blob) - 1]
    for offset in offsets:
        with open(cut, "wb") as f:
            f.write(blob[:offset])
        shutil.copy(manifest_path(path), manifest_path(cut))
        with pytest.raises(CheckpointError):
            verify_checkpoint(cut)
        with pytest.raises(CheckpointError):
            _master().load_checkpoint(cut)
        # legacy shape too: no manifest, the zip CRC/central-dir walk rejects
        os.unlink(manifest_path(cut))
        with pytest.raises(CheckpointError):
            verify_checkpoint(cut)
    delta = metrics.delta(before, _snap())
    assert delta.get("misaka_checkpoint_rejected_total", 0) >= 3 * len(offsets)


def test_stale_manifest_with_intact_file_heals(tmp_path):
    """The overwrite crash window: the data rename commits but the process
    dies before the manifest rename, leaving a fully valid NEW checkpoint
    under the OLD sidecar.  verify_checkpoint must accept it via the CRC
    fallback — rejecting committed data (whose predecessor is already
    gone) would turn one crash into permanent loss."""
    m = _master()
    path = str(tmp_path / "ck.npz")
    m.save_checkpoint(path)
    stale_manifest = open(manifest_path(path), "rb").read()
    m.run()
    try:
        assert m.compute(1) == 3  # state moves, so the second save differs
    finally:
        m.pause()
    m.save_checkpoint(path)
    with open(manifest_path(path), "wb") as f:
        f.write(stale_manifest)  # simulate the crash between the renames
    verify_checkpoint(path)  # accepted: intact npz, stale sidecar
    _master().load_checkpoint(path)
    # but a file that ALSO fails the CRC walk stays rejected
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(CheckpointError):
        verify_checkpoint(path)


def test_corrupt_byte_rejected_by_checksum(tmp_path):
    m = _master()
    path = str(tmp_path / "ck.npz")
    m.save_checkpoint(path)
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF  # same size, different content
    with open(path, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(CheckpointError):
        verify_checkpoint(path)


def test_ckpt_crash_fault_leaves_target_intact(tmp_path):
    m = _master()
    path = str(tmp_path / "ck.npz")
    m.save_checkpoint(path)
    good = open(path, "rb").read()
    faults.configure("ckpt_crash")
    with pytest.raises(OSError):
        m.save_checkpoint(path)
    faults.configure(None)
    # the crash landed between the tmp write and the atomic replace: the
    # previous checkpoint is byte-identical, still verified, still loadable,
    # and no tmp litter survives
    assert open(path, "rb").read() == good
    verify_checkpoint(path)
    _master().load_checkpoint(path)
    assert [n for n in os.listdir(tmp_path) if ".tmp." in n] == []


def test_ckpt_torn_write_fault_rejected_then_recovers(tmp_path):
    m = _master()
    m.run()
    try:
        assert m.compute(1) == 3
        path = str(tmp_path / "ck.npz")
        faults.configure("ckpt_torn_write=0.5")
        m.save_checkpoint(path)  # the file on disk is torn at 50%
        faults.configure(None)
        with pytest.raises(CheckpointError):
            _master().load_checkpoint(path)
        # the serving master was never touched by the failed durability
        # round trip — and a clean retry fully recovers
        assert m.compute(2) == 4
        m.save_checkpoint(path)
        _master().load_checkpoint(path)
    finally:
        m.pause()


def test_autockpt_rotation_and_fallback_restore(tmp_path):
    m = _master()
    m.run()
    try:
        assert m.compute(5) == 7
    finally:
        m.pause()
    ckdir = str(tmp_path / "auto")
    ac = AutoCheckpointer(m, ckdir, interval_s=3600, keep=3)
    try:
        for _ in range(5):
            ac.save_once()
    finally:
        ac.close()
    snaps = AutoCheckpointer.snapshots(ckdir)
    assert len(snaps) == 3  # rotation kept the newest `keep`
    assert os.path.basename(snaps[0]) == "auto-00000005.npz"
    # tear the newest: boot restore must fall back to the next valid one
    with open(snaps[0], "r+b") as f:
        f.truncate(os.path.getsize(snaps[0]) // 2)
    m2 = _master()
    restored = AutoCheckpointer.restore_latest(m2, ckdir)
    assert restored == snaps[1]
    m2.run()
    try:
        assert m2.compute(1) == 3  # serving resumes from the restored state
    finally:
        m2.pause()
    # a fresh directory is a fresh boot, not an error
    assert AutoCheckpointer.restore_latest(_master(), str(tmp_path / "empty")) is None


def test_autockpt_periodic_thread_snapshots(tmp_path):
    m = _master()
    ckdir = str(tmp_path / "auto")
    ac = AutoCheckpointer(m, ckdir, interval_s=0.05, keep=2)
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if len(AutoCheckpointer.snapshots(ckdir)) == 2:
                break
            time.sleep(0.02)
        snaps = AutoCheckpointer.snapshots(ckdir)
        assert len(snaps) == 2
        for s in snaps:
            verify_checkpoint(s)
    finally:
        ac.close()


def test_checkpoint_age_metric_tracks_saves(tmp_path):
    m = _master()
    age = metrics.REGISTRY.get("misaka_checkpoint_age_seconds")
    assert age.value == -1.0  # no save yet on the live master
    m.save_checkpoint(str(tmp_path / "ck.npz"))
    assert 0.0 <= age.value < 60.0


# --- frontend supervisor ----------------------------------------------------


def _supervisor(n, tmp_path, **kw):
    from misaka_tpu.runtime import frontends

    port = frontends.pick_free_port()
    sup = frontends.FrontendSupervisor(
        n, port, "http://127.0.0.1:9", str(tmp_path / "plane.sock"), **kw
    )
    return sup, port


def _wait_for(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return predicate()


def test_supervisor_respawns_kill9_and_surfaces_degraded(tmp_path):
    from misaka_tpu.runtime import frontends

    sup, port = _supervisor(2, tmp_path, backoff_base=0.4, poll_s=0.05)
    try:
        assert frontends.wait_ready(port)
        assert _wait_for(lambda: sup.state()["alive"] == 2)
        victim = sup._slots[0]["proc"].pid
        os.kill(victim, signal.SIGKILL)
        # the shrunk pool is never silent: degraded shows while down
        assert _wait_for(lambda: sup.state()["degraded"], timeout=5)
        st = sup.state()
        assert st["alive"] == 1 and st["configured"] == 2
        # ... and the supervisor restores strength on its own
        assert _wait_for(lambda: sup.state()["alive"] == 2, timeout=5)
        st = sup.state()
        assert st["restarts_total"] == 1 and not st["degraded"]
        assert frontends.wait_ready(port)
    finally:
        sup.close()


def test_supervisor_circuit_breaker_stops_crash_loop(tmp_path, monkeypatch):
    # every spawned worker hard-exits right after boot (the worker_exit
    # fault point, inherited via the environment): the breaker must open
    # instead of fork-bombing the host
    monkeypatch.setenv("MISAKA_FAULTS", "worker_exit=0")
    sup, _ = _supervisor(
        1, tmp_path, backoff_base=0.02, fast_crash_s=5.0,
        breaker_threshold=2, breaker_reset_s=60.0, poll_s=0.05,
    )
    try:
        assert _wait_for(lambda: sup.state()["breaker_open"] == 1, timeout=20)
        st = sup.state()
        assert st["degraded"] and st["alive"] == 0
        settled = sup.state()["restarts_total"]
        time.sleep(0.5)
        assert sup.state()["restarts_total"] == settled  # breaker holds
    finally:
        sup.close()


@pytest.mark.slow
def test_kill9_under_concurrent_load_zero_client_errors(tmp_path):
    """The acceptance scenario: kill -9 one frontend worker under sustained
    concurrent load — capacity restored automatically, no client-visible
    errors beyond the pooled client's single stale-socket retry, restart
    visible in /metrics."""
    from misaka_tpu.client import MisakaClient
    from misaka_tpu.runtime import frontends

    m = _master(batch=8)
    engine_httpd = make_http_server(m, port=0)
    threading.Thread(target=engine_httpd.serve_forever, daemon=True).start()
    plane_path = str(tmp_path / "plane.sock")
    plane = frontends.start_compute_plane(m, plane_path)
    port = frontends.pick_free_port()
    before = _snap()
    sup = frontends.FrontendSupervisor(
        2, port, f"http://127.0.0.1:{engine_httpd.server_address[1]}",
        plane_path, backoff_base=0.05, fast_crash_s=0.5, poll_s=0.05,
    )
    engine_httpd.misaka_supervisor = sup
    m.run()
    errors: list[Exception] = []
    stop = threading.Event()
    warmed = threading.Semaphore(0)

    def client_loop(i):
        c = MisakaClient(f"http://127.0.0.1:{port}", timeout=20)
        vals = (np.arange(16, dtype=np.int32) + i) % 1000
        try:
            # warm-up: the first request parks this client's socket in the
            # pool, so everything in flight at kill time rides a REUSED
            # connection — the shape retry_stale's single replay covers
            # (a fresh first dial is deliberately not replayed)
            out = c.compute_raw(vals)
            warmed.release()
            if not np.array_equal(out, vals + 2):
                raise AssertionError(f"client {i}: wrong warm-up outputs")
            while not stop.is_set():
                out = c.compute_raw(vals)
                if not np.array_equal(out, vals + 2):
                    raise AssertionError(f"client {i}: wrong outputs")
        except Exception as e:  # noqa: BLE001 — collected for the assert
            warmed.release()
            errors.append(e)
        finally:
            c.close()

    try:
        assert frontends.wait_ready(port)
        threads = [
            threading.Thread(target=client_loop, args=(i,), daemon=True)
            for i in range(32)
        ]
        for t in threads:
            t.start()
        for _ in range(32):  # every client warmed (socket pooled)
            assert warmed.acquire(timeout=30)
        assert errors == []
        time.sleep(0.5)  # sustained load on pooled keep-alive sockets
        victim = sup._slots[0]["proc"].pid
        os.kill(victim, signal.SIGKILL)
        assert _wait_for(lambda: sup.state()["alive"] == 2, timeout=5)
        time.sleep(1.0)  # keep serving through and after the recovery
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert errors == []
        st = sup.state()
        assert st["restarts_total"] >= 1 and not st["degraded"]
        delta = metrics.delta(before, _snap())
        assert delta.get("misaka_frontend_restarts_total", 0) >= 1
        # /healthz carries the supervisor surface end to end
        import urllib.request

        engine_port = engine_httpd.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{engine_port}/healthz", timeout=10
        ) as resp:
            payload = json.loads(resp.read())
        assert payload["frontends"]["configured"] == 2
        assert payload["degraded"] is False
    finally:
        stop.set()
        m.pause()
        sup.close()
        plane.close()
        engine_httpd.shutdown()


# --- client connect-retry ---------------------------------------------------


def test_client_connect_retry_rides_out_restart_window():
    """A refused FRESH dial (server restarting) is retried with backoff —
    the satellite to the supervisor's respawn; retries are exactly-once
    safe because a refused connect never sent anything."""
    import socket
    import urllib.error

    from misaka_tpu.client import MisakaClient
    from misaka_tpu.runtime import frontends

    port = frontends.pick_free_port()
    # nothing listens: opt-out surfaces the refusal immediately
    c0 = MisakaClient(f"http://127.0.0.1:{port}", timeout=5, connect_retries=0)
    t0 = time.monotonic()
    with pytest.raises(urllib.error.URLError):
        c0.healthz()
    assert time.monotonic() - t0 < 0.5
    # with retries armed: the server boots DURING the backoff window and
    # the same request lands on it (the rolling-restart shape)
    m = _master()
    holder: list = []

    def serve_late():
        time.sleep(0.3)
        server = make_http_server(m, port=port)
        holder.append(server)
        server.serve_forever()

    threading.Thread(target=serve_late, daemon=True).start()
    c = MisakaClient(f"http://127.0.0.1:{port}", timeout=5, connect_retries=6)
    try:
        assert c.healthz()["ok"] is True
    finally:
        c.close()
        if holder:
            holder[0].shutdown()


# --- distributed peer health ------------------------------------------------


@pytest.mark.slow
def test_dead_peer_fails_fast_typed_and_recovers(monkeypatch):
    """A downed distributed peer yields PeerUnavailable well inside the
    request deadline (not a 30s park), /status shows the peer down, and
    the cluster recovers with NO master restart once the peer returns."""
    pytest.importorskip("grpc")
    from misaka_tpu.runtime.master import PeerUnavailable
    from misaka_tpu.runtime.nodes import (
        MasterNodeProcess,
        ProgramNodeProcess,
        Resolver,
    )

    monkeypatch.setenv("MISAKA_PEER_PROBE_S", "0.2")
    monkeypatch.setenv("MISAKA_PEER_DOWN_AFTER", "2")
    program = "IN ACC\nADD 2\nOUT ACC"
    resolver = Resolver()
    node = ProgramNodeProcess(
        master_uri="last_order", resolver=resolver,
        grpc_port=0, host="127.0.0.1",
    )
    node.load_program(program)
    port = node.start()
    resolver.set_addr("n", f"127.0.0.1:{port}")
    master = MasterNodeProcess(
        node_info={"n": {"type": "program"}},
        resolver=resolver, grpc_port=0, host="127.0.0.1",
    )
    resolver.set_addr("last_order", f"127.0.0.1:{master.start()}")
    replacement = None
    try:
        master.run()
        assert master.compute(1, timeout=30) == 3
        node.close()  # the peer dies outright
        assert _wait_for(
            lambda: master.status()["peers"]["n"]["state"] == "down",
            timeout=10,
        )
        t0 = time.monotonic()
        with pytest.raises(PeerUnavailable):
            master.compute(2, timeout=30)
        assert time.monotonic() - t0 < 5  # typed fast-fail, not a 30s park
        # peer returns on the SAME address: health flips up, service resumes
        replacement = ProgramNodeProcess(
            master_uri="last_order", resolver=resolver,
            grpc_port=port, host="127.0.0.1",
        )
        replacement.load_program(program)
        replacement.start()
        replacement.run_cmd()
        assert _wait_for(
            lambda: master.status()["peers"]["n"]["state"] == "up",
            timeout=10,
        )
        assert master.compute(10, timeout=30) == 12
    finally:
        master.close()
        node.close()
        if replacement is not None:
            replacement.close()


# --- the program registry under chaos (runtime/registry.py) -----------------


def test_swap_during_load_fault_point_parses():
    spec = faults.parse_spec("swap_during_load=0.3")
    assert spec == {"swap_during_load": (0.3, 1.0)}


@pytest.mark.slow
def test_hot_swap_under_pooled_load_zero_errors():
    """The swap_during_load chaos scenario: publish a new program version
    while 64 POOLED keep-alive clients hammer the program's compute
    route, with the fault point holding the swap's park gate closed for
    0.5s (the widened race window).  The contract: ZERO client-visible
    errors — every response is either the old or the new program's
    output, request-consistently — and the evicted old version's state
    round-trips bit-identically through its manifest-verified checkpoint.
    """
    from misaka_tpu.client import MisakaClient
    from misaka_tpu.runtime.registry import ProgramRegistry
    from misaka_tpu.runtime.topology import Topology

    small = dict(stack_cap=16, in_cap=16, out_cap=16)
    reg = ProgramRegistry(None, batch=4, engine="scan", chunk_steps=32,
                          caps=small)
    top = networks.add2(**small)
    master = MasterNode(top, chunk_steps=32, batch=4, engine="scan")
    reg.seed("default", master, top)
    httpd = make_http_server(master, port=0, registry=reg)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    master.run()
    v_old = reg.publish(
        "victim", tis="IN ACC\nADD 10\nOUT ACC\n"
    )["version"]

    n_clients = 64
    stop = threading.Event()
    start_bar = threading.Barrier(n_clients + 1)
    failures: list = []
    bad: list = []
    counts = [0] * n_clients

    def client_loop(i):
        c = MisakaClient(base, program="victim", timeout=60)
        try:
            c.compute_raw([0])  # warm the pooled connection pre-barrier
            start_bar.wait()
            while not stop.is_set():
                vals = [i, i + 1]
                out = c.compute_raw(vals).tolist()
                if out not in ([i + 10, i + 11], [i + 20, i + 21]):
                    bad.append((i, out))
                    return
                counts[i] += 1
        except Exception as e:  # pragma: no cover — the failure path
            failures.append((i, repr(e)))
            stop.set()
        finally:
            c.close()

    threads = [
        threading.Thread(target=client_loop, args=(i,))
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    try:
        start_bar.wait(timeout=60)
        time.sleep(0.3)  # sustained pre-swap load
        faults.configure("swap_during_load=0.5")  # park gate held closed
        out = reg.publish("victim", tis="IN ACC\nADD 20\nOUT ACC\n")
        assert out["swapped"]
        time.sleep(0.5)  # sustained post-swap load
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    assert not failures, failures[:3]
    assert not bad, bad[:3]
    assert sum(counts) > n_clients  # the fleet really ran through the swap
    # post-swap traffic serves the new version...
    with reg.lease("victim") as m:
        assert m.compute_coalesced([1]) == [21]
    # ...and the drained old version checkpointed durably: the manifest
    # gate passes and a fresh engine restores EXACTLY the saved arrays
    ckpt = reg._state_path("victim", v_old)
    verify_checkpoint(ckpt)
    fresh = MasterNode(
        Topology(node_info={"main": "program"},
                 programs={"main": "IN ACC\nADD 10\nOUT ACC\n"}, **small),
        chunk_steps=32, batch=4, engine="scan",
    )
    fresh.load_checkpoint(ckpt)
    snap = fresh.snapshot()
    with np.load(ckpt) as data:
        for field in snap._fields:
            if field in data:
                np.testing.assert_array_equal(
                    np.asarray(getattr(snap, field)), data[field],
                    err_msg=field,
                )
    fresh.close()
    # the old version is still addressable and revives from its checkpoint
    with reg.lease(f"victim@{v_old}") as m:
        assert m.compute_coalesced([1]) == [11]
    master.pause()
    reg.close()
    httpd.shutdown()


# --- the production edge: overload shed + quota exhaustion ------------------


def test_overload_shed_tenant_isolation(tmp_path, monkeypatch):
    """The edge shed drill at the REAL admission sites (runtime/edge.py):
    with `overload:<tenant>` armed, every flooded-tenant request is shed
    with a typed 429 + Retry-After at the door, while the neighbor
    tenant's in-quota traffic sees ZERO client-visible errors — and the
    shed is visible on misaka_edge_rejected_total with tenant labels."""
    from misaka_tpu.client import MisakaClient, MisakaClientError
    from misaka_tpu.runtime import edge

    keyfile = tmp_path / "keys.json"
    with open(keyfile, "w") as f:
        json.dump({"keys": [
            {"key": "flood-key", "tenant": "flood"},
            {"key": "good-key", "tenant": "good"},
        ]}, f)
    monkeypatch.setenv("MISAKA_API_KEYS", str(keyfile))
    m = _master(batch=4)
    m.run()
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        before = _snap().get(
            'misaka_edge_rejected_total{reason="overload",tenant="flood"}', 0
        )
        faults.configure("overload:flood")
        results = {"flood_429": 0, "flood_other": 0, "good_err": 0,
                   "good_ok": 0}
        lock = threading.Lock()

        def flood_worker():
            c = MisakaClient(base, api_key="flood-key")
            for _ in range(10):
                try:
                    c.compute(1)
                    with lock:
                        results["flood_other"] += 1
                except MisakaClientError as e:
                    with lock:
                        if e.status == 429 and e.retry_after is not None:
                            results["flood_429"] += 1
                        else:
                            results["flood_other"] += 1
            c.close()

        def good_worker():
            c = MisakaClient(base, api_key="good-key")
            for i in range(10):
                try:
                    assert int(c.compute(i)) == i + 2
                    with lock:
                        results["good_ok"] += 1
                except Exception:
                    with lock:
                        results["good_err"] += 1
            c.close()

        threads = [threading.Thread(target=flood_worker) for _ in range(4)]
        threads += [threading.Thread(target=good_worker) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        # every flooded request shed with the typed 429; zero of anything
        # else — and the neighbor saw zero errors of any kind
        assert results["flood_429"] == 40
        assert results["flood_other"] == 0
        assert results["good_ok"] == 20
        assert results["good_err"] == 0
        after = _snap().get(
            'misaka_edge_rejected_total{reason="overload",tenant="flood"}', 0
        )
        assert after - before == 40
    finally:
        faults.configure(None)
        edge.reset()
        m.pause()
        httpd.shutdown()


def test_quota_exhaust_fault_backs_clients_off(tmp_path, monkeypatch):
    """`quota_exhaust` trips the quota stage at its real site: typed 429
    whose Retry-After the client surfaces (MisakaClientError.retry_after)
    so callers back off instead of retrying hot."""
    from misaka_tpu.client import MisakaClient, MisakaClientError
    from misaka_tpu.runtime import edge

    keyfile = tmp_path / "keys.json"
    with open(keyfile, "w") as f:
        json.dump({"keys": [{"key": "k", "tenant": "t"}]}, f)
    monkeypatch.setenv("MISAKA_API_KEYS", str(keyfile))
    m = _master(batch=2)
    m.run()
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    c = MisakaClient(
        f"http://127.0.0.1:{httpd.server_address[1]}", api_key="k"
    )
    try:
        assert int(c.compute(1)) == 3
        faults.configure("quota_exhaust")
        with pytest.raises(MisakaClientError) as ei:
            c.compute(1)
        assert ei.value.status == 429
        assert ei.value.retry_after is not None
        # recovery: disarm and the tenant serves again
        faults.configure(None)
        assert int(c.compute(2)) == 4
    finally:
        faults.configure(None)
        edge.reset()
        c.close()
        m.pause()
        httpd.shutdown()
