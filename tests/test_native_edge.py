"""The native C++ edge tier (native/frontend.cpp + runtime/frontends.py
NativeFrontendSupervisor): byte-level route parity against the CPython
route table, typed edge rejections from pushed state, keep-alive
discipline, and the fallback ladder.

The parity oracle is the engine's own HTTP server on the SAME master:
every hot-route response the native tier produces (plane-shipped compute,
locally-answered 401/413, wire-protocol 400s) must be bit-identical in
status + body + load-bearing headers to what the CPython route table
answers for the same bytes.  Responses that legitimately differ per
request (Date, Server, Server-Timing, X-Misaka-Trace) are normalized out.
"""

import http.client
import json
import struct
import threading
import time

import pytest

from misaka_tpu import networks
from misaka_tpu.runtime import edge
from misaka_tpu.runtime import frontends
from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.utils import faults
from misaka_tpu.utils import wire


def _master(batch=4, engine="scan", **kw):
    return MasterNode(
        networks.add2(in_cap=16, out_cap=16, stack_cap=16),
        chunk_steps=32, batch=batch, engine=engine, **kw,
    )


def _write_keys(path, entries) -> str:
    with open(path, "w") as f:
        json.dump({"keys": entries}, f)
    return str(path)


# Two burst-capped tenants with IDENTICAL specs: the 429 parity probe
# sends each tier a different tenant so the shared process-level token
# buckets never cross-contaminate the A/B legs.
KEYS = [
    {"key": "adm-secret", "tenant": "ops", "admin": True},
    {"key": "tiny-a-secret", "tenant": "tiny-a", "quota": "vps<4"},
    {"key": "tiny-b-secret", "tenant": "tiny-b", "quota": "vps<4"},
    {"key": "eve-secret", "tenant": "eve", "disabled": True},
]

pytestmark = pytest.mark.skipif(
    not frontends._FRONTEND_LIB.available(),
    reason="native frontend.so unavailable (no g++?)",
)


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    edge.reset()
    faults.configure(None)


@pytest.fixture
def tiers(tmp_path, monkeypatch):
    """One shared master behind BOTH tiers: the engine's CPython HTTP
    server (the parity oracle and the native tier's proxy target) and
    the C++ edge speaking the same compute plane."""
    kf = _write_keys(tmp_path / "keys.json", KEYS)
    monkeypatch.setenv("MISAKA_API_KEYS", kf)
    monkeypatch.setenv("MISAKA_MAX_BODY", "65536")
    m = _master(batch=2)
    m.run()
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    engine_port = httpd.server_address[1]
    plane_path = str(tmp_path / "plane.sock")
    plane = frontends.start_compute_plane(m, plane_path)
    sup = frontends.NativeFrontendSupervisor(
        port=0, proxy_port=engine_port, plane_path=plane_path,
        threads=2, plane_conns=1,
    )
    try:
        yield engine_port, sup.port
    finally:
        sup.close()
        plane.close()
        m.pause()
        httpd.shutdown()


def _req(port, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    conn.request(method, path, body=body, headers=headers or {})
    r = conn.getresponse()
    data = r.read()
    hdrs = {k.lower(): v for k, v in r.getheaders()}
    conn.close()
    return r.status, hdrs, data


# headers compared byte-for-byte when present on either side; everything
# per-request (Date, Server, Server-Timing, X-Misaka-Trace, Connection,
# Keep-Alive) is normalized out
_PARITY_HEADERS = ("content-type", "content-length", "retry-after",
                   "www-authenticate")


def _parity(engine_port, native_port, method, path, body=None,
            headers=None, native_headers=None):
    es, eh, eb = _req(engine_port, method, path, body, headers)
    ns, nh, nb = _req(native_port, method, path, body,
                      native_headers or headers)
    assert (ns, nb) == (es, eb), (
        f"{method} {path}: native {ns} {nb!r} != engine {es} {eb!r}"
    )
    for h in _PARITY_HEADERS:
        assert nh.get(h) == eh.get(h), (
            f"{method} {path}: header {h}: native {nh.get(h)!r} != "
            f"engine {eh.get(h)!r}"
        )
    return ns, nh, nb


# --- byte parity: success shapes --------------------------------------------


def test_parity_raw_legacy(tiers):
    engine_port, native_port = tiers
    body = struct.pack("<4i", 1, 2, 3, 4)
    s, h, b = _parity(engine_port, native_port, "POST", "/compute_raw",
                      body, {"X-Misaka-Key": "adm-secret"})
    assert s == 200
    assert h["content-type"] == "application/octet-stream"
    assert struct.unpack("<4i", b) == (3, 4, 5, 6)


def test_parity_raw_binary_wire(tiers):
    engine_port, native_port = tiers
    payload = struct.pack("<3i", 10, 20, 30)
    body = wire.pack(payload)
    s, h, b = _parity(
        engine_port, native_port, "POST", "/compute_raw", body,
        {"X-Misaka-Key": "adm-secret", "Content-Type": wire.CONTENT_TYPE,
         "Accept": wire.CONTENT_TYPE},
    )
    assert s == 200
    assert h["content-type"] == wire.CONTENT_TYPE
    assert struct.unpack("<3i", wire.unpack(b)) == (12, 22, 32)


def test_parity_compute_form(tiers):
    engine_port, native_port = tiers
    s, _, b = _parity(engine_port, native_port, "POST", "/compute",
                      b"value=7", {"X-Misaka-Key": "adm-secret"})
    assert (s, b) == (200, b'{"value": 9}\n')


def test_parity_batch_mixed_widths(tiers):
    engine_port, native_port = tiers
    # mixed magnitudes exercise the textcodec width-padded JSON shape
    s, h, b = _parity(engine_port, native_port, "POST", "/compute_batch",
                      b"values=5,-17,300&spread=1",
                      {"X-Misaka-Key": "adm-secret"})
    assert s == 200
    assert h["content-type"] == "application/json"
    assert json.loads(b)["values"] == [7, -15, 302]


# --- byte parity: typed rejections ------------------------------------------


def test_parity_401_missing_key(tiers):
    engine_port, native_port = tiers
    body = struct.pack("<2i", 1, 2)
    s, h, b = _parity(engine_port, native_port, "POST", "/compute_raw",
                      body)
    assert s == 401
    assert b"API key required" in b
    assert h["www-authenticate"].startswith("Bearer")


def test_parity_401_unknown_key(tiers):
    engine_port, native_port = tiers
    body = struct.pack("<2i", 1, 2)
    s, _, b = _parity(engine_port, native_port, "POST", "/compute_raw",
                      body, {"X-Misaka-Key": "who-is-this"})
    assert (s, b) == (401, b"unknown API key")


def test_parity_403_disabled_key(tiers):
    # disabled keys are IN the pushed digest set, so the native tier
    # ships them to the engine chain — the client must see the canonical
    # 403, never a wrong local 401
    engine_port, native_port = tiers
    body = struct.pack("<2i", 1, 2)
    s, _, b = _parity(engine_port, native_port, "POST", "/compute_raw",
                      body, {"X-Misaka-Key": "eve-secret"})
    assert (s, b) == (403, b"API key disabled")


def test_parity_413_burst_cap(tiers):
    # 16 values > vps<4's burst capacity (max(1, 4*2) = 8): a single
    # unsplittable request answers a terminal 413 with NO Retry-After —
    # the native tier renders it locally from the pushed burst spec
    engine_port, native_port = tiers
    body = struct.pack("<16i", *range(16))
    es, eh, eb = _req(engine_port, "POST", "/compute_raw", body,
                      {"X-Misaka-Key": "tiny-a-secret"})
    ns, nh, nb = _req(native_port, "POST", "/compute_raw", body,
                      {"X-Misaka-Key": "tiny-b-secret"})
    assert (ns, nb.replace(b"tiny-b", b"tiny-a")) == (es, eb)
    assert es == 413 and b"split the request" in eb
    assert "retry-after" not in eh and "retry-after" not in nh


def test_parity_429_rate_with_retry_after(tiers):
    engine_port, native_port = tiers
    body = struct.pack("<4i", 1, 2, 3, 4)  # drains vps<4's bucket whole
    for port, key in ((engine_port, "tiny-a-secret"),
                      (native_port, "tiny-b-secret")):
        results = []
        for _ in range(3):
            results.append(_req(port, "POST", "/compute_raw", body,
                                {"X-Misaka-Key": key}))
        statuses = [r[0] for r in results]
        assert 429 in statuses, (port, statuses)
        s, h, b = results[statuses.index(429)]
        assert b"value rate quota exhausted (4 values/s)" in b
        assert h["retry-after"].isdigit() and int(h["retry-after"]) >= 1


def test_parity_400_bad_binary_wire(tiers):
    engine_port, native_port = tiers
    hdr = {"X-Misaka-Key": "adm-secret", "Content-Type": wire.CONTENT_TYPE}
    # header promises more values than the body carries
    good = wire.pack(struct.pack("<3i", 1, 2, 3))
    for body in (b"short", good[:-4], b"\xff" * 12):
        s, _, b = _parity(engine_port, native_port, "POST",
                          "/compute_raw", body, hdr)
        assert s == 400 and b.startswith(b"bad binary body: "), (body, b)


def test_parity_400_misaligned_raw(tiers):
    engine_port, native_port = tiers
    s, _, b = _parity(engine_port, native_port, "POST", "/compute_raw",
                      b"\x01\x02\x03", {"X-Misaka-Key": "adm-secret"})
    assert (s, b) == (400, b"body must be raw int32 values")


def test_parity_404_unknown_program_route(tiers):
    # Program-addressed requests ship via the plane on BOTH the native
    # and the CPython worker tier, so those two are byte-identical; the
    # engine's own HTTP route renders a pre-existing slightly longer
    # hint ("(set MISAKA_PROGRAMS_DIR)") — compare the typed shape, not
    # the bytes, against the direct-engine oracle.
    engine_port, native_port = tiers
    body = struct.pack("<2i", 1, 2)
    hdr = {"X-Misaka-Key": "adm-secret"}
    es, _, eb = _req(engine_port, "POST",
                     "/programs/no-such-prog/compute_raw", body, hdr)
    ns, _, nb = _req(native_port, "POST",
                     "/programs/no-such-prog/compute_raw", body, hdr)
    assert ns == es == 404
    for b in (eb, nb):
        assert b"cannot route to program 'no-such-prog'" in b


# --- keep-alive + drain discipline ------------------------------------------


def test_keepalive_after_error(tiers):
    _, native_port = tiers
    conn = http.client.HTTPConnection("127.0.0.1", native_port, timeout=15)
    # 401 (keyless) with a drainable body must NOT kill the connection
    conn.request("POST", "/compute_raw", body=struct.pack("<2i", 1, 2))
    r = conn.getresponse()
    r.read()
    assert r.status == 401
    # same socket: an authed request must still be answered
    conn.request("POST", "/compute_raw", body=struct.pack("<2i", 5, 6),
                 headers={"X-Misaka-Key": "adm-secret"})
    r = conn.getresponse()
    out = r.read()
    conn.close()
    assert r.status == 200
    assert struct.unpack("<2i", out) == (7, 8)


def test_oversized_body_413_closes(tiers):
    engine_port, native_port = tiers
    body = b"\x00" * (100 * 1024)  # > MISAKA_MAX_BODY=65536 from the fixture
    s, h, b = _parity(engine_port, native_port, "POST", "/compute_raw",
                      body, {"X-Misaka-Key": "adm-secret"})
    assert s == 413
    assert b == (b"body of 102400 bytes exceeds the 65536-byte cap "
                 b"(MISAKA_MAX_BODY)")
    # the MSK006 contract: an oversized body is NEVER drained — the
    # server must close the TCP stream (like the engine: no
    # Connection: close header, just EOF) so the client can't wedge
    # pipelining on it
    conn = http.client.HTTPConnection("127.0.0.1", native_port, timeout=15)
    conn.request("POST", "/compute_raw", body=body,
                 headers={"X-Misaka-Key": "adm-secret"})
    r = conn.getresponse()
    r.read()
    assert r.status == 413
    conn.sock.settimeout(10)
    assert conn.sock.recv(1) == b""  # EOF: the server closed, no drain
    conn.close()


# --- proxy lane --------------------------------------------------------------


def test_proxy_non_hot_routes(tiers):
    engine_port, native_port = tiers
    hdr = {"X-Misaka-Key": "adm-secret"}
    for path in ("/status", "/metrics", "/debug/requests"):
        es, _, eb = _req(engine_port, "GET", path, headers=hdr)
        ns, _, nb = _req(native_port, "GET", path, headers=hdr)
        assert ns == es == 200, (path, ns, es)
        if path == "/status":
            assert json.loads(nb).keys() == json.loads(eb).keys()
    # and an UNAUTHED admin GET proxies to the same typed 401
    es, _, eb = _req(engine_port, "GET", "/status")
    ns, _, nb = _req(native_port, "GET", "/status")
    assert (ns, nb) == (es, eb)
    assert es == 401


def test_native_healthz_and_state(tiers):
    _, native_port = tiers
    s, h, b = _req(native_port, "GET", "/healthz")
    assert s == 200
    assert h["server"] == "misaka-native-edge/1"
    assert json.loads(b)  # the pushed snapshot is well-formed JSON


# --- fallback ladder ---------------------------------------------------------


def test_build_failure_chaos_point_raises(tmp_path):
    """The fallback ladder's load-bearing rung: an injected build
    failure must raise out of the supervisor constructor (app.py catches
    it and keeps the CPython workers on the public port)."""
    faults.configure("edge_native_build")
    with pytest.raises(RuntimeError, match="injected fault"):
        frontends.NativeFrontendSupervisor(
            port=0, proxy_port=1, plane_path=str(tmp_path / "p.sock"),
        )


def test_supervisor_restart_cycle(tmp_path):
    """close() must fully release the C++ engine (one per process by
    design) so a later boot in the SAME interpreter can start a fresh
    tier — the singleton is restartable, not one-shot."""
    plane_path = str(tmp_path / "plane.sock")
    ports = set()
    for _ in range(2):
        sup = frontends.NativeFrontendSupervisor(
            port=0, proxy_port=1, plane_path=plane_path,
            threads=1, plane_conns=1,
        )
        try:
            ports.add(sup.port)
            s, _, _ = _req(sup.port, "GET", "/healthz")
            assert s == 200
        finally:
            sup.close()
    assert len(ports) == 2  # both cycles actually served


def test_edge_state_snapshot_shape(tmp_path):
    kf = edge.KeyFile(_write_keys(tmp_path / "k.json", KEYS))
    chain = edge.EdgeChain(keyfile=kf, internal_token="fleet-tok")
    st = edge.native_edge_state(chain)
    assert st["auth_armed"]
    # every key (INCLUDING the disabled one) + the internal token
    assert len(st["digests"]) == len(KEYS) + 1
    bursts = [d for d in st["digests"].values() if "burst_cap" in d]
    assert len(bursts) == 2  # tiny-a + tiny-b; never the disabled key
    assert all(b["burst_cap"] == 8.0 for b in bursts)
    assert any(d.get("tenant") == "_fleet" for d in st["digests"].values())
    assert "API key required" in st["reject_missing"]
