"""The replicated engine fleet (runtime/fleet.py + the frontend router).

Fast lane: routing invariants (consistent-hash stability under
join/leave, least-queue-depth tie-breaking, typed fleet-down 503, drain
reroute, scoped blackhole hedging), the stdlib manifest verifier, the
metrics relabeler, and the /fleet/drain HTTP surface — all in-process
against real ComputePlanes over stub engines (no jax boot per replica).

Slow lane: the acceptance scenario against a REAL subprocess fleet —
kill -9 of one replica under 64 pooled concurrent clients with zero
client-visible errors, then a full POST /fleet/roll across every
replica under the same load losing zero requests, with bit-identical
per-replica checkpoint restore (the PR 6 np.load comparison pattern).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from misaka_tpu.runtime import frontends
from misaka_tpu.runtime.fleet import (
    HashRing,
    relabel_metrics_text,
    verify_manifest,
)
from misaka_tpu.utils import faults, metrics


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.configure(None)


# --- consistent hashing -----------------------------------------------------


def test_hash_ring_covers_and_is_deterministic():
    ring = HashRing(range(4))
    order = ring.lookup("tenant-a")
    assert sorted(order) == [0, 1, 2, 3]  # every replica, exactly once
    assert order == ring.lookup("tenant-a")  # deterministic
    assert HashRing(range(4)).lookup("tenant-a") == order  # across builds


def test_hash_ring_spreads_keys():
    ring = HashRing(range(4))
    owners = [ring.owner(f"prog-{i}") for i in range(2000)]
    counts = {r: owners.count(r) for r in range(4)}
    # perfect split is 500 each; vnode hashing keeps every replica well
    # inside [250, 750]
    assert all(250 < c < 750 for c in counts.values()), counts


def test_hash_ring_leave_moves_only_departed_keys():
    """The stickiness contract: removing one replica from an N-ring
    remaps ONLY the keys it owned (~1/N); every other key keeps its
    owner — per-program engine state survives fleet churn."""
    keys = [f"prog-{i}" for i in range(2000)]
    before = {k: HashRing(range(4)).owner(k) for k in keys}
    after = {k: HashRing([0, 1, 3]).owner(k) for k in keys}  # 2 leaves
    moved_wrongly = [
        k for k in keys if before[k] != 2 and after[k] != before[k]
    ]
    assert moved_wrongly == []
    departed = [k for k in keys if before[k] == 2]
    assert departed  # replica 2 owned a real share
    assert all(after[k] != 2 for k in keys)


def test_hash_ring_join_moves_about_one_fifth():
    keys = [f"prog-{i}" for i in range(2000)]
    before = {k: HashRing(range(4)).owner(k) for k in keys}
    after = {k: HashRing(range(5)).owner(k) for k in keys}
    moved = sum(1 for k in keys if before[k] != after[k])
    # a 5th replica should claim ~1/5 of the keyspace, not reshuffle it
    assert 0.05 < moved / len(keys) < 0.40, moved


# --- the in-process fleet harness -------------------------------------------


class _StubMaster:
    """A jax-free engine twin for the ComputePlane: values + 2, with
    frame/value counters and an optional per-call delay.  `calls` counts
    FRAMES (the PlaneClient coalesces many requests into one frame);
    `values` counts every int32 served."""

    is_running = True

    def __init__(self, delay: float = 0.0):
        self.calls = 0
        self.values = 0
        self.delay = delay
        self._lock = threading.Lock()

    def compute_coalesced(self, values, timeout=30.0, return_array=True,
                          traces=()):
        with self._lock:
            self.calls += 1
            self.values += int(np.asarray(values).size)
        if self.delay:
            time.sleep(self.delay)
        return np.asarray(values) + 2


class _StubRegistry:
    """Just enough registry for program-addressed routing tests: every
    program resolves to the replica's one stub master."""

    def __init__(self, master):
        self._master = master

    def lease(self, program, values=0):
        import contextlib

        @contextlib.contextmanager
        def _cm():
            yield self._master

        return _cm()


def _stub_fleet(tmp_path, n=2, delay=0.0, **router_kw):
    masters = [_StubMaster(delay=delay) for _ in range(n)]
    planes = [
        frontends.start_compute_plane(
            masters[i], str(tmp_path / f"plane-{i}.sock"),
            registry=_StubRegistry(masters[i]),
            replica_label=str(i),
        )
        for i in range(n)
    ]
    router_kw.setdefault("down_grace", 0.3)
    router = frontends.FleetPlaneRouter(
        [p.path for p in planes], **router_kw
    )
    return masters, planes, router


BODY = np.arange(8, dtype=np.int32).tobytes()
WANT = np.arange(8, dtype=np.int32) + 2


def _check(out):
    assert np.array_equal(np.frombuffer(out, dtype="<i4"), WANT)


def test_router_least_depth_tie_breaks_to_lowest_index(tmp_path):
    masters, planes, router = _stub_fleet(tmp_path, n=3)
    try:
        # idle fleet: every depth is 0, the tie-break must be
        # deterministic (lowest index), so sequential traffic is stable
        cands = router._candidates(None, set())
        assert [r.idx for r in cands] == [0, 1, 2]
        _check(router.compute_raw(BODY, timeout=5))
        assert masters[0].calls == 1 and masters[1].calls == 0
        # load replica 0's queue: the next choice must prefer the others
        router._replicas[0].client._inflight += 1
        try:
            cands = router._candidates(None, set())
            assert [r.idx for r in cands][0] == 1
        finally:
            router._replicas[0].client._inflight -= 1
    finally:
        router.close()
        for p in planes:
            p.close()


def test_router_program_traffic_is_sticky(tmp_path):
    masters, planes, router = _stub_fleet(tmp_path, n=3)
    try:
        for _ in range(12):
            _check(router.compute_raw(BODY, timeout=5, program="tenant-a"))
        served = [m.calls for m in masters]
        assert sorted(served) == [0, 0, 12], served  # one replica only
        # a different program may land elsewhere, but is itself sticky
        for _ in range(6):
            _check(router.compute_raw(BODY, timeout=5, program="tenant-b"))
        assert sum(m.calls for m in masters) == 18
        assert sum(1 for m in masters if m.calls) <= 2
    finally:
        router.close()
        for p in planes:
            p.close()


def test_router_failover_under_concurrent_load_zero_errors(tmp_path):
    """Kill one replica's plane mid-load: every in-flight and subsequent
    request is hedged onto the sibling — zero client-visible errors, and
    the dead replica is marked down."""
    masters, planes, router = _stub_fleet(tmp_path, n=2, delay=0.002)
    errors: list[Exception] = []

    def worker(n):
        try:
            for _ in range(n):
                _check(router.compute_raw(BODY, timeout=10))
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errors.append(e)

    try:
        threads = [
            threading.Thread(target=worker, args=(25,)) for _ in range(12)
        ]
        for t in threads:
            t.start()
        # kill mid-load deterministically: the r17 pipelined plane moves
        # this whole workload faster than a fixed sleep — wait until some
        # (but nowhere near all) values are served, then pull the plug
        deadline = time.monotonic() + 5
        while (masters[0].values + masters[1].values) < 12 * 25 * 8 // 10 \
                and time.monotonic() < deadline:
            time.sleep(0.002)
        planes[1].close()  # the in-process kill -9
        for t in threads:
            t.join(timeout=30)
        assert errors == []
        # every request's values were served at least once (a frame the
        # dying replica computed but never answered is re-served by the
        # hedge — duplicates allowed, losses never)
        assert masters[0].values + masters[1].values >= 12 * 25 * 8
        assert masters[0].values > 0  # the survivor took the failover
        assert router.states()[1] == "down"
    finally:
        router.close()
        for p in planes:
            p.close()


def test_router_partitioned_peer_hedges_to_siblings(tmp_path):
    """The multi-host partition drill (chaos point plane_partition:<addr>,
    scoped to ONE peer's plane address): dials to the partitioned replica
    fail and queued frames never hit the wire, so every request hedges
    onto the sibling with zero client-visible errors, the hedge counter
    moves, and the router's probe keeps the partitioned peer out of the
    candidate set."""
    masters, planes, router = _stub_fleet(tmp_path, n=2, probe_s=0.05)
    errors: list[Exception] = []
    outs: list[bytes] = []
    lock = threading.Lock()

    def worker():
        try:
            out = router.compute_raw(BODY, timeout=10)
            with lock:
                outs.append(out)
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errors.append(e)

    try:
        _check(router.compute_raw(BODY, timeout=5))  # healthy baseline
        hedged0 = frontends.M_PLANE_HEDGED.value
        faults.configure("plane_partition:plane-1.sock")
        # tilt the depth tie-break toward the partitioned replica so the
        # router actually routes at it (idle traffic would pile onto
        # replica 0 and never exercise the failover)
        router._replicas[0].client._inflight += 1
        try:
            threads = [threading.Thread(target=worker) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
        finally:
            router._replicas[0].client._inflight -= 1
        assert errors == []
        assert len(outs) == 8
        for out in outs:
            _check(out)
        # the partition is grey, not clean: only the sibling served
        assert masters[1].values == 0
        assert masters[0].values >= 8 * 8
        # failovers are VISIBLE: re-routed frames ride the hedge counter
        assert frontends.M_PLANE_HEDGED.value > hedged0
        # probes cannot reach a partitioned peer either: it must sit out
        # of the candidate set, not flap up/down
        deadline = time.monotonic() + 5
        while router.states()[1] != "down" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.states()[1] == "down"
        # heal the partition: the prober readmits with no coordination
        faults.configure(None)
        deadline = time.monotonic() + 5
        while router.states()[1] != "up" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.states()[1] == "up"
    finally:
        router.close()
        for p in planes:
            p.close()


def test_router_readmits_restarted_replica(tmp_path):
    masters, planes, router = _stub_fleet(tmp_path, n=2, probe_s=0.05)
    try:
        planes[1].close()
        # The router starts optimistic and only learns from traffic: tilt
        # the depth tie-break toward the dead replica so a frame actually
        # hits it (idle traffic would pile onto replica 0 and never
        # notice), then watch the hedge mark it down.
        router._replicas[0].client._inflight += 1
        try:
            _check(router.compute_raw(BODY, timeout=5))
        finally:
            router._replicas[0].client._inflight -= 1
        assert router.states()[1] == "down"
        # a replacement binds the SAME path: the prober readmits it with
        # no coordination beyond the socket itself
        m2 = _StubMaster()
        p2 = frontends.start_compute_plane(m2, planes[1].path)
        try:
            deadline = time.monotonic() + 5
            while router.states()[1] != "up" and time.monotonic() < deadline:
                time.sleep(0.05)
            assert router.states()[1] == "up"
        finally:
            p2.close()
    finally:
        router.close()
        planes[0].close()


def test_router_drain_reroutes_with_zero_errors(tmp_path):
    """The roll's drain step: a draining replica answers PLANE_DRAINING,
    the router absorbs it (no client-visible error) and shifts traffic
    to siblings; inflight reaches zero."""
    masters, planes, router = _stub_fleet(tmp_path, n=2, delay=0.002)
    errors: list[Exception] = []

    def worker(n):
        try:
            for _ in range(n):
                _check(router.compute_raw(BODY, timeout=10))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    try:
        threads = [
            threading.Thread(target=worker, args=(20,)) for _ in range(8)
        ]
        for t in threads:
            t.start()
        time.sleep(0.05)
        planes[0].set_draining(True)
        deadline = time.monotonic() + 5
        while planes[0].inflight() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert planes[0].inflight() == 0  # drained to quiescence
        calls_at_drain = masters[0].calls
        for t in threads:
            t.join(timeout=30)
        assert errors == []
        assert masters[0].calls == calls_at_drain  # nothing after drain
        assert masters[1].calls > 0
        assert router.states()[0] == "draining"
        # undrain: the prober readmits without reconnection churn
        planes[0].set_draining(False)
        deadline = time.monotonic() + 5
        while router.states()[0] != "up" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.states()[0] == "up"
    finally:
        router.close()
        for p in planes:
            p.close()


def test_router_single_replica_readmits_inside_grace(tmp_path):
    """A 1-replica fleet mid-roll: every candidate has been tried, so
    the down-grace wait must FORGET attempt history — the one replica's
    own recovery (prober flips draining back to up) has to satisfy the
    request, not a guaranteed 503."""
    masters, planes, router = _stub_fleet(
        tmp_path, n=1, probe_s=0.05, down_grace=5.0
    )
    try:
        planes[0].set_draining(True)

        def undrain():
            time.sleep(0.4)
            planes[0].set_draining(False)

        threading.Thread(target=undrain, daemon=True).start()
        _check(router.compute_raw(BODY, timeout=10))  # no 503
        assert masters[0].calls >= 1
    finally:
        router.close()
        for p in planes:
            p.close()


def test_router_draining_fleet_maps_to_503_never_599(tmp_path):
    """The plane-private PLANE_DRAINING status must never reach a
    caller: a fleet that stays draining past the request deadline
    answers a retryable 503."""
    masters, planes, router = _stub_fleet(
        tmp_path, n=1, probe_s=0.05, down_grace=30.0
    )
    try:
        planes[0].set_draining(True)
        with pytest.raises(frontends.PlaneError) as exc:
            router.compute_raw(BODY, timeout=1.0)
        assert exc.value.status == 503
        assert exc.value.status != frontends.PLANE_DRAINING
    finally:
        router.close()
        for p in planes:
            p.close()


def test_router_fleet_down_is_typed_503(tmp_path):
    masters, planes, router = _stub_fleet(tmp_path, n=2, down_grace=0.2)
    try:
        for p in planes:
            p.close()
        t0 = time.monotonic()
        with pytest.raises(frontends.PlaneError) as exc:
            router.compute_raw(BODY, timeout=5)
        assert exc.value.status == 503
        assert b"fleet down" in exc.value.body
        # bounded: the grace window, not the full request timeout
        assert time.monotonic() - t0 < 3.0
    finally:
        router.close()


def test_router_hedges_scoped_blackhole(tmp_path):
    """replica_blackhole:<idx> holds frames on ONE replica; the router's
    split deadline hedges onto the healthy sibling well inside the
    request budget."""
    masters, planes, router = _stub_fleet(tmp_path, n=2)
    try:
        faults.configure("replica_blackhole:0=30")
        t0 = time.monotonic()
        _check(router.compute_raw(BODY, timeout=4))
        took = time.monotonic() - t0
        assert took < 3.5
        assert masters[1].calls >= 1
        assert router.states()[0] == "down"
    finally:
        faults.configure(None)
        router.close()
        for p in planes:
            p.close()


def test_router_probe_cannot_readmit_frame_failed_replica(tmp_path):
    """Grey failure: a wedged-but-alive replica still answers probe
    frames instantly (the probe path touches nothing but the plane
    socket), so a probe success must NOT readmit a replica a REAL frame
    just failed on — it sits out a doubling hold instead of bouncing
    up<->down every probe_s and re-eating every sticky request's first
    half-deadline."""
    masters, planes, router = _stub_fleet(
        tmp_path, n=2, probe_s=0.05, suspect_hold=1.2
    )
    try:
        faults.configure("replica_blackhole:0=30")
        _check(router.compute_raw(BODY, timeout=2))  # hedges to 1
        assert router.states()[0] == "down"
        faults.configure(None)  # the wedge lifts; probes now succeed
        time.sleep(0.4)  # ~8 probe rounds, all inside the hold window
        assert router.states()[0] == "down"  # probe alone may not revive
        deadline = time.monotonic() + 5
        while router.states()[0] != "up" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.states()[0] == "up"  # hold expired -> readmitted
    finally:
        faults.configure(None)
        router.close()
        for p in planes:
            p.close()


def test_suspect_escalates_per_event_not_per_request():
    """One failed frame fans out to every request it coalesced: 64
    concurrent suspect() calls are ONE failure event and must leave the
    hold at its base, not jump the doubling curve to the 30s cap (which
    would turn a single stall into a half-minute lockout).  Only a
    failure AFTER the hold expired doubles it."""
    r = frontends._RouterReplica(0, "/nowhere", None)
    t0 = time.monotonic()
    for _ in range(64):
        r.suspect(0.5)
    assert r.state == "down"
    assert r.suspect_streak == 1
    assert r.suspect_until - t0 < 0.5 + 0.25  # base hold, not the cap
    r.suspect_until = time.monotonic() - 0.01  # hold expires
    r.suspect(0.5)
    assert r.suspect_streak == 2  # doubling resumes per real event
    r.absolve()
    assert r.suspect_streak == 0 and r.suspect_until == 0.0


def test_plane_client_replays_one_stale_socket(tmp_path):
    """A replica restart between frames costs ZERO hedges: the
    dispatcher replays the frame once on a fresh dial instead of failing
    the batch (which would mark the whole replica down)."""
    m1 = _StubMaster()
    p1 = frontends.start_compute_plane(m1, str(tmp_path / "p.sock"))
    client = frontends.PlaneClient(p1.path, conns=1)
    try:
        _check(client.compute_raw(BODY, timeout=5))
        p1.close()  # restart: established sockets die with it
        m2 = _StubMaster()
        p2 = frontends.start_compute_plane(m2, p1.path)
        try:
            _check(client.compute_raw(BODY, timeout=5))  # no error
            assert m2.calls == 1
        finally:
            p2.close()
    finally:
        client.close()


# --- the stdlib manifest verifier -------------------------------------------


def _write_ckpt(tmp_path, name="c.npz"):
    import hashlib

    path = str(tmp_path / name)
    np.savez(path.replace(".npz", ""), a=np.arange(32, dtype=np.int32))
    with open(path, "rb") as f:
        blob = f.read()
    with open(path + ".manifest", "w") as f:
        json.dump(
            {"size": len(blob), "sha256": hashlib.sha256(blob).hexdigest()},
            f,
        )
    return path


def test_verify_manifest_accepts_exact_match(tmp_path):
    verify_manifest(_write_ckpt(tmp_path))


def test_verify_manifest_rejects_truncation_and_corruption(tmp_path):
    path = _write_ckpt(tmp_path)
    with open(path, "rb") as f:
        blob = f.read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])
    with pytest.raises(RuntimeError, match="torn write"):
        verify_manifest(path)
    flipped = bytearray(blob)
    flipped[len(blob) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(flipped))
    with pytest.raises(RuntimeError, match="sha256 mismatch"):
        verify_manifest(path)


def test_verify_manifest_rejects_missing_manifest(tmp_path):
    path = _write_ckpt(tmp_path)
    os.unlink(path + ".manifest")
    # strict on purpose: a roll checkpoint was JUST written by the
    # manifest-emitting save path — no sidecar means the save tore
    with pytest.raises(RuntimeError, match="manifest"):
        verify_manifest(path)


# --- metrics relabeling -----------------------------------------------------


def test_relabel_metrics_text_injects_replica_label():
    text = (
        "# HELP misaka_x_total things\n"
        "# TYPE misaka_x_total counter\n"
        "misaka_x_total 41\n"
        'misaka_y_total{route="/compute",method="POST"} 7\n'
        'misaka_h_bucket{le="0.1"} 3\n'
    )
    samples, headers = relabel_metrics_text(text, 2)
    assert headers == [
        "# HELP misaka_x_total things",
        "# TYPE misaka_x_total counter",
    ]
    assert 'misaka_x_total{replica="2"} 41' in samples
    assert (
        'misaka_y_total{replica="2",route="/compute",method="POST"} 7'
        in samples
    )
    assert 'misaka_h_bucket{replica="2",le="0.1"} 3' in samples
    # round-trips through the strict exposition parser
    parsed = metrics.parse_text("\n".join(samples) + "\n")
    assert parsed['misaka_x_total{replica="2"}'] == 41.0


# --- the /fleet/drain HTTP surface ------------------------------------------


def test_fleet_drain_route_drives_plane(tmp_path):
    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    m = MasterNode(
        networks.add2(in_cap=16, out_cap=16, stack_cap=16),
        chunk_steps=32, engine="scan",
    )
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    plane = frontends.start_compute_plane(m, str(tmp_path / "plane.sock"))
    httpd.misaka_plane = plane
    import urllib.request

    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    def drain(state):
        req = urllib.request.Request(
            base + "/fleet/drain", data=f"state={state}".encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    try:
        payload = drain("on")
        assert payload["draining"] is True
        assert payload["inflight"] == 0
        assert payload["http_inflight"] == 0  # this request is excluded
        assert plane.draining
        payload = drain("off")
        assert payload["draining"] is False
        assert not plane.draining
    finally:
        plane.close()
        m.close()
        httpd.shutdown()


def test_fleet_drain_route_404_without_plane():
    from misaka_tpu import networks
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    m = MasterNode(
        networks.add2(in_cap=16, out_cap=16, stack_cap=16),
        chunk_steps=32, engine="scan",
    )
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    import urllib.error
    import urllib.request

    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{httpd.server_address[1]}/fleet/drain",
            data=b"state=on", method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 404
    finally:
        m.close()
        httpd.shutdown()


class _FakeProc:
    """A live-looking replica process for control-plane unit tests."""

    pid = 4242

    def poll(self):
        return None

    def terminate(self):
        pass

    def kill(self):
        pass

    def wait(self, timeout=None):
        return 0


def test_fanout_reports_skipped_replicas(tmp_path):
    """A lifecycle fan-out (/pause, /run, ...) that could not reach
    every CONFIGURED replica must not answer a uniform success: the
    skipped replica is reported per-replica and the status is non-2xx —
    a /pause that silently missed a mid-roll replica would leave the
    fleet divergent (one replica free-running against paused siblings)
    behind a 200."""
    import http.client
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from misaka_tpu.runtime.fleet import FleetManager, make_fleet_http_server

    class _OkHandler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length") or 0))
            body = b"Success"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    replica_srv = ThreadingHTTPServer(("127.0.0.1", 0), _OkHandler)
    threading.Thread(target=replica_srv.serve_forever, daemon=True).start()
    fm = FleetManager(2, str(tmp_path / "fleet"))
    ctrl = None
    try:
        # slot 0 looks up (fake live proc + passing probe, pointed at the
        # stub replica); slot 1 stays proc=None -> "down"
        fm._slots[0]["proc"] = _FakeProc()
        fm._slots[0]["probe_ok"] = True
        fm._slots[0]["port"] = replica_srv.server_address[1]
        ctrl = make_fleet_http_server(fm, port=0)
        threading.Thread(target=ctrl.serve_forever, daemon=True).start()
        conn = http.client.HTTPConnection(
            "127.0.0.1", ctrl.server_address[1], timeout=10
        )
        conn.request("POST", "/pause", b"", {})
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        assert resp.status == 502  # never a uniform 200 "Success"
        assert payload["ok"] is False
        rows = {r["replica"]: r for r in payload["replicas"]}
        assert rows[0]["status"] == 200  # the up replica took the change
        assert rows[1]["skipped"] is True  # the down one is REPORTED
        assert rows[1]["status"] == 503
        # whole fleet up again: the uniform one-replica ergonomics hold
        fm._slots[1]["proc"] = _FakeProc()
        fm._slots[1]["probe_ok"] = True
        fm._slots[1]["port"] = replica_srv.server_address[1]
        conn.request("POST", "/pause", b"", {})
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 200 and body == b"Success"
        conn.close()
    finally:
        if ctrl is not None:
            ctrl.shutdown()
        replica_srv.shutdown()
        fm.close()


def test_fleet_healthz_running_reflects_network_state(tmp_path):
    """The single-engine /healthz contract: `running` is the NETWORK
    run state, not process liveness — a fully paused fleet must not
    read as serving (the probers feed each slot's probed run state)."""
    import http.client

    from misaka_tpu.runtime.fleet import FleetManager, make_fleet_http_server

    fm = FleetManager(2, str(tmp_path / "fleet"))
    ctrl = None
    try:
        for s in fm._slots:
            s["proc"] = _FakeProc()
            s["probe_ok"] = True
            s["running"] = True
        ctrl = make_fleet_http_server(fm, port=0)
        threading.Thread(target=ctrl.serve_forever, daemon=True).start()
        conn = http.client.HTTPConnection(
            "127.0.0.1", ctrl.server_address[1], timeout=10
        )

        def healthz():
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            return json.loads(resp.read())

        payload = healthz()
        assert payload["ok"] is True and payload["running"] is True
        fm._slots[1]["running"] = False  # one replica paused
        payload = healthz()
        assert payload["ok"] is True  # processes are fine...
        assert payload["running"] is False  # ...but the fleet is not serving
        rows = {r["replica"]: r for r in payload["fleet"]["replicas"]}
        assert rows[0]["running"] is True and rows[1]["running"] is False
        conn.close()
    finally:
        if ctrl is not None:
            ctrl.shutdown()
        fm.close()


def test_undrain_async_retries_until_replica_recovers(tmp_path):
    """A failed roll's undrain must not give up when the replica is
    wedged at that moment (the roll failure may BE the wedge): the
    background retry keeps posting /fleet/drain state=off until it
    lands, then stops — a recovered replica never sits draining
    forever behind a passing /healthz."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from misaka_tpu.runtime.fleet import FleetManager

    calls: list[str] = []

    class _FlakyDrain(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length") or 0))
            calls.append(self.path)
            code = 500 if len(calls) < 3 else 200  # wedged twice, then ok
            body = b"ok" if code == 200 else b"wedged"
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FlakyDrain)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    fm = FleetManager(1, str(tmp_path / "fleet"))
    try:
        slot = fm._slots[0]
        slot["proc"] = _FakeProc()
        slot["port"] = srv.server_address[1]
        fm._undrain_async(slot)
        deadline = time.monotonic() + 10
        while len(calls) < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert calls == ["/fleet/drain"] * 3  # retried past the wedge
        time.sleep(1.2)
        assert len(calls) == 3  # and stopped once the undrain landed
    finally:
        srv.shutdown()
        fm.close()


def test_mark_healthy_keeps_restore_armed_while_rolling(tmp_path):
    """The roll arms slot["restore"] while the OLD replica is still
    alive and answering /healthz: a probe passing in that window must
    NOT disarm the checkpoint — the replacement would silently boot
    without restoring, breaking the roll's bit-identity guarantee.
    After the roll hands the slot back, the next healthy probe disarms
    as before (crash respawns fresh from there on)."""
    from misaka_tpu.runtime.fleet import FleetManager

    fm = FleetManager(1, str(tmp_path / "fleet"))
    try:
        slot = fm._slots[0]
        slot["rolling"] = True
        slot["restore"] = "/some/ckpt.npz"
        slot["run_on_boot"] = True
        fm._mark_healthy(slot)  # the roll's own readiness wait
        assert slot["probe_ok"] is True
        assert slot["restore"] == "/some/ckpt.npz"  # still armed
        assert slot["run_on_boot"] is True
        slot["rolling"] = False
        fm._mark_healthy(slot)  # first post-roll probe
        assert slot["restore"] is None and slot["run_on_boot"] is None
    finally:
        fm.close()


def test_fleet_fault_points_parse():
    spec = faults.parse_spec("replica_kill=2,replica_blackhole:1=5@0.5")
    assert spec["replica_kill"] == (2.0, 1.0)
    assert spec["replica_blackhole:1"] == (5.0, 0.5)
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec("replica_kill:0=2")  # not a scoped point


# --- the real thing ---------------------------------------------------------


ADD2_ENV = {
    "NODE_INFO": json.dumps({
        "misaka1": {"type": "program"},
        "misaka2": {"type": "program"},
        "misaka3": {"type": "stack"},
    }),
    "MISAKA_PROGRAMS": json.dumps({
        "misaka1": "IN ACC\nADD 1\nMOV ACC, misaka2:R0\nMOV R0, ACC\n"
                   "OUT ACC\n",
        "misaka2": "MOV R0, ACC\nADD 1\nPUSH ACC, misaka3\n"
                   "POP misaka3, ACC\nMOV ACC, misaka1:R0\n",
    }),
}


def _boot_fleet(tmp_path, port, replicas=4, workers=3, extra=None):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "MISAKA_FLEET": str(replicas),
        "MISAKA_HTTP_WORKERS": str(workers),
        "MISAKA_AUTORUN": "1",
        "MISAKA_PORT": str(port),
        "MISAKA_FLEET_DIR": str(tmp_path),
        "MISAKA_TTL_S": "600",
        **ADD2_ENV,
        **(extra or {}),
    }
    return subprocess.Popen(
        [sys.executable, "-m", "misaka_tpu.runtime.app"], env=env
    )


def _wait_fleet_healthy(base, deadline_s=180):
    import urllib.request

    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
                payload = json.loads(r.read())
            if payload.get("ok") and not payload.get("degraded"):
                return payload
        except OSError:
            pass
        time.sleep(0.5)
    raise AssertionError("fleet never became healthy")


@pytest.mark.slow
def test_fleet_kill9_and_roll_under_load_zero_errors(tmp_path):
    """The acceptance scenario, against a REAL subprocess fleet of 4
    engine replicas behind supervised frontends:

      1. kill -9 one replica under 64 pooled concurrent clients — zero
         client-visible errors, the supervisor respawns it;
      2. a full POST /fleet/roll across all 4 replicas under the same
         load — zero client-visible errors, drain/checkpoint/replace
         per replica visible in the report;
      3. quiesce, checkpoint every replica, roll again, checkpoint
         again: per-replica state is BIT-IDENTICAL across the roll
         (np.load array comparison — the restore really installed the
         drained state).
    """
    from misaka_tpu.client import MisakaClient

    port = frontends.pick_free_port()
    base = f"http://127.0.0.1:{port}"
    proc = _boot_fleet(tmp_path, port, replicas=4, workers=3)
    errors: list[Exception] = []
    stop = threading.Event()
    counts = [0] * 64

    def client_loop(i):
        c = MisakaClient(base, timeout=60)
        vals = (np.arange(16, dtype=np.int32) + i) % 1000
        try:
            while not stop.is_set():
                out = c.compute_raw(vals)
                if not np.array_equal(out, vals + 2):
                    raise AssertionError(f"client {i}: wrong outputs")
                counts[i] += 1
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errors.append(e)
        finally:
            c.close()

    try:
        _wait_fleet_healthy(base)
        client = MisakaClient(base, timeout=60)
        threads = [
            threading.Thread(target=client_loop, args=(i,), daemon=True)
            for i in range(64)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while sum(counts) < 64 and time.monotonic() < deadline:
            time.sleep(0.1)  # every client warmed (socket pooled)
        assert errors == []

        # 1. kill -9 one replica under load
        st = client.fleet_status()
        victim = st["replicas"][1]["pid"]
        os.kill(victim, signal.SIGKILL)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = client.fleet_status()
            if st["replicas"][1]["state"] == "up" and \
                    st["replicas"][1]["restarts"] >= 1:
                break
            time.sleep(0.25)
        assert st["replicas"][1]["state"] == "up"
        assert errors == []

        # 2. rolling restart under the same load
        report = client.fleet_roll(timeout=600)
        assert report["ok"] is True
        assert len(report["replicas"]) == 4
        for entry in report["replicas"]:
            assert entry["restored"] is True
            assert os.path.exists(entry["checkpoint"])
        time.sleep(1.0)  # keep serving through and after the roll
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert errors == []
        assert sum(counts) > 64  # the load was real

        # hedges/reroutes surfaced in the aggregated metrics
        text = client.metrics()
        parsed = metrics.parse_text(text)
        assert any(
            k.startswith("misaka_fleet_rolls_total") and v >= 1
            for k, v in parsed.items() if 'status="ok"' in k
        )
        # valid exposition: ONE TYPE line per family across the whole
        # fleet (replicas and the parent register many of the same
        # families; duplicates break strict Prometheus parsers)
        type_lines = [
            ln for ln in text.splitlines() if ln.startswith("# TYPE ")
        ]
        assert len(type_lines) == len(set(type_lines))

        # 3. bit-identical restore across a quiescent roll.  The TIS
        # machine free-runs (tick advances with no traffic), so freeze
        # it first: /pause fans out to every replica, and the roll must
        # PRESERVE the paused state (a deploy never flips a frozen
        # network back on) — only then is state comparable bit-for-bit.
        client.pause()
        resp = client._post_form("/checkpoint", name="verify-a")
        assert b"Success" in resp
        before = {}
        for i in range(4):
            path = str(tmp_path / f"replica-{i}" / "verify-a.npz")
            with np.load(path) as z:
                before[i] = {k: z[k].copy() for k in z.files}
        report = client.fleet_roll(timeout=600)
        assert report["ok"] is True
        # the replacements came back PAUSED (run state preserved)
        st = json.loads(client._request("/status", None, "GET"))
        for idx, row in st["replicas"].items():
            assert row["running"] is False, f"replica {idx} resumed"
        resp = client._post_form("/checkpoint", name="verify-b")
        assert b"Success" in resp
        for i in range(4):
            path = str(tmp_path / f"replica-{i}" / "verify-b.npz")
            with np.load(path) as z:
                after = {k: z[k].copy() for k in z.files}
            assert set(after) == set(before[i])
            for k in after:
                if k == "__tsdb__":
                    # the retained metric history (utils/tsdb.py, r15)
                    # rides checkpoints so /debug/series SURVIVES the
                    # roll — and it keeps accumulating samples across it
                    # by design.  Only the NETWORK state is bit-pinned;
                    # the history's presence is the contract here.
                    continue
                assert np.array_equal(after[k], before[i][k]), (
                    f"replica {i} array {k!r} changed across the roll"
                )
        client.close()
    finally:
        stop.set()
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()


@pytest.mark.slow
def test_fleet_replica_kill_fault_point(tmp_path):
    """MISAKA_FAULTS=replica_kill=N SIGKILLs one replica after boot; the
    fleet absorbs it: the supervisor respawns, traffic never errors."""
    from misaka_tpu.client import MisakaClient

    port = frontends.pick_free_port()
    base = f"http://127.0.0.1:{port}"
    proc = _boot_fleet(
        tmp_path, port, replicas=2, workers=2,
        extra={"MISAKA_FAULTS": "replica_kill=3"},
    )
    try:
        _wait_fleet_healthy(base)
        client = MisakaClient(base, timeout=60)
        vals = np.arange(16, dtype=np.int32)
        deadline = time.monotonic() + 60
        saw_restart = False
        while time.monotonic() < deadline:
            out = client.compute_raw(vals)
            assert np.array_equal(out, vals + 2)
            st = client.fleet_status()
            if st["restarts_total"] >= 1 and st["up"] == 2:
                saw_restart = True
                break
            time.sleep(0.2)
        assert saw_restart, "replica_kill fault never fired or never healed"
        client.close()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
