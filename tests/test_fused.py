"""Fused Pallas kernel vs XLA superstep: bit-identical on every config.

Runs the pallas kernel in interpreter mode (CPU CI); the TPU path is the
same kernel body.  Every BASELINE network plus stall/backpressure edge cases
must produce exactly the same NetworkState as core/step.py.
"""


import numpy as np
import pytest

pytestmark = pytest.mark.slow  # interpret-mode kernel parity sweeps — `make test-all` lane

from misaka_tpu import networks
from misaka_tpu.runtime.topology import Topology


def assert_states_equal(a, b):
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)),
            np.asarray(getattr(b, name)),
            err_msg=f"state field '{name}' diverged",
        )


def run_both(topology, batch, steps, n_inputs=4, seed=0, block_batch=128,
             unroll_cap=None):
    net = topology.compile(batch=batch)
    rng = np.random.default_rng(seed)
    vals = rng.integers(-100, 100, size=(batch, n_inputs)).astype(np.int32)

    def prep(state):
        return state._replace(
            in_buf=state.in_buf.at[:, :n_inputs].set(vals),
            in_wr=state.in_wr + n_inputs,
        )

    ref = net.run(prep(net.init_state()), steps)
    fused = net.fused_runner(
        steps, block_batch=block_batch, interpret=True, unroll_cap=unroll_cap
    )
    out = fused(prep(net.init_state()))
    return ref, out


# unroll_cap=4 forces every cap-8 buffer below onto the chunked VMEM-ref
# path (ref_gather/ref_scatter/ref_copy, fused.py) that production hits only
# at caps > UNROLL_CAP=64 — so both storage modes run in every parity case.
STORAGE_MODES = pytest.mark.parametrize(
    "unroll_cap", [None, 4], ids=["regs", "chunked"]
)


@STORAGE_MODES
@pytest.mark.parametrize(
    "name,steps",
    [("add2", 60), ("acc_loop", 50), ("ring4", 80), ("sorter", 50), ("mesh8", 60)],
)
def test_fused_bit_identical(name, steps, unroll_cap):
    top = networks.BASELINE_CONFIGS[name](in_cap=8, out_cap=8, stack_cap=8)
    ref, out = run_both(top, batch=128, steps=steps, unroll_cap=unroll_cap)
    assert_states_equal(ref, out)
    assert int(np.asarray(out.out_wr).min()) > 0  # it actually computed


@STORAGE_MODES
def test_fused_multiblock_grid(unroll_cap):
    # 4 grid blocks of 128: block independence + index maps.
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    ref, out = run_both(
        top, batch=512, steps=60, block_batch=128, unroll_cap=unroll_cap
    )
    assert_states_equal(ref, out)


@STORAGE_MODES
def test_fused_backpressure_parks(unroll_cap):
    # Tiny out ring (cap 8 chunked / 2 regs): producers park identically in
    # both kernels.  Chunked caps must be multiples of 8, so the chunked
    # variant uses out_cap=8 with more inputs to hit the cap.
    out_cap = 2 if unroll_cap is None else 8
    top = networks.acc_loop(in_cap=16, out_cap=out_cap, stack_cap=8)
    ref, out = run_both(
        top, batch=128, steps=120, n_inputs=out_cap + 4, unroll_cap=unroll_cap
    )
    assert_states_equal(ref, out)
    np.testing.assert_array_equal(np.asarray(out.out_wr), out_cap)  # parked


def test_fused_starvation_parks():
    # No inputs at all: every lane parks on IN; state identical, zero retired
    # on the IN line.
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    net = top.compile(batch=128)
    ref = net.run(net.init_state(), 40)
    out = net.fused_runner(40, block_batch=128, interpret=True)(net.init_state())
    assert_states_equal(ref, out)
    assert int(np.asarray(out.out_wr).sum()) == 0


@pytest.mark.parametrize("name", ["add2", "mesh8"])
def test_fused_engine_default_caps(name):
    # Engine-default 1024-deep rings/stacks (the caps every serve topology
    # gets unless overridden, engine.py) compile and hold bit-parity on the
    # chunked path at production thresholds — no unroll_cap override, so
    # this runs exactly the storage mode a default `engine=fused` serve hits.
    top = networks.BASELINE_CONFIGS[name]()  # stack/in/out caps = 1024
    ref, out = run_both(top, batch=128, steps=60, n_inputs=6)
    assert_states_equal(ref, out)
    assert int(np.asarray(out.out_wr).min()) > 0


def test_fused_deep_stack_push_chunked():
    # Flood a cap-128 stack to depth 100 (> UNROLL_CAP=64): every push above
    # slot 64 lands via ref_scatter across chunk boundaries.  add2's own
    # stack never passes depth 1, so this uses a dedicated pusher.
    top = Topology(
        node_info={"p": "program", "st": "stack"},
        programs={"p": "IN ACC\nPUSH ACC, st\n"},
        in_cap=104, out_cap=8, stack_cap=128,
    )
    ref, out = run_both(top, batch=128, steps=310, n_inputs=100)
    assert_states_equal(ref, out)
    np.testing.assert_array_equal(np.asarray(out.stack_top)[:, 0], 100)


def test_fused_deep_stack_pop_chunked():
    # Drain a prefilled depth-100 stack through OUT: every pop above slot 64
    # reads via ref_gather across chunk boundaries, and the LIFO stream
    # must match the scan engine value-for-value.
    top = Topology(
        node_info={"p": "program", "st": "stack"},
        programs={"p": "POP st, ACC\nOUT ACC\n"},
        in_cap=8, out_cap=104, stack_cap=128,
    )
    net = top.compile(batch=128)
    rng = np.random.default_rng(7)
    depth = 100
    fill = rng.integers(-1000, 1000, size=(128, 1, depth)).astype(np.int32)

    def prep(state):
        return state._replace(
            stack_mem=state.stack_mem.at[:, :, :depth].set(fill),
            stack_top=state.stack_top.at[:, 0].set(depth),
        )

    ref = net.run(prep(net.init_state()), 320)
    fused = net.fused_runner(320, block_batch=128, interpret=True)
    out = fused(prep(net.init_state()))
    assert_states_equal(ref, out)
    np.testing.assert_array_equal(np.asarray(out.out_wr), depth)
    np.testing.assert_array_equal(  # LIFO order through the chunked gather
        np.asarray(out.out_buf)[:, :depth], fill[:, 0, ::-1]
    )


def test_fused_requires_batch():
    net = networks.add2().compile()  # unbatched
    with pytest.raises(ValueError, match="batched"):
        net.fused_runner(8)


def test_fused_validates_block_batch():
    net = networks.add2().compile(batch=256)
    with pytest.raises(ValueError, match="multiple"):
        net.fused_runner(8, block_batch=100)


def _hi_live_lanes(net):
    from misaka_tpu.tis import isa

    cond = (isa.OP_JEZ, isa.OP_JNZ, isa.OP_JGZ, isa.OP_JLZ)
    code = np.asarray(net.code)
    lens = np.asarray(net.prog_len)
    live = []
    for n in range(code.shape[0]):
        ops = code[n, : lens[n], 0]
        srcs = code[n, : lens[n], 1]
        live.append(
            bool(
                np.isin(ops, cond).any()
                or ((ops == isa.OP_JRO) & (srcs == isa.SRC_ACC)).any()
            )
        )
    return live


@pytest.mark.parametrize(
    "name,steps",
    [("add2", 60), ("acc_loop", 50), ("ring4", 80), ("sorter", 50), ("mesh8", 60)],
)
def test_fused_elide_dead_hi_wire_identical(name, steps):
    """elide_dead_hi=True (the r5 VPU-headroom cut): every observable plane
    stays bit-identical to core/step.py; only acc_hi/bak_hi of hi-DEAD
    lanes (no cond-jumps / JRO-ACC readers) become unspecified.  sorter is
    all-live (branch-heavy) so it pins the live path under the flag too."""
    top = networks.BASELINE_CONFIGS[name](in_cap=8, out_cap=8, stack_cap=8)
    net = top.compile(batch=128)
    rng = np.random.default_rng(5)
    vals = rng.integers(-100, 100, size=(128, 4)).astype(np.int32)

    def prep(state):
        return state._replace(
            in_buf=state.in_buf.at[:, :4].set(vals),
            in_wr=state.in_wr + 4,
        )

    ref = net.run(prep(net.init_state()), steps)
    fused = net.fused_runner(
        steps, block_batch=128, interpret=True, elide_dead_hi=True
    )
    out = fused(prep(net.init_state()))

    live = _hi_live_lanes(net)
    for field in ref._fields:
        a, b = np.asarray(getattr(ref, field)), np.asarray(getattr(out, field))
        if field in ("acc_hi", "bak_hi"):
            for n, is_live in enumerate(live):
                if is_live:
                    np.testing.assert_array_equal(
                        a[:, n], b[:, n], err_msg=f"{field} lane {n} (hi-LIVE)"
                    )
            continue
        np.testing.assert_array_equal(a, b, err_msg=f"field {field}")
    if name == "sorter":
        assert all(live)  # branch-heavy: the flag must not elide anything
    if name in ("add2", "ring4"):
        assert not any(live)  # straight-line/JMP-only: fully elided
