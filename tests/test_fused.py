"""Fused Pallas kernel vs XLA superstep: bit-identical on every config.

Runs the pallas kernel in interpreter mode (CPU CI); the TPU path is the
same kernel body.  Every BASELINE network plus stall/backpressure edge cases
must produce exactly the same NetworkState as core/step.py.
"""

import numpy as np
import pytest

from misaka_tpu import networks


def assert_states_equal(a, b):
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)),
            np.asarray(getattr(b, name)),
            err_msg=f"state field '{name}' diverged",
        )


def run_both(topology, batch, steps, n_inputs=4, seed=0, block_batch=128):
    net = topology.compile(batch=batch)
    rng = np.random.default_rng(seed)
    vals = rng.integers(-100, 100, size=(batch, n_inputs)).astype(np.int32)

    def prep(state):
        return state._replace(
            in_buf=state.in_buf.at[:, :n_inputs].set(vals),
            in_wr=state.in_wr + n_inputs,
        )

    ref = net.run(prep(net.init_state()), steps)
    fused = net.fused_runner(steps, block_batch=block_batch, interpret=True)
    out = fused(prep(net.init_state()))
    return ref, out


@pytest.mark.parametrize(
    "name,steps",
    [("add2", 60), ("acc_loop", 50), ("ring4", 80), ("sorter", 50), ("mesh8", 60)],
)
def test_fused_bit_identical(name, steps):
    top = networks.BASELINE_CONFIGS[name](in_cap=8, out_cap=8, stack_cap=8)
    ref, out = run_both(top, batch=128, steps=steps)
    assert_states_equal(ref, out)
    assert int(np.asarray(out.out_wr).min()) > 0  # it actually computed


def test_fused_multiblock_grid():
    # 4 grid blocks of 128: block independence + index maps.
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    ref, out = run_both(top, batch=512, steps=60, block_batch=128)
    assert_states_equal(ref, out)


def test_fused_backpressure_parks():
    # Tiny out ring (cap 2): producers park identically in both kernels.
    top = networks.acc_loop(in_cap=8, out_cap=2, stack_cap=8)
    ref, out = run_both(top, batch=128, steps=50, n_inputs=6)
    assert_states_equal(ref, out)
    np.testing.assert_array_equal(np.asarray(out.out_wr), 2)  # parked at cap


def test_fused_starvation_parks():
    # No inputs at all: every lane parks on IN; state identical, zero retired
    # on the IN line.
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    net = top.compile(batch=128)
    ref = net.run(net.init_state(), 40)
    out = net.fused_runner(40, block_batch=128, interpret=True)(net.init_state())
    assert_states_equal(ref, out)
    assert int(np.asarray(out.out_wr).sum()) == 0


def test_fused_requires_batch():
    net = networks.add2().compile()  # unbatched
    with pytest.raises(ValueError, match="batched"):
        net.fused_runner(8)


def test_fused_validates_block_batch():
    net = networks.add2().compile(batch=256)
    with pytest.raises(ValueError, match="multiple"):
        net.fused_runner(8, block_batch=100)
