"""Scale envelopes: deep HBM stacks and wide lane pipelines.

The BASELINE configs top out at 8 lanes and tiny stacks; these tests pin the
dimensions a user would actually grow — stack depth (the reference's
unbounded IntStack is the long-context analogue, SURVEY.md §5) and lane
count (deeper pipelines) — including the lane-sharded multi-chip path.
"""

import numpy as np

from misaka_tpu import networks
from misaka_tpu.runtime.topology import Topology


def test_deep_stack_hbm():
    """A 16384-deep stack round-trips through the XLA engine (the fused
    kernel correctly refuses caps this size — VMEM budget — so big stacks
    are exactly what the scan engine is for)."""
    depth = 16384
    top = Topology(
        node_info={"p": "program", "s": "stack"},
        programs={
            "p": "TOP: IN ACC\nJLZ DRAIN\nPUSH ACC, s\nJMP TOP\nDRAIN: POP s, ACC\nOUT ACC\nJMP DRAIN"
        },
        stack_cap=depth,
        in_cap=depth + 8,
        out_cap=depth + 8,
    )
    net = top.compile()
    state = net.init_state()
    vals = list(range(1, depth + 1))
    state, took = net.feed(state, vals + [-1])  # -1 = switch to drain mode
    assert took == depth + 1
    # Each value costs ~3 ticks to push, ~3 to pop; generous budget.
    state, outs = net.compute_stream(state, [], expected=depth, max_steps=8 * depth + 1024)
    assert outs == vals[::-1]  # full LIFO reversal at depth
    assert int(state.stack_top[0]) == 0


def test_wide_pipeline_32_lanes():
    """ring(32): one value laps 32 nodes; output = v + 32."""
    net = networks.ring(32, in_cap=8, out_cap=8).compile()
    state = net.init_state()
    state, outs = net.compute_stream(state, [0, 100, -5], max_steps=100_000)
    assert outs == [32, 132, 27]


def test_wide_pipeline_sharded():
    """ring(32) lane-sharded over all 8 virtual devices matches single-chip."""
    import jax

    from misaka_tpu.parallel import make_mesh, make_sharded_runner, shard_state

    net = networks.ring(32, in_cap=8, out_cap=8).compile()
    ticks = 2048

    # single-chip reference run
    ref = net.init_state()
    ref, _ = net.feed(ref, [7, 8, 9])
    ref = net.run(ref, ticks)

    mesh = make_mesh(model_parallel=8)
    state = net.init_state()
    state, _ = net.feed(state, [7, 8, 9])
    state = shard_state(state, mesh, batched=False)
    runner = make_sharded_runner(net.code, net.prog_len, mesh, num_steps=ticks, batched=False)
    state = runner(state)

    for a, b, name in zip(ref, state, ref._fields):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    out_count = int(ref.out_wr - ref.out_rd)
    assert out_count == 3
    buf = np.asarray(ref.out_buf)
    assert buf[:3].tolist() == [39, 40, 41]
