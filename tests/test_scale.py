"""Scale envelopes: deep HBM stacks and wide lane pipelines.

The BASELINE configs top out at 8 lanes and tiny stacks; these tests pin the
dimensions a user would actually grow — stack depth (the reference's
unbounded IntStack is the long-context analogue, SURVEY.md §5) and lane
count (deeper pipelines) — including the lane-sharded multi-chip path and
the compact scatter-election kernel that auto-replaces the dense one-hot
kernel at/above compact_auto_lanes() lanes — platform-dependent, 0 on CPU
(core/routing.py; the dense
kernel's O(N·4N) election matrices fault the TPU worker at 256 lanes under
production batches).
"""


import numpy as np
import pytest

pytestmark = pytest.mark.slow  # wide-lane / deep-stack envelopes — `make test-all` lane

from misaka_tpu import networks
from misaka_tpu.core.engine import COMPACT_AUTO_LANES
from misaka_tpu.runtime.topology import Topology


def test_deep_stack_hbm():
    """A 16384-deep stack round-trips through the XLA engine (the fused
    kernel correctly refuses caps this size — VMEM budget — so big stacks
    are exactly what the scan engine is for)."""
    depth = 16384
    top = Topology(
        node_info={"p": "program", "s": "stack"},
        programs={
            "p": "TOP: IN ACC\nJLZ DRAIN\nPUSH ACC, s\nJMP TOP\nDRAIN: POP s, ACC\nOUT ACC\nJMP DRAIN"
        },
        stack_cap=depth,
        in_cap=depth + 8,
        out_cap=depth + 8,
    )
    net = top.compile()
    state = net.init_state()
    vals = list(range(1, depth + 1))
    state, took = net.feed(state, vals + [-1])  # -1 = switch to drain mode
    assert took == depth + 1
    # Each value costs ~3 ticks to push, ~3 to pop; generous budget.
    state, outs = net.compute_stream(state, [], expected=depth, max_steps=8 * depth + 1024)
    assert outs == vals[::-1]  # full LIFO reversal at depth
    assert int(state.stack_top[0]) == 0


def test_wide_pipeline_32_lanes():
    """ring(32): one value laps 32 nodes; output = v + 32."""
    net = networks.ring(32, in_cap=8, out_cap=8).compile()
    state = net.init_state()
    state, outs = net.compute_stream(state, [0, 100, -5], max_steps=100_000)
    assert outs == [32, 132, 27]


def _fuzz_wide_net(seed, n_lanes, batch=None):
    """A random multi-opcode network WIDE enough to land in compact-kernel
    territory (>= COMPACT_AUTO_LANES lanes)."""
    from misaka_tpu.core import CompiledNetwork
    from misaka_tpu.tis.lower import lower_program, pad_programs
    from tests.test_differential import random_program

    rng = np.random.default_rng(seed)
    n_stacks = int(rng.integers(1, 3))
    lane_names = [f"n{i}" for i in range(n_lanes)]
    stack_names = [f"s{i}" for i in range(n_stacks)]
    lane_ids = {name: i for i, name in enumerate(lane_names)}
    stack_ids = {name: i for i, name in enumerate(stack_names)}
    programs = [
        random_program(rng, lane_names, stack_names, int(rng.integers(1, 9)))
        for _ in lane_names
    ]
    code, lengths = pad_programs(
        [lower_program(p, lane_ids, stack_ids) for p in programs]
    )
    net = CompiledNetwork(
        code=code, prog_len=lengths, num_stacks=n_stacks,
        stack_cap=4, in_cap=8, out_cap=8, batch=batch,
    )
    return net, rng


@pytest.mark.parametrize("seed", range(6))
def test_compact_matches_dense_fuzzed(seed):
    """engine='compact' vs engine='dense' bit-identity on random 40-lane
    networks (every opcode, contended stacks/ports/IN/OUT)."""
    n_lanes = 40
    assert n_lanes >= COMPACT_AUTO_LANES
    net, rng = _fuzz_wide_net(3000 + seed, n_lanes)
    vals = rng.integers(-100, 100, size=6).astype(np.int32)

    def prep(state):
        return state._replace(
            in_buf=state.in_buf.at[:6].set(vals), in_wr=state.in_wr + 6
        )

    dense = net.run(prep(net.init_state()), 64, engine="dense")
    for engine in ("compact", "chained"):
        other = net.run(prep(net.init_state()), 64, engine=engine)
        for name in dense._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(dense, name)),
                np.asarray(getattr(other, name)),
                err_msg=f"state field '{name}' diverged "
                        f"({engine}, seed {seed})",
            )


def test_compact_matches_dense_batched():
    """Batched (vmapped) compact kernel matches dense on a fuzzed network."""
    net, rng = _fuzz_wide_net(4242, 36, batch=3)
    vals = rng.integers(-100, 100, size=(3, 6)).astype(np.int32)

    def prep(state):
        return state._replace(
            in_buf=state.in_buf.at[:, :6].set(vals), in_wr=state.in_wr + 6
        )

    dense = net.run(prep(net.init_state()), 64, engine="dense")
    compact = net.run(prep(net.init_state()), 64, engine="compact")
    for name in dense._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(dense, name)),
            np.asarray(getattr(compact, name)),
            err_msg=f"state field '{name}' diverged",
        )


def test_wide_pipeline_served_batched():
    """pipeline(64) through a batched MasterNode: the batched serve path's
    scan fallback auto-selects the compact step for wide networks."""
    from misaka_tpu.runtime.master import MasterNode

    master = MasterNode(
        networks.pipeline(64, in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=256, batch=2, engine="scan",
    )
    master.run()
    try:
        vals = list(range(-3, 5))
        assert master.compute_spread(vals, timeout=120) == [v + 64 for v in vals]
    finally:
        master.pause()


def test_wide_pipeline_served_unbatched():
    """pipeline(48) through an unbatched MasterNode: serve_chunk routes wide
    networks through the per-network compact serve closure."""
    from misaka_tpu.runtime.master import MasterNode

    master = MasterNode(
        networks.pipeline(48, in_cap=8, out_cap=8, stack_cap=8), chunk_steps=192
    )
    master.run()
    try:
        assert master.compute(5, timeout=120) == 53
        assert master.compute(-10, timeout=120) == 38
    finally:
        master.pause()


def test_wide_pipeline_sharded():
    """ring(32) lane-sharded over all 8 virtual devices matches single-chip."""
    import jax

    from misaka_tpu.parallel import make_mesh, make_sharded_runner, shard_state

    net = networks.ring(32, in_cap=8, out_cap=8).compile()
    ticks = 2048

    # single-chip reference run
    ref = net.init_state()
    ref, _ = net.feed(ref, [7, 8, 9])
    ref = net.run(ref, ticks)

    mesh = make_mesh(model_parallel=8)
    state = net.init_state()
    state, _ = net.feed(state, [7, 8, 9])
    state = shard_state(state, mesh, batched=False)
    runner = make_sharded_runner(net.code, net.prog_len, mesh, num_steps=ticks, batched=False)
    state = runner(state)

    for a, b, name in zip(ref, state, ref._fields):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
    out_count = int(ref.out_wr - ref.out_rd)
    assert out_count == 3
    buf = np.asarray(ref.out_buf)
    assert buf[:3].tolist() == [39, 40, 41]


def test_compact_auto_lanes_platform_and_override(monkeypatch):
    """The dense->compact auto-threshold is platform-dependent (CPU: compact
    always wins, measured r5) and env-overridable for A/B probes."""
    import jax

    from misaka_tpu.core.engine import compact_auto_lanes

    monkeypatch.delenv("MISAKA_COMPACT_AUTO_LANES", raising=False)
    expected = {"cpu": 0, "tpu": COMPACT_AUTO_LANES}.get(
        jax.default_backend(), COMPACT_AUTO_LANES
    )
    assert compact_auto_lanes() == expected
    monkeypatch.setenv("MISAKA_COMPACT_AUTO_LANES", "7")
    assert compact_auto_lanes() == 7


def test_wide_engine_platform_and_override(monkeypatch):
    """The wide-network kernel choice is platform-dependent (chained beats
    the scatter kernel 1.40-1.44x on TPU at 64/256 lanes, measured r5
    artifacts/r05/lane_followup.json; compact wins on CPU) and
    env-overridable; the auto path and step_fn must honor it."""
    import jax

    from misaka_tpu.core.engine import wide_engine

    monkeypatch.delenv("MISAKA_WIDE_ENGINE", raising=False)
    expected = {"cpu": "compact", "tpu": "chained"}.get(
        jax.default_backend(), "compact"
    )
    assert wide_engine() == expected
    monkeypatch.setenv("MISAKA_WIDE_ENGINE", "chained")
    assert wide_engine() == "chained"
    # step_fn must return the chained closure for a wide net under the
    # override (bit-identical kernels — selection is the observable)
    monkeypatch.setenv("MISAKA_COMPACT_AUTO_LANES", "2")
    net = networks.pipeline(4, in_cap=8, out_cap=8, stack_cap=8).compile()
    assert net.step_fn() is net._chained_step()
    monkeypatch.setenv("MISAKA_WIDE_ENGINE", "bogus")
    with pytest.raises(ValueError, match="MISAKA_WIDE_ENGINE"):
        wide_engine()


def test_cpu_auto_selects_compact_small_net(monkeypatch):
    """On CPU even a reference-scale (3-lane) network auto-runs the compact
    kernel — 1.5-2.4x dense on the serving path (ARCHITECTURE.md)."""
    import jax

    if jax.default_backend() != "cpu":
        pytest.skip("CPU auto-selection probe")
    # a shell still carrying A/B-probe overrides must not flip the auto
    # choice under the test (same guard as the test_tpu.py hardware lane)
    monkeypatch.delenv("MISAKA_WIDE_ENGINE", raising=False)
    monkeypatch.delenv("MISAKA_COMPACT_AUTO_LANES", raising=False)
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    net = top.compile()
    # the auto choice must BE the compact kernel, not just clear the
    # threshold: step_fn() returns the route-table closure on CPU
    assert net.step_fn() is net._compact_step()
    from misaka_tpu.core.engine import compact_auto_lanes

    assert net.num_lanes >= compact_auto_lanes()
