"""Golden tests for the TIS frontend.

Each case pins one branch of the reference grammar
(/root/reference/internal/tis/tokenizer.go:41-101) or one error path
(:19-21, :74, :101).
"""

import numpy as np
import pytest

from misaka_tpu.tis import isa
from misaka_tpu.tis.lower import TISLowerError, lower_program, pad_programs
from misaka_tpu.tis.parser import (
    TISParseError,
    generate_label_map,
    parse,
    tokenize,
)


def toks(program):
    rows, _ = parse(program)
    return rows


# --- label map (tokenizer.go:11-26) ----------------------------------------

def test_label_map_basic():
    assert generate_label_map(["start:", "NOP", "loop: ADD 1"]) == {
        "START": 0,
        "LOOP": 2,
    }


def test_label_map_uppercases():
    assert generate_label_map(["lOoP: NOP"]) == {"LOOP": 0}


def test_label_map_duplicate_rejected():
    with pytest.raises(TISParseError, match="Cannot repeat label"):
        generate_label_map(["a:", "A:"])


def test_label_indices_are_raw_line_numbers():
    # comments and blanks occupy slots, so labels later in the file keep
    # their raw line index (tokenizer.go:41-46 + program.go:429).
    program = "# header\n\nhere: NOP"
    _, label_map = parse(program)
    assert label_map == {"HERE": 2}


# --- token rows: every grammar branch --------------------------------------

@pytest.mark.parametrize(
    "line,row",
    [
        ("", ["NOP"]),
        ("   ", ["NOP"]),
        ("# a comment", ["NOP"]),
        ("lbl:", ["NOP"]),
        ("lbl: # trailing comment", ["NOP"]),
        ("NOP", ["NOP"]),
        ("SWP", ["SWP"]),
        ("SAV", ["SAV"]),
        ("NEG", ["NEG"]),
        ("MOV 5, ACC", ["MOV_VAL_LOCAL", "5", "ACC"]),
        ("MOV -3, NIL", ["MOV_VAL_LOCAL", "-3", "NIL"]),
        ("MOV 7, misaka2:R0", ["MOV_VAL_NETWORK", "7", "misaka2:R0"]),
        ("MOV ACC, NIL", ["MOV_SRC_LOCAL", "ACC", "NIL"]),
        ("MOV R2, ACC", ["MOV_SRC_LOCAL", "R2", "ACC"]),
        ("MOV ACC, misaka1:R3", ["MOV_SRC_NETWORK", "ACC", "misaka1:R3"]),
        ("MOV R0, n:R1", ["MOV_SRC_NETWORK", "R0", "n:R1"]),
        ("ADD 4", ["ADD_VAL", "4"]),
        ("SUB -9", ["SUB_VAL", "-9"]),
        ("ADD R1", ["ADD_SRC", "R1"]),
        ("SUB ACC", ["SUB_SRC", "ACC"]),
        ("JRO 2", ["JRO_VAL", "2"]),
        ("JRO -1", ["JRO_VAL", "-1"]),
        ("JRO ACC", ["JRO_SRC", "ACC"]),
        ("PUSH 3, st", ["PUSH_VAL", "3", "st"]),
        ("PUSH ACC, st", ["PUSH_SRC", "ACC", "st"]),
        ("POP st, ACC", ["POP", "st", "ACC"]),
        ("POP st, NIL", ["POP", "st", "NIL"]),
        ("IN ACC", ["IN", "ACC"]),
        ("IN NIL", ["IN", "NIL"]),
        ("OUT 12", ["OUT_VAL", "12"]),
        ("OUT ACC", ["OUT_SRC", "ACC"]),
        ("OUT R3", ["OUT_SRC", "R3"]),
    ],
)
def test_tokenize_branches(line, row):
    assert toks(line) == [row]


def test_jumps_resolve_and_uppercase():
    program = "start: NOP\nJMP start\nJEZ START\nJNZ start\nJGZ start\nJLZ start"
    rows, _ = parse(program)
    assert rows[1:] == [
        ["JMP", "START"],
        ["JEZ", "START"],
        ["JNZ", "START"],
        ["JGZ", "START"],
        ["JLZ", "START"],
    ]


def test_label_prefix_with_instruction():
    assert toks("loop: ADD 1") == [["ADD_VAL", "1"]]


# --- error paths ------------------------------------------------------------

def test_undeclared_jump_label():
    with pytest.raises(TISParseError, match="label 'NOWHERE' was not declared"):
        parse("JMP nowhere")


def test_invalid_instruction():
    with pytest.raises(TISParseError, match="not a valid instruction"):
        parse("FROB 1")


def test_comma_requires_trailing_whitespace():
    # `\s*,\s+` (tokenizer.go:50): no space after comma is a syntax error.
    with pytest.raises(TISParseError, match="not a valid instruction"):
        parse("MOV 1,ACC")


def test_mov_immediate_destination_must_be_local_or_port():
    with pytest.raises(TISParseError, match="not a valid instruction"):
        parse("MOV 1, R0")  # inbound ports are read-only locally


# --- lowering ---------------------------------------------------------------

LANES = {"misaka1": 0, "misaka2": 1}
STACKS = {"misaka3": 0}


def test_lower_add2_sender():
    p = lower_program(
        "IN ACC\nADD 1\nMOV ACC, misaka2:R0\nMOV R0, ACC\nOUT ACC",
        LANES,
        STACKS,
    )
    assert p.length == 5
    np.testing.assert_array_equal(
        p.code[:, isa.F_OP],
        [isa.OP_IN, isa.OP_ADD, isa.OP_MOV_NET, isa.OP_MOV_LOCAL, isa.OP_OUT],
    )
    assert p.code[2, isa.F_TGT] == 1
    assert p.code[2, isa.F_PORT] == 0
    assert p.code[2, isa.F_SRC] == isa.SRC_ACC
    assert p.code[3, isa.F_SRC] == isa.SRC_R0
    assert p.code[1, isa.F_SRC] == isa.SRC_IMM
    assert p.code[1, isa.F_IMM] == 1


def test_lower_stack_ops():
    p = lower_program("PUSH ACC, misaka3\nPOP misaka3, ACC", LANES, STACKS)
    assert p.code[0, isa.F_OP] == isa.OP_PUSH
    assert p.code[0, isa.F_TGT] == 0
    assert p.code[1, isa.F_OP] == isa.OP_POP
    assert p.code[1, isa.F_DST] == isa.DST_ACC


def test_lower_jump_targets_are_line_indices():
    p = lower_program("# hdr\nloop: ADD 1\nJMP loop", LANES, STACKS)
    assert p.code[2, isa.F_OP] == isa.OP_JMP
    assert p.code[2, isa.F_JMP] == 1


def test_lower_unknown_network_target():
    with pytest.raises(TISLowerError, match="not a program node"):
        lower_program("MOV ACC, ghost:R0", LANES, STACKS)


def test_lower_unknown_stack_target():
    with pytest.raises(TISLowerError, match="not a stack node"):
        lower_program("PUSH 1, ghost", LANES, STACKS)


def test_lower_immediate_wraps_to_int32():
    p = lower_program("ADD 2147483650", LANES, STACKS)
    assert p.code[0, isa.F_IMM] == -2147483646


def test_pad_programs():
    a = lower_program("NOP", LANES, STACKS)
    b = lower_program("ADD 1\nSUB 2\nNEG", LANES, STACKS)
    code, lengths = pad_programs([a, b])
    assert code.shape == (2, 3, isa.NFIELDS)
    np.testing.assert_array_equal(lengths, [1, 3])
    assert code[0, 1, isa.F_OP] == isa.OP_NOP  # padding
