"""Debugger + CLI: breakpoints, inspection, listings, and the front door."""

import json
import subprocess
import sys

import pytest

from misaka_tpu import networks
from misaka_tpu.__main__ import main as cli_main
from misaka_tpu.debug import Debugger


@pytest.fixture()
def dbg():
    return Debugger(networks.add2(in_cap=8, out_cap=8, stack_cap=8))


def test_breakpoint_stops_at_line(dbg):
    dbg.feed([5])
    # misaka2 line 2 = PUSH ACC, misaka3; acc must hold 5+1+1 when we arrive.
    dbg.add_breakpoint("misaka2", 2)
    hits = dbg.run(max_ticks=100)
    assert hits == [("misaka2", 2)]
    assert dbg.inspect("misaka2")["acc"] == 7
    assert dbg.inspect("misaka2")["pc"] == 2


def test_step_through_completion(dbg):
    dbg.feed([1])
    assert dbg.step(40) == []  # no breakpoints: runs the full count
    assert dbg.tick == 40
    assert dbg.outputs() == [3]


def test_inspect_ports_and_stacks(dbg):
    dbg.feed([9])
    dbg.add_breakpoint("misaka2", 3)  # POP misaka3, ACC — stack holds the value
    dbg.run(max_ticks=100)
    stacks = dbg.stacks()
    assert stacks["misaka3"] == [11]
    info = dbg.inspect("misaka1")
    assert set(info) == {"acc", "bak", "pc", "ports", "holding", "hold_val", "retired"}
    assert set(info["ports"]) == {"R0", "R1", "R2", "R3"}


def test_listing_shows_cursor_and_breakpoint(dbg):
    dbg.add_breakpoint("misaka1", 2)
    listing = dbg.listing("misaka1")
    lines = listing.split("\n")
    assert lines[0].startswith("-> ")       # pc=0 cursor
    assert lines[2].startswith("  B")       # breakpoint mark
    assert "IN ACC" in lines[0]
    assert "MOV ACC, misaka2:R0" in lines[2]


def test_history_listing(dbg):
    dbg.feed([4])
    dbg.step(10)
    hist = dbg.history(last=5)
    assert "misaka1" in hist and "pc=" in hist


def test_reset(dbg):
    dbg.feed([1])
    dbg.step(10)
    dbg.reset()
    assert dbg.tick == 0
    assert dbg.inspect("misaka1")["acc"] == 0


def test_bad_lane_and_line(dbg):
    with pytest.raises(KeyError):
        dbg.inspect("nope")
    with pytest.raises(ValueError):
        dbg.add_breakpoint("misaka1", 99)


# --- CLI ---------------------------------------------------------------------


def test_cli_check_named_config(capsys):
    assert cli_main(["check", "add2"]) == 0
    out = capsys.readouterr().out
    assert "2 program node(s), 1 stack node(s)" in out


def test_cli_check_bad_file(capsys):
    assert cli_main(["check", "/nonexistent.json"]) == 1


def test_cli_check_topology_file(tmp_path, capsys):
    spec = {"nodes": {"a": "program"}, "programs": {"a": "IN ACC\nOUT ACC"}}
    path = tmp_path / "net.json"
    path.write_text(json.dumps(spec))
    assert cli_main(["check", str(path)]) == 0
    assert "a: 2 line(s)" in capsys.readouterr().out


def test_cli_disasm(capsys):
    assert cli_main(["disasm", "add2"]) == 0
    out = capsys.readouterr().out
    assert "# --- misaka1 ---" in out
    assert "PUSH ACC, misaka3" in out


def test_cli_bench_smoke(capsys):
    assert cli_main(["bench", "--batch", "32", "--values", "8"]) == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["metric"] == "add2_cli_smoke"
    assert payload["value"] > 0


def test_cli_debug_scripted():
    """Drive the interactive debugger through a pipe end-to-end."""
    script = "\n".join(
        [
            "feed 5",
            "break misaka2 2",
            "run",
            "print misaka2",
            "stacks",
            "list misaka1",
            "out",
            "step 100",
            "out",
            "trace 4",
            "quit",
        ]
    )
    proc = subprocess.run(
        [sys.executable, "-m", "misaka_tpu", "debug", "add2"],
        input=script,
        capture_output=True,
        text=True,
        timeout=300,
        cwd="/root/repo",
        env={"PYTHONPATH": "/root/repo", "PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu", "HOME": "/root"},
    )
    assert proc.returncode == 0, proc.stderr
    assert "BREAK [('misaka2', 2)]" in proc.stdout
    assert '"acc": 7' in proc.stdout
    assert "[7]" in proc.stdout  # outputs after completion: 5+2
