"""Worker process for tests/test_multihost.py: one of two JAX processes.

Joins a real jax.distributed coordinator (gloo CPU collectives), builds the
hybrid DCN mesh, and runs the full sharded superstep engine on the add-2
network across the process boundary.  Prints "MULTIHOST_OK" on success.

Usage: python multihost_worker.py <coordinator_port> <process_id>
"""

import os
import sys

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)

import numpy as np

NUM_PROCS = 2
LOCAL_DEVICES = 4
MODEL_PARALLEL = 2
BATCH = 4          # = data axis size: 2 procs x (4 local / 2 mp)
PER_INSTANCE = 4
TICKS = 64


def main() -> None:
    port, pid = sys.argv[1], sys.argv[2]
    os.environ["MISAKA_COORDINATOR"] = f"127.0.0.1:{port}"
    os.environ["MISAKA_NUM_PROCESSES"] = str(NUM_PROCS)
    os.environ["MISAKA_PROCESS_ID"] = pid

    from misaka_tpu import networks
    from misaka_tpu.parallel import (
        MODEL_AXIS,
        hybrid_mesh,
        initialize_from_env,
        make_global_state,
        make_routed_runner,
        make_sharded_runner,
    )

    assert initialize_from_env()
    assert initialize_from_env()  # idempotent once up
    assert jax.process_count() == NUM_PROCS
    assert len(jax.local_devices()) == LOCAL_DEVICES

    mesh = hybrid_mesh(model_parallel=MODEL_PARALLEL)
    assert mesh.shape[MODEL_AXIS] == MODEL_PARALLEL
    # `model` must never cross a process boundary (ICI-only lane collectives).
    for row in mesh.devices:  # rows = data, cols = model
        assert len({d.process_index for d in row}) == 1

    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    net = top.compile(batch=BATCH)

    vals = (np.arange(BATCH)[:, None] * 10 + np.arange(PER_INSTANCE)[None, :]).astype(
        np.int32
    )
    in_buf = np.zeros((BATCH, 8), np.int32)
    in_buf[:, :PER_INSTANCE] = vals

    # Both lane-sharded kernels must work across the real process boundary:
    # the statically-routed default AND the first-generation gather variant.
    for label, factory in (
        ("routed", make_routed_runner), ("gather", make_sharded_runner)
    ):
        state = net.init_state()._replace(
            in_buf=in_buf,
            in_wr=np.full((BATCH,), PER_INSTANCE, np.int32),
        )
        gstate = make_global_state(state, mesh, batched=True)
        runner = factory(net.code, net.prog_len, mesh, num_steps=TICKS)
        gstate = runner(gstate)

        # Every locally-owned instance must have emitted all values, +2 each.
        expected_out = vals + 2
        checked = 0
        for shard in gstate.out_wr.addressable_shards:
            np.testing.assert_array_equal(
                np.asarray(shard.data), PER_INSTANCE,
                err_msg=f"kernel {label}: out_wr",
            )
        for shard in gstate.out_buf.addressable_shards:
            idx = shard.index[0]
            got = np.asarray(shard.data)[:, :PER_INSTANCE]
            np.testing.assert_array_equal(
                got, expected_out[idx], err_msg=f"kernel {label}: out_buf"
            )
            checked += got.shape[0]
        assert checked > 0, f"kernel {label}: no local shards checked"
    print("MULTIHOST_OK", flush=True)


if __name__ == "__main__":
    main()
