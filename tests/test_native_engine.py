"""The native serving engine (core/native_serve.py + engine="native").

Pins the three contracts the latency tier rests on:
  * serve_chunk parity — the host interpreter's serve iteration is
    field-for-field equivalent to the XLA `_serve_body` one (same packed
    snapshot, same state; stack_mem compared below each top since pops
    leave residue above it on the device path);
  * state portability — import/export round-trips every NetworkState
    field, rejects corrupt states, and checkpoints cross engines in both
    directions (native master -> scan master and back);
  * lifecycle — run/pause/reset/load/auto-grow behave identically under
    engine="native".
"""

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.core import native_serve
from misaka_tpu.runtime.master import MasterNode
from misaka_tpu.runtime.topology import Topology

pytestmark = pytest.mark.skipif(
    not native_serve.available(), reason="native interpreter unavailable (no g++)"
)


def masked_stack(state):
    """stack_mem with above-top residue zeroed (pops do not scrub slots)."""
    mem = np.asarray(state.stack_mem)
    top = np.asarray(state.stack_top)
    col = np.arange(mem.shape[-1])
    return np.where(col[None, :] < top[:, None], mem, 0)


def assert_states_equal(a, b):
    for f in type(a)._fields:
        if f == "stack_mem":
            np.testing.assert_array_equal(masked_stack(a), masked_stack(b), err_msg=f)
        else:
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
            )


def test_serve_chunk_parity_add2():
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    ns = native_serve.NativeServe(net)
    s_dev = net.init_state()
    s_nat = net.init_state()
    rng = np.random.default_rng(7)
    for it in range(12):
        # mixed schedule: feeds of varying size, including idle chunks
        count = int(rng.integers(0, 4)) if it % 3 else 0
        vals = np.zeros((net.in_cap,), np.int32)
        vals[:count] = rng.integers(-1000, 1000, size=count)
        free = net.in_cap - int(np.asarray(s_nat.in_wr) - np.asarray(s_nat.in_rd))
        count = min(count, free)
        s_dev, p_dev = net.serve_chunk(s_dev, vals, count, 16)
        s_nat, p_nat = ns.serve_chunk(s_nat, vals, count, 16)
        np.testing.assert_array_equal(np.asarray(p_dev), p_nat, err_msg=f"iter {it}")
        assert_states_equal(s_dev, s_nat)


def test_serve_chunk_parity_stack_net():
    # PUSH/POP traffic exercises stack export/import mid-flight
    top = Topology(
        node_info={"p": "program", "st": "stack"},
        programs={"p": "IN ACC\nPUSH ACC, st\nPUSH ACC, st\nPOP st, ACC\n"
                       "POP st, ACC\nOUT ACC"},
        in_cap=8, out_cap=8, stack_cap=4,
    )
    net = top.compile()
    ns = native_serve.NativeServe(net)
    s_dev, s_nat = net.init_state(), net.init_state()
    for i in range(10):
        vals = np.zeros((net.in_cap,), np.int32)
        vals[0] = i + 1
        s_dev, p_dev = net.serve_chunk(s_dev, vals, 1, 24)
        s_nat, p_nat = ns.serve_chunk(s_nat, vals, 1, 24)
        np.testing.assert_array_equal(np.asarray(p_dev), p_nat)
        assert_states_equal(s_dev, s_nat)


def test_import_export_roundtrip_and_rejects():
    from misaka_tpu.core.cinterp import NativeInterpreter

    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    with NativeInterpreter(
        np.asarray(net.code), np.asarray(net.prog_len),
        net.num_stacks, net.stack_cap, net.in_cap, net.out_cap,
    ) as it:
        it.feed(np.array([5, 6, 7], np.int32))
        it.run(13)
        d = it.export_arrays()
        it2_kw = dict(d)
        it.import_arrays(it2_kw)          # self-roundtrip
        d2 = it.export_arrays()
        for k in d:
            np.testing.assert_array_equal(d[k], d2[k], err_msg=k)
        # corrupt states are rejected with the interpreter unchanged
        for k, v in [
            ("pc", np.full_like(d["pc"], 99)),
            ("stack_top", np.full_like(d["stack_top"], net.stack_cap + 1)),
            ("in_rd", np.int32(-1)),
            ("out_wr", np.int32(d["out_rd"] - 1)),
        ]:
            bad = dict(d)
            bad[k] = v
            with pytest.raises(ValueError):
                it.import_arrays(bad)
        d3 = it.export_arrays()
        for k in d:
            np.testing.assert_array_equal(d[k], d3[k], err_msg=f"mutated by {k}")


def test_master_native_matches_scan():
    streams = [list(range(1, 30)), [0, -5, 2**31 - 3, -(2**31) + 1]]
    outs = {}
    for eng in ("scan", "native"):
        m = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                       chunk_steps=16, engine=eng)
        if eng == "native":
            assert m.engine_name == "native"
        m.run()
        try:
            outs[eng] = [m.compute_many(s) for s in streams]
            st = m.status()
            assert st["running"] and st["tick"] > 0
            assert st["engine"] == m.engine_name
        finally:
            m.pause()
    assert outs["scan"] == outs["native"]


def test_checkpoint_crosses_engines(tmp_path):
    # half the stream through a NATIVE master, checkpoint, finish on a SCAN
    # master restored from it (then the reverse direction)
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    for first, second in (("native", "scan"), ("scan", "native")):
        path = str(tmp_path / f"{first}-{second}.npz")
        m1 = MasterNode(top, chunk_steps=16, engine=first)
        m1.run()
        a = m1.compute_many([1, 2, 3])
        m1.pause()
        m1.save_checkpoint(path)
        m2 = MasterNode(top, chunk_steps=16, engine=second)
        m2.load_checkpoint(path)
        m2.run()
        b = m2.compute_many([10, 20, 30])
        m2.pause()
        assert a == [3, 4, 5] and b == [12, 22, 32], (first, second)


def test_native_lifecycle_reset_and_load():
    m = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                   chunk_steps=16, engine="native")
    m.run()
    assert m.compute(5) == 7
    m.reset()
    m.run()
    assert m.compute(5) == 7
    # live reprogram keeps the native engine
    m.load("misaka1", "IN ACC\nADD 10\nOUT ACC")
    m.run()
    assert m.compute(5) == 15
    assert m.engine_name == "native"
    m.pause()


@pytest.mark.slow
def test_native_autogrow():
    from tests.test_autogrow import reverser_top, run_reverser

    m = MasterNode(reverser_top(), chunk_steps=32, engine="native")
    m.run()
    run_reverser(m)
    assert m._net.stack_cap >= 64
    assert m.engine_name == "native"


def test_native_rejects_invalid_combos():
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    with pytest.raises(ValueError, match="single instance"):
        MasterNode(top, engine="native", batch=4)
    with pytest.raises(ValueError, match="scan engine"):
        MasterNode(top, engine="native", trace_cap=16)
    with pytest.raises(ValueError, match="single-chip"):
        MasterNode(top, engine="native", batch=None, model_parallel=2)


def test_native_restore_rejects_corrupt_state():
    # a value-corrupt snapshot (shapes fine, pc beyond the program) must be
    # rejected AT restore() — inside the device loop it would stop serving
    m = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                   chunk_steps=16, engine="native")
    snap = m.snapshot()
    bad = snap._replace(pc=np.full_like(np.asarray(snap.pc), 99))
    with pytest.raises(ValueError):
        m.restore(bad)
    m.run()
    try:
        assert m.compute(5) == 7  # the master kept its good state and serves
    finally:
        m.pause()


def compare_serve(seed, iters=10, chunk=16):
    """Random net through BOTH serve paths (device serve_chunk vs the
    native engine's twin) under a randomized feed schedule: packed
    snapshots byte-equal, states field-equal (live stack slots).  The
    soak tool (tools/soak_differential.py) cycles this past CI's seeds."""
    from tests.test_differential import (
        IN_CAP, OUT_CAP, STACK_CAP, build_random_network,
    )
    from misaka_tpu.core import CompiledNetwork

    code, lengths, n_stacks, inputs, programs = build_random_network(seed)
    net = CompiledNetwork(
        code=code, prog_len=lengths, num_stacks=max(1, n_stacks),
        stack_cap=STACK_CAP, in_cap=IN_CAP, out_cap=OUT_CAP, batch=None,
    )
    ns = native_serve.NativeServe(net)
    rng = np.random.default_rng(seed ^ 0x5EEDE)
    s_dev, s_nat = net.init_state(), net.init_state()
    for it in range(iters):
        free = net.in_cap - int(np.asarray(s_nat.in_wr) - np.asarray(s_nat.in_rd))
        count = min(int(rng.integers(0, 5)), free) if it % 4 else 0
        vals = np.zeros((net.in_cap,), np.int32)
        vals[:count] = rng.integers(-100, 100, size=count)
        s_dev, p_dev = net.serve_chunk(s_dev, vals, count, chunk)
        s_nat, p_nat = ns.serve_chunk(s_nat, vals, count, chunk)
        np.testing.assert_array_equal(
            np.asarray(p_dev), p_nat,
            err_msg=f"seed {seed} iter {it}\n" + "\n---\n".join(programs),
        )
        assert_states_equal(s_dev, s_nat)
    ns.close()


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(1000, 1015))
def test_serve_fuzz(seed):
    compare_serve(seed)
