"""The native serving engine (core/native_serve.py + engine="native").

Pins the three contracts the latency tier rests on:
  * serve_chunk parity — the host interpreter's serve iteration is
    field-for-field equivalent to the XLA `_serve_body` one (same packed
    snapshot, same state; stack_mem compared below each top since pops
    leave residue above it on the device path);
  * state portability — import/export round-trips every NetworkState
    field, rejects corrupt states, and checkpoints cross engines in both
    directions (native master -> scan master and back);
  * lifecycle — run/pause/reset/load/auto-grow behave identically under
    engine="native".
"""

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.core import native_serve
from misaka_tpu.runtime.master import MasterNode
from misaka_tpu.runtime.topology import Topology

pytestmark = pytest.mark.skipif(
    not native_serve.available(), reason="native interpreter unavailable (no g++)"
)


def masked_stack(state):
    """stack_mem with above-top residue zeroed (pops do not scrub slots)."""
    mem = np.asarray(state.stack_mem)
    top = np.asarray(state.stack_top)
    col = np.arange(mem.shape[-1])
    return np.where(col[None, :] < top[:, None], mem, 0)


def materialize(engine, state):
    """Resident-state native engines (r17) return their identity anchor
    with stale array contents; export before reading state fields — the
    exact step MasterNode._sync_native_state performs.  Residency stays
    armed on the returned object, so the differential loops below keep
    exercising the resident tick path AND the export coherence."""
    exp = getattr(engine, "export_resident", None)
    st = exp() if exp is not None else None
    return st if st is not None else state


def assert_states_equal(a, b):
    for f in type(a)._fields:
        if f == "stack_mem":
            np.testing.assert_array_equal(masked_stack(a), masked_stack(b), err_msg=f)
        else:
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
            )


def test_serve_chunk_parity_add2():
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    ns = native_serve.NativeServe(net)
    s_dev = net.init_state()
    s_nat = net.init_state()
    rng = np.random.default_rng(7)
    for it in range(12):
        # mixed schedule: feeds of varying size, including idle chunks
        count = int(rng.integers(0, 4)) if it % 3 else 0
        vals = np.zeros((net.in_cap,), np.int32)
        vals[:count] = rng.integers(-1000, 1000, size=count)
        free = net.in_cap - int(np.asarray(s_nat.in_wr) - np.asarray(s_nat.in_rd))
        count = min(count, free)
        s_dev, p_dev = net.serve_chunk(s_dev, vals, count, 16)
        s_nat, p_nat = ns.serve_chunk(s_nat, vals, count, 16)
        s_nat = materialize(ns, s_nat)
        np.testing.assert_array_equal(np.asarray(p_dev), p_nat, err_msg=f"iter {it}")
        assert_states_equal(s_dev, s_nat)


def test_serve_chunk_parity_stack_net():
    # PUSH/POP traffic exercises stack export/import mid-flight
    top = Topology(
        node_info={"p": "program", "st": "stack"},
        programs={"p": "IN ACC\nPUSH ACC, st\nPUSH ACC, st\nPOP st, ACC\n"
                       "POP st, ACC\nOUT ACC"},
        in_cap=8, out_cap=8, stack_cap=4,
    )
    net = top.compile()
    ns = native_serve.NativeServe(net)
    s_dev, s_nat = net.init_state(), net.init_state()
    for i in range(10):
        vals = np.zeros((net.in_cap,), np.int32)
        vals[0] = i + 1
        s_dev, p_dev = net.serve_chunk(s_dev, vals, 1, 24)
        s_nat, p_nat = ns.serve_chunk(s_nat, vals, 1, 24)
        s_nat = materialize(ns, s_nat)
        np.testing.assert_array_equal(np.asarray(p_dev), p_nat)
        assert_states_equal(s_dev, s_nat)


def test_import_export_roundtrip_and_rejects():
    from misaka_tpu.core.cinterp import NativeInterpreter

    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    with NativeInterpreter(
        np.asarray(net.code), np.asarray(net.prog_len),
        net.num_stacks, net.stack_cap, net.in_cap, net.out_cap,
    ) as it:
        it.feed(np.array([5, 6, 7], np.int32))
        it.run(13)
        d = it.export_arrays()
        it2_kw = dict(d)
        it.import_arrays(it2_kw)          # self-roundtrip
        d2 = it.export_arrays()
        for k in d:
            np.testing.assert_array_equal(d[k], d2[k], err_msg=k)
        # corrupt states are rejected with the interpreter unchanged
        for k, v in [
            ("pc", np.full_like(d["pc"], 99)),
            ("stack_top", np.full_like(d["stack_top"], net.stack_cap + 1)),
            ("in_rd", np.int32(-1)),
            ("out_wr", np.int32(d["out_rd"] - 1)),
        ]:
            bad = dict(d)
            bad[k] = v
            with pytest.raises(ValueError):
                it.import_arrays(bad)
        d3 = it.export_arrays()
        for k in d:
            np.testing.assert_array_equal(d[k], d3[k], err_msg=f"mutated by {k}")


def test_master_native_matches_scan():
    streams = [list(range(1, 30)), [0, -5, 2**31 - 3, -(2**31) + 1]]
    outs = {}
    for eng in ("scan", "native"):
        m = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                       chunk_steps=16, engine=eng)
        if eng == "native":
            assert m.engine_name == "native"
        m.run()
        try:
            outs[eng] = [m.compute_many(s) for s in streams]
            st = m.status()
            assert st["running"] and st["tick"] > 0
            assert st["engine"] == m.engine_name
        finally:
            m.pause()
    assert outs["scan"] == outs["native"]


def test_checkpoint_crosses_engines(tmp_path):
    # half the stream through a NATIVE master, checkpoint, finish on a SCAN
    # master restored from it (then the reverse direction)
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    for first, second in (("native", "scan"), ("scan", "native")):
        path = str(tmp_path / f"{first}-{second}.npz")
        m1 = MasterNode(top, chunk_steps=16, engine=first)
        m1.run()
        a = m1.compute_many([1, 2, 3])
        m1.pause()
        m1.save_checkpoint(path)
        m2 = MasterNode(top, chunk_steps=16, engine=second)
        m2.load_checkpoint(path)
        m2.run()
        b = m2.compute_many([10, 20, 30])
        m2.pause()
        assert a == [3, 4, 5] and b == [12, 22, 32], (first, second)


def test_native_lifecycle_reset_and_load():
    m = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                   chunk_steps=16, engine="native")
    m.run()
    assert m.compute(5) == 7
    m.reset()
    m.run()
    assert m.compute(5) == 7
    # live reprogram keeps the native engine
    m.load("misaka1", "IN ACC\nADD 10\nOUT ACC")
    m.run()
    assert m.compute(5) == 15
    assert m.engine_name == "native"
    m.pause()


@pytest.mark.slow
def test_native_autogrow():
    from tests.test_autogrow import reverser_top, run_reverser

    m = MasterNode(reverser_top(), chunk_steps=32, engine="native")
    m.run()
    run_reverser(m)
    assert m._net.stack_cap >= 64
    assert m.engine_name == "native"


def test_native_rejects_invalid_combos():
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    with pytest.raises(ValueError, match="scan engine"):
        MasterNode(top, engine="native", trace_cap=16)
    with pytest.raises(ValueError, match="single-chip"):
        MasterNode(top, engine="native", batch=None, model_parallel=2)
    with pytest.raises(ValueError, match="single-chip"):
        MasterNode(top, engine="native", batch=4, data_parallel=2,
                   model_parallel=2)


def test_native_restore_rejects_corrupt_state():
    # a value-corrupt snapshot (shapes fine, pc beyond the program) must be
    # rejected AT restore() — inside the device loop it would stop serving
    m = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                   chunk_steps=16, engine="native")
    snap = m.snapshot()
    bad = snap._replace(pc=np.full_like(np.asarray(snap.pc), 99))
    with pytest.raises(ValueError):
        m.restore(bad)
    m.run()
    try:
        assert m.compute(5) == 7  # the master kept its good state and serves
    finally:
        m.pause()


def compare_serve(seed, iters=10, chunk=16):
    """Random net through BOTH serve paths (device serve_chunk vs the
    native engine's twin) under a randomized feed schedule: packed
    snapshots byte-equal, states field-equal (live stack slots).  The
    soak tool (tools/soak_differential.py) cycles this past CI's seeds."""
    from tests.test_differential import (
        IN_CAP, OUT_CAP, STACK_CAP, build_random_network,
    )
    from misaka_tpu.core import CompiledNetwork

    code, lengths, n_stacks, inputs, programs = build_random_network(seed)
    net = CompiledNetwork(
        code=code, prog_len=lengths, num_stacks=max(1, n_stacks),
        stack_cap=STACK_CAP, in_cap=IN_CAP, out_cap=OUT_CAP, batch=None,
    )
    ns = native_serve.NativeServe(net)
    rng = np.random.default_rng(seed ^ 0x5EEDE)
    s_dev, s_nat = net.init_state(), net.init_state()
    for it in range(iters):
        free = net.in_cap - int(np.asarray(s_nat.in_wr) - np.asarray(s_nat.in_rd))
        count = min(int(rng.integers(0, 5)), free) if it % 4 else 0
        vals = np.zeros((net.in_cap,), np.int32)
        vals[:count] = rng.integers(-100, 100, size=count)
        s_dev, p_dev = net.serve_chunk(s_dev, vals, count, chunk)
        s_nat, p_nat = ns.serve_chunk(s_nat, vals, count, chunk)
        np.testing.assert_array_equal(
            np.asarray(p_dev), p_nat,
            err_msg=f"seed {seed} iter {it}\n" + "\n---\n".join(programs),
        )
        assert_states_equal(s_dev, s_nat)
    ns.close()


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(1000, 1015))
def test_serve_fuzz(seed):
    compare_serve(seed)


# --- the multi-threaded serving pool (NativeServePool) ----------------------


def test_pool_matches_batched_scan_twins():
    """The pool's serve/idle pair is BIT-IDENTICAL to the jitted batched
    serve twins (engine.make_batched_serve) over a randomized feed schedule
    — packed snapshots byte-equal, states field-equal (live stack slots)."""
    B = 4
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile(batch=B)
    pool = native_serve.NativeServePool(net, chunk_steps=16)
    serve_fn, idle_fn = net.make_batched_serve(None, 16)
    s_dev, s_nat = net.init_state(), net.init_state()
    rng = np.random.default_rng(11)
    try:
        for it in range(12):
            if it % 4 == 3:  # idle iterations interleave with fed ones
                s_dev, c_dev = idle_fn(s_dev)
                s_nat, c_nat = pool.idle(s_nat)
                s_nat = materialize(pool, s_nat)
                np.testing.assert_array_equal(
                    np.asarray(c_dev), c_nat, err_msg=f"idle iter {it}"
                )
            else:
                free = net.in_cap - (
                    np.asarray(s_nat.in_wr) - np.asarray(s_nat.in_rd)
                )
                counts = np.minimum(
                    rng.integers(0, 5, size=B), free
                ).astype(np.int32)
                vals = np.zeros((B, net.in_cap), np.int32)
                for b in range(B):
                    vals[b, : counts[b]] = rng.integers(
                        -1000, 1000, size=counts[b]
                    )
                s_dev, p_dev = serve_fn(s_dev, vals, counts)
                s_nat, p_nat = pool.serve(s_nat, vals, counts)
                s_nat = materialize(pool, s_nat)
                np.testing.assert_array_equal(
                    np.asarray(p_dev), p_nat, err_msg=f"iter {it}"
                )
            assert_states_equal(s_dev, s_nat)
    finally:
        pool.close()


def test_pool_matches_single_engine_and_oracle():
    """Each pool replica's output stream is bit-identical to the
    single-threaded native engine AND the Python oracle fed the same
    per-replica stream — the multi-threaded tier changes scheduling, never
    results."""
    from tests.oracle import Oracle

    B, in_cap = 3, 8
    net = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=8).compile(
        batch=B
    )
    pool = native_serve.NativeServePool(net, chunk_steps=24, threads=B)
    single_net = networks.add2(in_cap=in_cap, out_cap=in_cap, stack_cap=8).compile()
    rng = np.random.default_rng(23)
    streams = [rng.integers(-1000, 1000, size=6).astype(np.int32) for _ in range(B)]

    # pool: one serve iteration feeds every replica its whole stream
    vals = np.zeros((B, in_cap), np.int32)
    counts = np.zeros((B,), np.int32)
    for b, stream in enumerate(streams):
        vals[b, : len(stream)] = stream
        counts[b] = len(stream)
    state = net.init_state()
    state, packed = pool.serve(state, vals, counts, num_steps=96)
    pool.close()

    for b, stream in enumerate(streams):
        rd, wr = packed[b, 2], packed[b, 3]
        got = packed[b, 4:][(rd + np.arange(wr - rd)) % in_cap]
        # single-threaded native engine, same stream
        ns = native_serve.NativeServe(single_net)
        sv = np.zeros((in_cap,), np.int32)
        sv[: len(stream)] = stream
        s1, p1 = ns.serve_chunk(single_net.init_state(), sv, len(stream), 96)
        ns.close()
        srd, swr = p1[2], p1[3]
        np.testing.assert_array_equal(
            got, p1[4:][(srd + np.arange(swr - srd)) % in_cap],
            err_msg=f"replica {b} vs single-threaded engine",
        )
        # Python oracle, same stream
        oracle = Oracle(
            np.asarray(single_net.code), np.asarray(single_net.prog_len),
            single_net.num_stacks, single_net.stack_cap, in_cap, in_cap,
        )
        oracle.feed([int(v) for v in stream])
        oracle.run(96)
        expect = [
            oracle.out_buf[i % in_cap]
            for i in range(oracle.out_rd, oracle.out_wr)
        ]
        assert got.tolist() == expect, f"replica {b} vs oracle"


def test_pool_parity_corpus_replay():
    """The committed parity corpus through the MULTI-THREADED pool: every
    case's inputs stream through R replicas at once, and every replica's
    output stream must equal the committed single-engine recording."""
    import glob
    import json
    import os

    corpus = sorted(glob.glob(os.path.join(
        os.path.dirname(__file__), "corpus", "parity", "*.json"
    )))
    assert corpus, "parity corpus missing"
    R = 4
    for path in corpus:
        with open(path) as f:
            case = json.load(f)
        top = Topology(
            node_info=case["node_info"], programs=case["programs"],
            stack_cap=64, in_cap=32, out_cap=32,
        )
        net = top.compile(batch=R)
        pool = native_serve.NativeServePool(net, chunk_steps=768)
        try:
            inputs = np.asarray(case["inputs"], np.int32)
            vals = np.zeros((R, net.in_cap), np.int32)
            vals[:, : len(inputs)] = inputs
            counts = np.full((R,), len(inputs), np.int32)
            state, packed = pool.serve(net.init_state(), vals, counts)
        finally:
            pool.close()
        want = case["engine_outputs"]
        for r in range(R):
            rd, wr = packed[r, 2], packed[r, 3]
            got = packed[r, 4:][(rd + np.arange(wr - rd)) % net.out_cap].tolist()
            if case["compare"] == "stream":
                assert got == want, f"{case['name']} replica {r}"
            else:
                assert sorted(got) == sorted(want), f"{case['name']} replica {r}"


def test_pool_rejects_corrupt_state_unchanged():
    B = 2
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile(batch=B)
    pool = native_serve.NativeServePool(net, chunk_steps=16)
    try:
        good = net.init_state()
        bad = good._replace(pc=np.full_like(np.asarray(good.pc), 99))
        with pytest.raises(ValueError):
            pool.validate_state(bad)
        pool.validate_state(good)  # and the pool still serves good states
        s, p = pool.serve(
            good, np.zeros((B, net.in_cap), np.int32), np.zeros((B,), np.int32)
        )
        assert p.shape == (B, 4 + net.out_cap)
    finally:
        pool.close()


def test_import_rejects_out_of_range_values():
    """ADVICE r5 #1: a wider-integer state whose values exceed int32 must
    raise, not silently wrap into the valid range."""
    from misaka_tpu.core.cinterp import NativeInterpreter

    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    with NativeInterpreter(
        np.asarray(net.code), np.asarray(net.prog_len),
        net.num_stacks, net.stack_cap, net.in_cap, net.out_cap,
    ) as it:
        it.feed(np.array([1, 2], np.int32))
        it.run(8)
        d = it.export_arrays()
        for k, v in [
            ("acc", np.asarray(d["acc"], np.int64) + 2**40),
            ("in_rd", np.int64(2**33)),
            ("stack_mem", np.asarray(d["stack_mem"], np.uint64) + 2**32),
        ]:
            bad = dict(d)
            bad[k] = v
            with pytest.raises(ValueError):
                it.import_arrays(bad)
        # int64 VALUES that fit int32 still import fine (e.g. np.load of a
        # checkpoint edited through a default-int64 tool)
        ok = dict(d)
        ok["acc"] = np.asarray(d["acc"], np.int64)
        it.import_arrays(ok)


def test_master_batched_native_serves():
    """MasterNode(batch=B, engine='native'): the thread-pooled host tier
    through the real device loop — compute_many, compute_spread, status."""
    m = MasterNode(networks.add2(in_cap=8, out_cap=8, stack_cap=8),
                   chunk_steps=32, batch=4, engine="native")
    assert m.engine_name == "native"
    m.run()
    try:
        assert m.compute_many([1, 2, 3]) == [3, 4, 5]
        vals = np.arange(-40, 40, dtype=np.int32)
        np.testing.assert_array_equal(
            m.compute_spread(vals, return_array=True), vals + 2
        )
        st = m.status()
        assert st["engine"] == "native" and st["tick"] > 0
        assert st["batch"] == 4
    finally:
        m.pause()


def test_master_batched_native_checkpoint(tmp_path):
    """Checkpoints cross between the batched native pool and the batched
    scan engine in both directions (validate_state covers the pool side)."""
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    for first, second in (("native", "scan"), ("scan", "native")):
        path = str(tmp_path / f"b-{first}-{second}.npz")
        m1 = MasterNode(top, chunk_steps=16, batch=2, engine=first)
        m1.run()
        a = m1.compute_many([1, 2, 3])
        m1.pause()
        m1.save_checkpoint(path)
        m2 = MasterNode(top, chunk_steps=16, batch=2, engine=second)
        m2.load_checkpoint(path)
        m2.run()
        b = m2.compute_many([10, 20, 30])
        m2.pause()
        assert a == [3, 4, 5] and b == [12, 22, 32], (first, second)


def test_auto_engine_prefers_native_off_tpu(monkeypatch):
    """With no TPU attached, engine='auto' must serve through the native
    tier for both unbatched and batched masters (the r4/r5 driver captures
    served scan-compact at 0.16-0.34M/s with this tier sitting unused) —
    and MISAKA_NATIVE_AUTO=0 must restore the old behavior."""
    import jax

    if jax.devices()[0].platform == "tpu":
        pytest.skip("auto prefers the device engines on TPU")
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    assert MasterNode(top, chunk_steps=16, engine="auto").engine_name == "native"
    assert MasterNode(
        top, chunk_steps=16, batch=2, engine="auto"
    ).engine_name == "native"
    monkeypatch.setenv("MISAKA_NATIVE_AUTO", "0")
    assert MasterNode(
        top, chunk_steps=16, engine="auto"
    ).engine_name.startswith("scan-")
    monkeypatch.delenv("MISAKA_NATIVE_AUTO")
    # huge batches stay on the XLA engines (per-replica bookkeeping cost)
    monkeypatch.setenv("MISAKA_NATIVE_AUTO_MAX_BATCH", "2")
    assert MasterNode(
        top, chunk_steps=16, batch=4, engine="auto"
    ).engine_name.startswith("scan-")
