"""Native-backend hardening: hostile counter seeds and stale-.so detection.

Round-2 advisor findings (VERDICT r2 weak #7): `misaka_interp_seed_counters`
accepted arbitrary counters (a negative rd means a negative C++ `%` — an
out-of-bounds index on the next run), and staleness was mtime-based (a fresh
clone gives source and binary identical mtimes, so a stale shipped binary
was never rebuilt).  Counters are now validated at the ABI (interpreter.cpp)
and staleness is decided by an embedded source-hash tag (utils/nativelib.py).
"""

import ctypes
import os
import shutil

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.core import cinterp
from misaka_tpu.utils.nativelib import _TAG, NativeLib

needs_native = pytest.mark.skipif(
    not cinterp.available(), reason="native interpreter unavailable"
)


def make_interp():
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    return cinterp.NativeInterpreter(net.code, net.prog_len, 1, 8, 8, 8)


@needs_native
@pytest.mark.parametrize(
    "ctrs",
    [
        (-1, 0, 0, 0),          # negative rd: negative C++ % -> OOB index
        (0, -5, 0, 0),          # wr < rd
        (5, 2, 0, 0),           # inverted pair
        (0, 9, 0, 0),           # occupancy beyond in_cap=8
        (0, 0, -(2**31), 0),    # int32 min out_rd
        (0, 0, 0, 2**31 - 1),   # out ring over-occupied
    ],
)
def test_seed_counters_rejects_hostile(ctrs):
    with make_interp() as n:
        with pytest.raises(ValueError):
            n.seed_counters(*ctrs)
        # the reject left state untouched: the interpreter still computes
        n.feed([1, 2])
        n.run(100)
        assert n.drain() == [3, 4]


@needs_native
def test_seed_counters_accepts_valid():
    with make_interp() as n:
        n.seed_counters(16, 16, 24, 24)  # empty rings at rebased offsets
        n.feed([7])
        n.run(100)
        assert n.drain() == [9]


# --- stale-.so detection ----------------------------------------------------

SRC = """
extern "C" {
#ifndef MISAKA_SRC_HASH
#define MISAKA_SRC_HASH "unbuilt"
#endif
__attribute__((used)) const char misaka_src_hash_tag[] =
    "MISAKA-SRC-HASH:" MISAKA_SRC_HASH;
extern "C" int misaka_probe() { return %d; }
}
"""


def build_lib(tmp, version):
    src = os.path.join(tmp, "probe.cpp")
    so = os.path.join(tmp, "probe.so")
    with open(src, "w") as f:
        f.write(SRC % version)

    def configure(lib):
        lib.misaka_probe.restype = ctypes.c_int

    return NativeLib(src, so, configure), src, so


def toolchain():
    return shutil.which(os.environ.get("CXX", "g++")) is not None


@pytest.mark.skipif(not toolchain(), reason="no C++ toolchain")
def test_fresh_build_embeds_hash(tmp_path):
    nl, src, so = build_lib(str(tmp_path), 1)
    lib = nl.load()
    assert lib is not None and lib.misaka_probe() == 1
    with open(so, "rb") as f:
        assert _TAG in f.read()


@pytest.mark.skipif(not toolchain(), reason="no C++ toolchain")
def test_stale_so_is_rebuilt_despite_older_mtime(tmp_path):
    # A "fresh clone" shape: a v1 binary shipped next to v2 source, with the
    # binary's mtime NEWER than the source's — the old mtime rule would have
    # trusted it forever.  Separate directories per loader: dlopen caches by
    # pathname, so reloading a replaced .so at the same path in one process
    # is not meaningful to test.
    d1, d2 = tmp_path / "v1", tmp_path / "clone"
    d1.mkdir(), d2.mkdir()
    nl1, src1, so1 = build_lib(str(d1), 1)
    assert nl1.load() is not None and nl1.load().misaka_probe() == 1
    shutil.copy(so1, d2 / "probe.so")
    nl2, src2, so2 = build_lib(str(d2), 2)  # v2 source beside the v1 binary
    future = os.path.getmtime(src2) + 3600
    os.utime(so2, (future, future))
    assert not nl2._so_matches_src()
    lib = nl2.load()  # hash mismatch -> rebuild from the v2 source
    assert lib is not None and lib.misaka_probe() == 2


@pytest.mark.skipif(not toolchain(), reason="no C++ toolchain")
def test_tagless_so_is_rebuilt(tmp_path):
    # a doctored/pre-tag binary (no embedded hash) is never trusted
    d1, d2 = tmp_path / "v1", tmp_path / "doctored"
    d1.mkdir(), d2.mkdir()
    nl1, src1, so1 = build_lib(str(d1), 3)
    assert nl1.load() is not None
    with open(so1, "rb") as f:
        data = f.read()
    with open(d2 / "probe.so", "wb") as f:
        f.write(data.replace(_TAG, b"XXXXXX-XXX-XXXX:"))
    nl2, _, _ = build_lib(str(d2), 3)
    assert not nl2._so_matches_src()
    lib = nl2.load()  # rebuilds from source
    assert lib is not None and lib.misaka_probe() == 3
    assert nl2._so_matches_src()


def test_matches_missing_so(tmp_path):
    nl, src, so = build_lib(str(tmp_path), 1)
    assert not nl._so_matches_src()  # no .so on disk yet
