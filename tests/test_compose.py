"""Compose importer: a reference deployment file runs as one fused network."""

import os
import textwrap

import pytest

from misaka_tpu.runtime.compose import ComposeError, load_compose, parse_compose

# A compose file in the reference's shape (docker-compose.yml:1-77): master
# with NODE_INFO, program services with PROGRAM block scalars, a stack node,
# plus container plumbing that must be ignored.
SAMPLE = textwrap.dedent(
    """\
    version: '3'

    services:
      gateway:
        image: misaka_net
        ports:
          - "8000:8000"
        environment:
          NODE_TYPE: master
          NODE_INFO: |
            {
              "alpha": {"type": "program"},
              "beta": {"type": "program"},
              "store": {"type": "stack"}
            }
          CERT_FILE: ./openssl/service.pem
        command: ./app

      alpha:
        image: misaka_net
        environment:
          NODE_TYPE: program
          MASTER_URI: gateway
          PROGRAM: |
            IN ACC
            ADD 1
            MOV ACC, beta:R0
            MOV R0, ACC
            OUT ACC
        command: ./app

      beta:
        image: misaka_net
        environment:
          NODE_TYPE: program
          MASTER_URI: gateway
          PROGRAM: |
            MOV R0, ACC
            ADD 1
            PUSH ACC, store
            POP store, ACC
            MOV ACC, alpha:R0
        command: ./app

      store:
        image: misaka_net
        environment:
          NODE_TYPE: stack
        command: ./app

      unrelated_db:
        image: postgres
    """
)


def test_parse_sample_end_to_end():
    top = parse_compose(SAMPLE)
    assert top.node_info == {"alpha": "program", "beta": "program", "store": "stack"}
    # YAML block scalar keeps its trailing newline -> one NOP slot (parity).
    assert top.programs["alpha"].endswith("OUT ACC\n")

    net = top.compile()
    state = net.init_state()
    state, outs = net.compute_stream(state, [10, 20])
    assert outs == [12, 22]


def test_env_list_form():
    text = SAMPLE.replace(
        "environment:\n          NODE_TYPE: stack",
        'environment:\n          - "NODE_TYPE=stack"',
    )
    top = parse_compose(text)
    assert top.node_info["store"] == "stack"


def test_node_info_mismatch_rejected():
    text = SAMPLE.replace('"store": {"type": "stack"}', '"ghost": {"type": "stack"}')
    with pytest.raises(ComposeError, match="disagrees"):
        parse_compose(text)


def test_no_master_is_fine():
    """A compose file with only worker services still forms a network."""
    text = textwrap.dedent(
        """\
        services:
          solo:
            environment:
              NODE_TYPE: program
              PROGRAM: |
                IN ACC
                OUT ACC
          store:
            environment:
              NODE_TYPE: stack
        """
    )
    top = parse_compose(text)
    assert top.node_info == {"solo": "program", "store": "stack"}


def test_node_info_non_object_rejected():
    text = textwrap.dedent(
        """\
        services:
          gateway:
            environment:
              NODE_TYPE: master
              NODE_INFO: '["alpha", "beta"]'
          alpha:
            environment:
              NODE_TYPE: program
        """
    )
    with pytest.raises(ComposeError, match="NODE_INFO is not valid"):
        parse_compose(text)


def test_bad_yaml_and_empty():
    with pytest.raises(ComposeError, match="invalid YAML"):
        parse_compose(":\n  - {")
    with pytest.raises(ComposeError, match="no services"):
        parse_compose("services: 3")
    with pytest.raises(ComposeError, match="NODE_TYPE"):
        parse_compose("services:\n  a:\n    image: x\n")


def test_bad_program_surfaces_as_compose_error():
    text = SAMPLE.replace("IN ACC", "FROB 99")
    with pytest.raises(Exception, match="not a valid instruction"):
        parse_compose(text).compile()


REFERENCE_COMPOSE = "/root/reference/docker-compose.yml"


@pytest.mark.skipif(
    not os.path.exists(REFERENCE_COMPOSE), reason="reference checkout not mounted"
)
def test_reference_compose_file_runs():
    """The actual upstream deployment file computes v+2, fused."""
    top = load_compose(REFERENCE_COMPOSE)
    assert top.node_info == {
        "misaka1": "program",
        "misaka2": "program",
        "misaka3": "stack",
    }
    net = top.compile()
    state = net.init_state()
    state, outs = net.compute_stream(state, [5])
    assert outs == [7]
