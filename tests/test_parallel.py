"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

The gold standard: BOTH lane-sharded shard_map kernels — the
first-generation occupancy-gather kernel (parallel/sharded.py) and the
statically-routed two-collective kernel (parallel/routed.py, the default
model-parallel engine) — must produce BIT-IDENTICAL state to the
single-chip kernel for any program, any mesh factorization.
"""


import numpy as np
import pytest

pytestmark = pytest.mark.slow  # fuzzed sharded-kernel bit-identity — `make test-all` lane
import jax

from misaka_tpu import networks
from misaka_tpu.parallel import (
    make_mesh,
    make_routed_runner,
    make_sharded_runner,
    shard_state,
)

FACTORIES = {"gather": make_sharded_runner, "routed": make_routed_runner}


@pytest.fixture(params=sorted(FACTORIES))
def make_runner(request):
    return FACTORIES[request.param]


def assert_states_equal(a, b):
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)),
            np.asarray(getattr(b, name)),
            err_msg=f"state field '{name}' diverged",
        )


def run_both(make_runner, topology, mp, dp, batch, steps, seed=0):
    net = topology.compile(batch=batch)
    rng = np.random.default_rng(seed)
    vals = rng.integers(-100, 100, size=(batch, 4)).astype(np.int32)

    def prep(state):
        return state._replace(
            in_buf=state.in_buf.at[:, :4].set(vals), in_wr=state.in_wr + 4
        )

    ref = net.run(prep(net.init_state()), steps)
    mesh = make_mesh(mp * dp, model_parallel=mp)
    runner = make_runner(net.code, net.prog_len, mesh, num_steps=steps)
    sharded = runner(shard_state(prep(net.init_state()), mesh))
    return ref, sharded


def test_mesh8_dp2_mp4_bit_identical(make_runner):
    ref, sharded = run_both(
        make_runner, networks.mesh8(in_cap=8, out_cap=8), mp=4, dp=2, batch=4, steps=60
    )
    assert_states_equal(ref, sharded)
    assert int(np.asarray(sharded.out_wr).sum()) > 0  # it actually computed


def test_mesh8_mp8_pure_lane_parallel(make_runner):
    ref, sharded = run_both(
        make_runner, networks.mesh8(in_cap=8, out_cap=8), mp=8, dp=1, batch=2, steps=60
    )
    assert_states_equal(ref, sharded)


def test_add2_mp2_bit_identical(make_runner):
    ref, sharded = run_both(
        make_runner, networks.add2(in_cap=8, out_cap=8), mp=2, dp=4, batch=8, steps=80
    )
    assert_states_equal(ref, sharded)
    # every instance finished all 4 values: out_wr == 4 across the batch
    np.testing.assert_array_equal(np.asarray(sharded.out_wr), 4)


def test_ring8_mp4_bit_identical(make_runner):
    ref, sharded = run_both(
        make_runner, networks.ring(8, in_cap=8, out_cap=8), mp=4, dp=2, batch=4, steps=100
    )
    assert_states_equal(ref, sharded)


def test_dp_only_sharding(make_runner):
    # Pure data parallelism: mp=1, the whole lane axis on every shard.
    ref, sharded = run_both(
        make_runner, networks.add2(in_cap=8, out_cap=8), mp=1, dp=8, batch=8, steps=60
    )
    assert_states_equal(ref, sharded)


@pytest.mark.parametrize("seed", range(6))
def test_fuzzed_programs_bit_identical(make_runner, seed):
    """Random TIS programs (every opcode, self-sends, stacks, jumps) through
    the sharded kernels vs the single-chip engine — the same generator the
    oracle differential uses, now crossing shard boundaries (mp=4)."""
    from misaka_tpu.core import CompiledNetwork
    from misaka_tpu.tis.lower import lower_program, pad_programs
    from tests.test_differential import random_program

    rng = np.random.default_rng(7000 + seed)
    n_lanes, n_stacks = 4, int(rng.integers(0, 3))
    lane_names = [f"n{i}" for i in range(n_lanes)]
    stack_names = [f"s{i}" for i in range(n_stacks)]
    lane_ids = {name: i for i, name in enumerate(lane_names)}
    stack_ids = {name: i for i, name in enumerate(stack_names)}
    programs = [
        random_program(rng, lane_names, stack_names, int(rng.integers(1, 9)))
        for _ in lane_names
    ]
    code, lengths = pad_programs([lower_program(p, lane_ids, stack_ids) for p in programs])
    net = CompiledNetwork(
        code=code, prog_len=lengths, num_stacks=max(1, n_stacks),
        stack_cap=4, in_cap=8, out_cap=8, batch=2,
    )
    vals = rng.integers(-100, 100, size=(2, 6)).astype(np.int32)

    def prep(state):
        return state._replace(
            in_buf=state.in_buf.at[:, :6].set(vals), in_wr=state.in_wr + 6
        )

    ref = net.run(prep(net.init_state()), 48)
    mesh = make_mesh(8, model_parallel=4)
    runner = make_runner(net.code, net.prog_len, mesh, num_steps=48)
    sharded = runner(shard_state(prep(net.init_state()), mesh))
    assert_states_equal(ref, sharded)


def test_make_mesh_validates_divisibility():
    with pytest.raises(ValueError, match="not divisible"):
        make_mesh(8, model_parallel=3)


def test_lane_count_must_divide_model_axis(make_runner):
    net = networks.add2().compile()  # 2 lanes
    mesh = make_mesh(8, model_parallel=4)
    with pytest.raises(ValueError, match="not divisible"):
        make_runner(net.code, net.prog_len, mesh, num_steps=4)


def test_collectives_actually_cross_shards(make_runner):
    # Sanity: on mp=4, a value injected at lane a0 (shard 0) arrives at lane
    # a3 (shard 3) — the routing genuinely crosses shard boundaries.
    top = networks.mesh8(in_cap=8, out_cap=8)
    net = top.compile(batch=1)
    mesh = make_mesh(4, model_parallel=4)
    runner = make_runner(net.code, net.prog_len, mesh, num_steps=40)
    state = net.init_state()
    state = state._replace(in_buf=state.in_buf.at[:, 0].set(50), in_wr=state.in_wr + 1)
    out = runner(shard_state(state, mesh))
    assert int(np.asarray(out.out_wr)[0]) == 1
    assert int(np.asarray(out.out_buf)[0, 0]) == 54


def test_route_table_compactness():
    # The whole point of the routed kernel: election traffic scales with the
    # ACTIVE edge set, not the full lane x port dest axis.
    from misaka_tpu.parallel import build_route_table

    net = networks.mesh8(in_cap=8, out_cap=8).compile()
    route = build_route_table(net.code, net.prog_len)
    n_dests = net.num_lanes * 4
    assert 0 < route.n_send < n_dests
    # every active slot is a real (lane, port) named by some MOV_NET instr
    assert route.slot_lane.shape == (route.n_send,)
    assert (route.slot_lane >= 0).all() and (route.slot_lane < net.num_lanes).all()
    assert (route.slot_port >= 0).all() and (route.slot_port < 4).all()
    # dest_to_slot inverts the slot arrays
    full = route.slot_lane * 4 + route.slot_port
    np.testing.assert_array_equal(
        route.dest_to_slot[full], np.arange(route.n_send, dtype=np.int32)
    )
