"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

The gold standard: the lane-sharded shard_map kernel (explicit all_gather/
pmin/psum collectives over ICI) must produce BIT-IDENTICAL state to the
single-chip kernel for any program, any mesh factorization.
"""

import numpy as np
import pytest
import jax

from misaka_tpu import networks
from misaka_tpu.parallel import make_mesh, make_sharded_runner, shard_state


def assert_states_equal(a, b):
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)),
            np.asarray(getattr(b, name)),
            err_msg=f"state field '{name}' diverged",
        )


def run_both(topology, mp, dp, batch, steps, seed=0):
    net = topology.compile(batch=batch)
    rng = np.random.default_rng(seed)
    vals = rng.integers(-100, 100, size=(batch, 4)).astype(np.int32)

    def prep(state):
        return state._replace(
            in_buf=state.in_buf.at[:, :4].set(vals), in_wr=state.in_wr + 4
        )

    ref = net.run(prep(net.init_state()), steps)
    mesh = make_mesh(mp * dp, model_parallel=mp)
    runner = make_sharded_runner(net.code, net.prog_len, mesh, num_steps=steps)
    sharded = runner(shard_state(prep(net.init_state()), mesh))
    return ref, sharded


def test_mesh8_dp2_mp4_bit_identical():
    ref, sharded = run_both(networks.mesh8(in_cap=8, out_cap=8), mp=4, dp=2, batch=4, steps=60)
    assert_states_equal(ref, sharded)
    assert int(np.asarray(sharded.out_wr).sum()) > 0  # it actually computed


def test_mesh8_mp8_pure_lane_parallel():
    ref, sharded = run_both(networks.mesh8(in_cap=8, out_cap=8), mp=8, dp=1, batch=2, steps=60)
    assert_states_equal(ref, sharded)


def test_add2_mp2_bit_identical():
    ref, sharded = run_both(networks.add2(in_cap=8, out_cap=8), mp=2, dp=4, batch=8, steps=80)
    assert_states_equal(ref, sharded)
    # every instance finished all 4 values: out_wr == 4 across the batch
    np.testing.assert_array_equal(np.asarray(sharded.out_wr), 4)


def test_ring8_mp4_bit_identical():
    ref, sharded = run_both(networks.ring(8, in_cap=8, out_cap=8), mp=4, dp=2, batch=4, steps=100)
    assert_states_equal(ref, sharded)


def test_dp_only_sharding():
    # Pure data parallelism: mp=1, the whole lane axis on every shard.
    ref, sharded = run_both(networks.add2(in_cap=8, out_cap=8), mp=1, dp=8, batch=8, steps=60)
    assert_states_equal(ref, sharded)


def test_make_mesh_validates_divisibility():
    with pytest.raises(ValueError, match="not divisible"):
        make_mesh(8, model_parallel=3)


def test_lane_count_must_divide_model_axis():
    net = networks.add2().compile()  # 2 lanes
    mesh = make_mesh(8, model_parallel=4)
    with pytest.raises(ValueError, match="not divisible"):
        make_sharded_runner(net.code, net.prog_len, mesh, num_steps=4)


def test_collectives_actually_cross_shards():
    # Sanity: on mp=4, a value injected at lane a0 (shard 0) arrives at lane
    # a3 (shard 3) — the routing genuinely crosses shard boundaries.
    top = networks.mesh8(in_cap=8, out_cap=8)
    net = top.compile(batch=1)
    mesh = make_mesh(4, model_parallel=4)
    runner = make_sharded_runner(net.code, net.prog_len, mesh, num_steps=40)
    state = net.init_state()
    state = state._replace(in_buf=state.in_buf.at[:, 0].set(50), in_wr=state.in_wr + 1)
    out = runner(shard_state(state, mesh))
    assert int(np.asarray(out.out_wr)[0]) == 1
    assert int(np.asarray(out.out_buf)[0, 0]) == 54
