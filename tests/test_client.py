"""The Python client SDK against a live master (every route, both bulk lanes).

The reference ships curl snippets only; misaka_tpu.client is the typed
session a fleet client actually uses.  These tests drive a real
MasterNode + make_http_server on a loopback port through the client —
lifecycle, scalar and bulk compute, observability, checkpoints, and the
documented error shapes.
"""

import threading

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.client import MisakaClient, MisakaClientError
from misaka_tpu.runtime.master import MasterNode, make_http_server


@pytest.fixture
def served(tmp_path):
    master = MasterNode(
        networks.add2(in_cap=16, out_cap=16, stack_cap=16),
        chunk_steps=32, batch=4, trace_cap=None,
    )
    httpd = make_http_server(master, port=0, checkpoint_dir=str(tmp_path))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    client = MisakaClient(f"http://127.0.0.1:{httpd.server_address[1]}", timeout=60)
    try:
        yield master, client
    finally:
        master.pause()
        httpd.shutdown()


def test_lifecycle_and_compute(served):
    master, client = served
    # not running yet: the documented 400 shape
    with pytest.raises(MisakaClientError) as e:
        client.compute(1)
    assert e.value.status == 400 and "not running" in e.value.body

    client.run()
    assert client.compute(5) == 7
    assert client.compute(-9) == -7

    st = client.status()
    assert st["running"] is True and st["batch"] == 4

    client.pause()
    assert client.status()["running"] is False
    client.reset()
    client.run()
    assert client.compute(0) == 2


def test_bulk_lanes_roundtrip(served):
    master, client = served
    client.run()
    vals = np.arange(-40, 40, dtype=np.int32)
    np.testing.assert_array_equal(client.compute_raw(vals), vals + 2)
    np.testing.assert_array_equal(client.compute_batch(vals), vals + 2)
    # unspread (single-instance FIFO) still round-trips in order
    np.testing.assert_array_equal(
        client.compute_raw(vals[:16], spread=False), vals[:16] + 2
    )


def test_load_reprograms(served):
    master, client = served
    client.load("misaka1", "IN ACC\nADD 10\nOUT ACC")
    client.run()
    assert client.compute(1) == 11


def test_checkpoint_restore_roundtrip(served):
    master, client = served
    client.run()
    assert client.compute(3) == 5
    client.pause()
    client.checkpoint("snap1")
    client.load("misaka1", "IN ACC\nADD 100\nOUT ACC")  # diverge
    client.run()
    assert client.compute(3) == 103
    client.pause()
    client.restore("snap1")
    client.run()
    assert client.compute(3) == 5  # original program state back

    with pytest.raises(MisakaClientError) as e:
        client.restore("no/such..name")
    assert e.value.status == 400


def test_profiling_disabled_shape(served):
    # server was built without profile_dir: documented 403
    master, client = served
    with pytest.raises(MisakaClientError) as e:
        client.profile_start()
    assert e.value.status == 403


def test_trace_route_shape(tmp_path):
    master = MasterNode(
        networks.add2(in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=16, trace_cap=32,
    )
    httpd = make_http_server(master, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    client = MisakaClient(f"http://127.0.0.1:{httpd.server_address[1]}", timeout=60)
    try:
        client.run()
        assert client.compute(4) == 6
        rows = client.trace(last=8)
        assert rows and {"tick", "lane", "op", "committed"} <= set(rows[0])
    finally:
        master.pause()
        httpd.shutdown()
