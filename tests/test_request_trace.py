"""End-to-end request tracing (utils/tracespan.py): trace-ID propagation
across every hop the serving plane has, flight-recorder bounds, Perfetto
export validity, and the stays-cheap overhead guard.

The three propagation hops the acceptance pins:
  * fused HTTP: header in -> same ID in /debug/requests -> header out
  * frontend plane: an ID minted at a frontend worker is observable in
    the ENGINE's recorder, with the frontend's spans forwarded over the
    unix-socket frame metadata
  * distributed gRPC: the ID crosses as metadata and the peer records
    the receipt (rpc.recv.<Method> tier event)
"""

import http.client
import json
import logging
import threading
import time
import urllib.request

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.utils import tracespan


def _master(batch=4, **kw):
    top = networks.add2(in_cap=16, out_cap=16, stack_cap=8)
    return MasterNode(top, chunk_steps=64, batch=batch, **kw)


@pytest.fixture
def server():
    m = _master()
    httpd = make_http_server(m, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield m, f"http://127.0.0.1:{httpd.server_address[1]}"
    finally:
        m.pause()
        httpd.shutdown()


def _get(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    with urllib.request.urlopen(req, timeout=15) as resp:
        return resp.status, resp.read(), dict(resp.headers)


def _post(base, path, body, headers=None):
    req = urllib.request.Request(
        base + path, data=body, method="POST", headers=headers or {}
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, resp.read(), dict(resp.headers)


# --- the recorder (unit) ----------------------------------------------------


def test_ring_never_exceeds_n_and_slowest_k_survives():
    rec = tracespan.FlightRecorder(ring=8, slowest=2)
    slow = tracespan.Trace("slow0000")
    slow.dur = 9.5  # the known-slow synthetic request
    rec.record(slow)
    for i in range(50):
        t = tracespan.Trace(f"fast{i:04d}")
        t.dur = 0.001
        rec.record(t)
    assert len(rec.recent()) == 8  # ring bound holds
    assert all(t.trace_id.startswith("fast") for t in rec.recent())
    # ...but the reservoir still has the slow one, ranked first
    slowest = rec.slowest()
    assert len(slowest) == 2
    assert slowest[0].trace_id == "slow0000"
    assert rec.get("slow0000") is slow  # reachable by ID after eviction


def test_kill_switch_and_sampling():
    try:
        tracespan.configure({"MISAKA_TRACE_REQUESTS": "0"})
        assert not tracespan.enabled()
        assert tracespan.begin("aaaa1111") is None
        tracespan.configure({"MISAKA_TRACE_SAMPLE": "0.0"})
        # sampled out when minting...
        assert all(tracespan.begin() is None for _ in range(20))
        # ...but an inbound ID is always honored (the upstream hop chose)
        tr = tracespan.begin("bbbb2222")
        assert tr is not None and tr.trace_id == "bbbb2222"
        tracespan.end(tr, status=200)
    finally:
        tracespan.configure({})  # defaults


def test_inbound_id_sanitized():
    try:
        assert tracespan.sanitize_id("abc") is None  # too short
        assert tracespan.sanitize_id("x" * 65) is None  # too long
        assert tracespan.sanitize_id("has space") is None
        assert tracespan.sanitize_id("ab\r\nInjected: 1") is None
        assert tracespan.sanitize_id("dead-BEEF-0123") == "dead-BEEF-0123"
        tr = tracespan.begin("ab\r\nInjected: 1")  # minted instead
        assert tr is not None and "\r" not in tr.trace_id
        tracespan.end(tr)
    finally:
        tracespan.configure({})


def test_span_tree_and_merge():
    tr = tracespan.begin("cccc3333", route="/x", activate=False)
    with tracespan.span("serve.pass", trace=tr, values=4):
        time.sleep(0.001)
    tracespan.end(tr, status=200)
    d = tr.to_dict()
    assert d["spans"][0]["name"] == "serve.pass"
    assert d["spans"][0]["tier"] == "serve"
    assert d["spans"][0]["dur_ms"] >= 1.0
    assert d["spans"][0]["attrs"] == {"values": 4}
    # merging two completions of one ID unions the spans, dedup'd
    other = tracespan.Trace("cccc3333")
    other.add("http.parse", time.monotonic(), 0.001)
    other.dur = 0.002
    merged = tracespan.merge_traces([tr, other])
    assert {s.name for s in merged.spans} == {"serve.pass", "http.parse"}
    again = tracespan.merge_traces([merged, merged])
    assert len(again.spans) == len(merged.spans)


# --- hop 1: fused HTTP ------------------------------------------------------


def test_fused_http_trace_roundtrip(server):
    m, base = server
    m.run()
    tid = "feed0123beef4567"
    vals = np.arange(32, dtype=np.int32)
    status, body, headers = _post(
        base, "/compute_raw?spread=1", vals.astype("<i4").tobytes(),
        {"X-Misaka-Trace": tid},
    )
    assert status == 200
    np.testing.assert_array_equal(np.frombuffer(body, "<i4"), vals + 2)
    # hop out: the response header carries the same ID + phase timings
    assert headers["X-Misaka-Trace"] == tid
    timings = tracespan.parse_server_timing(headers["Server-Timing"])
    assert {"queue", "pass", "total"} <= set(timings)
    assert timings["total"] >= timings["pass"] > 0
    # observable in the recorder by ID, with the serve spans attached;
    # the trace completes in the handler's finally AFTER the response
    # flush, so poll — the response racing its own recording is the
    # known scrape-vs-finally beat, not a bug
    deadline = time.monotonic() + 5
    while True:
        _, body, _ = _get(base, "/debug/requests")
        if tid in {t["trace_id"] for t in json.loads(body)["recent"]}:
            break
        assert time.monotonic() < deadline, f"{tid} never recorded"
        time.sleep(0.02)
    _, body, _ = _get(base, f"/debug/requests/{tid}")
    names = [s["name"] for s in json.loads(body)["spans"]]
    assert "http.parse" in names
    assert "serve.queue" in names and "serve.pass" in names
    # a request WITHOUT an inbound ID gets one minted
    status, _, headers = _post(
        base, "/compute", b"value=5",
        {"Content-Type": "application/x-www-form-urlencoded"},
    )
    assert status == 200 and tracespan.sanitize_id(headers["X-Misaka-Trace"])
    # unknown trace IDs answer 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(base, "/debug/requests/nosuchtrace00000")
    assert e.value.code == 404


def test_perfetto_export_valid_with_coalesced_spans(server):
    m, base = server
    m.run()
    # concurrent small requests force the serve scheduler to coalesce
    ids = [f"cafe{i:04d}cafe{i:04d}" for i in range(8)]
    errors = []

    def one(tid):
        try:
            vals = np.arange(16, dtype=np.int32)
            status, body, _ = _post(
                base, "/compute_raw?spread=1", vals.astype("<i4").tobytes(),
                {"X-Misaka-Trace": tid},
            )
            assert status == 200
            np.testing.assert_array_equal(np.frombuffer(body, "<i4"), vals + 2)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=one, args=(tid,)) for tid in ids]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    # a trace is recorded in the handler's finally AFTER the response
    # bytes flush, so the last completions can land a beat after the
    # client sees its response — poll until every ID is in the export
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        _, body, headers = _get(base, "/debug/perfetto")
        doc = json.loads(body)  # MUST parse as trace-event JSON
        got = {
            ev.get("args", {}).get("trace_id")
            for ev in doc["traceEvents"] if ev.get("ph") == "X"
        }
        if set(ids) <= got:
            break
        time.sleep(0.02)
    assert headers["Content-Type"].startswith("application/json")
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    for ev in events:
        assert ev["ph"] in ("X", "M")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], (int, float))
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
    by_name = {}
    for ev in events:
        if ev["ph"] == "X" and ev.get("args", {}).get("trace_id") in ids:
            by_name.setdefault(ev["name"], set()).add(ev["args"]["trace_id"])
    # the coalesced concurrent requests all carry queue + pass spans
    assert len(by_name.get("serve.queue", ())) == len(ids)
    assert len(by_name.get("serve.pass", ())) == len(ids)
    # one "process" per tier: serve spans ride the serve tier's pid
    serve_pids = {
        ev["pid"] for ev in events
        if ev["ph"] == "X" and ev["name"].startswith("serve.")
    }
    assert serve_pids == {tracespan.TIER_PIDS["serve"]}


# --- hop 2: the frontend plane ----------------------------------------------


@pytest.fixture
def frontend(tmp_path):
    from misaka_tpu.runtime import frontends

    m = _master()
    engine_httpd = make_http_server(m, port=0)
    threading.Thread(target=engine_httpd.serve_forever, daemon=True).start()
    plane_path = str(tmp_path / "plane.sock")
    plane = frontends.start_compute_plane(m, plane_path)
    fe = frontends.make_frontend_server(
        0, f"http://127.0.0.1:{engine_httpd.server_address[1]}",
        plane_path, plane_conns=2,
    )
    threading.Thread(target=fe.serve_forever, daemon=True).start()
    try:
        yield m, fe.server_address[1], engine_httpd.server_address[1]
    finally:
        m.pause()
        fe.shutdown()
        plane.close()
        engine_httpd.shutdown()


def test_frontend_plane_trace_propagation(frontend):
    m, fe_port, engine_port = frontend
    m.run()
    tid = "fe000111fe000111"
    conn = http.client.HTTPConnection("127.0.0.1", fe_port, timeout=15)
    vals = np.arange(32, dtype=np.int32)
    conn.request(
        "POST", "/compute_raw?spread=1", vals.astype("<i4").tobytes(),
        {"X-Misaka-Trace": tid},
    )
    r = conn.getresponse()
    assert r.status == 200
    assert r.getheader("X-Misaka-Trace") == tid  # back to the client
    np.testing.assert_array_equal(np.frombuffer(r.read(), "<i4"), vals + 2)
    conn.close()
    # the worker-minted... here worker-RECEIVED ID reached the ENGINE's
    # recorder over the plane frame, with the frontend spans forwarded
    deadline = time.monotonic() + 5
    tr = None
    while time.monotonic() < deadline:
        tr = tracespan.RECORDER.get(tid)
        if tr is not None and any(
            s.name == "serve.pass" for s in tr.spans
        ):
            break
        time.sleep(0.02)
    assert tr is not None
    names = {s.name for s in tr.spans}
    assert {"frontend.coalesce", "plane.recv",
            "serve.queue", "serve.pass"} <= names
    tiers = {tracespan.tier_of(s.name) for s in tr.spans}
    assert {"frontend", "plane", "serve"} <= tiers
    # and a frontend request with NO inbound header still gets an ID,
    # minted at the worker, observable on the engine's HTTP surface
    conn = http.client.HTTPConnection("127.0.0.1", fe_port, timeout=15)
    conn.request("POST", "/compute", b"value=3")
    r = conn.getresponse()
    assert r.status == 200
    minted = r.getheader("X-Misaka-Trace")
    r.read()
    conn.close()
    assert tracespan.sanitize_id(minted)
    engine = http.client.HTTPConnection("127.0.0.1", engine_port, timeout=15)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        engine.request("GET", f"/debug/requests/{minted}")
        r = engine.getresponse()
        body = r.read()
        if r.status == 200:
            break
        time.sleep(0.02)
    assert r.status == 200, (minted, body)
    assert json.loads(body)["trace_id"] == minted
    engine.close()


# --- hop 3: loopback gRPC ---------------------------------------------------


@pytest.mark.slow
def test_grpc_metadata_propagation_loopback():
    from misaka_tpu.runtime.nodes import build_loopback_cluster

    master, close = build_loopback_cluster(
        {"misaka1": "program"}, {"misaka1": "IN ACC\nOUT ACC"}
    )
    httpd = make_http_server(master, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    tid = "d157d157d157d157"
    try:
        # /run broadcasts Program.Run to the peer inside the request scope
        status, _, headers = _post(
            base, "/run", b"", {"X-Misaka-Trace": tid}
        )
        assert status == 200 and headers["X-Misaka-Trace"] == tid
        # client side: the rpc.<Method> span landed in the recorded trace
        _, body, _ = _get(base, f"/debug/requests/{tid}")
        assert "rpc.Run" in {s["name"] for s in json.loads(body)["spans"]}
        # peer side: the metadata crossed the wire (server interceptor)
        received = [
            s for s in tracespan.tier_events()
            if s.name == "rpc.recv.Run"
            and (s.attrs or {}).get("trace_id") == tid
        ]
        assert received, "peer never saw the trace metadata"
        master.pause()
    finally:
        httpd.shutdown()
        close()


def test_grpc_metadata_on_direct_broadcast():
    """The fast twin of the loopback-HTTP test: a broadcast inside an
    explicitly begun trace carries metadata to an in-process peer."""
    from misaka_tpu.runtime.nodes import build_loopback_cluster

    master, close = build_loopback_cluster(
        {"misaka1": "program"}, {"misaka1": "IN ACC\nOUT ACC"}
    )
    tid = "ab12ab12ab12ab12"
    try:
        tr = tracespan.begin(tid, route="/run")
        master.run()
        tracespan.end(tr, status=200)
        assert "rpc.Run" in {s.name for s in tr.spans}
        assert any(
            s.name == "rpc.recv.Run"
            and (s.attrs or {}).get("trace_id") == tid
            for s in tracespan.tier_events()
        )
        master.pause()
    finally:
        close()


# --- satellites -------------------------------------------------------------


def test_jsonlog_carries_trace_id():
    from misaka_tpu.utils.jsonlog import JsonFormatter

    fmt = JsonFormatter()
    rec = logging.LogRecord("t", logging.INFO, __file__, 1, "hello", (), None)
    assert "trace_id" not in json.loads(fmt.format(rec))
    tr = tracespan.begin("0123456789abcdef")
    try:
        line = json.loads(fmt.format(rec))
        assert line["trace_id"] == "0123456789abcdef"
    finally:
        tracespan.end(tr)
    # out of scope again: no stale id
    assert "trace_id" not in json.loads(fmt.format(rec))


def test_client_parses_timings_and_error_trace_id(server):
    from misaka_tpu.client import MisakaClient, MisakaClientError

    m, base = server
    client = MisakaClient(base)
    # error BEFORE running: the raised message is grep-able server-side
    with pytest.raises(MisakaClientError) as e:
        client.compute(1)
    assert e.value.trace_id and f"[trace {e.value.trace_id}]" in str(e.value)
    m.run()
    result = client.compute(7)
    assert result == 9
    assert result.trace_id and "total" in result.timings
    out = client.compute_raw(np.arange(8, dtype=np.int32))
    np.testing.assert_array_equal(np.asarray(out), np.arange(8) + 2)
    assert out.trace_id
    assert {"queue", "pass", "total"} <= set(out.timings)
    out2 = client.compute_batch([1, 2, 3])
    assert out2.trace_id and out2.timings["total"] > 0
    client.close()


def test_overhead_guard_tracing_on_vs_off():
    """Tracing must be cheap enough to leave on: the full per-request
    begin/span/end path against the kill switch, generous bound (the
    bench A/B pins the real <=5% budget; this is the tripwire for an
    accidental O(expensive) on the hot path)."""
    m = _master()
    m.run()
    vals = np.arange(64, dtype=np.int32)

    def lap(n=150):
        t0 = time.perf_counter()
        for i in range(n):
            tr = tracespan.begin(route="/compute_raw")
            try:
                with tracespan.use(tr):
                    m.compute_coalesced(vals, return_array=True)
            finally:
                tracespan.end(tr, status=200)
        return time.perf_counter() - t0

    try:
        lap(20)  # warm both paths
        tracespan.configure({"MISAKA_TRACE_REQUESTS": "0"})
        off = lap()
        tracespan.configure({})
        on = lap()
        assert on <= off * 2.0 + 0.5, (on, off)
    finally:
        tracespan.configure({})
        m.pause()


def test_debug_requests_slowest_param(server):
    m, base = server
    m.run()
    _post(base, "/compute", b"value=1")
    _, body, _ = _get(base, "/debug/requests?slowest=1")
    doc = json.loads(body)
    assert "recent" not in doc and "slowest" in doc and doc["enabled"]
