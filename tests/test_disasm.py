"""Disassembler round-trip: lower(parse(disassemble(code))) is a fixed point.

The disassembler (misaka_tpu/tis/disasm.py) must invert lowering exactly —
every baseline network and a fuzzed corpus of random programs re-lower to
bit-identical tables, proving trace decoding / debugger listings never lie
about what the kernel executes.
"""

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.tis import disasm, isa
from misaka_tpu.tis.lower import lower_program, pad_programs
from tests.test_differential import build_random_network


def roundtrip(code, length, lane_names, stack_names):
    """disassemble -> parse+lower -> dense table."""
    text = disasm.disassemble_program(code, length, lane_names, stack_names)
    lane_ids = {n: i for i, n in enumerate(lane_names)}
    stack_ids = {n: i for i, n in enumerate(stack_names)}
    return lower_program(text, lane_ids, stack_ids)


@pytest.mark.parametrize("config", sorted(networks.BASELINE_CONFIGS))
def test_baseline_roundtrip(config):
    top = networks.BASELINE_CONFIGS[config]()
    lane_ids = top.lane_ids()
    stack_ids = top.stack_ids()
    lane_names = list(lane_ids)
    stack_names = list(stack_ids)
    lowered = [lower_program(top.programs[n], lane_ids, stack_ids) for n in lane_ids]
    code, lengths = pad_programs(lowered)
    for i, name in enumerate(lane_names):
        again = roundtrip(code[i], int(lengths[i]), lane_names, stack_names)
        assert again.length == int(lengths[i]), name
        np.testing.assert_array_equal(again.code, code[i, : again.length], err_msg=name)


@pytest.mark.parametrize("seed", range(40))
def test_fuzzed_roundtrip(seed):
    code, lengths, n_stacks, _, _ = build_random_network(seed)
    lane_names = [f"n{i}" for i in range(code.shape[0])]
    stack_names = [f"s{i}" for i in range(n_stacks)]
    for i in range(code.shape[0]):
        again = roundtrip(code[i], int(lengths[i]), lane_names, stack_names)
        np.testing.assert_array_equal(again.code, code[i, : again.length])


def test_default_names():
    """Positional node<i>/stack<i> names when no maps are given."""
    text = disasm.disassemble_program(
        np.array([[isa.OP_MOV_NET, isa.SRC_ACC, 0, 0, 1, 2, 0]], np.int32)
    )
    assert text == "MOV ACC, node1:R2"


def test_every_opcode_renders():
    """One line per opcode; all 18 semantic ops covered."""
    lane_names = ["a", "b"]
    stack_names = ["s"]
    program = "\n".join(
        [
            "NOP",
            "SWP",
            "SAV",
            "NEG",
            "MOV 7, ACC",
            "MOV ACC, b:R3",
            "ADD R0",
            "SUB -2",
            "HERE: JMP HERE",
            "JEZ HERE",
            "JNZ HERE",
            "JGZ HERE",
            "JLZ HERE",
            "JRO -1",
            "PUSH ACC, s",
            "POP s, NIL",
            "IN ACC",
            "OUT R1",
        ]
    )
    lane_ids = {n: i for i, n in enumerate(lane_names)}
    stack_ids = {n: i for i, n in enumerate(stack_names)}
    low = lower_program(program, lane_ids, stack_ids)
    ops = {int(row[isa.F_OP]) for row in low.code}
    assert ops == set(range(isa.NUM_OPS))
    again = roundtrip(low.code, low.length, lane_names, stack_names)
    np.testing.assert_array_equal(again.code, low.code)


def test_disassemble_network_keys():
    top = networks.add2()
    net = top.compile()
    texts = disasm.disassemble_network(
        net.code, net.prog_len, list(top.lane_ids()), list(top.stack_ids())
    )
    assert set(texts) == {"misaka1", "misaka2"}
    assert "PUSH ACC, misaka3" in texts["misaka2"]


def test_bad_table_raises():
    with pytest.raises(disasm.TISDisasmError):
        disasm.disassemble_program(np.array([[99, 0, 0, 0, 0, 0, 0]], np.int32))
    with pytest.raises(disasm.TISDisasmError):
        disasm.disassemble_program(
            np.array([[isa.OP_ADD, 42, 0, 0, 0, 0, 0]], np.int32)
        )
