"""Native flight recorder (ISSUE 15): in-C++ tick/dispenser event tracing
unified with the request-trace plane.

Pins: (1) the recorder is a pure OBSERVER — serving output is
bit-identical with it armed vs disarmed over the r16 differential corpus
(every engine rung, resident and stateless); (2) the per-thread rings are
BOUNDED — a snapshot never exceeds capacity and oldest-dropped records
count on misaka_native_trace_dropped_total; (3) one inbound
X-Misaka-Trace ID yields native worker spans in GET /debug/perfetto on a
live server (the >= 5-tier frontend-included drill is `make
native-trace-smoke`); (4) the derived dispenser/rung metrics and the
caller-inline lane surface.  docs/OBSERVABILITY.md "Native flight
recorder".
"""

import contextlib
import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.core import native_serve
from misaka_tpu.utils import metrics, tracespan
from tests.test_simd import (
    SMALL, assert_state_equal, run_schedule, topologies,
)

pytestmark = pytest.mark.skipif(
    not native_serve.available(),
    reason="native interpreter unavailable (no g++)",
)


@contextlib.contextmanager
def env(**kv):
    prev = {k: os.environ.get(k) for k in kv}
    for k, v in kv.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    # module-level arm flag follows the env like a fresh process would
    native_serve._TRACE_ON = native_serve.trace_enabled()
    try:
        yield
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        native_serve._TRACE_ON = native_serve.trace_enabled()


# --- 1. the recorder observes, never perturbs --------------------------------


@pytest.mark.parametrize("name", sorted(topologies()))
def test_recorder_on_off_bit_identity(name):
    """Full-state bit-identity (tick counts included) with the recorder
    armed vs MISAKA_NATIVE_TRACE=0 over the mixed serve/idle schedule —
    B=19 runs group units AND a scalar remainder."""
    net = topologies()[name].compile(batch=19)
    d_on, rows_on = run_schedule(net, None)
    with env(MISAKA_NATIVE_TRACE="0"):
        d_off, rows_off = run_schedule(net, None)
    assert_state_equal(d_on, d_off, f"{name}: recorder on vs off")
    for i, (ra, rb) in enumerate(zip(rows_on, rows_off)):
        np.testing.assert_array_equal(ra, rb, err_msg=f"{name} row {i}")


def test_recorder_on_off_bit_identity_ladder_and_stateless():
    """The same pin down the ladder (generic, scalar) and with residency
    disabled — the recorder must be invisible on every rung."""
    net = topologies()["diverge"].compile(batch=19)
    for mode in ("generic", "0"):
        d_on, rows_on = run_schedule(net, mode)
        with env(MISAKA_NATIVE_TRACE="0"):
            d_off, rows_off = run_schedule(net, mode)
        assert_state_equal(d_on, d_off, f"mode {mode}: recorder on vs off")
        for i, (ra, rb) in enumerate(zip(rows_on, rows_off)):
            np.testing.assert_array_equal(
                ra, rb, err_msg=f"mode {mode} row {i}"
            )
    with env(MISAKA_NATIVE_RESIDENT="0"):
        d_on, rows_on = run_schedule(net, None)
        with env(MISAKA_NATIVE_TRACE="0", MISAKA_NATIVE_RESIDENT="0"):
            d_off, rows_off = run_schedule(net, None)
    assert_state_equal(d_on, d_off, "stateless: recorder on vs off")
    for i, (ra, rb) in enumerate(zip(rows_on, rows_off)):
        np.testing.assert_array_equal(ra, rb, err_msg=f"stateless row {i}")


def test_recorder_on_off_bit_identity_specialized(tmp_path):
    """And through a per-program specialized build (switch-threaded
    ticks): recorder on vs off, both specialized."""
    from misaka_tpu.core import specialize

    net = topologies()["add2"].compile(batch=16)
    so = specialize.build(net, cache_dir=str(tmp_path))
    if so is None:
        pytest.skip("specialized build unavailable")
    d_on, rows_on = run_schedule(net, None, spec=so)
    with env(MISAKA_NATIVE_TRACE="0"):
        d_off, rows_off = run_schedule(net, None, spec=so)
    assert_state_equal(d_on, d_off, "specialized: recorder on vs off")
    for i, (ra, rb) in enumerate(zip(rows_on, rows_off)):
        np.testing.assert_array_equal(ra, rb, err_msg=f"spec row {i}")


# --- 2. ring bounds + the dropped counter ------------------------------------


def _pool(batch=16, threads=2, **envkv):
    net = networks.add2(**SMALL).compile(batch=batch)
    with env(**envkv):
        return native_serve.NativeServePool(net, chunk_steps=32,
                                            threads=threads), net


def _serve_rounds(pool, net, rounds, batch=16):
    state = net.init_state()
    vals = np.zeros((batch, net.in_cap), np.int32)
    vals[:, 0] = 7
    counts = np.ones((batch,), np.int32)
    for _ in range(rounds):
        state, _ = pool.serve(state, vals, counts)
    return state


def test_ring_bound_enforced_and_dropped_counted():
    """A ring snapshot NEVER exceeds MISAKA_NATIVE_TRACE_RING, the
    cursor keeps counting, and overwritten-oldest records land on
    misaka_native_trace_dropped_total (delta-checked through the real
    exposition)."""
    before = metrics.parse_text(metrics.render()).get(
        "misaka_native_trace_dropped_total", 0.0
    )
    pool, net = _pool(MISAKA_NATIVE_TRACE_RING="64")
    try:
        info = pool._pool.trace_info()
        assert info["rings"] == pool.threads + 1
        assert info["capacity"] == 64
        _serve_rounds(pool, net, 200)
        total_records = 0
        for ring in range(info["rings"]):
            recs, cursor, dropped = pool._pool.trace_read(ring)
            assert len(recs) <= 64, (ring, len(recs))
            assert cursor >= len(recs)
            assert dropped == max(0, cursor - 64)
            total_records += len(recs)
        assert total_records > 0
        assert pool._pool.trace_info()["dropped"] > 0  # 200 calls >> 64
        pool._pull_trace_stats(force=True)  # watermark init
        _serve_rounds(pool, net, 50)
        pool._pull_trace_stats(force=True)
        after = metrics.parse_text(metrics.render()).get(
            "misaka_native_trace_dropped_total", 0.0
        )
        assert after > before
    finally:
        pool.close()


def test_trace_set_runtime_toggle():
    """set_trace(False) stops emission on a built recorder (cursors
    freeze); re-arming resumes.  MISAKA_NATIVE_TRACE=0 at creation means
    there is nothing to arm."""
    pool, net = _pool()
    try:
        _serve_rounds(pool, net, 3)
        assert native_serve.set_trace(False)
        cursors = [pool._pool.trace_read(r)[1]
                   for r in range(pool.threads + 1)]
        _serve_rounds(pool, net, 5)
        assert cursors == [pool._pool.trace_read(r)[1]
                           for r in range(pool.threads + 1)]
        assert native_serve.set_trace(True)
        _serve_rounds(pool, net, 3)
        assert sum(pool._pool.trace_read(r)[1]
                   for r in range(pool.threads + 1)) > sum(cursors)
    finally:
        native_serve.set_trace(native_serve.trace_enabled())
        pool.close()
    pool2, net2 = _pool(MISAKA_NATIVE_TRACE="0")
    try:
        assert pool2._pool.trace_info()["rings"] == 0
        assert not pool2._pool.trace_set(True)
        _serve_rounds(pool2, net2, 2)  # emit-free serving still works
    finally:
        pool2.close()


# --- 3. surfaces: stats, payloads, the caller-inline lane --------------------


def test_stats_payload_and_caller_inline_lane():
    """trace_stats moves (serve calls, rung-tagged replicas, caller
    units on this 1-caller box), flight_payload decodes events, the
    dispenser metrics land in the exposition, and pool_counters carries
    the FIRST-CLASS caller-inline lane (work_ns = busy + caller-inline)."""
    pool, net = _pool(threads=2)
    try:
        _serve_rounds(pool, net, 20)
        s = pool._pool.trace_stats()
        assert s["serve_calls"] >= 20
        assert s["reps"], s  # rung-tagged unit aggregates moved
        assert all(r in ("scalar", "generic", "avx2", "spec-generic",
                         "spec-avx2") for r, _ in s["reps"])
        payload = native_serve.flight_payload()
        assert payload["enabled"] and payload["pools"]
        kinds = {
            ev["kind"]
            for p in payload["pools"]
            for ring in p["rings"]
            for ev in ring["events"]
        }
        assert "serve" in kinds and "unit" in kinds, kinds
        pool._pull_trace_stats(force=True)
        _serve_rounds(pool, net, 10)
        pool._pull_trace_stats(force=True)
        parsed = metrics.parse_text(metrics.render())
        assert any(k.startswith("misaka_native_units_replicas_total")
                   for k in parsed), "per-rung unit counter missing"
        assert any(
            k.startswith("misaka_native_dispenser_seconds_total")
            or k.startswith("misaka_native_caller_inline_units_total")
            for k in parsed
        ), "dispenser/caller-inline series missing"
        pc = native_serve.pool_counters()
        assert pc is not None
        assert pc["caller_inline_ns"] == pc["serial_ns"]
        assert pc["work_ns"] == pc["busy_ns"] + pc["caller_inline_ns"]
        assert pc["work_ns"] > 0
    finally:
        pool.close()


# --- 4. the unified timeline on a live server --------------------------------


def test_live_server_perfetto_has_native_spans_under_trace_id():
    """An inbound X-Misaka-Trace ID on a live server yields native
    flight-recorder spans under that ID in GET /debug/perfetto alongside
    the http/serve tiers, and /debug/native_trace attaches the same ID
    to its raw events."""
    from misaka_tpu.runtime.master import MasterNode, make_http_server

    tracespan.clear()
    master = MasterNode(
        networks.add2(in_cap=64, out_cap=64, stack_cap=16),
        chunk_steps=64, batch=16, engine="native",
    )
    httpd = make_http_server(master, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    tid = "f11687aaf11687aa"
    try:
        master.run()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        for _ in range(6):
            vals = np.arange(32, dtype=np.int32)
            conn.request(
                "POST", "/compute_raw?spread=1",
                vals.astype("<i4").tobytes(), {"X-Misaka-Trace": tid},
            )
            r = conn.getresponse()
            body = r.read()
            assert r.status == 200, body
            assert (np.frombuffer(body, "<i4") == vals + 2).all()

        def fetch(path):
            conn.request("GET", path)
            r = conn.getresponse()
            return json.loads(r.read())

        tiers, native_spans = set(), 0
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            doc = fetch("/debug/perfetto")
            tiers, native_spans = set(), 0
            for ev in doc["traceEvents"]:
                if ev.get("ph") != "X":
                    continue
                if ev.get("args", {}).get("trace_id") == tid:
                    tiers.add(tracespan.tier_of(ev["name"]))
                    if ev["name"].startswith("native."):
                        native_spans += 1
            if native_spans and len(tiers) >= 3:
                break
            time.sleep(0.2)
        assert native_spans > 0, "no native worker spans under the ID"
        assert {"http", "serve", "native"} <= tiers, tiers
        nt = fetch("/debug/native_trace")
        dump_ids = {
            i
            for p in nt["pools"]
            for ring in p["rings"]
            for ev in ring["events"]
            for i in ev.get("trace_ids", ())
        }
        assert tid in dump_ids, sorted(dump_ids)[:5]
        conn.close()
    finally:
        master.pause()
        httpd.shutdown()
        tracespan.clear()
