"""Lifecycle guards: servers must not outlive their operator (VERDICT r3 #1).

These spawn the real `python -m misaka_tpu.runtime.app` entrypoint (CPU
platform) and verify the three guard paths in runtime/lifecycle.py: TTL
deadline, orphan watchdog, and SIGTERM.  A leaked server wedges the
single-client TPU relay, so this is product-surface behavior, not test
hygiene.
"""

import pytest

pytestmark = pytest.mark.slow  # orphan/TTL wall-clock guards — `make test-all` lane

import json
import os
import signal
import subprocess
import sys
import time

SOLO = {"solo": {"type": "program"}}
PROGS = {"solo": "IN ACC\nADD 1\nOUT ACC\n"}


def _env(**extra):
    env = {k: v for k, v in os.environ.items() if not k.startswith("JAX")}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        JAX_PLATFORMS="cpu",
        NODE_INFO=json.dumps(SOLO),
        MISAKA_PROGRAMS=json.dumps(PROGS),
        MISAKA_PORT="0",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    env.update(extra)
    return env


def _spawn(**extra):
    return subprocess.Popen(
        [sys.executable, "-m", "misaka_tpu.runtime.app"],
        env=_env(**extra),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )


def _wait_gone(proc_or_pid, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if isinstance(proc_or_pid, subprocess.Popen):
            if proc_or_pid.poll() is not None:
                return True
        else:
            try:
                os.kill(proc_or_pid, 0)
            except OSError:
                return True
        time.sleep(0.25)
    return False


def test_ttl_deadline_exits():
    proc = _spawn(MISAKA_TTL_S="2")
    try:
        assert _wait_gone(proc), "server ignored MISAKA_TTL_S deadline"
        assert proc.returncode == 0
    finally:
        if proc.poll() is None:
            proc.kill()


def test_sigterm_exits_clean():
    proc = _spawn()
    try:
        time.sleep(1.0)  # let it boot far enough to install handlers
        # handlers are installed before the HTTP server starts; SIGTERM any
        # time after boot must exit 0 (lifecycle.py routes it through stop())
        deadline = time.monotonic() + 60
        while proc.poll() is None and time.monotonic() < deadline:
            proc.send_signal(signal.SIGTERM)
            time.sleep(0.5)
        assert proc.poll() is not None, "server survived SIGTERM"
        assert proc.returncode == 0
    finally:
        if proc.poll() is None:
            proc.kill()


def test_orphan_watchdog_exits():
    """A server backgrounded from a dying shell must die with it."""
    launcher = (
        "import subprocess, sys, os, time\n"
        "p = subprocess.Popen([sys.executable, '-m', 'misaka_tpu.runtime.app'],"
        " stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)\n"
        "print(p.pid, flush=True)\n"
        # a real shell outlives interpreter startup; the guard's contract
        # covers parents that die any time after the package import
        "time.sleep(1.0)\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", launcher],
        env=_env(),
        capture_output=True,
        text=True,
        timeout=60,
    )
    pid = int(out.stdout.strip())
    try:
        assert _wait_gone(pid), f"orphaned server pid {pid} kept running"
    finally:
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass


def test_orphan_ok_optout():
    """MISAKA_ORPHAN_OK=1 keeps a deliberately daemonized server alive."""
    launcher = (
        "import subprocess, sys, os\n"
        "p = subprocess.Popen([sys.executable, '-m', 'misaka_tpu.runtime.app'],"
        " stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)\n"
        "print(p.pid, flush=True)\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", launcher],
        env=_env(MISAKA_ORPHAN_OK="1", MISAKA_TTL_S="30"),
        capture_output=True,
        text=True,
        timeout=60,
    )
    pid = int(out.stdout.strip())
    try:
        # survives well past several watchdog polls
        assert not _wait_gone(pid, timeout=8.0), "daemonized server died early"
    finally:
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            pass
        _wait_gone(pid, timeout=30.0)
