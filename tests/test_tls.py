"""TLS loopback for the per-process transport (the reference's `make cert` path).

The reference encrypts every node-to-node RPC with a self-signed service
cert (program.go:52-55, :98-101).  These tests generate a throwaway cert
with a localhost SAN, serve a stack node and a program node over TLS, and
prove (a) encrypted round-trips work end-to-end and (b) a client without
the CA is rejected.
"""

import shutil
import subprocess

import pytest

grpc = pytest.importorskip("grpc")

from misaka_tpu.runtime.nodes import ProgramNodeProcess, StackNodeProcess
from misaka_tpu.transport.rpc import ProgramClient, StackClient

pytestmark = pytest.mark.skipif(
    shutil.which("openssl") is None, reason="openssl unavailable"
)


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    cert, key = str(d / "service.pem"), str(d / "service.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "ec",
            "-pkeyopt", "ec_paramgen_curve:prime256v1", "-nodes",
            "-keyout", key, "-out", cert, "-days", "1",
            "-subj", "/CN=localhost",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True,
        capture_output=True,
    )
    return cert, key


def test_stack_tls_roundtrip(certs):
    cert, key = certs
    node = StackNodeProcess(cert_file=cert, key_file=key, grpc_port=0, host="127.0.0.1")
    port = node.start()
    try:
        with StackClient(f"localhost:{port}", cert_file=cert) as client:
            client.run(timeout=5)
            client.push(41, timeout=5)
            client.push(42, timeout=5)
            assert client.pop(timeout=5) == 42
            assert client.pop(timeout=5) == 41
    finally:
        node.close()


def test_program_tls_load_and_send(certs):
    cert, key = certs
    node = ProgramNodeProcess(
        master_uri="nowhere", cert_file=cert, key_file=key, grpc_port=0, host="127.0.0.1"
    )
    port = node.start()
    try:
        with ProgramClient(f"localhost:{port}", cert_file=cert) as client:
            client.load("MOV R0, ACC", timeout=5)
            client.run(timeout=5)
            client.send(77, 0, timeout=5)
            deadline = 50
            import time

            while node.acc != 77 and deadline:
                time.sleep(0.1)
                deadline -= 1
            assert node.acc == 77
    finally:
        node.close()


def test_plaintext_client_rejected_by_tls_server(certs):
    cert, key = certs
    node = StackNodeProcess(cert_file=cert, key_file=key, grpc_port=0, host="127.0.0.1")
    port = node.start()
    try:
        with StackClient(f"localhost:{port}") as client:  # no CA: insecure channel
            with pytest.raises(grpc.RpcError):
                client.push(1, timeout=3)
    finally:
        node.close()


def test_wrong_ca_rejected(certs, tmp_path):
    cert, key = certs
    other_cert, other_key = str(tmp_path / "o.pem"), str(tmp_path / "o.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "ec",
            "-pkeyopt", "ec_paramgen_curve:prime256v1", "-nodes",
            "-keyout", other_key, "-out", other_cert, "-days", "1",
            "-subj", "/CN=localhost",
            "-addext", "subjectAltName=DNS:localhost",
        ],
        check=True,
        capture_output=True,
    )
    node = StackNodeProcess(cert_file=cert, key_file=key, grpc_port=0, host="127.0.0.1")
    port = node.start()
    try:
        with StackClient(f"localhost:{port}", cert_file=other_cert) as client:
            with pytest.raises(grpc.RpcError):
                client.push(1, timeout=3)
    finally:
        node.close()
