"""Resident-state native serving (r17): residency vs lifecycle.

The native engines keep batch state IN C++ between serve calls on the
trusted-identity path; these tests pin the contract's two halves:

  * bit-identity — the differential corpus replayed through a resident
    pool matches the stateless (MISAKA_NATIVE_RESIDENT=0) pool
    bit-for-bit, including under the resident_fallback chaos point
    flapping mid-stream;
  * lifecycle laziness — checkpoint, snapshot/restore, /load, reset,
    autogrow-style status reads, and registry eviction each force a
    lazy export whose content equals the eager path's, and a lifecycle
    replacement is never clobbered by a superseded resident copy.

(The fleet roll rides save_checkpoint/snapshot — the same export hook —
and its bit-identity drill lives in tests/test_fleet.py's slow lane.)
"""

import os

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.core import native_serve
from misaka_tpu.runtime.master import MasterNode
from misaka_tpu.utils import faults

pytestmark = pytest.mark.skipif(
    not native_serve.available(), reason="native interpreter unavailable (no g++)"
)


def make_pool(net, resident: bool, **kw):
    prev = os.environ.get("MISAKA_NATIVE_RESIDENT")
    os.environ["MISAKA_NATIVE_RESIDENT"] = "1" if resident else "0"
    try:
        return native_serve.NativeServePool(net, **kw)
    finally:
        if prev is None:
            os.environ.pop("MISAKA_NATIVE_RESIDENT", None)
        else:
            os.environ["MISAKA_NATIVE_RESIDENT"] = prev


def state_dict(state):
    return {f: np.asarray(getattr(state, f)) for f in state._fields}


def run_schedule(net, resident: bool, rounds=10, seed=3, fallback_every=None):
    """A randomized serve/idle schedule with partial-fill active lists;
    returns (final state dict, [packed rows]).  `fallback_every` arms the
    resident_fallback chaos point on every Nth round — the mid-stream
    degrade whose outputs must stay bit-identical."""
    B = net.batch
    pool = make_pool(net, resident, chunk_steps=48)
    rng = np.random.default_rng(seed)
    state = net.init_state()
    rows = []
    try:
        for it in range(rounds):
            if fallback_every:
                faults.configure(
                    "resident_fallback" if it % fallback_every == 0 else ""
                )
            if it % 4 == 3:
                state, ctrs = pool.idle(state, 24)
                state = pool.export_resident(state) or state
                rows.append(np.asarray(ctrs).copy())
                continue
            free = net.in_cap - (
                np.asarray(state.in_wr) - np.asarray(state.in_rd)
            )
            counts = np.minimum(
                rng.integers(0, net.in_cap + 1, size=B), free
            ).astype(np.int32)
            vals = rng.integers(
                -10_000, 10_000, size=(B, net.in_cap)
            ).astype(np.int32)
            active = None
            if it % 3 == 1:  # partial fill: half the replicas
                active = np.flatnonzero(np.arange(B) % 2 == 0)
                mask = np.zeros((B,), bool)
                mask[active] = True
                counts[~mask] = 0
            state, packed = pool.serve(state, vals, counts, active=active)
            state = pool.export_resident(state) or state
            packed = np.asarray(packed).copy()
            if active is not None:
                skipped = np.ones((B,), bool)
                skipped[active] = False
                packed[skipped, 4:] = 0  # np.empty residue by contract
            rows.append(packed)
        return state_dict(state), rows
    finally:
        faults.configure("")
        pool.close()


@pytest.mark.parametrize("batch", [6, 24])  # scalar-resident and group paths
def test_resident_bit_identical_to_stateless(batch):
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile(batch=batch)
    d_off, rows_off = run_schedule(net, resident=False)
    d_on, rows_on = run_schedule(net, resident=True)
    assert len(rows_off) == len(rows_on)
    for i, (a, b) in enumerate(zip(rows_off, rows_on)):
        np.testing.assert_array_equal(a, b, err_msg=f"round {i}")
    for f in d_off:
        np.testing.assert_array_equal(d_off[f], d_on[f], err_msg=f)


def test_resident_fallback_chaos_bit_identical():
    """The resident_fallback chaos point flapping mid-stream: every
    affected call exports coherently and serves stateless — outputs and
    final state stay bit-identical to both pure modes."""
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile(batch=16)
    d_ref, rows_ref = run_schedule(net, resident=False)
    d_chaos, rows_chaos = run_schedule(net, resident=True, fallback_every=2)
    for i, (a, b) in enumerate(zip(rows_ref, rows_chaos)):
        np.testing.assert_array_equal(a, b, err_msg=f"round {i}")
    for f in d_ref:
        np.testing.assert_array_equal(d_ref[f], d_chaos[f], err_msg=f)


def test_resident_counters_and_progress():
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile(batch=16)
    pool = make_pool(net, True, chunk_steps=48)
    try:
        hit0 = native_serve._res_events["hit"]
        miss0 = native_serve._res_events["miss"]
        state = net.init_state()
        counts = np.zeros((16,), np.int32)
        counts[3] = 2
        vals = np.zeros((16, 8), np.int32)
        vals[3, :2] = 7
        state, _ = pool.serve(state, vals, counts)  # miss: arms residency
        assert native_serve._res_events["miss"] == miss0 + 1
        prog = pool.consume_progress()
        assert prog is not None and prog.shape == (16,)
        assert prog[3] == 1  # the fed replica retired instructions
        # a partial-fill resident pass: only the active replica ticks
        active = np.array([3], np.int32)
        state, _ = pool.serve(state, vals, counts, active=active)
        assert native_serve._res_events["hit"] == hit0 + 1
        prog = pool.consume_progress()
        assert prog[3] == 1 and int(prog.sum()) == 1
    finally:
        pool.close()


def test_master_lifecycle_forces_lazy_export(tmp_path):
    """checkpoint / snapshot+restore / status through a RESIDENT native
    master: every read sees the live (exported) state, a restore round
    trip is bit-identical, and serving stays correct throughout."""
    master = MasterNode(
        networks.add2(in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=32, batch=8, engine="native",
    )
    try:
        master.run()
        for v in range(6):
            assert master.compute(v, timeout=30) == v + 2
        # /status reads state content (ticks, ring depths) — the export hook
        st = master.status()
        assert st["tick"] > 0
        for v in (100, 101):
            assert master.compute(v, timeout=30) == v + 2
        # pause: a RUNNING network keeps ticking, so bit-level comparisons
        # happen on a quiesced engine (the export path is the same)
        master.pause()
        snap = master.snapshot()  # forces the lazy export
        assert int(np.asarray(snap.tick).flat[0]) > 0
        master.restore(snap)
        snap2 = master.snapshot()
        for f in snap._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(snap, f)),
                np.asarray(getattr(snap2, f)), err_msg=f,
            )
        # checkpoint rides the same export; its arrays ARE the live state
        path = str(tmp_path / "resident.npz")
        master.save_checkpoint(path)
        arrays = dict(np.load(path))
        for f in snap._fields:
            np.testing.assert_array_equal(
                arrays[f], np.asarray(getattr(snap, f)), err_msg=f,
            )
        master.load_checkpoint(path)
        master.run()
        for v in (7, 8, 9):
            assert master.compute(v, timeout=30) == v + 2
    finally:
        master.close()


def test_master_reset_and_load_supersede_resident(tmp_path):
    """reset/load REPLACE the state: the superseded resident copy must
    never leak back through a later export (the anchor gate)."""
    master = MasterNode(
        networks.add2(in_cap=8, out_cap=8, stack_cap=8),
        chunk_steps=32, batch=8, engine="native",
    )
    try:
        master.run()
        for v in range(4):
            assert master.compute(v, timeout=30) == v + 2
        master.reset()
        snap = master.snapshot()  # must be the RESET state, not resident
        assert int(np.asarray(snap.tick).flat[0]) == 0
        assert not bool(np.asarray(snap.port_full).any())
        master.run()
        assert master.compute(5, timeout=30) == 7
    finally:
        master.close()


def test_registry_eviction_revives_resident_state():
    """Eviction drains + checkpoints a RESIDENT native engine (the lazy
    export under capacity pressure) and revival restores the state: the
    delay line continues where it left off — fresh state would answer 0.
    The checkpoint's arrays must equal the resident engine's live state
    at drain time (the export, not a stale snapshot)."""
    from misaka_tpu.runtime.master import verify_checkpoint
    from misaka_tpu.runtime.registry import ProgramRegistry

    caps = dict(stack_cap=16, in_cap=16, out_cap=16)
    delay = "IN ACC\nSWP\nOUT ACC\nSWP\nSAV\n"
    reg = ProgramRegistry(
        None, batch=None, engine="native", chunk_steps=32, caps=caps,
        max_active=4,
    )
    top = networks.add2(**caps)
    master = MasterNode(top, chunk_steps=32, batch=None, engine="native")
    reg.seed("default", master, top)
    master.run()
    try:
        v = reg.publish("delay", tis=delay)["version"]
        with reg.lease("delay") as m:
            assert m.compute_coalesced([5]) == [0]
            assert m.compute_coalesced([6]) == [5]
        assert reg.deactivate("delay")
        ckpt = reg._state_path("delay", v)
        verify_checkpoint(ckpt)
        with np.load(ckpt) as data:
            # the resident engine's BAK (the remembered value) reached
            # the checkpoint — the lazy export actually happened
            assert 6 in np.asarray(data["bak"]).reshape(-1)
        with reg.lease("delay") as m:
            assert m.compute_coalesced([7]) == [6]
    finally:
        master.pause()
        reg.close()


def test_unbatched_native_serve_resident_counters():
    """NativeServe (batch=None) rides the same identity discipline: the
    second chunk on the returned anchor is a resident hit."""
    net = networks.add2(in_cap=8, out_cap=8, stack_cap=8).compile()
    ns = native_serve.NativeServe(net)
    hit0 = native_serve._res_events["hit"]
    state = net.init_state()
    vals = np.zeros((net.in_cap,), np.int32)
    vals[0] = 41
    state, packed = ns.serve_chunk(state, vals, 1, 64)
    rd, wr = int(packed[2]), int(packed[3])
    assert wr - rd == 1 and int(packed[4:][rd % net.out_cap]) == 43
    vals[0] = 1
    state, packed = ns.serve_chunk(state, vals, 1, 64)
    assert native_serve._res_events["hit"] == hit0 + 1
    st = ns.export_resident(state)
    assert st is not None and int(np.asarray(st.tick)) > 0
    ns.close()
