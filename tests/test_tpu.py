"""Real-hardware lane: the Mosaic-COMPILED fused kernel vs the scan engine.

Every other fused test runs the Pallas kernel in interpret mode (CPU CI), so
a Mosaic-specific miscompile would surface only as a bench parity failure
with nothing minimized to bisect (VERDICT r2 weak #6).  This file runs the
same parity checks through the actual TPU compiler, one config per storage
mode (register-resident small caps, chunked VMEM-ref big caps).

Run: `make test-tpu`, i.e. `MISAKA_TPU_TESTS=1 pytest -m tpu tests/`.
Skipped entirely in the normal CPU suite (conftest.py forces cpu there).
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.tpu

if not os.environ.get("MISAKA_TPU_TESTS"):
    pytest.skip(
        "TPU lane disabled (set MISAKA_TPU_TESTS=1)", allow_module_level=True
    )

import jax  # noqa: E402  (after the env gate on purpose)

if not jax.devices() or jax.devices()[0].platform != "tpu":
    pytest.skip("no TPU attached", allow_module_level=True)

from misaka_tpu import networks  # noqa: E402
from misaka_tpu.runtime.topology import Topology  # noqa: E402


def assert_states_equal(a, b):
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)),
            np.asarray(getattr(b, name)),
            err_msg=f"state field '{name}' diverged on hardware",
        )


def run_both_compiled(top, batch, steps, n_inputs, seed=0):
    net = top.compile(batch=batch)
    rng = np.random.default_rng(seed)
    vals = rng.integers(-1000, 1000, size=(batch, n_inputs)).astype(np.int32)

    def prep(state):
        return state._replace(
            in_buf=state.in_buf.at[:, :n_inputs].set(vals),
            in_wr=state.in_wr + n_inputs,
        )

    ref = net.run(prep(net.init_state()), steps)
    fused = net.fused_runner(steps, block_batch=128)  # interpret=False: Mosaic
    out = fused(prep(net.init_state()))
    return ref, out


def test_mosaic_regs_mode_parity():
    # caps <= UNROLL_CAP: all storage lives in the fori_loop carry
    top = networks.add2(in_cap=8, out_cap=8, stack_cap=8)
    ref, out = run_both_compiled(top, batch=128, steps=60, n_inputs=4)
    assert_states_equal(ref, out)
    assert int(np.asarray(out.out_wr).min()) > 0


def test_mosaic_chunked_mode_parity():
    # caps > UNROLL_CAP: stacks/rings stay in VMEM refs, chunked
    # dynamic-slice access — the storage mode engine-default (1024) serving
    # uses; exercised here at 128 to keep hardware compile time sane
    top = networks.mesh8(in_cap=128, out_cap=128, stack_cap=128)
    ref, out = run_both_compiled(top, batch=128, steps=120, n_inputs=8)
    assert_states_equal(ref, out)
    assert int(np.asarray(out.out_wr).min()) > 0


def test_compact_kernel_hw_parity():
    """The compact scatter-election kernel (core/routing.py) compiled for
    real TPU vs the dense kernel on the same wide pipeline — scatters lower
    differently under Mosaic/XLA-TPU than in the CPU suite, and the compact
    kernel is the auto-selected engine at >= 32 lanes (kept at a
    measured-safe batch: wide dense/scatter configs at large batch have
    wedged this chip, see bench.py's caps)."""
    top = networks.pipeline(64, in_cap=8, out_cap=8, stack_cap=8)
    net = top.compile(batch=64)
    rng = np.random.default_rng(3)
    vals = rng.integers(-1000, 1000, size=(64, 4)).astype(np.int32)

    def prep(state):
        return state._replace(
            in_buf=state.in_buf.at[:, :4].set(vals), in_wr=state.in_wr + 4
        )

    dense = net.run(prep(net.init_state()), 250, engine="dense")
    compact = net.run(prep(net.init_state()), 250, engine="compact")
    assert_states_equal(dense, compact)
    # the scatter-free chained election through the TPU compiler too — the
    # r5 A/B candidate against scatter serialization must be parity-pinned
    # on hardware before its lane numbers mean anything
    chained = net.run(prep(net.init_state()), 250, engine="chained")
    assert_states_equal(dense, chained)
    # the pipeline completed: every instance emitted all 4 values, +64 each
    np.testing.assert_array_equal(np.asarray(compact.out_wr), 4)
    np.testing.assert_array_equal(
        np.asarray(compact.out_buf)[:, :4], vals + 64
    )


def test_mosaic_deep_stack_parity():
    # stack depth crosses the 64-slot chunk boundary under Mosaic
    top = Topology(
        node_info={"p": "program", "st": "stack"},
        programs={"p": "IN ACC\nPUSH ACC, st\n"},
        in_cap=104, out_cap=8, stack_cap=128,
    )
    ref, out = run_both_compiled(top, batch=128, steps=310, n_inputs=100)
    assert_states_equal(ref, out)
    np.testing.assert_array_equal(np.asarray(out.stack_top)[:, 0], 100)


def test_mosaic_elide_dead_hi_parity():
    """The hi-plane elision (r5 VPU-headroom cut) through the ACTUAL Mosaic
    compiler: wire/output planes bit-identical to the scan engine on add2
    (fully hi-dead) and sorter (fully hi-live, so the flag must be a
    no-op there).  Interpret-mode parity is pinned in test_fused.py; this
    guards against Mosaic-specific miscompiles of the elided kernel the
    capture A/B would otherwise hit first."""
    for name in ("add2", "sorter"):
        top = networks.BASELINE_CONFIGS[name](in_cap=8, out_cap=8, stack_cap=8)
        net = top.compile(batch=128)
        rng = np.random.default_rng(11)
        vals = rng.integers(-1000, 1000, size=(128, 4)).astype(np.int32)

        def prep(state):
            return state._replace(
                in_buf=state.in_buf.at[:, :4].set(vals),
                in_wr=state.in_wr + 4,
            )

        ref = net.run(prep(net.init_state()), 60)
        fused = net.fused_runner(60, block_batch=128, elide_dead_hi=True)
        out = fused(prep(net.init_state()))
        for field in ref._fields:
            if field in ("acc_hi", "bak_hi") and name == "add2":
                continue  # unspecified on hi-dead lanes by contract
            np.testing.assert_array_equal(
                np.asarray(getattr(ref, field)),
                np.asarray(getattr(out, field)),
                err_msg=f"{name}: field '{field}' diverged (elide_dead_hi)",
            )
        assert int(np.asarray(out.out_wr).min()) > 0


def test_mosaic_block_walk_wide_net():
    """The shared block-size walk on hardware: at 64 lanes (1,102 carry
    rows) the VMEM budget rejects every >=1024 block and Mosaic tiling
    rejects every partial <1024 block (the -2 block dim must be a multiple
    of 8 sublanes unless the block spans the batch — enforced eagerly in
    fused.py so the walk can skip, not die at compile).  The only viable
    wide fused config is single-block with batch <= 512 — the exact path
    the lane matrix (64, fused) config takes on TPU."""
    top = networks.pipeline(64, in_cap=8, out_cap=8, stack_cap=8)
    # batch 2048: 2048/1024 pass the divisibility pre-check and are
    # REJECTED by the VMEM budget (9/4.5 MB carry); 512/256/128 are
    # tileable on CPU-interpret but NOT on hardware (4/2/1 sublane-rows) —
    # the walk must exhaust its candidates with a budget/tiling error, not
    # return a block that faults at compile (the pre-fix behavior).
    with pytest.raises(ValueError, match="Mosaic-tileable|budget exceeded"):
        top.compile(batch=2048).fused_runner_walk(
            64, candidates=(2048, 1024, 512, 256, 128)
        )
    net = top.compile(batch=512)
    runner, bb = net.fused_runner_walk(
        64, candidates=(2048, 1024, 512, 256, 128)
    )
    assert bb == 512  # == batch: whole-axis block, tiling-exempt, 2.3 MB
    rng = np.random.default_rng(7)
    vals = rng.integers(-1000, 1000, size=(512, 4)).astype(np.int32)
    state = net.init_state()
    state = state._replace(
        in_buf=state.in_buf.at[:, :4].set(vals), in_wr=state.in_wr + 4
    )
    for _ in range(5):  # 5 x 64 = 320 ticks: fill + drain the 64 stages
        state = runner(state)
    np.testing.assert_array_equal(np.asarray(state.out_buf)[:, :4], vals + 64)


def test_chained_wide_default_serves_on_hardware(monkeypatch):
    """The r5 default flip end-to-end on the chip: a wide (40-lane) net's
    auto path must select the CHAINED election on TPU (wide_engine(),
    1.40-1.44x the scatter kernel measured at 64/256 lanes,
    artifacts/r05/lane_followup.json) and produce reference-correct
    results through BOTH run(engine=None) and the serve_chunk surface the
    MasterNode drives (program.go:80-92 semantics per lane)."""
    from misaka_tpu.core.engine import compact_auto_lanes, wide_engine

    # assert the platform DEFAULTS: clear the A/B override knobs a probe
    # shell may still export (test_scale.py precedent)
    monkeypatch.delenv("MISAKA_WIDE_ENGINE", raising=False)
    monkeypatch.delenv("MISAKA_COMPACT_AUTO_LANES", raising=False)

    assert wide_engine() == "chained"  # the TPU platform default
    n = 40
    top = networks.pipeline(n, in_cap=8, out_cap=8, stack_cap=8)
    net = top.compile()  # single instance: the serving shape
    assert net.num_lanes >= compact_auto_lanes()
    assert net.step_fn() is net._chained_step()

    vals = np.array([7, -3, 250, -999], dtype=np.int32)
    state = net.init_state()
    ticks = 3 * n + 3 * len(vals) + 64
    state, packed = net.serve_chunk(state, vals, len(vals), ticks)
    packed = np.asarray(packed)
    out_rd, out_wr = int(packed[2]), int(packed[3])
    assert out_wr - out_rd == len(vals)
    got = packed[4:][np.arange(out_rd, out_wr) % net.out_cap]
    np.testing.assert_array_equal(got, vals + n)

    # and the batched auto path (run engine=None -> chained on TPU)
    netb = top.compile(batch=128)
    b_vals = np.tile(vals, (128, 1))
    sb = netb.init_state()
    sb = sb._replace(
        in_buf=sb.in_buf.at[:, :4].set(b_vals), in_wr=sb.in_wr + 4
    )
    sb = netb.run(sb, ticks)  # engine=None: the flipped default
    np.testing.assert_array_equal(np.asarray(sb.out_buf)[:, :4], b_vals + n)
