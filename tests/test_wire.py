"""The zero-copy wire (ISSUE 12 layer 3): the headered binary client
protocol on /compute_raw (utils/wire.py, negotiated via
Content-Type/Accept, the client default) and the shared-memory compute
plane (MISAKA_PLANE_SHM=1 — payloads ride a per-connection segment, the
socket keeps the frame headers, handshake, drain, and probe semantics).
"""

import http.client
import os
import threading

import numpy as np
import pytest

from misaka_tpu import networks
from misaka_tpu.client import MisakaClient
from misaka_tpu.runtime import frontends
from misaka_tpu.runtime.master import MasterNode, make_http_server
from misaka_tpu.utils import wire

SMALL = dict(stack_cap=16, in_cap=16, out_cap=16)


# --- the protocol itself ----------------------------------------------------


def test_pack_unpack_roundtrip():
    payload = np.arange(-8, 8, dtype="<i4").tobytes()
    framed = wire.pack(payload)
    assert len(framed) == wire.HEADER_LEN + len(payload)
    assert wire.unpack(framed) == payload
    assert wire.unpack(wire.pack(b"")) == b""


@pytest.mark.parametrize("body,msg", [
    (b"", "shorter than"),
    (b"\x00" * 12, "bad magic"),
    (wire.header(5) + b"\x00" * 8, "promises 5 values"),
    (b"MSK1" + b"\x63\x00\x00\x00" + b"\x00\x00\x00\x00", "version"),
    (wire.pack(np.arange(3, dtype="<i4").tobytes())[:-1], "payload bytes"),
])
def test_unpack_rejects_malformed(body, msg):
    with pytest.raises(wire.WireError, match=msg):
        wire.unpack(body)


def test_pack_rejects_ragged_payload():
    with pytest.raises(wire.WireError):
        wire.pack(b"\x01\x02\x03")


def test_negotiation_helpers():
    assert wire.is_binary(wire.CONTENT_TYPE)
    assert wire.is_binary(wire.CONTENT_TYPE + "; charset=binary")
    assert not wire.is_binary("application/octet-stream")
    assert not wire.is_binary(None)
    assert wire.accepts_binary(f"text/plain, {wire.CONTENT_TYPE}")
    assert not wire.accepts_binary("*/*")
    assert not wire.accepts_binary(None)


# --- the HTTP surface -------------------------------------------------------


@pytest.fixture()
def server():
    top = networks.add2(**SMALL)
    master = MasterNode(top, chunk_steps=32, batch=2, engine="scan")
    httpd = make_http_server(master, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    master.run()
    try:
        yield master, httpd.server_address[1]
    finally:
        master.pause()
        httpd.shutdown()
        master.close()


def _post(port, body, headers=None, path="/compute_raw?spread=1"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("POST", path, body, headers or {})
        resp = conn.getresponse()
        return resp.status, resp.getheader("Content-Type"), resp.read()
    finally:
        conn.close()


def test_binary_request_and_response(server):
    _, port = server
    vals = np.arange(-5, 6, dtype="<i4")
    status, ctype, raw = _post(
        port, wire.pack(vals.tobytes()),
        {"Content-Type": wire.CONTENT_TYPE, "Accept": wire.CONTENT_TYPE},
    )
    assert status == 200 and ctype == wire.CONTENT_TYPE
    out = np.frombuffer(wire.unpack(raw), "<i4")
    np.testing.assert_array_equal(out, vals + 2)


def test_binary_request_legacy_response(server):
    # Content-Type negotiates the request form; without the Accept the
    # response stays the legacy headerless raw bytes
    _, port = server
    vals = np.arange(4, dtype="<i4")
    status, ctype, raw = _post(
        port, wire.pack(vals.tobytes()), {"Content-Type": wire.CONTENT_TYPE}
    )
    assert status == 200 and ctype == "application/octet-stream"
    np.testing.assert_array_equal(np.frombuffer(raw, "<i4"), vals + 2)


def test_legacy_raw_unchanged(server):
    _, port = server
    vals = np.arange(4, dtype="<i4")
    status, ctype, raw = _post(port, vals.tobytes())
    assert status == 200 and ctype == "application/octet-stream"
    np.testing.assert_array_equal(np.frombuffer(raw, "<i4"), vals + 2)


def test_malformed_binary_body_is_typed_400(server):
    _, port = server
    status, _, body = _post(
        port, wire.header(99) + b"\x00" * 4,
        {"Content-Type": wire.CONTENT_TYPE},
    )
    assert status == 400 and b"bad binary body" in body
    # and the server keeps serving (the error consumed the body)
    vals = np.arange(3, dtype="<i4")
    status, _, raw = _post(port, vals.tobytes())
    assert status == 200
    np.testing.assert_array_equal(np.frombuffer(raw, "<i4"), vals + 2)


def test_client_negotiates_binary_by_default(server):
    _, port = server
    c = MisakaClient(f"http://127.0.0.1:{port}", timeout=30)
    try:
        assert c.healthz()["wire_binary"] is True
        vals = np.arange(-20, 20, dtype=np.int32)
        out = c.compute_batch(vals)  # rides the binary /compute_raw lane
        np.testing.assert_array_equal(np.asarray(out), vals + 2)
        assert c._wire_binary is True  # the probe latched binary
        out = c.compute_raw(vals[:7])
        np.testing.assert_array_equal(np.asarray(out), vals[:7] + 2)
    finally:
        c.close()


def test_client_text_mode_keeps_legacy_lane(server):
    _, port = server
    c = MisakaClient(f"http://127.0.0.1:{port}", timeout=30, wire="text")
    try:
        vals = np.arange(5, dtype=np.int32)
        out = c.compute_batch(vals)
        np.testing.assert_array_equal(np.asarray(out), vals + 2)
        assert c._wire_binary is False
    finally:
        c.close()


def test_client_probe_failure_latches_text():
    # no server at all: the capability probe must fail SAFE (text), never
    # raise out of the probe itself
    c = MisakaClient("http://127.0.0.1:1", timeout=0.2, connect_retries=0)
    assert c._use_binary_wire() is False


# --- the shared-memory plane ------------------------------------------------


@pytest.fixture()
def shm_plane(tmp_path):
    top = networks.add2(**SMALL)
    master = MasterNode(top, chunk_steps=32, batch=4, engine="scan")
    plane = frontends.start_compute_plane(master, str(tmp_path / "p.sock"))
    master.run()
    try:
        yield master, plane
    finally:
        plane.close()
        master.pause()
        master.close()


def _with_shm_env(value):
    prev = os.environ.get("MISAKA_PLANE_SHM")
    if value is None:
        os.environ.pop("MISAKA_PLANE_SHM", None)
    else:
        os.environ["MISAKA_PLANE_SHM"] = value

    def restore():
        if prev is None:
            os.environ.pop("MISAKA_PLANE_SHM", None)
        else:
            os.environ["MISAKA_PLANE_SHM"] = prev

    return restore


def test_shm_plane_serves_and_counts(shm_plane):
    master, plane = shm_plane
    restore = _with_shm_env("1")
    try:
        before = frontends.M_PLANE_SHM_FRAMES.value
        client = frontends.PlaneClient(plane.path, conns=1)
        try:
            for k in range(5):
                vals = (np.arange(12, dtype=np.int32) + 100 * k)
                out = client.compute_raw(
                    np.ascontiguousarray(vals, "<i4").tobytes()
                )
                np.testing.assert_array_equal(
                    np.frombuffer(out, "<i4"), vals + 2
                )
        finally:
            client.close()
        assert frontends.M_PLANE_SHM_FRAMES.value >= before + 5
    finally:
        restore()


def test_shm_plane_default_off(shm_plane):
    master, plane = shm_plane
    restore = _with_shm_env(None)
    try:
        before = frontends.M_PLANE_SHM_FRAMES.value
        client = frontends.PlaneClient(plane.path, conns=1)
        try:
            vals = np.arange(8, dtype=np.int32)
            out = client.compute_raw(
                np.ascontiguousarray(vals, "<i4").tobytes()
            )
            np.testing.assert_array_equal(np.frombuffer(out, "<i4"), vals + 2)
        finally:
            client.close()
        # shipped behavior: zero shm frames without the flag
        assert frontends.M_PLANE_SHM_FRAMES.value == before
    finally:
        restore()


def test_shm_plane_preserves_drain_semantics(shm_plane):
    master, plane = shm_plane
    restore = _with_shm_env("1")
    try:
        client = frontends.PlaneClient(plane.path, conns=1)
        try:
            vals = np.arange(6, dtype=np.int32)
            body = np.ascontiguousarray(vals, "<i4").tobytes()
            out = client.compute_raw(body)  # arm the shm path first
            np.testing.assert_array_equal(np.frombuffer(out, "<i4"), vals + 2)
            plane.set_draining(True)
            # a single-engine PlaneClient maps the drain status to 503
            with pytest.raises(frontends.PlaneError) as e:
                client.compute_raw(body)
            assert e.value.status == 503
            plane.set_draining(False)
            out = client.compute_raw(body)
            np.testing.assert_array_equal(np.frombuffer(out, "<i4"), vals + 2)
        finally:
            client.close()
    finally:
        restore()


def test_shm_rearms_with_fresh_segment_after_restart(tmp_path):
    """A replica restart between frames: the stale-socket replay must
    re-arm on the NEW connection with a FRESH segment (never reusing the
    old one — a stale engine handler may still map it) and the request
    succeeds with zero client-visible errors."""
    top = networks.add2(**SMALL)
    path = str(tmp_path / "p.sock")
    m1 = MasterNode(top, chunk_steps=32, batch=4, engine="scan")
    p1 = frontends.start_compute_plane(m1, path)
    m1.run()
    restore = _with_shm_env("1")
    try:
        client = frontends.PlaneClient(path, conns=1)
        try:
            vals = np.arange(10, dtype=np.int32)
            body = np.ascontiguousarray(vals, "<i4").tobytes()
            out = client.compute_raw(body)
            np.testing.assert_array_equal(np.frombuffer(out, "<i4"), vals + 2)
            # "restart": sever the plane, bring a twin up on the same path
            p1.close()
            m1.pause()
            m2 = MasterNode(top, chunk_steps=32, batch=4, engine="scan")
            p2 = frontends.start_compute_plane(m2, path)
            m2.run()
            try:
                before = frontends.M_PLANE_SHM_FRAMES.value
                out = client.compute_raw(body)  # replay + re-arm
                np.testing.assert_array_equal(
                    np.frombuffer(out, "<i4"), vals + 2
                )
                assert frontends.M_PLANE_SHM_FRAMES.value >= before + 1
            finally:
                p2.close()
                m2.pause()
                m2.close()
        finally:
            client.close()
    finally:
        restore()
        m1.close()


def test_shm_armed_engine_still_accepts_socket_frames(shm_plane):
    # the transports can mix on one plane: a second, shm-less client
    # keeps socket payloads while the first rides the segment
    master, plane = shm_plane
    restore = _with_shm_env("1")
    try:
        shm_client = frontends.PlaneClient(plane.path, conns=1)
    finally:
        restore()
    plain_client = frontends.PlaneClient(plane.path, conns=1)
    try:
        for client in (shm_client, plain_client, shm_client):
            vals = np.arange(9, dtype=np.int32)
            out = client.compute_raw(
                np.ascontiguousarray(vals, "<i4").tobytes()
            )
            np.testing.assert_array_equal(np.frombuffer(out, "<i4"), vals + 2)
    finally:
        shm_client.close()
        plain_client.close()
