"""The project checkers: one rule per recurring review finding.

Each checker is deliberately narrow — it encodes ONE defect shape this
repo has actually shipped and fixed (docs/STATIC_ANALYSIS.md cites the
incidents), erring toward precision over recall: a project linter that
cries wolf gets baselined into silence.  Fixture twins in
tests/test_lint.py pin that every rule still catches its seeded-bad
snippet and passes the corrected one.
"""

from __future__ import annotations

import ast
from typing import Iterator

from misaka_tpu.lint.engine import (
    Checker,
    Finding,
    LintError,
    Module,
    call_name,
    dotted,
    walk_scope,
)

# Non-reentrant lock constructors: `with L:` inside `with L:` deadlocks.
# RLock is excluded by name — re-entry is its whole point.
_LOCK_CTORS = {"threading.Lock", "threading.Condition", "Lock", "Condition"}


def _is_lock_ctor(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and (dotted(node.func) or "") in _LOCK_CTORS)


def _with_lock_names(stmt: ast.With) -> list[str]:
    """Dotted names of plain `with <chain>:` context items (lock usage
    shape); `with open(...)` and friends render no name."""
    out = []
    for item in stmt.items:
        name = dotted(item.context_expr)
        if name is not None:
            out.append(name)
    return out


class LockDiscipline(Checker):
    """MSK001 — a call to a function that acquires non-reentrant lock L,
    made lexically inside a `with L:` block of the same module/class.

    The self-deadlock shape fixed three times in review: the usage
    ledger's and the SLO windows' recursive "other" resolution under
    their module `_lock` (PR 7, twice), and the admission governor's
    eviction path under its own `self._lock` (PR 9).  The acquirer
    registry is DERIVED per file — module-level `X = threading.Lock()`
    plus `self.X = threading.Lock()` instance locks — so new modules are
    covered the day they grow a lock, and the known registries
    (metrics/usage/slo/edge/ServeBatcher) are pinned by tests.
    """

    def __init__(self):
        super().__init__(
            rule="MSK001",
            summary="call re-acquires a non-reentrant lock already held "
                    "by a lexically enclosing `with` (self-deadlock)",
        )

    # -- registry derivation --------------------------------------------

    def module_locks(self, module: Module) -> dict[str, set[str]]:
        """{lock_name: {module-level functions that acquire it}} for
        module-level `X = threading.Lock()/Condition()` locks."""
        locks = {
            t.id
            for stmt in module.tree.body if isinstance(stmt, ast.Assign)
            for t in stmt.targets
            if isinstance(t, ast.Name) and _is_lock_ctor(stmt.value)
        }
        acquirers: dict[str, set[str]] = {name: set() for name in locks}
        if not locks:
            return acquirers
        for stmt in module.tree.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for name in self._acquired(stmt, locks):
                acquirers[name].add(stmt.name)
        return acquirers

    def class_locks(self, cls: ast.ClassDef) -> dict[str, set[str]]:
        """{`self.X`: {methods that acquire it}} for instance locks
        assigned `self.X = threading.Lock()/Condition()` in any method."""
        locks: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    name = dotted(t)
                    if name and name.startswith("self."):
                        locks.add(name)
        acquirers: dict[str, set[str]] = {name: set() for name in locks}
        if not locks:
            return acquirers
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for name in self._acquired(stmt, locks):
                acquirers[name].add(stmt.name)
        return acquirers

    @staticmethod
    def _acquired(func: ast.AST, locks: set[str]) -> set[str]:
        """Which of `locks` this function acquires in its own body
        (`with L:` or `L.acquire()`), nested defs excluded."""
        out: set[str] = set()
        for node in walk_scope(func):
            if isinstance(node, ast.With):
                for name in _with_lock_names(node):
                    if name in locks:
                        out.add(name)
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr == "acquire"):
                    name = dotted(f.value)
                    if name in locks:
                        out.add(name)
        return out

    # -- the check ------------------------------------------------------

    def check(self, module: Module) -> Iterator[Finding]:
        mod_acq = self.module_locks(module)
        for stmt in module.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan(module, stmt, mod_acq, receiver=None)
            elif isinstance(stmt, ast.ClassDef):
                cls_acq = self.class_locks(stmt)
                for m in stmt.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        # module locks are visible inside methods too
                        yield from self._scan(module, m, mod_acq,
                                              receiver=None)
                        yield from self._scan(module, m, cls_acq,
                                              receiver="self")
        return

    def _scan(self, module: Module, func: ast.AST,
              acquirers: dict[str, set[str]],
              receiver: str | None) -> Iterator[Finding]:
        """Flag calls to acquirers of L inside `with L:`, lexically."""
        if not any(acquirers.values()):
            return

        def visit(node: ast.AST, held: frozenset):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue  # a nested def runs later, not here
                child_held = held
                if isinstance(child, ast.With):
                    child_held = held | {
                        n for n in _with_lock_names(child) if n in acquirers
                    }
                if isinstance(child, ast.Call):
                    yield from self._check_call(module, child, held,
                                                acquirers, receiver)
                yield from visit(child, child_held)

        yield from visit(func, frozenset())

    def _check_call(self, module, call, held, acquirers, receiver):
        for lock in held:
            takers = acquirers.get(lock, ())
            f = call.func
            if receiver is None and isinstance(f, ast.Name) \
                    and f.id in takers:
                yield self.finding(
                    module, call,
                    f"{f.id}() acquires module lock `{lock}` but is "
                    f"called inside `with {lock}:` — non-reentrant "
                    f"self-deadlock",
                )
            elif receiver is not None and isinstance(f, ast.Attribute) \
                    and dotted(f) == f"self.{f.attr}" and f.attr in takers:
                yield self.finding(
                    module, call,
                    f"self.{f.attr}() acquires `{lock}` but is called "
                    f"inside `with {lock}:` — non-reentrant self-deadlock",
                )


class ExceptionBreadth(Checker):
    """MSK002 — HTTP-call try blocks whose handlers catch OSError-family
    types but not http.client.HTTPException, and bare `except:` anywhere.

    PR 8's fleet shipped this twice: a replica dying mid-response raises
    BadStatusLine (an HTTPException, NOT an OSError), so `except
    OSError` around post_form/getresponse turned a routine failover into
    an unhandled exception in the router.  conn.request() itself can
    raise CannotSendRequest (also HTTPException) on connection-state
    errors, so pooled-connection retry loops have the same hole.
    """

    # call names whose failure surface includes http.client.HTTPException
    RISKY = {"post_form", "_post_form", "getresponse", "urlopen", "request"}
    # TRANSPORT-level exception names that do NOT cover HTTPException on
    # their own.  urllib.error.HTTPError is deliberately absent: catching
    # it alone is status-code handling (read the error body), not the
    # failover shape this rule polices.
    NARROW = {"OSError", "ConnectionError", "IOError", "error",
              "URLError", "timeout", "TimeoutError"}
    COVERS = {"HTTPException", "Exception", "BaseException"}

    def __init__(self):
        super().__init__(
            rule="MSK002",
            summary="except clause around an HTTP call misses "
                    "http.client.HTTPException (or is a bare except)",
        )

    @staticmethod
    def _handler_names(handler: ast.ExceptHandler) -> list[str]:
        t = handler.type
        if t is None:
            return []
        nodes = t.elts if isinstance(t, ast.Tuple) else [t]
        out = []
        for n in nodes:
            name = dotted(n)
            if name:
                out.append(name.rsplit(".", 1)[-1])
        return out

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield self.finding(
                    module, node,
                    "bare `except:` swallows SystemExit/KeyboardInterrupt "
                    "— name the exceptions (at minimum `except Exception`)",
                )
            if not isinstance(node, ast.Try):
                continue
            risky = None
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and call_name(sub) in self.RISKY:
                    risky = call_name(sub)
                    break
            if risky is None:
                continue
            names: set[str] = set()
            for h in node.handlers:
                names.update(self._handler_names(h))
            if names and names & self.NARROW and not (names & self.COVERS):
                yield self.finding(
                    module, node,
                    f"try block calls {risky}() but handlers catch only "
                    f"{sorted(names & self.NARROW)} — http.client."
                    f"HTTPException (BadStatusLine, CannotSendRequest) "
                    f"escapes; catch (OSError, http.client.HTTPException)",
                )


class LabelCardinality(Checker):
    """MSK003 — tenant/program metric labels fed straight from a caller-
    supplied parameter without a cardinality launder.

    Client-chosen names minted unbounded metric series (and dict keys)
    until `metrics.capped_label` existed; PR 9 then re-audited every
    edge-side dict for the same hole.  The rule: a `.labels(...)` call
    whose tenant-identifying keyword (tenant/program/account/key) is a
    bare parameter of the enclosing function must launder — the value
    itself a `capped_label(...)`-family call, the parameter reassigned
    from one earlier in the function, or the function itself one of the
    module's launder wrappers (a function whose body calls capped_label,
    derived per module — edge.tenant_metric_label's shape).
    """

    CLIENT_KEYWORDS = {"tenant", "program", "account", "key"}
    LAUNDER = {"capped_label"}

    def __init__(self):
        super().__init__(
            rule="MSK003",
            summary="client-derived metric label bypasses "
                    "metrics.capped_label (unbounded series cardinality)",
        )

    def _launder_fns(self, module: Module) -> set[str]:
        """Module functions whose body calls capped_label — calling THEM
        is laundering too (tenant_metric_label wraps capped_label)."""
        out = set(self.LAUNDER)
        for stmt in module.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for node in walk_scope(stmt):
                    if isinstance(node, ast.Call) \
                            and call_name(node) in self.LAUNDER:
                        out.add(stmt.name)
                        break
        return out

    @staticmethod
    def _params(func) -> set[str]:
        a = func.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return {n for n in names if n not in ("self", "cls")}

    def check(self, module: Module) -> Iterator[Finding]:
        launder = self._launder_fns(module)
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"):
                continue
            func = module.enclosing_function(node)
            if func is None or func.name in launder:
                continue
            params = self._params(func)
            laundered = self._laundered_names(func, launder)
            for kw in node.keywords:
                if kw.arg not in self.CLIENT_KEYWORDS:
                    continue
                v = kw.value
                if isinstance(v, ast.Call) and call_name(v) in launder:
                    continue
                if isinstance(v, ast.Name) and v.id in params \
                        and v.id not in laundered:
                    yield self.finding(
                        module, node,
                        f".labels({kw.arg}={v.id}) feeds parameter "
                        f"`{v.id}` straight into a metric label — launder "
                        f"through metrics.capped_label / "
                        f"tenant_label_budget first",
                    )

    @staticmethod
    def _laundered_names(func, launder: set[str]) -> set[str]:
        """Names (re)assigned from a launder call anywhere in the
        function — `label = capped_label(...)` clears `label`."""
        out: set[str] = set()
        for node in walk_scope(func):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and call_name(node.value) in launder:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out


class ThreadLifecycle(Checker):
    """MSK004 — a threading.Thread that is neither daemonized nor
    reachable from any join path.

    The ComputePlane leaked one accept thread per close until PR 7: the
    thread was non-daemon and close() never joined it, so every
    open/close cycle in the full suite accumulated a blocked OS thread.
    Accepted shapes: `daemon=True` at construction; `X.daemon = True`
    before start; the Thread stored somewhere a lexically visible
    `.join(` reaches — same function for locals, any method of the class
    for `self.X` (close()/shutdown paths live there), and the list
    idiom: Threads collected into `ts = [...]` / `ts.append(...)` with a
    `for t in ts: t.join()` loop in the same scope.
    """

    def __init__(self):
        super().__init__(
            rule="MSK004",
            summary="threading.Thread neither daemonized nor joined "
                    "(leaks one OS thread per lifecycle)",
        )

    @staticmethod
    def _has_daemon_kwarg(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return True
        return False

    @staticmethod
    def _search(scope: ast.AST, target: str, attr: str) -> bool:
        """Does `target`.daemon = True or `target`.join( appear under
        scope?  target is a dotted chain ("t", "self._accept_thread")."""
        for node in ast.walk(scope):
            if attr == "join" and isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" \
                    and dotted(node.func.value) == target:
                return True
            if attr == "daemon" and isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                            and dotted(t.value) == target \
                            and isinstance(node.value, ast.Constant) \
                            and node.value.value is True:
                        return True
        return False

    @staticmethod
    def _joined_via_loop(scope: ast.AST, container: str) -> bool:
        """`for v in <container>: ... v.join()` anywhere under scope."""
        for node in ast.walk(scope):
            if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
                it = node.iter
                names = {dotted(it)}
                if isinstance(it, ast.Call) and it.args:
                    names.add(dotted(it.args[0]))   # for t in list(ts):
                elif isinstance(it, ast.BinOp):
                    names.add(dotted(it.left))      # for t in ts + more:
                    names.add(dotted(it.right))
                if container not in names:
                    continue
                v = node.target.id
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "join" \
                            and dotted(sub.func.value) == v:
                        return True
        return False

    def _container_of(self, module: Module, node: ast.Call) -> str | None:
        """The list/collection name a Thread call lands in: a list
        literal or comprehension assigned to a Name, or `ts.append(...)`."""
        cur, parent = node, module.parent(node)
        while parent is not None:
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                    and isinstance(parent.targets[0], ast.Name):
                return parent.targets[0].id
            if isinstance(parent, ast.AugAssign) \
                    and isinstance(parent.target, ast.Name):
                return parent.target.id  # ts += [Thread(...), ...]
            if isinstance(parent, ast.Call) \
                    and isinstance(parent.func, ast.Attribute) \
                    and parent.func.attr == "append":
                return dotted(parent.func.value)
            if not isinstance(parent, (ast.List, ast.Tuple, ast.ListComp,
                                       ast.GeneratorExp, ast.comprehension,
                                       ast.IfExp)):
                return None
            cur, parent = parent, module.parent(parent)
        return None

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and (dotted(node.func) in ("threading.Thread", "Thread"))):
                continue
            if self._has_daemon_kwarg(node):
                continue
            parent = module.parent(node)
            target = None
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
                target = dotted(parent.targets[0])
            ok = False
            if target:
                if target.startswith("self."):
                    cls = module.enclosing_class(node)
                    scope = cls if cls is not None else module.tree
                else:
                    scope = module.enclosing_function(node) or module.tree
                ok = (self._search(scope, target, "join")
                      or self._search(scope, target, "daemon")
                      or self._joined_via_loop(scope, target))
            else:
                container = self._container_of(module, node)
                if container:
                    scope = module.enclosing_function(node) or module.tree
                    ok = self._joined_via_loop(scope, container)
            if not ok:
                where = f"`{target}`" if target else "an unnamed thread"
                yield self.finding(
                    module, node,
                    f"threading.Thread assigned to {where} is neither "
                    f"daemon=True nor reachable from a .join() — one OS "
                    f"thread leaks per lifecycle (the ComputePlane "
                    f"accept-thread class)",
                )


class ClockDiscipline(Checker):
    """MSK005 — time.time() in +/- arithmetic, i.e. used as a duration
    or deadline.  Wall clocks step (NTP, manual set); every elapsed/
    deadline computation must use time.monotonic().  time.time() stays
    legal as a timestamp VALUE (checkpoint metadata, trace start epochs).
    """

    def __init__(self):
        super().__init__(
            rule="MSK005",
            summary="time.time() arithmetic (duration/deadline math "
                    "must use time.monotonic())",
        )

    @staticmethod
    def _is_walltime_call(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and dotted(node.func) in ("time.time",))

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, (ast.Sub, ast.Add)) \
                    and (self._is_walltime_call(node.left)
                         or self._is_walltime_call(node.right)):
                op = "-" if isinstance(node.op, ast.Sub) else "+"
                yield self.finding(
                    module, node,
                    f"time.time() used in `{op}` arithmetic — wall clocks "
                    f"step under NTP; durations and deadlines must use "
                    f"time.monotonic()",
                )


class HandlerDrain(Checker):
    """MSK006 — a POST handler answering an error status while the
    request body may still be unread, without the consume-or-close
    discipline.

    PR 3's keep-alive desync: an early `self._text(4xx, ...)` return
    that never read the POST body leaves those bytes in the socket, and
    the NEXT request on the connection parses them as its request line.
    The contract (shared helper since PR 9): before any early error
    response, either consume (`edge.drain_or_close`, `self._form()`,
    `self.rfile.read(...)`) or mark `self.close_connection = True`.
    Checked in POST-context methods (`do_POST`, `_handle_post*`,
    `_post*`) — GET paths carry no body.
    """

    POST_NAMES = ("do_POST", "_handle_post", "_post")
    CONSUMERS = {"drain_or_close", "_form", "_read_body"}

    def __init__(self):
        super().__init__(
            rule="MSK006",
            summary="POST handler answers an error before the body is "
                    "consumed or the connection marked to close "
                    "(keep-alive desync)",
        )

    @classmethod
    def _is_post_func(cls, func) -> bool:
        return any(func.name == n or func.name.startswith(n)
                   for n in cls.POST_NAMES)

    @staticmethod
    def _is_error_response(node: ast.AST) -> bool:
        """self._text(4xx/5xx-literal, ...) or send_error(4xx/5xx)."""
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("_text", "send_error")
                and node.args):
            return False
        status = node.args[0]
        return (isinstance(status, ast.Constant)
                and isinstance(status.value, int)
                and status.value >= 400)

    def _consumes(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in self.CONSUMERS:
                return True
            if name == "read" and isinstance(node.func, ast.Attribute) \
                    and dotted(node.func.value, ) in ("self.rfile", "rfile"):
                return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) \
                        and t.attr == "close_connection" \
                        and isinstance(node.value, ast.Constant) \
                        and node.value.value is True:
                    return True
        return False

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and self._is_post_func(node):
                yield from self._scan(module, node)

    def _scan(self, module: Module, func) -> Iterator[Finding]:
        # one-way latch in lexical statement order: conservative (a
        # consume in an earlier branch suppresses later findings) but
        # zero false positives on the repo's early-return shape, where
        # the consume always precedes the error response it licenses.
        consumed = False
        for node in walk_scope(func):
            if not consumed and self._consumes(node):
                consumed = True
            if not consumed and self._is_error_response(node):
                yield self.finding(
                    module, node,
                    "error response before the POST body is consumed — "
                    "call edge.drain_or_close(self) (or read the body / "
                    "set self.close_connection = True) first, or the "
                    "unread bytes desynchronize the next keep-alive "
                    "request",
                )


ALL_CHECKERS = (
    LockDiscipline(),
    ExceptionBreadth(),
    LabelCardinality(),
    ThreadLifecycle(),
    ClockDiscipline(),
    HandlerDrain(),
)


def checker_for(rule: str) -> Checker:
    for c in ALL_CHECKERS:
        if c.rule == rule:
            return c
    raise LintError(f"unknown rule {rule!r} (have "
                    f"{[c.rule for c in ALL_CHECKERS]})")
