"""CLI: `python -m misaka_tpu.lint` — the `make lint` entry point.

Exit codes: 0 clean (baselined findings allowed), 1 new findings,
2 engine/usage error.  Stale baseline entries print as warnings but do
not fail the run — paying down debt must never break the build that
paid it; `--update-baseline` rewrites the file (hand-edit the
justification comments back in afterward, or start from git diff).
"""

from __future__ import annotations

import argparse
import os
import sys

from misaka_tpu.lint.checkers import ALL_CHECKERS, checker_for
from misaka_tpu.lint.engine import (
    LintError,
    apply_baseline,
    format_findings,
    load_baseline,
    run_tree,
    save_baseline,
)

# What `make lint` covers: the package, the ops tooling, and the bench
# driver.  tests/ are deliberately out — they monkeypatch, hold locks
# across helpers, and spin short-lived joined-in-fixture threads in
# shapes every rule here would (correctly, uselessly) flag.
DEFAULT_ROOTS = ("misaka_tpu", "tools", "bench.py")

BASELINE_DEFAULT = os.path.join("misaka_tpu", "lint", "baseline.txt")


def repo_base() -> str:
    # misaka_tpu/lint/__main__.py -> the directory holding misaka_tpu/
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m misaka_tpu.lint",
        description="project static analysis (rules MSK001-MSK006)",
    )
    ap.add_argument("roots", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_ROOTS})")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {BASELINE_DEFAULT})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (e.g. MSK001,MSK005)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for c in ALL_CHECKERS:
            print(f"{c.rule}  {c.summary}")
        return 0

    base = repo_base()
    roots = args.roots or [r for r in DEFAULT_ROOTS
                           if os.path.exists(os.path.join(base, r))]
    baseline_path = os.path.join(
        base, args.baseline if args.baseline else BASELINE_DEFAULT)

    try:
        checkers = ALL_CHECKERS if args.rules is None else tuple(
            checker_for(r.strip()) for r in args.rules.split(","))
        findings = run_tree(roots, checkers, base)
    except LintError as e:
        print(f"lint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        save_baseline(
            baseline_path, findings,
            header=("misaka lint baseline — pre-existing findings judged "
                    "intentional.\nEach entry should carry a justification "
                    "comment; see docs/STATIC_ANALYSIS.md."),
        )
        print(f"lint: wrote {len(findings)} fingerprints to "
              f"{os.path.relpath(baseline_path, base)}")
        return 0

    baseline = set() if args.no_baseline else load_baseline(baseline_path)
    new, suppressed, stale = apply_baseline(findings, baseline)

    if new:
        print(format_findings(new))
    for fp in sorted(stale):
        print(f"lint: warning: stale baseline entry (debt paid? remove the "
              f"line): {fp}", file=sys.stderr)
    print(f"lint: {len(new)} new finding(s), {len(suppressed)} baselined, "
          f"{len(stale)} stale baseline entr(ies) — "
          f"{len(ALL_CHECKERS if args.rules is None else checkers)} rule(s)",
          file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
