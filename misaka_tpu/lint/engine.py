"""The lint engine: module model, findings, fingerprints, baseline.

Stdlib-only by design (ast + tokenize-free line scanning) — the linter
must run in every environment the code does, including the bare CI
container, with zero pip installs.

The moving parts:

  * ``Module`` — one parsed source file plus the derived context every
    checker needs: parent links, enclosing-scope chains, and the raw
    source lines (for `lint: disable=` suppressions).
  * ``Finding`` — one violation; its ``fingerprint`` deliberately omits
    the line number (rule + path + enclosing scope + message + an
    occurrence counter), so unrelated edits above a finding don't churn
    the baseline.
  * baseline — a committed text file of fingerprints with `#`
    justification comments.  Findings whose fingerprint is listed are
    suppressed; NEW findings fail the run; stale entries are reported so
    the file shrinks as debt is paid.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Iterable, Iterator

_RULE_ID_RE = re.compile(r"MSK\d{3}")


class LintError(Exception):
    """Engine misuse (unknown rule, unreadable baseline...)."""


@dataclass
class Finding:
    rule: str          # "MSK001"
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    scope: str         # dotted enclosing def/class chain, "<module>" at top
    message: str
    # distinguishes repeated identical findings in one scope so each
    # needs its own baseline entry (set by the runner, not checkers)
    occurrence: int = 1

    @property
    def fingerprint(self) -> str:
        base = f"{self.rule} {self.path} {self.scope} :: {self.message}"
        return base if self.occurrence == 1 else f"{base} #{self.occurrence}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope}] {self.message}")


class Module:
    """One parsed file + the context checkers share."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def scope_of(self, node: ast.AST) -> str:
        """Dotted chain of enclosing defs/classes ("Cls.method"), or
        "<module>" for top-level code."""
        names: list[str] = []
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            cur = self._parents.get(cur)
        return ".".join(reversed(names)) or "<module>"

    def enclosing_function(
        self, node: ast.AST
    ) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self._parents.get(cur)
        return None

    def enclosing_class(self, node: ast.AST) -> ast.ClassDef | None:
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self._parents.get(cur)
        return None

    def suppressed(self, line: int, rule: str) -> bool:
        """True when the physical line carries `lint: disable=<rule>`
        (comma- or space-separated rules allowed).  The escape hatch for
        a finding that is wrong ON THIS LINE but right as a rule; prefer
        the baseline for pre-existing debt."""
        if not 1 <= line <= len(self.lines):
            return False
        text = self.lines[line - 1]
        marker = text.find("lint: disable=")
        if marker < 0 or "#" not in text[:marker]:
            return False
        # tolerate sloppy separators ("MSK001, MSK002") and an empty
        # list ("disable=" with the rule forgotten suppresses nothing)
        listed = _RULE_ID_RE.findall(text[marker + len("lint: disable="):])
        return rule in listed


@dataclass
class Checker:
    """Base: subclasses set `rule`/`summary` and implement check()."""

    rule: str = "MSK000"
    summary: str = ""

    def check(self, module: Module) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, module: Module, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.rule,
            path=module.relpath,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            scope=module.scope_of(node),
            message=message,
        )


# --- small shared AST helpers (checkers import these) -----------------------


def call_name(node: ast.Call) -> str | None:
    """The terminal name a call targets: f() -> "f", a.b.f() -> "f"."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def dotted(node: ast.AST) -> str | None:
    """Render a Name/Attribute chain ("threading.Lock", "self._lock");
    None when the expression is not a plain chain."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def walk_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Pre-order, DOCUMENT-order walk that does not descend into nested
    function/class defs — the body of the scope itself (a nested def
    only runs when called; analyzing it as if inline produces false
    lock/drain findings).  Document order matters: the handler-drain
    latch is one-way over lexical statement order."""
    for child in ast.iter_child_nodes(node):
        yield child
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
            yield from walk_scope(child)


# --- the runner -------------------------------------------------------------

# Generated / vendored files no checker should parse opinions into.
EXCLUDE_SUFFIXES = ("_pb2.py",)


def iter_py_files(roots: Iterable[str], base: str) -> Iterator[tuple[str, str]]:
    """(abspath, relpath-to-base) for every lintable .py under roots;
    roots may be files or directories."""
    for root in roots:
        rootabs = os.path.join(base, root) if not os.path.isabs(root) else root
        if os.path.isfile(rootabs):
            if rootabs.endswith(".py"):
                yield rootabs, os.path.relpath(rootabs, base)
            continue
        for dirpath, dirnames, filenames in os.walk(rootabs):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if not fn.endswith(".py") or fn.endswith(EXCLUDE_SUFFIXES):
                    continue
                full = os.path.join(dirpath, fn)
                yield full, os.path.relpath(full, base)


def _number_occurrences(findings: list[Finding]) -> list[Finding]:
    seen: dict[str, int] = {}
    for f in findings:
        key = f"{f.rule} {f.path} {f.scope} :: {f.message}"
        seen[key] = seen.get(key, 0) + 1
        f.occurrence = seen[key]
    return findings


def run_source(source: str, checkers, relpath: str = "<snippet>.py"
               ) -> list[Finding]:
    """Lint one source string (the fixture-test entry point)."""
    module = Module(relpath, relpath, source)
    findings: list[Finding] = []
    for checker in checkers:
        for f in checker.check(module):
            if not module.suppressed(f.line, f.rule):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return _number_occurrences(findings)


def run_tree(roots, checkers, base: str) -> list[Finding]:
    """Lint every .py under roots; syntax errors are findings, not
    crashes (a half-written file must fail lint, loudly and located)."""
    findings: list[Finding] = []
    for path, rel in iter_py_files(roots, base):
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        try:
            module = Module(path, rel, source)
        except SyntaxError as e:
            findings.append(Finding(
                rule="MSK000", path=rel.replace(os.sep, "/"),
                line=e.lineno or 1, col=e.offset or 0,
                scope="<module>", message=f"syntax error: {e.msg}",
            ))
            continue
        for checker in checkers:
            for f in checker.check(module):
                if not module.suppressed(f.line, f.rule):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return _number_occurrences(findings)


# --- baseline ---------------------------------------------------------------


def load_baseline(path: str) -> set[str]:
    """Fingerprint set from a baseline file; missing file = empty
    baseline (a fresh checkout with no debt needs no file)."""
    if not os.path.exists(path):
        return set()
    out: set[str] = set()
    with open(path, encoding="utf-8") as fh:
        for raw in fh:
            line = raw.strip()
            if line and not line.startswith("#"):
                out.add(line)
    return out


def save_baseline(path: str, findings: Iterable[Finding],
                  header: str = "") -> None:
    """Write every finding's fingerprint, sorted — `--update-baseline`.
    This OVERWRITES the file, dropping hand-written justification
    comments: restore them from the git diff afterward (the enforced
    workflow — tests/test_lint.py fails the tree while any entry lacks
    its comment, so a clobber cannot land silently)."""
    lines = sorted(f.fingerprint for f in findings)
    with open(path, "w", encoding="utf-8") as fh:
        if header:
            for h in header.splitlines():
                fh.write(f"# {h}\n")
        for line in lines:
            fh.write(line + "\n")


def apply_baseline(
    findings: list[Finding], baseline: set[str]
) -> tuple[list[Finding], list[Finding], set[str]]:
    """Split into (new, suppressed, stale-baseline-entries)."""
    new: list[Finding] = []
    suppressed: list[Finding] = []
    hit: set[str] = set()
    for f in findings:
        if f.fingerprint in baseline:
            suppressed.append(f)
            hit.add(f.fingerprint)
        else:
            new.append(f)
    return new, suppressed, baseline - hit


def format_findings(findings: Iterable[Finding]) -> str:
    return "\n".join(f.render() for f in findings)
