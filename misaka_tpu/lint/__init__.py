"""Project lint engine: the repo's recurring bug classes as machine checks.

Nine PRs of review history kept re-finding the same defect shapes — lock
self-deadlocks (fixed in PR 7 twice, again in PR 9's admission governor),
`except OSError` around HTTP calls that raise `http.client.HTTPException`
(PR 8, twice), thread-per-close leaks (the ComputePlane accept thread),
unbounded client-minted metric labels (until `metrics.capped_label`),
wall-clock duration math, and keep-alive desync on undrained POST bodies
(PR 3).  This package turns each of those into a stdlib-only AST checker
with a rule ID, so the PATTERN fails `make lint` the day it is
reintroduced instead of costing another review round.

Rule catalog (docs/STATIC_ANALYSIS.md has the originating incidents):

  MSK001  lock-discipline   calling a function that acquires lock L while
                            lexically inside `with L:` (self-deadlock)
  MSK002  exception-breadth `except OSError` around post_form/urlopen/
                            getresponse sites (miss HTTPException); bare
                            `except:` anywhere
  MSK003  label-cardinality client-derived tenant/program metric labels
                            not laundered through metrics.capped_label
  MSK004  thread-lifecycle  threading.Thread neither daemonized nor
                            reachable from a join path
  MSK005  clock-discipline  time.time() arithmetic used as a duration
                            (must be time.monotonic())
  MSK006  handler-drain     POST route bodies answering an error before
                            consuming-or-closing the request body

Pre-existing, deliberate findings live in misaka_tpu/lint/baseline.txt
(one fingerprint per line, `#` justification comments); NEW findings fail
the run.  Entry point: `python -m misaka_tpu.lint` / `make lint`.
"""

from misaka_tpu.lint.engine import (  # noqa: F401
    Finding,
    LintError,
    Module,
    format_findings,
    load_baseline,
    run_source,
    run_tree,
    save_baseline,
)
from misaka_tpu.lint.checkers import ALL_CHECKERS, checker_for  # noqa: F401
