"""Command-line interface: python -m misaka_tpu <command>.

The reference has no CLI beyond `./app` + env vars (cmd/app.go) and curl
(README.md:50-80).  This front door adds developer tooling around the same
surfaces:

  serve                      run a node/master (same env contract as
                             `python -m misaka_tpu.runtime.app`)
  check    <topology>        compile a topology, report per-node code sizes
  disasm   <topology>        compile then disassemble every program node
  compute  <v...> [--url]    send values to a running master's /compute
  bench    [--batch --values] quick add-2 throughput smoke (the real harness
                             is bench.py at the repo root)
  replay   <segment|dir>     shadow-replay a captured .mskcap traffic segment
                             byte-for-byte (tools/replay.py; --candidate gives
                             the pre-deploy verdict for a new topology; a
                             directory sweeps the capture spool's history)
  usage-report [--url ...]   pull + verify the signed billing export
                             (GET /usage/export; --secret checks every HMAC)
  debug    <topology>        interactive single-step debugger (misaka_tpu.debug)

<topology> is a baseline config name (add2, acc_loop, ring4, sorter,
mesh8 — misaka_tpu/networks.py), a path to a declarative JSON file
({"nodes": {...}, "programs": {...}} — runtime/topology.py), or a reference
docker-compose .yml whose services carry NODE_TYPE/PROGRAM envs
(runtime/compose.py) — the drop-in migration path.
"""

from __future__ import annotations

import argparse
import json
import sys


def _load_topology(spec: str):
    from misaka_tpu import networks
    from misaka_tpu.runtime.topology import Topology

    if spec in networks.BASELINE_CONFIGS:
        return networks.BASELINE_CONFIGS[spec]()
    if spec.endswith((".yml", ".yaml")):
        # a reference-style docker-compose deployment file (runtime/compose.py)
        from misaka_tpu.runtime.compose import load_compose

        return load_compose(spec)
    with open(spec) as f:
        return Topology.from_json(f.read())


def cmd_check(args) -> int:
    try:
        top = _load_topology(args.topology)
        net = top.compile()
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    lanes = top.lane_ids()
    print(f"ok: {len(lanes)} program node(s), {len(top.stack_ids())} stack node(s)")
    for name, i in lanes.items():
        print(f"  {name}: {int(net.prog_len[i])} line(s)")
    return 0


def cmd_disasm(args) -> int:
    from misaka_tpu.tis.disasm import disassemble_network

    top = _load_topology(args.topology)
    net = top.compile()
    texts = disassemble_network(
        net.code, net.prog_len, list(top.lane_ids()), list(top.stack_ids())
    )
    for name, text in texts.items():
        print(f"# --- {name} ---")
        print(text)
    return 0


def cmd_compute(args) -> int:
    import urllib.error

    from misaka_tpu.client import MisakaClient, MisakaClientError

    client = MisakaClient(args.url, timeout=args.timeout)
    for v in args.values:
        try:
            print(json.dumps({"value": client.compute(v)}))
        except MisakaClientError as e:
            print(f"error: {e.body}", file=sys.stderr)
            return 1
        except urllib.error.URLError as e:
            print(f"error: cannot reach {args.url}: {e.reason}", file=sys.stderr)
            return 1
    return 0


def cmd_bench(args) -> int:
    """Quick engine-path throughput smoke on the add-2 network."""
    import time

    import numpy as np

    from misaka_tpu import networks

    batch, per = args.batch, args.values
    net = networks.add2(in_cap=per, out_cap=per, stack_cap=16).compile(batch=batch)
    vals = np.random.default_rng(0).integers(-1000, 1000, (batch, per)).astype(np.int32)
    state = net.init_state()._replace(
        in_buf=vals, in_wr=np.full((batch,), per, np.int32)
    )
    import jax

    ticks = 14 * per + 64  # add-2 retires one value per ~12-14 ticks
    # Warm the compile cache on a throwaway state — and block, or the async
    # warmup execution would bleed into the timed region below.
    jax.block_until_ready(net.run(net.init_state(), ticks))
    t0 = time.perf_counter()
    state = net.run(state, ticks)
    out_wr = np.asarray(state.out_wr)
    dt = time.perf_counter() - t0
    if not (out_wr == per).all():
        print(f"error: only {int(out_wr.min())}/{per} outputs after {ticks} ticks",
              file=sys.stderr)
        return 1
    got = np.asarray(state.out_buf)
    if not (np.sort(got, axis=1) == np.sort(vals + 2, axis=1)).all():
        print("error: output mismatch", file=sys.stderr)
        return 1
    rate = batch * per / dt
    print(json.dumps({"metric": "add2_cli_smoke", "value": round(rate, 1),
                      "unit": "inputs/sec"}))
    return 0


def cmd_replay(args) -> int:
    # the implementation lives with the other operator tooling
    # (tools/replay.py, also runnable standalone); load it by path so
    # tools/ never needs to be a package
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "replay.py")
    spec = importlib.util.spec_from_file_location("_misaka_replay", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    fn = (mod.replay_directory if os.path.isdir(args.segment)
          else mod.replay_segment)
    return fn(
        args.segment,
        candidate=args.candidate,
        program=args.program,
        engine=args.engine,
        limit=args.limit,
        emit_model=args.emit_model,
    )


def cmd_usage_report(args) -> int:
    """Pull the signed billing export from a server (or fleet hub),
    verify every signature when a secret is at hand, and print the
    conserved per-tenant totals."""
    import urllib.error

    from misaka_tpu.client import MisakaClient, MisakaClientError
    from misaka_tpu.runtime import usage as usage_mod

    client = MisakaClient(args.url, timeout=args.timeout,
                          api_key=args.key)
    try:
        lines = client.usage_export(since=args.since)
    except MisakaClientError as e:
        print(f"error: {e.body}", file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"error: cannot reach {args.url}: {e.reason}", file=sys.stderr)
        return 1
    if args.raw:
        for line in lines:
            print(json.dumps(line, separators=(",", ":")))
        return 0
    try:
        totals = usage_mod.totals_from_lines(
            lines, secret=args.secret.encode() if args.secret else None
        )
    except usage_mod.UsageExportError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(json.dumps(totals, indent=2, sort_keys=True))
    if args.secret and not totals.get("verified"):
        print("error: export carried no signed lines to verify",
              file=sys.stderr)
        return 1
    return 0


def cmd_debug(args) -> int:
    from misaka_tpu.debug import Debugger

    top = _load_topology(args.topology)
    dbg = Debugger(top)
    lanes = list(top.lane_ids())
    print(f"misaka_tpu debugger — lanes: {', '.join(lanes)} (type 'help')")
    while True:
        try:
            line = input("(mdb) ").strip()
        except EOFError:
            return 0
        if not line:
            continue
        cmd, *rest = line.split()
        try:
            if cmd in ("q", "quit", "exit"):
                return 0
            elif cmd == "help":
                print(
                    "step [n]         advance n ticks (default 1)\n"
                    "run [n]          run until breakpoint (budget n, default 10000)\n"
                    "break LANE LINE  set a breakpoint\n"
                    "clear            clear all breakpoints\n"
                    "feed V [V...]    queue input values\n"
                    "out              drain outputs\n"
                    "print LANE       show a lane's registers/ports\n"
                    "stacks           show stack contents\n"
                    "list LANE        disassembly with pc cursor\n"
                    "trace [n]        recent execution history\n"
                    "reset            reset all state\n"
                    "quit             exit"
                )
            elif cmd == "step":
                hits = dbg.step(int(rest[0]) if rest else 1)
                print(f"tick={dbg.tick}" + (f" BREAK {hits}" if hits else ""))
            elif cmd == "run":
                hits = dbg.run(int(rest[0]) if rest else 10_000)
                print(f"tick={dbg.tick}" + (f" BREAK {hits}" if hits else " (no hit)"))
            elif cmd == "break":
                dbg.add_breakpoint(rest[0], int(rest[1]))
                print(f"breakpoint at {rest[0]}:{rest[1]}")
            elif cmd == "clear":
                dbg.clear_breakpoints()
            elif cmd == "feed":
                took = dbg.feed([int(v) for v in rest])
                print(f"queued {took}")
            elif cmd == "out":
                print(dbg.outputs())
            elif cmd == "print":
                print(json.dumps(dbg.inspect(rest[0]), indent=2))
            elif cmd == "stacks":
                print(json.dumps(dbg.stacks()))
            elif cmd == "list":
                print(dbg.listing(rest[0]))
            elif cmd == "trace":
                print(dbg.history(int(rest[0]) if rest else 16))
            elif cmd == "reset":
                dbg.reset()
                print("reset")
            else:
                print(f"unknown command '{cmd}' (try 'help')")
        except (KeyError, ValueError, IndexError) as e:
            print(f"error: {e}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="misaka_tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("serve", help="run a node/master from env vars")
    p = sub.add_parser("check", help="compile a topology")
    p.add_argument("topology")
    p = sub.add_parser("disasm", help="disassemble a topology's programs")
    p.add_argument("topology")
    p = sub.add_parser("compute", help="POST values to a running master")
    p.add_argument("values", nargs="+", type=int)
    p.add_argument("--url", default="http://localhost:8000")
    p.add_argument("--timeout", type=float, default=60.0)
    p = sub.add_parser("bench", help="quick add-2 throughput smoke")
    p.add_argument("--batch", type=int, default=1024)
    p.add_argument("--values", type=int, default=32)
    p = sub.add_parser(
        "replay",
        help="shadow-replay a captured .mskcap segment (tools/replay.py)",
    )
    p.add_argument("segment")
    p.add_argument("--candidate")
    p.add_argument("--program")
    p.add_argument("--engine")
    p.add_argument("--limit", type=int)
    p.add_argument("--emit-model", metavar="OUT.json")
    p = sub.add_parser(
        "usage-report",
        help="pull + verify the signed billing export (GET /usage/export)",
    )
    p.add_argument("--url", default="http://localhost:8000")
    p.add_argument("--since", type=float, default=0.0,
                   help="unix seconds lower bound on exported periods")
    p.add_argument("--key", help="admin API key (the route is admin-gated)")
    p.add_argument("--secret",
                   help="plane secret to verify every line's HMAC")
    p.add_argument("--raw", action="store_true",
                   help="print the JSONL lines verbatim instead of totals")
    p.add_argument("--timeout", type=float, default=60.0)
    p = sub.add_parser("debug", help="interactive debugger")
    p.add_argument("topology")

    args = parser.parse_args(argv)
    if args.command == "serve":
        # same boot-window signal contract as `python -m misaka_tpu.runtime.app`
        from misaka_tpu.runtime.lifecycle import arm_boot_handlers

        arm_boot_handlers()
        from misaka_tpu.runtime.app import main as serve_main

        serve_main()
        return 0
    return {
        "check": cmd_check,
        "disasm": cmd_disasm,
        "compute": cmd_compute,
        "bench": cmd_bench,
        "replay": cmd_replay,
        "usage-report": cmd_usage_report,
        "debug": cmd_debug,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
