"""jax.profiler integration — device-level tracing for the fused engine.

The reference's only "profiling" is wall-clock guessing over stdout logs
(SURVEY.md §5: no tracing/profiling subsystem at all).  The TPU-native
equivalent is XLA's own profiler: capture a trace around jitted chunks and
inspect kernel timings, HBM traffic, and host↔device transfers in
TensorBoard / Perfetto.

Two surfaces:
  * `capture(log_dir)` — context manager for scripts and benchmarks.
  * `Profiler` — start/stop object used by the master's HTTP routes
    (POST /profile/start, /profile/stop — runtime/master.py), so a live
    network can be profiled without restarting it.

Traces land in `log_dir/plugins/profile/<run>/` (TensorBoard layout, written
by jax.profiler).  One capture at a time per process — JAX's profiler is a
process-global singleton; Profiler enforces that with a lock.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


@contextmanager
def capture(log_dir: str):
    """Capture a jax.profiler trace of the enclosed block into `log_dir`."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class ProfilerError(RuntimeError):
    pass


class Profiler:
    """Process-wide start/stop profiler handle (one capture at a time)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active_dir: str | None = None
        # wall clock for the operator-facing timestamp, monotonic for
        # every elapsed computation (wall time steps under NTP — MSK005)
        self._started_unix: float | None = None
        self._started_mono: float | None = None

    @property
    def active_dir(self) -> str | None:
        return self._active_dir

    def active(self) -> dict | None:
        """The in-flight capture ({dir, started_unix, running_s}), or
        None — the info the HTTP 409 carries so an operator can tell a
        forgotten capture from a concurrent one."""
        import time

        with self._lock:
            if self._active_dir is None:
                return None
            return {
                "dir": self._active_dir,
                "started_unix": round(self._started_unix, 3),
                "running_s": round(time.monotonic() - self._started_mono, 1),
            }

    def start(self, log_dir: str) -> None:
        import time

        import jax.profiler

        with self._lock:
            if self._active_dir is not None:
                raise ProfilerError(
                    f"a jax profiler capture is already running: writing "
                    f"to {self._active_dir} for "
                    f"{time.monotonic() - self._started_mono:.0f}s — POST "
                    f"/profile/stop to finish it first (JAX's profiler "
                    f"is process-global; one capture at a time)"
                )
            jax.profiler.start_trace(log_dir)
            self._active_dir = log_dir
            self._started_unix = time.time()
            self._started_mono = time.monotonic()

    def stop(self) -> str:
        """Stop the capture; returns the directory the trace was written to.

        The handle resyncs even when stop_trace fails mid-write (full disk,
        unwritable dir): JAX's session is torn down either way, so keeping
        _active_dir set would wedge start/stop with 409s until restart.
        """
        import jax.profiler

        with self._lock:
            if self._active_dir is None:
                raise ProfilerError("profiler is not capturing")
            out, self._active_dir = self._active_dir, None
            self._started_unix = None
            self._started_mono = None
            jax.profiler.stop_trace()
            return out
