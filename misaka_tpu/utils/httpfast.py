"""Minimal HTTP/1.1 request parsing for the serving plane.

The stock BaseHTTPRequestHandler routes every request's headers through
email.feedparser — ~100us of pure Python per request, which at
64-keep-alive-client load was among the largest server-side costs (the
GIL is the serving plane's real budget).  `fast_parse_request` reads the
request line + headers with a tight loop into a dict, honoring the stock
limits (65536-byte lines, 100 headers) and keep-alive semantics.

Stdlib-only on purpose: the frontend worker processes
(runtime/frontends.py) import this without pulling jax — a frontend's
whole job is to stay a lean GIL of its own.
"""

from __future__ import annotations


class FastHeaders:
    """Case-insensitive header lookup over a plain dict — the minimal
    stand-in for email.message.Message that the serving-plane routes use
    (they only ever call .get)."""

    __slots__ = ("_d",)

    def __init__(self, d: dict[str, str]):
        self._d = d

    def get(self, name: str, default=None):
        return self._d.get(name.lower(), default)

    def __contains__(self, name: str) -> bool:
        return name.lower() in self._d

    def items(self):
        return self._d.items()


def fast_parse_request(handler):
    """Parse handler.raw_requestline + headers from handler.rfile.

    Returns True when it parsed the request (handler.command/path/
    headers/close_connection are set), False to fall back to the stock
    parser (odd request lines, HTTP/0.9 — shapes where the canonical
    stdlib error handling matters more than speed), or None when it
    already ANSWERED an error (431) and the caller must not dispatch.
    Falling back is impossible once header bytes are consumed, so the
    fast path decides on the REQUEST LINE alone; everything after that
    is handled here.
    """
    line = handler.raw_requestline.decode("latin-1")
    words = line.split()
    if len(words) != 3:
        return False  # HTTP/0.9 or malformed: stock parser owns the shape
    command, path, version = words
    if version not in ("HTTP/1.1", "HTTP/1.0"):
        return False
    headers: dict[str, str] = {}
    while True:
        hline = handler.rfile.readline(65537)
        if len(hline) > 65536:
            handler.requestline = line.rstrip("\r\n")
            handler.command, handler.path = command, path
            handler.request_version = version
            handler.close_connection = True
            handler.send_error(431, "Line too long")
            return None  # answered; the caller must not dispatch
        if hline in (b"\r\n", b"\n", b""):
            break
        if len(headers) >= 100:
            handler.requestline = line.rstrip("\r\n")
            handler.command, handler.path = command, path
            handler.request_version = version
            handler.close_connection = True
            handler.send_error(431, "Too many headers")
            return None
        key, sep, value = hline.partition(b":")
        if not sep:
            continue  # ignore junk lines (lenient, like the email parser)
        headers[key.strip().decode("latin-1").lower()] = (
            value.strip().decode("latin-1")
        )
    handler.command = command
    handler.path = path
    handler.request_version = version
    handler.requestline = line.rstrip("\r\n")
    handler.headers = FastHeaders(headers)
    conntype = headers.get("connection", "").lower()
    if version == "HTTP/1.0":
        handler.close_connection = "keep-alive" not in conntype
    else:
        handler.close_connection = "close" in conntype
    if headers.get("expect", "").lower() == "100-continue" \
            and version == "HTTP/1.1":
        # the stock handle_expect_100 handshake (headers are already
        # consumed here, so falling back to the stock parser is not an
        # option; mimic it exactly)
        handler.send_response_only(100)
        handler.end_headers()
    return True
