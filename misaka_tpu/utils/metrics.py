"""Dependency-free Prometheus metrics plane (text exposition v0.0.4).

The reference's only observability was stdlib log lines (SURVEY.md §5) and
our /status is a point-in-time gauge snapshot — the r4→r6 serving
regressions (scan-compact at 0.16-0.34M/s while the native tier sat idle)
were only discoverable by re-running bench.py.  This module is the
production metrics plane those rounds lacked: cumulative counters, latency
histograms, and live gauges that a scraper (and bench.py itself) reads
from a running server at GET /metrics.

Three metric kinds, deliberately small (no client_library dependency —
the container must not need a pip install):

  * Counter    — monotonically increasing float; inc(amount>=0).
  * Gauge      — settable value, OR a zero-hot-path-cost callback read at
                 scrape time (`set_function`, weakref-friendly): queue
                 depths and pool fill ratios cost nothing per iteration.
  * Histogram  — fixed log-spaced buckets (`log_buckets`), cumulative
                 `_bucket{le=...}` + `_sum` + `_count` rendering.

All metrics are thread-safe (one lock per child — the device loop, HTTP
handler threads, and the native pool all write concurrently) and support
labels (`labels(route="/compute")` returns a memoized child).  Helper
constructors (`counter`/`gauge`/`histogram`) are get-or-create against the
process-global REGISTRY: masters and servers are created freely in tests
and benches, and re-construction must accumulate into the same process
series (standard Prometheus process semantics), not raise or fork state.

`parse_text` + `delta` close the loop: tests validate every rendered line
through the same parser bench.py uses to embed before/after scrape deltas
in its artifact, so a perf capture carries its own telemetry.
"""

from __future__ import annotations

import bisect
import math
import os
import re
import threading

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class MetricError(ValueError):
    """Invalid metric construction or use (bad name, label mismatch,
    duplicate registration under a different shape)."""


def log_buckets(lo: float, hi: float, per_decade: int = 3) -> tuple[float, ...]:
    """Log-spaced bucket upper bounds from `lo` until `hi` is covered.

    per_decade=3 gives the 1/2.2/4.6 pattern (10^(1/3) ratio); values are
    rounded to 4 significant digits so rendered `le` labels stay stable
    across platforms.  +Inf is implicit (the Histogram adds it).
    """
    if not (0 < lo < hi):
        raise MetricError(f"need 0 < lo < hi, got ({lo}, {hi})")
    if per_decade < 1:
        raise MetricError(f"per_decade must be >= 1, got {per_decade}")
    ratio = 10.0 ** (1.0 / per_decade)
    out: list[float] = []
    v = float(lo)
    # hi * (1+eps): float accumulation must not drop the top bucket
    while v <= hi * (1.0 + 1e-9):
        out.append(float(f"{v:.4g}"))
        v *= ratio
    return tuple(out)


def pow2_buckets(lo: int, hi: int) -> tuple[float, ...]:
    """Power-of-two bucket bounds (base-2 log spacing) — the natural grid
    for occupancy/size histograms (batch slots, queue depths)."""
    if not (0 < lo <= hi):
        raise MetricError(f"need 0 < lo <= hi, got ({lo}, {hi})")
    out, v = [], lo
    while v <= hi:
        out.append(float(v))
        v *= 2
    return tuple(out)


# Default duration buckets: 10us .. 10s, 3 per decade.  The serve paths
# span ~us (native pool chunk) to ~s (XLA autogrow compile), so one fixed
# grid serves every duration histogram (fixed buckets = aggregatable).
DURATION_BUCKETS = log_buckets(1e-5, 10.0)


def tenant_label_budget() -> int:
    """MISAKA_USAGE_LABEL_MAX (default 64): the ONE per-tenant cardinality
    cap shared by the whole health plane — usage ledger accounts, SLO
    windows and overrides, and every program-labeled metric series."""
    return int(os.environ.get("MISAKA_USAGE_LABEL_MAX", "") or 64)


def capped_label(existing, label: str, budget: int, exempt=()) -> str:
    """Resolve `label` against a cardinality budget: once `existing`
    (any container supporting `in`/`len`) already tracks `budget`
    distinct labels, a NEW label collapses to "other" — existing labels,
    "other" itself, and `exempt` members always resolve verbatim.

    MUST be called under the lock guarding `existing`, and deliberately
    never recurses or re-locks: the usage ledger and the SLO windows each
    independently grew this logic with a recursive "other" resolution
    that self-deadlocked their non-reentrant module locks — this helper
    is the single shared copy."""
    if label == "other" or label in existing or label in exempt:
        return label
    if len(existing) >= budget:
        return "other"
    return label


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if v != v:  # NaN
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _series(name: str, labelnames: tuple, labelvalues: tuple) -> str:
    if not labelnames:
        return name
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"'
        for k, v in zip(labelnames, labelvalues)
    )
    return f"{name}{{{inner}}}"


class _Child:
    def __init__(self):
        self._lock = threading.Lock()


class _CounterChild(_Child):
    def __init__(self):
        super().__init__()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise MetricError(f"counters only go up (inc {amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _GaugeChild(_Child):
    def __init__(self):
        super().__init__()
        self._value = 0.0
        self._fn = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def set_function(self, fn) -> None:
        """Read `fn()` at scrape time instead of a stored value — the
        zero-hot-path-cost gauge (queue depths, fill ratios).  The callback
        must be cheap and non-blocking; exceptions fall back to the last
        stored value (a scrape must never 500 on a dying master)."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            stored = self._value
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return stored
        return stored


class _HistogramChild(_Child):
    def __init__(self, uppers: tuple):
        super().__init__()
        self._uppers = uppers
        self._counts = [0] * (len(uppers) + 1)  # + the +Inf bucket
        self._sum = 0.0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self._uppers, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value

    def snapshot(self):
        with self._lock:
            return list(self._counts), self._sum


class _Metric:
    kind = "untyped"
    _child_cls = _Child

    def __init__(self, name: str, help: str, labelnames: tuple = ()):
        if not _NAME_RE.match(name):
            raise MetricError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise MetricError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple, _Child] = {}
        self._lock = threading.Lock()
        if not self.labelnames:
            # eager default child: unlabeled metrics render 0 before any
            # traffic, so a fresh scrape already shows the full catalog
            self._children[()] = self._new_child()

    def _new_child(self):
        return self._child_cls()

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise MetricError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}"
            )
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child

    def _default(self):
        if self.labelnames:
            raise MetricError(f"{self.name} has labels; use .labels(...)")
        return self._children[()]

    def _items(self):
        with self._lock:
            return sorted(self._children.items())

    def prune(self, predicate) -> None:
        """Drop labeled children the predicate (labels-dict -> bool)
        matches: a series whose label set no longer exists must DISAPPEAR
        from the scrape, not freeze at its last value (e.g. the burn-rate
        series of a replaced per-program SLO objective)."""
        with self._lock:
            stale = [
                k for k in self._children
                if k and predicate(dict(zip(self.labelnames, k)))
            ]
            for k in stale:
                del self._children[k]

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for key, child in self._items():
            lines.append(
                f"{_series(self.name, self.labelnames, key)} "
                f"{_fmt(child.value)}"
            )
        return lines


class Counter(_Metric):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(_Metric):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set_function(self, fn) -> None:
        self._default().set_function(fn)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=DURATION_BUCKETS):
        b = tuple(float(x) for x in buckets)
        if not b or any(y <= x for x, y in zip(b, b[1:])):
            raise MetricError(f"{name}: buckets must strictly increase: {b}")
        if b[-1] == math.inf:
            b = b[:-1]  # +Inf is implicit
        self.buckets = b
        super().__init__(name, help, labelnames)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for key, child in self._items():
            counts, total = child.snapshot()
            cum = 0
            for upper, c in zip(self.buckets + (math.inf,), counts):
                cum += c
                series = _series(
                    f"{self.name}_bucket",
                    self.labelnames + ("le",),
                    key + (_fmt(upper),),
                )
                lines.append(f"{series} {cum}")
            lines.append(
                f"{_series(self.name + '_sum', self.labelnames, key)} "
                f"{_fmt(total)}"
            )
            lines.append(
                f"{_series(self.name + '_count', self.labelnames, key)} {cum}"
            )
        return lines


class Registry:
    """A namespace of metrics; render() is the GET /metrics body."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def get_or_create(self, cls, name, help, labelnames=(), **kw) -> _Metric:
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != labelnames:
                    raise MetricError(
                        f"{name} already registered as {existing.kind} with "
                        f"labels {existing.labelnames}"
                    )
                if cls is Histogram and "buckets" in kw:
                    want = tuple(float(x) for x in kw["buckets"])
                    if want and want[-1] == math.inf:
                        want = want[:-1]
                    if existing.buckets != want:
                        raise MetricError(
                            f"{name} already registered with buckets "
                            f"{existing.buckets}"
                        )
                return existing
            metric = cls(name, help, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def all_metrics(self) -> list:
        """Every registered metric (the embedded TSDB's collection walk —
        utils/tsdb.py reads values through each metric's own child locks,
        so only the dict copy needs this registry lock)."""
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n" if lines else ""


REGISTRY = Registry()


def counter(name, help, labelnames=(), registry=None) -> Counter:
    return (registry or REGISTRY).get_or_create(Counter, name, help, labelnames)


def gauge(name, help, labelnames=(), registry=None) -> Gauge:
    return (registry or REGISTRY).get_or_create(Gauge, name, help, labelnames)


def histogram(
    name, help, labelnames=(), buckets=DURATION_BUCKETS, registry=None
) -> Histogram:
    return (registry or REGISTRY).get_or_create(
        Histogram, name, help, labelnames, buckets=buckets
    )


def render(registry=None) -> str:
    return (registry or REGISTRY).render()


# --- histogram estimation math (shared with the SLO windows) ----------------


def quantile_from_buckets(uppers, counts, q: float) -> float:
    """Estimate the q-quantile (q in [0, 1]) from cumulative-style bucket
    data: `uppers` are the bucket upper bounds (ascending, +Inf implicit),
    `counts` the PER-BUCKET (non-cumulative) counts, len(uppers) + 1 long.

    Linear interpolation inside the straddling bucket (the Prometheus
    histogram_quantile convention): the first bucket interpolates from 0,
    and a quantile landing in the +Inf bucket returns the last finite
    bound (the estimate saturates — there is no upper edge to lerp to).
    Returns 0.0 when there are no observations.  Reused by utils/slo.py's
    sliding windows, so its accuracy is pinned by tests/test_metrics.py.
    """
    if not 0.0 <= q <= 1.0:
        raise MetricError(f"quantile must be in [0, 1], got {q}")
    if len(counts) != len(uppers) + 1:
        raise MetricError(
            f"need len(uppers)+1 counts, got {len(counts)} for "
            f"{len(uppers)} bounds"
        )
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= rank:
            if i >= len(uppers):  # the +Inf bucket: saturate
                return float(uppers[-1]) if uppers else 0.0
            lo = float(uppers[i - 1]) if i > 0 else 0.0
            hi = float(uppers[i])
            frac = (rank - cum) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        cum += c
    return float(uppers[-1]) if uppers else 0.0


def fraction_over(uppers, counts, threshold: float) -> float:
    """Estimated fraction of observations ABOVE `threshold`, from the same
    per-bucket counts quantile_from_buckets takes.  The bucket straddling
    the threshold contributes linearly (uniform-within-bucket assumption).
    The +Inf bucket counts whole — its observations exceed every finite
    bound, and over-counting an unbounded tail is the conservative error
    for an SLO bad-event estimate.  0.0 with no observations."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    over = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if i >= len(uppers):  # the +Inf bucket
            over += c
            continue
        lo = float(uppers[i - 1]) if i > 0 else 0.0
        hi = float(uppers[i])
        if lo >= threshold:
            over += c
        elif hi > threshold:
            over += c * (hi - threshold) / (hi - lo)
    return over / total


# --- the read side: the same parser for tests and bench deltas -------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)(?:\s+\d+)?$"
)
_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    return float(s)


def parse_text(text: str) -> dict[str, float]:
    """Parse exposition text into {series: value}, where `series` is the
    canonical `name{label="v",...}` string (labels in source order).
    Raises MetricError on any malformed non-comment line — the tests use
    this to assert every rendered line is valid."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise MetricError(f"unparseable exposition line: {line!r}")
        name, labelblob, value = m.groups()
        if labelblob:
            pairs = _PAIR_RE.findall(labelblob)
            # reject junk between pairs (e.g. an unescaped quote)
            rebuilt = ",".join(f'{k}="{v}"' for k, v in pairs)
            if rebuilt != labelblob.rstrip(","):
                raise MetricError(f"unparseable label block: {labelblob!r}")
            series = name + "{" + rebuilt + "}"
        else:
            series = name
        out[series] = _parse_value(value)
    return out


def parse_series(series: str) -> tuple[str, dict[str, str]]:
    """Split a parse_text key into (name, {label: value})."""
    if "{" not in series:
        return series, {}
    name, blob = series.split("{", 1)
    blob = blob.rstrip("}")
    return name, {k: _unescape_label(v) for k, v in _PAIR_RE.findall(blob)}


def delta(
    before: dict[str, float],
    after: dict[str, float],
    skip_buckets: bool = True,
) -> dict[str, float]:
    """after-minus-before for every series that moved — the compact
    snapshot bench.py embeds in its artifact.  Histogram buckets are
    dropped by default (the _sum/_count pair carries the signal; buckets
    would triple the artifact for no headline)."""
    out: dict[str, float] = {}
    for series, v in after.items():
        name, _ = parse_series(series)
        if skip_buckets and name.endswith("_bucket"):
            continue
        d = v - before.get(series, 0.0)
        if d:
            out[series] = round(d, 9)
    return out
