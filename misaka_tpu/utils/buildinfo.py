"""Build identity: the `misaka_build_info` gauge + the /status `build`
block — the standard fleet-debugging stamp.

When a fleet of replicas misbehaves, the first question is "which BUILD
is each one running" — version, commit, runtime versions, and (here)
which native components actually loaded.  The Prometheus convention is a
constant `<thing>_build_info` gauge valued 1 whose labels carry the
identity, so `count by (git_sha) (misaka_build_info)` instantly shows a
mixed-version fleet mid-rollout.  The same dict rides /status as the
`build` block for humans.

Everything is computed ONCE and cached: git shells out a single
rev-parse (absent in a deployed image — falls back to
MISAKA_BUILD_SHA, then "unknown"), jax's version is read only if jax is
already imported (this module must not force a multi-second backend
boot on a process that never touched jax), and the native components
report the source hash of the .so each loader would serve.
"""

from __future__ import annotations

import os
import subprocess
import sys

from misaka_tpu import __version__
from misaka_tpu.utils import metrics

_info_cache: dict | None = None


def _git_sha() -> str:
    env = os.environ.get("MISAKA_BUILD_SHA")
    if env:
        return env
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    try:
        out = subprocess.run(
            ["git", "-C", repo, "rev-parse", "--short=12", "HEAD"],
            capture_output=True, text=True, timeout=5,
        )
        sha = out.stdout.strip()
        if out.returncode == 0 and sha:
            return sha
    except (OSError, subprocess.SubprocessError):
        pass
    return "unknown"


def _native_labels() -> dict[str, str]:
    """Source hash per native component when its .so is present and
    current, "absent" otherwise — the provenance tag utils/nativelib.py
    embeds at build time, read from the loader's own source hash."""
    out: dict[str, str] = {}
    try:
        from misaka_tpu.core import cinterp
        from misaka_tpu.utils import textcodec

        for name, lib in (
            ("interp", cinterp._NATIVE),
            ("textcodec", getattr(textcodec, "_NATIVE", None)),
        ):
            if lib is None:
                continue
            try:
                out[name] = (
                    lib._src_hash() if lib._so_matches_src() else "absent"
                )
            except OSError:
                out[name] = "absent"
    except Exception:  # pragma: no cover — identity must never crash boot
        pass
    return out


def info() -> dict:
    """The cached build-identity dict (/status `build` block)."""
    global _info_cache
    if _info_cache is None:
        jax_version = "unloaded"
        mod = sys.modules.get("jax")
        if mod is not None:
            jax_version = getattr(mod, "__version__", "unknown")
        _info_cache = {
            "version": __version__,
            "git_sha": _git_sha(),
            "python": ".".join(str(v) for v in sys.version_info[:3]),
            "jax": jax_version,
            "native": _native_labels(),
        }
    elif _info_cache["jax"] == "unloaded" and "jax" in sys.modules:
        # jax was imported after the first call: upgrade the stamp, and
        # re-stamp the gauge so /metrics and /status keep agreeing
        _info_cache["jax"] = getattr(
            sys.modules["jax"], "__version__", "unknown"
        )
        if _metric_installed:
            install_metric()
    return _info_cache


_metric_installed = False


def install_metric() -> None:
    """Register misaka_build_info (value 1, identity in labels) into the
    default registry — called by make_http_server, so every serving
    process stamps itself.  Re-entrant: a jax-version upgrade (info())
    re-stamps, dropping the stale jax="unloaded" series so the gauge
    never disagrees with the /status build block."""
    global _metric_installed
    i = info()
    native = i["native"]
    g = metrics.gauge(
        "misaka_build_info",
        "Build identity (constant 1; the identity lives in the labels)",
        ("version", "git_sha", "python", "jax", "native_interp"),
    )
    g.prune(lambda kv: kv["jax"] != i["jax"])
    g.labels(
        version=i["version"],
        git_sha=i["git_sha"],
        python=i["python"],
        jax=i["jax"],
        native_interp=native.get("interp", "absent"),
    ).set(1)
    _metric_installed = True
