"""Vectorized decimal text codec for int32 value streams.

The /compute_batch text lane (the reference-shaped client surface,
master.go:197-224) moves millions of integers per request as decimal text.
CPython's per-value paths — `" ".join(map(str, ...))`, `json.dumps` over a
list, `np.array(list_of_str)` — cost 300-900ms per million values and hold
the GIL throughout, which capped round-2's served text throughput at 859k/s.

This module formats and parses entirely in numpy array ops (a handful of C
passes over the byte stream, GIL mostly released):

- `ints_to_dec(arr, sep, zero_pad=False)` — int -> decimal tokens joined by
  one separator byte.  Tokens are right-aligned in fixed-width fields (the
  width of the widest value in the call), padded with spaces — legal JSON
  whitespace, so a comma-joined stream drops straight into a JSON array and
  ordinary json.loads clients decode it unchanged.  `zero_pad=True` pads
  with '0' instead (NOT legal JSON, fine for form bodies): it skips all
  leading-zero masking and is ~2x faster — the client-request fast path.
- `dec_to_ints(text)` — separator-joined decimal text -> int32.  When the
  stream is fixed-stride (everything `ints_to_dec` emits), a reshape-based
  parser handles it in ~10 vector passes; anything ragged falls back to a
  general parser.  Malformed input raises ValueError either way.

Both directions carry a native single-pass C++ fast path
(native/textcodec.cpp, loaded via utils/nativelib.py, same degrade-to-
Python contract as the native assembler): ~10x the numpy passes and the
GIL is released for the whole call, so serving threads overlap with the
codec.  `MISAKA_NATIVE_CODEC=0` forces the numpy path (A/B and fallback
coverage); `=1` requires native (raises when no toolchain).  Byte-exact
equivalence is pinned by tests/test_textcodec.py's differential lane.
"""

from __future__ import annotations

import ctypes
import os
import warnings

import numpy as np

from misaka_tpu.utils.nativelib import NativeLib

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _configure(lib: ctypes.CDLL) -> None:
    lib.misaka_fmt_i32.restype = ctypes.c_int64
    lib.misaka_fmt_i32.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_uint8,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    lib.misaka_parse_i32.restype = ctypes.c_int64
    lib.misaka_parse_i32.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
    ]


_NATIVE = NativeLib(
    os.path.join(_REPO_ROOT, "native", "textcodec.cpp"),
    os.path.join(_REPO_ROOT, "native", "libmisaka_textcodec.so"),
    _configure,
)


def _native_lib() -> ctypes.CDLL | None:
    """The codec .so per MISAKA_NATIVE_CODEC: auto (default), 0=off, 1=require."""
    knob = os.environ.get("MISAKA_NATIVE_CODEC", "").strip()
    if knob == "0":
        return None
    lib = _NATIVE.load()
    if lib is None and knob == "1":
        raise RuntimeError("MISAKA_NATIVE_CODEC=1 but no native codec (no g++?)")
    return lib


def native_available() -> bool:
    return _NATIVE.available()

_SEPS = (ord(" "), ord(","), ord("+"), ord("\t"), ord("\n"), ord("\r"))
_SEP_TABLE = bytes.maketrans(b",+\t\n\r", b"     ")
_IS_SEP = np.zeros(256, bool)
_IS_SEP[list(_SEPS)] = True  # byte -> is-separator LUT (np.isin sorts; this gathers)

# np.fromstring(sep=...) is the one C-speed numpy text parser; it warns
# DeprecationWarning per call, so install ONE narrow module-scoped filter at
# import instead of mutating the global filter list per call (catch_warnings
# is not thread-safe under a threading HTTP server).
_FROMSTRING = getattr(np, "fromstring", None)
if _FROMSTRING is not None:
    warnings.filterwarnings(
        "ignore", message=".*fromstring.*", category=DeprecationWarning
    )


def ints_to_dec(arr: np.ndarray, sep: bytes = b" ", zero_pad: bool = False) -> bytes:
    """Format an int array as separator-joined decimal tokens (no leading or
    trailing separator), in O(max_digits) vectorized passes."""
    if len(sep) != 1:
        raise ValueError("sep must be a single byte")
    a = np.asanyarray(arr)
    n = a.size
    if n == 0:
        return b""
    if a.dtype == np.int32:
        lib = _native_lib()
        if lib is not None:
            src = np.ascontiguousarray(a.ravel())
            # width <= 11 (10 digits + sign column) -> field+sep <= 12 bytes
            out = np.empty(12 * n, np.uint8)
            rc = lib.misaka_fmt_i32(
                src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n,
                sep[0], int(zero_pad),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), out.size,
            )
            if rc >= 0:
                return out[:rc].tobytes()
    v = a.astype(np.int64).ravel()
    neg = v < 0
    mag = np.where(neg, -v, v).astype(np.uint32)  # int32 min fits unsigned

    nd_max = len(str(int(mag.max())))  # widest token this call, 1..10
    # digit columns in display order (most-significant first), no reversal
    pows = (10 ** np.arange(nd_max - 1, -1, -1, dtype=np.int64)).astype(np.uint32)
    digits = (mag[:, None] // pows[None, :]) % 10  # [N, nd_max] uint32

    width = nd_max + 1  # one extra column so a full-width token fits its '-'
    field = np.empty((n, width + 1), np.uint8)  # +1 separator column
    field[:, width] = sep[0]
    if zero_pad:
        # every digit column prints; sign column is '0' or '-'
        field[:, 1:width] = digits.astype(np.uint8) + ord("0")
        field[:, 0] = np.where(neg, np.uint8(ord("-")), np.uint8(ord("0")))
    else:
        pad = sep[0] if sep in (b" ", b"+") else ord(" ")
        # ndig via binary search over the 9 power-of-ten thresholds — cheaper
        # than a [N, nd_max] leading-zero mask reduction
        ndig = (
            np.searchsorted(_THRESHOLDS[: nd_max - 1], mag, side="right") + 1
        ).astype(np.int64)
        # column j (0-based in the digit block) displays iff it is within the
        # token's ndig rightmost columns: j >= nd_max - ndig
        col = np.arange(nd_max, dtype=np.int64)
        show = col[None, :] >= (nd_max - ndig)[:, None]
        field[:, 1:width] = np.where(
            show, (digits + ord("0")).astype(np.uint8), np.uint8(pad)
        )
        field[:, 0] = pad
        # '-' sits immediately left of the top digit
        rows = np.nonzero(neg)[0]
        field[rows, width - 1 - ndig[rows]] = ord("-")
    return field.tobytes()[:-1]  # drop the trailing separator


_THRESHOLDS = (10 ** np.arange(1, 10, dtype=np.int64)).astype(np.uint32)


def _parse_fixed(raw: np.ndarray) -> np.ndarray | None:
    """Fixed-stride parse: tokens of equal width, one separator byte between.

    Returns None on ANY anomaly — wrong grid, unexpected chars, structural
    problems — so the general parser below stays the single arbiter of what
    is an error vs. merely ragged-but-valid (e.g. a trailing separator)."""
    # Everything hot below runs on the CONTIGUOUS 1-D stream; column slices
    # of a [N, stride] view are strided, and numpy's strided loops run ~10x
    # slower than its contiguous SIMD paths, so 2-D work is confined to a
    # few narrow bool checks on small contiguous copies.
    is_digit = (raw >= ord("0")) & (raw <= ord("9"))
    is_minus = raw == ord("-")
    # six explicit compares beat a 256-entry LUT gather ~6x here (numpy's
    # fancy-index path is not SIMD)
    is_sep = (
        (raw == ord(" ")) | (raw == ord(",")) | (raw == ord("+"))
        | (raw == ord("\t")) | (raw == ord("\n")) | (raw == ord("\r"))
    )
    if not (is_digit | is_minus | is_sep).all():
        return None  # a char neither token nor separator/pad class
    tok = is_digit | is_minus
    first_tok = int(np.argmax(tok))
    if not tok[first_tok]:
        return None  # no token chars at all
    # the first separator AFTER the first token char ends the first field —
    # this sees through leading pad (pad bytes are separator-class)
    rel = int(np.argmax(is_sep[first_tok:]))
    if not is_sep[first_tok + rel]:
        return None  # single token, no separator
    stride = first_tok + rel + 1
    if (raw.size + 1) % stride:
        return None
    if stride - 1 > 11:
        # wider than any int32 token ("-2147483648"): necessarily
        # out-of-range or heavily padded — the general parser arbitrates
        return None
    n = (raw.size + 1) // stride

    def grid(flags, fill):
        """[N, stride] contiguous bool: `flags` plus one synthesized tail."""
        out = np.empty(raw.size + 1, bool)
        out[:-1] = flags
        out[-1] = fill
        return out.reshape(n, stride)

    sep2 = grid(is_sep, True)
    if not sep2[:, -1].all():
        return None  # separators not on the stride grid
    dig2 = grid(is_digit, False)
    if not dig2[:, -2].all():
        return None  # every token must end in a digit at the field edge
    # structure: pad* ['-'] digit+ — token chars must form a suffix of each
    # field.  Every legal field contributes exactly ONE token->nontoken
    # transition in the flat stream (its last digit into its separator, via
    # the two column checks above), so a total transition count of n is
    # equivalent to the full per-field monotonicity check — in two
    # contiguous 1-D passes instead of strided 2-D ones.
    if int(np.count_nonzero(tok[:-1] & ~tok[1:])) + int(tok[-1]) != n:
        return None
    min_rows = np.nonzero(is_minus)[0] // stride  # sparse: O(#negatives)
    if min_rows.size:
        tok2 = grid(tok, False)
        m2 = grid(is_minus, False)
        if (m2[:, 1:-1] & tok2[:, :-2]).any():
            return None  # '-' mid-token
    # magnitude via one BLAS matvec: tokens are right-aligned, so column j
    # always weighs 10^(stride-2-j); pads/'-' are mapped to '0' and the
    # constant ASCII offset is subtracted once at the end.  float64 is
    # exact out to 2^53, far past the 10-digit int32 range.
    dchars = np.empty(raw.size + 1, np.uint8)
    dchars[:-1] = np.where(is_digit, raw, np.uint8(ord("0")))
    dchars[-1] = ord("0")
    d = dchars.astype(np.float64).reshape(n, stride)
    val = d[:, :-1] @ (10.0 ** np.arange(stride - 2, -1, -1)) \
        - _ASCII_OFFSET[stride - 1]
    if min_rows.size:
        neg = np.zeros(n, bool)
        neg[min_rows] = True
        if (val > np.where(neg, 2.0**31, 2.0**31 - 1)).any():
            return None  # out of int32 range: the general path re-checks
        val = np.where(neg, -val, val)
    elif (val > 2.0**31 - 1).any():
        return None
    return val.astype(np.int32)


# ord('0') * (10^w - 1)/9: what the matvec over '0'-padded ASCII bytes
# overshoots the digit value by, per token width
_ASCII_OFFSET = [ord("0") * (10**w - 1) // 9 for w in range(12)]


def dec_to_ints(text: bytes | str) -> np.ndarray:
    """Parse whitespace/comma/plus-separated decimal tokens to int32.

    Raises ValueError on malformed input (non-numeric tokens or characters
    outside [0-9 space tab newline , + -])."""
    if isinstance(text, str):
        text = text.encode("ascii", errors="strict")
    raw = np.frombuffer(text, np.uint8)
    if raw.size == 0:
        return np.empty((0,), np.int32)
    lib = _native_lib()
    if lib is not None:
        if not isinstance(text, bytes):  # bytearray/memoryview: c_char_p wants bytes
            text = bytes(text)
        # every token but the last needs >= 1 separator byte, so
        # (len+1)//2 bounds the token count — -2 (capacity) is unreachable
        out = np.empty((raw.size + 1) // 2, np.int32)
        rc = lib.misaka_parse_i32(
            text, raw.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), out.size,
        )
        if rc < 0:
            raise ValueError("cannot parse values")
        return out[:rc].copy()
    fixed = _parse_fixed(raw)
    if fixed is not None:
        return fixed

    # --- general (ragged) path --------------------------------------------
    is_digit = (raw >= ord("0")) & (raw <= ord("9"))
    is_sep = _IS_SEP[raw]
    is_minus = raw == ord("-")
    if not (is_digit | is_sep | is_minus).all():
        raise ValueError("cannot parse values")
    tok = is_digit | is_minus
    starts = tok & ~np.concatenate(([False], tok[:-1]))
    # '-' legality: must be a token start and followed by a digit
    nxt_digit = np.concatenate((is_digit[1:], [False]))
    if (is_minus & (~starts | ~nxt_digit)).any():
        raise ValueError("cannot parse values")
    n_tokens = int(starts.sum())
    if n_tokens == 0:
        return np.empty((0,), np.int32)
    cleaned = text.translate(_SEP_TABLE).decode("ascii")
    try:
        if _FROMSTRING is not None:
            out = _FROMSTRING(cleaned, dtype=np.int64, sep=" ")
        else:  # np.fromstring removed (future numpy)
            out = np.array(cleaned.split(), dtype=np.int64)
    except OverflowError:  # token beyond int64 in the fallback path
        raise ValueError("cannot parse values") from None
    # np.fromstring stops silently at anything it can't parse; the charset
    # check above plus a token-count match makes that loud instead.
    if out.size != n_tokens:
        raise ValueError("cannot parse values")
    if ((out > 2**31 - 1) | (out < -(2**31))).any():
        raise ValueError("cannot parse values")
    return out.astype(np.int32)
