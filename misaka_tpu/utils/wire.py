"""Binary client wire protocol v1 for /compute_raw (ISSUE 12 layer 3).

The text lanes pay decimal encode/parse per value; the legacy raw lane is
already little-endian int32 both ways but headerless, so the server can
only trust Content-Length framing and the client cannot negotiate.  This
module defines the headered binary protocol both sides speak by default:

    request:  POST /compute_raw
              Content-Type: application/x-misaka-i32
              body = 12-byte header + count * int32 (little-endian)
    response: negotiated by Accept: application/x-misaka-i32 —
              same header framing + raw int32 outputs

    header:   <IHHI  magic 0x314B534D ("MSK1"), version, flags, count

Negotiation is strictly additive: a body without the Content-Type is the
legacy headerless raw lane (byte-identical to the shipped behavior), and a
request without the Accept gets the legacy raw response.  The header buys
framing validation (count vs Content-Length — a truncated proxy body is a
typed 400, not silently-computed garbage) and a place for future flags;
the payload stays the zero-copy np.frombuffer shape on both sides.

Stdlib-only: the jax-free frontend workers and the pure-stdlib client both
import this.

LOCKSTEP: native/msk_frame.hpp reimplements this codec (header layout,
magic/version, and the four WireError sentences, byte for byte) for the
C++ edge tier — tests/test_native_edge.py's parity corpus pins the two
together; change either side only with its twin.
"""

from __future__ import annotations

import struct

MAGIC = 0x314B534D  # b"MSK1" read as little-endian uint32
VERSION = 1
CONTENT_TYPE = "application/x-misaka-i32"
_HDR = struct.Struct("<IHHI")  # magic, version, flags, count
HEADER_LEN = _HDR.size  # 12


class WireError(ValueError):
    """Malformed binary-protocol body (bad magic/version/count)."""


def header(count: int, flags: int = 0) -> bytes:
    return _HDR.pack(MAGIC, VERSION, flags, count)


def pack(payload: bytes, flags: int = 0) -> bytes:
    """Frame one raw little-endian int32 payload."""
    if len(payload) % 4:
        raise WireError("payload must be whole int32 values")
    return _HDR.pack(MAGIC, VERSION, flags, len(payload) // 4) + payload


def unpack(body: bytes) -> bytes:
    """Validate the header and return the raw int32 payload bytes.

    Raises WireError on anything malformed — the server answers a typed
    400 instead of computing on garbage."""
    if len(body) < HEADER_LEN:
        raise WireError(
            f"body of {len(body)} bytes is shorter than the "
            f"{HEADER_LEN}-byte header"
        )
    magic, version, _flags, count = _HDR.unpack_from(body)
    if magic != MAGIC:
        raise WireError(f"bad magic 0x{magic:08x} (expected 0x{MAGIC:08x})")
    if version != VERSION:
        raise WireError(f"unsupported protocol version {version}")
    payload = body[HEADER_LEN:]
    if len(payload) != count * 4:
        raise WireError(
            f"header promises {count} values but body carries "
            f"{len(payload)} payload bytes"
        )
    return payload


def is_binary(content_type: str | None) -> bool:
    """Does this Content-Type select the headered binary request form?"""
    return bool(content_type) and content_type.split(";", 1)[0].strip() \
        == CONTENT_TYPE


def accepts_binary(accept: str | None) -> bool:
    """Does this Accept header negotiate the headered binary response?"""
    return bool(accept) and CONTENT_TYPE in accept
