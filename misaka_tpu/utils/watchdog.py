"""Regression watchdog: a rule engine over the embedded TSDB.

The SLO engine (utils/slo.py) pages on declared objectives; nothing
watches for REGRESSIONS against the service's own recent past — "p99 is
2x its 1h median and has been for 5 minutes", "the canary has failed
every probe since the roll", "replicas are restarting faster than
deploys explain".  This module closes that gap: rules evaluate over the
retained history (utils/tsdb.py), and findings feed the EXISTING alert
surface — a block on ``GET /debug/alerts`` and the same ``degraded``
flag on ``/healthz`` the supervisor/SLO machinery raises — not a
parallel one.

Rule grammar (``MISAKA_WATCHDOG``, comma-separated; ``0`` disables, unset
arms the defaults below)::

    MISAKA_WATCHDOG="p99-drift=misaka_http_request_duration_seconds:p99>2x@1h for 300s ->warning,
                     canary=misaka_canary_success{tier=full}<1 for 20s ->page"

Each entry: ``[name=]series[{label=value}] OP threshold [for SUSTAINs]
[->severity]`` (the rule name's separator is ``=`` because series names
themselves contain ``:`` for the derived quantile forms) where

  * ``series``  — a TSDB series name (including derived ``:p50``/
                  ``:p99``/``:rate`` names), with an optional single
                  ``{label=value}`` filter; multiple matching series are
                  evaluated together (worst wins).
  * ``OP``      — ``>`` or ``<`` against either an absolute number, or
                  the ratio form ``Nx@WINDOW`` ("N times the series' own
                  median over the trailing WINDOW") — the regression
                  shape.  Ratio rules stay silent until the baseline
                  window holds ``MISAKA_WATCHDOG_MIN_POINTS`` (default 5)
                  points: no baseline, no verdict.
  * ``for N[s|m|h]`` — the condition must hold continuously this long
                  before the rule fires (monotonic clock), and clear
                  continuously this long before it resets.  Default 0.
  * ``->severity`` — ``warning`` (default) or ``page``; a paging rule
                  raises /healthz ``degraded`` exactly like an SLO page.

The current value a rule compares is the mean over the trailing
``MISAKA_WATCHDOG_RECENT_S`` (default 60) seconds of stage-0 points.

Evaluation rides the TSDB collector's tick hook — no second thread, no
second clock, and rules always see freshly collected points.
Stdlib-only; findings carry exemplar trace IDs when the flight recorder
has them (attached at the /debug/alerts route, next to the SLO pages').
"""

from __future__ import annotations

import os
import re
import threading
import time

from misaka_tpu.utils import tsdb as tsdb_mod

SEVERITIES = ("ok", "warning", "page")

_RULE_RE = re.compile(
    r"^(?:(?P<name>[A-Za-z0-9._-]+)=(?=[a-zA-Z_]))?"
    r"(?P<series>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<lk>[a-zA-Z_][a-zA-Z0-9_]*)=(?P<lv>[^}]*)\})?"
    r"\s*(?P<op>[<>])\s*"
    r"(?P<value>\d+(?:\.\d+)?)"
    r"(?:x@(?P<baseline>[0-9.]+[smh]?))?"
    r"(?:\s+for\s+(?P<sustain>[0-9.]+[smh]?))?"
    r"\s*(?:->\s*(?P<severity>warning|page))?$"
)


class WatchdogSpecError(ValueError):
    """Malformed MISAKA_WATCHDOG rule spec."""


class Rule:
    """One parsed rule + its firing state."""

    __slots__ = ("name", "series", "labels", "op", "threshold", "factor",
                 "baseline_s", "sustain_s", "severity", "spec",
                 "state", "_bad_since", "_ok_since", "last_value",
                 "last_baseline", "fired_unix")

    def __init__(self, name, series, labels, op, threshold, factor,
                 baseline_s, sustain_s, severity, spec):
        self.name = name
        self.series = series
        self.labels = labels          # {} or single {k: v}
        self.op = op                  # ">" | "<"
        self.threshold = threshold    # absolute (None for ratio rules)
        self.factor = factor          # ratio multiple (None for absolute)
        self.baseline_s = baseline_s  # trailing window for the median
        self.sustain_s = sustain_s
        self.severity = severity
        self.spec = spec
        self.state = "ok"
        self._bad_since: float | None = None   # monotonic
        self._ok_since: float | None = None
        self.last_value: float | None = None
        self.last_baseline: float | None = None
        self.fired_unix: float | None = None

    def payload(self) -> dict:
        out = {
            "rule": self.name,
            "spec": self.spec,
            "series": self.series,
            "state": self.state,
            "severity": self.severity,
        }
        if self.labels:
            out["labels"] = self.labels
        if self.last_value is not None:
            out["value"] = round(self.last_value, 6)
        if self.last_baseline is not None:
            out["baseline"] = round(self.last_baseline, 6)
            out["threshold"] = round(
                self.last_baseline * (self.factor or 1.0), 6
            )
        elif self.threshold is not None:
            out["threshold"] = self.threshold
        if self.state != "ok" and self.fired_unix is not None:
            out["since_unix"] = round(self.fired_unix, 3)
        return out


def parse_spec(text: str) -> list[Rule]:
    rules: list[Rule] = []
    for i, raw in enumerate((text or "").split(",")):
        item = raw.strip()
        if not item:
            continue
        m = _RULE_RE.match(item)
        if not m:
            raise WatchdogSpecError(
                f"cannot parse watchdog rule {item!r} (grammar: "
                f"[name=]series[{{label=value}}] <|> N[x@window] "
                f"[for Ns] [->warning|page])"
            )
        g = m.groupdict()
        factor = baseline_s = threshold = None
        if g["baseline"]:
            factor = float(g["value"])
            baseline_s = tsdb_mod.parse_window(g["baseline"])
            if factor <= 0:
                raise WatchdogSpecError(f"ratio must be > 0 in {item!r}")
        else:
            threshold = float(g["value"])
        sustain_s = tsdb_mod.parse_window(
            g["sustain"], allow_zero=True
        ) if g["sustain"] else 0.0
        labels = {g["lk"]: g["lv"]} if g["lk"] else {}
        rules.append(Rule(
            name=g["name"] or f"rule{i}",
            series=g["series"],
            labels=labels,
            op=g["op"],
            threshold=threshold,
            factor=factor,
            baseline_s=baseline_s,
            sustain_s=sustain_s,
            severity=g["severity"] or "warning",
            spec=item,
        ))
    return rules


def default_rules(interval_s: float) -> list[Rule]:
    """The always-on defaults (MISAKA_WATCHDOG unset): a full-stack
    canary that keeps failing pages; edge p99 doubling over its own
    trailing hour warns; replicas restarting faster than ~4/h warn;
    sustained telemetry-spool loss (TSDB slots or capture records
    dropped, or spool write errors) warns — durable retention that is
    silently shedding its own history is the failure mode the durable
    plane exists to prevent.
    Each stays silent until its series exists and (for the ratio rule)
    a baseline accumulated — so the p99 rule, which watches the
    ENGINE's own HTTP histogram, is simply inert behind a frontend
    tier (compute rides the plane there; the canary rule is the active
    deep-path watchdog in those topologies)."""
    canary_sustain = max(3.0 * interval_s, 15.0)
    return parse_spec(
        f"canary-full=misaka_canary_success{{tier=full}}<1 "
        f"for {canary_sustain:g}s ->page,"
        f"p99-drift=misaka_http_request_duration_seconds:p99"
        f"{{route=/compute_raw}}>2x@1h for 300s ->warning,"
        f"replica-restarts=misaka_fleet_replica_restarts_total"
        f">0.0011 for 300s ->warning,"
        f"tsdb-spool-drops=misaka_tsdb_spool_dropped_total"
        f">0.001 for 300s ->warning,"
        f"capture-spool-drops=misaka_capture_spool_dropped_total"
        f">0.001 for 300s ->warning,"
        f"spool-errors=misaka_spool_errors_total>0.001 for 60s ->warning"
    )


def _median(values: list[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    return vs[n // 2] if n % 2 else (vs[n // 2 - 1] + vs[n // 2]) / 2.0


class Watchdog:
    """Rule state + evaluation (driven by the TSDB tick hook)."""

    def __init__(self, rules: list[Rule], recent_s: float = 60.0,
                 min_points: int = 5):
        self.rules = rules
        self.recent_s = max(0.05, float(recent_s))
        self.min_points = max(1, int(min_points))
        self._lock = threading.Lock()

    def evaluate(self, db) -> None:
        now_mono = time.monotonic()
        with self._lock:
            for rule in self.rules:
                self._evaluate_rule(rule, db, now_mono)

    def _current_value(self, rule: Rule, db) -> float | None:
        """The worst matching series' recent mean (None = no data)."""
        worst = None
        for row in db.query(rule.series, rule.labels, self.recent_s):
            pts = [p[1] for p in row["points"]]
            if not pts:
                continue
            v = sum(pts) / len(pts)
            if worst is None:
                worst = v
            elif rule.op == ">":
                worst = max(worst, v)
            else:
                worst = min(worst, v)
        return worst

    def _baseline(self, rule: Rule, db) -> float | None:
        """Median over the trailing baseline window, recent part
        excluded (the regression must not lift its own baseline)."""
        pts: list[float] = []
        now = time.time()
        for row in db.query(rule.series, rule.labels, rule.baseline_s):
            for t, avg, _mx in row["points"]:
                if now - t > self.recent_s:
                    pts.append(avg)
        if len(pts) < self.min_points:
            return None
        return _median(pts)

    def _evaluate_rule(self, rule: Rule, db, now_mono: float) -> None:
        value = self._current_value(rule, db)
        rule.last_value = value
        if value is None:
            return  # no data: hold the current state, never invent one
        if rule.factor is not None:
            baseline = self._baseline(rule, db)
            rule.last_baseline = baseline
            if baseline is None:
                return  # no baseline yet: silent, not wrong
            threshold = baseline * rule.factor
        else:
            threshold = rule.threshold
        bad = value > threshold if rule.op == ">" else value < threshold
        if bad:
            rule._ok_since = None
            if rule._bad_since is None:
                rule._bad_since = now_mono
            if (now_mono - rule._bad_since >= rule.sustain_s
                    and rule.state == "ok"):
                rule.state = rule.severity
                rule.fired_unix = time.time()
        else:
            rule._bad_since = None
            if rule.state != "ok":
                # clear only after the condition has been good for the
                # same sustain (a flapping series must not strobe alerts)
                if rule._ok_since is None:
                    rule._ok_since = now_mono
                if now_mono - rule._ok_since >= rule.sustain_s:
                    rule.state = "ok"
                    rule.fired_unix = None
                    rule._ok_since = None

    def overall_state(self) -> str:
        worst = "ok"
        with self._lock:
            for rule in self.rules:
                if SEVERITIES.index(rule.state) > SEVERITIES.index(worst):
                    worst = rule.state
        return worst

    def payload(self) -> dict:
        with self._lock:
            rules = [r.payload() for r in self.rules]
        return {
            "enabled": True,
            "state": self.overall_state(),
            "recent_s": self.recent_s,
            "min_points": self.min_points,
            "rules": rules,
        }


# --- the process-global instance --------------------------------------------

_lock = threading.Lock()
_watchdog: Watchdog | None = None
_spec_error: str | None = None


def enabled(environ=os.environ) -> bool:
    return environ.get("MISAKA_WATCHDOG", "1") != "0"


def get() -> Watchdog | None:
    return _watchdog


def ensure_started(environ=os.environ) -> Watchdog | None:
    """Build the process watchdog from the env and hook it onto the
    TSDB collector; None when either it or the TSDB is disabled."""
    global _watchdog, _spec_error
    if not enabled(environ):
        return None
    db = tsdb_mod.ensure_started(environ)
    if db is None:
        return None  # no history, nothing to watch
    with _lock:
        if _watchdog is None:
            spec = environ.get("MISAKA_WATCHDOG", "")
            _spec_error = None
            try:
                rules = parse_spec(spec) if spec else \
                    default_rules(db.interval_s)
            except WatchdogSpecError as e:
                # a typo'd spec must not take down the server — but
                # silently watching nothing would be worse: loud on the
                # alerts payload, defaults stay armed
                _spec_error = f"MISAKA_WATCHDOG={spec!r}: {e}"
                rules = default_rules(db.interval_s)
            _watchdog = Watchdog(
                rules,
                recent_s=tsdb_mod.env_float(
                    environ, "MISAKA_WATCHDOG_RECENT_S", 60.0
                ),
                min_points=int(tsdb_mod.env_float(
                    environ, "MISAKA_WATCHDOG_MIN_POINTS", 5
                )),
            )
        db.add_hook(_watchdog.evaluate)
    return _watchdog


def shutdown() -> None:
    """Drop the global watchdog (tests; the A/B's off side)."""
    global _watchdog, _spec_error
    with _lock:
        db = tsdb_mod.get()
        if db is not None and _watchdog is not None:
            db.remove_hook(_watchdog.evaluate)
        _watchdog = None
        _spec_error = None


def overall_state() -> str | None:
    """The worst rule state, or None while disarmed (the /healthz
    `degraded` integration keys on "page", like the SLO engine's)."""
    w = _watchdog
    return w.overall_state() if w is not None else None


def debug_payload() -> dict:
    """The `watchdog` block on GET /debug/alerts."""
    w = _watchdog
    if w is None:
        return {"enabled": False, "state": "ok", "rules": []}
    out = w.payload()
    if _spec_error:
        out["spec_error"] = _spec_error
    return out
