"""Per-request distributed tracing: trace IDs, span trees, and a bounded
in-memory flight recorder with Perfetto export.

The metrics plane (utils/metrics.py) answers "how is the fleet doing";
nothing before this module could answer "where did THIS slow request
spend its 17 ms" across the serving chain PRs 1-4 built: frontend worker
-> unix-socket compute plane -> ServeBatcher -> device-loop pass ->
native pool / gRPC peer.  This is the Dapper-style answer every serving
stack grows: every request entering any HTTP route gets a trace ID
(honoring an inbound ``X-Misaka-Trace`` header, minting one otherwise)
and a tree of spans with monotonic start/duration, recorded into a ring
of the last N completed traces plus an always-on reservoir of the
slowest K.  The ID crosses every hop — plane frames, gRPC metadata, the
``Server-Timing``/``X-Misaka-Trace`` response headers — and the whole
recorder exports as Chrome trace-event JSON (``GET /debug/perfetto``,
loadable in Perfetto or chrome://tracing) with one "process" per tier,
so a fused pass shows the coalesced requests stacked on it.

Span catalog (the tier is the name's dotted prefix):

  http.parse          request line + headers parsed (fast parser)
  frontend.coalesce   wait in the frontend-local coalescer before its
                      frame was built (runtime/frontends.PlaneClient)
  plane.ship          frontend-side frame round trip over the unix socket
  plane.recv          engine-side frame handling (recv -> outputs sent)
  serve.queue         wait in the serve scheduler before first dispatch
  serve.pass          one fused engine pass serving this request
                      (ServeBatcher) — or the submit+collect window on
                      the direct compute_many/compute_spread lanes
  engine.chunk        one device-loop iteration (tier event: the loop
                      serves many requests at once, so chunks are
                      recorded per tier, not per trace)
  native.tick         one native-pool serve call (tier event, same)
  rpc.<Method>        one outbound gRPC call inside a request scope;
                      the receiving peer records rpc.recv.<Method>

Cost discipline — this must be cheap enough to leave on: span recording
is lock-light (spans append to per-trace lists; completed traces swap
into the ring under one short lock), everything no-ops on a None trace,
``MISAKA_TRACE_SAMPLE`` (default 1.0 — the recorder is bounded anyway)
thins root traces, and ``MISAKA_TRACE_REQUESTS=0`` is the kill switch
that turns ``begin`` into a constant ``return None``.  Stdlib-only by
design, like metrics.py and jsonlog.py: frontend workers import this
without paying for jax.
"""

from __future__ import annotations

import contextlib
import contextvars
import heapq
import itertools
import os
import random
import re
import threading
import time
from collections import deque

# The header carrying the trace ID on every HTTP hop (inbound honored,
# outbound always set on traced responses); lowercase twin for gRPC
# metadata keys, which grpc requires to be lowercase.
TRACE_HEADER = "X-Misaka-Trace"
RPC_METADATA_KEY = "x-misaka-trace"

# Inbound IDs are attacker-controlled (an unauthenticated header): accept
# only a short hex/dash token so the recorder and logs can't be made to
# store arbitrary bytes.
_ID_RE = re.compile(r"^[0-9a-zA-Z-]{4,64}$")

# Tier -> Perfetto pid.  Stable small ints so exports from different
# rounds diff cleanly; unknown prefixes collapse to "other".
TIER_PIDS = {
    "http": 1, "frontend": 2, "plane": 3, "serve": 4,
    "engine": 5, "native": 6, "rpc": 7, "other": 8,
}


def tier_of(name: str) -> str:
    t = name.split(".", 1)[0]
    return t if t in TIER_PIDS else "other"


class Span:
    """One timed operation inside a trace: monotonic start + duration.

    ``start`` is time.monotonic() seconds (CLOCK_MONOTONIC — comparable
    across processes on one host, which is what lets the frontend forward
    its spans to the engine over the plane with no clock translation)."""

    __slots__ = ("name", "start", "dur", "attrs")

    def __init__(self, name: str, start: float, dur: float, attrs=None):
        self.name = name
        self.start = start
        self.dur = dur
        self.attrs = attrs

    def to_dict(self, base: float) -> dict:
        d = {
            "name": self.name,
            "tier": tier_of(self.name),
            "start_ms": round((self.start - base) * 1e3, 3),
            "dur_ms": round(self.dur * 1e3, 3),
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class Trace:
    """One request's span collection.

    Spans are appended with ``list.append`` from whichever thread served
    part of the request (handler thread, batcher worker) — atomic under
    the GIL, so the hot path takes no lock; the one short recorder lock
    runs at completion only."""

    __slots__ = ("trace_id", "route", "status", "start_mono", "start_unix",
                 "dur", "spans", "inbound", "_token")

    def __init__(self, trace_id: str, route: str | None = None):
        self.trace_id = trace_id
        self.route = route
        self.status: int | None = None
        self.start_mono = time.monotonic()
        self.start_unix = time.time()
        self.dur: float | None = None  # set at end()
        self.spans: list[Span] = []
        self.inbound = False  # ID honored from the request (vs minted) —
        # the capture plane's sampling bypass rides on this
        self._token = None  # contextvar reset token (activating begin only)

    def add(self, name: str, start: float, dur: float, attrs=None) -> None:
        self.spans.append(Span(name, start, dur, attrs))

    @property
    def duration_ms(self) -> float:
        dur = self.dur if self.dur is not None \
            else time.monotonic() - self.start_mono
        return round(dur * 1e3, 3)

    def summary(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "route": self.route,
            "status": self.status,
            "start_unix": round(self.start_unix, 3),
            "duration_ms": self.duration_ms,
            "spans": len(self.spans),
        }

    def to_dict(self) -> dict:
        d = self.summary()
        d["spans"] = [s.to_dict(self.start_mono) for s in self.spans]
        return d


class FlightRecorder:
    """Bounded storage for completed traces: a ring of the last N plus a
    min-heap reservoir of the slowest K (so the request worth debugging
    is still there after N fast ones pushed it out of the ring).  One
    short lock guards the swap; readers copy under it."""

    def __init__(self, ring: int = 256, slowest: int = 32):
        self._lock = threading.Lock()
        self._seq = itertools.count()  # heap tiebreaker
        self.resize(ring, slowest)

    def resize(self, ring: int, slowest: int) -> None:
        with self._lock:
            self._ring: deque[Trace] = deque(maxlen=max(1, int(ring)))
            self._slow: list[tuple[float, int, Trace]] = []
            self._k = max(1, int(slowest))

    def record(self, trace: Trace) -> None:
        with self._lock:
            self._ring.append(trace)
            item = (trace.dur or 0.0, next(self._seq), trace)
            if len(self._slow) < self._k:
                heapq.heappush(self._slow, item)
            elif item[0] > self._slow[0][0]:
                heapq.heapreplace(self._slow, item)

    def recent(self) -> list[Trace]:
        with self._lock:
            return list(self._ring)

    def slowest(self) -> list[Trace]:
        with self._lock:
            items = list(self._slow)
        return [t for _, _, t in sorted(items, reverse=True)]

    def get(self, trace_id: str) -> Trace | None:
        """The completed trace for an ID — MERGED when several share it:
        one request crossing an in-process hop (frontend tier driven in
        one process, the loopback test cluster) completes once per hop,
        and the union of their spans is the whole story.  In production
        each process holds its own half; its recorder then has exactly
        one."""
        with self._lock:
            matches = [t for t in self._ring if t.trace_id == trace_id]
            matches += [
                t for _, _, t in self._slow
                if t.trace_id == trace_id and t not in matches
            ]
        if not matches:
            return None
        if len(matches) == 1:
            return matches[0]
        return merge_traces(matches)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()


def mem_bytes() -> int:
    """Approximate recorder footprint for the /healthz debug_mem block
    (one budget surface with the native flight rings and the capture
    ring): per-trace object overhead plus ~112 bytes per span."""
    total = 0
    with RECORDER._lock:
        traces = list(RECORDER._ring) + [t for _, _, t in RECORDER._slow]
    for t in traces:
        total += 240 + 112 * len(t.spans)
    return total


def merge_traces(traces: list[Trace]) -> Trace:
    """One Trace unioning several completions of the same ID (dedup by
    (name, start): a span the frontend forwarded over the plane appears
    in both halves)."""
    first = min(traces, key=lambda t: t.start_mono)
    merged = Trace(first.trace_id, route=first.route)
    merged.start_mono = first.start_mono
    merged.start_unix = first.start_unix
    end_mono = max(t.start_mono + (t.dur or 0.0) for t in traces)
    merged.dur = end_mono - first.start_mono
    merged.status = max(
        (t.status for t in traces if t.status is not None), default=None
    )
    seen = set()
    for t in sorted(traces, key=lambda t: t.start_mono):
        for s in t.spans:
            key = (s.name, round(s.start, 6))
            if key not in seen:
                seen.add(key)
                merged.spans.append(s)
    return merged


RECORDER = FlightRecorder()

# Tier events: spans that belong to a TIER rather than one request (a
# device-loop chunk or native-pool call serves many coalesced requests
# at once — attributing it to each would multiply hot-path work).  A
# lock-free bounded deque; merged into the Perfetto export so a fused
# pass visually underlies the request spans stacked above it.
_TIER_EVENTS: deque[Span] = deque(maxlen=1024)

_current: contextvars.ContextVar[Trace | None] = contextvars.ContextVar(
    "misaka_trace", default=None
)

_ENABLED = True
_SAMPLE = 1.0


def configure(environ=os.environ) -> None:
    """(Re-)read the env knobs — called at import; tests and the bench
    A/B call it again after toggling the environment.

      MISAKA_TRACE_REQUESTS=0   kill switch: begin() returns None always
      MISAKA_TRACE_SAMPLE       root-trace sampling rate (default 1.0;
                                inbound-ID requests are always traced —
                                the upstream hop already decided)
      MISAKA_TRACE_RING         completed-trace ring size (default 256)
      MISAKA_TRACE_SLOWEST      slowest-K reservoir size (default 32)
    """
    global _ENABLED, _SAMPLE
    _ENABLED = environ.get("MISAKA_TRACE_REQUESTS", "1") != "0"
    try:
        _SAMPLE = min(1.0, max(0.0, float(
            environ.get("MISAKA_TRACE_SAMPLE", "") or 1.0
        )))
    except ValueError:
        _SAMPLE = 1.0
    # malformed knobs fall back to defaults: configure() runs at import,
    # and a typo'd env var must not take down every process that imports
    # this module (engine, frontend workers, jsonlog)
    try:
        ring = int(environ.get("MISAKA_TRACE_RING", "") or 256)
    except ValueError:
        ring = 256
    try:
        slowest = int(environ.get("MISAKA_TRACE_SLOWEST", "") or 32)
    except ValueError:
        slowest = 32
    RECORDER.resize(ring, slowest)


configure()


def enabled() -> bool:
    return _ENABLED


def mint() -> str:
    # random.getrandbits, not os.urandom: an ID is minted per request on
    # the serving hot path, and urandom is a SYSCALL — a preemption
    # point that measurably stretches closed-loop latency on a saturated
    # box.  Trace IDs need uniqueness, not unpredictability.
    return f"{random.getrandbits(64):016x}"


def sanitize_id(raw) -> str | None:
    """An inbound trace ID, or None when it isn't one we accept."""
    if not raw or not isinstance(raw, str):
        return None
    raw = raw.strip()
    return raw if _ID_RE.match(raw) else None


def begin(trace_id=None, route: str | None = None,
          activate: bool = True) -> Trace | None:
    """Start a trace for one request; returns None when tracing is off or
    the request sampled out (every later call no-ops on None).

    An acceptable inbound ``trace_id`` skips sampling — the upstream hop
    already chose to trace, and dropping its continuation here would
    orphan the cross-hop story.  ``activate=False`` skips the contextvar
    (the compute plane begins several traces per frame; none of them is
    "the" current one for its connection thread)."""
    if not _ENABLED:
        return None
    tid = sanitize_id(trace_id)
    inbound = tid is not None
    if tid is None:
        if _SAMPLE < 1.0 and random.random() >= _SAMPLE:
            return None
        tid = mint()
    trace = Trace(tid, route=route)
    trace.inbound = inbound
    if activate:
        trace._token = _current.set(trace)
    return trace


def end(trace: Trace | None, status: int | None = None) -> None:
    """Finalize + record into the flight recorder (no-op on None)."""
    if trace is None:
        return
    if trace._token is not None:
        try:
            _current.reset(trace._token)
        except ValueError:  # ended from a different context: just clear
            _current.set(None)
        trace._token = None
    if status is not None:
        trace.status = status
    trace.dur = time.monotonic() - trace.start_mono
    RECORDER.record(trace)


def current() -> Trace | None:
    return _current.get()


def current_id() -> str | None:
    t = _current.get()
    return t.trace_id if t is not None else None


@contextlib.contextmanager
def use(trace: Trace | None):
    """Make ``trace`` current for a worker thread's scope."""
    token = _current.set(trace)
    try:
        yield trace
    finally:
        _current.reset(token)


@contextlib.contextmanager
def span(name: str, trace: Trace | None = None, **attrs):
    """Record one timed span into ``trace`` (default: the current trace);
    a cheap no-op when there is none."""
    t = trace if trace is not None else _current.get()
    if t is None:
        yield None
        return
    t0 = time.monotonic()
    try:
        yield t
    finally:
        t.add(name, t0, time.monotonic() - t0, attrs or None)


def add_span(trace: Trace | None, name: str, start: float, dur: float,
             attrs=None) -> None:
    """Explicit-timestamp recording (queue delays measured elsewhere,
    spans forwarded across the plane)."""
    if trace is not None:
        trace.add(name, start, dur, attrs)


def note_tier(name: str, dur: float, start: float | None = None,
              attrs=None) -> None:
    """Record a tier event (see _TIER_EVENTS) — one deque append."""
    if not _ENABLED:
        return
    if start is None:
        start = time.monotonic() - dur
    _TIER_EVENTS.append(Span(name, start, dur, attrs))


def tier_events() -> list[Span]:
    return list(_TIER_EVENTS)


# Pluggable tier sources: subsystems with their OWN event storage (the
# native flight recorder's in-C++ per-thread rings, core/native_serve)
# contribute spans to the Perfetto export at read time instead of
# double-buffering into _TIER_EVENTS.  A source returns a list of Span
# objects; two attrs are interpreted by the exporter: ``_lane`` names a
# per-source timeline lane (one Perfetto thread per distinct lane —
# worker threads read as parallel tracks), and ``trace_ids`` lists the
# request-trace IDs the span served — the span is then ALSO emitted on
# each of those traces' own timelines, which is what makes one trace ID
# read as a single story from http.parse down to the worker-thread unit
# that ticked it.  Registered sources must never raise usefully: the
# exporter swallows per-source failures (a debug surface answers).
_TIER_SOURCES: list = []


def register_tier_source(fn) -> None:
    """Register a callable returning a list of Spans for the Perfetto
    export (idempotent per callable)."""
    if fn not in _TIER_SOURCES:
        _TIER_SOURCES.append(fn)


def clear() -> None:
    """Tests: wipe the recorder and tier events."""
    RECORDER.clear()
    _TIER_EVENTS.clear()


def server_timing(trace: Trace | None) -> str | None:
    """The ``Server-Timing`` response-header value for a trace: queue and
    pass phases summed from the serve spans recorded so far, plus the
    total so far — written while the response headers go out, so `total`
    excludes only the response write itself."""
    if trace is None:
        return None
    queue_s = pass_s = 0.0
    for s in trace.spans:  # one pass; this runs per response
        if s.name == "serve.queue":
            queue_s += s.dur
        elif s.name == "serve.pass":
            pass_s += s.dur
    parts = []
    if queue_s or pass_s:
        parts.append(f"queue;dur={queue_s * 1e3:.3f}")
        parts.append(f"pass;dur={pass_s * 1e3:.3f}")
    parts.append(f"total;dur={trace.duration_ms:.3f}")
    return ", ".join(parts)


def parse_server_timing(value: str) -> dict[str, float]:
    """``"queue;dur=1.2, pass;dur=3.4"`` -> {"queue": 1.2, "pass": 3.4}
    (the client-side half; ignores metrics without a dur)."""
    out: dict[str, float] = {}
    for item in value.split(","):
        name, _, params = item.strip().partition(";")
        for p in params.split(";"):
            k, _, v = p.strip().partition("=")
            if k == "dur":
                try:
                    out[name.strip()] = float(v)
                except ValueError:
                    pass
    return out


def debug_payload() -> dict:
    """The GET /debug/requests body: recent + slowest summaries."""
    return {
        "enabled": _ENABLED,
        "sample": _SAMPLE,
        "recent": [t.summary() for t in reversed(RECORDER.recent())],
        "slowest": [t.summary() for t in RECORDER.slowest()],
    }


def slowest_exemplars(k: int = 3, program: str | None = None) -> list[dict]:
    """Alert exemplars: the slowest completed traces (optionally only
    those whose serve spans billed to `program`), each linking straight
    to its full trace at /debug/requests/<id>.  The /debug/alerts route
    attaches these to SLO pages and watchdog findings, so "p99 is
    burning" comes with the actual requests that burned it."""
    out: list[dict] = []
    for t in RECORDER.slowest():
        if program is not None and not any(
            s.attrs and s.attrs.get("program") == program
            for s in t.spans
        ):
            continue
        out.append({
            "trace_id": t.trace_id,
            "route": t.route,
            "duration_ms": t.duration_ms,
            "href": f"/debug/requests/{t.trace_id}",
        })
        if len(out) >= k:
            break
    return out


def perfetto() -> dict:
    """The whole recorder as Chrome trace-event JSON (the "JSON Array
    Format" both Perfetto and chrome://tracing load).

    Layout: one Perfetto "process" per tier (TIER_PIDS), one "thread"
    per trace inside each tier it touched — so the serve tier shows the
    coalesced requests of one fused pass stacked on top of each other,
    with the engine tier's chunk events running underneath.  Tier events
    ride tid 0 of their tier."""
    events: list[dict] = []
    for tier, pid in TIER_PIDS.items():
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"tier: {tier}"},
        })
    groups: dict[str, list[Trace]] = {}
    for t in RECORDER.recent() + RECORDER.slowest():
        group = groups.setdefault(t.trace_id, [])
        if t not in group:
            group.append(t)
    tids: dict[str, int] = {}
    for trace_id, group in groups.items():
        t = group[0] if len(group) == 1 else merge_traces(group)
        tid = tids.setdefault(trace_id, len(tids) + 1)
        for pid in {TIER_PIDS[tier_of(s.name)] for s in t.spans}:
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": t.trace_id},
            })
        for s in t.spans:
            ev = {
                "ph": "X",
                "name": s.name,
                "pid": TIER_PIDS[tier_of(s.name)],
                "tid": tid,
                "ts": round(s.start * 1e6, 1),
                "dur": round(s.dur * 1e6, 1),
                "args": {"trace_id": t.trace_id},
            }
            if s.attrs:
                ev["args"].update(s.attrs)
            events.append(ev)
    for s in tier_events():
        ev = {
            "ph": "X",
            "name": s.name,
            "pid": TIER_PIDS[tier_of(s.name)],
            "tid": 0,
            "ts": round(s.start * 1e6, 1),
            "dur": round(s.dur * 1e6, 1),
        }
        if s.attrs:
            ev["args"] = dict(s.attrs)
        events.append(ev)
    # pluggable tier sources (register_tier_source): per-lane timelines
    # plus duplication onto the request traces each span served
    lane_tids: dict[str, int] = {}
    for fn in list(_TIER_SOURCES):
        try:
            spans = fn()
        except Exception:
            continue
        for s in spans:
            attrs = dict(s.attrs) if s.attrs else {}
            lane = attrs.pop("_lane", None)
            trace_ids = attrs.pop("trace_ids", None)
            pid = TIER_PIDS[tier_of(s.name)]
            tid = 0
            if lane is not None:
                tid = lane_tids.get(lane)
                if tid is None:
                    tid = 10001 + len(lane_tids)
                    lane_tids[lane] = tid
                    events.append({
                        "ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "args": {"name": lane},
                    })
            ev = {
                "ph": "X",
                "name": s.name,
                "pid": pid,
                "tid": tid,
                "ts": round(s.start * 1e6, 1),
                "dur": round(s.dur * 1e6, 1),
            }
            if trace_ids:
                attrs["trace_id"] = ",".join(trace_ids)
            if attrs:
                ev["args"] = attrs
            events.append(ev)
            # the same span on each served trace's own timeline: the
            # unified per-trace story (only for traces the export knows)
            for trace_id in trace_ids or ():
                tr_tid = tids.get(trace_id)
                if tr_tid is None:
                    continue
                ev2 = dict(ev)
                ev2["tid"] = tr_tid
                ev2["args"] = dict(attrs)
                ev2["args"]["trace_id"] = trace_id
                events.append(ev2)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
