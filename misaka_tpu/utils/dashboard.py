"""The fleet dashboard: golden-signal sparklines over the embedded TSDB,
served as ONE self-contained HTML page at GET /debug/dashboard.

Same discipline as the flamegraph viewer (utils/sampler.py): zero
external assets — the data is baked into the page at render time and the
rendering is ~100 lines of vanilla JS drawing inline SVG, so an
air-gapped ops box (or a curl into a file) gets the whole picture.

Panels are the golden signals the ISSUE names: throughput, p50/p99,
error rate, admission sheds, queue depth, native-pool busy fraction,
replica health, canary success/latency — plus per-program value rates
and per-program p99 for drill-down.  In fleet mode the parent serves the
same page over its replica-merged series (every series carries a
``replica`` label there), and the page's label filters become the
per-replica drill-down.

The page is built against a QUERY FUNCTION, not the TSDB directly:
``query_fn(name, window_s) -> [{labels, points, ...}]`` — the engine
passes utils/tsdb.query, the fleet parent passes its merging aggregator.
"""

from __future__ import annotations

import json
import time

# (title, series name, aggregation hint for the JS, unit)
#   agg: how multiple matching series combine per time slot in the
#   headline line — "sum" (rates), "max" (latencies/depths), "min"
#   (success bits: any replica failing shows).
PANELS = (
    ("Throughput (values/s)", "misaka_compute_values_total", "sum", "/s"),
    ("HTTP p99", "misaka_http_request_duration_seconds:p99", "max", "s"),
    ("HTTP p50", "misaka_http_request_duration_seconds:p50", "max", "s"),
    ("HTTP errors (/s)", "misaka_http_errors_total", "sum", "/s"),
    ("Admission sheds (/s)", "misaka_edge_rejected_total", "sum", "/s"),
    ("Queue depth (waiting requests)", "misaka_serve_waiting_requests",
     "max", ""),
    ("Native pool busy fraction", "misaka_native_pool_busy_fraction",
     "max", ""),
    ("Residency hit ratio", "misaka_native_resident_hit_ratio",
     "max", ""),
    ("Pipelined plane frames (/s)", "misaka_plane_pipelined_frames_total",
     "sum", "/s"),
    ("Plane pipeline depth (max)", "misaka_plane_pipeline_depth_max",
     "max", ""),
    ("Dispenser wait p99", "misaka_native_dispenser_wait_seconds:p99",
     "max", "s"),
    ("Dispenser spin ratio", "misaka_native_dispenser_spin_ratio",
     "max", ""),
    ("SIMD lane width", "misaka_native_simd_lane_width", "max", ""),
    ("Specialized engines", "misaka_native_specialized_active", "max", ""),
    ("JIT engines", "misaka_native_jit_active", "max", ""),
    ("Elided pack rows (/s)", "misaka_native_elided_rows_total", "sum",
     "/s"),
    ("Plane shm frames (/s)", "misaka_plane_shm_frames_total", "sum", "/s"),
    ("Replicas alive", "misaka_fleet_replicas_alive", "min", ""),
    ("Canary success", "misaka_canary_success", "min", ""),
    ("Canary p99", "misaka_canary_latency_seconds:p99", "max", "s"),
    ("Per-program values/s", "misaka_usage_values_total", "sum", "/s"),
    ("Per-program SLO p99", "misaka_slo_p99_seconds", "max", "s"),
    ("TSDB spool on disk (bytes)", "misaka_tsdb_spool_bytes", "max", "B"),
    ("Spool drops (/s)", "misaka_tsdb_spool_dropped_total", "sum", "/s"),
    ("Capture spool on disk (bytes)", "misaka_capture_spool_bytes",
     "max", "B"),
    ("Spool errors (/s)", "misaka_spool_errors_total", "sum", "/s"),
)


def payload(query_fn, window_s: float, extra: dict | None = None) -> dict:
    """The baked DATA object: every panel's matching series over the
    window, plus canary/watchdog state when the caller passes it."""
    panels = []
    for title, name, agg, unit in PANELS:
        series = query_fn(name, window_s)
        panels.append({
            "title": title,
            "metric": name,
            "agg": agg,
            "unit": unit,
            "series": series,
        })
    out = {
        "generated_unix": round(time.time(), 3),
        "window_s": window_s,
        "panels": panels,
    }
    if extra:
        out.update(extra)
    return out


_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>misaka observatory</title>
<style>
 body { font: 13px system-ui, sans-serif; margin: 16px; background: #fff;
        color: #222; }
 h1 { font-size: 16px; margin: 0 0 4px; }
 .meta { color: #555; margin-bottom: 10px; }
 .filters { margin-bottom: 12px; }
 .filters select { margin-right: 10px; }
 .grid { display: flex; flex-wrap: wrap; gap: 12px; }
 .panel { border: 1px solid #ddd; border-radius: 4px; padding: 8px;
          width: 320px; }
 .panel h2 { font-size: 12px; margin: 0 0 2px; font-weight: 600; }
 .panel .now { font-size: 18px; font-weight: 700; }
 .panel .range { color: #777; font-size: 11px; }
 .panel svg { display: block; margin-top: 4px; }
 .spark { stroke: #2a6fb0; stroke-width: 1.5; fill: none; }
 .sparkmax { stroke: #c0504d; stroke-width: 1; fill: none;
             stroke-dasharray: 2 2; }
 .empty { color: #999; font-size: 11px; margin-top: 8px; }
 .bad .now { color: #c0504d; }
 .alertbox { border: 1px solid #e4c0c0; background: #fdf4f4;
             border-radius: 4px; padding: 8px; margin-bottom: 12px;
             font-size: 12px; }
 .alertbox.ok { border-color: #cfe3cf; background: #f4faf4; }
</style></head><body>
<h1>misaka observatory</h1>
<div class="meta" id="meta"></div>
<div class="alertbox" id="alerts"></div>
<div class="filters">
  <label>program <select id="f_program"><option value="">all</option>
  </select></label>
  <label>replica <select id="f_replica"><option value="">all</option>
  </select></label>
</div>
<div class="grid" id="grid"></div>
<script>
const DATA = %s;
document.getElementById('meta').textContent =
  `window ${DATA.window_s}s | generated ` +
  new Date(DATA.generated_unix * 1000).toISOString();
// status strip: canary + watchdog state when the server baked them in
const alerts = document.getElementById('alerts');
{
  const bits = [];
  let bad = false;
  if (DATA.canary) {
    const c = DATA.canary;
    if (c.failing_tier) { bad = true;
      bits.push(`canary FAILING at tier "${c.failing_tier}" ` +
                `(${c.consecutive_full_failures} consecutive)`); }
    else bits.push('canary ok');
  }
  if (DATA.watchdog) {
    const firing = (DATA.watchdog.rules || [])
      .filter(r => r.state !== 'ok');
    if (firing.length) { bad = true;
      bits.push('watchdog: ' + firing.map(
        r => `${r.rule} ${r.state}`).join(', ')); }
    else bits.push('watchdog ok');
  }
  alerts.textContent = bits.join(' · ') || 'no canary/watchdog state';
  alerts.className = 'alertbox' + (bad ? '' : ' ok');
}
// label filters: every distinct program/replica value seen in any series
const labelValues = key => {
  const vals = new Set();
  for (const p of DATA.panels)
    for (const s of p.series)
      if (s.labels && s.labels[key] !== undefined) vals.add(s.labels[key]);
  return [...vals].sort();
};
for (const key of ['program', 'replica']) {
  const sel = document.getElementById('f_' + key);
  for (const v of labelValues(key)) {
    const o = document.createElement('option');
    o.value = v; o.textContent = v; sel.appendChild(o);
  }
  sel.onchange = render;
}
function fmt(v, unit) {
  if (v === null || v === undefined || !isFinite(v)) return '-';
  const a = Math.abs(v);
  let s;
  if (a >= 1e6) s = (v / 1e6).toFixed(2) + 'M';
  else if (a >= 1e3) s = (v / 1e3).toFixed(2) + 'k';
  else if (a >= 1 || a === 0) s = v.toFixed(2);
  else if (a >= 1e-3) s = (v * 1e3).toFixed(2) + 'm';
  else s = (v * 1e6).toFixed(1) + 'u';
  return s + unit;
}
function aggregate(series, agg) {
  // combine matching series per time slot: avg-line and max-line
  const slots = new Map();
  for (const s of series)
    for (const [t, avg, mx] of s.points) {
      let e = slots.get(t);
      if (!e) { e = {avg: null, max: null}; slots.set(t, e); }
      e.avg = e.avg === null ? avg :
        (agg === 'sum' ? e.avg + avg :
         agg === 'min' ? Math.min(e.avg, avg) : Math.max(e.avg, avg));
      e.max = e.max === null ? mx :
        (agg === 'sum' ? e.max + mx :
         agg === 'min' ? Math.min(e.max, mx) : Math.max(e.max, mx));
    }
  return [...slots.entries()].sort((x, y) => x[0] - y[0])
    .map(([t, e]) => [t, e.avg, e.max]);
}
function sparkline(points, w, h) {
  if (!points.length) return null;
  const ts = points.map(p => p[0]);
  const t0 = Math.min(...ts), t1 = Math.max(...ts);
  const vs = points.map(p => p[1]).concat(points.map(p => p[2]));
  let lo = Math.min(...vs), hi = Math.max(...vs);
  if (hi === lo) { hi = lo + 1; lo = lo - (lo === 0 ? 0 : 1e-9); }
  const x = t => t1 === t0 ? w / 2 : (t - t0) / (t1 - t0) * (w - 4) + 2;
  const y = v => h - 3 - (v - lo) / (hi - lo) * (h - 8);
  const line = i => points.map(
    p => `${x(p[0]).toFixed(1)},${y(p[i]).toFixed(1)}`).join(' ');
  return {avgLine: line(1), maxLine: line(2), lo, hi};
}
function render() {
  const fp = document.getElementById('f_program').value;
  const fr = document.getElementById('f_replica').value;
  const grid = document.getElementById('grid');
  grid.textContent = '';
  for (const p of DATA.panels) {
    const matching = p.series.filter(s => {
      const L = s.labels || {};
      if (fp && L.program !== undefined && L.program !== fp) return false;
      if (fp && p.metric.indexOf('usage') >= 0 &&
          L.program === undefined) return false;
      if (fr && L.replica !== undefined && L.replica !== fr) return false;
      return true;
    });
    const pts = aggregate(matching, p.agg);
    const div = document.createElement('div');
    div.className = 'panel';
    const h2 = document.createElement('h2');
    h2.textContent = p.title;
    div.appendChild(h2);
    if (!pts.length) {
      const e = document.createElement('div');
      e.className = 'empty';
      e.textContent = 'no data in window';
      div.appendChild(e);
      grid.appendChild(div);
      continue;
    }
    const last = pts[pts.length - 1];
    const now = document.createElement('span');
    now.className = 'now';
    now.textContent = fmt(last[1], p.unit);
    if (p.metric === 'misaka_canary_success' && last[1] < 1)
      div.classList.add('bad');
    div.appendChild(now);
    const sp = sparkline(pts, 300, 48);
    const range = document.createElement('div');
    range.className = 'range';
    range.textContent =
      `min ${fmt(sp.lo, p.unit)} · max ${fmt(sp.hi, p.unit)} · ` +
      `${pts.length} pts · ${matching.length} series`;
    div.appendChild(range);
    const svg = document.createElementNS(
      'http://www.w3.org/2000/svg', 'svg');
    svg.setAttribute('width', 300); svg.setAttribute('height', 48);
    for (const [cls, line] of
         [['sparkmax', sp.maxLine], ['spark', sp.avgLine]]) {
      const pl = document.createElementNS(
        'http://www.w3.org/2000/svg', 'polyline');
      pl.setAttribute('class', cls);
      pl.setAttribute('points', line);
      svg.appendChild(pl);
    }
    div.appendChild(svg);
    grid.appendChild(div);
  }
}
render();
</script></body></html>
"""


def render_html(query_fn, window_s: float, extra: dict | None = None) -> str:
    """The GET /debug/dashboard body (``?window=`` selects the span)."""
    return _PAGE % json.dumps(payload(query_fn, window_s, extra))
