"""SLO burn-rate engine: sliding-window objectives per program.

The metrics plane says what the box is doing; the trace plane says where
one request went; NOTHING before this module watched the service against
a declared objective continuously.  This is the SRE multi-window
burn-rate practice (the alerting discipline Google's SRE workbook
standardized) grown over the PR 7 metrics plane: declare objectives,
estimate latency quantiles and error rates over sliding windows, and
surface ok / warning / page states a fleet scheduler (and ISSUE 8+'s
admission control) can act on.

Objectives — the ``MISAKA_SLO`` grammar (comma-separated)::

    MISAKA_SLO="p99<25ms,err<0.1%"

  * ``p<NN><T>``  — latency: at most (100-NN)% of requests may exceed T
                    (units: us/ms/s).  The quantile IS the objective; the
                    burn math treats requests over T as bad events
                    against the (100-NN)% budget.
  * ``err<P%``    — error rate: at most P% of requests may fail (HTTP
                    5xx / plane errors; 4xx are the client's problem).

Per-program overrides ride the registry (runtime/registry.py): an upload
carrying an ``slo`` form field installs that spec for the program when
the version becomes ``latest``, replacing the env default for it.

Windows: a ring of fixed-width buckets per program, two tiers (fine
buckets cover the two short windows, coarse the two long ones — summing
a window never walks more than ~120 buckets).  Default windows
10s/1m/5m/1h, tunable via ``MISAKA_SLO_WINDOWS=10,60,300,3600`` (tests
shrink them to seconds so page->recovery fits a fast lane).  Each bucket
holds a request count, an error count, and a latency histogram on the
metrics plane's fixed duration grid — quantile and over-threshold
estimation reuse utils/metrics.py's bucket math (quantile_from_buckets /
fraction_over), whose accuracy tests pin.

Burn rate = bad_fraction / budget.  Evaluation is the multi-window
discipline (a rule fires only when BOTH its windows burn — the short one
proves it is still happening, the long one that it is not a blip)::

    page:    burn >= 14.4 over (windows[1] AND windows[0])
    page:    burn >=  6.0 over (windows[2] AND windows[1])
    warning: burn >=  3.0 over (windows[3] AND windows[2])

A window with fewer than ``MISAKA_SLO_MIN_EVENTS`` (default 10) requests
reports burn 0 — one unlucky request must not page.  States surface at
``GET /debug/alerts``, in ``/healthz`` (page => the PR 9 ``degraded``
flag), and as ``misaka_slo_*`` gauges on /metrics.

Program cardinality is bounded by the SAME knob as the usage ledger —
``MISAKA_USAGE_LABEL_MAX`` (default 64), one per-tenant cap for the
whole health plane: past it, new programs' windows collapse into
``"other"``.  Lowering it constrains usage counters AND merges surplus
tenants' SLO windows together, deliberately — the two surfaces must
agree on who is a tracked tenant.

Stdlib-only, like metrics/tracespan/jsonlog.  Disabled (every observe a
no-op) until an objective exists — MISAKA_SLO unset and no registry
override means zero serving-path cost.
"""

from __future__ import annotations

import bisect
import logging
import math
import os
import re
import threading
import time

from misaka_tpu.utils import metrics

log = logging.getLogger("misaka_tpu.slo")

# The latency grid: the metrics plane's fixed duration buckets (10us..10s,
# 3/decade) — fixed buckets are what make window sums and cross-program
# aggregation coherent.
UPPERS = metrics.DURATION_BUCKETS

STATES = ("ok", "warning", "page")

# Programs whose traffic NEVER feeds the SLO windows: the synthetic
# canary (runtime/canary.py) deliberately probes through the full public
# stack — including fault drills that make it slow on purpose — and must
# not burn any tenant's error budget while doing it.  Canary failures
# page through the watchdog (utils/watchdog.py) instead.  This is the
# one chokepoint: every entry path (HTTP edge, compute plane) lands in
# observe(), so the exclusion cannot be bypassed by route.
EXCLUDED_PROGRAMS = frozenset({"_canary"})

M_SLO_STATE = metrics.gauge(
    "misaka_slo_state",
    "Per-program SLO state (0 = ok, 1 = warning, 2 = page)",
    ("program",),
)
M_SLO_BURN = metrics.gauge(
    "misaka_slo_burn_rate",
    "Error-budget burn rate per program/objective/window (1.0 = burning "
    "exactly the budget; the page rules fire at 14.4x and 6x)",
    ("program", "objective", "window"),
)
M_SLO_ERR = metrics.gauge(
    "misaka_slo_error_ratio",
    "Observed error ratio per program over each window",
    ("program", "window"),
)
M_SLO_P99 = metrics.gauge(
    "misaka_slo_p99_seconds",
    "Estimated p99 latency per program over each window",
    ("program", "window"),
)


class SLOSpecError(ValueError):
    """Malformed MISAKA_SLO / per-program objective spec."""


_LAT_RE = re.compile(r"^p(\d{1,2}(?:\.\d+)?)<(\d+(?:\.\d+)?)(us|ms|s)$")
_ERR_RE = re.compile(r"^err<(\d+(?:\.\d+)?)%$")
_UNIT = {"us": 1e-6, "ms": 1e-3, "s": 1.0}


class Objective:
    """One declared objective: a bad-event predicate + an error budget."""

    __slots__ = ("name", "kind", "quantile", "threshold_s", "budget")

    def __init__(self, name, kind, budget, quantile=None, threshold_s=None):
        self.name = name            # the spec text, e.g. "p99<25ms"
        self.kind = kind            # "latency" | "error"
        self.budget = budget        # allowed bad fraction, in (0, 1)
        self.quantile = quantile    # latency only: 0.99 for p99
        self.threshold_s = threshold_s  # latency only: seconds


def parse_spec(text: str) -> list[Objective]:
    """``"p99<25ms,err<0.1%"`` -> [Objective, ...] (raises SLOSpecError)."""
    objectives: list[Objective] = []
    for raw in (text or "").split(","):
        item = raw.strip()
        if not item:
            continue
        m = _LAT_RE.match(item)
        if m:
            q = float(m.group(1)) / 100.0
            if not 0.0 < q < 1.0:
                raise SLOSpecError(f"quantile out of range in {item!r}")
            threshold = float(m.group(2)) * _UNIT[m.group(3)]
            if threshold <= 0:
                raise SLOSpecError(f"threshold must be > 0 in {item!r}")
            objectives.append(Objective(
                item, "latency", budget=1.0 - q,
                quantile=q, threshold_s=threshold,
            ))
            continue
        m = _ERR_RE.match(item)
        if m:
            budget = float(m.group(1)) / 100.0
            if not 0.0 < budget < 1.0:
                raise SLOSpecError(f"error budget out of range in {item!r}")
            objectives.append(Objective(item, "error", budget=budget))
            continue
        raise SLOSpecError(
            f"cannot parse objective {item!r} (grammar: pNN<T[us|ms|s] "
            f"or err<P%)"
        )
    return objectives


class _Ring:
    """Fixed-width bucket ring holding (requests, errors, latency counts).

    One tier of a program's sliding windows: `width` seconds per bucket,
    `length` buckets of history.  observe() lands in the bucket for "now";
    sums walk backward from now over ceil(window/width) buckets, skipping
    buckets stale enough to predate the span (the ring is positional —
    each slot carries the epoch it was last reset for, so an idle period
    cannot leak month-old counts into a fresh window)."""

    __slots__ = ("width", "length", "epochs", "reqs", "errs", "lat")

    def __init__(self, width: float, length: int):
        self.width = float(width)
        self.length = int(length)
        self.epochs = [-1] * self.length   # bucket index in absolute time
        self.reqs = [0] * self.length
        self.errs = [0] * self.length
        self.lat = [None] * self.length    # lazily [len(UPPERS)+1] counts

    def _slot(self, now: float) -> int:
        epoch = int(now / self.width)
        i = epoch % self.length
        if self.epochs[i] != epoch:  # rotate: reclaim the stale slot
            self.epochs[i] = epoch
            self.reqs[i] = 0
            self.errs[i] = 0
            self.lat[i] = None
        return i

    def observe(self, now: float, dur_s: float, error: bool) -> None:
        i = self._slot(now)
        self.reqs[i] += 1
        if error:
            self.errs[i] += 1
        counts = self.lat[i]
        if counts is None:
            counts = self.lat[i] = [0] * (len(UPPERS) + 1)
        counts[bisect.bisect_left(UPPERS, dur_s)] += 1

    def window_sum(self, now: float, window_s: float):
        """(requests, errors, lat_counts) over the last `window_s`."""
        n = min(self.length, max(1, math.ceil(window_s / self.width)))
        epoch_now = int(now / self.width)
        reqs = errs = 0
        lat = [0] * (len(UPPERS) + 1)
        for back in range(n):
            epoch = epoch_now - back
            i = epoch % self.length
            if self.epochs[i] != epoch:
                continue  # stale or never-written slot
            reqs += self.reqs[i]
            errs += self.errs[i]
            counts = self.lat[i]
            if counts is not None:
                for j, c in enumerate(counts):
                    if c:
                        lat[j] += c
        return reqs, errs, lat


class _ProgramWindows:
    """Both ring tiers for one program, under one lock."""

    __slots__ = ("lock", "fine", "coarse")

    def __init__(self, windows):
        self.lock = threading.Lock()
        # fine tier: 10 buckets per shortest window, spanning windows[1];
        # coarse tier: 10 per windows[2], spanning windows[3]
        fw = max(windows[0] / 10.0, 0.05)
        cw = max(windows[2] / 10.0, fw)
        self.fine = _Ring(fw, max(2, math.ceil(windows[1] / fw) + 1))
        self.coarse = _Ring(cw, max(2, math.ceil(windows[3] / cw) + 1))

    def observe(self, now, dur_s, error):
        with self.lock:
            self.fine.observe(now, dur_s, error)
            self.coarse.observe(now, dur_s, error)

    def window_sum(self, now, window_s, boundary):
        ring = self.fine if window_s <= boundary else self.coarse
        with self.lock:
            return ring.window_sum(now, window_s)


# (long_window_index, short_window_index, burn_threshold, state):
# both windows must burn past the threshold for the rule to fire.
BURN_RULES = (
    (1, 0, 14.4, "page"),
    (2, 1, 6.0, "page"),
    (3, 2, 3.0, "warning"),
)

_lock = threading.Lock()
_windows: dict[str, _ProgramWindows] = {}
_overrides: dict[str, list[Objective]] = {}
_default_objectives: list[Objective] = []
_spec_error: str | None = None
_WINDOWS: tuple[float, ...] = (10.0, 60.0, 300.0, 3600.0)
_MIN_EVENTS = 10
_eval_cache: dict[str, tuple[float, dict]] = {}


def configure(environ=os.environ) -> None:
    """(Re-)read the env knobs and reset the window state.

      MISAKA_SLO          default objectives (unset + no overrides = the
                          engine is disarmed; observe() is then a no-op)
      MISAKA_SLO_WINDOWS  four ascending second values (default
                          "10,60,300,3600"; tests shrink them)
      MISAKA_SLO_MIN_EVENTS  per-window sample floor below which burn
                          reads 0 (default 10)
    """
    global _default_objectives, _WINDOWS, _MIN_EVENTS, _spec_error
    spec = environ.get("MISAKA_SLO", "")
    _spec_error = None
    try:
        _default_objectives = parse_spec(spec)
    except SLOSpecError as e:
        # a typo'd env var must not take down every importing process —
        # but silently disarming would mean pages that never fire, so the
        # mistake is loud: logged here AND carried on /debug/alerts
        _default_objectives = []
        _spec_error = f"MISAKA_SLO={spec!r}: {e}"
        log.warning("SLO engine DISARMED by a malformed spec — %s",
                    _spec_error)
    raw = environ.get("MISAKA_SLO_WINDOWS", "")
    windows = (10.0, 60.0, 300.0, 3600.0)
    if raw:
        try:
            parsed = tuple(float(x) for x in raw.split(","))
            if len(parsed) == 4 and all(
                0 < a < b for a, b in zip(parsed, parsed[1:])
            ):
                windows = parsed
        except ValueError:
            pass
    _WINDOWS = windows
    try:
        _MIN_EVENTS = max(1, int(environ.get("MISAKA_SLO_MIN_EVENTS", "") or 10))
    except ValueError:
        _MIN_EVENTS = 10
    with _lock:
        _windows.clear()
        _overrides.clear()
        _eval_cache.clear()


configure()


def set_objectives(program: str, spec: str | None) -> None:
    """Install (or clear, spec=None) a per-program objective override —
    the registry calls this when a program's `latest` version moves.

    Bounded by the health plane's shared cardinality cap
    (MISAKA_USAGE_LABEL_MAX): overrides name programs VERBATIM in the
    misaka_slo_* gauge labels and the /debug/alerts walk, so an upload
    flood must not mint unbounded series — past the cap a NEW override
    raises SLOSpecError (replacing an installed one is always allowed;
    the registry surfaces the refusal as a logged warning, the program
    still serves under the env-default objectives)."""
    with _lock:
        if spec:
            cap = metrics.tenant_label_budget()
            if program not in _overrides and len(_overrides) >= cap:
                raise SLOSpecError(
                    f"per-program SLO override budget exhausted "
                    f"({cap} programs, MISAKA_USAGE_LABEL_MAX) — "
                    f"{program!r} keeps the default objectives"
                )
            _overrides[program] = parse_spec(spec)
        else:
            _overrides.pop(program, None)
        _eval_cache.pop(program, None)


def objectives_for(program: str | None) -> list[Objective]:
    label = program or "default"
    return _overrides.get(label, _default_objectives)


def armed() -> bool:
    """True when ANY objective exists — the serving path's cheap gate."""
    return bool(_default_objectives) or bool(_overrides)


def _windows_for(program: str) -> _ProgramWindows:
    w = _windows.get(program)
    if w is not None:
        return w
    with _lock:
        # metrics.capped_label never recurses (resolving "other" by
        # recursing here once self-deadlocked the non-reentrant _lock).
        # A program with an EXPLICIT override is exempt from the
        # collapse — its observations landing in "other" would leave its
        # declared objectives evaluating 0 requests, a page that can
        # never fire; overrides are themselves capped at the same budget
        # in set_objectives, so window cardinality stays within 2*cap.
        program = metrics.capped_label(
            _windows, program, metrics.tenant_label_budget(),
            exempt=_overrides,
        )
        w = _windows.get(program)
        if w is None:
            w = _windows[program] = _ProgramWindows(_WINDOWS)
    return w


def observe(program: str | None, dur_s: float, error: bool = False) -> None:
    """One edge-observed request outcome into `program`'s windows
    (no-op while disarmed, and for canary-tagged programs)."""
    if not armed() or program in EXCLUDED_PROGRAMS:
        return
    _windows_for(program or "default").observe(
        time.monotonic(), dur_s, bool(error)
    )


def _evaluate(program: str, now: float) -> dict:
    """One program's objective states over every window (uncached)."""
    pw = _windows.get(program)
    objectives = objectives_for(program)
    boundary = _WINDOWS[1]
    out_objectives = []
    state = "ok"
    win_stats = []
    for w in _WINDOWS:
        reqs, errs, lat = (
            pw.window_sum(now, w, boundary) if pw is not None
            else (0, 0, [0] * (len(UPPERS) + 1))
        )
        win_stats.append((w, reqs, errs, lat))
    for obj in objectives:
        burns = []
        for w, reqs, errs, lat in win_stats:
            if reqs < _MIN_EVENTS:
                burns.append(0.0)
                continue
            if obj.kind == "error":
                bad = errs / reqs
            else:
                bad = metrics.fraction_over(UPPERS, lat, obj.threshold_s)
            burns.append(bad / obj.budget)
        obj_state = "ok"
        for long_i, short_i, threshold, s in BURN_RULES:
            if burns[long_i] >= threshold and burns[short_i] >= threshold:
                obj_state = s
                break
        if STATES.index(obj_state) > STATES.index(state):
            state = obj_state
        out_objectives.append({
            "objective": obj.name,
            "state": obj_state,
            "burn": {
                _win_label(w): round(b, 3)
                for (w, *_), b in zip(win_stats, burns)
            },
        })
    payload = {
        "state": state,
        "objectives": out_objectives,
        "windows": {
            _win_label(w): {
                "requests": reqs,
                "error_ratio": round(errs / reqs, 6) if reqs else 0.0,
                "p50_ms": round(
                    metrics.quantile_from_buckets(UPPERS, lat, 0.5) * 1e3, 3
                ),
                "p99_ms": round(
                    metrics.quantile_from_buckets(UPPERS, lat, 0.99) * 1e3, 3
                ),
            }
            for w, reqs, errs, lat in win_stats
        },
    }
    # refresh the exported gauges for this program (label cardinality is
    # bounded by the window-map guard above)
    M_SLO_STATE.labels(program=program).set(STATES.index(state))
    for o, obj in zip(out_objectives, objectives):
        for wl, b in o["burn"].items():
            M_SLO_BURN.labels(
                program=program, objective=obj.name, window=wl
            ).set(b)
    # a replaced override must not leave the OLD objective's burn series
    # frozen at its last value (a Prometheus alert on it would never
    # clear) — drop this program's children for objectives that no
    # longer exist
    current = {obj.name for obj in objectives}
    M_SLO_BURN.prune(
        lambda kv: kv["program"] == program
        and kv["objective"] not in current
    )
    for w, reqs, errs, lat in win_stats:
        wl = _win_label(w)
        M_SLO_ERR.labels(program=program, window=wl).set(
            errs / reqs if reqs else 0.0
        )
        M_SLO_P99.labels(program=program, window=wl).set(
            metrics.quantile_from_buckets(UPPERS, lat, 0.99)
        )
    return payload


def _win_label(w: float) -> str:
    if w >= 3600 and w % 3600 == 0:
        return f"{int(w // 3600)}h"
    if w >= 60 and w % 60 == 0:
        return f"{int(w // 60)}m"
    return f"{w:g}s"


def evaluate(program: str) -> dict:
    """One program's cached evaluation (cache TTL 0.25s: /healthz probes
    and scrapes must not re-walk every ring on every poll)."""
    now = time.monotonic()
    cached = _eval_cache.get(program)
    if cached is not None and now - cached[0] < 0.25:
        return cached[1]
    payload = _evaluate(program, now)
    _eval_cache[program] = (now, payload)
    return payload


def _program_set() -> list[str]:
    with _lock:
        names = set(_windows) | set(_overrides)
    if _default_objectives and not names:
        names = {"default"}
    return sorted(names)


def evaluate_all() -> dict[str, dict]:
    return {p: evaluate(p) for p in _program_set()}


def overall_state() -> str | None:
    """The worst program state, or None while disarmed (the /healthz
    `degraded` integration keys on "page")."""
    if not armed():
        return None
    worst = "ok"
    for payload in evaluate_all().values():
        if STATES.index(payload["state"]) > STATES.index(worst):
            worst = payload["state"]
    return worst


def refresh_metrics() -> None:
    """Refresh the misaka_slo_* gauges (the /metrics route calls this
    before rendering; a no-op while disarmed)."""
    if armed():
        evaluate_all()


def debug_payload() -> dict:
    """The GET /debug/alerts body."""
    out = {
        "enabled": armed(),
        "default_objectives": [o.name for o in _default_objectives],
        "overrides": {
            name: [o.name for o in objs]
            for name, objs in sorted(_overrides.items())
        },
        "windows_s": list(_WINDOWS),
        "min_events": _MIN_EVENTS,
        "burn_rules": [
            {
                "long": _win_label(_WINDOWS[li]),
                "short": _win_label(_WINDOWS[si]),
                "burn": t,
                "state": s,
            }
            for li, si, t, s in BURN_RULES
        ],
        "state": overall_state() or "ok",
        "programs": evaluate_all(),
    }
    if _spec_error:
        out["spec_error"] = _spec_error
    return out
