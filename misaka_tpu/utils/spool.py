"""Append-only segment spool: the one shared durability primitive under
the telemetry plane (TSDB retention in utils/tsdb.py, the billing ledger
in runtime/usage.py).

A spool is a directory of numbered segment files, each MAGIC (8 bytes)
followed by u32-LE length-prefixed JSON frames — the same framing as the
.mskcap capture segments, minus the manifest sidecar, because a spool's
tail is a LIVE append target, not a finalized artifact.  The writer
appends frames and fsyncs on flush(); a crash mid-append leaves at most
one torn frame at the tail, which reload() truncates away (and keeps
appending after — a torn tail is expected wear, not corruption).

Rotation + retention: when the active segment passes ``segment_bytes``
the writer rolls to the next sequence number; when the directory passes
``budget_bytes`` the OLDEST segments are unlinked first.  Both events
are reported through the caller's counters (``on_evict`` /
``on_error``) — a silent cap would read as "retained everything".

Single-writer discipline: exactly one thread appends (the TSDB
collector tick, or the usage flusher).  Readers (usage export walks the
frames; boot-time reload) tolerate a concurrent tail append by stopping
at the first torn frame instead of raising.
"""

from __future__ import annotations

import json
import logging
import os
import struct

from misaka_tpu.utils import metrics

log = logging.getLogger("misaka.spool")

# One shared error family across every spooling plane (TSDB retention,
# the usage ledger, capture rotation) — the watchdog's spool-health rule
# watches this name.
M_SPOOL_ERRORS = metrics.counter(
    "misaka_spool_errors_total",
    "Telemetry spool write/read failures, by plane",
    ("plane",),
)

MAGIC = b"MSKSPL1\n"
_LEN = struct.Struct("<I")
_MAX_FRAME = 64 << 20

DEFAULT_SEGMENT_BYTES = 4 << 20


class SpoolError(Exception):
    """Unusable spool directory or malformed segment content."""


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class SegmentSpool:
    """One spool directory: numbered ``<prefix>-<seq>.seg`` segments."""

    def __init__(self, directory: str, prefix: str = "spool", *,
                 budget_bytes: int = 64 << 20,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 on_evict=None, on_error=None):
        self.dir = directory
        self.prefix = prefix
        self.budget_bytes = max(1 << 16, int(budget_bytes))
        self.segment_bytes = max(1 << 12, int(segment_bytes))
        self._on_evict = on_evict or (lambda n: None)
        self._on_error = on_error or (lambda: None)
        self._fd = None
        self._active_seq = -1
        self._active_bytes = 0
        self._next_seq = 0
        self.evicted = 0
        self.errors = 0

    # --- layout -------------------------------------------------------------

    def _path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{self.prefix}-{seq:08d}.seg")

    def segments(self) -> list[tuple[int, str]]:
        """[(seq, path)] sorted oldest-first (missing dir -> [])."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        want = f"{self.prefix}-"
        for name in names:
            if not (name.startswith(want) and name.endswith(".seg")):
                continue
            try:
                seq = int(name[len(want):-len(".seg")])
            except ValueError:
                continue
            out.append((seq, os.path.join(self.dir, name)))
        out.sort()
        return out

    def disk_bytes(self) -> int:
        total = 0
        for _, path in self.segments():
            try:
                total += os.path.getsize(path)
            except OSError:
                pass
        return total

    # --- reload (boot) ------------------------------------------------------

    def reload(self, fn=None) -> int:
        """Walk every retained frame oldest-first, truncating torn tails
        in place, then position the writer after the newest frame.
        ``fn(frame_dict)`` per frame; returns frames seen.  Unreadable
        segments are counted + skipped, never fatal — a booting server
        must come up even over a mangled spool."""
        os.makedirs(self.dir, exist_ok=True)
        frames = 0
        segs = self.segments()
        for seq, path in segs:
            self._next_seq = max(self._next_seq, seq + 1)
            try:
                frames += self._walk_one(path, fn, repair=True)
            except (OSError, SpoolError) as e:
                log.warning("spool %s: skipping unreadable segment %s: %s",
                            self.prefix, path, e)
                self._record_error()
        # keep appending to the newest segment when it has headroom
        if segs:
            seq, path = segs[-1]
            try:
                size = os.path.getsize(path)
            except OSError:
                size = self.segment_bytes
            if 0 < size < self.segment_bytes:
                try:
                    self._fd = open(path, "ab")
                    self._active_seq = seq
                    self._active_bytes = size
                except OSError as e:
                    log.warning("spool %s: cannot reopen %s: %s",
                                self.prefix, path, e)
                    self._record_error()
        return frames

    def _walk_one(self, path: str, fn, repair: bool) -> int:
        """Frames of one segment; with ``repair`` a torn tail is
        truncated away in place (crash recovery), without it the walk
        just stops there (a reader racing the live appender)."""
        frames = 0
        with open(path, "r+b" if repair else "rb") as f:
            magic = f.read(len(MAGIC))
            if magic != MAGIC:
                raise SpoolError(f"bad magic {magic!r}")
            good = f.tell()
            while True:
                raw = f.read(4)
                if not raw:
                    break
                if len(raw) < 4:
                    self._truncate_tail(f, path, good, repair)
                    break
                (length,) = _LEN.unpack(raw)
                if length > _MAX_FRAME:
                    self._truncate_tail(f, path, good, repair)
                    break
                blob = f.read(length)
                if len(blob) < length:
                    self._truncate_tail(f, path, good, repair)
                    break
                try:
                    frame = json.loads(blob.decode())
                except (ValueError, UnicodeDecodeError):
                    self._truncate_tail(f, path, good, repair)
                    break
                good = f.tell()
                frames += 1
                if fn is not None:
                    fn(frame)
        return frames

    def _truncate_tail(self, f, path: str, good: int, repair: bool) -> None:
        if not repair:
            return
        log.warning("spool %s: torn tail in %s, truncating to %d bytes",
                    self.prefix, path, good)
        f.truncate(good)
        f.flush()
        os.fsync(f.fileno())

    def read_frames(self, fn) -> int:
        """Read-only walk of every retained frame oldest-first (exports;
        safe against the live appender: stops at a torn tail)."""
        frames = 0
        for _, path in self.segments():
            try:
                frames += self._walk_one(path, fn, repair=False)
            except (OSError, SpoolError):
                continue
        return frames

    # --- append (the single writer) -----------------------------------------

    def append(self, obj: dict) -> bool:
        """Serialize + buffer one frame (no fsync until flush()).
        Returns False (and counts the error) when the write fails — the
        caller's telemetry tick must never die on a full disk."""
        blob = json.dumps(obj, separators=(",", ":")).encode()
        try:
            if self._fd is None:
                os.makedirs(self.dir, exist_ok=True)
                seq = self._next_seq
                self._next_seq = seq + 1
                f = open(self._path(seq), "ab")
                if f.tell() == 0:
                    f.write(MAGIC)
                self._fd = f
                self._active_seq = seq
                self._active_bytes = f.tell()
            self._fd.write(_LEN.pack(len(blob)))
            self._fd.write(blob)
            self._active_bytes += 4 + len(blob)
            return True
        except (OSError, ValueError) as e:
            log.warning("spool %s: append failed: %s", self.prefix, e)
            self._close_fd()
            self._record_error()
            return False

    def flush(self) -> None:
        """fsync the active segment, rotate past ``segment_bytes``, and
        evict oldest segments past ``budget_bytes``."""
        if self._fd is not None:
            try:
                self._fd.flush()
                os.fsync(self._fd.fileno())
            except (OSError, ValueError) as e:
                log.warning("spool %s: fsync failed: %s", self.prefix, e)
                self._close_fd()
                self._record_error()
            else:
                if self._active_bytes >= self.segment_bytes:
                    self._close_fd()
                    _fsync_dir(self.dir)
        self._enforce_budget()

    def _enforce_budget(self) -> None:
        segs = self.segments()
        sizes = {}
        total = 0
        for seq, path in segs:
            try:
                sizes[seq] = os.path.getsize(path)
            except OSError:
                sizes[seq] = 0
            total += sizes[seq]
        evicted = 0
        for seq, path in segs:
            if total <= self.budget_bytes:
                break
            if seq == self._active_seq:
                break  # never evict the live append target
            try:
                os.unlink(path)
            except OSError as e:
                log.warning("spool %s: evict of %s failed: %s",
                            self.prefix, path, e)
                self._record_error()
                continue
            total -= sizes[seq]
            evicted += 1
        if evicted:
            self.evicted += evicted
            log.warning(
                "spool %s: disk budget %.1f MiB exceeded — evicted %d "
                "oldest segment(s)", self.prefix,
                self.budget_bytes / (1 << 20), evicted,
            )
            try:
                self._on_evict(evicted)
            except Exception:  # pragma: no cover — counters must not kill IO
                pass

    def _record_error(self) -> None:
        self.errors += 1
        try:
            self._on_error()
        except Exception:  # pragma: no cover
            pass

    def _close_fd(self) -> None:
        if self._fd is not None:
            try:
                self._fd.close()
            except OSError:
                pass
            self._fd = None
            self._active_seq = -1
            self._active_bytes = 0

    def close(self) -> None:
        self.flush()
        self._close_fd()
