"""Fault injection: named failure points driven by one env spec.

The robustness plane (frontend supervisor, durable checkpoints, RPC
backoff) exists to survive process death, torn writes, and dead peers —
failures that never occur in a clean test run.  This module makes them
occur ON DEMAND, in-process and cheaply, so the chaos suite and
`make chaos-smoke` exercise the recovery paths end to end instead of
trusting them by inspection.

Spec (env var `MISAKA_FAULTS`, or `configure()` for tests): a comma-
separated list of armed fault points,

    MISAKA_FAULTS="ckpt_torn_write=0.5,rpc_delay=0.2@0.1,worker_exit=1.5"

each entry `name[=value][@probability]`:

  * `name`        — one of the named points below (unknown names are an
                    error at parse time: a typo'd fault spec silently
                    injecting nothing would be worse than no harness).
  * `value`       — a float parameter the point interprets (default 1.0).
  * `probability` — chance in [0, 1] that an individual `fire()` call
                    triggers (default 1.0 = always).

Named points (the hook sites live next to the code they break):

  worker_exit     — a frontend worker process hard-exits `value` seconds
                    after boot (runtime/frontends.py frontend_main): the
                    supervisor's respawn path, without kill(1).
  rpc_drop        — a gRPC client call raises InjectedRpcError instead of
                    sending (transport/rpc.py): the node retry/backoff
                    path and the control plane's peer-health accounting.
  rpc_delay       — a gRPC client call sleeps `value` seconds before
                    sending: deadline and slow-peer behavior.
  ckpt_torn_write — the checkpoint file is truncated to `value` fraction
                    of its bytes AFTER the atomic replace
                    (runtime/master.py save_checkpoint): simulates the
                    torn write a crash mid-`np.savez` used to leave, so
                    the manifest/checksum rejection path is exercised.
  ckpt_crash      — save_checkpoint raises after writing the tmp file but
                    BEFORE the atomic replace: the crash the atomic write
                    discipline exists for (target must stay intact).
  swap_during_load — the program registry's hot-swap sleeps `value`
                    seconds WITH THE PARK GATE CLOSED (between building
                    the replacement engine and installing it,
                    runtime/registry.py): every alias-addressed request
                    arriving in that window parks — the widened race the
                    zero-client-visible-errors swap contract is tested
                    against.
  serve_delay     — every serve-scheduler pass sleeps `value` seconds
                    before dispatching (runtime/master.py ServeBatcher):
                    the rpc_delay of the fused serving plane.  The scoped
                    form `serve_delay:<program>` delays ONLY that
                    registry program's passes — the per-tenant SLO chaos
                    scenario (one tenant pages on /debug/alerts, its
                    neighbors stay green; tests/test_slo.py).
  replica_kill    — the fleet manager SIGKILLs one live engine replica
                    `value` seconds after fleet start (runtime/fleet.py
                    FleetManager.start, fired once per boot): the
                    kill(9)-under-load failover scenario — the router
                    must hedge in-flight frames onto siblings with zero
                    client-visible errors and the supervisor must
                    respawn the replica.
  replica_blackhole — an engine replica's compute plane HOLDS each
                    frame unanswered for `value` seconds before serving
                    it (runtime/frontends.py ComputePlane): the
                    grey-failure twin of replica_kill — the process is
                    alive but silent, so the router's frame deadline
                    (not a connection error) must trip the hedge.  The
                    scoped form `replica_blackhole:<idx>` blackholes
                    ONLY the fleet replica with that MISAKA_FLEET_REPLICA
                    index — siblings stay healthy, which is exactly what
                    the hedge contract is tested against.  Use @prob to
                    blackhole a fraction of frames.
  overload        — the edge admission governor (runtime/edge.py) treats
                    the plane as saturated and sheds with a typed 429 +
                    Retry-After, without needing 4x real load.  The
                    scoped form `overload:<tenant>` saturates ONLY that
                    tenant's admissions — the fair-share shed drill (the
                    flooded tenant sheds, its neighbor's in-quota
                    traffic sees zero errors; tests/test_chaos.py).
  quota_exhaust   — every quota check at the edge (runtime/edge.py)
                    reports its token bucket empty: the typed-429 +
                    Retry-After client-backoff path, exercised at the
                    real admission sites.
  plane_partition — plane frames to a peer are black-holed
                    (runtime/frontends.py PlaneClient): dials to the
                    peer fail and queued frames are never written, so
                    the frame deadline — not a connection error — trips
                    the router's hedge, and the peer probe keeps
                    reporting it down.  The scoped form
                    `plane_partition:<addr>` partitions ONLY the plane
                    whose address (socket path or host:port) contains
                    that substring — siblings stay reachable, which is
                    the multi-host partition drill: a partitioned
                    remote replica fails over onto its siblings with
                    zero client-visible errors (tests/test_chaos.py).
  plane_delay     — every plane frame send sleeps `value` seconds
                    before hitting the wire (runtime/frontends.py
                    PlaneClient): the WAN-latency twin of rpc_delay
                    for the multi-host plane — deadline margins and
                    hedge budgets under slow links.  Use @prob to
                    delay a fraction of frames.

Fault checks are zero-cost when nothing is armed (`fire` returns None
after one dict lookup on an empty dict); the module imports stdlib only —
it is imported by the jax-free frontend workers.
"""

from __future__ import annotations

import os
import random
import threading

POINTS = frozenset({
    "worker_exit",
    "rpc_drop",
    "rpc_delay",
    "ckpt_torn_write",
    "ckpt_crash",
    "swap_during_load",
    "serve_delay",
    "replica_kill",
    "replica_blackhole",
    "overload",
    "quota_exhaust",
    "specialize_fail",
    "edge_native_build",
    "resident_fallback",
    "jit_fail",
    "plane_partition",
    "plane_delay",
})

# Points that accept a ":<qualifier>" suffix scoping the fault to one
# target: `serve_delay:tenant-b=0.05` injects latency into ONLY that
# registry program's serve passes (runtime/master.py ServeBatcher) — the
# per-tenant SLO chaos scenario, where one program must page while its
# neighbors stay green.
SCOPED_POINTS = frozenset(
    {"serve_delay", "replica_blackhole", "overload", "plane_partition"}
)


class FaultSpecError(ValueError):
    """Malformed MISAKA_FAULTS spec (unknown point, bad value/probability)."""


def parse_spec(text: str | None) -> dict[str, tuple[float, float]]:
    """`name[=value][@prob],...` -> {name: (value, probability)}."""
    spec: dict[str, tuple[float, float]] = {}
    for raw in (text or "").split(","):
        entry = raw.strip()
        if not entry:
            continue
        prob = 1.0
        if "@" in entry:
            entry, prob_s = entry.rsplit("@", 1)
            try:
                prob = float(prob_s)
            except ValueError:
                raise FaultSpecError(
                    f"cannot parse probability {prob_s!r} in {raw!r}"
                ) from None
            if not 0.0 <= prob <= 1.0:
                raise FaultSpecError(
                    f"probability must be in [0, 1], got {prob} in {raw!r}"
                )
        value = 1.0
        if "=" in entry:
            entry, value_s = entry.split("=", 1)
            try:
                value = float(value_s)
            except ValueError:
                raise FaultSpecError(
                    f"cannot parse value {value_s!r} in {raw!r}"
                ) from None
        name = entry.strip()
        base = name.split(":", 1)[0]
        if name not in POINTS and not (
            ":" in name and base in SCOPED_POINTS and name[len(base) + 1:]
        ):
            raise FaultSpecError(
                f"unknown fault point {name!r} (known: {sorted(POINTS)}; "
                f"scoped: {sorted(SCOPED_POINTS)} accept ':<target>')"
            )
        spec[name] = (value, prob)
    return spec


# The armed spec is an IMMUTABLE dict swapped whole by configure(): readers
# (the hot RPC / device-loop hook sites) take no lock — a reference read is
# atomic under the GIL, and a reader sees either the old or the new spec,
# never a torn one.  The lock only serializes concurrent configure() calls.
_lock = threading.Lock()
_spec: dict[str, tuple[float, float]] = parse_spec(os.environ.get("MISAKA_FAULTS"))


def configure(text: str | None) -> None:
    """Re-arm from a spec string (tests); None/"" disarms everything."""
    global _spec
    parsed = parse_spec(text)
    with _lock:
        _spec = parsed


def armed() -> bool:
    """True when ANY fault point is armed — the one-dict-truthiness check
    hot paths use to skip their per-point rolls entirely."""
    return bool(_spec)


def active() -> frozenset[str]:
    """The currently armed point names (empty when faults are off)."""
    return frozenset(_spec)


def fire(point: str) -> float | None:
    """Roll the dice for one armed fault point.

    Returns the point's configured value when it triggers, None when the
    point is unarmed or its probability roll misses.  Callers interpret
    the value (seconds, fraction, ...) at the hook site.  Lock-free: one
    dict lookup on the current (immutable) spec.
    """
    armed_point = _spec.get(point)
    if armed_point is None:
        return None
    value, prob = armed_point
    if prob < 1.0 and random.random() >= prob:
        return None
    return value
