"""Opt-in structured JSON logging (MISAKA_LOG_JSON=1, runtime/app.py).

One JSON object per line on stderr — the shape container log pipelines
(fluentd / vector / CloudWatch) parse without grok rules:

  {"time": "2026-08-03T12:00:00.123Z", "level": "INFO",
   "logger": "misaka_tpu.master", "msg": "network was run",
   "route": "/run"}

`route` appears when the record carries one (the HTTP handler passes
`extra={"route": ...}` in runtime/master.py log_message); `trace_id`
appears on every line emitted while a request trace is in scope on the
logging thread (utils/tracespan.py context var — the join key that lets
a log line be matched to its `/debug/requests/<id>` entry); exceptions
land under "exc" as a single escaped string, so a traceback stays ONE
log event instead of N unparseable lines.  Stdlib-only by design — same
constraint as the metrics plane (utils/metrics.py): nothing to pip
install.
"""

from __future__ import annotations

import json
import logging
import sys
import time

from misaka_tpu.utils import tracespan


def _current_program() -> str | None:
    """The lease-context program (lazy import: runtime.usage sits one
    package over; a plain-format process must not pay for it at import)."""
    try:
        from misaka_tpu.runtime import usage

        return usage.current_program()
    except Exception:  # pragma: no cover — logging must never crash
        return None


class JsonFormatter(logging.Formatter):
    """Format every record as one JSON object per line."""

    def format(self, record: logging.LogRecord) -> str:
        obj = {
            # UTC ISO-8601 with ms: sortable, timezone-unambiguous
            "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            ) + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        route = getattr(record, "route", None)
        if route:
            obj["route"] = route
        # an explicit extra={"trace_id": ...} wins; otherwise the trace
        # current on the EMITTING thread (set by the HTTP handlers)
        trace_id = getattr(record, "trace_id", None) or tracespan.current_id()
        if trace_id:
            obj["trace_id"] = trace_id
        # the program (tenant) in scope on the emitting thread — set by
        # the registry lease (runtime/usage.py program_scope) — so
        # log <-> trace <-> tenant correlation is one grep
        program = getattr(record, "program", None) or _current_program()
        if program:
            obj["program"] = program
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        # default=str: a log call must never crash on an unserializable arg
        return json.dumps(obj, ensure_ascii=False, default=str)


def install(level: int = logging.INFO, stream=None) -> None:
    """Replace root handlers with one JSON-formatted stderr handler."""
    handler = logging.StreamHandler(stream if stream is not None else sys.stderr)
    handler.setFormatter(JsonFormatter())
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(level)
