"""Embedded time-series history: ring-buffer retention over the live
metrics registry, served at GET /debug/series.

Every surface the health plane grew through PR 7-9 — /metrics,
/debug/alerts, /debug/usage, /debug/flamegraph — is a point-in-time
snapshot: "is p99 drifting since the last roll?" and "which replica
degraded first?" needed an external Prometheus nobody wires up on a
single box.  This module is the retained-history substrate those
questions (and the ROADMAP's autoscaling loop) read: a background
collector samples the process metrics registry (utils/metrics.py) every
``MISAKA_TSDB_INTERVAL_S`` seconds into fixed-size ring buffers with
staged downsampling, and a query API slices any series over any window
up to the retention horizon.

Sampling semantics per metric kind:

  * Counter   — stored as a RATE (delta / elapsed since the previous
                sample; a process restart resets counters, so a negative
                delta re-bases instead of spiking).  The series keeps the
                counter's name.
  * Gauge     — stored verbatim.
  * Histogram — three derived series per child: ``<name>:p50`` and
                ``<name>:p99`` estimated from the PER-INTERVAL bucket
                delta (utils/metrics.quantile_from_buckets — the interval
                with no observations writes nothing, not a false zero),
                and ``<name>:rate`` (observations/s).

Staged downsampling: every sample lands in all retention stages at once —
by default ``interval x 720`` (1 h at the 5 s default), ``1 m x 360``
(6 h), and ``5 m x 288`` (24 h).  A stage slot aggregates mean AND max
(a p99 spike must survive downsampling), and slots are positional rings
keyed by absolute epoch (``int(unix / width)``) — the same
stale-slot-reclaim discipline as the SLO windows, so idle time cannot
leak month-old points into a fresh window.  Wall-clock epochs are
deliberate: they are timestamps (the dashboard's x-axis, and what lets a
restored snapshot land in the right slots after a process restart);
durations and deadlines elsewhere in this module use time.monotonic().

Memory is bounded twice over: per series, the three stages hold
720+360+288 = 1368 slots x 28 bytes (epoch int64 + sum double + count
uint32 + max double in array-module storage) ~= 38 KiB; and at most
``MISAKA_TSDB_MAX_SERIES`` (default 512) series are retained — worst
case ~20 MiB.  Past the cap NEW series are dropped and counted
(``dropped_series`` on the index payload — a silent cap would read as
"covered everything").  Golden-signal families are collected first each
sample, so a per-program label flood can never crowd out the dashboard's
own series.

Collector cost is governed like the PR 7 stack sampler: the loop EMAs
its own per-sample wall cost and stretches its period to stay under
``MISAKA_TSDB_BUDGET`` (default 1%) of one core.

History survives restarts through the durable-checkpoint path:
``snapshot_bytes()`` rides ``__tsdb__`` inside MasterNode checkpoints
and ``restore_bytes()`` merges it back — a restored slot installs only
where it is strictly NEWER than what the live ring holds, which makes a
stale eviction-era checkpoint a no-op and a fleet-roll restore a full
history handoff with the same rule.

Durable retention (the telemetry plane's tentpole): set
``MISAKA_TSDB_DIR`` and the collector's tick also appends every
FINALIZED ring slot to fsync'd segment files there (utils/spool.py —
length-prefixed frames, torn-tail truncation on reopen, rotation, and
oldest-segment eviction under ``MISAKA_TSDB_DISK_MB``, counted on
``misaka_tsdb_spool_dropped_total``).  Two tiers: "fine" persists the
finest stage (full-resolution restart continuity), "long" persists a
coarse long-horizon stage (``MISAKA_TSDB_LONG_S`` x
``MISAKA_TSDB_LONG_SLOTS``, 5 m x 4032 = two weeks by default) that also
DEEPENS the in-memory coarsest ring so ``window=7d`` answers from RAM.
Boot reloads the spool back into the rings — /debug/series spans
restarts without checkpoints.  Unset, nothing changes: no thread, no
file, no extra stage.

Stdlib-only, like the rest of the plane.  ``MISAKA_TSDB=0`` is the kill
switch; ``shutdown()`` stops the collector (the bench A/B measures both
sides).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from array import array

from misaka_tpu.utils import metrics
from misaka_tpu.utils.spool import M_SPOOL_ERRORS, SegmentSpool

DEFAULT_INTERVAL_S = 5.0
DEFAULT_MAX_SERIES = 512
DEFAULT_BUDGET = 0.01
DEFAULT_DISK_MB = 64.0
DEFAULT_LONG_S = 300.0
DEFAULT_LONG_SLOTS = 4032  # two weeks of 5 m slots

M_SPOOL_DROPPED = metrics.counter(
    "misaka_tsdb_spool_dropped_total",
    "TSDB spool segments evicted by the MISAKA_TSDB_DISK_MB budget",
)
M_SPOOL_BYTES = metrics.gauge(
    "misaka_tsdb_spool_bytes",
    "On-disk footprint of the TSDB retention spool",
)

# Families sampled FIRST each pass (the dashboard's golden signals and
# the watchdog's default rules): a label flood elsewhere may exhaust the
# series cap, but never these.
PRIORITY_PREFIXES = (
    "misaka_canary_",
    "misaka_http_",
    "misaka_compute_",
    "misaka_serve_",
    "misaka_edge_",
    "misaka_fleet_",
    "misaka_native_pool_",
    "misaka_usage_values_total",
    "misaka_slo_p99_seconds",
    "misaka_frontend_",
)


class TSDBError(ValueError):
    """Invalid query or snapshot content."""


def parse_window(text: str | float | int, allow_zero: bool = False) -> float:
    """``"30s"`` / ``"5m"`` / ``"1h"`` / ``"7d"`` / bare seconds ->
    seconds.  `allow_zero` admits 0 (the watchdog's no-sustain clause);
    a query window stays strictly positive."""
    if isinstance(text, (int, float)):
        v = float(text)
    else:
        t = str(text).strip().lower()
        mult = 1.0
        if t.endswith("d"):
            mult, t = 86400.0, t[:-1]
        elif t.endswith("h"):
            mult, t = 3600.0, t[:-1]
        elif t.endswith("m"):
            mult, t = 60.0, t[:-1]
        elif t.endswith("s"):
            t = t[:-1]
        try:
            v = float(t) * mult
        except ValueError:
            raise TSDBError(f"cannot parse window {text!r} "
                            f"(use e.g. 30s / 5m / 1h / 7d)") from None
    if v < 0 or (v == 0 and not allow_zero):
        raise TSDBError(f"window must be > 0, got {text!r}")
    return v


def parse_query(query: dict) -> tuple[str | None, dict[str, str], float]:
    """The GET /debug/series query contract, shared by the engine and
    fleet handlers (one copy of the grammar): `query` is a parse_qs
    dict; returns (name-or-None, label filters, window seconds).
    Raises TSDBError (the handlers answer it as 400) on a malformed
    window or a label entry that is not k=v."""
    window_s = parse_window(query.get("window", ["1h"])[0])
    name = query.get("name", [None])[0]
    labels: dict[str, str] = {}
    for item in query.get("label", ()):
        k, sep, v = item.partition("=")
        if not sep:
            raise TSDBError(f"label filter {item!r} is not k=v")
        labels[k] = v
    return name, labels, window_s


def env_float(environ, name: str, default: float) -> float:
    """An env knob parsed with a silent fallback (a typo'd MISAKA_*
    value must not take down a booting server) — the one shared copy
    for this module's and the watchdog's ensure_started."""
    try:
        return float(environ.get(name, "") or default)
    except ValueError:
        return default


def _stage_plan(interval_s: float, long_s: float | None = None,
                long_slots: int = DEFAULT_LONG_SLOTS,
                ) -> tuple[tuple[float, int], ...]:
    """(width_s, length) per retention stage for one sample interval.
    Coarser stages keep their absolute spans when the interval shrinks
    (tests run 50 ms intervals; the 1 m/5 m tiers stay meaningful), and
    widen to the interval when it grows past them.  With the disk spool
    armed (``long_s`` set) the coarsest stage becomes the long-horizon
    tier: ``long_s``-wide slots held ``long_slots`` deep (two weeks at
    the 5 m default), the in-memory landing zone the spool reloads into
    so ``window=7d`` answers from RAM after a restart."""
    stages = [(interval_s, 720)]
    for width, length in ((60.0, 360), (300.0, 288)):
        if width > interval_s and (long_s is None or width < long_s):
            stages.append((width, length))
    if long_s is not None and long_s > interval_s:
        stages.append((long_s, max(288, int(long_slots))))
    return tuple(stages)


class _Ring:
    """One retention stage of one series: positional slots keyed by
    absolute epoch, each aggregating (sum, count, max) of the samples
    that landed in its span."""

    __slots__ = ("width", "length", "epochs", "sums", "counts", "maxs")

    def __init__(self, width: float, length: int):
        self.width = float(width)
        self.length = int(length)
        self.epochs = array("q", [-1]) * self.length
        self.sums = array("d", [0.0]) * self.length
        self.counts = array("L", [0]) * self.length
        self.maxs = array("d", [0.0]) * self.length

    def add(self, now_unix: float, value: float) -> None:
        epoch = int(now_unix / self.width)
        i = epoch % self.length
        if self.epochs[i] != epoch:
            self.epochs[i] = epoch
            self.sums[i] = 0.0
            self.counts[i] = 0
            self.maxs[i] = value
        self.sums[i] += value
        self.counts[i] += 1
        if value > self.maxs[i]:
            self.maxs[i] = value

    def points(self, now_unix: float, window_s: float) -> list[list[float]]:
        """[[slot_start_unix, mean, max], ...] oldest -> newest over the
        last `window_s` (unwritten / stale slots skipped)."""
        n = min(self.length, max(1, math.ceil(window_s / self.width)))
        epoch_now = int(now_unix / self.width)
        out: list[list[float]] = []
        for back in range(n - 1, -1, -1):
            epoch = epoch_now - back
            i = epoch % self.length
            if self.epochs[i] != epoch or not self.counts[i]:
                continue
            out.append([
                round(epoch * self.width, 3),
                self.sums[i] / self.counts[i],
                self.maxs[i],
            ])
        return out

    def install(self, epoch: int, total: float, count: int,
                peak: float) -> None:
        """Snapshot restore: install a slot only where it is strictly
        newer than the live ring's occupant — a stale (eviction-era)
        snapshot must never clobber fresher history, and re-restoring
        the same snapshot must never double-count."""
        i = epoch % self.length
        if epoch > self.epochs[i]:
            self.epochs[i] = epoch
            self.sums[i] = total
            self.counts[i] = count
            self.maxs[i] = peak

    def merge(self, epoch: int, total: float, count: int,
              peak: float) -> None:
        """Spool reload: ACCUMULATE into a matching-epoch slot (a fine
        on-disk slot re-aggregating into a coarser ring), install fresh
        where newer, and — unlike install() — never touch a slot the
        live ring already holds newer data for."""
        i = epoch % self.length
        if epoch > self.epochs[i]:
            self.epochs[i] = epoch
            self.sums[i] = total
            self.counts[i] = count
            self.maxs[i] = peak
        elif epoch == self.epochs[i]:
            self.sums[i] += total
            self.counts[i] += count
            if peak > self.maxs[i]:
                self.maxs[i] = peak

    def slot_at(self, epoch: int) -> tuple[float, int, float] | None:
        """(sum, count, max) of one absolute epoch, None when unwritten
        or reclaimed — the spool writer's finalized-slot read."""
        i = epoch % self.length
        if self.epochs[i] != epoch or not self.counts[i]:
            return None
        return (self.sums[i], int(self.counts[i]), self.maxs[i])

    def dump(self) -> list[list[float]]:
        out = []
        for i in range(self.length):
            if self.epochs[i] >= 0 and self.counts[i]:
                out.append([
                    int(self.epochs[i]), self.sums[i],
                    int(self.counts[i]), self.maxs[i],
                ])
        return out


class _Series:
    """All retention stages of one series."""

    __slots__ = ("name", "labels", "kind", "stages")

    def __init__(self, name: str, labels: dict[str, str], kind: str,
                 plan: tuple[tuple[float, int], ...]):
        self.name = name
        self.labels = labels
        self.kind = kind  # "rate" | "gauge" | "quantile"
        self.stages = tuple(_Ring(w, n) for w, n in plan)

    def add(self, now_unix: float, value: float) -> None:
        for ring in self.stages:
            ring.add(now_unix, value)

    def stage_for(self, window_s: float) -> _Ring:
        """The finest stage whose retention covers the window (the
        coarsest one when nothing does)."""
        for ring in self.stages:
            if ring.width * ring.length >= window_s:
                return ring
        return self.stages[-1]


class TSDB:
    """The store + the governed collector thread."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 max_series: int = DEFAULT_MAX_SERIES,
                 budget: float = DEFAULT_BUDGET, registry=None,
                 spool_dir: str | None = None,
                 disk_mb: float = DEFAULT_DISK_MB,
                 long_s: float = DEFAULT_LONG_S,
                 long_slots: int = DEFAULT_LONG_SLOTS,
                 segment_bytes: int = 1 << 20):
        self.interval_s = max(0.02, float(interval_s))
        self.max_series = max(16, int(max_series))
        self.budget = min(0.5, max(0.001, float(budget)))
        self._registry = registry if registry is not None else metrics.REGISTRY
        self.spool_dir = spool_dir
        self._long_armed = (
            spool_dir is not None and float(long_s) > self.interval_s
        )
        self._plan = _stage_plan(
            self.interval_s,
            long_s=float(long_s) if self._long_armed else None,
            long_slots=long_slots,
        )
        self._lock = threading.Lock()
        self._series: dict[tuple, _Series] = {}  # (name, sorted-label-items)
        self._dropped: set[tuple] = set()
        self._samples = 0
        self._cost_ema = 0.0
        # previous raw values for rate/quantile derivation, keyed like
        # _series: counters -> float, histograms -> (counts, last_mono)
        self._prev_counter: dict[tuple, float] = {}
        self._prev_hist: dict[tuple, list[int]] = {}
        self._last_mono: float | None = None
        # per-tick hooks (the regression watchdog registers here: rules
        # evaluate right after each sample lands, on this thread — no
        # second clock, no second thread)
        self._hooks: list = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # --- the disk spool (MISAKA_TSDB_DIR; None = today's in-memory
        # behavior).  Two tiers ride the same SegmentSpool discipline:
        # "fine" persists every finalized finest-stage slot (restart
        # continuity at full resolution, ~days under the budget) and
        # "long" persists the coarse long-horizon slots (weeks).  The
        # budget splits 3:1 fine:long.
        self._spools: dict[str, SegmentSpool] = {}
        self._flushed_epoch: dict[str, int] = {}
        self._long_hi = -1  # newest long-tier epoch seen at reload
        self.spooled_frames = 0
        self.reloaded_frames = 0
        if spool_dir is not None:
            budget_bytes = max(1 << 20, int(float(disk_mb) * (1 << 20)))
            tiers = [("fine", self.stages_widths()[0], budget_bytes * 3 // 4)]
            if self._long_armed:
                tiers.append(
                    ("long", self.stages_widths()[-1], budget_bytes // 4)
                )
            now_unix = time.time()
            for tier, width, tier_budget in tiers:
                sp = SegmentSpool(
                    spool_dir, prefix=f"tsdb-{tier}",
                    budget_bytes=tier_budget,
                    segment_bytes=segment_bytes,
                    on_evict=M_SPOOL_DROPPED.inc,
                    on_error=lambda: M_SPOOL_ERRORS.labels(
                        plane="tsdb").inc(),
                )
                self._spools[tier] = sp
                self._flushed_epoch[tier] = int(now_unix / width) - 1
            self._spool_reload()

    def stages_widths(self) -> list[float]:
        return [w for w, _ in self._plan]

    # --- lifecycle ----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="misaka-tsdb"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None
        for sp in self._spools.values():
            sp.close()

    def add_hook(self, fn) -> None:
        """Register fn(tsdb) to run after every collected sample."""
        with self._lock:
            if fn not in self._hooks:
                self._hooks.append(fn)

    def remove_hook(self, fn) -> None:
        with self._lock:
            if fn in self._hooks:
                self._hooks.remove(fn)

    # --- the collector ------------------------------------------------------

    def _current_period(self) -> float:
        """Nominal interval, stretched whenever one sample's measured
        cost would blow the duty-cycle budget (the PR 7 sampler's
        governor discipline)."""
        return max(self.interval_s, self._cost_ema / self.budget)

    def _loop(self) -> None:
        while not self._stop.wait(self._current_period()):
            t0 = time.perf_counter()
            try:
                self.sample_once()
                self._spool_flush()
            except Exception:  # pragma: no cover — the collector must
                pass           # never take serving down with it
            dt = time.perf_counter() - t0
            self._cost_ema = (
                dt if self._cost_ema == 0.0
                else 0.8 * self._cost_ema + 0.2 * dt
            )
            hooks = list(self._hooks)
            for fn in hooks:
                try:
                    fn(self)
                except Exception:  # pragma: no cover — a broken rule
                    pass           # must not stop history collection

    def _series_for(self, name: str, labels: dict[str, str],
                    kind: str) -> _Series | None:
        key = (name, tuple(sorted(labels.items())))
        s = self._series.get(key)
        if s is not None:
            return s
        if len(self._series) >= self.max_series:
            self._dropped.add(key)
            return None
        s = self._series[key] = _Series(name, labels, kind, self._plan)
        return s

    def _record(self, now_unix: float, name: str, labels: dict,
                kind: str, value: float) -> None:
        s = self._series_for(name, labels, kind)
        if s is not None:
            s.add(now_unix, value)

    def sample_once(self) -> None:
        """One collection pass over the metrics registry (the collector
        thread's body; tests call it directly for deterministic time)."""
        now_unix = time.time()
        now_mono = time.monotonic()
        last = self._last_mono
        self._last_mono = now_mono
        dt = (now_mono - last) if last is not None else None
        if dt is not None and dt <= 0:
            dt = None
        all_metrics = self._registry.all_metrics()
        # priority families first: the series cap must never starve the
        # golden signals (see PRIORITY_PREFIXES)
        all_metrics.sort(
            key=lambda m: (
                not m.name.startswith(PRIORITY_PREFIXES), m.name
            )
        )
        with self._lock:
            self._samples += 1
            for m in all_metrics:
                if isinstance(m, metrics.Histogram):
                    self._sample_histogram(m, now_unix, dt)
                elif isinstance(m, metrics.Counter):
                    self._sample_counter(m, now_unix, dt)
                elif isinstance(m, metrics.Gauge):
                    for lkey, child in m._items():
                        labels = dict(zip(m.labelnames, lkey))
                        self._record(
                            now_unix, m.name, labels, "gauge", child.value
                        )

    def _sample_counter(self, m, now_unix: float, dt: float | None) -> None:
        for lkey, child in m._items():
            key = (m.name, lkey)
            cur = child.value
            prev = self._prev_counter.get(key)
            self._prev_counter[key] = cur
            if prev is None or dt is None:
                continue  # first sight: establish the baseline only
            delta = cur - prev
            if delta < 0:
                delta = cur  # process/metric reset: re-base, don't spike
            labels = dict(zip(m.labelnames, lkey))
            self._record(now_unix, m.name, labels, "rate", delta / dt)

    def _sample_histogram(self, m, now_unix: float,
                          dt: float | None) -> None:
        uppers = m.buckets
        for lkey, child in m._items():
            counts, _total = child.snapshot()
            key = (m.name, lkey)
            prev = self._prev_hist.get(key)
            self._prev_hist[key] = counts
            if prev is None or dt is None or len(prev) != len(counts):
                continue
            delta = [c - p for c, p in zip(counts, prev)]
            n = sum(delta)
            if n < 0:  # reset: re-base on the fresh counts
                delta, n = counts, sum(counts)
            labels = dict(zip(m.labelnames, lkey))
            self._record(
                now_unix, f"{m.name}:rate", labels, "rate", n / dt
            )
            if n <= 0:
                continue  # an idle interval writes no false-zero quantile
            for q, suffix in ((0.5, ":p50"), (0.99, ":p99")):
                self._record(
                    now_unix, f"{m.name}{suffix}", labels, "quantile",
                    metrics.quantile_from_buckets(uppers, delta, q),
                )

    # --- the disk spool -----------------------------------------------------

    def _tier_stage(self, tier: str) -> int:
        return 0 if tier == "fine" else len(self._plan) - 1

    def _spool_flush(self) -> None:
        """Collector-tick hook: append every newly FINALIZED slot (its
        epoch fully in the past) to the tier's segment spool, fsync, and
        let the spool enforce rotation + the disk budget.  Runs on the
        collector thread only (the spool is single-writer)."""
        if not self._spools:
            return
        now_unix = time.time()
        wrote = False
        for tier, sp in self._spools.items():
            stage_i = self._tier_stage(tier)
            width = self._plan[stage_i][0]
            current = int(now_unix / width)
            # bound catch-up after a stall — older slots are still in
            # RAM but no longer worth a giant write burst
            start = max(self._flushed_epoch[tier] + 1, current - 64)
            tier_wrote = False
            for epoch in range(start, current):
                rows = []
                with self._lock:
                    for s in self._series.values():
                        slot = s.stages[stage_i].slot_at(epoch)
                        if slot is not None:
                            rows.append([
                                s.name, s.labels, s.kind,
                                slot[0], slot[1], slot[2],
                            ])
                self._flushed_epoch[tier] = epoch
                if rows:
                    sp.append({"k": "slots", "tier": tier, "w": width,
                               "e": epoch, "rows": rows})
                    self.spooled_frames += 1
                    tier_wrote = True
            if tier_wrote:
                sp.flush()
                wrote = True
        if wrote:
            M_SPOOL_BYTES.set(
                sum(sp.disk_bytes() for sp in self._spools.values())
            )

    def _spool_reload(self) -> None:
        """Boot: retained frames -> the in-memory rings, so /debug/series
        answers across restarts without checkpoints.  Long-tier frames
        own the coarsest ring outright; fine frames re-aggregate into
        every FINER stage (never the coarsest — the long tier already
        carries that span, and merging both would double-count)."""
        for tier in ("long", "fine"):
            sp = self._spools.get(tier)
            if sp is None:
                continue
            self.reloaded_frames += sp.reload(
                lambda fr, t=tier: self._install_frame(t, fr)
            )

    def _install_frame(self, tier: str, frame: dict) -> None:
        if frame.get("k") != "slots":
            return
        try:
            width = float(frame["w"])
            epoch = int(frame["e"])
            rows = frame["rows"]
        except (KeyError, TypeError, ValueError):
            return
        stage_i = self._tier_stage(tier)
        live_width = self._plan[stage_i][0]
        if abs(width - live_width) < 1e-9:
            # same tier geometry across the restart: the writer resumes
            # AFTER the newest on-disk epoch (no duplicate frames)
            self._flushed_epoch[tier] = max(
                self._flushed_epoch[tier], epoch
            )
        if tier == "long":
            self._long_hi = max(self._long_hi, epoch)
        slot_start = epoch * width
        for row in rows:
            try:
                name, labels, kind, total, count, peak = row
            except (TypeError, ValueError):
                continue
            s = self._series_for(
                str(name),
                {str(k): str(v) for k, v in (labels or {}).items()},
                str(kind),
            )
            if s is None:
                continue  # over the cap: counted in dropped_series
            if tier == "long" or not self._long_armed:
                targets = s.stages[stage_i:stage_i + 1]
            else:
                # fine frames fill every finer stage; the coarsest too,
                # but only PAST the long tier's newest reloaded epoch —
                # a young server has no finalized long slots yet, and
                # window=7d must still show pre-restart points without
                # double-counting spans the long tier already carries
                targets = list(s.stages[:-1])
                coarse = s.stages[-1]
                if slot_start >= (self._long_hi + 1) * coarse.width:
                    targets.append(coarse)
            for ring in targets:
                if ring.width + 1e-9 < width:
                    continue  # cannot disaggregate into a finer ring
                ring.merge(
                    int(slot_start / ring.width) if ring.width != width
                    else epoch,
                    float(total), int(count), float(peak),
                )

    def spool_status(self) -> dict | None:
        if not self._spools:
            return None
        return {
            "dir": self.spool_dir,
            "disk_bytes": sum(
                sp.disk_bytes() for sp in self._spools.values()
            ),
            "frames_spooled": self.spooled_frames,
            "frames_reloaded": self.reloaded_frames,
            "evicted_segments": sum(
                sp.evicted for sp in self._spools.values()
            ),
            "errors": sum(sp.errors for sp in self._spools.values()),
            "tiers": {
                tier: {
                    "width_s": self._plan[self._tier_stage(tier)][0],
                    "segments": len(sp.segments()),
                    "budget_bytes": sp.budget_bytes,
                }
                for tier, sp in self._spools.items()
            },
        }

    # --- the read side ------------------------------------------------------

    def series_index(self) -> dict:
        with self._lock:
            names: dict[str, int] = {}
            for s in self._series.values():
                names[s.name] = names.get(s.name, 0) + 1
            dropped = len(self._dropped)
            count = len(self._series)
        return {
            "enabled": True,
            "running": self.running,
            "interval_s": self.interval_s,
            "effective_interval_s": round(self._current_period(), 3),
            "budget": self.budget,
            "sample_cost_us": round(self._cost_ema * 1e6, 1),
            "samples": self._samples,
            "stages": [
                {"width_s": w, "slots": n, "span_s": round(w * n, 1)}
                for w, n in self._plan
            ],
            "series_count": count,
            "max_series": self.max_series,
            "dropped_series": dropped,
            "bytes_per_series": sum(28 * n for _, n in self._plan),
            "names": {k: names[k] for k in sorted(names)},
        } | (
            {"spool": self.spool_status()} if self._spools else {}
        )

    def query(self, name: str, labels: dict[str, str] | None = None,
              window_s: float = 3600.0) -> list[dict]:
        """Every series matching `name` (+ label subset filter) over the
        last `window_s`: [{labels, stage_s, points: [[t, avg, max]...]}]."""
        now_unix = time.time()
        want = labels or {}
        with self._lock:
            matches = [
                s for (n, _), s in self._series.items()
                if n == name and all(
                    s.labels.get(k) == v for k, v in want.items()
                )
            ]
            out = []
            for s in matches:
                ring = s.stage_for(window_s)
                out.append({
                    "labels": s.labels,
                    "kind": s.kind,
                    "stage_s": ring.width,
                    "points": ring.points(now_unix, window_s),
                })
        out.sort(key=lambda r: sorted(r["labels"].items()))
        return out

    # --- snapshot / restore (the durable-checkpoint ride) -------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "format": 1,
                "interval_s": self.interval_s,
                "saved_unix": round(time.time(), 3),
                "series": [
                    {
                        "name": s.name,
                        "labels": s.labels,
                        "kind": s.kind,
                        "stages": [
                            {"width_s": ring.width, "slots": ring.dump()}
                            for ring in s.stages
                        ],
                    }
                    for s in self._series.values()
                ],
            }

    def restore(self, snap: dict) -> int:
        """Merge a snapshot() payload into the live rings (strictly-newer
        slots only; see _Ring.install).  Returns the series count
        touched.  Raises TSDBError on malformed content."""
        if not isinstance(snap, dict) or snap.get("format") != 1:
            raise TSDBError("unrecognized tsdb snapshot format")
        touched = 0
        with self._lock:
            for row in snap.get("series", ()):
                name = row.get("name")
                labels = row.get("labels") or {}
                if not isinstance(name, str) or not isinstance(labels, dict):
                    raise TSDBError("malformed tsdb snapshot series row")
                s = self._series_for(
                    name, {str(k): str(v) for k, v in labels.items()},
                    str(row.get("kind") or "gauge"),
                )
                if s is None:
                    continue  # over the cap: counted in dropped_series
                touched += 1
                by_width = {ring.width: ring for ring in s.stages}
                for st in row.get("stages", ()):
                    ring = by_width.get(float(st.get("width_s", -1)))
                    if ring is None:
                        continue  # interval changed across the restore
                    for slot in st.get("slots", ()):
                        epoch, total, count, peak = slot
                        ring.install(
                            int(epoch), float(total), int(count), float(peak)
                        )
        return touched


# --- the process-global instance --------------------------------------------

_lock = threading.Lock()
_tsdb: TSDB | None = None


def enabled(environ=os.environ) -> bool:
    return environ.get("MISAKA_TSDB", "1") != "0"


def get() -> TSDB | None:
    return _tsdb


def ensure_started(environ=os.environ) -> TSDB | None:
    """Start (or return) the process-global collector — called by
    make_http_server so every serving process retains its own history
    from boot; None when MISAKA_TSDB=0."""
    global _tsdb
    if not enabled(environ):
        return None
    with _lock:
        if _tsdb is None:
            _tsdb = TSDB(
                interval_s=env_float(
                    environ, "MISAKA_TSDB_INTERVAL_S", DEFAULT_INTERVAL_S
                ),
                max_series=int(env_float(
                    environ, "MISAKA_TSDB_MAX_SERIES", DEFAULT_MAX_SERIES
                )),
                budget=env_float(
                    environ, "MISAKA_TSDB_BUDGET", DEFAULT_BUDGET
                ),
                # the durable telemetry plane (unset = today's behavior)
                spool_dir=environ.get("MISAKA_TSDB_DIR") or None,
                disk_mb=env_float(
                    environ, "MISAKA_TSDB_DISK_MB", DEFAULT_DISK_MB
                ),
                long_s=env_float(
                    environ, "MISAKA_TSDB_LONG_S", DEFAULT_LONG_S
                ),
                long_slots=int(env_float(
                    environ, "MISAKA_TSDB_LONG_SLOTS", DEFAULT_LONG_SLOTS
                )),
                segment_bytes=int(env_float(
                    environ, "MISAKA_TSDB_SEG_KB", 1024.0
                ) * 1024),
            )
        if not _tsdb.running:
            _tsdb.start()
    return _tsdb


def shutdown() -> None:
    """Stop and drop the global collector (tests; the A/B's off side)."""
    global _tsdb
    with _lock:
        if _tsdb is not None:
            _tsdb.stop()
            _tsdb = None


def query(name: str, labels: dict[str, str] | None = None,
          window_s: float = 3600.0) -> list[dict]:
    t = _tsdb
    return t.query(name, labels, window_s) if t is not None else []


def index_payload() -> dict:
    t = _tsdb
    if t is None:
        return {
            "enabled": enabled(),
            "running": False,
            "series_count": 0,
            "names": {},
            "hint": "tsdb not started (MISAKA_TSDB=0, or no HTTP server "
                    "in this process)",
        }
    return t.series_index()


def query_payload(name: str, labels: dict[str, str] | None = None,
                  window_s: float = 3600.0) -> dict:
    """The GET /debug/series?name=... body."""
    return {
        "name": name,
        "window_s": window_s,
        "series": query(name, labels, window_s),
    }


def snapshot_bytes() -> bytes | None:
    """The __tsdb__ checkpoint payload (None when no collector runs)."""
    t = _tsdb
    if t is None:
        return None
    return json.dumps(t.snapshot(), separators=(",", ":")).encode()


def restore_bytes(blob: bytes) -> int:
    """Merge a snapshot_bytes() payload into the live store (starting it
    if needed); returns series touched, 0 when the TSDB is disabled."""
    t = ensure_started()
    if t is None:
        return 0
    return t.restore(json.loads(blob.decode()))
