"""Shared build-and-load scaffolding for the native C++ backends.

Both native components (tis/native.py assembler, core/cinterp.py interpreter)
follow the same contract: the .so is built on demand next to its source
(binaries are NOT checked in; `make native` prebuilds them), rebuilt
whenever the binary does not carry the current source's identity hash or
fails to load (stale/foreign-arch artifact) and a compiler is available; a
process-wide failure latch so an unavailable toolchain degrades quietly to
the pure-Python paths instead of retrying every call.

Staleness is decided by CONTENT, not mtime: each .cpp embeds a
"MISAKA-SRC-HASH:<sha256[:16]>" tag injected at build time, and the loader
scans the .so bytes for the tag matching the current source hash.  (A fresh
clone gives source and binary identical mtimes, so the old mtime comparison
could never flag a stale shipped binary.)  A binary with a wrong or missing
tag is rebuilt; if no toolchain is available the component is treated as
unavailable rather than running stale native code.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Callable

_TAG = b"MISAKA-SRC-HASH:"


class NativeLib:
    """Lazy loader for one shared object built from one C++ source file.

    `so_env` names an environment variable that, when set, OVERRIDES the
    .so path and disables the staleness rebuild entirely: the sanitizer
    lanes (make native-asan / tools/sanitize_stress.py) point it at an
    instrumented build whose bytes never match the default flags' hash —
    rebuilding "stale" here would silently replace the sanitized binary
    with an uninstrumented one and the lane would test nothing.
    """

    def __init__(self, src: str, so: str,
                 configure: Callable[[ctypes.CDLL], None],
                 so_env: str | None = None):
        self._src = src
        self._so = so
        self._so_env = so_env
        self._configure = configure  # declares restype/argtypes; may raise
        self._lock = threading.Lock()
        self._lib: ctypes.CDLL | None = None
        self._failed = False

    def _src_hash(self) -> str:
        with open(self._src, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()[:16]

    def _so_matches_src(self) -> bool:
        """True iff the on-disk .so embeds the current source's hash tag."""
        try:
            with open(self._so, "rb") as f:
                data = f.read()
        except OSError:
            return False
        i = data.find(_TAG)
        if i < 0:
            return False  # pre-tag binary: provenance unknown, rebuild
        want = self._src_hash().encode()
        return data[i + len(_TAG): i + len(_TAG) + len(want)] == want

    def _build(self) -> None:
        cxx = os.environ.get("CXX", "g++")
        # Compile to a temp name and swap atomically: truncating a .so that
        # some process has dlopen'd rewrites its mapped text pages (SIGSEGV
        # in that process); os.replace gives the new build a fresh inode and
        # leaves existing mappings intact.
        tmp = f"{self._so}.tmp.{os.getpid()}"
        try:
            subprocess.run(
                [
                    # -pthread: the interpreter's serving pool runs
                    # std::thread workers; -fopenmp-simd honors the SIMD
                    # loop pragmas (no OpenMP runtime) — both harmless for
                    # the other components
                    cxx, "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                    "-fopenmp-simd",
                    f'-DMISAKA_SRC_HASH="{self._src_hash()}"',
                    self._src, "-o", tmp,
                ],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, self._so)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def load(self) -> ctypes.CDLL | None:
        with self._lock:
            if self._lib is not None or self._failed:
                return self._lib
            override = self._so_env and os.environ.get(self._so_env)
            if override:
                try:
                    lib = ctypes.CDLL(override)
                    self._configure(lib)
                    self._lib = lib
                except Exception:
                    # loud, not latched-quiet: an armed override that
                    # fails to load means the lane is NOT testing what
                    # it thinks — degrade-to-Python would hide that
                    self._failed = True
                    raise
                return self._lib
            try:
                if os.path.exists(self._src) and not self._so_matches_src():
                    self._build()
                try:
                    lib = ctypes.CDLL(self._so)
                except OSError:
                    # Shipped binary unloadable (e.g. built for another
                    # arch): rebuild from source once and retry.  dlopen
                    # caches by path, so this only works because nothing
                    # loaded the old file in this process.
                    if not os.path.exists(self._src):
                        raise
                    self._build()
                    lib = ctypes.CDLL(self._so)
                self._configure(lib)
                self._lib = lib
            except Exception:
                self._failed = True
            return self._lib

    def available(self) -> bool:
        return self.load() is not None
