"""Shared build-and-load scaffolding for the native C++ backends.

Both native components (tis/native.py assembler, core/cinterp.py interpreter)
follow the same contract: a checked-in .so for zero-setup use, rebuilt from
source when the source is newer OR when the shipped binary fails to load
(stale/foreign-arch artifact) and a compiler is available; a process-wide
failure latch so an unavailable toolchain degrades quietly to the pure-Python
paths instead of retrying every call.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable


class NativeLib:
    """Lazy loader for one shared object built from one C++ source file."""

    def __init__(self, src: str, so: str, configure: Callable[[ctypes.CDLL], None]):
        self._src = src
        self._so = so
        self._configure = configure  # declares restype/argtypes; may raise
        self._lock = threading.Lock()
        self._lib: ctypes.CDLL | None = None
        self._failed = False

    def _build(self) -> None:
        cxx = os.environ.get("CXX", "g++")
        subprocess.run(
            [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", self._src, "-o", self._so],
            check=True,
            capture_output=True,
        )

    def load(self) -> ctypes.CDLL | None:
        with self._lock:
            if self._lib is not None or self._failed:
                return self._lib
            try:
                if not os.path.exists(self._so) or (
                    os.path.exists(self._src)
                    and os.path.getmtime(self._src) > os.path.getmtime(self._so)
                ):
                    self._build()
                try:
                    lib = ctypes.CDLL(self._so)
                except OSError:
                    # Shipped binary unloadable (stale or built for another
                    # arch): rebuild from source once and retry.
                    if not os.path.exists(self._src):
                        raise
                    self._build()
                    lib = ctypes.CDLL(self._so)
                self._configure(lib)
                self._lib = lib
            except Exception:
                self._failed = True
            return self._lib

    def available(self) -> bool:
        return self.load() is not None
